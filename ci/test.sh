#!/usr/bin/env bash
#
# CI entry point (the reference's ci/test.sh analog: pre-merge fast suite vs
# nightly --runslow, ci/test.sh:20-57). Usage:
#   ci/test.sh            # pre-merge: lint + fast tests
#   ci/test.sh --nightly  # adds the large-scale --runslow tests
#   ci/test.sh --spark    # Spark barrier-stage integration lane (needs a
#                         # pyspark install; tests self-skip without one)
#
set -euo pipefail
cd "$(dirname "$0")/.."

# CI artifacts (analysis + regression verdicts) land side by side here
ARTIFACTS="${CI_ARTIFACT_DIR:-/tmp/srml_ci_artifacts}"
mkdir -p "$ARTIFACTS"

echo "== static analysis (AST lint: ci/analysis — compile, invariants, registries, lock discipline, imports)"
# the gate prints its own wall time against --time-budget; the verdict JSON
# (incl. wall_s + cache hit count) lands next to the regression verdict
python -m ci.analysis --json-out "$ARTIFACTS/analysis_verdict.json" --time-budget 60

echo "== perf regression gate (report-only against the checked-in BENCH trajectory)"
python -m benchmark.regression --report-only --out "$ARTIFACTS/regression_verdict.json"

echo "== ops snapshot artifact (SLO verdicts + decision log + tenant accounting + efficiency attribution)"
python -m benchmark.opsreport --json --write "$ARTIFACTS/ops_snapshot.json" \
  --write-efficiency "$ARTIFACTS/efficiency_report.json" > /dev/null

echo "== fleet aggregation smoke (3-rank LocalRendezvous ops round; merged counters must equal the per-rank sum)"
# archives the merged cluster snapshot next to the verdict JSONs
# (docs/observability.md "Fleet plane")
python -m benchmark.bench_fleet --smoke --nranks 3 \
  --write "$ARTIFACTS/cluster_snapshot.json"

echo "== chaos smoke (kill one rank mid-solve; survivors must recover + post-mortem must name it)"
python ci/chaos_smoke.py

echo "== concurrency sanitizer lanes (SRML_LOCKCHECK=1 over the threaded families; report archived)"
SRML_LOCKCHECK=1 SRML_LOCKCHECK_REPORT="$ARTIFACTS/lockcheck_report.json" \
  python -m pytest tests/test_chaos.py tests/test_scheduler.py tests/test_serving.py \
    tests/test_ops_plane.py tests/test_lockcheck.py -q
python - "$ARTIFACTS/lockcheck_report.json" <<'PY'
import json, sys
rep = json.load(open(sys.argv[1]))
print(f"lockcheck: {len(rep['locks'])} locks, {len(rep['edges'])} edges, "
      f"{len(rep['inversions'])} inversion(s), {len(rep['long_holds'])} long hold(s)")
sys.exit(1 if rep["inversions"] else 0)  # zero-inversion acceptance gate
PY

echo "== numerics sanitizer lanes (SRML_NUMCHECK=1 over the solver/streaming/serving/segmented families; report archived)"
# test_recovery drives run_segmented_while, so the segment.* checkpoint
# boundary is exercised by the gate (test_numcheck's own segment trips are
# deliberately discarded by its snapshot/restore fixture); test_precision
# runs every bf16 solver family under the sanitizer (the mixed-precision
# acceptance: zero trips, no bf16 solver-state watermark)
SRML_NUMCHECK=1 SRML_NUMCHECK_REPORT="$ARTIFACTS/numcheck_report.json" \
  python -m pytest tests/test_kmeans.py tests/test_oocore.py tests/test_serving.py \
    tests/test_recovery.py tests/test_numcheck.py tests/test_precision.py -q
python - "$ARTIFACTS/numcheck_report.json" <<'PY'
import json, sys
rep = json.load(open(sys.argv[1]))
print(f"numcheck: {rep['checks']} boundary checks, {len(rep['trips'])} trip(s), "
      f"{len(rep['watermarks'])} watermarked stage(s)")
if rep["checks"] == 0:
    print("numcheck: 0 checks — the instrumented lanes did not exercise the hook")
    sys.exit(1)
sys.exit(1 if rep["trips"] else 0)  # zero-trip acceptance gate
PY

if [[ "${1:-}" == "--nightly" ]]; then
  echo "== nightly: full suite incl. large-scale slow tests"
  python -m pytest tests/ -q --runslow
  echo "== nightly: multichip dryrun"
  python __graft_entry__.py
elif [[ "${1:-}" == "--spark" ]]; then
  echo "== spark integration lane (real local[N] barrier stage)"
  python -c "import pyspark" 2>/dev/null || {
    echo "pyspark not installed - the pyspark lane will self-skip"; }
  python -m pytest tests/test_spark.py -q
else
  echo "== unit/parity tests (virtual 8-device CPU mesh)"
  python -m pytest tests/ -q
fi
echo "CI OK"
