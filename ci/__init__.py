# CI tooling package root — makes `python -m ci.analysis` resolvable from the
# repo root (ci/test.sh and the ci/lint.py shim both run from there).
