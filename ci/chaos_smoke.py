#
# Chaos smoke lane (ci/test.sh): two tiny end-to-end fault scenarios.
#
# (1) kill+recover: a 3-process FileRendezvous `recover`-mode fit
# (tests/chaos_worker.py — a distributed Lloyd loop under
# core.recoverable_stage with solver checkpoints on), SIGKILLs rank 2
# mid-solve via SRML_FAULT_PLAN, and asserts the elastic-recovery contract
# held: survivors reform to a 2-rank group, resume from the checkpoint,
# finish clean, and the assembled post-mortem NAMES the killed rank and the
# recovery epoch.
#
# (2) oom-demotion: a single-process fit under an `oom:budget=` chaos plan
# (tests/oom_worker.py) must complete via the RESIDENT -> STREAM demotion
# ladder — fit.demotions == 1, overlap measured, model matching the clean
# resident fit the same process runs once the plan is spent (docs/
# robustness.md "Memory safety").
#
# The full parametrized sweeps live in tests/test_chaos.py +
# tests/test_oocore.py; this is the pre-merge canary.
#
import json
import os
import signal
import subprocess
import sys
import tempfile
import uuid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "chaos_worker.py")
OOM_WORKER = os.path.join(REPO, "tests", "oom_worker.py")

NRANKS = 3
ITERS = 6
# round 8 = iteration 3 of the worker's 2-rounds-per-iteration traffic —
# after the iteration-2 checkpoint landed, so survivors must RESUME
PLAN = "kill:rank=2:round=8"


def fail(msg: str) -> None:
    print(f"chaos smoke: FAIL — {msg}")
    sys.exit(1)


def oom_demotion_case(tmp: str) -> None:
    """An injected-budget OOM at fit entry completes the fit via demotion."""
    out = os.path.join(tmp, "oom_demote.json")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SRML_FAULT_PLAN"] = "oom:budget=16000"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the 16000-byte budget is calibrated per device over the same 8-device
    # CPU mesh the pytest harness forces (tests/conftest.py): demote the
    # resident placement, admit the streaming working set
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, OOM_WORKER, "demote", out],
        env=env, capture_output=True, timeout=240,
    )
    if proc.returncode != 0:
        fail(
            "oom worker exited "
            f"{proc.returncode}:\n{proc.stdout.decode()}{proc.stderr.decode()}"
        )
    with open(out) as f:
        res = json.load(f)
    if res["error"] is not None:
        fail(f"oom worker raised {res['error']}: {res.get('detail')}")
    if res["admission_faulted"].get("verdict") != "stream":
        fail(f"faulted fit was not demoted: {res['admission_faulted']}")
    if res["admission_clean"].get("verdict") != "resident":
        fail(f"clean fit did not run resident: {res['admission_clean']}")
    if res["counters"].get("fit.demotions") != 1:
        fail(f"fit.demotions == {res['counters'].get('fit.demotions')}, expected 1")
    if not res["gauges"].get("ingest.overlap_fraction", 0) > 0:
        fail("no double-buffer overlap measured on the demoted fit")
    if not res["max_rel_center_diff"] < 1e-9:
        fail(f"streamed centers diverged: {res['max_rel_center_diff']}")
    print(
        "chaos smoke: OK — injected-budget OOM demoted to streaming "
        f"(overlap {res['gauges']['ingest.overlap_fraction']:.2f}), "
        "model matches resident"
    )


def main() -> None:
    sys.path.insert(0, REPO)
    from spark_rapids_ml_tpu import diagnostics

    tmp = tempfile.mkdtemp(prefix="srml_chaos_smoke_")
    flightrec = os.path.join(tmp, "flightrec")
    out_dir = os.path.join(tmp, "out")
    os.makedirs(out_dir, exist_ok=True)
    run_id = uuid.uuid4().hex
    trace_id = f"smoke-{run_id[:8]}"

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SRML_FAULT_PLAN"] = PLAN
    env["SRML_FLIGHTREC_DIR"] = flightrec
    env["SRML_TRACE_ID"] = trace_id
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs = [
        subprocess.Popen(
            [
                sys.executable, WORKER, str(r), str(NRANKS),
                os.path.join(tmp, "rdv"), out_dir, run_id,
                str(ITERS), "2.0", "45.0", "recover",
            ],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(NRANKS)
    ]
    outputs = [p.communicate(timeout=180)[0].decode() for p in procs]

    if procs[2].returncode != -signal.SIGKILL:
        fail(f"victim rank 2 exited {procs[2].returncode}, expected SIGKILL")
    for r in (0, 1):
        if procs[r].returncode != 0:
            fail(f"survivor rank {r} exited {procs[r].returncode}:\n{outputs[r]}")
        with open(os.path.join(out_dir, f"result_rank{r}.json")) as f:
            res = json.load(f)
        if res["error"] is not None:
            fail(f"survivor rank {r} raised {res['error']}: {res.get('detail')}")
        if res["live_final"] != [0, 1]:
            fail(f"survivor rank {r} finished on {res['live_final']}, expected [0, 1]")
        c = res["counters"]
        if c.get("fit.recoveries") != 1:
            fail(f"rank {r} fit.recoveries == {c.get('fit.recoveries')}, expected 1")
        if not c.get("checkpoint.restores"):
            fail(f"rank {r} resumed from scratch (no checkpoint.restores)")

    pm = diagnostics.assemble_postmortem(flightrec, nranks=NRANKS, trace_id=trace_id)
    if pm.get("failed_rank") != 2:
        fail(f"post-mortem blamed rank {pm.get('failed_rank')}, expected 2")
    epochs = pm.get("recovery_epochs") or []
    if not any(e.get("survivors") == [0, 1] for e in epochs):
        fail(f"post-mortem shows no [0, 1]-survivor recovery epoch: {epochs}")
    print(
        "chaos smoke: OK — rank 2 SIGKILLed, survivors resumed from "
        f"checkpoint, post-mortem names rank 2 and epoch g{epochs[0]['generation']}"
    )
    oom_demotion_case(tmp)


if __name__ == "__main__":
    main()
