#
# Chaos smoke lane (ci/test.sh): one tiny kill+recover fit, end to end.
#
# Launches a 3-process FileRendezvous `recover`-mode fit (tests/chaos_worker.py
# — a distributed Lloyd loop under core.recoverable_stage with solver
# checkpoints on), SIGKILLs rank 2 mid-solve via SRML_FAULT_PLAN, and asserts
# the elastic-recovery contract held: survivors reform to a 2-rank group,
# resume from the checkpoint, finish clean, and the assembled post-mortem
# NAMES the killed rank and the recovery epoch. The full parametrized sweep
# lives in tests/test_chaos.py; this is the pre-merge canary.
#
import json
import os
import signal
import subprocess
import sys
import tempfile
import uuid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "chaos_worker.py")

NRANKS = 3
ITERS = 6
# round 8 = iteration 3 of the worker's 2-rounds-per-iteration traffic —
# after the iteration-2 checkpoint landed, so survivors must RESUME
PLAN = "kill:rank=2:round=8"


def fail(msg: str) -> None:
    print(f"chaos smoke: FAIL — {msg}")
    sys.exit(1)


def main() -> None:
    sys.path.insert(0, REPO)
    from spark_rapids_ml_tpu import diagnostics

    tmp = tempfile.mkdtemp(prefix="srml_chaos_smoke_")
    flightrec = os.path.join(tmp, "flightrec")
    out_dir = os.path.join(tmp, "out")
    os.makedirs(out_dir, exist_ok=True)
    run_id = uuid.uuid4().hex
    trace_id = f"smoke-{run_id[:8]}"

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SRML_FAULT_PLAN"] = PLAN
    env["SRML_FLIGHTREC_DIR"] = flightrec
    env["SRML_TRACE_ID"] = trace_id
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs = [
        subprocess.Popen(
            [
                sys.executable, WORKER, str(r), str(NRANKS),
                os.path.join(tmp, "rdv"), out_dir, run_id,
                str(ITERS), "2.0", "45.0", "recover",
            ],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(NRANKS)
    ]
    outputs = [p.communicate(timeout=180)[0].decode() for p in procs]

    if procs[2].returncode != -signal.SIGKILL:
        fail(f"victim rank 2 exited {procs[2].returncode}, expected SIGKILL")
    for r in (0, 1):
        if procs[r].returncode != 0:
            fail(f"survivor rank {r} exited {procs[r].returncode}:\n{outputs[r]}")
        with open(os.path.join(out_dir, f"result_rank{r}.json")) as f:
            res = json.load(f)
        if res["error"] is not None:
            fail(f"survivor rank {r} raised {res['error']}: {res.get('detail')}")
        if res["live_final"] != [0, 1]:
            fail(f"survivor rank {r} finished on {res['live_final']}, expected [0, 1]")
        c = res["counters"]
        if c.get("fit.recoveries") != 1:
            fail(f"rank {r} fit.recoveries == {c.get('fit.recoveries')}, expected 1")
        if not c.get("checkpoint.restores"):
            fail(f"rank {r} resumed from scratch (no checkpoint.restores)")

    pm = diagnostics.assemble_postmortem(flightrec, nranks=NRANKS, trace_id=trace_id)
    if pm.get("failed_rank") != 2:
        fail(f"post-mortem blamed rank {pm.get('failed_rank')}, expected 2")
    epochs = pm.get("recovery_epochs") or []
    if not any(e.get("survivors") == [0, 1] for e in epochs):
        fail(f"post-mortem shows no [0, 1]-survivor recovery epoch: {epochs}")
    print(
        "chaos smoke: OK — rank 2 SIGKILLed, survivors resumed from "
        f"checkpoint, post-mortem names rank 2 and epoch g{epochs[0]['generation']}"
    )


if __name__ == "__main__":
    main()
