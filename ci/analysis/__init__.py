#
# ci.analysis — framework-aware static analysis gate. AST engine + rule
# catalog replacing ci/lint.py's line regexes (that file is now a thin shim
# over this package). Entry points:
#
#   python -m ci.analysis              # analyze the repo, exit 1 on new findings
#   python -m ci.analysis --json       # machine-readable verdict on stdout
#   python -m ci.analysis --json-out F # verdict artifact for CI (ci/test.sh)
#   python -m ci.analysis --write-baseline   # freeze/shrink the ratchet
#
# docs/development.md: rule catalog, waiver policy, baseline workflow.
#
from .cli import main
from .engine import (
    FileContext,
    Finding,
    RegistrySources,
    Run,
    RuleBase,
    analyze_source,
    analyze_sources,
)

__all__ = [
    "main",
    "Finding",
    "FileContext",
    "RegistrySources",
    "Run",
    "RuleBase",
    "analyze_source",
    "analyze_sources",
]
