#
# CLI for the analysis gate. Text mode prints `file:line:col [rule-id]
# message` per NEW finding; `--json` / `--json-out` emit the machine-
# readable verdict (the artifact ci/test.sh stores next to the perf
# regression gate's). Exit 0 iff no new findings and the import smoke
# passes.
#
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

from . import baseline as baseline_mod
from .engine import Run

DEFAULT_TARGETS = ("spark_rapids_ml_tpu", "benchmark", "tests")
# import-time breakage must fail the gate (the old lint.py contract)
IMPORT_SMOKE = ("spark_rapids_ml_tpu", "benchmark.benchmark_runner")
VERDICT_VERSION = 1
# finding ids emitted by the engine itself, outside any registered rule —
# listed so the verdict's catalog covers every id a finding can carry
ENGINE_RULE_IDS = (
    ("syntax-error", "file fails the in-memory compile() check"),
    ("encoding", "file is not valid utf-8"),
)


def _catalog(run: Run):
    rows = []
    for r in run.rules:
        rows.append({"id": r.id, "waiver": r.waiver, "description": r.description})
        for sub_id, sub_desc in getattr(r, "sub_ids", ()):
            rows.append({"id": sub_id, "waiver": r.waiver, "description": sub_desc})
    for rule_id, desc in ENGINE_RULE_IDS:
        rows.append({"id": rule_id, "waiver": None, "description": desc})
    return rows


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _import_smoke(root: str) -> Dict[str, str]:
    results: Dict[str, str] = {}
    if root not in sys.path:
        sys.path.insert(0, root)
    for mod in IMPORT_SMOKE:
        try:
            importlib.import_module(mod)
            results[mod] = "ok"
        except Exception as e:
            results[mod] = f"error: {e!r}"
    return results


def explain_rule(run: Run, rule_id: str) -> int:
    """`--explain <rule-id>`: the rule's one-line invariant, waiver form, and
    the full module docstring it ships with (the rationale + examples)."""
    for rule in run.rules:
        ids = [rule.id] + [sid for sid, _ in getattr(rule, "sub_ids", ())]
        if rule_id not in ids:
            continue
        print(f"rule: {rule.id}")
        if getattr(rule, "sub_ids", ()):
            print("sub-ids: " + ", ".join(sid for sid, _ in rule.sub_ids))
        waiver = f"# {rule.waiver}-ok: <reason>" if rule.waiver else "(none — not waivable)"
        print(f"waiver: {waiver}")
        print(f"scope: {', '.join(rule.tree_scope)}")
        print(f"invariant: {rule.description}")
        doc = getattr(rule, "explain", None) or sys.modules[type(rule).__module__].__doc__
        if doc:
            print("\n" + doc.strip("\n"))
        return 0
    for rule_id_known, desc in ENGINE_RULE_IDS:
        if rule_id == rule_id_known:
            print(f"rule: {rule_id} (engine-emitted)\nwaiver: (none)\ninvariant: {desc}")
            return 0
    print(f"analysis: unknown rule id `{rule_id}` — see --list-rules")
    return 1


def build_verdict(
    run: Run,
    verdict: baseline_mod.Verdict,
    baseline_path: str,
    imports: Dict[str, str],
    wall_s: float = 0.0,
) -> Dict:
    ok = (
        verdict.ok
        and not run.missing_targets
        and all(v == "ok" for v in imports.values())
    )
    findings = [dict(f.as_dict(), status="new") for f in verdict.new] + [
        dict(f.as_dict(), status="baselined") for f in verdict.baselined
    ]
    findings.sort(key=lambda d: (d["path"], d["line"], d["col"], d["rule"]))
    return {
        "version": VERDICT_VERSION,
        "verdict": "pass" if ok else "fail",
        "files_scanned": run.files_scanned,
        "files_cached": run.files_cached,
        "wall_s": wall_s,
        "missing_targets": list(run.missing_targets),
        "rules": _catalog(run),
        "findings": findings,
        "baseline": {
            "path": baseline_path,
            "stale": verdict.stale,
            "counts": baseline_mod.current_counts(run.findings),
        },
        "imports": imports,
        "dynamic_metric_names": sorted(run.dynamic_names),
        "skipped_paths": sorted(run.skipped),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ci.analysis",
        description="framework-aware AST static analysis gate (docs/development.md)",
    )
    ap.add_argument("targets", nargs="*", default=list(DEFAULT_TARGETS),
                    help=f"trees to analyze under --root (default: {' '.join(DEFAULT_TARGETS)})")
    ap.add_argument("--root", default=None, help="repo root (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: ci/analysis/baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings (ratchet: shrink or "
                         "hold; growth is refused without --allow-baseline-growth)")
    ap.add_argument("--allow-baseline-growth", action="store_true",
                    help="let --write-baseline add keys / raise counts — ONLY for "
                         "landing a new rule with its known findings frozen")
    ap.add_argument("--json", action="store_true", help="print the JSON verdict on stdout")
    ap.add_argument("--json-out", default=None, help="also write the JSON verdict here")
    ap.add_argument("--no-imports", action="store_true",
                    help="skip the package import smoke (fixture runs)")
    ap.add_argument("--list-rules", action="store_true", help="print the rule catalog and exit")
    ap.add_argument("--explain", metavar="RULE_ID", default=None,
                    help="print one rule's invariant, waiver form, and rationale, then exit")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write the per-file result cache "
                         "(ci/analysis/cache.json)")
    ap.add_argument("--time-budget", type=float, default=60.0,
                    help="analysis wall-time budget in seconds — printed with the "
                         "measured time; exceeding it warns, never fails (default 60)")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root or _repo_root())
    run = Run(root, targets=args.targets, use_cache=not args.no_cache)

    if args.list_rules:
        for row in _catalog(run):
            waiver = f"# {row['waiver']}-ok: <reason>" if row["waiver"] else "(no waiver)"
            print(f"{row['id']:24s} {waiver:28s} {row['description']}")
        return 0
    if args.explain is not None:
        return explain_rule(run, args.explain)

    baseline_path = args.baseline or os.path.join(
        root, "ci", "analysis", "baseline.json"
    )
    t0 = time.perf_counter()  # telemetry-ok: CLI wall-time budget, not framework stage timing
    run.analyze()
    wall_s = time.perf_counter() - t0
    baseline = baseline_mod.load(baseline_path)
    verdict = baseline_mod.apply(run.findings, baseline)

    if args.write_baseline:
        if run.missing_targets:
            for t in run.missing_targets:
                print(f"analysis: target `{t}` does not exist under {root} — refusing to write a baseline from a partial scan")
            return 1
        counts = baseline_mod.current_counts(run.findings)
        # a subset run (explicit sub-targets) must not erase entries for
        # trees it never scanned: preserve baseline keys for paths OUTSIDE
        # every scanned target prefix, ratchet only what this run covered
        # (a deleted file under a scanned target is covered — its entry
        # drops, as it should)
        # normalize the CLI spelling ('./spark_rapids_ml_tpu', trailing /)
        # to the repo-relative form finding paths use
        scanned = [
            os.path.normpath(t).replace(os.sep, "/") for t in run.targets
        ]
        # the registry rules' finalize pass emits findings at the schema/doc
        # paths on EVERY run, so those are covered (ratchetable) even though
        # they sit outside the scanned code trees
        finalize_paths = {
            run.sources.config_schema_relpath,
            run.sources.config_docs_relpath,
            run.sources.metric_docs_relpath,
        }

        def covered(path: str) -> bool:
            return path in finalize_paths or any(
                path == t or path.startswith(t + "/") for t in scanned
            )

        counts = dict(
            {k: v for k, v in baseline.items() if not covered(k.rsplit(":", 1)[0])},
            **counts,
        )
        grown = {
            k: (baseline.get(k, 0), v)
            for k, v in sorted(counts.items())
            if v > baseline.get(k, 0)
        }
        if grown and not args.allow_baseline_growth:
            # the ratchet only tightens: new violations are fixed or waived,
            # never parked — growth is reserved for landing a new rule
            for key, (old, new) in grown.items():
                print(f"analysis: refusing to grow baseline {key}: {old} -> {new}")
            print(
                "analysis: --write-baseline would GROW the baseline; fix/waive "
                "the findings above, or pass --allow-baseline-growth when "
                "landing a new rule (docs/development.md)"
            )
            return 1
        baseline_mod.dump(baseline_path, counts)
        print(
            f"analysis: baseline written to {baseline_path} "
            f"({len(run.findings)} finding(s) across {len(counts)} key(s))"
        )
        return 0

    imports = {} if args.no_imports else _import_smoke(root)
    payload = build_verdict(run, verdict, baseline_path, imports, wall_s=wall_s)

    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
    if args.json:
        print(json.dumps(payload, indent=2))
    else:
        for f_ in verdict.new:
            print(f_.render())
        for t in run.missing_targets:
            print(f"analysis: target `{t}` does not exist under {root} — nothing scanned")
        for mod, status in imports.items():
            if status != "ok":
                print(f"import {mod}: {status}")
        if verdict.stale:
            stale = ", ".join(f"{k} (-{v})" for k, v in sorted(verdict.stale.items()))
            print(
                f"analysis: baseline is stale — findings fixed under: {stale}; "
                "run `python -m ci.analysis --write-baseline` to ratchet down"
            )
        n_new = len(verdict.new) + len(run.missing_targets)
        n_imp = sum(1 for v in imports.values() if v != "ok")
        if payload["verdict"] == "pass":
            print(
                f"analysis: OK ({run.files_scanned} files, "
                f"{run.files_cached} cached, {len(run.rules)} rules, "
                f"{len(verdict.baselined)} baselined finding(s))"
            )
        else:
            print(f"analysis: {n_new + n_imp} issue(s)")
        over = " — OVER BUDGET" if wall_s > args.time_budget else ""
        print(
            f"analysis: wall time {wall_s:.2f}s "
            f"(budget {args.time_budget:g}s{over})"
        )
    return 0 if payload["verdict"] == "pass" else 1
