#
# Baseline ratchet: a new rule lands with its known findings FROZEN in
# ci/analysis/baseline.json (counts per `<path>:<rule-id>` — line numbers
# drift with unrelated edits, so positions are not pinned) and ratcheted
# down from there. Semantics:
#
#   * a finding whose key count exceeds the baseline is NEW -> gate fails;
#   * findings at or under their baselined count pass (reported as
#     "baselined", never silently dropped);
#   * when a file gets BETTER (count drops, incl. to zero) the stale
#     entries are reported and `--write-baseline` shrinks the file — the
#     ratchet only ever tightens.
#
# The acceptance state for this repo is an EMPTY baseline: every finding is
# fixed or carries a reasoned waiver at the line itself.
#
from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List

from .engine import Finding

VERSION = 1


def load(path: str) -> Dict[str, int]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    counts = data.get("counts", {})
    return {str(k): int(v) for k, v in counts.items()}


def dump(path: str, counts: Dict[str, int]) -> None:
    payload = {
        "version": VERSION,
        "counts": {k: v for k, v in sorted(counts.items()) if v > 0},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


@dataclass
class Verdict:
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale: Dict[str, int] = field(default_factory=dict)  # key -> unused slack

    @property
    def ok(self) -> bool:
        return not self.new


def apply(findings: List[Finding], baseline: Dict[str, int]) -> Verdict:
    """Split findings into new vs baselined. Within one key, the EARLIEST
    findings (file order) consume the baseline budget — deterministic, so
    the same tree always reports the same new findings."""
    verdict = Verdict()
    budget = dict(baseline)
    for f in findings:  # findings arrive sorted by (path, line, col, rule)
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            verdict.baselined.append(f)
        else:
            verdict.new.append(f)
    current = Counter(f.key for f in findings)
    for key, allowed in sorted(baseline.items()):
        if current.get(key, 0) < allowed:
            verdict.stale[key] = allowed - current.get(key, 0)
    return verdict


def current_counts(findings: List[Finding]) -> Dict[str, int]:
    return dict(Counter(f.key for f in findings))
