#
# Pass 1 of the two-pass analysis engine: the whole-program model the
# interprocedural concurrency rules (rules/concurrency.py) run on.
#
# Per file, `extract_facts` distills the parsed AST into a JSON-able fact
# dict — the repo-wide symbol table (functions/methods/classes), the lock
# inventory (every `threading.Lock/RLock/Condition` or `lockcheck.make_lock`
# construction, named `<module>.<Class>.<attr>` / `<module>.<global>`),
# `# guarded-by: <lock>` field declarations, lock-returning helpers
# (`def admission(self): return self._admission_lock`), and a per-function
# event stream: every lock ACQUIRE, CALL, potentially-BLOCKing operation,
# and guarded-field ACCESS, each tagged with the lexically-held lock set and
# any waiver tags on its lines. Facts are what the content-hash cache
# (cache.py) persists, so an unchanged file contributes to the whole-program
# pass without being re-parsed.
#
# `Program` assembles every file's facts into one model: cross-file call
# resolution (imports -> module functions; unique-method-name match with a
# receiver-name hint for the stdlib-shaped names like `.get`/`.release`),
# then three fixpoints pass 2 consumes:
#
#   trans_acquires(f)  locks f may acquire, directly or through any resolved
#                      call chain (with the acquisition site + chain)
#   may_block(f)       blocking operations f may reach, likewise
#   entry_held(f)      locks held at EVERY resolved in-program call site of
#                      f (intersection) — how `_locked`-suffixed helpers and
#                      other always-called-under-lock functions are proven
#                      safe without lexical `with` blocks of their own
#
# Soundness posture (documented in docs/development.md): dynamic dispatch the
# resolver cannot see (callbacks, hooks, thread targets, ambiguous method
# names) is skipped, never guessed — the rules prefer missed findings over
# false cycles.
#
# PR 15 grew the same per-function event stream a NUMERICS layer (consumed by
# rules/numerics.py): every local dtype binding is tracked through a small
# lattice (f64/f32/bf16/f16), emitting
#
#   narrow   an f64-bound local rebound/augmented with a narrower expression
#            (silent accumulator narrowing)
#   lowdot   a dot-like call (dot/matmul/einsum/tensordot/pl.dot or the `@`
#            operator) with per-operand dtype descriptors ({"dt": token} when
#            locally evident, {"param": name} when the operand is a bare
#            function parameter) and its `preferred_element_type` token
#   f64      a jnp-level float64 constant/cast/ctor, tagged with whether it
#            sits lexically under an x64 guard (`enable_x64`/`x64_scope`
#            context or a `jax_enable_x64` conditional)
#
# and call events carry `argdt` (positional-arg dtype descriptors) + `x64`
# so pass 2 can thread dtypes and x64-guardedness through resolved calls.
#
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple, Union

# ------------------------------------------------------------ lock spotting --

_LOCK_CTOR_KINDS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
}
# the runtime sanitizer's factory (utils/lockcheck.py) — construction through
# it must stay visible to the static pass
_LOCKCHECK_FACTORIES = {"make_lock", "make_condition"}

# blocking-operation trigger set for the held-critical-section rule
_RENDEZVOUS_TAILS = {"barrier", "allgather", "allgather_concat", "reform"}
_NETWORK_CALLS = {
    "urllib.request.urlopen",
    "socket.create_connection",
    "socket.create_server",
}

# method tails too generic to resolve by name alone (dict.get, list.append,
# str.join, file.write ... would alias onto framework methods); these resolve
# only when the receiver's name hints at the owning class (`self._ledger
# .release` -> HbmLedger.release, but `self._entries.get` stays unresolved)
_COMMON_METHOD_TAILS = {
    "get", "put", "set", "pop", "add", "append", "extend", "clear", "keys",
    "values", "items", "update", "copy", "remove", "discard", "insert",
    "sort", "reverse", "count", "index", "join", "split", "strip", "read",
    "write", "close", "flush", "open", "send", "recv", "load", "save",
    "dump", "dumps", "loads", "popleft", "appendleft", "setdefault",
    "move_to_end", "total", "release", "acquire", "submit", "result",
    "done", "start", "stop", "run", "record", "reset", "stats", "fit",
    "wait", "notify", "names", "events", "tail",
}

_WAIVER_TAGS = ("lock-order", "held", "guard", "precision")

# ------------------------------------------------------------ dtype lattice --

# spelled dtype -> lattice token; anything else is "unknown" (None)
_DTYPE_TOKENS = {
    "float64": "f64", "double": "f64", "f64": "f64",
    "float32": "f32", "single": "f32", "f32": "f32",
    "bfloat16": "bf16", "bf16": "bf16",
    "float16": "f16", "half": "f16", "f16": "f16",
}
# dot-like call tails the lowdot event covers (plus the `@` operator and
# einsum, handled separately for its leading equation string)
_DOT_TAILS = {"dot", "dot_general", "matmul", "tensordot"}
# array constructors whose dtype argument types the RESULT
_DTYPE_CTORS = {
    "zeros", "ones", "full", "empty", "array", "asarray", "arange",
    "linspace", "eye", "zeros_like", "ones_like", "full_like", "empty_like",
}
# attribute accesses that preserve the receiver's dtype (`x.T`, `x.mT`)
_DTYPE_TRANSPARENT_ATTRS = {"T", "mT", "real"}


def _dtype_token(expr: Optional[ast.AST], imports: Dict[str, str]) -> Optional[str]:
    """A dtype-position expression (`jnp.float64`, `np.float32`, "bfloat16")
    -> lattice token, else None."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return _DTYPE_TOKENS.get(expr.value)
    name = _dotted(expr, imports) if expr is not None else None
    if name is None:
        return None
    return _DTYPE_TOKENS.get(name.split(".")[-1])


def _is_jax_dtype(expr: Optional[ast.AST], imports: Dict[str, str]) -> bool:
    """Whether a dtype-position expression is spelled through jax (`jnp.
    float64`) rather than numpy — host-side np.float64 is sanctioned, a
    device-side jnp f64 needs the x64 guard."""
    name = _dotted(expr, imports) if expr is not None else None
    return name is not None and name.startswith("jax")


def _mentions_x64(expr: ast.AST) -> bool:
    """Whether an expression names the x64 machinery (`enable_x64(...)`,
    `x64_scope(...)`, `jax.config.jax_enable_x64`) — the lexical guard the
    f64 events record."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and "x64" in node.id:
            return True
        if isinstance(node, ast.Attribute) and "x64" in node.attr:
            return True
    return False

# a held-set entry is a resolved lock id (str) or an unresolved
# `with helper():` call spec (dict) normalized at assembly
HeldEntry = Union[str, Dict[str, Any]]


def module_path(relpath: str) -> str:
    """Repo relpath -> the short dotted module id lock/function names use:
    `spark_rapids_ml_tpu/scheduler/ledger.py` -> `scheduler.ledger` (package
    prefix dropped for readability; `__init__.py` names the package)."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = p.split("/")
    if parts and parts[0] == "spark_rapids_ml_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else "pkg"


def full_module(relpath: str) -> str:
    """Repo relpath -> the full dotted import path (`spark_rapids_ml_tpu.
    scheduler.ledger`) used to resolve import origins."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _dotted(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(imports.get(node.id, node.id))
    return ".".join(reversed(parts))


def _ctor_kind(value: Optional[ast.AST], imports: Dict[str, str]) -> Optional[str]:
    """Lock kind when `value` constructs one (threading.* or the lockcheck
    factory), else None."""
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func, imports)
    if name is None:
        return None
    for ctor, kind in _LOCK_CTOR_KINDS.items():
        if name == ctor or name.endswith("." + ctor):
            return kind
    tail = name.split(".")[-1]
    if tail in _LOCKCHECK_FACTORIES:
        if tail == "make_condition":
            return "condition"
        for kw in value.keywords:
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                return str(kw.value.value)
        if len(value.args) > 1 and isinstance(value.args[1], ast.Constant):
            return str(value.args[1].value)
        return "lock"
    return None


def _parse_guard(comment: str) -> Optional[str]:
    """`# guarded-by: <lock>` -> the lock name, else None. The declaration
    may trail prose (`# events ever recorded  # guarded-by: _lock`)."""
    idx = comment.find("guarded-by:")
    if idx < 0:
        return None
    name = comment[idx + len("guarded-by:"):].strip()
    return name.split()[0] if name else None


# ------------------------------------------------------------- extraction ---


class _FactsBuilder:
    """One file -> fact dict (see module docstring). Walks class/function
    structure itself so every event carries the enclosing function and the
    lexically-held lock tuple."""

    def __init__(self, ctx: Any):
        self.ctx = ctx
        self.mod = module_path(ctx.relpath)
        self.imports: Dict[str, str] = dict(ctx.imports)
        self.locks: Dict[str, Dict[str, Any]] = {}
        self.guards: Dict[str, Dict[str, Any]] = {}
        self.guard_problems: List[Dict[str, Any]] = []
        self.lock_returns: Dict[str, str] = {}
        self.functions: Dict[str, Dict[str, Any]] = {}
        self.classes: List[str] = []
        # filled by the pre-scan so a method defined ABOVE __init__ still
        # resolves `self._lock`
        self._class_locks: Dict[str, Dict[str, str]] = {}
        self._module_locks: Dict[str, str] = {}
        self._class_guards: Dict[str, Dict[str, str]] = {}
        self._module_guards: Dict[str, str] = {}
        # numerics layer: per-function local dtype environment + param set
        # (live only while that function is being scanned)
        self._envs: Dict[str, Dict[str, str]] = {}
        self._params: Dict[str, List[str]] = {}
        self._x64_depth = 0  # lexical x64-guard nesting (With/If markers)

    # -- entry -------------------------------------------------------------
    def build(self, tree: ast.Module) -> Dict[str, Any]:
        self._prescan(tree)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._build_class(node, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(node, f"{self.mod}.{node.name}", None)
        self._resolve_guard_locks()
        return {
            "relpath": self.ctx.relpath,
            "module": self.mod,
            "full_module": full_module(self.ctx.relpath),
            "classes": list(self.classes),
            "locks": self.locks,
            "guards": self.guards,
            "guard_problems": self.guard_problems,
            "lock_returns": self.lock_returns,
            "functions": self.functions,
        }

    # -- pre-scan: lock + guard declarations -------------------------------
    def _prescan(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                kind = _ctor_kind(getattr(node, "value", None), self.imports)
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if kind is not None:
                        lock_id = f"{self.mod}.{t.id}"
                        self.locks[lock_id] = {
                            "kind": kind, "relpath": self.ctx.relpath,
                            "line": node.lineno, "attr": t.id, "cls": None,
                        }
                        self._module_locks[t.id] = lock_id
                    else:
                        guard = self._guard_on(node)
                        if guard is not None:
                            key = f"{self.mod}.{t.id}"
                            self._module_guards[t.id] = key
                            self.guards[key] = {
                                "lock_name": guard, "relpath": self.ctx.relpath,
                                "line": node.lineno, "cls": None, "attr": t.id,
                            }
            elif isinstance(node, ast.ClassDef):
                self._prescan_class(node, node.name)

    def _build_class(self, cls: ast.ClassDef, name: str) -> None:
        """Visit a class's methods (and recurse into NESTED classes —
        `LocalRendezvous._Shared`-style holders own real locks too)."""
        self.classes.append(name)
        for sub in cls.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(sub, f"{self.mod}.{name}.{sub.name}", name)
            elif isinstance(sub, ast.ClassDef):
                self._build_class(sub, f"{name}.{sub.name}")

    def _prescan_class(self, cls: ast.ClassDef, name: str) -> None:
        for sub in cls.body:
            if isinstance(sub, ast.ClassDef):
                self._prescan_class(sub, f"{name}.{sub.name}")
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in ast.walk(method):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                for t in targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    kind = _ctor_kind(stmt.value, self.imports)
                    if kind is not None:
                        lock_id = f"{self.mod}.{name}.{t.attr}"
                        self.locks[lock_id] = {
                            "kind": kind, "relpath": self.ctx.relpath,
                            "line": stmt.lineno, "attr": t.attr, "cls": name,
                        }
                        self._class_locks.setdefault(name, {})[t.attr] = lock_id
                    elif method.name == "__init__":
                        guard = self._guard_on(stmt)
                        if guard is not None:
                            key = f"{self.mod}.{name}.{t.attr}"
                            self._class_guards.setdefault(name, {})[t.attr] = key
                            self.guards[key] = {
                                "lock_name": guard, "relpath": self.ctx.relpath,
                                "line": stmt.lineno, "cls": name, "attr": t.attr,
                            }

    def _guard_on(self, node: ast.AST) -> Optional[str]:
        lo = getattr(node, "lineno", None)
        hi = getattr(node, "end_lineno", None) or lo
        if lo is None:
            return None
        for ln in range(lo, hi + 1):
            comment = self.ctx.comments.get(ln)
            if comment:
                guard = _parse_guard(comment)
                if guard is not None:
                    return guard
        return None

    def _resolve_guard_locks(self) -> None:
        """Turn each guard's `lock_name` into a lock id; unresolvable names
        become guard_problems (the rule reports them — a typo'd guarded-by
        must not silently guard nothing)."""
        for key, g in self.guards.items():
            name = g.pop("lock_name")
            attr = name[5:] if name.startswith("self.") else name
            lock_id = None
            if g["cls"] is not None:
                lock_id = self._class_locks.get(g["cls"], {}).get(attr)
            if lock_id is None:
                lock_id = self._module_locks.get(attr)
            if lock_id is None:
                self.guard_problems.append(
                    {
                        "relpath": g["relpath"], "line": g["line"],
                        "attr": g["attr"], "name": name,
                    }
                )
            g["lock"] = lock_id

    # -- helpers -----------------------------------------------------------
    def _waived(self, node: ast.AST) -> List[str]:
        return [tag for tag in _WAIVER_TAGS if self.ctx.waived(tag, node)]

    def _lock_of_expr(self, expr: Optional[ast.AST], cls: Optional[str]) -> Optional[str]:
        """Resolve an expression to a lock id when statically evident:
        `self._lock` (class lock attr), a module-global lock name, or — for
        non-self receivers — an attr that is a lock of exactly ONE class in
        this file."""
        if isinstance(expr, ast.Name):
            return self._module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" and cls:
                hit = self._class_locks.get(cls, {}).get(expr.attr)
                if hit:
                    return hit
            owners = [c for c, attrs in self._class_locks.items() if expr.attr in attrs]
            if len(owners) == 1:
                return self._class_locks[owners[0]][expr.attr]
            if len(owners) > 1:
                # `scope.lock` when several classes declare `lock`: the
                # receiver name disambiguates (same hint rule as calls)
                hinted = [c for c in owners if self._recv_hint(expr.value, c)]
                if len(hinted) == 1:
                    return self._class_locks[hinted[0]][expr.attr]
        return None

    def _target_spec(self, node: ast.Call, cls: Optional[str]) -> Optional[Dict[str, Any]]:
        func = node.func
        if isinstance(func, ast.Name):
            return {"kind": "name", "tail": func.id, "name": self.imports.get(func.id, func.id)}
        if isinstance(func, ast.Attribute):
            recv = func.value
            spec: Dict[str, Any] = {
                "kind": "attr",
                "tail": func.attr,
                "dotted": _dotted(func, self.imports),
            }
            if isinstance(recv, ast.Name) and recv.id == "self" and cls is not None:
                spec["self_cls"] = cls
            hint = None
            if isinstance(recv, ast.Attribute):
                hint = recv.attr
            elif isinstance(recv, ast.Name):
                hint = recv.id
            spec["recv_hint"] = hint
            return spec
        return None

    # -- function bodies ---------------------------------------------------
    def _function(
        self, fn: ast.AST, qual: str, cls: Optional[str],
        parent_env: Optional[Dict[str, str]] = None,
    ) -> None:
        args = fn.args
        params = [
            a.arg
            for a in getattr(args, "posonlyargs", []) + args.args
            if a.arg not in ("self", "cls")
        ]
        events: List[Dict[str, Any]] = []
        self.functions[qual] = {
            "relpath": self.ctx.relpath, "line": fn.lineno,
            "cls": cls, "name": fn.name, "events": events,
            "params": params,
        }
        # lock-returning helper: `return self._admission_lock`
        for stmt in fn.body:
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                lock = self._lock_of_expr(stmt.value, cls)
                if lock is not None:
                    self.lock_returns[qual] = lock
        # closures read outer locals: a nested def's dtype env starts as a
        # COPY of what was visible at its definition point
        self._envs[qual] = dict(parent_env) if parent_env else {}
        self._params[qual] = params
        # like `held`, the x64 guard does NOT extend into a nested def: the
        # closure runs when CALLED, after the scoped guard has exited
        saved_x64 = self._x64_depth
        self._x64_depth = 0
        self._scan_block(fn.body, qual, cls, held=(), region_waived=frozenset())
        self._x64_depth = saved_x64

    def _scan_block(
        self, body: Sequence[ast.AST], qual: str, cls: Optional[str],
        held: Tuple[HeldEntry, ...], region_waived: frozenset,
    ) -> None:
        for stmt in body:
            self._scan_stmt(stmt, qual, cls, held, region_waived)

    def _scan_stmt(
        self, stmt: ast.AST, qual: str, cls: Optional[str],
        held: Tuple[HeldEntry, ...], region_waived: frozenset,
    ) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs when CALLED, not here — own function entry
            # (thread targets, closures), resolvable as `<qual>.<name>`
            self._function(stmt, f"{qual}.{stmt.name}", cls,
                           parent_env=self._envs.get(qual))
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            # a waiver on the `with` header covers the whole critical
            # section it opens — the reason describes the SECTION, so every
            # event inside inherits it
            inner_waived = region_waived | frozenset(self._waived(stmt))
            for item in stmt.items:
                lock = self._lock_of_expr(item.context_expr, cls)
                if lock is not None:
                    self._emit(qual, "acq", stmt, held=inner, lock=lock,
                               waiver_node=stmt, region_waived=region_waived)
                    inner = inner + (lock,)
                    continue
                # scan the header expr (calls/blocking/accesses inside it)
                self._scan_expr(item.context_expr, qual, cls, inner, region_waived)
                if isinstance(item.context_expr, ast.Call):
                    spec = self._target_spec(item.context_expr, cls)
                    if spec is not None:
                        # `with self._ledger.admission():` — the helper's
                        # returned lock is resolved at assembly; held-set
                        # entries carry the spec until then
                        self._emit(qual, "acq", stmt, held=inner, lock=None,
                                   via_call=spec, waiver_node=stmt,
                                   region_waived=region_waived)
                        inner = inner + ({"call": spec},)
            # `with enable_x64(True):` / `with x64_scope(...):` — f64 events
            # inside the section are guarded
            guard_x64 = any(_mentions_x64(i.context_expr) for i in stmt.items)
            if guard_x64:
                self._x64_depth += 1
            self._scan_block(stmt.body, qual, cls, inner, inner_waived)
            if guard_x64:
                self._x64_depth -= 1
            return
        if isinstance(stmt, ast.If) and _mentions_x64(stmt.test):
            # `if jax.config.jax_enable_x64:` guards its TRUE arm; a negated
            # test (`if not ...:`, `... == False`/`is False`) guards the
            # ELSE arm instead — the true arm there runs precisely when x64
            # is OFF, the exact state the f64 findings exist for
            self._scan_expr(stmt.test, qual, cls, held, region_waived)
            negated = isinstance(stmt.test, ast.UnaryOp) and isinstance(
                stmt.test.op, ast.Not
            )
            if isinstance(stmt.test, ast.Compare) and len(stmt.test.ops) == 1:
                comp = stmt.test.comparators[0]
                if isinstance(comp, ast.Constant) and comp.value is False:
                    # `== False` / `is False` negate; `!= False` / `is not
                    # False` are truthy exactly when x64 is ON
                    negated = isinstance(stmt.test.ops[0], (ast.Eq, ast.Is))
            for arm, guarded in ((stmt.body, not negated), (stmt.orelse, negated)):
                if guarded:
                    self._x64_depth += 1
                self._scan_block(arm, qual, cls, held, region_waived)
                if guarded:
                    self._x64_depth -= 1
            return
        for expr in self._stmt_exprs(stmt):
            self._scan_expr(expr, qual, cls, held, region_waived)
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in targets:
                for node in ast.walk(t):
                    self._maybe_access(node, qual, cls, held, "write", region_waived)
            self._track_dtype(stmt, qual, held, region_waived)
        for block in self._stmt_blocks(stmt):
            self._scan_block(block, qual, cls, held, region_waived)

    @staticmethod
    def _stmt_exprs(stmt: ast.AST) -> List[ast.AST]:
        out = []
        for field in ("value", "test", "iter", "exc", "msg", "cause"):
            v = getattr(stmt, field, None)
            if isinstance(v, ast.AST):
                out.append(v)
        return out

    @staticmethod
    def _stmt_blocks(stmt: ast.AST) -> List[List[ast.AST]]:
        out = []
        for field in ("body", "orelse", "finalbody"):
            v = getattr(stmt, field, None)
            if isinstance(v, list):
                out.append(v)
        for h in getattr(stmt, "handlers", None) or []:
            out.append(h.body)
        return out

    # -- expressions: calls, blocking ops, guarded accesses ----------------
    def _scan_expr(
        self, expr: ast.AST, qual: str, cls: Optional[str],
        held: Tuple[HeldEntry, ...], region_waived: frozenset,
    ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Call):
                self._call_event(node, qual, cls, held, region_waived)
            else:
                if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                    # `a @ b`: the operator spelling of a dot-like op — no
                    # preferred_element_type is expressible here, so a
                    # low-precision operand is always a finding candidate
                    self._emit(
                        qual, "lowdot", node, held=held, waiver_node=node,
                        region_waived=region_waived, op="@",
                        args=[self._operand_desc(node.left, qual),
                              self._operand_desc(node.right, qual)],
                        pref=None,
                    )
                self._maybe_access(node, qual, cls, held, "read", region_waived)

    def _maybe_access(
        self, node: ast.AST, qual: str, cls: Optional[str],
        held: Tuple[HeldEntry, ...], mode: str, region_waived: frozenset,
    ) -> None:
        key = None
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self" and cls:
                key = self._class_guards.get(cls, {}).get(node.attr)
            if key is None:
                owners = [
                    c for c, attrs in self._class_guards.items()
                    if node.attr in attrs and c != cls
                ]
                if len(owners) == 1 and self._recv_hint(node.value, owners[0]):
                    key = self._class_guards[owners[0]][node.attr]
        elif isinstance(node, ast.Name):
            key = self._module_guards.get(node.id)
        if key is not None:
            self._emit(qual, "access", node, held=held, guard=key, mode=mode,
                       waiver_node=node, region_waived=region_waived)

    @staticmethod
    def _recv_hint(recv: ast.AST, cls_name: str) -> bool:
        tail = None
        if isinstance(recv, ast.Attribute):
            tail = recv.attr
        elif isinstance(recv, ast.Name):
            tail = recv.id
        if not tail:
            return False
        t = tail.strip("_").lower().replace("_", "")
        return bool(t) and t in cls_name.lower()

    # -- numerics layer: local dtype inference + events ---------------------
    _PRECISION_ORDER = {"f64": 3, "f32": 2, "bf16": 1, "f16": 1}
    _RANDOM_SAMPLER_TAILS = {
        "normal", "uniform", "truncated_normal", "gamma", "beta",
        "exponential", "laplace", "gumbel",
    }

    def _promote(self, a: Optional[str], b: Optional[str]) -> Optional[str]:
        """Widest-wins promotion; "weak" (python scalar) defers, unknown
        poisons — a result mixing unknown operands stays unknown so the
        narrow check never fires on guessed dtypes."""
        if a == "weak":
            return b
        if b == "weak":
            return a
        if a is None or b is None:
            return None
        return a if self._PRECISION_ORDER[a] >= self._PRECISION_ORDER[b] else b

    def _expr_dtype(self, expr: ast.AST, qual: str) -> Optional[str]:
        tok = self._dt(expr, self._envs.get(qual, {}))
        return None if tok == "weak" else tok

    def _dt(self, node: ast.AST, env: Dict[str, str]) -> Optional[str]:
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Constant):
            return "weak" if isinstance(node.value, (int, float)) else None
        if isinstance(node, ast.Attribute):
            if node.attr in _DTYPE_TRANSPARENT_ATTRS:
                return self._dt(node.value, env)
            return None
        if isinstance(node, ast.Subscript):
            return self._dt(node.value, env)
        if isinstance(node, ast.UnaryOp):
            return self._dt(node.operand, env)
        if isinstance(node, ast.BinOp):
            return self._promote(self._dt(node.left, env), self._dt(node.right, env))
        if isinstance(node, ast.IfExp):
            a, b = self._dt(node.body, env), self._dt(node.orelse, env)
            return a if a == b else None
        if isinstance(node, ast.Call):
            return self._call_dtype(node, env)
        return None

    def _call_dtype(self, node: ast.Call, env: Dict[str, str]) -> Optional[str]:
        name = _dotted(node.func, self.imports)
        tail = None
        if isinstance(node.func, ast.Attribute):
            tail = node.func.attr
        elif name is not None:
            tail = name.split(".")[-1]
        if tail == "astype" and node.args:
            return _dtype_token(node.args[0], self.imports)
        kw_dtype = None
        for kw in node.keywords:
            if kw.arg == "dtype":
                kw_dtype = kw.value
        if tail in _DTYPE_CTORS:
            dt_expr = kw_dtype
            if dt_expr is None and len(node.args) > 1:
                dt_expr = node.args[1]
            tok = _dtype_token(dt_expr, self.imports)
            if tok is not None:
                return tok
            if tail.endswith("_like") and node.args:
                return self._dt(node.args[0], env)
            return None
        if tail in self._RANDOM_SAMPLER_TAILS and name and name.startswith("jax.random"):
            dt_expr = kw_dtype if kw_dtype is not None else (
                node.args[2] if len(node.args) > 2 else None
            )
            return _dtype_token(dt_expr, self.imports)
        if tail in _DOT_TAILS or tail == "einsum":
            for kw in node.keywords:
                if kw.arg == "preferred_element_type":
                    return _dtype_token(kw.value, self.imports)
            out: Optional[str] = "weak"
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    continue  # einsum equation
                out = self._promote(out, self._dt(a, env))
            return None if out == "weak" else out
        if name is not None and _DTYPE_TOKENS.get(name.split(".")[-1]) and node.args:
            return _DTYPE_TOKENS[name.split(".")[-1]]  # jnp.float64(x)-style cast
        return None

    def _track_dtype(
        self, stmt: ast.AST, qual: str,
        held: Tuple[HeldEntry, ...], region_waived: frozenset,
    ) -> None:
        """Maintain the per-function dtype env across (Ann/Aug)Assign and
        emit `narrow` events when an f64 binding takes a narrower value."""
        env = self._envs.get(qual)
        value = getattr(stmt, "value", None)
        if env is None or value is None:
            return
        new_dt = self._expr_dtype(value, qual)
        if isinstance(stmt, ast.AugAssign):
            t = stmt.target
            # `acc += f32_expr` on an f64 accumulator: the dtype survives the
            # promotion but the ADDEND was computed at the narrow precision
            if (
                isinstance(t, ast.Name)
                and env.get(t.id) == "f64"
                and new_dt in ("f32", "bf16", "f16")
            ):
                self._emit(qual, "narrow", stmt, held=held, waiver_node=stmt,
                           region_waived=region_waived, name=t.id,
                           frm="f64", to=new_dt, aug=True)
            return
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                if env.get(t.id) == "f64" and new_dt in ("f32", "bf16", "f16"):
                    self._emit(qual, "narrow", stmt, held=held, waiver_node=stmt,
                               region_waived=region_waived, name=t.id,
                               frm="f64", to=new_dt, aug=False)
                if new_dt is not None:
                    env[t.id] = new_dt
                else:
                    env.pop(t.id, None)
            else:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        env.pop(sub.id, None)

    def _operand_desc(self, expr: ast.AST, qual: str) -> Dict[str, Any]:
        """Dtype descriptor for a dot operand / call argument: a locally
        evident dtype, a bare parameter reference (resolved interprocedurally
        in pass 2), or unknown."""
        dt = self._expr_dtype(expr, qual)
        if dt is not None:
            return {"dt": dt}
        inner = expr
        while isinstance(inner, ast.Attribute) and inner.attr in _DTYPE_TRANSPARENT_ATTRS:
            inner = inner.value
        if isinstance(inner, ast.Name) and inner.id in self._params.get(qual, []):
            return {"param": inner.id}
        return {"dt": None}

    def _numeric_events(
        self, node: ast.Call, qual: str, dotted_name: Optional[str],
        tail: Optional[str], held: Tuple[HeldEntry, ...], region_waived: frozenset,
    ) -> None:
        jaxish = dotted_name is not None and dotted_name.startswith("jax")
        if jaxish and (tail in _DOT_TAILS or tail == "einsum"):
            pref: Optional[str] = None
            for kw in node.keywords:
                if kw.arg == "preferred_element_type":
                    pref = _dtype_token(kw.value, self.imports) or "dynamic"
            args = [
                a for a in node.args
                if not (isinstance(a, ast.Constant) and isinstance(a.value, str))
            ]
            self._emit(qual, "lowdot", node, held=held, waiver_node=node,
                       region_waived=region_waived, op=tail,
                       args=[self._operand_desc(a, qual) for a in args[:4]],
                       pref=pref)
        f64 = False
        if (
            tail == "astype"
            and node.args
            and _dtype_token(node.args[0], self.imports) == "f64"
            and _is_jax_dtype(node.args[0], self.imports)
        ):
            f64 = True  # x.astype(jnp.float64) — device-side widening intent
        elif jaxish and tail is not None and _DTYPE_TOKENS.get(tail) == "f64":
            f64 = True  # jnp.float64(x)
        elif jaxish:
            dt_expr = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt_expr = kw.value
            if dt_expr is None and tail in _DTYPE_CTORS and len(node.args) > 1:
                dt_expr = node.args[1]
            if dt_expr is not None and _dtype_token(dt_expr, self.imports) == "f64":
                f64 = True  # jnp ctor/sampler typed float64
        if f64:
            self._emit(qual, "f64", node, held=held, waiver_node=node,
                       region_waived=region_waived, x64=self._x64_depth > 0)

    def _call_event(
        self, node: ast.Call, qual: str, cls: Optional[str],
        held: Tuple[HeldEntry, ...], region_waived: frozenset,
    ) -> None:
        dotted = _dotted(node.func, self.imports)
        tail = None
        recv: Optional[ast.AST] = None
        if isinstance(node.func, ast.Attribute):
            tail = node.func.attr
            recv = node.func.value
        elif isinstance(node.func, ast.Name):
            tail = node.func.id
        block = self._block_op(node, dotted, tail, recv, cls)
        if block is not None:
            self._emit(qual, "block", node, held=held, waiver_node=node,
                       region_waived=region_waived, **block)
        self._numeric_events(node, qual, dotted, tail, held, region_waived)
        spec = self._target_spec(node, cls)
        if spec is not None:
            # positional alignment is broken past a *args splat: stop there,
            # so param_dtypes only ever meets a dtype into the parameter
            # that actually receives it (later params fall off the list and
            # resolve to unknown)
            argdt: List[Dict[str, Any]] = []
            for a in node.args[:8]:
                if isinstance(a, ast.Starred):
                    break
                argdt.append(self._operand_desc(a, qual))
            self._emit(qual, "call", node, held=held, target=spec,
                       argdt=argdt, x64=self._x64_depth > 0,
                       waiver_node=node, region_waived=region_waived)

    def _block_op(
        self, node: ast.Call, dotted: Optional[str], tail: Optional[str],
        recv: Optional[ast.AST], cls: Optional[str],
    ) -> Optional[Dict[str, Any]]:
        if dotted == "time.sleep":
            return {"op": "time.sleep()"}
        if tail == "block_until_ready" or dotted == "jax.block_until_ready":
            return {"op": "block_until_ready() (device sync)"}
        if dotted == "jax.device_get":
            return {"op": "jax.device_get() (host fetch)"}
        if tail == "item" and not node.args and not node.keywords:
            return {"op": ".item() (host fetch)"}
        if tail == "wait":
            recv_lock = self._lock_of_expr(recv, cls)
            return {"op": ".wait() (event/condition wait)", "recv_lock": recv_lock}
        if tail in _RENDEZVOUS_TAILS:
            return {"op": f".{tail}() (rendezvous round)"}
        if tail == "join" and dotted is not None and "thread" in dotted.lower():
            return {"op": ".join() (thread join)"}
        if tail == "result":
            return {"op": ".result() (future wait)"}
        if (
            isinstance(node.func, ast.Name)
            and node.func.id == "open"
            and "open" not in self.imports
        ):
            return {"op": "open() (file I/O)"}
        if dotted in _NETWORK_CALLS or (
            dotted is not None and dotted.startswith(("requests.", "subprocess."))
        ):
            return {"op": f"{dotted}() (network/subprocess)"}
        return None

    def _emit(
        self, qual: str, t: str, node: ast.AST, *,
        held: Tuple[HeldEntry, ...], waiver_node: ast.AST,
        region_waived: frozenset = frozenset(), **fields: Any,
    ) -> None:
        ev = {
            "t": t,
            "line": getattr(node, "lineno", 1),
            "col": getattr(node, "col_offset", 0) + 1,
            "held": list(held),
            "waived": sorted(set(self._waived(waiver_node)) | region_waived),
        }
        ev.update(fields)
        self.functions[qual]["events"].append(ev)


def extract_facts(ctx: Any) -> Optional[Dict[str, Any]]:
    """File facts for the whole-program pass; None for unparsable files (the
    syntax-error finding already fails the gate)."""
    if ctx.tree is None:
        return None
    return _FactsBuilder(ctx).build(ctx.tree)


# --------------------------------------------------------------- assembly ---


class Program:
    """Every file's facts assembled into one model + the fixpoints
    (module docstring). Rebuilt each run from (possibly cached) facts —
    assembly is linear in the fact count and costs milliseconds."""

    def __init__(self, facts_by_file: Dict[str, Optional[Dict[str, Any]]]):
        self.files: Dict[str, Dict[str, Any]] = {
            rel: f for rel, f in facts_by_file.items() if f is not None
        }
        self.locks: Dict[str, Dict[str, Any]] = {}
        self.guards: Dict[str, Dict[str, Any]] = {}
        self.guard_problems: List[Dict[str, Any]] = []
        self.lock_returns: Dict[str, str] = {}
        self.functions: Dict[str, Dict[str, Any]] = {}
        self._method_index: Dict[str, List[str]] = {}
        self._module_by_full: Dict[str, str] = {}
        self._class_index: Dict[str, List[str]] = {}
        for f in self.files.values():
            self.locks.update(f["locks"])
            self.guards.update(f["guards"])
            self.guard_problems.extend(f["guard_problems"])
            self.lock_returns.update(f["lock_returns"])
            self.functions.update(f["functions"])
            self._module_by_full[f["full_module"]] = f["module"]
            for c in f["classes"]:
                self._class_index.setdefault(c, []).append(f["module"])
        for qual, fn in self.functions.items():
            if fn["cls"] is not None:
                self._method_index.setdefault(fn["name"], []).append(qual)
        self._resolve_all()
        self._trans_acq: Optional[Dict[str, Dict[str, Any]]] = None
        self._may_blk: Optional[Dict[str, Dict[str, Any]]] = None
        self._entry_held: Optional[Dict[str, Set[str]]] = None
        self._param_dt: Optional[Dict[str, Dict[str, Optional[str]]]] = None
        self._entry_x64: Optional[Dict[str, bool]] = None

    # -- call resolution ---------------------------------------------------
    def _module_of_dotted_head(self, head: str) -> Optional[str]:
        """Match a dotted import origin against known modules by suffix —
        `scheduler.ledger`, `ledger`, and the full import path all hit."""
        for full, mod in self._module_by_full.items():
            if full == head or full.endswith("." + head):
                return mod
        for f in self.files.values():
            if f["module"] == head or f["module"].endswith("." + head):
                return f["module"]
        return None

    def _resolve_target(self, caller_qual: str, spec: Dict[str, Any]) -> Optional[str]:
        tail = spec["tail"]
        mod = self.functions[caller_qual]["relpath"]
        mod = module_path(mod)
        if spec["kind"] == "name":
            name = spec["name"]
            nested = f"{caller_qual}.{name}"
            if nested in self.functions:
                return nested
            local = f"{mod}.{name}"
            if local in self.functions:
                return local
            if name in self._class_index and len(self._class_index[name]) == 1:
                init = f"{self._class_index[name][0]}.{name}.__init__"
                return init if init in self.functions else None
            if "." in name:
                head, _, f_name = name.rpartition(".")
                owner = self._module_of_dotted_head(head)
                if owner is not None:
                    cand = f"{owner}.{f_name}"
                    if cand in self.functions:
                        return cand
                    init = f"{owner}.{f_name}.__init__"
                    if init in self.functions:
                        return init
            return None
        if spec.get("self_cls"):
            cand = f"{mod}.{spec['self_cls']}.{tail}"
            if cand in self.functions:
                return cand
        dotted = spec.get("dotted")
        if dotted and "." in dotted:
            head, _, f_name = dotted.rpartition(".")
            owner = self._module_of_dotted_head(head)
            if owner is not None:
                cand = f"{owner}.{f_name}"
                if cand in self.functions:
                    return cand
        candidates = self._method_index.get(tail, [])
        if not candidates:
            return None
        hint = spec.get("recv_hint")
        if len(candidates) > 1 or tail in _COMMON_METHOD_TAILS:
            if hint is None:
                return None
            hinted = [
                q for q in candidates
                if self._hint_matches(hint, self.functions[q]["cls"])
            ]
            return hinted[0] if len(hinted) == 1 else None
        return candidates[0]

    @staticmethod
    def _hint_matches(hint: str, cls_name: Optional[str]) -> bool:
        if not cls_name:
            return False
        t = hint.strip("_").lower().replace("_", "")
        return bool(t) and t in cls_name.lower()

    def _resolve_all(self) -> None:
        for qual, fn in self.functions.items():
            for ev in fn["events"]:
                if ev["t"] == "call":
                    callee = self._resolve_target(qual, ev["target"])
                    ev["callee"] = callee
                    if callee is not None and callee in self.lock_returns:
                        ev["returns_lock"] = self.lock_returns[callee]
                elif ev["t"] == "acq" and ev.get("lock") is None and ev.get("via_call"):
                    callee = self._resolve_target(qual, ev["via_call"])
                    if callee is not None and callee in self.lock_returns:
                        ev["lock"] = self.lock_returns[callee]
        # normalize held-set entries: unresolved `with helper():` specs
        # become lock ids (or drop when the helper is unknown)
        for qual, fn in self.functions.items():
            for ev in fn["events"]:
                normalized: List[str] = []
                for entry in ev["held"]:
                    if isinstance(entry, str):
                        normalized.append(entry)
                        continue
                    callee = self._resolve_target(qual, entry["call"])
                    if callee is not None and callee in self.lock_returns:
                        normalized.append(self.lock_returns[callee])
                ev["held"] = normalized

    # -- fixpoints ---------------------------------------------------------
    def trans_acquires(self) -> Dict[str, Dict[str, Any]]:
        """qual -> {lock_id: {"site": (relpath, line), "chain": [quals]}} —
        every lock the function may acquire through any resolved path.
        Acquisitions waived `# lock-order-ok` do not propagate (the waiver
        covers the edges that acquisition creates)."""
        if self._trans_acq is not None:
            return self._trans_acq
        acq: Dict[str, Dict[str, Any]] = {q: {} for q in self.functions}
        for qual, fn in self.functions.items():
            for ev in fn["events"]:
                lock = None
                if ev["t"] == "acq":
                    lock = ev.get("lock")
                elif ev["t"] == "call" and ev.get("returns_lock"):
                    lock = ev["returns_lock"]
                if lock is not None and lock not in acq[qual]:
                    acq[qual][lock] = {
                        "site": [fn["relpath"], ev["line"]],
                        "chain": [qual],
                        "waived": "lock-order" in ev.get("waived", []),
                    }
        changed = True
        while changed:
            changed = False
            for qual, fn in self.functions.items():
                for ev in fn["events"]:
                    if ev["t"] != "call" or not ev.get("callee"):
                        continue
                    for lock, info in acq.get(ev["callee"], {}).items():
                        if lock not in acq[qual]:
                            acq[qual][lock] = {
                                "site": info["site"],
                                "chain": [qual] + info["chain"],
                                "waived": info["waived"],
                            }
                            changed = True
        self._trans_acq = acq
        return acq

    def may_block(self) -> Dict[str, Dict[str, Any]]:
        """qual -> {op: {"site", "chain", "recv_lock", "waived"}} — blocking
        operations reachable from the function through resolved calls."""
        if self._may_blk is not None:
            return self._may_blk
        blk: Dict[str, Dict[str, Any]] = {q: {} for q in self.functions}
        for qual, fn in self.functions.items():
            for ev in fn["events"]:
                if ev["t"] != "block":
                    continue
                key = ev["op"]
                if key not in blk[qual]:
                    blk[qual][key] = {
                        "site": [fn["relpath"], ev["line"]],
                        "chain": [qual],
                        "recv_lock": ev.get("recv_lock"),
                        "waived": "held" in ev.get("waived", []),
                    }
        changed = True
        while changed:
            changed = False
            for qual, fn in self.functions.items():
                for ev in fn["events"]:
                    if ev["t"] != "call" or not ev.get("callee"):
                        continue
                    for op, info in blk.get(ev["callee"], {}).items():
                        if op not in blk[qual]:
                            blk[qual][op] = {
                                "site": info["site"],
                                "chain": [qual] + info["chain"],
                                "recv_lock": info.get("recv_lock"),
                                "waived": info.get("waived", False),
                            }
                            changed = True
        self._may_blk = blk
        return blk

    def entry_held(self) -> Dict[str, Set[str]]:
        """qual -> locks held at EVERY resolved in-program call site
        (intersection). Functions with no resolved caller hold nothing on
        entry — public APIs must do their own locking."""
        if self._entry_held is not None:
            return self._entry_held
        callers: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        for qual, fn in self.functions.items():
            for ev in fn["events"]:
                if ev["t"] == "call" and ev.get("callee"):
                    callers.setdefault(ev["callee"], []).append((qual, tuple(ev["held"])))
        held: Dict[str, Set[str]] = {q: set() for q in self.functions}
        # fixpoint from ∅ so the intersection only ever PROVES locks held,
        # never assumes them
        for _ in range(len(self.functions) + 1):
            changed = False
            for callee, sites in callers.items():
                new: Optional[Set[str]] = None
                for caller, lex in sites:
                    site_held = set(lex) | held.get(caller, set())
                    new = site_held if new is None else (new & site_held)
                new = new or set()
                if new != held.get(callee, set()):
                    held[callee] = new
                    changed = True
            if not changed:
                break
        self._entry_held = held
        return held

    def lock_kind(self, lock_id: str) -> str:
        return self.locks.get(lock_id, {}).get("kind", "lock")

    # -- numerics fixpoints (rules/numerics.py pass 2) ----------------------
    def param_dtypes(self) -> Dict[str, Dict[str, Optional[str]]]:
        """qual -> {param: dtype token} where EVERY resolved in-program call
        site passes that dtype (meet over sites — an unknown or conflicting
        site poisons the param to None). Like `entry_held`, this only ever
        PROVES a dtype, never assumes one: a low-precision param finding
        requires every caller to agree."""
        if self._param_dt is not None:
            return self._param_dt
        sites: Dict[str, List[Tuple[str, List[Dict[str, Any]]]]] = {}
        for qual, fn in self.functions.items():
            for ev in fn["events"]:
                if ev["t"] == "call" and ev.get("callee") and ev.get("argdt") is not None:
                    sites.setdefault(ev["callee"], []).append((qual, ev["argdt"]))
        result: Dict[str, Dict[str, Optional[str]]] = {
            q: {p: None for p in fn.get("params", [])}
            for q, fn in self.functions.items()
        }

        def resolve(caller: str, desc: Dict[str, Any]) -> Optional[str]:
            if "param" in desc:
                return result.get(caller, {}).get(desc["param"])
            return desc.get("dt")

        for _ in range(len(self.functions) + 1):
            changed = False
            for callee, callers in sites.items():
                params = self.functions[callee].get("params", [])
                for i, p in enumerate(params):
                    met: Optional[str] = "unseen"
                    for caller, argdt in callers:
                        tok = resolve(caller, argdt[i]) if i < len(argdt) else None
                        if tok is None:
                            met = None
                            break
                        met = tok if met == "unseen" else (met if met == tok else None)
                        if met is None:
                            break
                    new = None if met == "unseen" else met
                    if result[callee].get(p) != new:
                        result[callee][p] = new
                        changed = True
            if not changed:
                break
        self._param_dt = result
        return result

    def entry_x64(self) -> Dict[str, bool]:
        """qual -> True iff the function is only ever reached through
        x64-guarded code: every resolved in-program call site is lexically
        under an x64 guard, or its caller is itself entry-guarded."""
        if self._entry_x64 is not None:
            return self._entry_x64
        callers: Dict[str, List[Tuple[str, bool]]] = {}
        for qual, fn in self.functions.items():
            for ev in fn["events"]:
                if ev["t"] == "call" and ev.get("callee"):
                    callers.setdefault(ev["callee"], []).append(
                        (qual, bool(ev.get("x64")))
                    )
        guarded: Dict[str, bool] = {q: False for q in self.functions}
        for _ in range(len(self.functions) + 1):
            changed = False
            for callee, sites in callers.items():
                new = all(x64 or guarded.get(caller, False) for caller, x64 in sites)
                if new != guarded.get(callee, False):
                    guarded[callee] = new
                    changed = True
            if not changed:
                break
        self._entry_x64 = guarded
        return guarded


def build_program(facts_by_file: Dict[str, Optional[Dict[str, Any]]]) -> Program:
    return Program(facts_by_file)
