#
# Content-hash result cache for the analysis gate (ci/analysis/cache.json,
# gitignored). Per file it stores the per-file rule findings, the pass-1
# program facts (program.py), each collector rule's per-file state
# contribution (rules/registries.py usages), and the file's dynamic-name
# entries — everything a re-parse would produce — keyed by the sha256 of the
# file's bytes. The whole cache is invalidated by the ENGINE hash: a sha256
# over every .py under ci/analysis/, so editing a rule or the engine re-runs
# everything (a stale rule result must never survive a rule change).
#
# Cross-file work (the program fixpoints, registry finalize, baseline
# ratchet) always re-runs from the cached facts/states — only parsing and
# per-file rule traversal are skipped.
#
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

VERSION = 1
CACHE_BASENAME = "cache.json"


def engine_hash(analysis_dir: str) -> str:
    """sha256 over every .py in ci/analysis (sorted, path-tagged) — the
    invalidation key for engine/rule-source changes."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(analysis_dir):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, analysis_dir).encode())
            with open(p, "rb") as f:
                h.update(hashlib.sha256(f.read()).digest())
    return h.hexdigest()


def hash_bytes(raw: bytes) -> str:
    return hashlib.sha256(raw).hexdigest()


class Cache:
    """Load-mutate-save wrapper over cache.json. Disabled (load returns
    None) when the analysis dir does not exist under the scanned root —
    fixture roots in tests must not grow cache files."""

    def __init__(self, path: str, engine: str, entries: Dict[str, Any]):
        self.path = path
        self.engine = engine
        self.entries = entries
        self.hits = 0
        self._dirty = False

    @classmethod
    def load(cls, root: str) -> Optional["Cache"]:
        analysis_dir = os.path.join(root, "ci", "analysis")
        if not os.path.isdir(analysis_dir):
            return None
        path = os.path.join(analysis_dir, CACHE_BASENAME)
        engine = engine_hash(os.path.dirname(os.path.abspath(__file__)))
        entries: Dict[str, Any] = {}
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if data.get("version") == VERSION and data.get("engine") == engine:
                entries = data.get("entries", {})
        except (OSError, ValueError):
            entries = {}  # corrupt/missing cache: start cold, never crash
        return cls(path, engine, entries)

    def lookup(self, relpath: str, content_hash: str) -> Optional[Dict[str, Any]]:
        """Entry for `relpath` iff its stored hash matches `content_hash` —
        the caller hashes the exact bytes it will analyze, so a file
        modified mid-run can never map its new hash onto stale results."""
        entry = self.entries.get(relpath)
        if entry is None or content_hash != entry.get("hash"):
            return None
        self.hits += 1
        return entry

    def store(
        self,
        relpath: str,
        content_hash: str,
        findings: List[Dict[str, Any]],
        facts: Optional[Dict[str, Any]],
        state: Dict[str, Any],
        dynamic: List[str],
    ) -> None:
        self.entries[relpath] = {
            "hash": content_hash,
            "findings": findings,
            "facts": facts,
            "state": state,
            "dynamic": dynamic,
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        payload = {"version": VERSION, "engine": self.engine, "entries": self.entries}
        tmp = f"{self.path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
