#
# AST port of the unbounded-blocking rule: `while True` poll loops and bare
# `.wait()` calls with no timeout are how a dead peer becomes a HUNG process
# instead of a typed RankFailedError/RendezvousTimeoutError
# (docs/robustness.md "Guard rails"). All bounded waiting lives in
# parallel/context.py — the one deadline owner; anywhere else a blocking
# construct must carry `# blocking-ok: <reason>` naming its bound. The AST
# form no longer trips on `while True` inside strings/comments, and —
# unlike the regex — `.wait(timeout)` with a positional bound passes.
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase


class BlockingRule(RuleBase):
    id = "unbounded-blocking"
    waiver = "blocking"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset({"context.py"})  # the deadline owner
    description = "while-True loops and timeout-less .wait() outside the deadline owner"

    @staticmethod
    def _unbounded_wait(node: ast.Call) -> bool:
        """Bare `.wait()` — and the spelled-out equivalents `.wait(None)` /
        `.wait(timeout=None)`, which block forever just the same."""
        if not node.args and not node.keywords:
            return True
        args = [a for a in node.args] + [k.value for k in node.keywords]
        if len(args) != 1:
            return False
        (arg,) = args
        return isinstance(arg, ast.Constant) and arg.value is None

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.While):
                test = node.test
                if isinstance(test, ast.Constant) and bool(test.value) is True:
                    ctx.emit(
                        self,
                        node,
                        "unbounded `while True` in the framework — a dead peer "
                        "must raise a typed error, not hang; bound it with a "
                        "deadline (see parallel/context.py) or mark "
                        "`# blocking-ok: <reason>`",
                    )
            elif isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "wait"
                    and self._unbounded_wait(node)
                ):
                    ctx.emit(
                        self,
                        node,
                        "`.wait()` with no timeout in the framework — a dead "
                        "peer must raise a typed error, not hang; pass a "
                        "deadline (see parallel/context.py) or mark "
                        "`# blocking-ok: <reason>`",
                    )
