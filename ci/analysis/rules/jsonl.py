#
# AST port of the JSONL-bypass rule: framework JSONL emission goes through
# the telemetry sink (`telemetry._sink_write`) or the flight recorder
# (`diagnostics.FlightRecorder.dump`) — the two owners that tag records with
# rank + trace ids and keep per-rank files from interleaving. A hand-rolled
# `f.write(json.dumps(...) + "\n")` elsewhere produces records the trace
# merge and post-mortem assemblers cannot correlate. The AST form matches a
# real `.write(...)` call whose payload contains a `json.dumps` call, or a
# `json.dumps(...) + "\n"` concatenation — never the pattern quoted in a
# docstring. Non-JSONL json uses (json.dump to a metadata file, bare
# json.dumps control-plane payloads) don't match.
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase, dotted


def _contains_json_dumps(node: ast.AST, imports) -> bool:
    return any(
        isinstance(sub, ast.Call) and dotted(sub.func, imports) == "json.dumps"
        for sub in ast.walk(node)
    )


class JsonlRule(RuleBase):
    id = "jsonl-bypass"
    waiver = "sink"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset({"telemetry.py", "diagnostics.py"})  # the two sink owners
    description = "hand-rolled JSONL emission outside the telemetry/flight-recorder sinks"

    _MSG = (
        "hand-rolled JSONL emission in the framework — records must flow "
        "through the telemetry sink or flight recorder (rank + trace-id "
        "tagging, per-rank files) or mark `# sink-ok: <reason>`"
    )

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        # nodes already covered by a flagged `.write(...)` — the BinOp
        # branch must not double-report the same violation
        inside_flagged_write: set = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "write"
                    and node.args
                    and _contains_json_dumps(node.args[0], ctx.imports)
                ):
                    ctx.emit(self, node, self._MSG)
                    inside_flagged_write.update(id(n) for n in ast.walk(node.args[0]))
        for node in ast.walk(tree):
            if id(node) in inside_flagged_write:
                continue
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
                sides = (node.left, node.right)
                has_dumps = any(
                    isinstance(s, ast.Call)
                    and dotted(s.func, ctx.imports) == "json.dumps"
                    for s in sides
                )
                has_newline = any(
                    isinstance(s, ast.Constant) and s.value == "\n" for s in sides
                )
                if has_dumps and has_newline:
                    ctx.emit(self, node, self._MSG)
