#
# Rule catalog (docs/development.md has the rationale per invariant). Two
# tiers: AST ports of the six regex-era rules, and the framework-aware
# detectors regexes cannot express. `default_rules()` returns FRESH
# instances — the registry rules accumulate per-run state.
#
from __future__ import annotations

from typing import List

from ..engine import RuleBase
from .blocking import BlockingRule
from .concurrency import BlockingUnderLockRule, GuardDisciplineRule, LockOrderRule
from .distance import RawDistanceRule
from .exporter import ExporterScopeRule
from .histogram import HistogramLoopRule
from .hostsync import HostSyncRule
from .hygiene import KNOWN_WAIVER_TAGS, HygieneRule
from .jsonl import JsonlRule
from .ledger import LedgerBypassRule
from .memstats import MemStatsRule
from .numerics import PrecisionFlowRule, PrngDisciplineRule
from .padrows import PadRowsRule
from .profiler import ProfilerScopeRule
from .purity import TracedImpurityRule
from .registries import ConfigKeyRule, MetricNameRule
from .serving import ServeDispatchRule
from .sleeps import SleepRule
from .spmd import SpmdDivergenceRule
from .timing import PerfCounterRule
from .wallclock import WallclockDeadlineRule


def default_rules() -> List[RuleBase]:
    rules: List[RuleBase] = [
        HygieneRule(),
        # --- AST ports of the regex-era gate -----------------------------
        PerfCounterRule(),
        BlockingRule(),
        JsonlRule(),
        SleepRule(),
        WallclockDeadlineRule(),
        MemStatsRule(),
        PadRowsRule(),
        # --- framework-aware detectors -----------------------------------
        SpmdDivergenceRule(),
        HostSyncRule(),
        TracedImpurityRule(),
        RawDistanceRule(),
        HistogramLoopRule(),
        ServeDispatchRule(),
        LedgerBypassRule(),
        ExporterScopeRule(),
        ProfilerScopeRule(),
        ConfigKeyRule(),
        MetricNameRule(),
        # --- whole-program concurrency rules (pass-2 over program.py) ----
        LockOrderRule(),
        BlockingUnderLockRule(),
        GuardDisciplineRule(),
        # --- whole-program numerics rules (pass-2 over program.py) -------
        PrecisionFlowRule(),
        PrngDisciplineRule(),
    ]
    # the hygiene waiver-form check must know every tag the catalog uses
    tags = {r.waiver for r in rules if r.waiver}
    missing = tags - KNOWN_WAIVER_TAGS
    assert not missing, f"rules/hygiene.KNOWN_WAIVER_TAGS is missing {missing}"
    return rules


__all__ = [
    "default_rules",
    "HygieneRule",
    "PerfCounterRule",
    "BlockingRule",
    "JsonlRule",
    "SleepRule",
    "WallclockDeadlineRule",
    "MemStatsRule",
    "PadRowsRule",
    "SpmdDivergenceRule",
    "HostSyncRule",
    "TracedImpurityRule",
    "RawDistanceRule",
    "ServeDispatchRule",
    "LedgerBypassRule",
    "ExporterScopeRule",
    "ProfilerScopeRule",
    "ConfigKeyRule",
    "MetricNameRule",
    "LockOrderRule",
    "BlockingUnderLockRule",
    "GuardDisciplineRule",
    "PrecisionFlowRule",
    "PrngDisciplineRule",
    "HistogramLoopRule",
]
