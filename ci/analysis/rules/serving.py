#
# serve-dispatch: the serving plane's async contract, CI-enforced
# (docs/serving.md "Async dispatch"). Inside `spark_rapids_ml_tpu/serving/`,
# predict work must flow through a model's resident `core.PredictProgram`
# (dispatch = pad + run, NO host fetch) and block exactly once — at the
# engine's response-assembly point. A stray `jax.jit` mints a second program
# cache the prewarm ladder never warmed; a stray `block_until_ready` /
# `device_get` turns async micro-batching back into the reference's
# synchronous per-batch dispatch. Both are findings anywhere in serving/;
# the ONE sanctioned assembly point carries `# serve-ok: <reason>`, and the
# baseline stays empty.
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase, dotted

_BLOCKED_CALLS = {"jax.jit", "jax.block_until_ready", "jax.device_get"}


class ServeDispatchRule(RuleBase):
    id = "serve-dispatch"
    waiver = "serve"
    tree_scope = ("spark_rapids_ml_tpu",)
    description = (
        "direct jit/block_until_ready/device_get inside serving/ outside the "
        "engine's waived dispatch point"
    )

    def applies(self, ctx: FileContext) -> bool:
        return ctx.relpath.startswith("spark_rapids_ml_tpu/serving/")

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = dotted(func, ctx.imports)
            if name in _BLOCKED_CALLS:
                what = name.split(".", 1)[1]
                ctx.emit(
                    self,
                    node,
                    f"direct `{what}` in serving/ — predict dispatch flows "
                    "through the model's resident PredictProgram and blocks "
                    "only at the engine's response-assembly point; mark the "
                    "one sanctioned site `# serve-ok: <reason>` "
                    "(docs/serving.md)",
                )
            elif (
                isinstance(func, ast.Attribute)
                and func.attr == "block_until_ready"
            ):
                # the Array METHOD form (`result.block_until_ready()`) — the
                # receiver is a local value, but the method name is
                # unambiguous in jax code
                ctx.emit(
                    self,
                    node,
                    "direct `.block_until_ready()` in serving/ — the engine's "
                    "response-assembly point is the one sanctioned sync "
                    "(`# serve-ok: <reason>`, docs/serving.md)",
                )
