#
# Device-timing scope rule (PR 17): the efficiency plane
# (ops_plane/efficiency.py, fed through the telemetry.py hooks) is the ONE
# owner of device-time attribution. Hand-rolled device timing anywhere else
# — a `jax.profiler.*` reference, or the classic
# `t0 = perf_counter(); ...; x.block_until_ready(); perf_counter() - t0`
# idiom — produces numbers the attribution ledger never sees, double-syncs
# boundaries the plane already times, and drifts from the execute/compile/
# host/idle taxonomy docs/observability.md documents.
#
# Two findings:
#   * any `jax.profiler.*` reference (trace, TraceAnnotation, start_trace,
#     ...) outside the exempt owners — the profiler surface is wrapped by
#     telemetry.span()/fit_scope and the SRML_PROFILE_DIR hook in core.py
#     (waived there: it IS the sanctioned whole-fit trace entry point);
#   * a `time.perf_counter` reference in a function whose IMMEDIATE body
#     also references `block_until_ready` — the sync-then-clock device-
#     timing idiom. Scoped to the immediate body (nested defs excluded) so
#     timing a closure that syncs internally (the autotuner's measurement
#     timer, already `# telemetry-ok`-waived for the bare-perf-counter
#     rule) does not double-report; the PerfCounterRule still covers plain
#     perf_counter use.
#
# Waiver: `# profiler-ok: <reason>`. Baseline: EMPTY — the tree is clean at
# introduction and stays clean.
#
from __future__ import annotations

import ast
from typing import List, Tuple

from ..engine import FileContext, RuleBase, dotted


class ProfilerScopeRule(RuleBase):
    id = "profiler-scope"
    waiver = "profiler"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset({"telemetry.py", "efficiency.py"})  # the attribution owners
    description = (
        "hand-rolled device timing (jax.profiler.* or perf_counter around "
        "block_until_ready) outside the efficiency plane"
    )

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        # ONE finding per reference: `jax.profiler.trace` matches on the
        # outermost attribute only (its inner `jax.profiler` value node
        # would double-report — ast.walk is breadth-first, so the outer
        # node is seen first and its descendants are skipped)
        inner: set = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.Attribute, ast.Name)) and id(node) not in inner:
                d = dotted(node, ctx.imports)
                if d and (d == "jax.profiler" or d.startswith("jax.profiler.")):
                    for child in ast.walk(node):
                        if child is not node:
                            inner.add(id(child))
                    ctx.emit(
                        self,
                        node,
                        "direct jax.profiler use in the framework — device "
                        "timing goes through telemetry.device_wait()/"
                        "span() and the efficiency plane (or mark "
                        "`# profiler-ok: <reason>`)",
                    )
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(node, ctx)

    def _immediate_refs(
        self, fn: ast.AST
    ) -> List[Tuple[ast.AST, str]]:
        """(node, dotted-or-attr-name) pairs in `fn`'s immediate body —
        nested function/class bodies excluded, so a closure that syncs
        internally doesn't mark its enclosing function as device-timing."""
        out: List[Tuple[ast.AST, str]] = []
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(node, (ast.Attribute, ast.Name)):
                name = (
                    node.attr if isinstance(node, ast.Attribute) else node.id
                )
                out.append((node, name))
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _check_function(
        self, fn: ast.AST, ctx: FileContext
    ) -> None:
        refs = self._immediate_refs(fn)
        if not any(name == "block_until_ready" for _, name in refs):
            return
        for node, _name in refs:
            if dotted(node, ctx.imports) in (
                "time.perf_counter",
                "time.perf_counter_ns",
            ):
                ctx.emit(
                    self,
                    node,
                    "perf_counter around block_until_ready — the sync-then-"
                    "clock device-timing idiom belongs to the efficiency "
                    "plane: use telemetry.device_wait(stage) (or mark "
                    "`# profiler-ok: <reason>`)",
                )
