#
# AST port of the raw-pad-rows rule: transform/serving code pads batches
# through the bucket ladder (parallel/mesh.py bucket_rows), never raw
# pad_rows — an exact-shape pad mints one compiled `predict` program per
# distinct tail shape (tens of seconds each on a TPU backend) where the
# ladder compiles once per bucket (docs/performance.md "Multi-fit engine").
# pad_rows stays legal inside mesh.py itself (the ladder is built on it) and
# on lines carrying `# bucket-ok: <reason>` (fit-side layout code, where
# every fit pads to ONE shape anyway).
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase, dotted


class PadRowsRule(RuleBase):
    id = "raw-pad-rows"
    waiver = "bucket"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset({"mesh.py"})  # the ladder is built on pad_rows
    description = "raw pad_rows outside the bucket ladder"

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func, ctx.imports)
            if name is not None and name.split(".")[-1] == "pad_rows":
                ctx.emit(
                    self,
                    node,
                    "raw pad_rows in the framework — serving batches pad "
                    "through the bucket ladder (mesh.bucket_rows: one compile "
                    "per bucket, not per tail shape); use it or mark "
                    "`# bucket-ok: <reason>`",
                )
