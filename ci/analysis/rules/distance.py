#
# Raw-pairwise-distance detector: the neighbor family's `x·cᵀ -> argmin /
# top-k` inner loop lives in ONE place — ops/distance.py (the tiled core
# with the Pallas kernel + bit-compatible fallback, docs/performance.md
# "Tiled distance core"). Before the core existed, five estimators each
# hand-rolled that loop, and the hand-rolled KMeans form was the r01->r03
# 2.2x scaling cliff. This rule stops the pattern from growing back:
#
#   a `jnp.argmin` / `lax.top_k` / `lax.approx_min_k` whose operand was
#   built from a LOCAL matmul (`@`, `jnp.dot`, `jnp.einsum`,
#   `jax.lax.dot(_general)`) is a finding anywhere in the framework
#   outside ops/distance.py.
#
# Taint is function-scoped and deliberately shallow: a name bound to a
# matmul-containing expression is tainted, and taint flows through
# arithmetic (BinOp/UnaryOp), subscripts, and the shape-preserving
# combinators (`jnp.where` / `maximum` / `minimum` / `concatenate` / `pad`)
# — but NOT through arbitrary calls: a result that went through the shared
# core (`distance.pairwise_d2(...)`, `distance.topk_tile(...)`) or any
# other function is clean, which is exactly how consumers are expected to
# look after porting. Gathered-bucket scans and other genuinely different
# shapes waive with `# distance-ok: <reason>`.
#
from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..engine import FileContext, RuleBase, dotted

# reductions that define the banned pattern when fed a matmul-built operand
_REDUCER_TAILS = {"argmin", "argmax", "top_k", "approx_min_k", "approx_max_k"}
# calls that ARE matmuls (taint sources), by resolved-name tail
_MATMUL_TAILS = {"dot", "dot_general", "matmul", "einsum", "tensordot", "inner"}
# calls taint flows THROUGH (shape-preserving combinators); everything else
# launders — notably the shared core's own entry points
_PROPAGATING_TAILS = {"where", "maximum", "minimum", "concatenate", "pad",
                      "negative", "abs", "sqrt", "square"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_jaxish(name: Optional[str]) -> bool:
    return name is not None and name.startswith(("jax.", "numpy."))


class RawDistanceRule(RuleBase):
    id = "raw-distance"
    waiver = "distance"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset({"distance.py"})  # the core owns the loop
    description = "raw pairwise-distance argmin/top-k outside ops/distance.py"

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        self._scope(tree.body, set(), ctx)

    # ---------------------------------------------------------- traversal --

    def _scope(self, body: List[ast.stmt], inherited: Set[str], ctx: FileContext) -> None:
        """One lexical scope, statements in source order. Nested function
        scopes inherit a COPY of the taint visible at their definition point
        (closures read outer locals — how `def one_tile(q)` bodies inside a
        tiled pass are still seen)."""
        tainted: Set[str] = set(inherited)
        for stmt in body:
            self._stmt(stmt, tainted, ctx)

    def _stmt(self, stmt: ast.stmt, tainted: Set[str], ctx: FileContext) -> None:
        if isinstance(stmt, _FUNC_NODES) or isinstance(stmt, ast.ClassDef):
            self._scope(stmt.body, tainted, ctx)
            return
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While,
                             ast.With, ast.AsyncWith, ast.Try)):
            # compound statement: check header expressions against the
            # CURRENT taint, then recurse into each sub-statement in source
            # order so bindings inside the block are visible to later
            # statements of the same block
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    for node in ast.walk(child):
                        if isinstance(node, ast.Call):
                            self._check_call(node, tainted, ctx)
                elif isinstance(child, ast.withitem):
                    for node in ast.walk(child.context_expr):
                        if isinstance(node, ast.Call):
                            self._check_call(node, tainted, ctx)
            if isinstance(stmt, (ast.For, ast.AsyncFor)) and self._tainted(
                stmt.iter, tainted
            ):
                tainted.update(
                    n.id for n in ast.walk(stmt.target) if isinstance(n, ast.Name)
                )
            for field in ("body", "orelse", "finalbody"):
                for sub in getattr(stmt, field, []) or []:
                    self._stmt(sub, tainted, ctx)
            for handler in getattr(stmt, "handlers", []) or []:
                for sub in handler.body:
                    self._stmt(sub, tainted, ctx)
            return
        # nested defs anywhere inside this statement get their own scope
        # pass; their nodes are excluded from this statement's flat walk
        nested = [n for n in ast.walk(stmt) if isinstance(n, _FUNC_NODES)]
        skip: Set[int] = set()
        for fn in nested:
            for sub in ast.walk(fn):
                if sub is not fn:
                    skip.add(id(sub))
        # findings first (an assignment's RHS may itself hold the reduction)
        for node in ast.walk(stmt):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Call):
                self._check_call(node, tainted, ctx)
        # then taint updates from this statement's bindings
        for node in ast.walk(stmt):
            if id(node) in skip:
                continue
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            if value is None:
                continue
            tnt = self._tainted(value, tainted)
            for t in targets:
                names = [n.id for n in ast.walk(t) if isinstance(n, ast.Name)]
                if tnt:
                    tainted.update(names)
                elif isinstance(node, ast.Assign) and isinstance(t, ast.Name):
                    tainted.discard(t.id)  # clean rebinding
        for fn in nested:
            self._scope(fn.body, tainted, ctx)

    def _check_call(self, node: ast.Call, tainted: Set[str], ctx: FileContext) -> None:
        operand: Optional[ast.expr] = None
        label: Optional[str] = None
        name = dotted(node.func, ctx.imports)
        if (
            name is not None
            and name.split(".")[-1] in _REDUCER_TAILS
            and _is_jaxish(name)
            and node.args
        ):
            operand, label = node.args[0], name.split(".")[-1]
        elif isinstance(node.func, ast.Attribute) and node.func.attr in (
            "argmin",
            "argmax",
        ):
            # method form: d2.argmin(axis=1)
            operand, label = node.func.value, node.func.attr
        if operand is None:
            return
        # lambdas in the operand (rare) are treated as opaque
        if self._tainted(operand, tainted):
            ctx.emit(
                self,
                node,
                f"`{label}` over a locally-built `x @ c.T`-shaped operand — "
                "the neighbor family's distance/argmin/top-k loop is owned by "
                "ops/distance.py (tile_topk / argmin_assign / "
                "assign_accumulate / pairwise_d2): hand-rolled copies are the "
                "r01->r03 KMeans scaling-cliff pattern. Call the shared core, "
                "or mark `# distance-ok: <reason>`",
            )

    # --------------------------------------------------------------- taint --

    def _tainted(self, node: ast.expr, tainted: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.MatMult):
                return True
            return self._tainted(node.left, tainted) or self._tainted(node.right, tainted)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand, tainted)
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value, tainted)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._tainted(e, tainted) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self._tainted(node.body, tainted) or self._tainted(node.orelse, tainted)
        if isinstance(node, ast.Call):
            name = dotted(node.func, None)
            tail = name.split(".")[-1] if name else None
            if tail in _MATMUL_TAILS:
                return True
            if tail in _PROPAGATING_TAILS:
                return any(self._tainted(a, tainted) for a in node.args)
            return False  # any other call launders (incl. the shared core)
        return False
