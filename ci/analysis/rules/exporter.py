#
# exporter-scope: the ops plane's export surface, CI-enforced
# (docs/observability.md "Ops plane"). `spark_rapids_ml_tpu/ops_plane/` is
# the ONE owner of scrape-surface machinery: raw `http.server` /
# `socketserver` use, raw `socket.socket()`/`socket.create_server()`
# construction, and Prometheus text-format assembly (string literals
# carrying the `# TYPE ` / `# HELP ` exposition markers) anywhere else in
# the framework or benchmark trees are findings. A second ad-hoc HTTP
# endpoint would ship metrics with none of the rank labels, SLO verdicts,
# or health semantics the one exporter guarantees — and a hand-assembled
# Prometheus line is exactly the kind of stringly-typed drift the metric
# registry rules exist to kill. Genuinely non-exporter socket use (the
# distributed coordinator's free-port probe) carries
# `# exporter-ok: <reason>`; the baseline stays EMPTY.
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase, dotted

_SERVER_MODULES = ("http.server", "socketserver")
_SOCKET_CALLS = {"socket.socket", "socket.create_server", "socket.create_connection"}
_PROM_MARKERS = ("# TYPE ", "# HELP ")


class ExporterScopeRule(RuleBase):
    id = "exporter-scope"
    waiver = "exporter"
    tree_scope = ("spark_rapids_ml_tpu", "benchmark")
    description = (
        "raw http.server/socket use or Prometheus text assembly outside "
        "ops_plane/"
    )

    def applies(self, ctx: FileContext) -> bool:
        if not super().applies(ctx):
            return False
        return not ctx.relpath.startswith("spark_rapids_ml_tpu/ops_plane/")

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name in _SERVER_MODULES or alias.name.startswith(
                        tuple(m + "." for m in _SERVER_MODULES)
                    ):
                        self._emit_server(node, alias.name, ctx)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod in _SERVER_MODULES or mod.startswith(
                    tuple(m + "." for m in _SERVER_MODULES)
                ):
                    self._emit_server(node, mod, ctx)
            elif isinstance(node, ast.Call):
                name = dotted(node.func, ctx.imports)
                if name in _SOCKET_CALLS or (
                    name
                    and name.startswith(tuple(m + "." for m in _SERVER_MODULES))
                ):
                    if not ctx.waived(self.waiver, node):
                        ctx.emit(
                            self,
                            node,
                            f"raw `{name}` outside ops_plane/ — the scrape "
                            "surface lives in ops_plane/export.py (rank "
                            "labels, SLO health, one port); mark a genuinely "
                            "non-exporter socket `# exporter-ok: <reason>` "
                            "(docs/observability.md)",
                        )
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                if any(m in node.value for m in _PROM_MARKERS):
                    if not ctx.waived(self.waiver, node):
                        ctx.emit(
                            self,
                            node,
                            "Prometheus exposition-format assembly (`# TYPE `/"
                            "`# HELP ` marker) outside ops_plane/ — metrics "
                            "export flows through ops_plane/export.py's one "
                            "renderer, or names/labels drift "
                            "(`# exporter-ok: <reason>` to waive; "
                            "docs/observability.md)",
                        )

    def _emit_server(self, node: ast.AST, mod: str, ctx: FileContext) -> None:
        if ctx.waived(self.waiver, node):
            return
        ctx.emit(
            self,
            node,
            f"`{mod}` import outside ops_plane/ — HTTP metric/health "
            "endpoints live in ops_plane/export.py so every surface carries "
            "the same rank labels and SLO verdict "
            "(`# exporter-ok: <reason>` to waive; docs/observability.md)",
        )
