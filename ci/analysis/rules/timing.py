#
# AST port of the regex-era perf_counter rule: stage timing inside the
# framework goes through telemetry spans (spark_rapids_ml_tpu/telemetry.py),
# not hand-rolled perf_counter deltas — ad-hoc timing is invisible to the
# registry/JSONL sinks and drifts from the span taxonomy. The AST form
# matches actual references to `time.perf_counter` (call or bare handle,
# through any import alias), so the string "perf_counter" in a comment or
# docstring no longer trips the gate.
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase, dotted


class PerfCounterRule(RuleBase):
    id = "bare-perf-counter"
    waiver = "telemetry"
    tree_scope = ("spark_rapids_ml_tpu",)
    # the clock owners: telemetry spans and the efficiency attribution plane
    exempt_files = frozenset({"telemetry.py", "efficiency.py"})
    description = "bare time.perf_counter timing outside telemetry.py"

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.Attribute, ast.Name)):
                # a bare HANDLE (`clock = time.perf_counter`) is as much a
                # bypass as a call, so references match, not just Calls; the
                # _ns variant kept regex-era coverage ("perf_counter" was a
                # substring match)
                if dotted(node, ctx.imports) in (
                    "time.perf_counter",
                    "time.perf_counter_ns",
                ):
                    ctx.emit(
                        self,
                        node,
                        "bare perf_counter timing in the framework — use "
                        "telemetry.span()/registry (or mark "
                        "`# telemetry-ok: <reason>`)",
                    )
