#
# Wall-clock deadline rule: `time.time()` feeding deadline/timeout
# arithmetic in the framework is a finding — `time.monotonic()` is the
# deadline contract (docs/serving.md "Overload & backpressure"). Wall clocks
# step (NTP slew, VM migration, leap smearing); a deadline computed from one
# can expire a request instantly or never. The serving plane's deadline
# admission (PR 18) made this a framework-wide invariant, so the gate pins
# it the way bare-sleep/perf_counter are pinned.
#
# What fires:
#   * a Compare with a wall-tainted operand — `if time.time() > deadline`,
#     `while now - t0 < timeout` where `now = time.time()`;
#   * a deadline/timeout-named binding assigned a wall-tainted value —
#     `deadline = time.time() + 5`;
#   * a deadline/timeout-named call keyword passed a wall-tainted value.
#
# What does NOT fire (the timestamping idiom is legal everywhere):
#   * `{"t": time.time()}` record fields, bare `t = time.time()` stamps,
#     attribute stamps (`self._w0 = time.time()`) — a reading that never
#     reaches comparison or deadline arithmetic;
#   * `time.monotonic()` anything.
#
# Taint is function-scoped (module scope counts as one scope): a name
# assigned from `time.time()` — directly or through +/- arithmetic — is
# wall-tainted for that scope. Cross-clock comparisons that are genuinely
# wall-clock (file mtimes) carry `# wallclock-ok: <reason>`.
#
from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from ..engine import FileContext, RuleBase, dotted

_DEADLINE_NAME = re.compile(r"deadline|timeout|expir|t_end|until", re.I)

_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_scope(root: ast.AST) -> Iterator[ast.AST]:
    """Walk `root` without descending into nested function scopes (each
    nested function is analyzed as its own scope)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _SCOPE_TYPES):
            stack.extend(ast.iter_child_nodes(node))


class WallclockDeadlineRule(RuleBase):
    id = "wallclock-deadline"
    waiver = "wallclock"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset()
    description = "time.time() feeding deadline/timeout arithmetic"

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        scopes = [tree] + [
            n for n in ast.walk(tree) if isinstance(n, _SCOPE_TYPES)
        ]
        for scope in scopes:
            self._check_scope(scope, ctx)

    # ------------------------------------------------------------- scope --
    def _check_scope(self, scope: ast.AST, ctx: FileContext) -> None:
        tainted: Set[str] = set()
        # two passes so order of definition doesn't matter for the taint set
        for _ in range(2):
            for node in _walk_scope(scope):
                if isinstance(node, ast.Assign) and self._wall(node.value, ctx, tainted):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    if isinstance(node.target, ast.Name) and self._wall(
                        node.value, ctx, tainted
                    ):
                        tainted.add(node.target.id)

        for node in _walk_scope(scope):
            if isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if any(self._wall(o, ctx, tainted) for o in operands):
                    ctx.emit(
                        self,
                        node,
                        "wall-clock time.time() in a deadline/timeout "
                        "comparison — the deadline contract is "
                        "time.monotonic() (or mark `# wallclock-ok: <reason>`)",
                    )
            elif isinstance(node, ast.Assign):
                if self._wall(node.value, ctx, tainted) and any(
                    self._deadliney(t) for t in node.targets
                ):
                    ctx.emit(
                        self,
                        node,
                        "deadline/timeout bound computed from wall-clock "
                        "time.time() — use time.monotonic() (or mark "
                        "`# wallclock-ok: <reason>`)",
                    )
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg
                        and _DEADLINE_NAME.search(kw.arg)
                        and self._wall(kw.value, ctx, tainted)
                    ):
                        ctx.emit(
                            self,
                            node,
                            f"wall-clock time.time() passed as {kw.arg!r} — "
                            "deadline/timeout arguments take monotonic "
                            "readings (or mark `# wallclock-ok: <reason>`)",
                        )

    # ----------------------------------------------------------- helpers --
    def _wall(self, node: ast.AST, ctx: FileContext, tainted: Set[str]) -> bool:
        """Whether `node` carries a wall-clock reading: a `time.time()` call,
        a tainted name, or +/- arithmetic over either."""
        if isinstance(node, ast.Call):
            return dotted(node.func, ctx.imports) == "time.time"
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Sub)):
            return self._wall(node.left, ctx, tainted) or self._wall(
                node.right, ctx, tainted
            )
        if isinstance(node, ast.IfExp):
            return self._wall(node.body, ctx, tainted) or self._wall(
                node.orelse, ctx, tainted
            )
        return False

    @staticmethod
    def _deadliney(target: ast.AST) -> bool:
        if isinstance(target, ast.Name):
            return bool(_DEADLINE_NAME.search(target.id))
        if isinstance(target, ast.Attribute):
            return bool(_DEADLINE_NAME.search(target.attr))
        return False
