#
# Traced-impurity detector: Python side effects inside functions that jax
# TRACES — jit/vmap targets, `lax.while_loop`/`scan`/`fori_loop`/`cond`
# bodies — run exactly once, at trace time, and never again for the
# compiled program's lifetime. A `print`, a `time.*` read, a telemetry call,
# or a closure-list `.append` inside a solver body therefore records one
# stale value per COMPILE instead of one per iteration — silently. The
# sanctioned escape hatch is `jax.debug.callback`/`jax.debug.print` (how
# ops/owlqn.py and ops/logistic.py stream per-iteration convergence points,
# gated at trace time behind SRML_TRACE_CONVERGENCE); anything else is a
# finding.
#
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..engine import FileContext, RuleBase, dotted

# call targets whose function-valued arguments are traced
_TRACING_TAILS = {
    "jit",
    "vmap",
    "pmap",
    "while_loop",
    "scan",
    "fori_loop",
    "cond",
    "switch",
    "map",
    "remat",
    "checkpoint",
    "shard_map",
    "grad",
    "value_and_grad",
}
# side-effect escape hatches: their argument subtrees are host callbacks by
# design, not trace-time effects
_ESCAPE_TAILS = {"callback", "print", "pure_callback", "io_callback", "host_callback"}
_MUTATORS = {"append", "extend", "insert", "add"}


def _is_jax_call(name: Optional[str]) -> bool:
    return name is not None and (
        name.startswith(("jax.", "lax.", "jnp.")) or name in ("jit", "vmap", "shard_map")
    )


def _is_tracing_call(name: Optional[str]) -> bool:
    return _is_jax_call(name) and name.split(".")[-1] in _TRACING_TAILS


def _is_escape_call(name: Optional[str]) -> bool:
    return _is_jax_call(name) and name.split(".")[-1] in _ESCAPE_TAILS


class TracedImpurityRule(RuleBase):
    id = "traced-impurity"
    waiver = "traced"
    tree_scope = ("spark_rapids_ml_tpu",)
    description = "Python side effects inside jit/vmap/while_loop/scan bodies (run once at trace time)"

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        traced: Set[int] = set()  # id() of traced function nodes
        traced_nodes: List[ast.AST] = []

        def mark(fn: ast.AST) -> None:
            if id(fn) not in traced:
                traced.add(id(fn))
                traced_nodes.append(fn)

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._decorator_traces(dec, ctx):
                        mark(node)
            if isinstance(node, ast.Call) and _is_tracing_call(dotted(node.func, ctx.imports)):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        mark(arg)
                    elif isinstance(arg, ast.Name):
                        for fn in defs.get(arg.id, []):
                            mark(fn)

        # a local function CALLED from a traced body is traced too
        idx = 0
        while idx < len(traced_nodes):
            fn = traced_nodes[idx]
            idx += 1
            body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
            for sub in ast.walk(ast.Module(body=list(body), type_ignores=[])):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                    for cand in defs.get(sub.func.id, []):
                        mark(cand)

        for fn in traced_nodes:
            self._check_traced(fn, ctx)

    def _decorator_traces(self, dec: ast.AST, ctx: FileContext) -> bool:
        name = dotted(dec, ctx.imports)
        if _is_tracing_call(name):
            return True
        if isinstance(dec, ast.Call):
            if _is_tracing_call(dotted(dec.func, ctx.imports)):
                return True  # @jax.jit(static_argnums=...)
            fname = dotted(dec.func, ctx.imports)
            if fname is not None and fname.split(".")[-1] == "partial" and dec.args:
                return _is_tracing_call(dotted(dec.args[0], ctx.imports))
        return False

    def _check_traced(self, fn: ast.AST, ctx: FileContext) -> None:
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        local_names: Set[str] = set()
        args = fn.args
        for p in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            local_names.add(p.arg)
        for sub in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.NamedExpr, ast.For)):
                target = getattr(sub, "targets", None) or [getattr(sub, "target")]
                for t in target:
                    for s in ast.walk(t):
                        if isinstance(s, ast.Name):
                            local_names.add(s.id)
        for stmt in body:
            self._scan(stmt, ctx, local_names)

    def _scan(self, node: ast.AST, ctx: FileContext, local_names: Set[str]) -> None:
        if isinstance(node, ast.Call):
            name = dotted(node.func, ctx.imports)
            if _is_escape_call(name):
                return  # jax.debug.callback(...) subtree: the sanctioned hatch
            if isinstance(node.func, ast.Name) and node.func.id == "print":
                ctx.emit(
                    self,
                    node,
                    "print() inside a traced function runs once at trace "
                    "time, not per execution — use jax.debug.print, or mark "
                    "`# traced-ok: <reason>`",
                )
            elif name is not None and name.startswith("time."):
                ctx.emit(
                    self,
                    node,
                    f"`{name}` inside a traced function reads the clock once "
                    "at trace time and bakes the value into the compiled "
                    "program — time on the host side, or mark "
                    "`# traced-ok: <reason>`",
                )
            elif name is not None and (
                name.startswith("telemetry.") or ".telemetry." in f".{name}"
            ):
                ctx.emit(
                    self,
                    node,
                    f"`{name}` called directly inside a traced function "
                    "records once at trace time — route per-iteration "
                    "telemetry through jax.debug.callback (see "
                    "ops/owlqn.py), or mark `# traced-ok: <reason>`",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id not in local_names
            ):
                ctx.emit(
                    self,
                    node,
                    f"`.{node.func.attr}()` on closed-over "
                    f"`{node.func.value.id}` inside a traced function "
                    "mutates it once at trace time, not per execution — "
                    "carry state through the loop carry / return value, or "
                    "mark `# traced-ok: <reason>`",
                )
        for child in ast.iter_child_nodes(node):
            self._scan(child, ctx, local_names)
