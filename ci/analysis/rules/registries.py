#
# Registry-drift detectors: the framework's stringly-typed surfaces —
# `config["..."]` keys and `"<subsystem>.<name>"` metric strings — are held
# in sync with their declared schemas by CI instead of by review.
#
# config-key: every `config["..."]` / `config.get("...")` read or write in
# the framework + benchmark trees must name a key declared in the
# module-level `config = {...}` literal in spark_rapids_ml_tpu/core.py, and
# every declared key must appear in docs/configuration.md's table (and vice
# versa). A typo'd key silently reads a default or creates a dead entry;
# this makes it a CI failure instead of a review catch.
#
# metric-name: every constant counter/gauge/histogram/convergence name
# handed to the telemetry registry must appear in docs/observability.md,
# and undocumented names are checked against the documented set for
# near-miss typos (edit distance 1 — `ingest.row` vs `ingest.rows`).
# Dynamic names (f-strings like f"{solver}.fits") cannot be checked
# statically; they are counted in the verdict's `dynamic_names` so the gap
# is visible, never silent.
#
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..engine import FileContext, Finding, RuleBase, Run, dotted

_METRIC_METHODS = {"inc", "gauge", "gauge_max", "observe"}
_CONVERGENCE_FUNCS = {"record_convergence_point", "record_convergence"}
_DOC_NAME_RE = re.compile(r"\b[a-z0-9_]+(?:\.[a-z0-9_]+)+\b")
_DOC_QUOTED_RE = re.compile(r"\"([a-z0-9_.]+)\"")
_DOC_TABLE_KEY_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")


def _edit_distance_le_1(a: str, b: str) -> bool:
    if a == b:
        return True
    la, lb = len(a), len(b)
    if abs(la - lb) > 1:
        return False
    if la > lb:
        a, b, la, lb = b, a, lb, la
    # la <= lb; one substitution (equal length) or one insertion into a
    i = j = diffs = 0
    while i < la and j < lb:
        if a[i] == b[j]:
            i += 1
            j += 1
            continue
        diffs += 1
        if diffs > 1:
            return False
        if la == lb:
            i += 1
        j += 1
    return diffs + (lb - j) + (la - i) <= 1


class ConfigKeyRule(RuleBase):
    id = "config-key"
    waiver = "config"
    tree_scope = ("spark_rapids_ml_tpu", "benchmark")
    description = "config[...] keys checked against the core.config schema and docs/configuration.md"

    def __init__(self) -> None:
        # (key, relpath, line, col)
        self.usages: List[Tuple[str, str, int, int]] = []

    def _is_core_config(self, node: ast.AST, ctx: FileContext) -> bool:
        name = dotted(node, ctx.imports)
        if name is None:
            return False
        if name == "config":
            # an UNRESOLVED bare `config` is the schema dict only inside the
            # module that defines it; elsewhere it is a local/parameter of
            # that name (imports of the real dict resolve to core.config)
            return ctx.filename == "core.py" and ctx.relpath.startswith(
                "spark_rapids_ml_tpu/"
            )
        return name.endswith("core.config")

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            key_node: Optional[ast.Constant] = None
            if isinstance(node, ast.Subscript) and self._is_core_config(node.value, ctx):
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    key_node = sl
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "setdefault", "pop")
                and self._is_core_config(node.func.value, ctx)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                key_node = node.args[0]
            if key_node is not None and not ctx.waived(self.waiver, node):
                self.usages.append(
                    (key_node.value, ctx.relpath, node.lineno, node.col_offset + 1)
                )

    # content-hash cache hooks (engine.RuleBase): the per-file usage slice
    # is stored on a miss and replayed on a hit, so cache-skipped files
    # still feed the cross-file registry check in finalize
    def file_state(self, relpath: str):
        return [list(u) for u in self.usages if u[1] == relpath]

    def restore_state(self, relpath: str, state) -> None:
        self.usages.extend(tuple(u) for u in state)

    def finalize(self, run: Run) -> List[Finding]:
        out: List[Finding] = []
        schema = run.sources.config_schema_keys
        docs = run.sources.config_docs_text
        if self.usages:
            # a moved/renamed schema or doc must fail, not silently disable
            # the checks for the usages this run collected
            for rel in (
                run.sources.config_schema_relpath,
                run.sources.config_docs_relpath,
            ):
                if rel in run.sources.missing:
                    out.append(
                        Finding(
                            rel,
                            1,
                            1,
                            self.id,
                            f"registry source `{rel}` is missing — "
                            f"{len(self.usages)} config-key usage(s) cannot be "
                            "checked; a silently disabled registry rule is a "
                            "green pass that checks nothing",
                        )
                    )
        for key, relpath, line, col in self.usages:
            if key not in schema:
                out.append(
                    Finding(
                        relpath,
                        line,
                        col,
                        self.id,
                        f"unknown config key `{key}` — not declared in the "
                        f"{run.sources.config_schema_relpath} `config` schema; a typo "
                        "here silently reads a default (or creates a dead "
                        "entry) instead of the knob you meant",
                    )
                )
        if docs:
            doc_keys: Dict[str, int] = {}
            for lineno, line_text in enumerate(docs.splitlines(), 1):
                m = _DOC_TABLE_KEY_RE.match(line_text)
                if m:
                    doc_keys.setdefault(m.group(1), lineno)
            for key, schema_line in sorted(schema.items()):
                if f"`{key}`" not in docs:
                    out.append(
                        Finding(
                            run.sources.config_schema_relpath,
                            schema_line,
                            1,
                            self.id,
                            f"config key `{key}` is declared in the schema but "
                            f"undocumented in {run.sources.config_docs_relpath} — "
                            "registry drift",
                        )
                    )
            for key, doc_line in sorted(doc_keys.items()):
                if key not in schema:
                    out.append(
                        Finding(
                            run.sources.config_docs_relpath,
                            doc_line,
                            1,
                            self.id,
                            f"documented config key `{key}` does not exist in the "
                            f"{run.sources.config_schema_relpath} `config` schema — "
                            "registry drift",
                        )
                    )
        return out


class MetricNameRule(RuleBase):
    id = "metric-name"
    waiver = "metric"
    tree_scope = ("spark_rapids_ml_tpu",)
    description = "telemetry metric names checked against docs/observability.md (+ near-miss typos)"

    def __init__(self) -> None:
        self.usages: List[Tuple[str, str, int, int]] = []

    def _collect(self, name_node: ast.AST, at: ast.AST, ctx: FileContext) -> None:
        if isinstance(name_node, ast.Constant) and isinstance(name_node.value, str):
            if not ctx.waived(self.waiver, at):
                self.usages.append(
                    (name_node.value, ctx.relpath, at.lineno, at.col_offset + 1)
                )
        else:
            ctx.run.dynamic_names.append(f"{ctx.relpath}:{at.lineno}")

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _METRIC_METHODS
                and node.args
            ):
                self._collect(node.args[0], node, ctx)
                continue
            name = dotted(func, ctx.imports)
            tail = name.split(".")[-1] if name else None
            if tail in _CONVERGENCE_FUNCS and node.args:
                self._collect(node.args[0], node, ctx)
            elif tail == "partial" and len(node.args) >= 2:
                inner = dotted(node.args[0], ctx.imports)
                if inner and inner.split(".")[-1] in _CONVERGENCE_FUNCS:
                    self._collect(node.args[1], node, ctx)

    # cache hooks — same contract as ConfigKeyRule.file_state above
    def file_state(self, relpath: str):
        return [list(u) for u in self.usages if u[1] == relpath]

    def restore_state(self, relpath: str, state) -> None:
        self.usages.extend(tuple(u) for u in state)

    def finalize(self, run: Run) -> List[Finding]:
        docs = run.sources.metric_docs_text
        if self.usages and run.sources.metric_docs_relpath in run.sources.missing:
            return [
                Finding(
                    run.sources.metric_docs_relpath,
                    1,
                    1,
                    self.id,
                    f"registry source `{run.sources.metric_docs_relpath}` is "
                    f"missing — {len(self.usages)} metric name(s) cannot be "
                    "checked; a silently disabled registry rule is a green "
                    "pass that checks nothing",
                )
            ]
        if not docs:
            return []
        declared: Set[str] = set(_DOC_NAME_RE.findall(docs))
        declared.update(_DOC_QUOTED_RE.findall(docs))
        used_names = {u[0] for u in self.usages}
        out: List[Finding] = []
        for name, relpath, line, col in self.usages:
            if name in declared:
                continue
            near = sorted(
                n
                for n in declared | (used_names - {name})
                if _edit_distance_le_1(name, n)
            )
            hint = (
                f" — near-miss of `{near[0]}` (typo?)"
                if near
                else ""
            )
            out.append(
                Finding(
                    relpath,
                    line,
                    col,
                    self.id,
                    f"metric name `{name}` is not documented in "
                    f"{run.sources.metric_docs_relpath}{hint}; every registry "
                    "name ships with its meaning, or dashboards and the "
                    "regression gate's counter lanes drift",
                )
            )
        return out
