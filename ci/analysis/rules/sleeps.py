#
# AST port of the bare-sleep rule: `time.sleep` in the framework is either a
# poll loop that should be event/deadline-driven or an ad-hoc delay that
# stretches failure detection past its documented budget
# (docs/robustness.md "Guard rails"). Sleeping is legal only for the
# retry-backoff, heartbeat-pacing, and rendezvous-poll owners — every such
# line carries `# sleep-ok: <reason>` naming its bound. The AST form matches
# the resolved call through any alias (`from time import sleep`,
# `import time as t`) and never a mention in a comment or string.
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase, dotted


class SleepRule(RuleBase):
    id = "bare-sleep"
    waiver = "sleep"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset()  # no file-level owner: every sleep is waived by line
    description = "bare time.sleep outside the retry/heartbeat/poll owners"

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and dotted(node.func, ctx.imports) == "time.sleep":
                ctx.emit(
                    self,
                    node,
                    "bare time.sleep in the framework — sleeping belongs to "
                    "the retry-backoff/heartbeat/poll owners; bound it and "
                    "mark `# sleep-ok: <why>`",
                )
