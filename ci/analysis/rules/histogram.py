#
# Hand-rolled binned-accumulation detector (the `raw-distance` taint pattern
# extended to histograms, seeded for ROADMAP item 4): the RF/tree family's
# `bin ids -> (node, feature, bin) accumulation` inner loop is about to get
# ONE shared Pallas histogram core (the same consolidation ops/distance.py
# performed for the neighbor family), and this rule is the ratchet that
# porting lands against — private copies of the loop are findings from day
# one, so the port can delete them without new ones growing back.
#
#   an accumulation sink — `segment_sum`, `scatter_add`, an
#   `.at[bins].add(...)` scatter, or a one-hot matmul (`one_hot(bins) @ x`,
#   `jnp.dot(one_hot(bins).T, x)`) — whose segment/index operand was built
#   from a LOCAL binning call (`jnp.digitize`, `jnp.searchsorted`,
#   `bucketize`) is a finding anywhere in the framework outside the future
#   histogram core (ops/histogram.py, reserved).
#
# Taint is function-scoped and shallow exactly like raw-distance: names
# bound to binning-derived expressions are tainted, taint flows through
# arithmetic, subscripts, `astype`/`clip`/`reshape`/`ravel` and the
# shape-preserving combinators, and any other call launders — a bin tensor
# produced by one function and accumulated by another is the factored shape
# the future core will own, not a hand-rolled loop. Genuinely different
# shapes waive with `# histogram-ok: <reason>`. The baseline lands EMPTY:
# today's tree bins (ops/trees.py `_bin_features`) and accumulates
# (`_grow_level`) in separate functions, which is exactly the boundary the
# rule preserves.
#
from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..engine import FileContext, RuleBase, dotted

# taint sources: calls that turn values into BIN IDS
_BINNING_TAILS = {"digitize", "searchsorted", "bucketize"}
# function-call combinators taint flows through (positional args)
_PROPAGATING_TAILS = {
    "where", "maximum", "minimum", "concatenate", "pad", "clip",
    "broadcast_to", "one_hot",
}
# method calls whose RECEIVER carries the taint through
_METHOD_PROPAGATING = {"astype", "reshape", "ravel", "flatten", "clip"}
# accumulation sinks over a binned operand
_SEGMENT_TAILS = {"segment_sum"}
_SCATTER_TAILS = {"scatter_add", "scatter_add_p"}
_DOT_TAILS = {"dot", "matmul", "einsum", "tensordot"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class HistogramLoopRule(RuleBase):
    id = "histogram-loop"
    waiver = "histogram"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset({"histogram.py"})  # the (future) core owns the loop
    description = (
        "hand-rolled binned accumulation (segment_sum/scatter/one-hot-matmul "
        "over locally-binned ids) outside the histogram core"
    )

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        self._scope(tree.body, set(), ctx)

    # ---------------------------------------------------------- traversal --

    def _scope(self, body: List[ast.stmt], inherited: Set[str], ctx: FileContext) -> None:
        tainted: Set[str] = set(inherited)
        for stmt in body:
            self._stmt(stmt, tainted, ctx)

    def _stmt(self, stmt: ast.stmt, tainted: Set[str], ctx: FileContext) -> None:
        if isinstance(stmt, _FUNC_NODES) or isinstance(stmt, ast.ClassDef):
            self._scope(stmt.body, tainted, ctx)
            return
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While,
                             ast.With, ast.AsyncWith, ast.Try)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    for node in ast.walk(child):
                        if isinstance(node, ast.Call):
                            self._check_call(node, tainted, ctx)
                elif isinstance(child, ast.withitem):
                    for node in ast.walk(child.context_expr):
                        if isinstance(node, ast.Call):
                            self._check_call(node, tainted, ctx)
            if isinstance(stmt, (ast.For, ast.AsyncFor)) and self._tainted(
                stmt.iter, tainted
            ):
                tainted.update(
                    n.id for n in ast.walk(stmt.target) if isinstance(n, ast.Name)
                )
            for field in ("body", "orelse", "finalbody"):
                for sub in getattr(stmt, field, []) or []:
                    self._stmt(sub, tainted, ctx)
            for handler in getattr(stmt, "handlers", []) or []:
                for sub in handler.body:
                    self._stmt(sub, tainted, ctx)
            return
        nested = [n for n in ast.walk(stmt) if isinstance(n, _FUNC_NODES)]
        skip: Set[int] = set()
        for fn in nested:
            for sub in ast.walk(fn):
                if sub is not fn:
                    skip.add(id(sub))
        for node in ast.walk(stmt):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Call):
                self._check_call(node, tainted, ctx)
            elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                self._check_matmul(node.left, node.right, node, tainted, ctx)
        for node in ast.walk(stmt):
            if id(node) in skip:
                continue
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            if value is None:
                continue
            tnt = self._tainted(value, tainted)
            for t in targets:
                names = [n.id for n in ast.walk(t) if isinstance(n, ast.Name)]
                if tnt:
                    tainted.update(names)
                elif isinstance(node, ast.Assign) and isinstance(t, ast.Name):
                    tainted.discard(t.id)  # clean rebinding
        for fn in nested:
            self._scope(fn.body, tainted, ctx)

    # ------------------------------------------------------------- sinks ---

    def _check_call(self, node: ast.Call, tainted: Set[str], ctx: FileContext) -> None:
        name = dotted(node.func, ctx.imports)
        tail = name.split(".")[-1] if name else None
        if tail in _SEGMENT_TAILS and len(node.args) > 1:
            if self._tainted(node.args[1], tainted):
                self._emit(node, "segment_sum over locally-binned segment ids", ctx)
            return
        if tail in _SCATTER_TAILS and any(
            self._tainted(a, tainted) for a in node.args
        ):
            self._emit(node, "scatter-add over locally-binned indices", ctx)
            return
        if tail in _DOT_TAILS and name is not None:
            args = [
                a for a in node.args
                if not (isinstance(a, ast.Constant) and isinstance(a.value, str))
            ]
            self._check_matmul(
                args[0] if args else None,
                args[1] if len(args) > 1 else None, node, tainted, ctx,
            )
            return
        # `.at[bins].add(...)`: Call(add) over Subscript over `.at`
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "add"
            and isinstance(node.func.value, ast.Subscript)
            and isinstance(node.func.value.value, ast.Attribute)
            and node.func.value.value.attr == "at"
        ):
            if self._tainted(node.func.value.slice, tainted):
                self._emit(node, ".at[bins].add(...) over locally-binned indices", ctx)

    def _check_matmul(
        self, left: Optional[ast.expr], right: Optional[ast.expr],
        node: ast.AST, tainted: Set[str], ctx: FileContext,
    ) -> None:
        for side in (left, right):
            if side is not None and self._tainted(side, tainted):
                self._emit(node, "one-hot matmul over locally-binned ids", ctx)
                return

    def _emit(self, node: ast.AST, what: str, ctx: FileContext) -> None:
        ctx.emit(
            self,
            node,
            f"{what} — hand-rolled binned accumulation is the pattern the "
            "shared histogram core will own (ROADMAP item 4, the "
            "ops/distance.py consolidation shape); keep binning and "
            "accumulation behind the core boundary, or mark "
            "`# histogram-ok: <reason>`",
        )

    # --------------------------------------------------------------- taint --

    def _tainted(self, node: Optional[ast.expr], tainted: Set[str]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.BinOp):
            return self._tainted(node.left, tainted) or self._tainted(node.right, tainted)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand, tainted)
        if isinstance(node, ast.Attribute):
            return self._tainted(node.value, tainted)  # `bins.T`, `oh.T`
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value, tainted)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._tainted(e, tainted) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self._tainted(node.body, tainted) or self._tainted(node.orelse, tainted)
        if isinstance(node, ast.Call):
            name = dotted(node.func, None)
            tail = name.split(".")[-1] if name else None
            if tail is None and isinstance(node.func, ast.Attribute):
                tail = node.func.attr
            if tail in _BINNING_TAILS:
                return True
            if tail in _PROPAGATING_TAILS or tail in _METHOD_PROPAGATING:
                if any(self._tainted(a, tainted) for a in node.args):
                    return True
                # method form: `bins.astype(i32)` carries the receiver's taint
                return isinstance(node.func, ast.Attribute) and self._tainted(
                    node.func.value, tainted
                )
            return False  # any other call launders (incl. the future core)
        return False
