#
# Text-level hygiene carried over from the regex-era gate (tabs, trailing
# whitespace) plus the waiver-form contract: every `# <tag>-ok` waiver must
# carry a `: <reason>` suffix — a reason-less waiver suppresses nothing and
# is itself a finding, so the rationale for every exemption lives next to it.
#
from __future__ import annotations

import ast
from typing import Optional

from ..engine import FileContext, RuleBase

# tags whose `<tag>-ok` comments are waivers (kept in sync with the rule
# catalog by rules/__init__.default_rules, which unions in every rule.waiver)
KNOWN_WAIVER_TAGS = {
    "telemetry",
    "blocking",
    "sink",
    "sleep",
    "hbm",
    "bucket",
    "spmd",
    "submesh",
    "host-fetch",
    "traced",
    "config",
    "metric",
    "distance",
    "serve",
    "ledger",
    "exporter",
    "lock-order",
    "held",
    "guard",
    "precision",
    "prng",
    "histogram",
    "profiler",
    "wallclock",
}


class HygieneRule(RuleBase):
    id = "hygiene"
    waiver = None
    tree_scope = ("spark_rapids_ml_tpu", "benchmark", "tests")
    text_only = True  # runs even when the file fails to parse
    description = "tabs, trailing whitespace, and reason-less waiver comments"
    # the ids this rule actually emits findings under (verdict catalog rows)
    sub_ids = (
        ("tab", "tab character"),
        ("trailing-whitespace", "trailing whitespace"),
        ("waiver-missing-reason", "`# <tag>-ok` waiver without the required `: <reason>`"),
    )

    def check_module(self, tree: Optional[ast.Module], ctx: FileContext) -> None:
        for lineno, line in enumerate(ctx.lines, 1):
            if "\t" in line:
                ctx.emit_at("tab", lineno, line.index("\t") + 1, "tab character")
            if line != line.rstrip():
                ctx.emit_at(
                    "trailing-whitespace", lineno, len(line.rstrip()) + 1, "trailing whitespace"
                )
        for lineno, tags in sorted(ctx.waivers.items()):
            for tag, reason in tags.items():
                if tag in KNOWN_WAIVER_TAGS and not reason:
                    ctx.emit_at(
                        "waiver-missing-reason",
                        lineno,
                        1,
                        f"`# {tag}-ok` waiver without a reason — the required "
                        f"form is `# {tag}-ok: <reason>` (docs/development.md); "
                        "a bare waiver suppresses nothing",
                    )
