#
# SPMD-divergence detector — the invariant PRs 3/5/6 detect at runtime
# (deadline timeouts, flight-recorder post-mortems naming the blocked
# round), caught before it ships: a control-plane collective (`allgather`,
# `barrier`, `reform`, or the `allgather_concat` helper) that only SOME
# ranks reach. Under the barrier-clique design (PAPER.md L4) every rank must
# enter every round in lockstep; a collective guarded by a rank-identity
# test (`rank`, `orig_rank`, `process_index`) or placed inside an `except`
# handler (only ranks whose try body raised get there) hangs the survivors
# until the round deadline, then kills the fit with
# RendezvousTimeoutError. Rank-dependent PAYLOADS are fine (every rank still
# calls the collective); rank-dependent REACHABILITY is the bug.
#
# PR 19 adds the PLACEMENT spelling of the same hang: code running under a
# carved sub-mesh (`with submesh(...)` / `with chip_scope(...)`) executes on
# only SOME of the pool, but the control-plane collectives above span the
# FULL rendezvous clique — a full-mesh `allgather` reachable from
# sub-mesh-scoped code strands the ranks outside the carve exactly like a
# rank-conditional does. Waive deliberate full-group rounds (e.g. a sweep
# shard reporting back to the whole clique) with `# submesh-ok: <reason>`.
#
from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ..engine import FileContext, RuleBase, dotted

RANK_IDENTIFIERS = {"rank", "orig_rank", "process_index"}
COLLECTIVE_ATTRS = {"allgather", "barrier", "reform"}
COLLECTIVE_NAMES = {"allgather_concat"}
SUBMESH_SCOPE_NAMES = {"submesh", "chip_scope"}


def _mentions_rank(test: ast.AST) -> Optional[str]:
    """The rank identifier a conditional tests, if any."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Name) and sub.id in RANK_IDENTIFIERS:
            return sub.id
        if isinstance(sub, ast.Attribute) and sub.attr in RANK_IDENTIFIERS:
            return sub.attr
    return None


def _collective_name(node: ast.Call, imports) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in COLLECTIVE_ATTRS:
        return func.attr
    name = dotted(func, imports)
    if name is not None and name.split(".")[-1] in COLLECTIVE_NAMES:
        return name.split(".")[-1]
    return None


def _submesh_scope_name(expr: ast.AST, imports) -> Optional[str]:
    """The sub-mesh carving helper a `with` item enters, if any."""
    if not isinstance(expr, ast.Call):
        return None
    func = expr.func
    if isinstance(func, ast.Attribute) and func.attr in SUBMESH_SCOPE_NAMES:
        return func.attr
    name = dotted(func, imports)
    if name is not None and name.split(".")[-1] in SUBMESH_SCOPE_NAMES:
        return name.split(".")[-1]
    return None


def _collectives_in(stmts, imports) -> List[str]:
    """Ordered collective calls in a branch (nested functions excluded) —
    used to recognize SYMMETRIC conditionals, where every arm performs the
    same collective sequence and lockstep is preserved."""
    out: List[str] = []
    for stmt in stmts:
        stack = [stmt]
        while stack:
            node = stack.pop(0)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                name = _collective_name(node, imports)
                if name is not None:
                    out.append(name)
            stack.extend(ast.iter_child_nodes(node))
    return out


def _has_early_exit(stmts, in_nested_loop: bool = False) -> bool:
    """Does this branch body leave the enclosing block (return/raise, or a
    continue/break at this loop level), making everything AFTER the
    conditional unreachable for the ranks that took it? Nested functions
    don't count (they exit the nested scope), and a break/continue inside a
    NESTED loop only exits that inner loop, not the guarded block."""
    for node in stmts:
        if isinstance(node, (ast.Return, ast.Raise)):
            return True
        if isinstance(node, (ast.Continue, ast.Break)) and not in_nested_loop:
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        nested = in_nested_loop or isinstance(
            node, (ast.For, ast.AsyncFor, ast.While)
        )
        for field in ("body", "orelse", "finalbody"):
            if _has_early_exit(getattr(node, field, []) or [], nested):
                return True
        for handler in getattr(node, "handlers", []) or []:
            if _has_early_exit(handler.body, nested):
                return True
    return False


class SpmdDivergenceRule(RuleBase):
    id = "spmd-divergence"
    waiver = "spmd"
    tree_scope = ("spark_rapids_ml_tpu",)
    description = (
        "collectives reachable by only some ranks (rank-conditional, "
        "except-handler, or full-mesh collective under a sub-mesh scope)"
    )

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        self._visit_block(tree.body, ctx, [])

    def _visit_block(
        self, stmts, ctx: FileContext, stack: List[Tuple[str, int, str]]
    ) -> None:
        """Visit a statement SEQUENCE: a rank-guarded early exit
        (`if rank != 0: return`) makes every later statement in the block
        divergent-reachable too — the other failure spelling of the same
        hang, where the collective sits in straight-line code below the
        guard instead of inside it."""
        stack = list(stack)
        for stmt in stmts:
            self._visit(stmt, ctx, stack)
            if isinstance(stmt, ast.If):
                rank_id = _mentions_rank(stmt.test)
                if rank_id and (
                    _has_early_exit(stmt.body) or _has_early_exit(stmt.orelse)
                ):
                    stack.append(
                        (
                            f"rank-identity conditional on `{rank_id}` with an "
                            "early exit",
                            stmt.lineno,
                            "spmd",
                        )
                    )

    def _visit(
        self, node: ast.AST, ctx: FileContext, stack: List[Tuple[str, int, str]]
    ) -> None:
        # a nested function body does not execute under the enclosing
        # conditional — it executes wherever it is CALLED — so the
        # divergence context resets at every function boundary
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if isinstance(node, ast.Lambda):
                self._visit(node.body, ctx, [])
            else:
                self._visit_block(node.body, ctx, [])
            return
        if isinstance(node, ast.Call):
            name = _collective_name(node, ctx.imports)
            if name is not None and stack:
                kind, line, tag = stack[-1]
                if tag == "submesh":
                    # different failure, different waiver: the collective's
                    # clique is the FULL process group, but the enclosing
                    # scope carved the pool — ranks outside the carve never
                    # enter the round
                    if not ctx.waived("submesh", node):
                        ctx.emit_at(
                            self.id,
                            node.lineno,
                            node.col_offset + 1,
                            f"full-mesh collective `{name}` under {kind} "
                            f"(line {line}): the rendezvous round spans the "
                            "whole clique but only the carved sub-mesh's "
                            "ranks reach it, stranding the rest until the "
                            "round deadline; run the round on the sub-mesh's "
                            "own group, hoist it out of the carve, or mark "
                            "`# submesh-ok: <reason>`",
                        )
                else:
                    ctx.emit(
                        self,
                        node,
                        f"collective `{name}` reachable by only some ranks — "
                        f"{kind} (line {line}) lets ranks skip it, hanging peers "
                        "in the round until the rendezvous deadline; hoist the "
                        "collective so every rank reaches it (keep the payload "
                        "rank-dependent instead) or mark `# spmd-ok: <reason>`",
                    )
        if isinstance(node, (ast.If, ast.While)):
            rank_id = _mentions_rank(node.test)
            frame = (
                f"rank-identity conditional on `{rank_id}`", node.lineno, "spmd"
            )
            self._visit(node.test, ctx, stack)
            inner = stack + [frame] if rank_id else stack
            if rank_id and isinstance(node, ast.If) and node.orelse:
                # symmetric conditional: every arm performs the SAME
                # collective sequence, so every rank still enters every
                # round — only the payload is rank-dependent, which is the
                # documented correct pattern
                body_c = _collectives_in(node.body, ctx.imports)
                else_c = _collectives_in(node.orelse, ctx.imports)
                if body_c and body_c == else_c:
                    inner = stack
            self._visit_block(node.body, ctx, inner)
            self._visit_block(node.orelse, ctx, inner)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._visit(node.iter, ctx, stack)
            self._visit_block(node.body, ctx, stack)
            self._visit_block(node.orelse, ctx, stack)
            return
        if isinstance(node, ast.Try):
            self._visit_block(node.body, ctx, stack)
            for handler in node.handlers:
                frame = ("except handler", handler.lineno, "spmd")
                self._visit_block(handler.body, ctx, stack + [frame])
            self._visit_block(node.orelse, ctx, stack)
            self._visit_block(node.finalbody, ctx, stack)
            return
        if isinstance(node, ast.With):
            inner = stack
            for item in node.items:
                self._visit(item.context_expr, ctx, stack)
                scope = _submesh_scope_name(item.context_expr, ctx.imports)
                if scope is not None:
                    inner = inner + [
                        (f"sub-mesh scope `{scope}(...)`", node.lineno, "submesh")
                    ]
            self._visit_block(node.body, ctx, inner)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, ctx, stack)
