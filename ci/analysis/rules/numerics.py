#
# Numerics gate: two interprocedural rules over the pass-1 whole-program
# model (ci/analysis/program.py) guarding the framework's headline numeric
# contracts — streaming==resident at rtol 1e-9, bit-identical checkpoint
# resume, batched==sequential sweeps, per-partition datagen bit-identity for
# any process count, and the per-model bf16 serving accuracy contract
# (docs/robustness.md "Numerics contract"):
#
#   precision-flow    a dtype lattice (f64/f32/bf16/f16) threaded through
#                     local bindings and resolved calls. Three findings:
#                     (1) silent narrowing into an accumulator — an
#                     f64-bound local reassigned or augmented with an
#                     f32/bf16/f16 expression; (2) a low-precision dot —
#                     `dot`/`matmul`/`einsum`/`tensordot`/Pallas `pl.dot`/
#                     the `@` operator on a bf16/f16 operand (locally
#                     evident, or proven via the param-dtype meet over every
#                     resolved call site) without a `preferred_element_type`
#                     of f32-or-wider — one-pass MXU bf16 carries ~3 decimal
#                     digits, the accuracy cliff docs/serving.md documents;
#                     (3) a jnp-level float64 constant/cast/ctor reachable
#                     without the x64 guard (`enable_x64`/`x64_scope`
#                     context, a `jax_enable_x64` conditional, or every
#                     resolved call site guarded) — with
#                     `jax_enable_x64=False` those silently run at f32.
#                     Sanctioned sites (ops/distance.py's parity-tested
#                     fast-bf16 path) waive `# precision-ok: <reason>`.
#
#   prng-discipline   linearity checking of `jax.random` keys, per function:
#                     a key consumed twice (two sampling sinks, or sampled
#                     after being `split`) draws correlated streams; a
#                     `split`/`fold_in` result that is never bound is
#                     entropy minted and dropped; a key seeded from
#                     wall-clock/`os.urandom`/process identity — or any
#                     legacy global `np.random.*` call — breaks the
#                     per-partition datagen bit-identity contract
#                     (benchmark/gen_data* is in scope for exactly that
#                     reason); and rank-dependent key derivation
#                     (`PRNGKey(seed + rank)`, `fold_in(key, rank)`) in a
#                     function that reaches a rendezvous collective
#                     (composing with the PR-9 spmd facts via
#                     `program.may_block`) seeds divergent streams where the
#                     SPMD lockstep contract requires agreement. Deliberate
#                     per-rank sampling (RF bagging, UMAP negative-sample
#                     salts) waives `# prng-ok: <reason>`.
#
# The runtime twin (spark_rapids_ml_tpu/utils/numcheck.py, SRML_NUMCHECK=1)
# asserts finite-ness and records dtype watermarks at the solver boundaries
# that already host-fetch — the static pass proposes, the sanitizer verifies,
# exactly the lockcheck pattern.
#
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from ..engine import FileContext, Finding, RuleBase, Run, dotted
from ..program import module_path
from .spmd import _mentions_rank

# --------------------------------------------------------- precision-flow --

_LOW = ("bf16", "f16")


class PrecisionFlowRule(RuleBase):
    id = "precision-flow"
    waiver = "precision"
    tree_scope = ("spark_rapids_ml_tpu",)
    description = (
        "silent f64->f32/bf16 narrowing into accumulators, low-precision "
        "dot-like ops without preferred_element_type, and unguarded jnp f64"
    )

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        pass  # pass-1 facts carry everything; findings come from finalize

    def finalize(self, run: Run) -> List[Finding]:
        program = getattr(run, "program", None)
        if program is None:
            return []
        param_dt = program.param_dtypes()
        entry_x64 = program.entry_x64()
        out: List[Finding] = []
        for qual, fn in program.functions.items():
            for ev in fn["events"]:
                if "precision" in ev.get("waived", []):
                    continue
                if ev["t"] == "narrow":
                    how = (
                        "augmented with"
                        if ev.get("aug")
                        else "reassigned"
                    )
                    out.append(
                        Finding(
                            fn["relpath"], ev["line"], ev["col"], self.id,
                            f"f64 accumulator `{ev['name']}` {how} "
                            f"a {ev['to']} expression in `{qual}` — silent "
                            "precision narrowing breaks the rtol-1e-9 "
                            "solver contracts; widen the expression "
                            "(accumulate at f64), or mark "
                            "`# precision-ok: <reason>`",
                        )
                    )
                elif ev["t"] == "lowdot":
                    toks = [
                        self._resolve(d, qual, param_dt) for d in ev.get("args", [])
                    ]
                    low = sorted({t for t in toks if t in _LOW})
                    pref = ev.get("pref")
                    if low and (pref is None or pref in _LOW):
                        op = ev["op"]
                        fix = (
                            "spell the accumulation dtype with "
                            "`preferred_element_type=jnp.float32`"
                            if op != "@"
                            else "use jnp.matmul/lax.dot with "
                            "`preferred_element_type=jnp.float32` instead "
                            "of the `@` operator"
                        )
                        out.append(
                            Finding(
                                fn["relpath"], ev["line"], ev["col"], self.id,
                                f"`{op}` on {'/'.join(low)} operand(s) "
                                "without an f32-or-wider "
                                f"preferred_element_type in `{qual}` — "
                                "one-pass MXU bf16 accumulation carries ~3 "
                                f"decimal digits; {fix}, or mark "
                                "`# precision-ok: <reason>`",
                            )
                        )
                elif ev["t"] == "f64":
                    if ev.get("x64") or entry_x64.get(qual):
                        continue
                    out.append(
                        Finding(
                            fn["relpath"], ev["line"], ev["col"], self.id,
                            f"jnp-level float64 in `{qual}` reachable "
                            "without the x64 guard — with "
                            "jax_enable_x64=False this silently computes at "
                            "f32; run it under `enable_x64`/`x64_scope` "
                            "(parallel/mesh.py owns the guard), or mark "
                            "`# precision-ok: <reason>`",
                        )
                    )
        return out

    @staticmethod
    def _resolve(
        desc: Dict[str, Any], qual: str,
        param_dt: Dict[str, Dict[str, Optional[str]]],
    ) -> Optional[str]:
        if "param" in desc:
            return param_dt.get(qual, {}).get(desc["param"])
        return desc.get("dt")


# -------------------------------------------------------- prng-discipline --

# jax.random calls that CONSUME their key (linearity: at most one per key
# binding) — sampling primitives plus `split` (drawing from a key after
# splitting it correlates with the children, the classic reuse bug)
_CONSUMING_TAILS = {
    "split", "normal", "uniform", "randint", "choice", "categorical",
    "bernoulli", "permutation", "shuffle", "truncated_normal", "gamma",
    "beta", "exponential", "laplace", "gumbel", "rademacher", "bits",
    "dirichlet", "poisson", "multivariate_normal", "orthogonal", "ball",
}
# derivation that does NOT consume: `fold_in(key, i)` with distinct data is
# the sanctioned many-streams-from-one-key pattern (per-partition datagen,
# per-tree bagging)
_ENTROPY_SOURCES = {
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "os.urandom", "os.getpid", "uuid.uuid4", "secrets.token_bytes",
    "secrets.randbits",
}
# legacy global-state numpy RNG surface; the sanctioned form is
# `np.random.default_rng(<explicit seed>)`
_NP_GLOBAL_TAILS = {
    "seed", "normal", "uniform", "rand", "randn", "randint", "random",
    "choice", "shuffle", "permutation", "standard_normal",
}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class PrngDisciplineRule(RuleBase):
    id = "prng-discipline"
    waiver = "prng"
    tree_scope = ("spark_rapids_ml_tpu", "benchmark")
    description = (
        "jax.random key reuse/dropped splits, nondeterministic or global-RNG "
        "seeding, and rank-dependent keys in lockstep (collective) functions"
    )

    def __init__(self) -> None:
        # relpath -> deferred rank-dependent mint candidates, resolved in
        # finalize against the whole-program collective-reachability facts
        self._deferred: Dict[str, List[Dict[str, Any]]] = {}
        self._file_emitted: set = set()

    def applies(self, ctx: FileContext) -> bool:
        if not super().applies(ctx):
            return False
        if ctx.target == "benchmark":
            # only the datagen family carries the bit-identity contract
            return ctx.filename.startswith("gen_data")
        return True

    def file_state(self, relpath: str):
        state = self._deferred.get(relpath)
        return list(state) if state else None

    def restore_state(self, relpath: str, state) -> None:
        self._deferred[relpath] = list(state)

    # ------------------------------------------------------------ traversal

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        mod = module_path(ctx.relpath)
        # finding dedup is FILE-scoped, not scope-scoped: the loop bodies'
        # double scan re-enters nested scopes too, and a per-scope set would
        # double-report everything inside a closure defined in a loop
        self._file_emitted: set = set()
        self._scan_scope(tree.body, ctx, mod, None)

    def _scan_scope(
        self, body: List[ast.stmt], ctx: FileContext, qual: str,
        cls: Optional[str],
    ) -> None:
        """One function (or module) scope: a fresh linear key-consumption
        state; nested defs/classes recurse with fresh scopes (a nested
        function's `key` parameter is a new binding, not the outer key)."""
        state: Dict[str, Any] = {"consumed": {}}
        self._scan_block(body, ctx, qual, cls, state, in_loop=False)

    def _scan_block(
        self, stmts: List[ast.stmt], ctx: FileContext, qual: str,
        cls: Optional[str], state: Dict[str, Any], in_loop: bool,
    ) -> None:
        for stmt in stmts:
            self._scan_stmt(stmt, ctx, qual, cls, state, in_loop)

    def _scan_stmt(
        self, stmt: ast.stmt, ctx: FileContext, qual: str,
        cls: Optional[str], state: Dict[str, Any], in_loop: bool,
    ) -> None:
        if isinstance(stmt, _FUNC_NODES):
            # nested def: fresh scope, named `<qual>.<name>` exactly as the
            # program model names it (finalize joins on these quals)
            self._scan_scope(stmt.body, ctx, f"{qual}.{stmt.name}", None)
            return
        if isinstance(stmt, ast.ClassDef):
            # methods are `<module>.<Class[.Nested]>.<method>`; `qual` here
            # is still the module path (classes only appear at module level
            # or nested in other classes in this tree)
            cname = stmt.name if cls is None else f"{cls}.{stmt.name}"
            for sub in stmt.body:
                if isinstance(sub, _FUNC_NODES):
                    self._scan_scope(sub.body, ctx, f"{qual}.{cname}.{sub.name}", None)
                elif isinstance(sub, ast.ClassDef):
                    self._scan_stmt(sub, ctx, qual, cname, state, in_loop)
            return
        if isinstance(stmt, ast.If):
            self._scan_exprs([stmt.test], ctx, qual, state, in_loop)
            snap = dict(state["consumed"])
            self._scan_block(stmt.body, ctx, qual, cls, state, in_loop)
            after_body = state["consumed"]
            state["consumed"] = dict(snap)
            self._scan_block(stmt.orelse, ctx, qual, cls, state, in_loop)
            # after the conditional: a key consumed in EITHER arm counts as
            # consumed (and a key consumed in both arms was consumed once
            # per execution — not a reuse)
            merged = dict(state["consumed"])
            merged.update(after_body)
            state["consumed"] = merged
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            header = [stmt.iter] if isinstance(stmt, (ast.For, ast.AsyncFor)) else [stmt.test]
            self._scan_exprs(header, ctx, qual, state, in_loop)
            # scan the body TWICE: the second pass sees the consumption state
            # the first iteration left behind, so sampling an outer-scope key
            # inside the loop is caught as cross-iteration reuse, while a key
            # re-split/re-minted inside the body stays clean. Findings
            # deduplicate via the per-file emitted set. The loop TARGET is a
            # fresh binding each iteration (`for sub in split(key, n):` is
            # the sanctioned batch-split idiom) — clear it before each pass.
            targets = (
                [n.id for n in ast.walk(stmt.target) if isinstance(n, ast.Name)]
                if isinstance(stmt, (ast.For, ast.AsyncFor))
                else []
            )
            for _ in range(2):
                for name in targets:
                    state["consumed"].pop(name, None)
                self._scan_block(stmt.body, ctx, qual, cls, state, in_loop=True)
            self._scan_block(stmt.orelse, ctx, qual, cls, state, in_loop)
            return
        if isinstance(stmt, ast.Try):
            snap = dict(state["consumed"])
            self._scan_block(stmt.body, ctx, qual, cls, state, in_loop)
            merged = dict(state["consumed"])
            for handler in stmt.handlers:
                state["consumed"] = dict(snap)
                self._scan_block(handler.body, ctx, qual, cls, state, in_loop)
                merged.update(state["consumed"])
            state["consumed"] = merged
            self._scan_block(stmt.orelse, ctx, qual, cls, state, in_loop)
            self._scan_block(stmt.finalbody, ctx, qual, cls, state, in_loop)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._scan_exprs(
                [i.context_expr for i in stmt.items], ctx, qual, state, in_loop
            )
            self._scan_block(stmt.body, ctx, qual, cls, state, in_loop)
            return
        # dropped derivation: a bare `jax.random.split(key)` statement mints
        # subkeys nobody binds
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            name = dotted(stmt.value.func, ctx.imports)
            if name in ("jax.random.split", "jax.random.fold_in"):
                self._emit_once(
                    ctx, state, stmt.value, "drop",
                    f"`{name.rsplit('.', 1)[1]}` result is never bound — "
                    "freshly derived subkeys are dropped (either use them or "
                    "delete the call); mark `# prng-ok: <reason>` if "
                    "deliberate",
                )
        # expressions first (uses), then bindings (rebind resets linearity)
        exprs: List[ast.AST] = []
        for field in ("value", "test", "exc", "msg", "cause"):
            v = getattr(stmt, field, None)
            if isinstance(v, ast.AST):
                exprs.append(v)
        self._scan_exprs(exprs, ctx, qual, state, in_loop)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in targets:
                for node in ast.walk(t):
                    if isinstance(node, ast.Name):
                        state["consumed"].pop(node.id, None)

    def _scan_exprs(
        self, exprs: List[ast.AST], ctx: FileContext, qual: str,
        state: Dict[str, Any], in_loop: bool,
    ) -> None:
        for expr in exprs:
            if expr is None:
                continue
            for node in ast.walk(expr):
                if isinstance(node, (ast.Lambda,) + _FUNC_NODES):
                    continue
                if isinstance(node, ast.NamedExpr) and isinstance(
                    node.target, ast.Name
                ):
                    state["consumed"].pop(node.target.id, None)
                if isinstance(node, ast.Call):
                    self._check_call(node, ctx, qual, state)

    # ------------------------------------------------------------- checks --

    def _check_call(
        self, node: ast.Call, ctx: FileContext, qual: str,
        state: Dict[str, Any],
    ) -> None:
        name = dotted(node.func, ctx.imports)
        if name is None:
            return
        tail = name.rsplit(".", 1)[-1]
        if name.startswith("jax.random."):
            self._check_entropy(node, ctx, state, tail)
            self._check_rank_dep(node, ctx, qual, state, name, tail)
            if tail in _CONSUMING_TAILS and node.args:
                key = node.args[0]
                if isinstance(key, ast.Name):
                    first = state["consumed"].get(key.id)
                    if first is not None:
                        self._emit_once(
                            ctx, state, node, "reuse",
                            f"key `{key.id}` already consumed by "
                            f"`{first[2]}` at line {first[0]} is consumed "
                            f"again by `{tail}` — reusing a jax.random key "
                            "draws correlated streams; split first, or mark "
                            "`# prng-ok: <reason>`",
                        )
                    else:
                        state["consumed"][key.id] = (
                            node.lineno, node.col_offset + 1, tail
                        )
            return
        if name.startswith("numpy.random."):
            if tail == "default_rng":
                if not node.args and not node.keywords:
                    self._emit_once(
                        ctx, state, node, "unseeded",
                        "`np.random.default_rng()` without an explicit seed "
                        "— OS-entropy seeding breaks the per-partition "
                        "datagen bit-identity contract; pass a seed derived "
                        "from the partition/config, or mark "
                        "`# prng-ok: <reason>`",
                    )
                else:
                    self._check_entropy(node, ctx, state, tail)
                    self._check_rank_dep(node, ctx, qual, state, name, tail)
            elif tail in _NP_GLOBAL_TAILS:
                self._emit_once(
                    ctx, state, node, "global-rng",
                    f"legacy global-state `np.random.{tail}` — hidden "
                    "process-wide RNG state is not reproducible per "
                    "partition; use `np.random.default_rng(<seed>)`, or "
                    "mark `# prng-ok: <reason>`",
                )

    def _check_entropy(
        self, node: ast.Call, ctx: FileContext, state: Dict[str, Any],
        tail: str,
    ) -> None:
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call):
                    src = dotted(sub.func, ctx.imports)
                    if src in _ENTROPY_SOURCES:
                        self._emit_once(
                            ctx, state, node, "entropy",
                            f"`{tail}` seeded from `{src}()` — wall-clock/"
                            "OS-entropy seeds are not reproducible and break "
                            "the per-partition datagen bit-identity "
                            "contract; derive the seed from config + "
                            "partition id, or mark `# prng-ok: <reason>`",
                        )
                        return

    def _check_rank_dep(
        self, node: ast.Call, ctx: FileContext, qual: str,
        state: Dict[str, Any], name: str, tail: str,
    ) -> None:
        """Defer rank-dependent key minting (`PRNGKey(seed + rank)`,
        `fold_in(key, rank)`, `default_rng(seed * p + rank)`) to finalize —
        it is only a finding when the enclosing function participates in the
        SPMD lockstep (reaches a rendezvous collective, per the program
        model's may_block facts)."""
        if tail not in ("PRNGKey", "key", "fold_in", "default_rng"):
            return
        seed_args = node.args[1:] if tail == "fold_in" else node.args[:1]
        rank_id = None
        for a in seed_args:
            rank_id = _mentions_rank(a)
            if rank_id:
                break
        if not rank_id:
            return
        dedup = ("rankdep", node.lineno, node.col_offset + 1)
        if dedup in self._file_emitted:
            return
        self._file_emitted.add(dedup)
        self._deferred.setdefault(ctx.relpath, []).append(
            {
                "line": node.lineno,
                "col": node.col_offset + 1,
                "qual": qual,
                "tail": tail,
                "rank_id": rank_id,
                "waived": ctx.waived(self.waiver, node),
            }
        )

    def _emit_once(
        self, ctx: FileContext, state: Dict[str, Any], node: ast.AST,
        kind: str, message: str,
    ) -> None:
        dedup = (kind, getattr(node, "lineno", 1), getattr(node, "col_offset", 0) + 1)
        if dedup in self._file_emitted:
            return
        self._file_emitted.add(dedup)
        ctx.emit(self, node, message)

    # ------------------------------------------------------------ finalize --

    def finalize(self, run: Run) -> List[Finding]:
        program = getattr(run, "program", None)
        may_block = program.may_block() if program is not None else {}
        out: List[Finding] = []
        for relpath, cands in sorted(self._deferred.items()):
            for c in cands:
                if c.get("waived"):
                    continue
                ops = may_block.get(c["qual"], {})
                collective = next(
                    (op for op in sorted(ops) if "rendezvous round" in op), None
                )
                if collective is None:
                    continue  # not a lockstep function: per-rank keys are fine
                out.append(
                    Finding(
                        relpath, c["line"], c["col"], self.id,
                        f"rank-dependent key derivation (`{c['tail']}` over "
                        f"`{c['rank_id']}`) in `{c['qual']}`, which reaches "
                        f"a collective ({collective}) — the SPMD lockstep "
                        "contract requires every rank to agree on "
                        "key-derived values; derive the key from data/"
                        "partition identity instead, or mark "
                        "`# prng-ok: <reason>` for deliberate per-rank "
                        "sampling whose results are later gathered",
                    )
                )
        return out
