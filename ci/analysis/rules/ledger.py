#
# ledger-bypass: capacity math stays behind the shared HBM ledger
# (docs/scheduling.md "The shared ledger").
#
# The admission controllers in memory.py and the scheduler are the ONLY
# places allowed to decide what fits: they charge against capacity minus the
# process-wide `scheduler.HbmLedger` and reserve what they admit. A direct
# `admit_fit` / `admit_model_load` call elsewhere is an admission the ledger
# lifecycle (reserve -> hold -> release) doesn't manage — its bytes either
# never appear in the book (other tenants overshoot) or leak forever; a
# direct `memory_stats()` is capacity read outside the budget/override/chaos
# resolution (the split-brain the direct-memstats rule already polices —
# re-checked here because a scheduler-era bypass breaks BOTH planes).
# The two sanctioned call sites — core's fit entry and the serving
# registry's load — carry `# ledger-ok: <reason>`; the baseline stays EMPTY.
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase, dotted

_ADMISSION_CALLS = {"admit_fit", "admit_model_load"}


class LedgerBypassRule(RuleBase):
    id = "ledger-bypass"
    waiver = "ledger"
    tree_scope = ("spark_rapids_ml_tpu",)
    # the budgeter owns admission + capacity; telemetry.py is the sanctioned
    # watermark sampler (same exemption as direct-memstats)
    exempt_files = frozenset({"memory.py", "telemetry.py"})
    description = (
        "direct admit_fit/admit_model_load/memory_stats capacity math "
        "outside memory.py and scheduler/"
    )

    def applies(self, ctx: FileContext) -> bool:
        if not super().applies(ctx):
            return False
        # the scheduler package IS the ledger owner
        return not ctx.relpath.startswith("spark_rapids_ml_tpu/scheduler/")

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Attribute):
                # any attribute spelling: memory.admit_fit(...),
                # _memory.admit_model_load(...), d.memory_stats()
                if func.attr in _ADMISSION_CALLS or func.attr == "memory_stats":
                    name = func.attr
            elif isinstance(func, ast.Name):
                # bare names only when the import resolves to the budgeter's
                # functions — a local helper that happens to share the name
                # is not an admission call
                origin = ctx.imports.get(func.id, "")
                tail = origin.rsplit(".", 1)[-1]
                if tail in _ADMISSION_CALLS and "memory" in origin:
                    name = tail
            if name is None:
                continue
            ctx.emit(
                self,
                node,
                f"direct `{name}` outside memory.py/scheduler/ — admission "
                "and capacity math flow through the shared HBM ledger "
                "(memory.admit_* reserve in scheduler.HbmLedger; releases "
                "are owned by core/the registry/the scheduler). Route "
                "through those layers or mark the sanctioned site "
                "`# ledger-ok: <reason>` (docs/scheduling.md)",
            )
