#
# Interprocedural concurrency rules over the pass-1 whole-program model
# (ci/analysis/program.py). Three invariants, all cross-file — the class of
# bug the per-file PR-9 rules could not see (docs/robustness.md "Threading
# model"):
#
#   lock-order            the static lock-acquisition graph (which named
#                         locks can be acquired while which others are held,
#                         following resolved calls across files) must be
#                         acyclic; a cycle is a latent deadlock between the
#                         paths that realize its edges. Re-entrant
#                         re-acquisition of an RLock/Condition is not an
#                         edge; re-acquiring a plain Lock while held is an
#                         immediate self-deadlock finding.
#   blocking-under-lock   a held lock's critical section must not reach a
#                         blocking operation — a rendezvous round,
#                         `block_until_ready`/host fetch, `.wait()` on
#                         anything but the held condition itself,
#                         `time.sleep`, file/network I/O, a future `.result`
#                         or thread join — directly or through any resolved
#                         call chain. The deadlock-and-tail-latency factory:
#                         every other thread needing that lock waits out the
#                         blocked section.
#   guard-discipline      a field declared `# guarded-by: <lock>` on its
#                         `__init__` (or module-global) assignment may only
#                         be read/written with that lock held — lexically,
#                         or because every resolved in-program call site of
#                         the enclosing function holds it (how `_locked`-
#                         suffixed helpers are proven safe).
#
# The runtime twin (spark_rapids_ml_tpu/utils/lockcheck.py, SRML_LOCKCHECK=1)
# validates the same order graph under real contention at test time: the
# static pass proposes, the sanitizer verifies.
#
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from ..engine import FileContext, Finding, RuleBase, Run


def _fmt_chain(chain: List[str]) -> str:
    return " -> ".join(q.rsplit(".", 1)[-1] + "()" for q in chain)


class _ProgramRule(RuleBase):
    """Shared base: these rules run entirely in `finalize` over
    `run.program`; per-file traversal happens in pass 1."""

    tree_scope = ("spark_rapids_ml_tpu",)

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        pass  # pass-1 facts carry everything; nothing to do per file


class LockOrderRule(_ProgramRule):
    id = "lock-order"
    waiver = "lock-order"
    description = (
        "cycles in the static lock-acquisition graph (lock B acquired while "
        "A held, across resolved call chains) — a latent deadlock"
    )

    def finalize(self, run: Run) -> List[Finding]:
        program = getattr(run, "program", None)
        if program is None:
            return []
        trans = program.trans_acquires()
        # edge (a, b): lock b acquired while a held; keep the first
        # (deterministic, shallowest-chain) witness per edge
        edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        findings: List[Finding] = []

        def note_edge(a: str, b: str, relpath: str, line: int, col: int,
                      via: Optional[List[str]], acq_site: List[Any]) -> None:
            if a == b:
                if program.lock_kind(a) in ("rlock", "condition"):
                    return  # re-entrant by construction: not an edge
                findings.append(
                    Finding(
                        relpath, line, col, self.id,
                        f"non-reentrant Lock `{a}` can be re-acquired while "
                        "already held"
                        + (f" (via {_fmt_chain(via)})" if via and len(via) > 1 else "")
                        + " — a guaranteed self-deadlock on that path; use an "
                        "RLock or drop the inner acquisition, or mark "
                        "`# lock-order-ok: <reason>`",
                    )
                )
                return
            key = (a, b)
            if key not in edges:
                edges[key] = {
                    "relpath": relpath, "line": line, "col": col,
                    "via": via, "acq_site": acq_site,
                }

        for qual, fn in program.functions.items():
            for ev in fn["events"]:
                if "lock-order" in ev.get("waived", []):
                    continue
                held = ev.get("held", [])
                if not held:
                    continue
                if ev["t"] == "acq" and ev.get("lock"):
                    for h in held:
                        note_edge(h, ev["lock"], fn["relpath"], ev["line"],
                                  ev["col"], None, [fn["relpath"], ev["line"]])
                elif ev["t"] == "call" and ev.get("callee"):
                    for lock, info in trans.get(ev["callee"], {}).items():
                        if info.get("waived"):
                            continue
                        for h in held:
                            note_edge(h, lock, fn["relpath"], ev["line"],
                                      ev["col"], [qual] + info["chain"],
                                      info["site"])

        findings.extend(self._cycles(edges))
        return findings

    def _cycles(self, edges: Dict[Tuple[str, str], Dict[str, Any]]) -> List[Finding]:
        graph: Dict[str, List[str]] = {}
        for a, b in edges:
            graph.setdefault(a, []).append(b)
            graph.setdefault(b, [])
        # iterative Tarjan SCC
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(root: str) -> None:
            work = [(root, iter(graph[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for succ in it:
                    if succ not in index:
                        index[succ] = low[succ] = counter[0]
                        counter[0] += 1
                        stack.append(succ)
                        on_stack.add(succ)
                        work.append((succ, iter(graph[succ])))
                        advanced = True
                        break
                    if succ in on_stack:
                        low[node] = min(low[node], index[succ])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(sorted(scc))

        for node in sorted(graph):
            if node not in index:
                strongconnect(node)

        out: List[Finding] = []
        for scc in sorted(sccs):
            cycle = self._extract_cycle(scc, graph)
            parts = []
            for a, b in zip(cycle, cycle[1:]):
                e = edges[(a, b)]
                via = f" via {_fmt_chain(e['via'])}" if e.get("via") else ""
                parts.append(f"`{b}` at {e['relpath']}:{e['line']}{via} (while `{a}` held)")
            rep = min(
                (edges[(a, b)] for a, b in zip(cycle, cycle[1:])),
                key=lambda e: (e["relpath"], e["line"], e["col"]),
            )
            out.append(
                Finding(
                    rep["relpath"], rep["line"], rep["col"], self.id,
                    "lock-order cycle — these acquisition paths can deadlock "
                    "against each other: " + "; ".join(parts) + ". Acquire in "
                    "one global order (docs/robustness.md \"Threading "
                    "model\"), or mark the safe edge "
                    "`# lock-order-ok: <reason>`",
                )
            )
        return out

    @staticmethod
    def _extract_cycle(scc: List[str], graph: Dict[str, List[str]]) -> List[str]:
        """One concrete cycle through the SCC as [n0, ..., n0]: a BFS from
        `start`'s successors back to `start`, restricted to SCC members —
        every consecutive pair is a REAL edge (a greedy walk could dead-end
        and fabricate a closing edge that was never recorded)."""
        members = set(scc)
        start = scc[0]
        parent: Dict[str, Optional[str]] = {}
        frontier = []
        for succ in sorted(graph[start]):
            if succ in members and succ not in parent:
                parent[succ] = None
                frontier.append(succ)
        while frontier:
            nxt = []
            for node in frontier:
                if node == start:
                    continue
                for succ in sorted(graph[node]):
                    if succ == start and start not in parent:
                        parent[start] = node
                    elif succ in members and succ not in parent:
                        parent[succ] = node
                        nxt.append(succ)
            if start in parent:
                break
            frontier = nxt
        # start is reachable from its own successor set by SCC definition
        path = [start]
        node = parent[start]
        while node is not None:
            path.append(node)
            node = parent[node]
        path.append(start)
        path.reverse()
        return path


class BlockingUnderLockRule(_ProgramRule):
    id = "blocking-under-lock"
    waiver = "held"
    description = (
        "a blocking operation (rendezvous round, device sync/host fetch, "
        "foreign .wait(), time.sleep, file/network I/O, future/thread join) "
        "reachable while a lock is held"
    )

    _MAX_OPS_NAMED = 3

    def finalize(self, run: Run) -> List[Finding]:
        program = getattr(run, "program", None)
        if program is None:
            return []
        may_block = program.may_block()
        out: List[Finding] = []
        for qual, fn in program.functions.items():
            for ev in fn["events"]:
                held = ev.get("held", [])
                if not held or "held" in ev.get("waived", []):
                    continue
                if ev["t"] == "block":
                    recv = ev.get("recv_lock")
                    if recv is not None and recv in held:
                        continue  # waiting on the held condition: sanctioned
                    out.append(
                        Finding(
                            fn["relpath"], ev["line"], ev["col"], self.id,
                            f"{ev['op']} while holding {self._locks(held)} — "
                            "every thread needing the lock waits out this "
                            "blocking call (deadlock/tail-latency factory); "
                            "narrow the critical section, or mark "
                            "`# held-ok: <reason>`",
                        )
                    )
                elif ev["t"] == "call" and ev.get("callee"):
                    ops = []
                    for op, info in sorted(may_block.get(ev["callee"], {}).items()):
                        if info.get("waived"):
                            continue
                        recv = info.get("recv_lock")
                        if recv is not None and recv in held:
                            continue
                        site = info["site"]
                        ops.append(
                            f"{op} at {site[0]}:{site[1]} via "
                            f"{_fmt_chain([qual] + info['chain'])}"
                        )
                    if ops:
                        named = "; ".join(ops[: self._MAX_OPS_NAMED])
                        more = len(ops) - self._MAX_OPS_NAMED
                        if more > 0:
                            named += f" (+{more} more)"
                        out.append(
                            Finding(
                                fn["relpath"], ev["line"], ev["col"], self.id,
                                f"call reaches a blocking operation while "
                                f"holding {self._locks(held)}: {named} — "
                                "narrow the critical section or hoist the "
                                "call out of it, or mark "
                                "`# held-ok: <reason>`",
                            )
                        )
        return out

    @staticmethod
    def _locks(held: List[str]) -> str:
        return ", ".join(f"`{h}`" for h in held)


class GuardDisciplineRule(_ProgramRule):
    id = "guard-discipline"
    waiver = "guard"
    description = (
        "fields declared `# guarded-by: <lock>` read/written without that "
        "lock held (lexically or via every resolved call site)"
    )

    def finalize(self, run: Run) -> List[Finding]:
        program = getattr(run, "program", None)
        if program is None:
            return []
        out: List[Finding] = []
        for p in program.guard_problems:
            out.append(
                Finding(
                    p["relpath"], p["line"], 1, self.id,
                    f"`# guarded-by: {p['name']}` on field `{p['attr']}` "
                    "names no lock declared in this class/module — a typo'd "
                    "guard protects nothing",
                )
            )
        entry_held = program.entry_held()
        for qual, fn in program.functions.items():
            for ev in fn["events"]:
                if ev["t"] != "access" or "guard" in ev.get("waived", []):
                    continue
                g = program.guards.get(ev["guard"])
                if g is None or g.get("lock") is None:
                    continue
                if fn["name"] == "__init__" and fn["cls"] == g["cls"]:
                    continue  # construction happens-before publication
                held = set(ev.get("held", [])) | entry_held.get(qual, set())
                if g["lock"] in held:
                    continue
                out.append(
                    Finding(
                        fn["relpath"], ev["line"], ev["col"], self.id,
                        f"field `{g['attr']}` is `# guarded-by` "
                        f"`{g['lock']}` ({g['relpath']}:{g['line']}) but is "
                        f"{'written' if ev['mode'] == 'write' else 'read'} "
                        f"here without it (in `{qual}`) — hold the lock, "
                        "prove every call site holds it, or mark "
                        "`# guard-ok: <reason>`",
                    )
                )
        return out
