#
# AST port of the direct-memstats rule: HBM accounting goes through the
# admission budgeter (memory.py — capacity resolution, chaos-injected
# budgets, config override order) and the telemetry watermark sampler
# (telemetry.record_device_memory). A direct `Device.memory_stats()` call
# elsewhere bypasses the `hbm_budget_bytes` override and the chaos
# `oom:budget=` injection, so the code under test budgets against a
# DIFFERENT capacity than the admission controller — exactly the
# split-brain the memory-safety plane exists to prevent
# (docs/robustness.md "Memory safety").
#
from __future__ import annotations

import ast

from ..engine import FileContext, RuleBase


class MemStatsRule(RuleBase):
    id = "direct-memstats"
    waiver = "hbm"
    tree_scope = ("spark_rapids_ml_tpu",)
    exempt_files = frozenset({"memory.py", "telemetry.py"})  # budgeter + watermark sampler
    description = "direct Device.memory_stats() outside the admission budgeter"

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "memory_stats"
            ):
                ctx.emit(
                    self,
                    node,
                    "direct memory_stats() in the framework — HBM capacity "
                    "flows through the admission budgeter "
                    "(memory.device_capacity_bytes: honors hbm_budget_bytes + "
                    "chaos budgets) or the telemetry watermark sampler; use "
                    "them or mark `# hbm-ok: <why>`",
                )
