#
# Host-sync-in-hot-path detector: an implicit device->host fetch —
# `float()`/`int()`/`bool()` on a jax value, `.item()`, `np.asarray`,
# `jax.device_get` — inside a loop in the solver layer
# (spark_rapids_ml_tpu/ops/, checkpoint.py) blocks the Python host on the
# device EVERY iteration: ~50 ms per fetch through a remote TPU tunnel
# (measured in the kmeans deferred-shift work, ops/kmeans.py), which is why
# the framework's loops fetch at deliberate, annotated boundaries only
# (deferred convergence checks, checkpoint cadences, out-of-core per-chunk
# accumulation) and carry `# host-fetch-ok: <reason>` there.
#
# "jax value" is tracked per function with a flow-insensitive taint pass:
#   * sources — parameters annotated `jax.Array`, results of jax/jnp/lax
#     calls, results of module-local jit-wrapped functions, blocks yielded
#     by the streaming placement helper (`stream_place_blocks`), and any
#     call fed a tainted argument (a function of device values is assumed
#     to return device values);
#   * sinks that LAUNDER — a fetch call's result is a host value, so
#     `probs = np.asarray(min_d2) * sw` taints nothing downstream;
#   * never tainted — host-metadata reads (`.shape`, `.dtype`, `len()`).
#
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ..engine import FileContext, RuleBase, dotted

_FETCH_BUILTINS = {"float", "int", "bool"}
_NP_FETCHES = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}
# jax calls that RETURN host values (so assignment from them does not taint)
_HOST_RETURNING = {
    "jax.device_get",
    "jax.process_index",
    "jax.process_count",
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
    "jax.local_device_count",
}
_METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "itemsize", "nbytes"}
# framework helpers known to yield/return device-resident values even though
# their dotted names are not jax-rooted (the framework-aware part)
_KNOWN_DEVICE_FUNCS = {"stream_place_blocks"}
_JIT_TAILS = {"jit", "pmap", "vmap"}


def _is_array_annotation(ann: Optional[ast.AST]) -> bool:
    # `jax.Array` (and friends spelled `...Array`) taint; `np.ndarray` is a
    # HOST array and must not
    return ann is not None and "Array" in ast.dump(ann)


def _jax_rooted(name: Optional[str]) -> bool:
    return name is not None and (
        name == "jax" or name.startswith(("jax.", "jnp.", "lax."))
    )


def _assign_targets(target: ast.AST) -> Iterable[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from _assign_targets(el)
    elif isinstance(target, ast.Starred):
        yield from _assign_targets(target.value)


def _iter_scope(body: Iterable[ast.AST]) -> Iterable[ast.AST]:
    """Walk statements without descending into nested function scopes."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class HostSyncRule(RuleBase):
    id = "host-sync"
    waiver = "host-fetch"
    tree_scope = ("spark_rapids_ml_tpu",)
    description = "implicit device->host fetches inside solver-layer loops"
    hot_path_dirs: Tuple[str, ...] = ("spark_rapids_ml_tpu/ops/",)
    hot_path_files: Tuple[str, ...] = ("spark_rapids_ml_tpu/checkpoint.py",)

    def applies(self, ctx: FileContext) -> bool:
        if ctx.target not in self.tree_scope:
            return False
        return ctx.relpath in self.hot_path_files or any(
            ctx.relpath.startswith(d) for d in self.hot_path_dirs
        )

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        # module-local functions whose results live on device: jit-decorated
        # defs and `name = jax.jit(...)`-style wrappers anywhere in the file
        self._device_funcs: Set[str] = set(_KNOWN_DEVICE_FUNCS)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec, ctx):
                        self._device_funcs.add(node.name)
            elif isinstance(node, ast.Assign) and self._is_jit_expr(node.value, ctx):
                for t in node.targets:
                    self._device_funcs.update(_assign_targets(t))

        self._check_scope(tree.body, ctx, params=[])
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                params = list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
                self._check_scope(node.body, ctx, params=params)

    def _is_jit_expr(self, node: ast.AST, ctx: FileContext) -> bool:
        """`@jax.jit`, `@partial(jax.jit, ...)`, `jax.jit(f, ...)`."""
        name = dotted(node, ctx.imports)
        if _jax_rooted(name) and name.split(".")[-1] in _JIT_TAILS:
            return True
        if isinstance(node, ast.Call):
            fname = dotted(node.func, ctx.imports)
            if _jax_rooted(fname) and fname.split(".")[-1] in _JIT_TAILS:
                return True
            if fname is not None and fname.split(".")[-1] == "partial" and node.args:
                return self._is_jit_expr(node.args[0], ctx)
        return False

    def _check_scope(
        self, body: Iterable[ast.AST], ctx: FileContext, params: List[ast.arg]
    ) -> None:
        taints: Set[str] = {
            p.arg for p in params if _is_array_annotation(p.annotation)
        }
        assigns: List[Tuple[List[str], ast.AST]] = []
        for node in _iter_scope(body):
            if isinstance(node, ast.Assign):
                names: List[str] = []
                for t in node.targets:
                    names.extend(_assign_targets(t))
                assigns.append((names, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                names = list(_assign_targets(node.target))
                if _is_array_annotation(node.annotation):
                    taints.update(names)
                assigns.append((names, node.value))
            elif isinstance(node, ast.AugAssign):
                assigns.append((list(_assign_targets(node.target)), node.value))
            elif isinstance(node, ast.NamedExpr):
                assigns.append((list(_assign_targets(node.target)), node.value))
            elif isinstance(node, ast.For):
                assigns.append((list(_assign_targets(node.target)), node.iter))

        for _ in range(12):  # fixpoint over the flow-insensitive assignment set
            grew = False
            for names, value in assigns:
                if names and self._expr_tainted(value, taints, ctx):
                    new = set(names) - taints
                    if new:
                        taints.update(new)
                        grew = True
            if not grew:
                break

        seen: Set[int] = set()  # a call inside nested loops is one finding
        for node in _iter_scope(body):
            if isinstance(node, (ast.For, ast.While)):
                self._check_loop(node, ctx, taints, seen)

    def _expr_tainted(self, expr: ast.AST, taints: Set[str], ctx: FileContext) -> bool:
        """Does this expression carry a device value?"""
        if isinstance(expr, ast.Name):
            return expr.id in taints
        if isinstance(expr, ast.Attribute):
            if expr.attr in _METADATA_ATTRS:
                return False  # host-metadata read, never blocks on the device
            return self._expr_tainted(expr.value, taints, ctx)
        if isinstance(expr, ast.Call):
            name = dotted(expr.func, ctx.imports)
            if name in _HOST_RETURNING:
                return False
            if self._fetch_kind(expr, taints, ctx, require_taint=False) is not None:
                return False  # a fetch's RESULT is a host value (taint laundered)
            if isinstance(expr.func, ast.Name) and expr.func.id == "len":
                return False
            if _jax_rooted(name):
                return True
            if name is not None and name.split(".")[-1] in self._device_funcs:
                return True
            # a call fed device values is assumed to return device values
            # (`centers, _, shift = step(centers, fast)`); method calls also
            # propagate their receiver's taint (`(x + d).astype(t)`)
            parts: List[ast.AST] = list(expr.args) + [k.value for k in expr.keywords]
            if isinstance(expr.func, ast.Attribute):
                parts.append(expr.func.value)
            return any(self._expr_tainted(p, taints, ctx) for p in parts)
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        return any(
            self._expr_tainted(child, taints, ctx)
            for child in ast.iter_child_nodes(expr)
        )

    def _fetch_kind(
        self, node: ast.Call, taints: Set[str], ctx: FileContext, require_taint: bool = True
    ) -> Optional[str]:
        name = dotted(node.func, ctx.imports)
        if name == "jax.device_get":
            return "jax.device_get(...)"
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _FETCH_BUILTINS
            and node.func.id not in ctx.imports
            and len(node.args) == 1
        ):
            if not require_taint or self._expr_tainted(node.args[0], taints, ctx):
                return f"{node.func.id}(...)"
            return None
        if name in _NP_FETCHES and node.args:
            if not require_taint or self._expr_tainted(node.args[0], taints, ctx):
                return f"{name}(...)"
            return None
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            if not require_taint or self._expr_tainted(node.func.value, taints, ctx):
                return ".item()"
            return None
        return None

    def _check_loop(
        self, loop: ast.AST, ctx: FileContext, taints: Set[str], seen: Set[int]
    ) -> None:
        region: List[ast.AST] = list(loop.body) + list(getattr(loop, "orelse", []))
        if isinstance(loop, ast.While):
            region.append(loop.test)  # a while-test fetch syncs every iteration too
        for node in _iter_scope(region):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            kind = self._fetch_kind(node, taints, ctx)
            if kind is None:
                continue
            ctx.emit(
                self,
                node,
                f"implicit device->host fetch (`{kind}` on a jax value) "
                "inside a solver loop — each fetch synchronizes host and "
                "device (~50ms per round-trip through a remote TPU tunnel); "
                "hoist it out of the loop, defer it (see the kmeans "
                "pipelined shift check), or mark the deliberate boundary "
                "`# host-fetch-ok: <reason>`",
            )
