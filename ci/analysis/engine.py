#
# AST lint engine: the framework-aware replacement for ci/lint.py's line
# regexes. One pass per file — explicit utf-8 read, in-memory `compile()`
# syntax check (no __pycache__ litter), tokenize for comments/waivers,
# `ast.parse` for structure — then every rule walks the module with full
# scope/import context. Findings are structured (`file:line:col rule-id
# message`) so the CLI can render text or a machine-readable JSON verdict,
# and a checked-in baseline (ci/analysis/baseline.json) lets a new rule land
# with known findings frozen and ratcheted down (docs/development.md).
#
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# A waiver comment must START with the tag (a mention inside prose — e.g. a
# doc comment quoting "`# hbm-ok` waiver" — is not a waiver) and must carry a
# `: <reason>` suffix to actually suppress; a bare tag is itself a finding
# (rules/hygiene.py `waiver-missing-reason`).
_WAIVER_RE = re.compile(r"^#\s*([a-z][a-z0-9_]*(?:-[a-z0-9_]+)*)-ok\b(:?)\s*(.*)$")

# Paths the gate never analyzes: bytecode caches, generated trees, and
# notebook exports (mechanical .ipynb conversions carry cell magics and
# duplicated output the rules would false-positive on).
_SKIP_DIR_NAMES = {"__pycache__", "generated", "_generated", ".ipynb_checkpoints"}
_SKIP_FILE_SUFFIXES = ("_nb.py", ".nbconvert.py", "_nb_export.py")


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    rule: str
    message: str

    @property
    def key(self) -> str:
        """Baseline ratchet key: line numbers drift with unrelated edits, so
        the baseline counts findings per (file, rule) instead of pinning
        exact positions."""
        return f"{self.path}:{self.rule}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def as_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
        }


def build_imports(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted origin, so rules match CALLS not spellings:
    `import time as t; t.sleep(...)` and `from time import sleep` both
    resolve to `time.sleep`. Relative imports keep their tail (`from ..core
    import config` -> `core.config`) — rules match on suffixes."""
    imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    # `import a.b.c` binds only `a` locally
                    root = a.name.split(".", 1)[0]
                    imports[root] = root
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                origin = f"{mod}.{a.name}" if mod else a.name
                imports[a.asname or a.name] = origin
    return imports


def dotted(node: ast.AST, imports: Optional[Dict[str, str]] = None) -> Optional[str]:
    """Resolve an attribute chain to a dotted path with import aliases
    applied; None when the chain is rooted in something dynamic (a call, a
    subscript)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = node.id
    if imports:
        root = imports.get(root, root)
    parts.append(root)
    return ".".join(reversed(parts))


def node_span(node: ast.AST) -> Tuple[int, int]:
    line = getattr(node, "lineno", 1)
    return line, getattr(node, "end_lineno", None) or line


class FileContext:
    """Everything a rule may ask about the file under analysis."""

    def __init__(self, run: "Run", path: str, relpath: str, target: str, source: str):
        self.run = run
        self.path = path
        self.relpath = relpath
        self.target = target  # top-level tree the file was discovered under
        self.filename = os.path.basename(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.imports: Dict[str, str] = {}
        # lineno -> full comment text (one comment token per line in Python)
        self.comments: Dict[int, str] = {}
        # lineno -> {tag: reason}; reason == "" means the bare (invalid) form
        self.waivers: Dict[int, Dict[str, str]] = {}
        self.findings: List[Finding] = []
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
                    m = _WAIVER_RE.match(tok.string)
                    if m:
                        tag, colon, reason = m.group(1), m.group(2), m.group(3).strip()
                        self.waivers.setdefault(tok.start[0], {})[tag] = (
                            reason if colon else ""
                        )
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # the compile() check reports the syntax error itself

    def waived(self, tag: Optional[str], node: ast.AST) -> bool:
        """A finding is waived when a line its node spans carries
        `# <tag>-ok: <reason>`. For statements WITH a body (While/If/Try/
        FunctionDef) only the header lines count — otherwise a waiver
        written for one call deep inside a loop body would silently waive
        the loop-level finding too. A reason-less waiver does NOT suppress —
        the waiver itself is the finding then."""
        if tag is None:
            return False
        lo, hi = node_span(node)
        body = getattr(node, "body", None)
        if isinstance(body, list) and body and hasattr(body[0], "lineno"):
            hi = max(lo, body[0].lineno - 1)
        for ln in range(lo, hi + 1):
            reason = self.waivers.get(ln, {}).get(tag)
            if reason:
                return True
        return False

    def emit(self, rule: "RuleBase", node: ast.AST, message: str) -> None:
        if self.waived(rule.waiver, node):
            return
        self.findings.append(
            Finding(
                path=self.relpath,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                rule=rule.id,
                message=message,
            )
        )

    def emit_at(self, rule_id: str, line: int, col: int, message: str) -> None:
        self.findings.append(
            Finding(path=self.relpath, line=line, col=col, rule=rule_id, message=message)
        )


class RuleBase:
    """One invariant. `check_module` walks a parsed file (rules own their
    traversal — structural rules need custom context the shared walker can't
    anticipate); `finalize` runs once after every file, for cross-file rules
    (the registries and the program-model concurrency rules).
    docs/development.md documents the catalog + how to add one.

    `file_state`/`restore_state` are the content-hash cache's hooks for
    collector rules: the per-file slice of accumulated state is stored on a
    miss and replayed on a hit, so a cache-skipped file still contributes to
    `finalize`."""

    id: str = ""
    waiver: Optional[str] = None  # waiver tag; comment form `# <tag>-ok: <reason>`
    tree_scope: Tuple[str, ...] = ("spark_rapids_ml_tpu",)
    exempt_files: frozenset = frozenset()
    description: str = ""

    def applies(self, ctx: FileContext) -> bool:
        return ctx.target in self.tree_scope and ctx.filename not in self.exempt_files

    def check_module(self, tree: ast.Module, ctx: FileContext) -> None:
        raise NotImplementedError

    def finalize(self, run: "Run") -> List[Finding]:
        return []

    def file_state(self, relpath: str):
        """JSON-able per-file contribution to cross-file state (None when
        the rule accumulates none)."""
        return None

    def restore_state(self, relpath: str, state) -> None:
        """Replay a cached `file_state` contribution (cache hit path)."""


@dataclass
class RegistrySources:
    """The declared-schema side of the registry rules, injectable so fixture
    tests can run them against synthetic schemas/docs."""

    config_schema_keys: Dict[str, int] = field(default_factory=dict)  # key -> lineno
    config_schema_relpath: str = "spark_rapids_ml_tpu/core.py"
    config_docs_text: str = ""
    config_docs_relpath: str = "docs/configuration.md"
    metric_docs_text: str = ""
    metric_docs_relpath: str = "docs/observability.md"
    # relpaths load() expected but could not read: a moved/renamed schema or
    # doc must FAIL the registry rules, never silently disable them (fixture
    # sources constructed directly leave this empty on purpose)
    missing: List[str] = field(default_factory=list)

    @classmethod
    def load(cls, root: str) -> "RegistrySources":
        src = cls()
        schema_path = os.path.join(root, src.config_schema_relpath)
        if os.path.exists(schema_path):
            with open(schema_path, encoding="utf-8") as f:
                src.config_schema_keys = extract_config_schema(f.read())
        else:
            src.missing.append(src.config_schema_relpath)
        for attr, rel in (
            ("config_docs_text", src.config_docs_relpath),
            ("metric_docs_text", src.metric_docs_relpath),
        ):
            p = os.path.join(root, rel)
            if os.path.exists(p):
                with open(p, encoding="utf-8") as f:
                    setattr(src, attr, f.read())
            else:
                src.missing.append(rel)
        return src


def extract_config_schema(core_source: str) -> Dict[str, int]:
    """String keys (with line numbers) of the module-level `config = {...}`
    literal in core.py — the one declared schema the config-key rule checks
    usages against."""
    keys: Dict[str, int] = {}
    tree = ast.parse(core_source)
    for node in tree.body:
        target = None
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            target = node.target.id
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            target = node.targets[0].id
        if target == "config" and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys[k.value] = k.lineno
    return keys


class Run:
    """One analysis invocation: discover files, run rules, collect findings."""

    def __init__(
        self,
        root: str,
        targets: Sequence[str] = ("spark_rapids_ml_tpu", "benchmark", "tests"),
        rules: Optional[Sequence[RuleBase]] = None,
        sources: Optional[RegistrySources] = None,
        use_cache: bool = True,
    ):
        if rules is None:
            from .rules import default_rules

            rules = default_rules()
        self.root = os.path.abspath(root)
        self.targets = list(targets)
        self.rules = list(rules)
        self.sources = sources if sources is not None else RegistrySources.load(self.root)
        self.use_cache = use_cache
        self.findings: List[Finding] = []
        self.files_scanned = 0
        self.files_cached = 0
        self.skipped: List[str] = []
        self.missing_targets: List[str] = []
        # names metric/config rules could not check statically (f-strings,
        # variables) — surfaced in the verdict so dynamic names are a visible
        # gap, not a silent one
        self.dynamic_names: List[str] = []
        # pass 1 of the two-pass engine: per-file program facts, assembled
        # into the whole-program model the interprocedural rules finalize on
        self._facts: Dict[str, Optional[Dict[str, Any]]] = {}
        self.program: Optional[Any] = None

    def discover(self) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for target in self.targets:
            base = os.path.join(self.root, target)
            if os.path.isfile(base) and base.endswith(".py"):
                out.append((target, base))
                continue
            if not os.path.isdir(base):
                # a typo'd/renamed target must FAIL the gate, not produce a
                # green zero-file pass
                self.missing_targets.append(target)
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIR_NAMES and d != "notebooks"
                )
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    if fn.endswith(_SKIP_FILE_SUFFIXES):
                        self.skipped.append(
                            os.path.relpath(os.path.join(dirpath, fn), self.root)
                        )
                        continue
                    out.append((target, os.path.join(dirpath, fn)))
        return out

    def analyze_file(
        self, target: str, path: str, raw: Optional[bytes] = None
    ) -> List[Finding]:
        relpath = os.path.relpath(path, self.root).replace(os.sep, "/")
        if raw is None:
            with open(path, "rb") as f:
                raw = f.read()
        try:
            # explicit: no locale-dependent reads in CI; -sig strips a BOM,
            # which CPython accepts but compile(str) would reject as U+FEFF
            source = raw.decode("utf-8-sig")
        except UnicodeDecodeError as e:
            return [Finding(relpath, 1, 1, "encoding", f"not valid utf-8: {e}")]
        return self.analyze_one(path, relpath, source)

    def analyze_one(self, path: str, relpath: str, source: str) -> List[Finding]:
        """One file through the whole pipeline — compile gate, parse, rule
        dispatch, text-only fallback. Shared by the tree scan and the
        fixture entry point so they cannot drift."""
        # rules scope on the TOP-LEVEL tree, not the CLI spelling: a sub-path
        # target (`python -m ci.analysis spark_rapids_ml_tpu/ops`) must run
        # the same rules as the full tree, never a silently rule-less pass
        target = relpath.split("/", 1)[0]
        ctx = FileContext(self, path, relpath, target, source)
        try:
            # hermetic syntax gate: in-memory compile, no __pycache__ litter
            compile(source, path, "exec", dont_inherit=True)
            ctx.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            ctx.emit_at("syntax-error", e.lineno or 1, (e.offset or 0) + 1, e.msg or "syntax error")
        except ValueError as e:
            # e.g. a NUL byte: valid utf-8, but compile() rejects it — a
            # per-file finding, never a gate crash (py_compile parity)
            ctx.emit_at("syntax-error", 1, 1, str(e) or "uncompilable source")
        if ctx.tree is not None:
            ctx.imports = build_imports(ctx.tree)
            for rule in self.rules:
                if rule.applies(ctx):
                    rule.check_module(ctx.tree, ctx)
        else:
            # text-level hygiene still runs on unparsable files
            for rule in self.rules:
                if getattr(rule, "text_only", False) and rule.applies(ctx):
                    rule.check_module(None, ctx)  # type: ignore[arg-type]
        # pass-1 facts for the whole-program model (framework tree only —
        # the concurrency rules scope there)
        if target == "spark_rapids_ml_tpu":
            from . import program as program_mod

            self._facts[relpath] = (
                program_mod.extract_facts(ctx) if ctx.tree is not None else None
            )
        return ctx.findings

    def analyze(self) -> List[Finding]:
        from . import cache as cache_mod
        from . import program as program_mod

        cache = cache_mod.Cache.load(self.root) if self.use_cache else None
        for target, path in self.discover():
            relpath = os.path.relpath(path, self.root).replace(os.sep, "/")
            # ONE read per file: the cache key is the hash of the exact
            # bytes analyzed below, so a mid-run edit can never bind its new
            # hash to stale results
            raw: Optional[bytes] = None
            content_hash: Optional[str] = None
            if cache is not None:
                try:
                    with open(path, "rb") as f:
                        raw = f.read()
                    content_hash = cache_mod.hash_bytes(raw)
                except OSError:
                    raw = None
                if content_hash is not None:
                    entry = cache.lookup(relpath, content_hash)
                    if entry is not None:
                        self.findings.extend(Finding(**f) for f in entry["findings"])
                        if target == "spark_rapids_ml_tpu":
                            self._facts[relpath] = entry.get("facts")
                        for rule in self.rules:
                            state = entry.get("state", {}).get(rule.id)
                            if state is not None:
                                rule.restore_state(relpath, state)
                        self.dynamic_names.extend(entry.get("dynamic", []))
                        self.files_scanned += 1
                        self.files_cached += 1
                        continue
            file_findings = self.analyze_file(target, path, raw=raw)
            self.findings.extend(file_findings)
            self.files_scanned += 1
            if cache is not None and content_hash is not None:
                state = {}
                for rule in self.rules:
                    s = rule.file_state(relpath)
                    if s is not None:
                        state[rule.id] = s
                cache.store(
                    relpath,
                    content_hash,
                    [f.as_dict() for f in file_findings],
                    self._facts.get(relpath),
                    state,
                    [d for d in self.dynamic_names if d.startswith(relpath + ":")],
                )
        self.program = program_mod.build_program(self._facts)
        for rule in self.rules:
            self.findings.extend(rule.finalize(self))
        self.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        if cache is not None:
            cache.save()
        return self.findings


def analyze_source(
    source: str,
    relpath: str = "spark_rapids_ml_tpu/snippet.py",
    rules: Optional[Sequence[RuleBase]] = None,
    sources: Optional[RegistrySources] = None,
    root: str = "/",
) -> List[Finding]:
    """Fixture-test entry point: run rules over one in-memory snippet as if
    it lived at `relpath` under the repo root — the exact same pipeline as
    the tree scan (analyze_one), so fixtures cannot drift from production
    behavior."""
    return analyze_sources({relpath: source}, rules=rules, sources=sources, root=root)


def analyze_sources(
    files: Dict[str, str],
    rules: Optional[Sequence[RuleBase]] = None,
    sources: Optional[RegistrySources] = None,
    root: str = "/",
) -> List[Finding]:
    """Multi-file fixture entry: the cross-file pipeline (per-file rules,
    pass-1 facts, whole-program assembly, finalize) over in-memory snippets —
    how the lock-order cycle tests seed an inversion SPLIT across files that
    no per-file analysis could see."""
    from . import program as program_mod

    run = Run(
        root, targets=(), rules=rules, sources=sources or RegistrySources(),
        use_cache=False,
    )
    findings: List[Finding] = []
    for relpath, source in files.items():
        findings.extend(run.analyze_one(relpath, relpath, source))
    run.program = program_mod.build_program(run._facts)
    for rule in run.rules:
        findings.extend(rule.finalize(run))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
