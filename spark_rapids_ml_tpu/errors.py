#
# Typed exception hierarchy for the fault-tolerant control plane.
#
# The reference gets crash recovery for free from Spark: a dead barrier task
# fails the stage and lineage-based re-execution retries it (Zaharia et al.,
# NSDI 2012). The TPU-native rendezvous has no such supervisor, so failures
# must become PROMPT, TYPED errors that the fit driver (core.retryable_stage)
# can classify as transient (retry the stage) or permanent (propagate):
#
#   SrmlError
#   ├── RendezvousTimeoutError   transient — a round's deadline elapsed with
#   │                            ranks missing; symmetric (every waiting rank
#   │                            raises it), so a coordinated retry is safe
#   ├── RankFailedError          permanent — a peer PUBLISHED its failure
#   │                            (abort sentinel) or stopped heartbeating;
#   │                            its work is gone, a plain retry cannot help
#   ├── SolverDivergedError      permanent — a solver produced non-finite
#   │                            state; carries the last-good iterate so
#   │                            callers can resume/diagnose
#   ├── IngestValidationError    permanent — NaN/Inf found in an input column
#   │                            (config["validate_ingest"]); names the column
#   ├── MeshTopologyError        permanent — a requested mesh/sub-mesh shape
#   │                            cannot be built over the visible devices
#   │                            (worker count does not divide the pool, or a
#   │                            topology axis product disagrees with it)
#   ├── HbmBudgetError           permanent — the fit's working set cannot fit
#   │                            device memory even on the out-of-core
#   │                            streaming path (or a real backend OOM was
#   │                            caught and the streaming retry is impossible
#   │                            or also failed); carries the estimate, the
#   │                            capacity, and the largest term so the fix
#   │                            points at WHAT doesn't fit
#   ├── PreemptedError           internal — the multi-tenant scheduler asked
#   │                            a running fit to yield at its next solver
#   │                            segment boundary; TRANSIENT from the
#   │                            tenant's view (the job requeues and resumes
#   │                            from its checkpoint) but never retried in
#   │                            place, so `is_transient` stays False —
#   │                            the scheduler, not `retryable_stage`, owns
#   │                            the resume
#   ├── NumericsError            permanent — the opt-in runtime numerics
#   │                            sanitizer (utils/numcheck.py,
#   │                            SRML_NUMCHECK=1) found NaN/Inf at a solver
#   │                            boundary that already host-fetches; carries
#   │                            solver/iteration/stage + which value
#   │                            tripped, so the break is named at the
#   │                            boundary it crossed, not iterations later.
#   │                            Distinguish from SolverDivergedError: that
#   │                            is the always-on convergence guard on
#   │                            scalars the solver fetches anyway; this is
#   │                            the opt-in sweep of everything else
#   ├── SchedulerSaturatedError  permanent — a submitted job's SMALLEST
#   │                            possible footprint (the streaming floor, or
#   │                            the resident estimate when the estimator
#   │                            has no out-of-core path) exceeds the whole
#   │                            HBM budget: no amount of queueing or
#   │                            preemption can ever place it. Mirrors
#   │                            `HbmBudgetError`: carries the estimate, the
#   │                            budget, and the largest term so the refusal
#   │                            names WHAT doesn't fit
#   ├── RequestTimeoutError      permanent — a scoring request's server-side
#   │                            deadline (`submit(deadline_ms=)`) elapsed
#   │                            before dispatch; the request never touched
#   │                            the device. Carries the deadline, how long
#   │                            it waited, and the queue state at failure
#   ├── ServeOverloadError       permanent (for THIS request) — serving
#   │                            admission refused the request: queue bound
#   │                            hit, predicted queue wait exceeds the
#   │                            deadline, or the tenant's backpressure
#   │                            ladder is throttling/shedding. Carries the
#   │                            evidence (queue depth/rows, predicted wait,
#   │                            deadline, ladder level) so the refusal
#   │                            names WHY; callers retry with backoff
#   └── ServingStoppedError      permanent — the scoring engine stopped
#                                before a queued request dispatched; carries
#                                the model name and the request's queue
#                                position at shutdown
#
# Multiple inheritance keeps old call sites working: RendezvousTimeoutError
# IS-A TimeoutError (FileRendezvous raised bare TimeoutError before),
# IngestValidationError IS-A ValueError, HbmBudgetError IS-A MemoryError.
#
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

__all__ = [
    "SrmlError",
    "RendezvousTimeoutError",
    "RankFailedError",
    "SolverDivergedError",
    "IngestValidationError",
    "MeshTopologyError",
    "HbmBudgetError",
    "NumericsError",
    "PreemptedError",
    "SchedulerSaturatedError",
    "RequestTimeoutError",
    "ServeOverloadError",
    "ServingStoppedError",
    "is_transient",
]


class SrmlError(Exception):
    """Base class for every framework-raised error.

    Construction notifies the flight recorder (diagnostics.on_srml_error):
    the error lands in the ring, the last-K ring events are attached as
    ``self.flightrec_tail``, and — when a dump dir is configured — the whole
    ring is dumped to ``flightrec_rank_<r>.jsonl`` for post-mortem assembly.
    Subclasses must therefore set their diagnostic attributes (failed_rank,
    round_index, ...) BEFORE calling ``super().__init__`` so the recorded
    event carries them. The hook never raises: diagnostics failures must not
    mask the error being constructed."""

    flightrec_tail: Any = None

    def __init__(self, *args: Any) -> None:
        super().__init__(*args)
        try:
            from . import diagnostics

            diagnostics.on_srml_error(self)
        except Exception:  # pragma: no cover - never mask the real error
            pass


class RendezvousTimeoutError(SrmlError, TimeoutError):
    """A control-plane round's deadline elapsed with ranks still missing.

    TRANSIENT: the deadline fires symmetrically on every rank still waiting,
    so all survivors unwind to the fit driver together and a coordinated
    retry (new rendezvous epoch) is safe. Distinguish from `RankFailedError`,
    where a peer is KNOWN dead."""

    def __init__(
        self,
        message: str,
        *,
        round_index: Optional[int] = None,
        missing_ranks: Optional[Sequence[int]] = None,
        timeout_s: Optional[float] = None,
    ):
        # attributes BEFORE super().__init__: the flight-recorder hook fires
        # inside it and records whatever diagnostic fields are already set
        self.round_index = round_index
        self.missing_ranks = list(missing_ranks) if missing_ranks is not None else None
        self.timeout_s = timeout_s
        super().__init__(message)


class RankFailedError(SrmlError, RuntimeError):
    """A peer rank failed mid-fit: it published an ``ABORT:<rank>:<reason>``
    sentinel through the rendezvous, or its heartbeat went stale (killed
    process). On a reform-capable rendezvous, `core.recoverable_stage`
    absorbs this by opening a recovery epoch (survivor re-meshing, bounded
    by ``config["recovery_max_rank_losses"]``); when that budget is
    exhausted — or the substrate cannot reform — the error propagates with
    ``recovery_exhausted``/``recovery_generations`` stamped so callers and
    post-mortems can tell "never tried" from "tried and ran out".
    PERMANENT once it propagates: an external supervisor (not an in-process
    retry) must relaunch the rank."""

    # stamped by core.recoverable_stage when it re-raises after recovery
    # epochs were attempted: how many membership reforms this fit survived
    # before the budget ran out (0 = recovery was never opened)
    recovery_exhausted: bool = False
    recovery_generations: int = 0

    def __init__(
        self,
        failed_rank: int,
        reason: str = "",
        *,
        round_index: Optional[int] = None,
    ):
        self.failed_rank = int(failed_rank)
        self.reason = reason
        self.round_index = round_index
        where = f" at round {round_index}" if round_index is not None else ""
        super().__init__(
            f"rank {failed_rank} failed{where}: {reason or 'no reason published'}"
        )


class SolverDivergedError(SrmlError, ArithmeticError):
    """An iterative solver produced non-finite state (NaN/Inf objective,
    shift, or coefficients). Carries the last iterate known finite and the
    iteration at which divergence was detected, so callers can warm-restart
    or report precisely where the numerics broke."""

    def __init__(
        self,
        solver: str,
        iteration: int,
        *,
        last_good: Optional[Dict[str, Any]] = None,
        detail: str = "",
    ):
        self.solver = solver
        self.iteration = int(iteration)
        self.last_good: Dict[str, Any] = dict(last_good) if last_good else {}
        msg = f"{solver} diverged at iteration {self.iteration}"
        if detail:
            msg += f": {detail}"
        if self.last_good:
            msg += f" (last-good iterate keys: {sorted(self.last_good)})"
        super().__init__(msg)


class IngestValidationError(SrmlError, ValueError):
    """``config["validate_ingest"]`` found a non-finite value in an input
    column. Names the column (and the first offending row) so the fix points
    at the data, not at a NaN surfacing iterations later inside a solver."""

    def __init__(self, column: str, row: Optional[int] = None, kind: str = "non-finite"):
        self.column = column
        self.row = row
        at = f" (first at row {row})" if row is not None else ""
        super().__init__(
            f"input column {column!r} contains {kind} values{at}; "
            "clean the data or disable config['validate_ingest']"
        )


class MeshTopologyError(SrmlError, ValueError):
    """A requested mesh shape cannot be built over the visible devices: the
    worker count does not divide (or exceeds) the device pool, a topology's
    axis product disagrees with the pool size, or a sub-mesh carve asks for
    more chips than the parent mesh holds. PERMANENT — a config error, not a
    runtime fault. Carries both sides of the mismatch so the message names
    the requested shape AND the pool it was checked against (before this
    error, an uneven split surfaced as an opaque numpy reshape failure)."""

    def __init__(
        self,
        message: str,
        *,
        requested: Optional[int] = None,
        available: Optional[int] = None,
        topology: Optional[Dict[str, int]] = None,
    ):
        # attributes BEFORE super().__init__ (flight-recorder contract above)
        self.requested = None if requested is None else int(requested)
        self.available = None if available is None else int(available)
        self.topology: Dict[str, int] = dict(topology) if topology else {}
        parts = [message]
        if requested is not None and available is not None:
            parts.append(
                f"(requested {self.requested} against {self.available} "
                "visible devices)"
            )
        if self.topology:
            shape = " x ".join(f"{k}={v}" for k, v in self.topology.items())
            parts.append(f"[topology: {shape}]")
        super().__init__(" ".join(parts))


class HbmBudgetError(SrmlError, MemoryError):
    """A fit's working set does not fit device memory — decided either by the
    PREFLIGHT HBM budgeter (`spark_rapids_ml_tpu.memory`: even the streaming
    working set of double-buffered chunks + solver workspace exceeds the
    per-device budget), or by a REAL backend allocation failure caught at
    placement/solve when the one-shot streaming retry is impossible or also
    failed. PERMANENT: retrying the same fit on the same devices cannot help —
    shrink the data/model, raise ``config["hbm_budget_bytes"]``, or add chips.

    Carries the per-device byte accounting so the message (and post-mortems)
    name WHAT doesn't fit: ``estimate_bytes`` (total working set),
    ``capacity_bytes`` (per-device budget it was checked against),
    ``largest_term`` / ``largest_term_bytes`` (the dominant line item, e.g.
    ``placement.X`` or ``workspace.gram``), and the full ``terms`` dict."""

    def __init__(
        self,
        message: str,
        *,
        estimate_bytes: Optional[int] = None,
        capacity_bytes: Optional[int] = None,
        largest_term: Optional[str] = None,
        largest_term_bytes: Optional[int] = None,
        terms: Optional[Dict[str, int]] = None,
    ):
        # attributes BEFORE super().__init__: the flight-recorder hook fires
        # inside it and records whatever diagnostic fields are already set
        self.estimate_bytes = None if estimate_bytes is None else int(estimate_bytes)
        self.capacity_bytes = None if capacity_bytes is None else int(capacity_bytes)
        self.largest_term = largest_term
        self.largest_term_bytes = (
            None if largest_term_bytes is None else int(largest_term_bytes)
        )
        self.terms: Dict[str, int] = dict(terms) if terms else {}
        parts = [message]
        if estimate_bytes is not None and capacity_bytes is not None:
            parts.append(
                f"(estimated {self.estimate_bytes} bytes/device against a "
                f"{self.capacity_bytes}-byte budget)"
            )
        if largest_term is not None:
            lt = (
                f"largest term: {largest_term}"
                if largest_term_bytes is None
                else f"largest term: {largest_term} = {self.largest_term_bytes} bytes"
            )
            parts.append(f"[{lt}]")
        super().__init__(" ".join(parts))


class NumericsError(SrmlError, ArithmeticError):
    """The runtime numerics sanitizer (``spark_rapids_ml_tpu.utils.numcheck``,
    opt-in via ``SRML_NUMCHECK=1``) found a non-finite value at a solver
    boundary that already host-fetches — a k-means cadence fetch, a
    ``run_segmented_while`` segment boundary, a streaming chunk boundary, or
    the serving response assembly. PERMANENT: like `SolverDivergedError`, a
    retry re-runs the same arithmetic.

    Carries ``stage`` (the boundary's name, e.g. ``kmeans.iterate``),
    ``solver``, ``iteration``, ``value_name`` (which checked value tripped),
    and ``detail`` (NaN/Inf counts) so the report points at the exact
    boundary the non-finite value crossed."""

    def __init__(
        self,
        stage: str,
        *,
        solver: str = "",
        iteration: Optional[int] = None,
        value_name: str = "",
        detail: str = "",
    ):
        # attributes BEFORE super().__init__: the flight-recorder hook fires
        # inside it and records whatever diagnostic fields are already set
        self.stage = stage
        self.solver = solver
        self.iteration = None if iteration is None else int(iteration)
        self.value_name = value_name
        self.detail = detail
        parts = [f"non-finite value at numerics boundary {stage!r}"]
        if solver:
            at = f" iteration {self.iteration}" if self.iteration is not None else ""
            parts.append(f"(solver {solver}{at})")
        if value_name:
            parts.append(f"in {value_name!r}")
        if detail:
            parts.append(f"— {detail}")
        super().__init__(" ".join(parts))


class PreemptedError(SrmlError):
    """The multi-tenant fit scheduler (`spark_rapids_ml_tpu/scheduler/`,
    docs/scheduling.md) asked this fit to yield: a higher-priority job needs
    its HBM reservation. Raised COOPERATIVELY — only at a solver segment
    boundary (``config["checkpoint_every_iters"]``), immediately AFTER the
    boundary's `SolverCheckpoint` landed in the job's store — so the fit
    unwinds with zero lost work and a later re-admission resumes
    bit-identically on the same mesh.

    Internal and transient FROM THE TENANT'S VIEW (the job requeues; its
    future still resolves), but deliberately NOT `is_transient`: an in-place
    `retryable_stage` retry would re-enter the solve while the scheduler is
    trying to free its reservation. The scheduler's job runner is the one
    sanctioned catcher."""

    def __init__(
        self,
        job_id: int,
        *,
        solver: str = "",
        iteration: int = 0,
        reason: str = "",
    ):
        # attributes BEFORE super().__init__: the flight-recorder hook fires
        # inside it and records whatever diagnostic fields are already set
        self.job_id = int(job_id)
        self.solver = solver
        self.iteration = int(iteration)
        self.reason = reason
        at = f" at {solver} iteration {iteration}" if solver else ""
        super().__init__(
            f"job {job_id} preempted{at}: "
            f"{reason or 'higher-priority job needs the reservation'}"
        )


class SchedulerSaturatedError(SrmlError, MemoryError):
    """A job submitted to the multi-tenant fit scheduler can NEVER be placed:
    its smallest possible working set — the streaming floor for estimators
    with an out-of-core path, the resident estimate otherwise — exceeds the
    whole per-device budget even with every other job drained. PERMANENT,
    refused at `FitScheduler.submit` so the tenant learns immediately
    instead of queueing forever. Mirrors `HbmBudgetError`'s accounting:
    ``estimate_bytes`` / ``budget_bytes`` / ``largest_term`` /
    ``largest_term_bytes`` / ``terms`` name WHAT doesn't fit
    (docs/scheduling.md)."""

    def __init__(
        self,
        message: str,
        *,
        tenant: Optional[str] = None,
        estimate_bytes: Optional[int] = None,
        budget_bytes: Optional[int] = None,
        largest_term: Optional[str] = None,
        largest_term_bytes: Optional[int] = None,
        terms: Optional[Dict[str, int]] = None,
    ):
        # attributes BEFORE super().__init__ (flight-recorder contract above)
        self.tenant = tenant
        self.estimate_bytes = None if estimate_bytes is None else int(estimate_bytes)
        self.budget_bytes = None if budget_bytes is None else int(budget_bytes)
        self.largest_term = largest_term
        self.largest_term_bytes = (
            None if largest_term_bytes is None else int(largest_term_bytes)
        )
        self.terms: Dict[str, int] = dict(terms) if terms else {}
        parts = [message]
        if estimate_bytes is not None and budget_bytes is not None:
            parts.append(
                f"(minimal working set {self.estimate_bytes} bytes/device "
                f"against a {self.budget_bytes}-byte budget)"
            )
        if largest_term is not None:
            parts.append(
                f"[largest term: {largest_term}"
                + (
                    f" = {self.largest_term_bytes} bytes]"
                    if largest_term_bytes is not None
                    else "]"
                )
            )
        super().__init__(" ".join(parts))


class RequestTimeoutError(SrmlError, TimeoutError):
    """A scoring request's server-side deadline elapsed before dispatch
    (``ScoringEngine.submit(deadline_ms=)``, default
    ``config["serve_default_deadline_ms"]``; monotonic-clock only,
    docs/serving.md "Overload & backpressure").

    The request NEVER touched the device: expired requests are dropped at
    the head of the queue or filtered out of a coalesced group before
    dispatch, so a caller whose client already gave up does not burn device
    time. PERMANENT for this request — resubmit with a larger deadline or
    at lower load. Distinguish from the bare ``TimeoutError`` that
    ``ScoreFuture.result(timeout)`` raises: that is the CLIENT giving up
    while the request may still dispatch; this is the SERVER refusing to
    dispatch stale work."""

    def __init__(
        self,
        message: str,
        *,
        model: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        waited_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        queue_rows: Optional[int] = None,
    ):
        # attributes BEFORE super().__init__: the flight-recorder hook fires
        # inside it and records whatever diagnostic fields are already set
        self.model = model
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self.waited_ms = None if waited_ms is None else float(waited_ms)
        self.queue_depth = None if queue_depth is None else int(queue_depth)
        self.queue_rows = None if queue_rows is None else int(queue_rows)
        parts = [message]
        if deadline_ms is not None:
            w = (
                f" after waiting {self.waited_ms:.1f}ms"
                if waited_ms is not None
                else ""
            )
            parts.append(f"(deadline {self.deadline_ms:.1f}ms elapsed{w})")
        if queue_depth is not None:
            parts.append(
                f"[queue: {self.queue_depth} requests"
                + (
                    f", {self.queue_rows} rows]"
                    if queue_rows is not None
                    else "]"
                )
            )
        super().__init__(" ".join(parts))


class ServeOverloadError(SrmlError, RuntimeError):
    """Serving admission refused this request (docs/serving.md "Overload &
    backpressure"): the bounded queue is full
    (``config["serve_max_queue_rows"]``), the live windowed queue-wait p99
    predicts the deadline cannot be met, or the tenant's backpressure
    ladder is throttling (token bucket empty) or shedding (sustained SLO
    burn). PERMANENT for this request, by design cheap and synchronous at
    ``submit()`` — the closed loop's refusal, raised BEFORE any queueing so
    callers can back off while the evidence (queue depth, predicted wait,
    deadline, ladder level) names why."""

    def __init__(
        self,
        message: str,
        *,
        model: Optional[str] = None,
        tenant: Optional[str] = None,
        level: Optional[str] = None,
        queue_depth: Optional[int] = None,
        queue_rows: Optional[int] = None,
        predicted_wait_ms: Optional[float] = None,
        deadline_ms: Optional[float] = None,
    ):
        # attributes BEFORE super().__init__ (flight-recorder contract above)
        self.model = model
        self.tenant = tenant
        self.level = level
        self.queue_depth = None if queue_depth is None else int(queue_depth)
        self.queue_rows = None if queue_rows is None else int(queue_rows)
        self.predicted_wait_ms = (
            None if predicted_wait_ms is None else float(predicted_wait_ms)
        )
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        parts = [message]
        if predicted_wait_ms is not None and deadline_ms is not None:
            parts.append(
                f"(predicted wait {self.predicted_wait_ms:.1f}ms against a "
                f"{self.deadline_ms:.1f}ms deadline)"
            )
        if queue_depth is not None or queue_rows is not None:
            parts.append(
                f"[queue: {self.queue_depth or 0} requests, "
                f"{self.queue_rows or 0} rows]"
            )
        if level is not None:
            parts.append(f"[tenant {tenant!r} at ladder level {level!r}]")
        super().__init__(" ".join(parts))


class ServingStoppedError(SrmlError, RuntimeError):
    """The scoring engine stopped before this queued request dispatched
    (``ScoringEngine.stop()`` drain deadline elapsed, or the engine was
    never going to run it). Carries the model name and the request's
    position in the queue at shutdown, so a caller distinguishing "my
    request was slow" from "the service went away under me" has the
    evidence in the exception, not in a log."""

    def __init__(self, model: str, *, queue_position: Optional[int] = None):
        # attributes BEFORE super().__init__ (flight-recorder contract above)
        self.model = model
        self.queue_position = (
            None if queue_position is None else int(queue_position)
        )
        at = (
            f" (queue position {self.queue_position})"
            if queue_position is not None
            else ""
        )
        super().__init__(
            f"scoring engine stopped before request for model {model!r} "
            f"dispatched{at}"
        )


def is_transient(exc: BaseException) -> bool:
    """Whether the fit driver may retry the stage after this error.

    Transient today: rendezvous round timeouts (symmetric — every rank
    unwinds together) and the distributed-init race (two fits standing up
    `jax.distributed` concurrently; the loser sees an 'already initialized'
    RuntimeError and succeeds on retry). `RankFailedError`,
    `SolverDivergedError`, and `PreemptedError` (the scheduler owns that
    resume, not the in-place retry loop) are deliberately NOT transient."""
    if isinstance(exc, RendezvousTimeoutError):
        return True
    if isinstance(exc, RuntimeError) and not isinstance(exc, SrmlError):
        # ONLY the already-initialized loser race — a broader 'initialize'
        # match would retry deterministic config errors for minutes
        msg = str(exc).lower()
        if "distributed" in msg and "already initialized" in msg:
            return True
    return False
