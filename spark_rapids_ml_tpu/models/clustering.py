#
# Clustering algorithms: KMeans (DBSCAN lands in this module too — reference
# clustering.py holds both).
#
# API-parity target: reference clustering.py:67-499 (`KMeans`/`KMeansModel`),
# drop-in for `pyspark.ml.clustering.KMeans`. Distributed strategy identical in
# math (row data-parallel Lloyd with center allreduce, SURVEY.md §2.2) but as
# one jitted while_loop program instead of per-iteration cuML MG calls.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import FitInputs, _TpuEstimator, _TpuModel, _TpuModelWithColumns, pred
from ..data import ExtractedData
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasIDCol,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasMaxIter,
    HasWeightCol,
    Param,
    TypeConverters,
)


class _KMeansParams(
    HasFeaturesCol, HasFeaturesCols, HasPredictionCol, HasSeed, HasTol, HasMaxIter, HasWeightCol
):
    k = Param("k", "the number of clusters to create", TypeConverters.toInt)
    initMode = Param(
        "initMode", "the initialization algorithm: 'k-means||' or 'random'", TypeConverters.toString
    )
    initSteps = Param("initSteps", "the number of steps for k-means|| initialization", TypeConverters.toInt)
    distanceMeasure = Param("distanceMeasure", "the distance measure (euclidean only)", TypeConverters.toString)

    def getK(self) -> int:
        return self.getOrDefault("k")

    def getInitMode(self) -> str:
        return self.getOrDefault("initMode")

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # mirrors reference clustering.py param mapping (Spark -> cuml kwargs)
        return {
            "k": "n_clusters",
            "maxIter": "max_iter",
            "tol": "tol",
            "seed": "random_state",
            "initMode": "init",
            "initSteps": "",  # accepted, ignored (cuML has no analog; reference does the same)
            "distanceMeasure": None,  # only 'euclidean'; validated in _set_params
            "weightCol": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        return {"tol": lambda v: 1e-16 if v == 0 else v}  # reference clustering.py:96-108 tol=0 remap

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {
            "n_clusters": 8,
            "max_iter": 300,
            "tol": 1e-4,
            "random_state": 1,
            "init": "scalable-k-means++",
            "max_samples_per_batch": 32768,
            "oversampling_factor": 2.0,
            "verbose": False,
            # "fast" = one-pass bf16 in-loop matmuls (f32 accumulate); the
            # reported inertia is always re-evaluated at high precision.
            # Measured at the protocol shape: 1.6x per iteration, true
            # inertia agrees to ~1e-5 (ops/kmeans.py _mm). "high" restores
            # the 3-pass-bf16 in-loop matmuls.
            "distance_precision": "fast",
            # per-estimator override of config["solver_precision"]; "bf16"
            # forces the fast in-loop path on BOTH the resident and the
            # streaming fit (streaming otherwise runs full precision)
            "solver_precision": None,
        }


class KMeans(_KMeansParams, _TpuEstimator):
    """KMeans estimator, drop-in for ``pyspark.ml.clustering.KMeans``.

    Fit is a single XLA program: `lax.while_loop` of Lloyd iterations over the
    row-sharded mesh, each iteration scanning row tiles of
    ``max_samples_per_batch`` rows (HBM-bounded) and psum-reducing (k,d) center
    sums — the TPU-native equivalent of `KMeansMG.fit` (reference
    clustering.py:339-384).
    """

    # Lloyd's argmin assignment tolerates the 3-pass MXU mode; the center-update
    # reductions are plain f32 sums — see dtype_scope (parallel/mesh.py) policy.
    _matmul_precision = "BF16_BF16_F32_X3"

    # the Lloyd loop is one pure SPMD program; the only host-side state — the
    # init centers — is computed from a rendezvous-gathered row sample below
    _supports_multiprocess = True
    # per-chunk assignment + center accumulation: an over-HBM dataset demotes
    # to ops/streaming.kmeans_fit_streaming (same host loop, same checkpoints)
    _supports_streaming_fit = True

    def _solver_workspace_terms(
        self, rows_per_device: int, n_cols: int, params: Dict[str, Any], itemsize: int
    ) -> Dict[str, int]:
        # per-device tile buffers of the assignment scan: the [b, k] distance
        # + one-hot blocks for batch_rows-row tiles, plus the (k, d) centers
        # and sums (replicated), plus the PREDICT-side assignment tile — the
        # transform path row-tiles through the shared distance core at
        # config["distance_tile_rows"] rows (ops/distance.argmin_assign), so
        # an admission-approved fit cannot OOM at predict; its [tile, k]
        # block is budgeted here like the fit-side tiles
        from ..ops.distance import tile_rows

        k = int(params.get("n_clusters", 8))
        b = min(int(params.get("max_samples_per_batch", 32768)), max(1, rows_per_device))
        predict_rows = min(tile_rows(), max(1, rows_per_device))
        return {
            "tile_buffers": 2 * b * k * itemsize,
            "centers": 2 * k * n_cols * itemsize,
            "predict_tile": predict_rows * k * itemsize,
        }

    def _solver_flop_estimate(self, n_rows: int, n_cols: int) -> Optional[float]:
        # Lloyd roofline model (ops_plane/efficiency.py): per iteration the
        # x·cᵀ term of the ‖x−c‖² expansion (2·n·k·d) plus the one-hot
        # center accumulation (≤ 2·n·k·d). maxIter bounds iterations from
        # above, so the MFU derived from this is an upper bound.
        k = int(self._solver_params.get("n_clusters", 8))
        iters = int(self._solver_params.get("max_iter", 300))
        return 4.0 * n_rows * k * n_cols * iters

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=2, initMode="k-means||", initSteps=2, maxIter=20, tol=1e-4, seed=1,
                         distanceMeasure="euclidean")
        self._set_params(**kwargs)

    def _set_params(self, **kwargs):
        if "distanceMeasure" in kwargs and kwargs["distanceMeasure"] != "euclidean":
            raise ValueError("Only distanceMeasure='euclidean' is supported")
        kwargs.pop("distanceMeasure", None)
        return super()._set_params(**kwargs)

    def setK(self, value: int) -> "KMeans":
        return self._set_params(k=value)

    def setMaxIter(self, value: int) -> "KMeans":
        return self._set_params(maxIter=value)

    def setTol(self, value: float) -> "KMeans":
        return self._set_params(tol=value)

    def setSeed(self, value: int) -> "KMeans":
        return self._set_params(seed=value)

    def setInitMode(self, value: str) -> "KMeans":
        return self._set_params(initMode=value)

    def setFeaturesCol(self, value) -> "KMeans":
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str) -> "KMeans":
        return self._set_params(predictionCol=value)

    def setWeightCol(self, value: str) -> "KMeans":
        return self._set_params(weightCol=value)

    def _resolve_warm_start(self, source: Any) -> Dict[str, Any]:
        """Warm-start payload for `fit(..., warm_start_from=...)`: a fitted
        `KMeansModel`'s centers, or a `SolverCheckpoint`'s portable center
        subset (the PR-6 elastic-recovery iterate, public API here)."""
        from .. import checkpoint as _ckpt

        if isinstance(source, _ckpt.SolverCheckpoint):
            centers = (source.portable or {}).get(
                "centers", (source.state or {}).get("centers")
            )
            if centers is None:
                raise ValueError(
                    "SolverCheckpoint warm start for KMeans needs a "
                    "'centers' payload (k-means checkpoints carry one)"
                )
            return {
                "cluster_centers_": np.asarray(centers),
                "n_iter_": int(source.iteration),
            }
        centers = getattr(source, "cluster_centers_", None)
        if centers is None:
            raise TypeError(
                f"cannot warm-start KMeans from {type(source).__name__}: "
                "expected a fitted KMeansModel or a SolverCheckpoint"
            )
        return {
            "cluster_centers_": np.asarray(centers),
            "n_iter_": int(getattr(source, "n_iter_", 0) or 0),
        }

    def _get_tpu_fit_func(self, extracted: ExtractedData):
        from ..ops.kmeans import (
            kmeans_fit,
            kmeans_plus_plus_init,
            random_init,
            scalable_kmeans_init,
        )

        x_host = extracted.features
        w_host = extracted.weight

        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            k = int(params["n_clusters"])
            if k > inputs.n_valid:
                raise ValueError(f"k={k} exceeds number of rows {inputs.n_valid}")
            init_mode = params.get("init", "scalable-k-means++")
            seed = int(params.get("random_state", 1) or 1)
            # public warm start (fit(..., warm_start_from=model_or_checkpoint),
            # docs/scheduling.md "Warm starts"): the donor's centers ARE the
            # init — the seeding passes below are skipped entirely, and Lloyd
            # continues the donor's trajectory (adoption + the donor's
            # already-paid iterations are counted)
            warm = getattr(self, "_warm_start", None)
            warm_centers = None
            if warm is not None:
                c0 = np.asarray(warm["cluster_centers_"])
                if tuple(c0.shape) != (k, int(inputs.n_cols)):
                    raise ValueError(
                        f"warm-start centers shape {tuple(c0.shape)} does not "
                        f"match this fit (k={k}, d={inputs.n_cols})"
                    )
                from .. import telemetry as _telemetry

                if _telemetry.enabled():
                    reg = _telemetry.registry()
                    reg.inc("fit.warm_starts")
                    reg.inc(
                        "fit.warm_start_iterations_saved",
                        int(warm.get("n_iter_", 0) or 0),
                    )
                warm_centers = c0
            # under multi-process SPMD the init must be computed from GLOBAL
            # rows: every rank contributes a bounded sample (the whole local
            # block when small), the rendezvous concatenates them in rank
            # order, and every rank runs the SAME seeded init on the union —
            # so all ranks enter the Lloyd loop with identical centers (the
            # reference's distributed k-means|| init runs inside KMeansMG)
            x_init, w_init = x_host, w_host
            if warm_centers is None and inputs.ctx is not None and inputs.ctx.is_spmd:
                cap = max(4 * k, 262_144 // inputs.ctx.nranks)
                n_loc = x_host.shape[0]
                if n_loc > cap:
                    rs = np.random.default_rng(seed * 100_003 + inputs.ctx.rank)  # prng-ok: deliberate per-rank sampling of LOCAL rows; the allgather below hands every rank the identical union, so the seeded init agrees
                    sel = np.sort(rs.choice(n_loc, cap, replace=False))
                    xs = np.asarray(x_host[sel], dtype=np.float64)
                    ws = None if w_host is None else np.asarray(w_host[sel], dtype=np.float64)
                else:
                    xs = np.asarray(x_host, dtype=np.float64)
                    ws = None if w_host is None else np.asarray(w_host, dtype=np.float64)
                x_init = inputs.allgather_array(xs)
                w_init = None if ws is None else inputs.allgather_array(ws)
            if warm_centers is not None:
                centers0 = warm_centers  # the donor's iterate IS the init
            elif init_mode == "random":
                centers0 = random_init(x_init, k, seed)
            elif k >= 64:
                # true k-means|| for large k: O(rounds) device passes instead
                # of k sequential host passes (minutes at the protocol k=1000)
                centers0 = scalable_kmeans_init(x_init, k, seed, w_init)
            else:  # small k: classic k-means++ (exactness-friendly for tests)
                centers0 = kmeans_plus_plus_init(x_init, k, seed, w_init)
            centers0 = centers0.astype(inputs.dtype)
            # `solver_precision="bf16"` (per-estimator or config-wide) forces
            # the bf16-compute/f32-accumulate in-loop path on both fit modes;
            # the legacy `distance_precision` knob keeps governing the
            # resident loop when solver_precision stays at its "f32" default
            from ..core import resolve_solver_precision

            solver_precision = resolve_solver_precision(params)
            if inputs.stream is not None:
                # out-of-core: per-chunk assignment + center accumulation
                # under the SAME deferred-convergence host loop and the SAME
                # checkpoint key as the resident fit. In-loop chunk matmuls
                # honor solver_precision ("bf16" -> distance core fast path);
                # the reported inertia is always re-evaluated full precision.
                from ..ops.streaming import kmeans_fit_streaming

                # the streaming kernel materializes its [chunk_dev, k]
                # distance/one-hot buffers UNTILED, while the workspace
                # estimate charges tiles of at most max_samples_per_batch
                # rows — clamp the chunk so the per-device slice never
                # exceeds the tile the admission verdict budgeted for
                # (smaller chunks only shrink the admitted working set)
                b = int(params.get("max_samples_per_batch", 32768))
                n_dev = int(inputs.mesh.devices.size)
                inputs.stream.chunk_rows = max(
                    1, min(int(inputs.stream.chunk_rows), b * n_dev)
                )
                state = kmeans_fit_streaming(
                    inputs,
                    centers0,
                    max_iter=int(params["max_iter"]),
                    tol=float(params["tol"]),
                    precision_mode="fast" if solver_precision == "bf16" else "high",
                )
            else:
                state = kmeans_fit(
                    inputs.X,
                    inputs.w,
                    centers0,
                    mesh=inputs.mesh,
                    max_iter=int(params["max_iter"]),
                    tol=float(params["tol"]),
                    batch_rows=int(params.get("max_samples_per_batch", 32768)),
                    precision_mode=(
                        "fast"
                        if solver_precision == "bf16"
                        else str(params.get("distance_precision", "fast"))
                    ),
                )
            return {
                "cluster_centers_": np.asarray(state["cluster_centers_"]),
                "inertia_": float(state["inertia_"]),
                "n_iter_": int(state["n_iter_"]),
                "n_cols": inputs.n_cols,
                "dtype": np.dtype(inputs.dtype).name,
            }

        return _fit

    def _create_model(self, attrs: Dict[str, Any]) -> "KMeansModel":
        return KMeansModel(**attrs)


class KMeansModel(_KMeansParams, _TpuModelWithColumns):
    """Fitted KMeans model (reference clustering.py:386-499)."""

    _matmul_precision = "BF16_BF16_F32_X3"
    _spark_converter = "kmeans_to_spark"  # `.cpu()` (reference clustering.py:422-443)

    def __init__(
        self,
        cluster_centers_: Optional[np.ndarray] = None,
        inertia_: float = 0.0,
        n_iter_: int = 0,
        n_cols: int = 0,
        dtype: str = "float32",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            cluster_centers_=cluster_centers_,
            inertia_=inertia_,
            n_iter_=n_iter_,
            n_cols=n_cols,
            dtype=dtype,
        )
        self.cluster_centers_ = np.asarray(cluster_centers_)
        self.inertia_ = float(inertia_)
        self.n_iter_ = int(n_iter_)
        self.n_cols = int(n_cols)
        self.dtype = dtype
        self._setDefault(k=int(self.cluster_centers_.shape[0]) if cluster_centers_ is not None else 2)

    def clusterCenters(self) -> List[np.ndarray]:
        """Spark ML surface: list of center vectors."""
        return [c for c in self.cluster_centers_]

    @property
    def numClusters(self) -> int:
        return self.cluster_centers_.shape[0]

    def predict(self, value) -> int:
        """Single-vector predict (Spark ML model surface)."""
        from ..linalg import Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        d2 = np.sum((self.cluster_centers_ - v[None, :]) ** 2, axis=1)
        return int(np.argmin(d2))

    def setFeaturesCol(self, value) -> "KMeansModel":
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str) -> "KMeansModel":
        return self._set_params(predictionCol=value)

    def _out_column_names(self) -> List[str]:
        return [self.getOrDefault("predictionCol")]

    def _get_transform_func(self):
        import jax

        from ..ops.kmeans import kmeans_predict
        from ..parallel.mesh import default_local_device

        centers = self.cluster_centers_
        dtype = np.float32 if self._float32_inputs else np.float64

        def construct():
            return jax.device_put(centers.astype(dtype), default_local_device())

        def predict(state, xb):
            return kmeans_predict(xb.astype(dtype), state)

        return construct, predict, None

    # serving hooks (docs/serving.md) -------------------------------------

    _serve_dtypes = (None, "float32", "float64", "bf16")

    def _serve_program(self, serve_dtype=None, *, cap=None):
        """KMeans serving hook: `serve_dtype="bf16"` routes assignment
        through the distance core's parity-tested fast-bf16 mode (one-pass
        bf16 MXU matmuls, f32 accumulation) — assignment flips only for
        near-tied rows (docs/serving.md "bf16 serving" accuracy contract)."""
        if serve_dtype != "bf16":
            return super()._serve_program(serve_dtype, cap=cap)
        self._serve_check(serve_dtype)
        import jax

        from ..core import PredictProgram
        from ..ops.distance import argmin_assign
        from ..parallel.mesh import default_local_device

        centers = self.cluster_centers_
        dtype = np.float32 if self._float32_inputs else np.float64

        def construct():
            return jax.device_put(centers.astype(dtype), default_local_device())

        def predict(state, xb):
            return argmin_assign(xb.astype(dtype), state, fast=True)

        return PredictProgram(self, construct=construct, predict=predict, cap=cap)

    def _serve_workspace_terms(self, bucket_rows_count, itemsize):
        # the predict-side assignment tile: a [tile, k] distance block per
        # dispatched bucket, row-tiled through the shared distance core at
        # config["distance_tile_rows"] rows — the same term the fit-side
        # budgeter charges as `predict_tile`
        from ..ops.distance import tile_rows

        k = int(self.cluster_centers_.shape[0])
        tile = min(tile_rows(), max(1, int(bucket_rows_count)))
        return {"predict_tile": tile * k * itemsize}

    def _serve_flop_estimate(self, n_rows, n_cols):
        # roofline numerator: the [n, k] squared-distance block (~3*n*k*d for
        # the expanded |x|^2 - 2 x.c + |c|^2 form); argmin epilogue omitted
        k = max(1, int(self.cluster_centers_.shape[0]))
        return 3.0 * n_rows * k * n_cols


class _DBSCANParams(HasFeaturesCol, HasFeaturesCols, HasPredictionCol, HasIDCol):
    """Param surface of the reference's DBSCAN (reference clustering.py:522-639):
    solver knobs are first-class Params (there is no pyspark DBSCAN to map from)."""

    eps = Param(
        "eps",
        "maximum distance between 2 points such they reside in the same neighborhood",
        TypeConverters.toFloat,
    )
    min_samples = Param(
        "min_samples",
        "number of samples in a neighborhood for a point to be a core point (incl. itself)",
        TypeConverters.toInt,
    )
    metric = Param("metric", "distance metric: 'euclidean' or 'cosine'", TypeConverters.toString)
    algorithm = Param("algorithm", "neighbor computation algorithm: 'brute' or 'rbc'", TypeConverters.toString)
    max_mbytes_per_batch = Param(
        "max_mbytes_per_batch",
        "memory budget (MB) for each pairwise-distance tile — trades runtime for memory "
        "on the N^2 distance computation",
        TypeConverters.identity,
    )
    calc_core_sample_indices = Param(
        "calc_core_sample_indices", "whether to compute core sample indices", TypeConverters.toBoolean
    )

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # identity mapping: the Param names ARE the solver kwargs (no pyspark
        # class exists to translate from; reference clustering.py:503-505 has
        # an empty mapping for the same reason but syncs via shared names)
        return {
            "eps": "eps",
            "min_samples": "min_samples",
            "metric": "metric",
            "algorithm": "algorithm",
            "max_mbytes_per_batch": "max_mbytes_per_batch",
            "calc_core_sample_indices": "calc_core_sample_indices",
        }

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        # Param-tier defaults live HERE so a directly-constructed model resolves
        # them too. calc_core_sample_indices follows the reference's Param tier
        # (True, clustering.py:526-533) — its cuml tier says False but the Param
        # default wins there as well.
        self._setDefault(
            eps=0.5, min_samples=5, metric="euclidean", algorithm="brute",
            max_mbytes_per_batch=None, calc_core_sample_indices=True,
        )

    def _get_solver_params_default(self) -> Dict[str, Any]:
        # reference clustering.py:508-515 defaults (Param tier overrides above)
        return {
            "eps": 0.5,
            "min_samples": 5,
            "metric": "euclidean",
            "algorithm": "brute",
            "verbose": False,
            "max_mbytes_per_batch": None,
            "calc_core_sample_indices": False,  # cuml-tier default (reference clustering.py:513); Param tier above wins
        }

    def getEps(self) -> float:
        return self.getOrDefault("eps")

    def setEps(self, value: float):
        return self._set_params(eps=value)

    def getMinSamples(self) -> int:
        return self.getOrDefault("min_samples")

    def setMinSamples(self, value: int):
        return self._set_params(min_samples=value)

    def getMetric(self) -> str:
        return self.getOrDefault("metric")

    def setMetric(self, value: str):
        return self._set_params(metric=value)

    def setMaxMbytesPerBatch(self, value):
        return self._set_params(max_mbytes_per_batch=value)

    def getMaxMbytesPerBatch(self):
        return self.getOrDefault("max_mbytes_per_batch")

    def getAlgorithm(self) -> str:
        return self.getOrDefault("algorithm")

    def setAlgorithm(self, value: str):
        return self._set_params(algorithm=value)

    def getCalcCoreSampleIndices(self) -> bool:
        return self.getOrDefault("calc_core_sample_indices")

    def setCalcCoreSampleIndices(self, value: bool):
        return self._set_params(calc_core_sample_indices=value)

    def setFeaturesCol(self, value):
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str):
        return self._set_params(predictionCol=value)

    def setIdCol(self, value: str):
        return self._set_params(idCol=value)


class DBSCAN(_DBSCANParams, _TpuEstimator):
    """DBSCAN estimator (reference clustering.py:641-849).

    Like the reference, ``fit`` is a no-op returning a parameter-copied model —
    the clustering itself runs in ``model.transform`` because DBSCAN has no
    train/inference split (reference clustering.py:820-833).

    >>> model = DBSCAN(eps=0.5, min_samples=5).setFeaturesCol("features").fit(df)
    >>> out = model.transform(df)   # df + prediction column, noise = -1

    Distributed strategy: the dataset is replicated to every device and the N²
    pairwise-distance work is row-sliced across the mesh (the reference's
    broadcast + rank-sliced DBSCANMG, clustering.py:1013-1091) in three tiled
    MXU passes — core mask, core-graph components by min-label propagation with
    pointer jumping, border adoption. `max_mbytes_per_batch` bounds each
    distance tile.
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _set_params(self, **kwargs):
        if "metric" in kwargs and kwargs["metric"] not in ("euclidean", "cosine", "precomputed"):
            raise ValueError(
                f"metric must be 'euclidean', 'cosine' or 'precomputed', got {kwargs['metric']!r}"
            )
        if "algorithm" in kwargs and kwargs["algorithm"] not in ("brute", "rbc"):
            raise ValueError(f"algorithm must be 'brute' or 'rbc', got {kwargs['algorithm']!r}")
        return super()._set_params(**kwargs)

    def _get_tpu_fit_func(self, extracted: ExtractedData):  # pragma: no cover
        raise NotImplementedError("DBSCAN does not fit and generate model (reference parity)")

    def _fit_internal(self, dataset: Any, paramMaps):
        # parameter-copied model(s), no data touched (reference
        # clustering.py:820-833); one model per param map for fitMultiple
        sources = [self.copy(pm) for pm in paramMaps] if paramMaps else [self]
        models = []
        for src in sources:
            model = DBSCANModel(n_cols=0, dtype="")
            src._copyValues(model)
            src._copy_solver_params(model)
            models.append(model)
        return models

    def _create_model(self, attrs):  # pragma: no cover - _fit_internal overridden
        return DBSCANModel(**attrs)


class DBSCANModel(_DBSCANParams, _TpuModel):
    """DBSCAN 'model': runs the clustering inside transform and appends the
    label column (reference clustering.py:852-1100).

    `idCol` is accepted for API compatibility with the reference, which needs
    an id join because Spark rows are unordered; the pandas path preserves row
    order, so labels are attached positionally and the id column is left
    untouched."""

    def __init__(self, n_cols: int = 0, dtype: str = "", **kwargs: Any) -> None:
        super().__init__(n_cols=n_cols, dtype=dtype)
        self.n_cols = int(n_cols)
        self.dtype = dtype
        self.core_sample_indices_: Optional[np.ndarray] = None

    def transform(self, dataset: Any):
        from ..data import as_pandas
        from ..ops.dbscan import dbscan_fit
        from ..parallel import TpuContext, get_mesh
        from ..parallel.context import allgather_concat
        from ..parallel.mesh import default_devices, dtype_scope

        active = TpuContext.current()
        spmd = active is not None and active.is_spmd
        pdf = as_pandas(dataset)
        extracted = self._pre_process_data(dataset, for_fit=False)
        feats = extracted.features
        if hasattr(feats, "todense"):
            feats = np.asarray(feats.todense())
        feats = np.asarray(feats, dtype=np.float32)
        row_offset, n_local = 0, feats.shape[0]
        if spmd:
            # replicated-data strategy (reference clustering.py:1013-1091): the
            # whole dataset is rendezvous-gathered to every rank (chunked by
            # config["broadcast_chunk_bytes"]), the N² passes run cooperatively
            # over the GLOBAL mesh, and each rank keeps its own rows' labels
            feats, row_offset = allgather_concat(active.rendezvous, feats)
            mesh = active.mesh
        else:
            mesh = get_mesh(min(self.num_workers, len(default_devices())))
        with dtype_scope(np.float32):
            labels, core_idx = dbscan_fit(
                feats,
                mesh=mesh,
                eps=float(self.getOrDefault("eps")),
                min_samples=int(self.getOrDefault("min_samples")),
                metric=self.getOrDefault("metric"),
                max_mbytes_per_batch=self.getOrDefault("max_mbytes_per_batch"),
                calc_core_sample_indices=bool(self.getOrDefault("calc_core_sample_indices")),
            )
        if spmd:
            # labels are GLOBAL; keep this rank's slice (core_sample_indices_
            # stay global row positions, like the reference's idCol join space)
            labels = labels[row_offset : row_offset + n_local]
        # labels attach positionally: _pre_process_data must not drop/reorder rows
        assert len(labels) == len(pdf), (
            f"row count mismatch: {len(labels)} labels vs {len(pdf)} input rows"
        )
        # most-recent-transform state, mirroring cuML's fit_predict attribute;
        # concurrent transforms of one model should each use their own copy()
        self.core_sample_indices_ = core_idx
        out = pdf.copy(deep=False)
        out[self.getOrDefault("predictionCol")] = labels.astype(np.int64)
        return out
