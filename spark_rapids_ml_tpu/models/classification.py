#
# Classification algorithms: LogisticRegression (RandomForestClassifier joins
# this module when the tree family lands — reference classification.py hosts
# both).
#
# API-parity target: reference classification.py:665-1581, drop-in for
# `pyspark.ml.classification.LogisticRegression`: binomial + multinomial,
# standardization, intercept centering, single-class degenerate handling,
# rawPrediction/probability/prediction output columns, threshold(s).
#
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import FitInputs, _TpuEstimatorSupervised, _TpuModelWithColumns, pred
from ..data import ExtractedData, as_pandas, vectors_to_pandas_column
from ..params import (
    HasElasticNetParam,
    HasEnableSparseDataOptim,
    HasFeaturesCol,
    HasFeaturesCols,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasRegParam,
    HasStandardization,
    HasTol,
    HasWeightCol,
    Param,
    TypeConverters,
)


from .tree import _RandomForestEstimator, _RandomForestModel


class RandomForestClassifier(HasProbabilityCol, HasRawPredictionCol, _RandomForestEstimator):
    """RandomForestClassifier, drop-in for
    ``pyspark.ml.classification.RandomForestClassifier``.

    Ensemble-split fit (reference tree.py:270-281 strategy): each mesh device
    grows its share of the forest on its row shard with level-wise histogram
    tree building (ops/trees.py); tree arrays are gathered at the end (the
    Treelite-concat analog). Impurity: gini (default) or entropy.
    """

    _is_classification = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._setDefault(impurity="gini")
        if self._solver_params.get("split_criterion") is None:
            self._solver_params["split_criterion"] = "gini"

    def _set_params(self, **kwargs):
        if "impurity" in kwargs and kwargs["impurity"] not in ("gini", "entropy"):
            raise ValueError("impurity must be 'gini' or 'entropy' for classification")
        return super()._set_params(**kwargs)

    def setProbabilityCol(self, value: str) -> "RandomForestClassifier":
        return self._set_params(probabilityCol=value)

    def setRawPredictionCol(self, value: str) -> "RandomForestClassifier":
        return self._set_params(rawPredictionCol=value)

    def _create_model(self, attrs: Dict[str, Any]) -> "RandomForestClassificationModel":
        return RandomForestClassificationModel(**attrs)


class RandomForestClassificationModel(HasProbabilityCol, HasRawPredictionCol, _RandomForestModel):
    """Fitted RF classification model (reference classification.py:302-662)."""

    _is_classification = True

    @property
    def numClasses(self) -> int:
        return len(self.classes_)

    def _leaf_values(self) -> np.ndarray:
        # normalized per-node class distribution (Spark averages leaf distributions)
        totals = self.node_stats.sum(axis=2, keepdims=True)
        return self.node_stats / np.maximum(totals, 1e-30)

    def setProbabilityCol(self, value: str) -> "RandomForestClassificationModel":
        return self._set_params(probabilityCol=value)

    def setRawPredictionCol(self, value: str) -> "RandomForestClassificationModel":
        return self._set_params(rawPredictionCol=value)

    def _out_column_names(self) -> List[str]:
        return [
            self.getOrDefault("rawPredictionCol"),
            self.getOrDefault("probabilityCol"),
            self.getOrDefault("predictionCol"),
        ]

    def _split_output(self, result, names, extracted) -> Dict[str, Any]:
        mean_dist = np.asarray(result, dtype=np.float64)
        prob = mean_dist / np.maximum(mean_dist.sum(axis=1, keepdims=True), 1e-30)
        raw = mean_dist * self.num_trees  # Spark raw = summed tree votes
        prediction = self.classes_[np.argmax(prob, axis=1)].astype(np.float64)
        as_vec = extracted.feature_kind == "vector"
        return {
            names[0]: vectors_to_pandas_column(raw) if as_vec else list(raw),
            names[1]: vectors_to_pandas_column(prob) if as_vec else list(prob),
            names[2]: prediction,
        }

    def predict(self, value) -> float:
        from ..linalg import Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        dist = np.asarray(self._raw_forest_output(v[None, :]), dtype=np.float64)[0]
        return float(self.classes_[int(np.argmax(dist))])

    def predictRaw(self, value):
        """Summed per-tree normalized votes (Spark's RF raw prediction;
        computed natively — the reference delegates to .cpu())."""
        from ..linalg import DenseVector, Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        dist = np.asarray(self._raw_forest_output(v[None, :]), dtype=np.float64)[0]
        return DenseVector(dist * self.num_trees)

    def predictProbability(self, value):
        from ..linalg import DenseVector, Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        dist = np.asarray(self._raw_forest_output(v[None, :]), dtype=np.float64)[0]
        return DenseVector(dist / max(dist.sum(), 1e-30))

    def evaluate(self, dataset):
        """Evaluate on a dataset via the converted JVM model's summary
        (reference classification.py:604-662). Accepts framework datasets
        (pandas/arrow/dict) or a Spark DataFrame."""
        from ..spark_interop import as_spark_df

        return self.cpu().evaluate(as_spark_df(dataset))


class _LogisticRegressionParams(
    HasEnableSparseDataOptim,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasMaxIter,
    HasTol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasWeightCol,
):
    family = Param("family", "label distribution: 'auto', 'binomial' or 'multinomial'", TypeConverters.toString)
    threshold = Param("threshold", "binary prediction threshold in [0, 1]", TypeConverters.toFloat)
    thresholds = Param(
        "thresholds",
        "multiclass thresholds: predict argmax(p/threshold)",
        TypeConverters.toListFloat,
    )

    def getFamily(self) -> str:
        return self.getOrDefault("family")

    def getThreshold(self) -> float:
        return self.getOrDefault("threshold")

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # mirrors reference classification.py param mapping for LogisticRegression
        return {
            "maxIter": "max_iter",
            "regParam": "alpha",
            "elasticNetParam": "l1_ratio",
            "tol": "tol",
            "fitIntercept": "fit_intercept",
            "standardization": "standardization",
            "family": "",  # resolved from the label cardinality at fit time
            "threshold": "",
            "thresholds": "",
            "weightCol": "",
        }

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {
            "alpha": 0.0,
            "l1_ratio": 0.0,
            "max_iter": 100,
            "tol": 1e-6,
            "fit_intercept": True,
            "standardization": True,
            "lbfgs_memory": 10,  # reference parity: lbfgs_memory=10 (classification.py:1056-1057)
            "verbose": False,
            # per-estimator override of config["solver_precision"]; "bf16"
            # runs the X·β / Xᵀr matvecs bf16-in/f32-accumulate while the
            # L-BFGS state, line search, and convergence scalars stay full
            # precision (docs/performance.md "Mixed-precision solvers")
            "solver_precision": None,
        }


class LogisticRegression(_LogisticRegressionParams, _TpuEstimatorSupervised):
    """LogisticRegression estimator, drop-in for
    ``pyspark.ml.classification.LogisticRegression``.

    Distributed L-BFGS where every objective/gradient evaluation is one fused
    MXU matmul + psum over the rows mesh; standardization statistics are
    computed in-graph and folded into the coefficients (no standardized copy of
    the data) — the TPU-native form of the reference's CuPy pre-standardization
    + `LogisticRegressionMG` path (classification.py:984-1089).
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            maxIter=100, regParam=0.0, elasticNetParam=0.0, tol=1e-6, fitIntercept=True,
            standardization=True, family="auto", threshold=0.5,
        )
        self._set_params(**kwargs)

    def _set_params(self, **kwargs):
        if "family" in kwargs and kwargs["family"] not in ("auto", "binomial", "multinomial"):
            raise ValueError(
                f"family must be 'auto', 'binomial' or 'multinomial', got {kwargs['family']!r}"
            )
        return super()._set_params(**kwargs)

    def setMaxIter(self, value: int) -> "LogisticRegression":
        return self._set_params(maxIter=value)

    def setRegParam(self, value: float) -> "LogisticRegression":
        return self._set_params(regParam=value)

    def setElasticNetParam(self, value: float) -> "LogisticRegression":
        return self._set_params(elasticNetParam=value)

    def setTol(self, value: float) -> "LogisticRegression":
        return self._set_params(tol=value)

    def setFitIntercept(self, value: bool) -> "LogisticRegression":
        return self._set_params(fitIntercept=value)

    def setStandardization(self, value: bool) -> "LogisticRegression":
        return self._set_params(standardization=value)

    def setFamily(self, value: str) -> "LogisticRegression":
        return self._set_params(family=value)

    def setThreshold(self, value: float) -> "LogisticRegression":
        return self._set_params(threshold=value)

    def setThresholds(self, value: List[float]) -> "LogisticRegression":
        return self._set_params(thresholds=value)

    def setFeaturesCol(self, value) -> "LogisticRegression":
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setLabelCol(self, value: str) -> "LogisticRegression":
        return self._set_params(labelCol=value)

    def setPredictionCol(self, value: str) -> "LogisticRegression":
        return self._set_params(predictionCol=value)

    def setProbabilityCol(self, value: str) -> "LogisticRegression":
        return self._set_params(probabilityCol=value)

    def setRawPredictionCol(self, value: str) -> "LogisticRegression":
        return self._set_params(rawPredictionCol=value)

    def setWeightCol(self, value: str) -> "LogisticRegression":
        return self._set_params(weightCol=value)

    # host-side class discovery is rendezvous-merged below; everything else is
    # one pure SPMD program — correct under multi-process
    _supports_multiprocess = True
    # CSR input fits via the padded-ELL sparse program (ops/sparse.py) without
    # densifying — the reference's sparse qn path (classification.py:975-1098)
    _supports_sparse_input = True
    # full-batch gradients accumulate over row chunks: an over-HBM dataset
    # demotes to ops/streaming.logistic_fit_streaming (smooth L2 path; the
    # L1/elastic-net OWL-QN solver has no out-of-core form and raises the
    # typed HbmBudgetError instead — docs/robustness.md "Memory safety")
    _supports_streaming_fit = True

    def _solver_workspace_terms(
        self, rows_per_device: int, n_cols: int, params: Dict[str, Any], itemsize: int
    ) -> Dict[str, int]:
        # GLM working set: the per-row logits held TWICE (z at the iterate +
        # z along the search direction) and the circular L-BFGS (S, Y)
        # history over the flat parameter vector. Class count is unknown
        # before the fit sees labels: binomial/auto estimate with k_out=1,
        # an explicit multinomial family with a documented floor of 2.
        # (`family` is a Spark param, not a solver param — query it directly.)
        try:
            family = self.getOrDefault("family")
        except Exception:
            family = "auto"
        k_out = 2 if family == "multinomial" else 1
        n_flat = n_cols * k_out + k_out
        mem = int(params.get("lbfgs_memory", 10))
        return {
            "glm_logits": 2 * rows_per_device * k_out * itemsize,
            "lbfgs_history": 2 * mem * n_flat * itemsize,
        }

    def _solver_flop_estimate(self, n_rows: int, n_cols: int) -> Optional[float]:
        # GLM roofline model (ops_plane/efficiency.py): each L-BFGS
        # iteration is dominated by the X·B forward matvec and the Xᵀr
        # gradient matvec, 2·n·d·k_out FLOPs each; pointwise link terms are
        # O(n·k) and omitted. max_iter is an UPPER bound on iterations, so
        # MFU from this estimate is an upper bound too (documented bias).
        try:
            family = self.getOrDefault("family")
        except Exception:
            family = "auto"
        k_out = 2 if family == "multinomial" else 1
        iters = int(self._solver_params.get("max_iter", 100))
        return 4.0 * n_rows * n_cols * k_out * iters

    def _fit_streaming(
        self, inputs: FitInputs, params: Dict[str, Any], classes, labels_host,
        alpha: float, l1_ratio: float,
    ) -> Dict[str, Any]:
        """Out-of-core logistic fit (docs/robustness.md "Memory safety"):
        streamed full-batch GLM quasi-Newton. L1/elastic-net has no
        out-of-core path — OWL-QN's pseudo-gradient projection is not a
        chunk-accumulable reduction — so a demoted L1 fit fails typed."""
        from ..errors import HbmBudgetError
        from ..ops.streaming import logistic_fit_streaming

        if alpha * l1_ratio > 0:
            raise HbmBudgetError(
                "logistic L1/elastic-net fit does not fit device memory and "
                "the OWL-QN solver has no out-of-core streaming path "
                "(set elasticNetParam=0 or raise the budget)",
                largest_term="solver.owlqn",
            )
        multinomial, y_idx_host = self._fit_geometry_host(classes, labels_host)
        statics = self._solver_statics(params)
        common = dict(
            k=len(classes),
            multinomial=multinomial,
            lam_l2=alpha,
            lam_l1=0.0,
            use_l1=False,
            **statics,
        )
        state = logistic_fit_streaming(
            inputs, y_idx_host,
            k=len(classes), multinomial=multinomial, lam_l2=alpha,
            fit_intercept=statics["fit_intercept"],
            standardize=statics["standardize"],
            max_iter=statics["max_iter"], tol=statics["tol"],
            lbfgs_memory=statics["lbfgs_memory"],
            fast=statics["fast"],
            # param-identifying key, mirroring the resident checkpointed
            # fit's "logistic:<params>" — a static key would let sequential
            # param sets of one demoted sweep resume EACH OTHER'S trajectories
            ckpt_key="logistic_stream:" + repr(sorted(common.items())),
        )
        state = {k_: np.asarray(v) for k_, v in state.items()}
        return self._finalize_state(state, classes, inputs, common)

    def _resolve_classes(self, labels_host: np.ndarray, inputs: FitInputs) -> np.ndarray:
        """Sorted global class values for THIS fit's rows. Honors a fold's
        row mask (a weight-masked CV fold must discover classes from its
        TRAIN rows only — physical-split parity) and merges across ranks
        under SPMD (the reference gets this for free because cuML's qn fit
        allgathers label cardinality internally)."""
        import json

        lbl = labels_host if inputs.host_mask is None else labels_host[inputs.host_mask]
        local_classes = np.unique(lbl).astype(np.float64)
        gathered = inputs.allgather_host(json.dumps(local_classes.tolist()))
        return np.unique(np.concatenate([np.asarray(json.loads(g)) for g in gathered]))

    def _degenerate_single_class(self, classes: np.ndarray, inputs: FitInputs) -> Dict[str, Any]:
        # degenerate single-class fit: P(class)=1 (Spark parity,
        # reference classification.py:1122-1135)
        return {
            "coef_": np.zeros((1, inputs.n_cols)),
            "intercept_": np.array([np.inf if classes[0] == 1.0 else -np.inf]),
            "classes_": classes,
            "n_iter_": 0,
            "objective_": 0.0,
            "n_cols": inputs.n_cols,
            "dtype": np.dtype(inputs.dtype).name,
        }

    def _fit_geometry_host(self, classes: np.ndarray, labels_host: np.ndarray):
        """(multinomial, y_idx HOST array) — the label geometry both the
        resident paths (which place y_idx) and the streaming path (which
        slices it per chunk) derive from."""
        family = self.getOrDefault("family")
        k = len(classes)
        multinomial = family == "multinomial" or (family == "auto" and k > 2)
        if family == "binomial" and k > 2:
            raise ValueError(f"family='binomial' but found {k} classes")
        # Under a fold mask, held-out rows may carry labels OUTSIDE the
        # fold's class set; their weight is 0 so they contribute nothing,
        # but the index must stay in [0, k) for the traced gather — clip
        # (exact for every in-set label: classes is sorted unique)
        y_idx_host = np.clip(
            np.searchsorted(classes, labels_host), 0, k - 1
        ).astype(np.int32)
        return multinomial, y_idx_host

    def _fit_geometry(self, classes: np.ndarray, labels_host: np.ndarray, inputs: FitInputs):
        """(multinomial, y_idx device array) shared by the sequential and
        batched solve paths."""
        multinomial, y_idx_host = self._fit_geometry_host(classes, labels_host)
        return multinomial, inputs.put_rows(y_idx_host)

    @staticmethod
    def _finalize_state(state: Dict[str, Any], classes, inputs: FitInputs, common) -> Dict[str, Any]:
        """Host-fetched solver state -> model attribute dict, running the
        shared divergence guard / stall warning / telemetry record."""
        from .. import telemetry
        from ..ops.logistic import check_glm_result, warn_if_early_stall

        check_glm_result(state)
        warn_if_early_stall(
            state, standardize=common["standardize"], max_iter=common["max_iter"]
        )
        if telemetry.enabled():  # gate: the arg fetches sync with the device
            telemetry.record_solver_result(
                "logistic",
                n_iter=int(state["n_iter_"]),
                objective=float(state["objective_"]),
                stalled=bool(np.asarray(state.get("stalled_", False))),
            )
        return {
            "coef_": np.asarray(state["coef_"], dtype=np.float64),
            "intercept_": np.asarray(state["intercept_"], dtype=np.float64),
            "classes_": classes,
            "n_iter_": int(state["n_iter_"]),
            "objective_": float(state["objective_"]),
            "n_cols": inputs.n_cols,
            "dtype": np.dtype(inputs.dtype).name,
        }

    @staticmethod
    def _solver_statics(params: Dict[str, Any]) -> Dict[str, Any]:
        from ..core import resolve_solver_precision

        return dict(
            fit_intercept=bool(params["fit_intercept"]),
            standardize=bool(params["standardization"]),
            max_iter=int(params["max_iter"]),
            tol=float(params["tol"]),
            lbfgs_memory=int(params["lbfgs_memory"]),
            # static of every GLM entry point; also part of the checkpoint
            # key repr, so bf16 and f32 trajectories can never cross-resume
            fast=resolve_solver_precision(params) == "bf16",
        )

    def _resolve_warm_start(self, source: Any) -> Dict[str, Any]:
        """Warm-start payload for `fit(..., warm_start_from=...)`: a fitted
        `LogisticRegressionModel`'s original-space (coef_, intercept_)
        iterate, or a `SolverCheckpoint` carrying one. GLM segment
        checkpoints store the STANDARDIZED flat iterate — dataset-specific
        scaling, not portable across fits — so those are rejected with a
        pointer at the model route (the scheduler resumes them through the
        checkpoint store instead, where the placement is pinned equal)."""
        from .. import checkpoint as _ckpt

        if isinstance(source, _ckpt.SolverCheckpoint):
            st = dict(source.portable or {})
            st.update({k: v for k, v in (source.state or {}).items() if k not in st})
            if "coef_" not in st:
                raise ValueError(
                    "SolverCheckpoint warm start for LogisticRegression needs "
                    "an original-space 'coef_' payload; GLM segment "
                    "checkpoints carry the standardized iterate (dataset-"
                    "specific) — warm-start from the fitted model instead"
                )
            coef = np.asarray(st["coef_"])
            return {
                "coef_": coef,
                "intercept_": np.asarray(
                    st.get("intercept_", np.zeros(coef.shape[0], coef.dtype))
                ),
                "n_iter_": int(st.get("n_iter_", source.iteration) or 0),
            }
        coef = getattr(source, "coef_", None)
        if coef is None:
            raise TypeError(
                f"cannot warm-start LogisticRegression from "
                f"{type(source).__name__}: expected a fitted "
                "LogisticRegressionModel or a SolverCheckpoint"
            )
        coef = np.asarray(coef)
        return {
            "coef_": coef,
            "intercept_": np.asarray(
                getattr(source, "intercept_", np.zeros(coef.shape[0], coef.dtype))
            ),
            "n_iter_": int(np.max(getattr(source, "n_iter_", 0)) or 0),
        }

    def _get_tpu_fit_func(self, extracted: ExtractedData):
        from .. import checkpoint as _ckpt
        from ..ops.logistic import (
            logistic_fit,
            logistic_fit_checkpointed,
            logistic_fit_ell,
            logistic_fit_ell_checkpointed,
        )

        labels_host = extracted.label

        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            alpha = float(params["alpha"])
            l1_ratio = float(params["l1_ratio"])
            classes = self._resolve_classes(labels_host, inputs)
            if len(classes) == 1:
                return self._degenerate_single_class(classes, inputs)
            if inputs.stream is not None:
                return self._fit_streaming(
                    inputs, params, classes, labels_host, alpha, l1_ratio
                )
            multinomial, y_idx = self._fit_geometry(classes, labels_host, inputs)
            common = dict(
                k=len(classes),
                multinomial=multinomial,
                lam_l2=alpha * (1.0 - l1_ratio),
                lam_l1=alpha * l1_ratio,
                use_l1=alpha * l1_ratio > 0,
                **self._solver_statics(params),
            )
            # public warm start (fit(..., warm_start_from=...),
            # docs/scheduling.md "Warm starts"): seed the L-BFGS/OWL-QN
            # iterate from the donor's original-space coefficients — the
            # solver rebuilds the standardized flat iterate via the exact
            # inverse of its own fold-out (ops/logistic._warm_x0)
            warm_tuple = None
            _warm = getattr(self, "_warm_start", None)
            if _warm is not None:
                k_out = len(classes) if multinomial else 1
                wcoef = np.asarray(_warm["coef_"])
                if tuple(wcoef.shape) != (k_out, int(inputs.n_cols)):
                    raise ValueError(
                        f"warm-start coef shape {tuple(wcoef.shape)} does not "
                        f"match this fit (k_out={k_out}, d={inputs.n_cols})"
                    )
                from .. import telemetry as _telemetry

                if _telemetry.enabled():
                    reg = _telemetry.registry()
                    reg.inc("fit.warm_starts")
                    reg.inc(
                        "fit.warm_start_iterations_saved",
                        int(_warm.get("n_iter_", 0) or 0),
                    )
                warm_tuple = (
                    wcoef.astype(inputs.dtype),
                    np.asarray(_warm["intercept_"]).astype(inputs.dtype),
                )
            # elastic recovery: with a checkpoint cadence configured and a
            # store installed by the enclosing recoverable stage, the solver
            # loop runs host-segmented so an interrupted fit resumes from
            # the last boundary. Single-controller only: the segment
            # boundary host-fetches globally-sharded state, which a
            # multi-process rank cannot address alone.
            use_ckpt = _ckpt.solver_checkpoints_active() and (
                inputs.ctx is None or not inputs.ctx.is_spmd
            )
            ckpt_common = (
                dict(
                    ckpt_key="logistic:" + repr(sorted(common.items())),
                    placement_key=_ckpt.placement_key_of(inputs),
                )
                if use_ckpt
                else {}
            )
            if inputs.X_sparse is not None:
                ell_val, ell_idx = inputs.ell_rows()
                w_dev = inputs.put_rows(np.asarray(inputs.w, dtype=inputs.dtype))
                fit_fn = logistic_fit_ell_checkpointed if use_ckpt else logistic_fit_ell
                state = fit_fn(
                    ell_val, ell_idx, y_idx, w_dev, d=inputs.n_cols,
                    warm_start=warm_tuple, **common, **ckpt_common,
                )
            else:
                fit_fn = logistic_fit_checkpointed if use_ckpt else logistic_fit
                state = fit_fn(
                    inputs.X, y_idx, inputs.w, warm_start=warm_tuple,
                    **common, **ckpt_common,
                )
            # ONE device->host fetch of the whole result, then the divergence
            # guard runs on the already-fetched scalars (no extra sync)
            state = {k: np.asarray(v) for k, v in state.items()}
            return self._finalize_state(state, classes, inputs, common)

        return _fit

    def _batch_group_key(self, sp: Dict[str, Any]):
        # regParam (alpha) and elasticNetParam (l1_ratio) are TRACED scalars
        # of the solver — a grid over them is one compiled program. The L1
        # solver choice is a derived STATIC (use_l1), so grids mixing
        # L1-on/off split into one batched program per side. Everything else
        # in the solver param dict changes program structure.
        use_l1 = float(sp["alpha"]) * float(sp["l1_ratio"]) > 0
        rest = tuple(sorted((k, repr(v)) for k, v in sp.items() if k not in ("alpha", "l1_ratio")))
        return (use_l1, rest)

    def _get_tpu_batched_fit_func(self, extracted: ExtractedData):
        from .. import telemetry
        from ..ops.logistic import logistic_fit_batched, logistic_fit_ell_batched

        labels_host = extracted.label

        def _fit_batch(inputs: FitInputs, param_sets) -> Optional[list]:
            if telemetry.convergence_trace_enabled():
                # per-iteration host callbacks receive per-grid-point scalars;
                # under vmap they would see batched values — trace sequentially
                return None
            classes = self._resolve_classes(labels_host, inputs)
            if len(classes) == 1:
                return [self._degenerate_single_class(classes, inputs) for _ in param_sets]
            multinomial, y_idx = self._fit_geometry(classes, labels_host, inputs)
            alphas = np.asarray([float(sp["alpha"]) for sp in param_sets])
            l1rs = np.asarray([float(sp["l1_ratio"]) for sp in param_sets])
            lam_l2s = (alphas * (1.0 - l1rs)).astype(inputs.dtype)
            lam_l1s = (alphas * l1rs).astype(inputs.dtype)
            statics = self._solver_statics(param_sets[0])  # uniform per group key
            common = dict(
                k=len(classes),
                multinomial=multinomial,
                use_l1=bool((lam_l1s > 0).any()),
                **statics,
            )
            if inputs.X_sparse is not None:
                ell_val, ell_idx = inputs.ell_rows()
                w_dev = inputs.put_rows(np.asarray(inputs.w, dtype=inputs.dtype))
                stacked = logistic_fit_ell_batched(
                    ell_val, ell_idx, y_idx, w_dev, lam_l2s, lam_l1s,
                    d=inputs.n_cols, **common,
                )
            else:
                stacked = logistic_fit_batched(
                    inputs.X, y_idx, inputs.w, lam_l2s, lam_l1s, **common
                )
            stacked = {k: np.asarray(v) for k, v in stacked.items()}  # ONE fetch
            return [
                self._finalize_state(
                    {k: v[i] for k, v in stacked.items()}, classes, inputs, common
                )
                for i in range(len(param_sets))
            ]

        return _fit_batch

    def _create_model(self, attrs: Dict[str, Any]) -> "LogisticRegressionModel":
        return LogisticRegressionModel(**attrs)

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        if not hasattr(evaluator, "getMetricName"):
            return False
        from ..metrics import MulticlassMetrics

        if evaluator.getMetricName() not in MulticlassMetrics.SUPPORTED_MULTI_CLASS_METRIC_NAMES:
            return False
        if evaluator.hasParam("weightCol") and evaluator.isDefined("weightCol"):
            return False
        return True


class LogisticRegressionModel(_LogisticRegressionParams, _TpuModelWithColumns):
    """Fitted logistic regression model (reference classification.py:1159-1581)."""

    def __init__(
        self,
        coef_: Optional[np.ndarray] = None,
        intercept_: Optional[np.ndarray] = None,
        classes_: Optional[np.ndarray] = None,
        n_iter_: int = 0,
        objective_: float = 0.0,
        n_cols: int = 0,
        dtype: str = "float32",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            coef_=coef_, intercept_=intercept_, classes_=classes_, n_iter_=n_iter_,
            objective_=objective_, n_cols=n_cols, dtype=dtype,
        )
        self.coef_ = np.atleast_2d(np.asarray(coef_))
        self.intercept_ = np.atleast_1d(np.asarray(intercept_))
        self.classes_ = np.asarray(classes_)
        self.n_iter_ = int(n_iter_)
        self.objective_ = float(objective_)
        self.n_cols = int(n_cols)
        self.dtype = dtype

    # -- Spark ML model surface -------------------------------------------
    @property
    def numClasses(self) -> int:
        return len(self.classes_)

    @property
    def numFeatures(self) -> int:
        return self.n_cols

    @property
    def _is_multinomial(self) -> bool:
        return self.coef_.shape[0] > 1

    @property
    def coefficients(self):
        from ..linalg import DenseVector

        if self._is_multinomial:
            raise Exception(
                "Multinomial models contain a matrix of coefficients, use coefficientMatrix instead."
            )
        return DenseVector(self.coef_[0])

    @property
    def intercept(self) -> float:
        if self._is_multinomial:
            raise Exception(
                "Multinomial models contain a vector of intercepts, use interceptVector instead."
            )
        return float(self.intercept_[0])

    @property
    def coefficientMatrix(self) -> np.ndarray:
        return self.coef_

    @property
    def interceptVector(self):
        from ..linalg import DenseVector

        return DenseVector(self.intercept_)

    _spark_converter = "logreg_to_spark"  # `.cpu()` (reference classification.py:1301-1323)

    def setFeaturesCol(self, value) -> "LogisticRegressionModel":
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setThreshold(self, value: float) -> "LogisticRegressionModel":
        return self._set_params(threshold=value)

    def setProbabilityCol(self, value: str) -> "LogisticRegressionModel":
        return self._set_params(probabilityCol=value)

    def setRawPredictionCol(self, value: str) -> "LogisticRegressionModel":
        return self._set_params(rawPredictionCol=value)

    def setPredictionCol(self, value: str) -> "LogisticRegressionModel":
        return self._set_params(predictionCol=value)

    # -- prediction machinery ---------------------------------------------
    def _get_transform_func(self):
        import jax

        from ..ops.logistic import logistic_predict
        from ..parallel.mesh import default_local_device

        coef_np, intercept_np = self.coef_, self.intercept_
        multinomial = self._is_multinomial
        dtype = np.float32 if self._float32_inputs else np.float64

        def construct():
            dev = default_local_device()
            return (
                jax.device_put(coef_np.astype(dtype), dev),
                jax.device_put(intercept_np.astype(dtype), dev),
            )

        def predict(state, xb):
            coef, b = state
            return logistic_predict(xb.astype(dtype), coef, b, multinomial=multinomial)

        return construct, predict, None

    def _serve_workspace_terms(self, bucket_rows_count, itemsize):
        # per-bucket predict workspace (docs/serving.md): the raw-margin and
        # probability blocks logistic_predict materializes, [bucket, k] each
        k_out = max(2, int(np.asarray(self.coef_).shape[0]))
        return {"logits": 2 * int(bucket_rows_count) * k_out * itemsize}

    def _serve_flop_estimate(self, n_rows, n_cols):
        # roofline numerator per dispatched bucket: the X @ coef.T matmul
        # (2*n*d*k) dominates; softmax/sigmoid epilogue omitted (lower bound)
        k_out = max(1, int(np.asarray(self.coef_).shape[0]))
        return 2.0 * n_rows * n_cols * k_out

    def _raw_prob(self, features) -> tuple:
        """Batched (raw, prob) arrays for a host feature block."""
        if np.isinf(self.intercept_).any():
            # degenerate single-class model
            n = features.shape[0]
            return np.tile(self.intercept_, (n, 1)), np.ones((n, 1))
        raw, prob = self._transform_arrays(features)
        return raw.astype(np.float64), prob.astype(np.float64)

    def _predict_from_prob(self, prob: np.ndarray) -> np.ndarray:
        if self.numClasses == 1:
            return np.full(prob.shape[0], float(self.classes_[0]))
        if self.isDefined("thresholds"):
            t = np.asarray(self.getOrDefault("thresholds"))
            idx = np.argmax(prob / t[None, :], axis=1)
        elif not self._is_multinomial and self.numClasses == 2:
            idx = (prob[:, 1] > self.getThreshold()).astype(int)
        else:
            idx = np.argmax(prob, axis=1)
        return self.classes_[idx].astype(np.float64)

    def transform(self, dataset: Any):
        pdf = as_pandas(dataset)
        extracted = self._pre_process_data(dataset, for_fit=False)
        raw, prob = self._raw_prob(extracted.features)
        out = pdf.copy(deep=False)
        as_vec = extracted.feature_kind == "vector"
        raw_col = vectors_to_pandas_column(raw) if as_vec else list(raw)
        prob_col = vectors_to_pandas_column(prob) if as_vec else list(prob)
        out[self.getOrDefault("rawPredictionCol")] = raw_col
        out[self.getOrDefault("probabilityCol")] = prob_col
        out[self.getOrDefault("predictionCol")] = self._predict_from_prob(prob)
        return out

    def predict(self, value) -> float:
        """Single-vector predict (Spark ML model surface)."""
        from ..linalg import Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        _, prob = self._raw_prob(v[None, :])
        return float(self._predict_from_prob(prob)[0])

    def predictProbability(self, value):
        from ..linalg import DenseVector, Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        _, prob = self._raw_prob(v[None, :])
        return DenseVector(prob[0])

    def predictRaw(self, value):
        """Raw margin scores per class (Spark surface; computed natively —
        the reference delegates to .cpu(), classification.py:1559-1576)."""
        from ..linalg import DenseVector, Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        raw, _ = self._raw_prob(v[None, :])
        return DenseVector(raw[0])

    def evaluate(self, dataset):
        """Evaluate on a dataset via the converted JVM model's summary (the
        reference's exact behavior, classification.py:1592-1599). Accepts
        framework datasets (pandas/arrow/dict) or a Spark DataFrame."""
        from ..spark_interop import as_spark_df

        return self.cpu().evaluate(as_spark_df(dataset))

    @property
    def summary(self):
        """No training summary is retained (reference parity,
        classification.py:1550-1557)."""
        raise RuntimeError(
            f"No training summary available for this {type(self).__name__}"
        )

    # -- fused CV path ------------------------------------------------------
    def _combine(self, models: List["LogisticRegressionModel"]) -> "LogisticRegressionModel":
        combined = LogisticRegressionModel(
            coef_=self.coef_, intercept_=self.intercept_, classes_=self.classes_,
            n_iter_=self.n_iter_, objective_=self.objective_, n_cols=self.n_cols, dtype=self.dtype,
        )
        combined._sub_models = list(models)
        self._copyValues(combined)
        self._copy_solver_params(combined)
        return combined

    def _transform_evaluate(self, dataset: Any, evaluator: Any) -> List[float]:
        """Score ALL packed models in one pass over a DATASET (extracts the
        feature block, then delegates to `_transform_evaluate_arrays`)."""
        from ..core import evaluator_label_column

        pdf = as_pandas(dataset)
        label = pdf[evaluator_label_column(self, evaluator)].to_numpy(dtype=np.float64)
        extracted = self._pre_process_data(dataset, for_fit=False)
        return self._transform_evaluate_arrays(extracted.features, label, evaluator)

    def _transform_evaluate_arrays(
        self, features: Any, label: np.ndarray, evaluator: Any
    ) -> List[float]:
        """Score ALL packed models over already-extracted blocks — the array
        entry point CrossValidator uses to score held-out rows by slicing
        the one ingested block (no pandas round-trip)."""
        from ..metrics import MulticlassMetrics

        assert hasattr(self, "_sub_models"), "call _combine first"
        want_logloss = evaluator.getMetricName() == "logLoss"
        eps = evaluator.getOrDefault("eps") if evaluator.hasParam("eps") else 1e-15
        scores = []
        for m in self._sub_models:
            _, prob = m._raw_prob(features)
            prediction = m._predict_from_prob(prob)
            pairs = np.stack([label, prediction], axis=1)
            uniq, inverse = np.unique(pairs, axis=0, return_inverse=True)
            counts = np.bincount(inverse, minlength=len(uniq)).astype(np.float64)
            confusion = {
                (float(uniq[i, 0]), float(uniq[i, 1])): float(counts[i]) for i in range(len(uniq))
            }
            log_loss = None
            if want_logloss:
                # exact class membership: labels unseen by this fold's model get
                # probability eps (the model assigns them ~0 mass)
                cls_idx = np.searchsorted(m.classes_, label)
                cls_idx_safe = np.clip(cls_idx, 0, len(m.classes_) - 1)
                known = m.classes_[cls_idx_safe] == label
                p_raw = prob[np.arange(len(label)), cls_idx_safe]
                p_true = np.clip(np.where(known, p_raw, 0.0), eps, 1 - eps)
                log_loss = float(np.sum(-np.log(p_true)))
            scores.append(MulticlassMetrics.from_confusion(confusion, log_loss).evaluate(evaluator))
        return scores
