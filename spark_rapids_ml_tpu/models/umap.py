#
# UMAP estimator/model — API-parity target: reference umap.py (1,327 LoC):
# `UMAP`/`UMAPModel` with the cuML param surface, single-controller fit +
# batched transform, and the numpy-sidecar persistence variant
# (reference umap.py:1262-1327).
#
# Strategy parity (SURVEY.md §2.2): the reference fits on ONE node (coalesce(1),
# umap.py:830-842) and broadcasts (embedding_, raw_data_) for distributed
# transform. Here fit runs single-controller with the kNN-graph stage sharded
# over the mesh (ops/umap.py), and transform batches new rows against the
# retained training state.
#
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..core import _TpuEstimator, _TpuModel, _TpuReader, _TpuWriter, _np_default
from ..data import ExtractedData, as_pandas
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasOutputCol,
    Param,
    TypeConverters,
)


class _UMAPParams(HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasOutputCol):
    """Param surface of reference umap.py:121-604 (cuML UMAP knobs as
    first-class Params; identity-mapped into solver params)."""

    n_neighbors = Param("n_neighbors", "size of the local neighborhood", TypeConverters.toFloat)
    n_components = Param("n_components", "embedding dimension", TypeConverters.toInt)
    metric = Param("metric", "distance metric: 'euclidean' or 'cosine'", TypeConverters.toString)
    n_epochs = Param("n_epochs", "number of optimization epochs", TypeConverters.identity)
    learning_rate = Param("learning_rate", "initial embedding learning rate", TypeConverters.toFloat)
    init = Param("init", "embedding initialization: 'spectral' or 'random'", TypeConverters.toString)
    min_dist = Param("min_dist", "minimum embedded distance between points", TypeConverters.toFloat)
    spread = Param("spread", "effective scale of embedded points", TypeConverters.toFloat)
    set_op_mix_ratio = Param("set_op_mix_ratio", "fuzzy union vs intersection mix", TypeConverters.toFloat)
    local_connectivity = Param("local_connectivity", "assumed local connectivity", TypeConverters.toFloat)
    repulsion_strength = Param("repulsion_strength", "negative-sample repulsion weight", TypeConverters.toFloat)
    negative_sample_rate = Param("negative_sample_rate", "negative samples per edge", TypeConverters.toInt)
    transform_queue_size = Param("transform_queue_size", "accepted, ignored (no analog)", TypeConverters.toFloat)
    a = Param("a", "embedding curve parameter a (derived from min_dist/spread if unset)", TypeConverters.identity)
    b = Param("b", "embedding curve parameter b (derived from min_dist/spread if unset)", TypeConverters.identity)
    precomputed_knn = Param("precomputed_knn", "precomputed (knn_indices, knn_dists) pair", TypeConverters.identity)
    random_state = Param("random_state", "random seed", TypeConverters.identity)
    sample_fraction = Param("sample_fraction", "fraction of rows used for fit", TypeConverters.toFloat)

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {name: name for name in (
            "n_neighbors", "n_components", "metric", "n_epochs", "learning_rate",
            "init", "min_dist", "spread", "set_op_mix_ratio", "local_connectivity",
            "repulsion_strength", "negative_sample_rate", "transform_queue_size",
            "a", "b", "precomputed_knn", "random_state",
        )}

    def _get_solver_params_default(self) -> Dict[str, Any]:
        # reference umap.py:95-116 defaults
        return {
            "n_neighbors": 15.0,
            "n_components": 2,
            "metric": "euclidean",
            "n_epochs": None,
            "learning_rate": 1.0,
            "init": "spectral",
            "min_dist": 0.1,
            "spread": 1.0,
            "set_op_mix_ratio": 1.0,
            "local_connectivity": 1.0,
            "repulsion_strength": 1.0,
            "negative_sample_rate": 5,
            "transform_queue_size": 4.0,
            "a": None,
            "b": None,
            "precomputed_knn": None,
            "random_state": None,
            "verbose": False,
        }

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(
            n_neighbors=15.0, n_components=2, metric="euclidean", n_epochs=None,
            learning_rate=1.0, init="spectral", min_dist=0.1, spread=1.0,
            set_op_mix_ratio=1.0, local_connectivity=1.0, repulsion_strength=1.0,
            negative_sample_rate=5, transform_queue_size=4.0, a=None, b=None,
            precomputed_knn=None, random_state=None, sample_fraction=1.0,
            outputCol="embedding",
        )

    # getters/setters (reference umap.py:343-604 surface)
    def getNNeighbors(self) -> float:
        return self.getOrDefault("n_neighbors")

    def setNNeighbors(self, value: float):
        return self._set_params(n_neighbors=value)

    def getNComponents(self) -> int:
        return self.getOrDefault("n_components")

    def setNComponents(self, value: int):
        return self._set_params(n_components=value)

    def getNEpochs(self):
        return self.getOrDefault("n_epochs")

    def setNEpochs(self, value):
        return self._set_params(n_epochs=value)

    def getMinDist(self) -> float:
        return self.getOrDefault("min_dist")

    def setMinDist(self, value: float):
        return self._set_params(min_dist=value)

    def getInit(self) -> str:
        return self.getOrDefault("init")

    def setInit(self, value: str):
        return self._set_params(init=value)

    def getRandomState(self):
        return self.getOrDefault("random_state")

    def setRandomState(self, value):
        return self._set_params(random_state=value)

    def getSampleFraction(self) -> float:
        return self.getOrDefault("sample_fraction")

    def setSampleFraction(self, value: float):
        return self._set_params(sample_fraction=value)

    def setFeaturesCol(self, value):
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setLabelCol(self, value: str):
        return self._set_params(labelCol=value)

    def setOutputCol(self, value: str):
        return self._set_params(outputCol=value)

    def getOutputCol(self) -> str:
        return self.getOrDefault("outputCol")


class UMAP(_UMAPParams, _TpuEstimator):
    """UMAP estimator (reference umap.py:606-1115).

    >>> model = UMAP(n_components=2).setFeaturesCol("features").fit(df)
    >>> out = model.transform(df)   # (features, embedding) columns

    Fit is single-controller like the reference's coalesce(1) fit
    (umap.py:830-842): the O(n²) kNN-graph stage is sharded over the mesh, the
    fuzzy-set calibration and the epoch-scheduled SGD layout run as jitted
    programs (ops/umap.py). Setting `labelCol` switches to supervised fit
    (categorical intersection), matching umap.py:940-950. `sample_fraction`
    subsamples rows before fitting.
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._set_params(**kwargs)

    def _set_params(self, **kwargs):
        if kwargs.get("metric") not in (None, "euclidean", "cosine"):
            raise ValueError(
                f"metric must be 'euclidean' or 'cosine', got {kwargs['metric']!r}"
            )
        if kwargs.get("precomputed_knn") is not None:
            # the reference's (knn_indices, knn_dists) pair (umap.py
            # precomputed_knn -> cuML); validated against the fit rows at fit
            pre = kwargs["precomputed_knn"]
            if not (isinstance(pre, (tuple, list)) and len(pre) == 2):
                raise ValueError(
                    "precomputed_knn must be a (knn_indices, knn_dists) pair "
                    "of [n, k] arrays (cuML/umap-learn convention)"
                )
        if "init" in kwargs and kwargs["init"] not in ("spectral", "random"):
            raise ValueError(f"init must be 'spectral' or 'random', got {kwargs['init']!r}")
        return super()._set_params(**kwargs)

    def _get_tpu_fit_func(self, extracted: ExtractedData):  # pragma: no cover
        raise NotImplementedError  # _fit_internal overridden

    def _fit_internal(self, dataset: Any, paramMaps):
        from ..ops.umap import umap_fit
        from ..parallel import TpuContext, get_mesh
        from ..parallel.mesh import default_devices, dtype_scope

        if paramMaps:
            raise NotImplementedError("UMAP does not support fitMultiple param maps")
        active = TpuContext.current()
        spmd = active is not None and active.is_spmd

        extracted = self._pre_process_data(dataset, for_fit=True)
        feats = extracted.features
        if hasattr(feats, "todense"):
            feats = np.asarray(feats.todense())
        feats = np.asarray(feats, dtype=np.float32)
        labels = extracted.label

        frac = float(self.getSampleFraction())
        if frac < 1.0:
            seed = self.getRandomState()
            # rank-distinct subsample stream; the union is gathered below
            rank_salt = active.rank if spmd else 0
            rng = np.random.default_rng((int(seed) if seed is not None else 0) + rank_salt)
            keep = rng.random(feats.shape[0]) < frac
            feats = feats[keep]
            labels = labels[keep] if labels is not None else None

        if spmd:
            # the reference fits UMAP on ONE node and broadcasts the model
            # (umap.py:830-909). SPMD analog: rendezvous-gather the (sampled)
            # blocks, then every rank runs the IDENTICAL seeded fit on its
            # LOCAL devices — same data + same seed = the same model
            # everywhere, no broadcast needed.
            import jax

            from ..parallel.context import allgather_concat

            feats, _ = allgather_concat(active.rendezvous, feats)
            if labels is not None:
                labels, _ = allgather_concat(active.rendezvous, np.asarray(labels))
            local_devs = jax.local_devices()
        else:
            local_devs = None

        sp = self._solver_params
        pre_knn = sp.get("precomputed_knn")
        if pre_knn is not None and (frac < 1.0 or spmd):
            # the pair indexes the caller's row order; subsampling or the
            # SPMD gather reorders rows out from under it (the reference has
            # the same single-node constraint for precomputed graphs)
            raise ValueError(
                "precomputed_knn cannot be combined with sample_fraction < 1 "
                "or a multi-process SPMD fit"
            )
        n_dev = (
            len(local_devs) if local_devs is not None
            else min(self.num_workers, len(default_devices()))
        )
        with dtype_scope(np.float32):
            state = umap_fit(
                feats,
                labels,
                mesh=get_mesh(n_dev, devices=local_devs),
                n_neighbors=int(float(sp["n_neighbors"])),
                n_components=int(sp["n_components"]),
                n_epochs=sp["n_epochs"],
                learning_rate=float(sp["learning_rate"]),
                init=sp["init"],
                min_dist=float(sp["min_dist"]),
                spread=float(sp["spread"]),
                set_op_mix_ratio=float(sp["set_op_mix_ratio"]),
                local_connectivity=float(sp["local_connectivity"]),
                repulsion_strength=float(sp["repulsion_strength"]),
                negative_sample_rate=int(sp["negative_sample_rate"]),
                a=sp["a"],
                b=sp["b"],
                random_state=sp["random_state"],
                precomputed_knn=pre_knn,
                metric=str(sp["metric"]),
            )
        model = UMAPModel(
            embedding_=state["embedding_"],
            raw_data_=feats,
            a_=float(state["a_"]),
            b_=float(state["b_"]),
            n_cols=extracted.n_cols,
            dtype="float32",
        )
        self._copyValues(model)
        self._copy_solver_params(model)
        return [model]

    def _create_model(self, attrs):  # pragma: no cover - _fit_internal overridden
        return UMAPModel(**attrs)

    def _pre_process_data(self, dataset: Any, for_fit: bool = True) -> ExtractedData:
        # label is OPTIONAL for UMAP (supervised only when labelCol is
        # EXPLICITLY set — the mixin default 'label' must not force it;
        # reference umap.py:940-950)
        self._supervised = for_fit and self.hasParam("labelCol") and self.isSet("labelCol")
        try:
            return super()._pre_process_data(dataset, for_fit=for_fit)
        finally:
            self._supervised = False


class UMAPModel(_UMAPParams, _TpuModel):
    """Fitted UMAP model holding (embedding_, raw_data_) like the reference's
    broadcast pair (umap.py:1118-1155)."""

    def __init__(
        self,
        embedding_: Optional[np.ndarray] = None,
        raw_data_: Optional[np.ndarray] = None,
        a_: float = 1.577,
        b_: float = 0.895,
        n_cols: int = 0,
        dtype: str = "float32",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            embedding_=embedding_, raw_data_=raw_data_, a_=a_, b_=b_,
            n_cols=n_cols, dtype=dtype,
        )
        self.embedding_ = np.asarray(embedding_, dtype=np.float32)
        self.raw_data_ = np.asarray(raw_data_, dtype=np.float32)
        self.a_ = float(a_)
        self.b_ = float(b_)
        self.n_cols = int(n_cols)
        self.dtype = dtype

    @property
    def embedding(self) -> List[List[float]]:
        return self.embedding_.tolist()

    @property
    def raw_data(self) -> List[List[float]]:
        return self.raw_data_.tolist()

    def transform(self, dataset: Any):
        """Embed new rows against the trained embedding. Output matches the
        reference's transform schema: (features, <outputCol>) columns
        (reference umap.py:1082-1096)."""
        import pandas as pd

        from ..ops.umap import umap_transform
        from ..parallel import TpuContext, get_mesh
        from ..parallel.mesh import default_devices, dtype_scope

        extracted = self._pre_process_data(dataset, for_fit=False)
        feats = extracted.features
        if hasattr(feats, "todense"):
            feats = np.asarray(feats.todense())
        feats = np.asarray(feats, dtype=np.float32)
        sp = self._solver_params
        active = TpuContext.current()
        if active is not None and active.is_spmd:
            # distributed transform (reference umap.py:1161-1230): each rank
            # embeds its LOCAL rows against the frozen model on its own devices
            import jax

            local_devs = jax.local_devices()
            mesh = get_mesh(len(local_devs), devices=local_devs)
        else:
            mesh = get_mesh(min(self.num_workers, len(default_devices())))
        with dtype_scope(np.float32):
            emb = umap_transform(
                feats,
                self.raw_data_,
                self.embedding_,
                mesh=mesh,
                n_neighbors=int(float(sp["n_neighbors"])),
                n_epochs=sp["n_epochs"],
                learning_rate=float(sp["learning_rate"]),
                local_connectivity=float(sp["local_connectivity"]),
                repulsion_strength=float(sp["repulsion_strength"]),
                negative_sample_rate=int(sp["negative_sample_rate"]),
                a=self.a_,
                b=self.b_,
                random_state=sp["random_state"],
                metric=str(sp["metric"]),
            )
        return pd.DataFrame(
            {"features": list(feats), self.getOutputCol(): list(emb)}
        )

    def _pre_process_data(self, dataset: Any, for_fit: bool = True) -> ExtractedData:
        self._supervised = False
        return super()._pre_process_data(dataset, for_fit=for_fit)

    # numpy-sidecar persistence (reference umap.py:1262-1327) ---------------
    def write(self) -> "_UMAPWriterNumpy":
        return _UMAPWriterNumpy(self)

    @classmethod
    def read(cls) -> "_UMAPReaderNumpy":
        return _UMAPReaderNumpy(cls)


class _UMAPWriterNumpy(_TpuWriter):
    """Same metadata layout as `_TpuWriter`; large arrays go to .npy sidecars
    under data/ instead of the npz bundle (reference _CumlModelWriterNumpy,
    umap.py:1262-1300)."""

    def _write_model_attributes(self, inst: Any, path: str) -> None:
        data_path = os.path.join(path, "data")
        os.makedirs(data_path, exist_ok=True)
        attrs: Dict[str, Any] = {}
        for key, value in inst._model_attributes.items():
            if isinstance(value, np.ndarray):
                np.save(os.path.join(data_path, f"{key}.npy"), value)
                attrs[key] = {"__npy__": f"{key}.npy"}
            else:
                attrs[key] = value
        with open(os.path.join(data_path, "attributes.json"), "w") as f:
            json.dump(attrs, f, default=_np_default)


class _UMAPReaderNumpy(_TpuReader):
    def _read_model_attributes(self, path: str) -> Dict[str, Any]:
        data_path = os.path.join(path, "data")
        with open(os.path.join(data_path, "attributes.json")) as f:
            attrs = json.load(f)
        for key, value in list(attrs.items()):
            if isinstance(value, dict) and "__npy__" in value:
                attrs[key] = np.load(os.path.join(data_path, value["__npy__"]))
        return attrs
