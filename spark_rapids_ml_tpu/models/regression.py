#
# Regression algorithms: LinearRegression (+Ridge/Lasso/ElasticNet via params).
# RandomForestRegressor joins this module when the tree family lands
# (mirroring reference regression.py which hosts both).
#
# API-parity target: reference regression.py:176-797, drop-in for
# `pyspark.ml.regression.LinearRegression`. Solver selection by reg params
# matches the reference (regression.py:510-548): OLS / Ridge(alpha·m) / CD.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import FitInputs, _TpuEstimatorSupervised, _TpuModelWithColumns, pred
from ..data import ExtractedData
from ..params import (
    HasElasticNetParam,
    HasEnableSparseDataOptim,
    HasFeaturesCol,
    HasFeaturesCols,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasRegParam,
    HasStandardization,
    HasTol,
    HasWeightCol,
    Param,
    TypeConverters,
)


from .tree import _RandomForestEstimator, _RandomForestModel


class RandomForestRegressor(_RandomForestEstimator):
    """RandomForestRegressor, drop-in for
    ``pyspark.ml.regression.RandomForestRegressor`` (reference
    regression.py:799-1080). Variance split criterion; ensemble split across
    the mesh like the classifier."""

    _is_classification = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._setDefault(impurity="variance")
        if self._solver_params.get("split_criterion") is None:
            self._solver_params["split_criterion"] = "variance"

    def _set_params(self, **kwargs):
        if "impurity" in kwargs and kwargs["impurity"] != "variance":
            raise ValueError("impurity must be 'variance' for regression")
        return super()._set_params(**kwargs)

    def _create_model(self, attrs: Dict[str, Any]) -> "RandomForestRegressionModel":
        return RandomForestRegressionModel(**attrs)


class RandomForestRegressionModel(_RandomForestModel):
    """Fitted RF regression model."""

    _is_classification = False

    def _leaf_values(self) -> np.ndarray:
        # node mean: Σwy / Σw, kept as [M, 1]
        w = self.node_stats[..., 0]
        wy = self.node_stats[..., 1]
        return (wy / np.maximum(w, 1e-30))[..., None]

    def _out_column_names(self) -> List[str]:
        return [self.getOrDefault("predictionCol")]

    def _split_output(self, result, names, extracted):
        return {names[0]: np.asarray(result)[:, 0]}

    def predict(self, value) -> float:
        from ..linalg import Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        return float(np.asarray(self._raw_forest_output(v[None, :]))[0, 0])


class _LinearRegressionParams(
    HasEnableSparseDataOptim,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasMaxIter,
    HasTol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasWeightCol,
):
    solver = Param("solver", "solver algorithm: 'auto', 'normal' or 'eig'", TypeConverters.toString)
    loss = Param("loss", "loss function: only 'squaredError'", TypeConverters.toString)

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # mirrors reference regression.py param mapping
        return {
            "maxIter": "max_iter",
            "regParam": "alpha",
            "elasticNetParam": "l1_ratio",
            "tol": "tol",
            "fitIntercept": "fit_intercept",
            "standardization": "normalize",
            "solver": "solver",
            "loss": "loss",
            "weightCol": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        def _solver(v):
            return {"auto": "eig", "normal": "eig", "eig": "eig"}.get(v)

        def _loss(v):
            return "squared_loss" if v in ("squaredError", "squared_loss") else None

        return {"solver": _solver, "loss": _loss}

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {
            "alpha": 0.0001,
            "l1_ratio": 0.0,
            "fit_intercept": True,
            "normalize": False,
            "max_iter": 1000,
            "tol": 1e-3,
            "solver": "eig",
            "loss": "squared_loss",
            "verbose": False,
            # per-estimator override of config["solver_precision"]; "bf16"
            # runs the sufficient-statistics gram contraction bf16-in /
            # f32-accumulate; the replicated solve stays full precision
            "solver_precision": None,
        }


class LinearRegression(_LinearRegressionParams, _TpuEstimatorSupervised):
    """LinearRegression estimator, drop-in for ``pyspark.ml.regression.LinearRegression``.

    One distributed pass builds the normal-equation sufficient statistics
    (XᵀWX/XᵀWy psum across the rows mesh); OLS/Ridge solve locally, L1/EN runs
    gram-space coordinate descent — no further passes over the data. The Ridge
    path scales alpha by Σw for Spark objective parity (reference
    regression.py:536-542).
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            maxIter=100, regParam=0.0, elasticNetParam=0.0, tol=1e-6,
            fitIntercept=True, standardization=True, solver="auto", loss="squaredError",
        )
        self._set_params(**kwargs)

    def setMaxIter(self, value: int) -> "LinearRegression":
        return self._set_params(maxIter=value)

    def setRegParam(self, value: float) -> "LinearRegression":
        return self._set_params(regParam=value)

    def setElasticNetParam(self, value: float) -> "LinearRegression":
        return self._set_params(elasticNetParam=value)

    def setTol(self, value: float) -> "LinearRegression":
        return self._set_params(tol=value)

    def setFitIntercept(self, value: bool) -> "LinearRegression":
        return self._set_params(fitIntercept=value)

    def setStandardization(self, value: bool) -> "LinearRegression":
        return self._set_params(standardization=value)

    def setLoss(self, value: str) -> "LinearRegression":
        return self._set_params(loss=value)

    def setFeaturesCol(self, value) -> "LinearRegression":
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setLabelCol(self, value: str) -> "LinearRegression":
        return self._set_params(labelCol=value)

    def setPredictionCol(self, value: str) -> "LinearRegression":
        return self._set_params(predictionCol=value)

    def setWeightCol(self, value: str) -> "LinearRegression":
        return self._set_params(weightCol=value)

    # fit is one pure SPMD program over (X, y, w): correct under multi-process
    _supports_multiprocess = True
    # CSR fits via the padded-ELL gram accumulation (ops/linear.py
    # linear_fit_ell) with full dense parity — centering happens on the
    # sufficient statistics, never the data
    _supports_sparse_input = True
    # sufficient statistics are accumulable over row chunks: an over-HBM
    # dataset demotes to ops/streaming.linear_fit_streaming (dense + ELL)
    _supports_streaming_fit = True

    def _solver_workspace_terms(
        self, rows_per_device: int, n_cols: int, params: Dict[str, Any], itemsize: int
    ) -> Dict[str, int]:
        # the replicated normal-equation solve: gram (d,d) + the handful of
        # d-vectors of the sufficient-statistics tuple (sx, c, scale, coef)
        return {
            "gram": n_cols * n_cols * itemsize,
            "vectors": 4 * n_cols * itemsize,
        }

    def _solver_flop_estimate(self, n_rows: int, n_cols: int) -> Optional[float]:
        # normal-equation roofline model (ops_plane/efficiency.py): the
        # XᵀX gram accumulation (2·n·d²) plus Xᵀy (2·n·d); the (d,d) solve
        # and any elastic-net CD sweeps over the gram are O(d²·iters) and
        # omitted — with n ≫ d this is a tight lower bound on the work.
        return 2.0 * n_rows * n_cols * (n_cols + 1)

    def _get_tpu_fit_func(self, extracted: ExtractedData):
        from .. import checkpoint as _ckpt
        from ..ops.linear import (
            linear_fit,
            linear_fit_checkpointed,
            linear_fit_ell,
            linear_fit_ell_checkpointed,
        )

        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            alpha = float(params["alpha"])
            l1_ratio = float(params["l1_ratio"])
            use_cd = bool(alpha > 0 and l1_ratio > 0)
            from ..core import resolve_solver_precision

            common = dict(
                alpha=alpha,
                l1_ratio=l1_ratio,
                fit_intercept=bool(params["fit_intercept"]),
                standardize=bool(params.get("normalize", False)),
                use_cd=use_cd,
                max_iter=int(params["max_iter"]),
                tol=float(params["tol"]),
                # static of every linear entry point (and of the retained-
                # statistics checkpoint key: bf16 stats are keyed apart)
                fast=resolve_solver_precision(params) == "bf16",
            )
            if inputs.stream is not None:
                # out-of-core: one streamed statistics pass, same replicated
                # solve (docs/robustness.md "Memory safety")
                from ..ops.streaming import linear_fit_streaming

                state = linear_fit_streaming(inputs, **common)
                return {
                    "coef_": np.asarray(state["coef_"]),
                    "intercept_": float(state["intercept_"]),
                    "n_iter_": int(state["n_iter_"]),
                    "n_cols": inputs.n_cols,
                    "dtype": np.dtype(inputs.dtype).name,
                }
            # elastic recovery: retain the sufficient statistics (the one
            # data pass) on host so a transient retry — and every further
            # sequential param set in this fit stage — solves without
            # another pass over the data. The stats never depend on
            # alpha/l1_ratio, so one key serves the whole sweep.
            use_ckpt = _ckpt.solver_checkpoints_active() and (
                inputs.ctx is None or not inputs.ctx.is_spmd
            )
            ckpt_common = (
                dict(placement_key=_ckpt.placement_key_of(inputs))
                if use_ckpt
                else {}
            )
            if inputs.X_sparse is not None:
                ell_val, ell_idx = inputs.ell_rows()
                fit_fn = linear_fit_ell_checkpointed if use_ckpt else linear_fit_ell
                state = fit_fn(
                    ell_val,
                    ell_idx,
                    inputs.put_rows(np.asarray(inputs.y, dtype=inputs.dtype)),
                    inputs.put_rows(np.asarray(inputs.w, dtype=inputs.dtype)),
                    d=inputs.n_cols,
                    **common,
                    **ckpt_common,
                )
            else:
                fit_fn = linear_fit_checkpointed if use_ckpt else linear_fit
                state = fit_fn(inputs.X, inputs.y, inputs.w, **common, **ckpt_common)
            return {
                "coef_": np.asarray(state["coef_"]),
                "intercept_": float(state["intercept_"]),
                "n_iter_": int(state["n_iter_"]),
                "n_cols": inputs.n_cols,
                "dtype": np.dtype(inputs.dtype).name,
            }

        return _fit

    def _batch_group_key(self, sp: Dict[str, Any]):
        # regParam (alpha) and elasticNetParam (l1_ratio) are TRACED scalars
        # of the normal-equation / gram-CD solve; the solver choice use_cd is
        # a derived STATIC, so grids mixing elastic-net and ridge/OLS points
        # split into one batched program per solver. A whole batched grid
        # costs ONE sufficient-statistics pass over the data.
        use_cd = float(sp["alpha"]) > 0 and float(sp["l1_ratio"]) > 0
        rest = tuple(sorted((k, repr(v)) for k, v in sp.items() if k not in ("alpha", "l1_ratio")))
        return (use_cd, rest)

    def _get_tpu_batched_fit_func(self, extracted: ExtractedData):
        from ..ops.linear import linear_fit_batched, linear_fit_ell_batched

        def _fit_batch(inputs: FitInputs, param_sets) -> Optional[list]:
            alphas = np.asarray([float(sp["alpha"]) for sp in param_sets], dtype=inputs.dtype)
            l1rs = np.asarray([float(sp["l1_ratio"]) for sp in param_sets], dtype=inputs.dtype)
            p0 = param_sets[0]  # statics are uniform per group key
            from ..core import resolve_solver_precision

            common = dict(
                fit_intercept=bool(p0["fit_intercept"]),
                standardize=bool(p0.get("normalize", False)),
                use_cd=bool(alphas[0] > 0 and l1rs[0] > 0),
                max_iter=int(p0["max_iter"]),
                tol=float(p0["tol"]),
                fast=resolve_solver_precision(p0) == "bf16",
            )
            if inputs.X_sparse is not None:
                ell_val, ell_idx = inputs.ell_rows()
                stacked = linear_fit_ell_batched(
                    ell_val,
                    ell_idx,
                    inputs.put_rows(np.asarray(inputs.y, dtype=inputs.dtype)),
                    inputs.put_rows(np.asarray(inputs.w, dtype=inputs.dtype)),
                    alphas, l1rs, d=inputs.n_cols, **common,
                )
            else:
                stacked = linear_fit_batched(
                    inputs.X, inputs.y, inputs.w, alphas, l1rs, **common
                )
            stacked = {k: np.asarray(v) for k, v in stacked.items()}  # ONE fetch
            return [
                {
                    "coef_": stacked["coef_"][i],
                    "intercept_": float(stacked["intercept_"][i]),
                    "n_iter_": int(stacked["n_iter_"][i]),
                    "n_cols": inputs.n_cols,
                    "dtype": np.dtype(inputs.dtype).name,
                }
                for i in range(len(param_sets))
            ]

        return _fit_batch

    def _create_model(self, attrs: Dict[str, Any]) -> "LinearRegressionModel":
        return LinearRegressionModel(**attrs)

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        if not hasattr(evaluator, "getMetricName"):
            return False
        if evaluator.getMetricName() not in ("rmse", "mse", "r2", "mae", "var"):
            return False
        # weighted evaluation must take the fallback path (the fused pass
        # produces unweighted sufficient stats)
        if evaluator.hasParam("weightCol") and evaluator.isDefined("weightCol"):
            return False
        return True


class LinearRegressionModel(_LinearRegressionParams, _TpuModelWithColumns):
    """Fitted linear regression model (reference regression.py:616-797)."""

    def __init__(
        self,
        coef_: Optional[np.ndarray] = None,
        intercept_: float = 0.0,
        n_iter_: int = 0,
        n_cols: int = 0,
        dtype: str = "float32",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            coef_=coef_, intercept_=intercept_, n_iter_=n_iter_, n_cols=n_cols, dtype=dtype
        )
        self.coef_ = np.asarray(coef_)
        self.intercept_ = float(intercept_)
        self.n_iter_ = int(n_iter_)
        self.n_cols = int(n_cols)
        self.dtype = dtype

    # -- Spark ML model surface -------------------------------------------
    @property
    def coefficients(self):
        from ..linalg import DenseVector

        return DenseVector(self.coef_)

    @property
    def intercept(self) -> float:
        return self.intercept_

    @property
    def numFeatures(self) -> int:
        return self.n_cols

    @property
    def hasSummary(self) -> bool:
        return False

    @property
    def scale(self) -> float:
        """Huber loss is unsupported (squaredError only); 1.0 for API
        compatibility (reference regression.py:699-703)."""
        return 1.0

    def evaluate(self, dataset):
        """Evaluate on a dataset via the converted JVM model's summary
        (reference regression.py:711-715). Accepts framework datasets
        (pandas/arrow/dict) or a Spark DataFrame."""
        from ..spark_interop import as_spark_df

        return self.cpu().evaluate(as_spark_df(dataset))

    def setFeaturesCol(self, value) -> "LinearRegressionModel":
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str) -> "LinearRegressionModel":
        return self._set_params(predictionCol=value)

    def predict(self, value) -> float:
        """Single-vector predict (Spark ML model surface)."""
        from ..linalg import Vector

        v = value.toArray() if isinstance(value, Vector) else np.asarray(value)
        return float(v @ self.coef_ + self.intercept_)

    _spark_converter = "linreg_to_spark"  # `.cpu()` (reference regression.py:658-672)

    def _out_column_names(self) -> List[str]:
        return [self.getOrDefault("predictionCol")]

    # -- fused CV path (reference regression.py:762-785, 90-142) -----------
    def _combine(self, models: List["LinearRegressionModel"]) -> "LinearRegressionModel":
        """Pack N fitted models into one multi-model (coef_ stacked [m, d])."""
        combined = LinearRegressionModel(
            coef_=np.stack([m.coef_ for m in models]),
            intercept_=0.0,
            n_iter_=self.n_iter_,
            n_cols=self.n_cols,
            dtype=self.dtype,
        )
        combined._intercepts = np.asarray([m.intercept_ for m in models])
        self._copyValues(combined)
        self._copy_solver_params(combined)
        return combined

    def _transform_evaluate(self, dataset: Any, evaluator: Any) -> List[float]:
        """Score ALL packed models in one pass over a DATASET (extracts the
        feature block, then delegates to `_transform_evaluate_arrays`)."""
        from ..core import evaluator_label_column
        from ..data import as_pandas

        extracted = self._pre_process_data(dataset, for_fit=False)
        # the evaluator's labelCol governs scoring (it may differ from the model's)
        label = as_pandas(dataset)[evaluator_label_column(self, evaluator)].to_numpy(
            dtype=np.float64
        )
        return self._transform_evaluate_arrays(extracted.features, label, evaluator)

    def _transform_evaluate_arrays(
        self, features: Any, label: np.ndarray, evaluator: Any
    ) -> List[float]:
        """Score ALL packed models over already-extracted blocks: predictions
        [n, m] via a single MXU matmul, then per-model regression sufficient
        stats. The array entry point exists so CrossValidator can score a
        held-out fold by SLICING the one ingested block instead of
        round-tripping the fold through pandas and re-extracting it."""
        from ..metrics import RegressionMetrics

        assert self.coef_.ndim == 2 and hasattr(self, "_intercepts"), "call _combine first"
        feats = features
        if hasattr(feats, "todense"):
            feats = np.asarray(feats.todense())
        preds = np.asarray(feats, dtype=np.float64) @ self.coef_.T + self._intercepts[None, :]  # [n, m]
        return [
            RegressionMetrics.from_values(label, preds[:, j]).evaluate(evaluator)
            for j in range(preds.shape[1])
        ]

    def _get_transform_func(self):
        import jax

        from ..ops.linear import linear_predict
        from ..parallel.mesh import default_local_device

        coef = self.coef_
        intercept = self.intercept_
        dtype = np.float32 if self._float32_inputs else np.float64

        def construct():
            dev = default_local_device()
            return (
                jax.device_put(coef.astype(dtype), dev),
                jax.device_put(np.asarray(intercept, dtype=dtype), dev),
            )

        def predict(state, xb):
            c, b = state
            return linear_predict(xb.astype(dtype), c, b)

        return construct, predict, None

    def _serve_workspace_terms(self, bucket_rows_count, itemsize):
        # per-bucket predict workspace (docs/serving.md): one prediction
        # scalar per row
        return {"pred": int(bucket_rows_count) * itemsize}

    def _serve_flop_estimate(self, n_rows, n_cols):
        # roofline numerator: the X @ coef dot per row (2*n*d)
        return 2.0 * n_rows * n_cols
