#
# Shared random-forest machinery (reference tree.py, 636 LoC): params common to
# classifier/regressor, the ensemble-split fit orchestration, and the
# array-forest model base. Subclasses live in classification.py/regression.py,
# mirroring the reference layout.
#
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import FitInputs, _TpuEstimatorSupervised, _TpuModelWithColumns
from ..data import ExtractedData
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasSeed,
    HasWeightCol,
    Param,
    TypeConverters,
)


def resolve_max_features(strategy: str, d: int, is_classification: bool) -> int:
    """featureSubsetStrategy -> number of features per split (Spark semantics)."""
    s = str(strategy).lower()
    if s == "auto":
        return max(1, int(math.sqrt(d))) if is_classification else max(1, d // 3)
    if s == "all":
        return d
    if s == "sqrt":
        return max(1, int(math.sqrt(d)))
    if s == "log2":
        return max(1, int(math.log2(d)))
    if s == "onethird":
        return max(1, d // 3)
    import re

    # Spark's grammar: "^[1-9]\d*$" is a feature COUNT; "(0.0, 1.0]" decimals
    # are a fraction — so "1.0" means ALL features, "1" means one feature
    if re.fullmatch(r"[1-9]\d*", s):
        return min(d, int(s))
    try:
        v = float(s)
        if 0 < v <= 1:
            return max(1, int(v * d))
    except ValueError:
        pass
    raise ValueError(f"Unsupported featureSubsetStrategy: {strategy!r}")


class _RandomForestParams(
    HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasPredictionCol, HasSeed, HasWeightCol
):
    numTrees = Param("numTrees", "number of trees in the forest", TypeConverters.toInt)
    maxDepth = Param("maxDepth", "maximum tree depth", TypeConverters.toInt)
    maxBins = Param("maxBins", "maximum number of feature histogram bins", TypeConverters.toInt)
    minInstancesPerNode = Param(
        "minInstancesPerNode", "minimum number of instances each child must have", TypeConverters.toInt
    )
    minInfoGain = Param("minInfoGain", "minimum information gain for a split", TypeConverters.toFloat)
    featureSubsetStrategy = Param(
        "featureSubsetStrategy",
        "number of features per split: auto|all|sqrt|log2|onethird|n|fraction",
        TypeConverters.toString,
    )
    subsamplingRate = Param("subsamplingRate", "fraction of rows sampled per tree", TypeConverters.toFloat)
    bootstrap = Param("bootstrap", "whether bootstrap samples are used", TypeConverters.toBoolean)
    impurity = Param("impurity", "split criterion", TypeConverters.toString)
    # accepted-and-ignored Spark knobs (reference maps these to "" the same way)
    checkpointInterval = Param("checkpointInterval", "ignored", TypeConverters.toInt)
    cacheNodeIds = Param("cacheNodeIds", "ignored", TypeConverters.toBoolean)
    maxMemoryInMB = Param("maxMemoryInMB", "ignored", TypeConverters.toInt)

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # mirrors reference tree.py param mapping
        return {
            "numTrees": "n_estimators",
            "maxDepth": "max_depth",
            "maxBins": "n_bins",
            "minInstancesPerNode": "min_samples_leaf",
            "minInfoGain": "min_impurity_decrease",
            "featureSubsetStrategy": "max_features",
            "subsamplingRate": "max_samples",
            "bootstrap": "bootstrap",
            "impurity": "split_criterion",
            "seed": "random_state",
            "checkpointInterval": "",
            "cacheNodeIds": "",
            "maxMemoryInMB": "",
            "weightCol": "",
        }

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {
            "n_estimators": 20,
            "max_depth": 5,
            "n_bins": 32,
            "min_samples_leaf": 1,
            "min_impurity_decrease": 0.0,
            "max_features": "auto",
            "max_samples": 1.0,
            "bootstrap": True,
            "split_criterion": None,  # set by subclass default
            "random_state": 0,
            "node_chunk": 256,
            "verbose": False,
        }

    def getNumTrees(self) -> int:
        return self.getOrDefault("numTrees")

    def getMaxDepth(self) -> int:
        return self.getOrDefault("maxDepth")


class _RandomForestEstimator(_RandomForestParams, _TpuEstimatorSupervised):
    """Shared fit orchestration (reference tree.py:240-431)."""

    _is_classification: bool = False
    # ensemble-split growth is per-device-local by design; the host-side state
    # (class set, quantile bin edges) is rendezvous-merged in _get_tpu_fit_func.
    # Like the reference's cuRF, the exact trees depend on the partition layout
    # (bootstrap draws are keyed per device) — parity across rank counts is
    # statistical, not bitwise.
    _supports_multiprocess = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            numTrees=20, maxDepth=5, maxBins=32, minInstancesPerNode=1, minInfoGain=0.0,
            featureSubsetStrategy="auto", subsamplingRate=1.0, bootstrap=True, seed=0,
        )
        self._set_params(**kwargs)

    # common setters (each subclass also exposes them through this base)
    def setNumTrees(self, value: int):
        return self._set_params(numTrees=value)

    def setMaxDepth(self, value: int):
        return self._set_params(maxDepth=value)

    def setMaxBins(self, value: int):
        return self._set_params(maxBins=value)

    def setFeatureSubsetStrategy(self, value: str):
        return self._set_params(featureSubsetStrategy=value)

    def setImpurity(self, value: str):
        return self._set_params(impurity=value)

    def setSeed(self, value: int):
        return self._set_params(seed=value)

    def setFeaturesCol(self, value):
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setLabelCol(self, value: str):
        return self._set_params(labelCol=value)

    def setPredictionCol(self, value: str):
        return self._set_params(predictionCol=value)

    def _row_stats(self, labels: np.ndarray, classes: np.ndarray) -> np.ndarray:
        """Per-row stat contributions: class one-hot (clf) or (1, y, y²) (reg)."""
        if self._is_classification:
            idx = np.searchsorted(classes, labels)
            stats = np.zeros((len(labels), len(classes)), np.float32)
            stats[np.arange(len(labels)), idx] = 1.0
            return stats
        y = labels.astype(np.float64)
        return np.stack([np.ones_like(y), y, y * y], axis=1).astype(np.float32)

    def _get_tpu_fit_func(self, extracted: ExtractedData):
        from ..ops.trees import bin_features, forest_fit, quantile_bins, split_bins_to_thresholds

        x_host = extracted.features
        labels_host = extracted.label

        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            d = inputs.n_cols
            max_bins = int(params["n_bins"])
            max_depth = int(params["max_depth"])
            seed = int(params["random_state"] or 0)
            if self._is_classification:
                # class set must be GLOBAL (a rank may hold a label subset)
                import json

                local_classes = np.unique(labels_host).astype(np.float64)
                gathered = inputs.allgather_host(json.dumps(local_classes.tolist()))
                classes = np.unique(
                    np.concatenate([np.asarray(json.loads(g)) for g in gathered])
                )
            else:
                classes = np.zeros(0)
            impurity = params["split_criterion"]
            # quantile sketch rows must be GLOBAL too: each rank contributes a
            # bounded sample, all ranks derive IDENTICAL bin edges from the
            # union (cuRF's distributed quantile computation analog)
            x_sketch = x_host
            if inputs.ctx is not None and inputs.ctx.is_spmd:
                cap = 100_000 // inputs.ctx.nranks
                n_loc = x_host.shape[0]
                if n_loc > cap:
                    rs = np.random.default_rng(seed * 99_991 + inputs.ctx.rank)  # prng-ok: deliberate per-rank sampling of LOCAL sketch rows; the allgather below gives every rank the identical union, so all ranks derive the same bin edges
                    sel = np.sort(rs.choice(n_loc, cap, replace=False))
                    x_sketch = inputs.allgather_array(np.asarray(x_host[sel], dtype=np.float64))
                else:
                    x_sketch = inputs.allgather_array(np.asarray(x_host, dtype=np.float64))
            edges_host = quantile_bins(x_sketch, max_bins, seed=seed)
            edges = edges_host.astype(np.float32)
            stats_host = self._row_stats(labels_host, classes)

            # bin the ALREADY device-resident features (inputs.X carries the
            # user weights + padding zeros in inputs.w); user weights scale each
            # row's histogram contribution and the bootstrap draw inside
            # forest_fit multiplies on top
            Xb_binned = bin_features(inputs.X, edges)
            w = inputs.w
            stats_global = inputs.put_rows(stats_host)

            state = forest_fit(
                Xb_binned,
                stats_global * w[:, None],
                w,
                int(params["random_state"] or 0),
                mesh=inputs.mesh,
                n_trees=int(params["n_estimators"]),
                max_depth=max_depth,
                max_bins=max_bins,
                max_features=resolve_max_features(params["max_features"], d, self._is_classification),
                impurity=impurity,
                node_chunk=int(params["node_chunk"]),
                bootstrap=bool(params["bootstrap"]),
                subsample_rate=float(params["max_samples"]),
                min_instances=float(params["min_samples_leaf"]),
                min_info_gain=float(params["min_impurity_decrease"]),
                n_stats=stats_host.shape[1],
            )
            n_trees = int(params["n_estimators"])
            feature = np.asarray(state["feature"])[:n_trees]
            split_bin = np.asarray(state["split_bin"])[:n_trees]
            node_stats = np.asarray(state["node_stats"], dtype=np.float64)[:n_trees]
            threshold = split_bins_to_thresholds(feature, split_bin, edges_host)
            node_stats = _fill_empty_nodes(feature, node_stats)
            return {
                "feature": feature.astype(np.int32),
                "threshold": threshold,
                "node_stats": node_stats,
                "classes_": classes,
                "num_trees": n_trees,
                "max_depth": max_depth,
                "n_cols": d,
                "dtype": np.dtype(inputs.dtype).name,
            }

        return _fit


def _fill_empty_nodes(feature: np.ndarray, node_stats: np.ndarray) -> np.ndarray:
    """Propagate parent stats into empty nodes so predict-time rows landing in a
    training-empty branch fall back to the parent distribution."""
    T, M, S = node_stats.shape
    out = node_stats.copy()
    for i in range(1, M):
        parent = (i - 1) // 2
        empty = out[:, i, :].sum(axis=1) == 0
        out[empty, i, :] = out[empty, parent, :]
    return out


class _RandomForestModel(_RandomForestParams, _TpuModelWithColumns):
    """Array-forest model base (reference tree.py:433-636)."""

    _is_classification: bool = False

    def __init__(
        self,
        feature: Optional[np.ndarray] = None,
        threshold: Optional[np.ndarray] = None,
        node_stats: Optional[np.ndarray] = None,
        classes_: Optional[np.ndarray] = None,
        num_trees: int = 0,
        max_depth: int = 0,
        n_cols: int = 0,
        dtype: str = "float32",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            feature=feature, threshold=threshold, node_stats=node_stats, classes_=classes_,
            num_trees=num_trees, max_depth=max_depth, n_cols=n_cols, dtype=dtype,
        )
        self.feature = np.asarray(feature)
        self.threshold = np.asarray(threshold)
        self.node_stats = np.asarray(node_stats)
        self.classes_ = np.asarray(classes_)
        self.num_trees = int(num_trees)
        self.max_depth = int(max_depth)
        self.n_cols = int(n_cols)
        self.dtype = dtype

    @property
    def getNumTrees(self) -> int:  # Spark model exposes this as a property
        return self.num_trees

    @property
    def numFeatures(self) -> int:
        return self.n_cols

    @property
    def totalNumNodes(self) -> int:
        return int(np.sum(self.feature >= 0) * 2 + self.num_trees)

    def setFeaturesCol(self, value):
        return self._set_params(featuresCol=value) if isinstance(value, str) else self._set_params(featuresCols=value)

    def setPredictionCol(self, value: str):
        return self._set_params(predictionCol=value)

    def _leaf_values(self) -> np.ndarray:
        """Per-node output values fed to the traversal (subclass defines)."""
        raise NotImplementedError

    # -- Spark-interop surface (reference tree.py:524-569, utils.py:311-481:
    # featureImportances, per-tree JSON, debug dump) ------------------------

    def _node_impurity_weight(self, stats: np.ndarray):
        """(impurity [..., M], weight [..., M]) from node stats.

        Classification stats are per-class counts (gini/entropy from the
        distribution); regression stats are (n, Σy, Σy²) (variance)."""
        if self._is_classification:
            tot = stats.sum(axis=-1)
            p = stats / np.maximum(tot[..., None], 1e-30)
            if str(self._solver_params.get("split_criterion")) == "entropy":
                with np.errstate(divide="ignore", invalid="ignore"):
                    plogp = np.where(p > 0, p * np.log2(np.maximum(p, 1e-30)), 0.0)
                imp = -plogp.sum(axis=-1)
            else:  # gini
                imp = 1.0 - (p * p).sum(axis=-1)
            return imp, tot
        n = stats[..., 0]
        mean = stats[..., 1] / np.maximum(n, 1e-30)
        var = stats[..., 2] / np.maximum(n, 1e-30) - mean * mean
        return np.maximum(var, 0.0), n

    @property
    def featureImportances(self):
        """Impurity-gain feature importances, Spark semantics: per-node gain
        = w·imp − w_l·imp_l − w_r·imp_r accumulated by split feature,
        normalized per tree, averaged over trees, normalized again."""
        from ..linalg import DenseVector

        T, M = self.feature.shape
        imp, w = self._node_impurity_weight(self.node_stats.astype(np.float64))
        total = np.zeros(self.n_cols, dtype=np.float64)
        for t in range(T):
            per_tree = np.zeros(self.n_cols, dtype=np.float64)
            for i in range(M):
                f = int(self.feature[t, i])
                l, r = 2 * i + 1, 2 * i + 2
                if f < 0 or r >= M:
                    continue
                gain = w[t, i] * imp[t, i] - w[t, l] * imp[t, l] - w[t, r] * imp[t, r]
                per_tree[f] += max(gain, 0.0)
            s = per_tree.sum()
            if s > 0:
                total += per_tree / s
        s = total.sum()
        return DenseVector(total / s if s > 0 else total)

    def _tree_to_dict(self, t: int, i: int = 0, leaves: Optional[np.ndarray] = None):
        """Nested-dict form of tree `t` (the per-tree JSON parity of the
        reference's cuML model_json -> Spark tree translation). `leaves` is
        computed once per forest and threaded through the recursion."""
        if leaves is None:
            leaves = self._leaf_values()
        M = self.feature.shape[1]
        f = int(self.feature[t, i])
        if f < 0 or 2 * i + 2 >= M:
            value = leaves[t, i]
            return {"leaf_value": [float(v) for v in np.atleast_1d(value)]}
        return {
            "split_feature": f,
            "threshold": float(self.threshold[t, i]),
            "yes": self._tree_to_dict(t, 2 * i + 1, leaves),  # feature <= threshold
            "no": self._tree_to_dict(t, 2 * i + 2, leaves),
        }

    @property
    def trees(self):
        """List of per-tree nested dicts (portable serialization surface)."""
        leaves = self._leaf_values()
        return [self._tree_to_dict(t, 0, leaves) for t in range(self.num_trees)]

    def treesToJson(self) -> List[str]:
        import json

        return [json.dumps(t) for t in self.trees]

    # `.cpu()` (base `_TpuModel.cpu`): array forest -> genuine JVM
    # RandomForest model (reference tree.py:524-569 _convert_to_java_trees)
    _spark_converter = "rf_to_spark"

    def predictLeaf(self, value) -> float:
        """Leaf indices for a feature vector, via the converted JVM model —
        the reference delegates to `.cpu()` identically (tree.py:513-518).
        Accepts any row representation (numpy, list, framework or pyspark
        Vector) — py4j cannot marshal numpy arrays directly."""
        from ..spark_interop import to_spark_vector

        return self.cpu().predictLeaf(to_spark_vector(value))

    def toDebugString(self) -> str:
        """Spark-style textual dump of the forest."""
        lines = [
            f"{type(self).__name__}: numTrees={self.num_trees}, "
            f"numFeatures={self.n_cols}, totalNumNodes={self.totalNumNodes}"
        ]

        def walk(node, indent):
            pad = " " * indent
            if "leaf_value" in node:
                vals = node["leaf_value"]
                pretty = vals[0] if len(vals) == 1 else vals
                lines.append(f"{pad}Predict: {pretty}")
                return
            f, thr = node["split_feature"], node["threshold"]
            lines.append(f"{pad}If (feature {f} <= {thr})")
            walk(node["yes"], indent + 1)
            lines.append(f"{pad}Else (feature {f} > {thr})")
            walk(node["no"], indent + 1)

        for t, tree in enumerate(self.trees):
            lines.append(f"  Tree {t} (weight 1.0):")
            walk(tree, 4)
        return "\n".join(lines)

    def _raw_forest_output(self, features) -> np.ndarray:
        """Batched mean-of-leaf-values [n, S] through the shared batching."""
        return self._transform_arrays(features)

    def _get_transform_func(self):
        import jax

        from ..ops.trees import forest_raw_predict
        from ..parallel.mesh import default_local_device

        feature = self.feature
        threshold = self.threshold
        leaves = self._leaf_values()
        max_depth = self.max_depth
        dtype = np.float32 if self._float32_inputs else np.float64

        def construct():
            dev = default_local_device()
            return (
                jax.device_put(feature, dev),
                jax.device_put(threshold.astype(dtype), dev),
                jax.device_put(leaves.astype(dtype), dev),
            )

        def predict(state, xb):
            f, t, lv = state
            return forest_raw_predict(xb.astype(dtype), f, t, lv, max_depth=max_depth)

        return construct, predict, None
