#
# Feature algorithms: PCA.
#
# API-parity target: reference feature.py:106-447 (`PCA`/`PCAModel`), itself a
# drop-in for `pyspark.ml.feature.PCA`. The distributed strategy is identical in
# math (rank-local covariance contribution + allreduce + eig; SURVEY.md §2.2),
# but executed as one SPMD jit program over the rows mesh instead of a barrier
# stage of cuML MG calls.
#
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import FitInputs, _TpuEstimator, _TpuModelWithColumns
from ..data import ExtractedData
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    Param,
    TypeConverters,
)


class _PCAParams(HasInputCol, HasInputCols, HasFeaturesCol, HasFeaturesCols, HasOutputCol):
    k = Param("k", "the number of principal components", TypeConverters.toInt)

    def getK(self) -> int:
        return self.getOrDefault("k")

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # reference feature.py param mapping: Spark `k` -> cuml `n_components`
        return {"k": "n_components"}

    def _get_solver_params_default(self) -> Dict[str, Any]:
        # mirrors cuML PCA(MG) kwargs the reference exposes via cuml_params
        return {
            "n_components": 1,
            "svd_solver": "auto",
            "whiten": False,
            "verbose": False,
            # per-estimator override of config["solver_precision"]; "bf16"
            # runs the covariance contraction bf16-in/f32-accumulate; the
            # eigendecomposition and reported variances stay full precision
            "solver_precision": None,
        }


class PCA(_PCAParams, _TpuEstimator):
    """PCA estimator, drop-in for ``pyspark.ml.feature.PCA``.

    >>> PCA(k=2, inputCol="features").fit(df).transform(df)

    Distributed fit: single pass computing the weighted mean + d×d covariance
    with an MXU contraction per row shard and a GSPMD psum across chips, then a
    replicated top-k symmetric eig with sign canonicalization — the TPU-native
    equivalent of the reference's `PCAMG.fit(parts, m, n, parts_rank_size, rank)`
    (reference feature.py:222-241).
    """

    # fit is one pure SPMD program over (X, w): correct under multi-process
    _supports_multiprocess = True
    # the (mean, covariance) statistics are accumulable over row chunks: an
    # over-HBM dataset demotes to ops/streaming.pca_fit_streaming
    _supports_streaming_fit = True

    def _solver_workspace_terms(
        self, rows_per_device: int, n_cols: int, params: Dict[str, Any], itemsize: int
    ) -> Dict[str, int]:
        # replicated d x d covariance (+ eigenvector output of equal size)
        # and the mean / variance d-vectors
        return {
            "covariance": 2 * n_cols * n_cols * itemsize,
            "vectors": 2 * n_cols * itemsize,
        }

    def _solver_flop_estimate(self, n_rows: int, n_cols: int) -> Optional[float]:
        # PCA roofline model (ops_plane/efficiency.py): the covariance
        # einsum (2·n·d²) dominates; the d×d eigendecomposition (~9·d³) is
        # negligible at n ≫ d and omitted.
        return 2.0 * n_rows * n_cols * n_cols

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=1)
        self._set_params(**kwargs)

    def setK(self, value: int) -> "PCA":
        return self._set_params(k=value)

    def setInputCol(self, value: str) -> "PCA":
        return self._set_params(inputCol=value) if isinstance(value, str) else self._set_params(inputCols=value)

    def setInputCols(self, value: List[str]) -> "PCA":
        return self._set_params(inputCols=value)

    def setOutputCol(self, value: str) -> "PCA":
        return self._set_params(outputCol=value)

    def _get_tpu_fit_func(self, extracted: ExtractedData):
        from .. import checkpoint as _ckpt
        from ..ops.pca import (
            check_pca_state,
            pca_fit,
            pca_fit_checkpointed,
            record_pca_fit,
        )

        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            from ..core import resolve_solver_precision

            k = int(params["n_components"])
            fast = resolve_solver_precision(params) == "bf16"
            if k < 1:
                raise ValueError(f"k must be >= 1, got {k}")
            if k > inputs.n_cols:
                raise ValueError(f"k={k} exceeds the number of features {inputs.n_cols}")
            if inputs.stream is not None:
                # out-of-core: two streamed passes (mean, then centered
                # covariance), same finish kernel as the resident fit
                from ..ops.streaming import pca_fit_streaming

                state = pca_fit_streaming(inputs, k=k, fast=fast)
                out = {name: np.asarray(v) for name, v in state.items()}
                check_pca_state(out, k=k)
                record_pca_fit(out, k=k)
                out["n_cols"] = inputs.n_cols
                out["dtype"] = np.dtype(inputs.dtype).name
                return out
            # elastic recovery: retain the (mean, covariance) statistics so a
            # transient retry (or a k sweep in this stage) skips the data pass
            use_ckpt = _ckpt.solver_checkpoints_active() and (
                inputs.ctx is None or not inputs.ctx.is_spmd
            )
            if use_ckpt:
                state = pca_fit_checkpointed(
                    inputs.X, inputs.w, k=k, fast=fast,
                    placement_key=_ckpt.placement_key_of(inputs),
                )
            else:
                state = pca_fit(inputs.X, inputs.w, k=k, fast=fast)
            out = {name: np.asarray(v) for name, v in state.items()}
            check_pca_state(out, k=k)  # guard on the host-fetched attributes
            record_pca_fit(out, k=k)
            out["n_cols"] = inputs.n_cols
            out["dtype"] = np.dtype(inputs.dtype).name
            return out

        return _fit

    def _create_model(self, attrs: Dict[str, Any]) -> "PCAModel":
        return PCAModel(**attrs)


class PCAModel(_PCAParams, _TpuModelWithColumns):
    """Fitted PCA model (reference feature.py:281-447 `PCAModel`).

    Exposes both the Spark ML surface (``pc``, ``explainedVariance``, ``mean``)
    and the solver-native attributes (``components_`` etc.).
    """

    def __init__(
        self,
        mean_: Optional[np.ndarray] = None,
        components_: Optional[np.ndarray] = None,
        explained_variance_: Optional[np.ndarray] = None,
        explained_variance_ratio_: Optional[np.ndarray] = None,
        singular_values_: Optional[np.ndarray] = None,
        n_cols: int = 0,
        dtype: str = "float32",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            mean_=mean_,
            components_=components_,
            explained_variance_=explained_variance_,
            explained_variance_ratio_=explained_variance_ratio_,
            singular_values_=singular_values_,
            n_cols=n_cols,
            dtype=dtype,
        )
        self.mean_ = np.asarray(mean_)
        self.components_ = np.asarray(components_)
        self.explained_variance_ = np.asarray(explained_variance_)
        self.explained_variance_ratio_ = np.asarray(explained_variance_ratio_)
        self.singular_values_ = np.asarray(singular_values_)
        self.n_cols = int(n_cols)
        self.dtype = dtype
        self._setDefault(k=int(self.components_.shape[0]) if components_ is not None else 1)

    # -- Spark ML model surface -------------------------------------------
    @property
    def mean(self) -> List[float]:
        return self.mean_.tolist()

    @property
    def pc(self) -> np.ndarray:
        """Principal components as a d×k column matrix (Spark's DenseMatrix layout)."""
        return self.components_.T

    @property
    def explainedVariance(self) -> np.ndarray:
        """Variance ratio per component (Spark parity: ratio, not raw variance)."""
        return self.explained_variance_ratio_

    _spark_converter = "pca_to_spark"  # `.cpu()` (reference feature.py:365-379)

    def setInputCol(self, value: str) -> "PCAModel":
        return self._set_params(inputCol=value) if isinstance(value, str) else self._set_params(inputCols=value)

    def setOutputCol(self, value: str) -> "PCAModel":
        return self._set_params(outputCol=value)

    def _out_column_names(self) -> List[str]:
        if self.hasParam("outputCol") and self.isDefined("outputCol"):
            return [self.getOrDefault("outputCol")]
        return [f"{self.uid}__output"]

    def _get_transform_func(self):
        import jax

        from ..ops.pca import pca_transform
        from ..parallel.mesh import default_local_device

        components = self.components_
        explained_variance = self.explained_variance_
        whiten = bool(self._solver_params.get("whiten", False))
        dtype = np.float32 if self._float32_inputs else np.float64

        def construct():
            dev = default_local_device()
            return (
                jax.device_put(components.astype(dtype), dev),
                jax.device_put(explained_variance.astype(dtype), dev),
            )

        def predict(state, xb):
            comps, ev = state
            return pca_transform(xb.astype(dtype), comps, ev, whiten=whiten)

        return construct, predict, None

    def _serve_workspace_terms(self, bucket_rows_count, itemsize):
        # per-bucket predict workspace (docs/serving.md): the [bucket, k]
        # projection block
        k = int(np.asarray(self.components_).shape[0])
        return {"proj": int(bucket_rows_count) * k * itemsize}

    def _serve_flop_estimate(self, n_rows, n_cols):
        # roofline numerator: the (X - mean) @ components.T projection matmul
        k = max(1, int(np.asarray(self.components_).shape[0]))
        return 2.0 * n_rows * n_cols * k
