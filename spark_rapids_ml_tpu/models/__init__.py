#
# User-facing estimator/model families — the drop-in PySpark-ML-compatible API
# surface (reference python/src/spark_rapids_ml/{feature,clustering,regression,
# classification,knn,umap,tuning}.py).
#
