#
# Exact + approximate nearest-neighbor estimators.
#
# API-parity target: reference knn.py (`NearestNeighbors` :74-785,
# `ApproximateNearestNeighbors` :787-1544): fit() registers the item set,
# `kneighbors(query_df)` returns (item_df, query_df, knn_df) with knn_df =
# (query_id, indices, distances); `exactNearestNeighborsJoin` /
# `approxSimilarityJoin` explode the pairs. Neither supports persistence
# (reference knn.py:370-394 raises the same way).
#
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core import FitInputs, _TpuEstimator, _TpuModel, alias
from ..data import ExtractedData, as_pandas
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasIDCol,
    HasInputCol,
    HasInputCols,
    HasLabelCol,
    Param,
    TypeConverters,
)


class _KNNParams(HasInputCol, HasInputCols, HasFeaturesCol, HasFeaturesCols, HasIDCol, HasLabelCol):
    k = Param("k", "the number of nearest neighbors to retrieve", TypeConverters.toInt)

    def getK(self) -> int:
        return self.getOrDefault("k")

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors"}

    def _get_solver_params_default(self) -> Dict[str, Any]:
        # batch_queries 0 = config["distance_tile_rows"] (the shared tiled
        # distance core's row-tile, docs/performance.md "Tiled distance
        # core"); a nonzero value overrides per estimator
        return {"n_neighbors": 5, "batch_queries": 0, "verbose": False}


class NearestNeighbors(_KNNParams, _TpuEstimator):
    """Exact kNN estimator (reference knn.py:74-447).

    >>> gnn = NearestNeighbors(k=2).setInputCol("features").setIdCol("id")
    >>> model = gnn.fit(item_df)
    >>> item_out, query_out, knn_df = model.kneighbors(query_df)

    Distributed strategy: items row-sharded on the mesh, queries replicated;
    per-shard MXU distance tiles + top-k, then an all-gather of the [k·nq]
    candidates and one final top-k — replacing the reference's UCX all-to-all
    item/query shuffle (knn.py:712-723) with one small ICI collective.
    CSR item sets search via tile-densify with a running top-k (never fully
    densified — the reference's cupyx-CSR kNN capability).
    """

    _supports_sparse_input = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=5)
        self._set_params(**kwargs)

    def setK(self, value: int) -> "NearestNeighbors":
        return self._set_params(k=value)

    def setInputCol(self, value) -> "NearestNeighbors":
        return self._set_params(inputCol=value) if isinstance(value, str) else self._set_params(inputCols=value)

    def setIdCol(self, value: str) -> "NearestNeighbors":
        return self._set_params(idCol=value)

    def _get_tpu_fit_func(self, extracted: ExtractedData):
        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            return {"n_cols": inputs.n_cols, "dtype": np.dtype(inputs.dtype).name}

        return _fit

    def _fit_internal(self, dataset: Any, paramMaps):
        # fit just registers the (host) item set; the heavy work happens in
        # kneighbors — mirroring the reference where fit returns a model bound
        # to the item dataframe (knn.py:333-368)
        pdf = as_pandas(dataset)
        extracted = self._pre_process_data(dataset, for_fit=True)
        model = NearestNeighborsModel(
            n_cols=extracted.n_cols, dtype="float32" if self._float32_inputs else "float64"
        )
        self._copyValues(model)
        self._copy_solver_params(model)
        model._item_pdf = pdf
        model._item_extracted = extracted
        return [model]

    def _create_model(self, attrs):  # pragma: no cover - _fit_internal overridden
        return NearestNeighborsModel(**attrs)

    def write(self):
        raise NotImplementedError("NearestNeighbors does not support saving (reference parity)")


class NearestNeighborsModel(_KNNParams, _TpuModel):
    _supports_sparse_input = True

    def __init__(self, n_cols: int = 0, dtype: str = "float32", **kwargs: Any) -> None:
        super().__init__(n_cols=n_cols, dtype=dtype)
        self.n_cols = int(n_cols)
        self.dtype = dtype
        self._item_pdf = None
        self._item_extracted: Optional[ExtractedData] = None

    def _ensure_id(self, pdf, extracted) -> np.ndarray:
        if extracted.row_id is not None:
            return extracted.row_id
        return np.arange(len(pdf), dtype=np.int64)

    def kneighbors(self, query_df: Any) -> Tuple[Any, Any, Any]:
        """Returns (item_df, query_df, knn_df) — knn_df has columns
        (query_id, indices, distances), indices being item id values.

        Under multi-process SPMD (an active ``TpuContext`` with nranks > 1):
        each rank holds LOCAL item and query blocks; items are laid out
        globally on the mesh, query blocks are rendezvous-replicated (the
        reference allgathers sizes/ids for the UCX shuffle the same way,
        knn.py:689-700), every rank computes the full result, and returns the
        rows for ITS OWN queries."""
        import pandas as pd

        from ..parallel import PartitionDescriptor, TpuContext, get_mesh, make_global_rows
        from ..parallel.context import allgather_ndarray
        from ..parallel.mesh import default_devices, dtype_scope

        from ..ops.knn import exact_knn

        assert self._item_pdf is not None, "model is not bound to an item dataframe"
        k = int(self._solver_params["n_neighbors"])
        item_ex = self._item_extracted
        query_pdf = as_pandas(query_df)
        active0 = TpuContext.current()
        if len(query_pdf) == 0 and (active0 is None or not active0.is_spmd):
            # 0-row query frame: nothing to search (ingest can't infer a width
            # from an empty column). SPMD ranks still run the full path — an
            # empty LOCAL block must participate in the collective gathers.
            item_ids = self._ensure_id(self._item_pdf, item_ex)
            id_col = self.getOrDefault("idCol") if self.isDefined("idCol") else alias.row_number
            item_out = self._item_pdf.copy(deep=False)
            if id_col not in item_out.columns:
                item_out[id_col] = item_ids
            query_out = query_pdf.copy(deep=False)
            if id_col not in query_out.columns:
                query_out[id_col] = np.zeros(0, dtype=np.int64)
            knn_df = pd.DataFrame(
                {"query_id": np.zeros(0, dtype=np.int64), "indices": [], "distances": []}
            )
            return item_out, query_out, knn_df
        query_ex = self._pre_process_data(query_df, for_fit=False)
        item_ids = self._ensure_id(self._item_pdf, item_ex)
        query_ids = self._ensure_id(query_pdf, query_ex)

        active = TpuContext.current()
        spmd = active is not None and active.is_spmd

        np_dtype = np.float32 if self._float32_inputs else np.float64
        with dtype_scope(np_dtype):
            import jax

            items = item_ex.features
            queries = query_ex.features
            if hasattr(queries, "todense"):
                queries = np.asarray(queries.todense())
            queries = np.asarray(queries, dtype=np_dtype)

            if item_ex.is_sparse and not spmd:
                # CSR item set: tile-densify with a running top-k (never fully
                # densified — the reference's sparse kNN capability)
                from ..ops.knn import exact_knn_sparse

                if k > item_ex.n_rows:
                    raise ValueError(
                        f"k={k} exceeds the number of item rows {item_ex.n_rows}"
                    )
                d_np, gidx_np = exact_knn_sparse(items, queries, k)
                dist = np.asarray(d_np, dtype=np.float64)
                indices = item_ids[np.maximum(np.asarray(gidx_np), 0)]
            elif item_ex.is_sparse and spmd:
                # SPMD sparse: each rank runs the exact tile-densify search on
                # its LOCAL CSR block for ALL queries, then the per-rank exact
                # top-k sets are merged on the control plane — the union of
                # exact local results IS the exact global result
                from ..ops.knn import exact_knn_sparse
                from ..parallel.context import allgather_concat

                rdv = active.rendezvous
                counts = [int(c) for c in rdv.allgather(str(item_ex.n_rows))]
                if k > sum(counts):
                    raise ValueError(f"k={k} exceeds the number of item rows {sum(counts)}")
                if item_ex.row_id is None:
                    item_ids = item_ids + sum(counts[: active.rank])
                if query_ex.row_id is None:
                    qcounts = [int(c) for c in rdv.allgather(str(len(query_ids)))]
                    query_ids = query_ids + sum(qcounts[: active.rank])
                queries_global, q_offset = allgather_concat(rdv, queries)
                nq_local = len(query_pdf)
                d_np, lidx = exact_knn_sparse(items, queries_global, k)
                local_user_ids = np.where(
                    np.asarray(lidx) >= 0, item_ids[np.maximum(np.asarray(lidx), 0)], -1
                )
                d_all = np.concatenate(
                    allgather_ndarray(rdv, np.asarray(d_np, dtype=np.float64)), axis=1
                )
                i_all = np.concatenate(
                    allgather_ndarray(rdv, local_user_ids.astype(np.int64)), axis=1
                )
                order = np.argsort(d_all, axis=1, kind="stable")[:, :k]
                dist = np.take_along_axis(d_all, order, axis=1)[q_offset : q_offset + nq_local]
                indices = np.take_along_axis(i_all, order, axis=1)[q_offset : q_offset + nq_local]
            else:
                if hasattr(items, "todense"):
                    items = np.asarray(items.todense())

                if spmd:
                    mesh = active.mesh
                    # agree on the global item layout (ragged local blocks ->
                    # common padded per-process size), like _build_fit_inputs
                    desc = PartitionDescriptor.build(
                        [items.shape[0]], item_ex.n_cols,
                        rank=active.rank, rendezvous=active.rendezvous,
                    )
                    if k > desc.m:
                        raise ValueError(f"k={k} exceeds the number of item rows {desc.m}")
                    # default row-number ids are rank-local — offset by the
                    # lower-rank row counts so they're globally unique (same
                    # rule as the sparse-SPMD and ANN-SPMD branches)
                    if item_ex.row_id is None:
                        item_ids = item_ids + desc.row_offset_of(active.rank)
                    n_local_dev = jax.local_device_count()
                    max_rows = max(r for _, r in desc.parts_rank_size)
                    local_rows_target = -(-max_rows // n_local_dev) * n_local_dev
                    X, w, _ = make_global_rows(
                        mesh, items.astype(np_dtype), local_rows_target=local_rows_target
                    )
                    # global padded-position -> user item id map (pad with -1)
                    ids_padded = np.full(local_rows_target, -1, np.int64)
                    ids_padded[: len(item_ids)] = item_ids
                    global_item_ids = np.concatenate(
                        allgather_ndarray(active.rendezvous, ids_padded)
                    )
                    # replicate the query blocks; remember this rank's slice
                    q_blocks = allgather_ndarray(active.rendezvous, queries)
                    q_offset = sum(len(b) for b in q_blocks[: active.rank])
                    if query_ex.row_id is None:
                        query_ids = query_ids + q_offset
                    nq_local = queries.shape[0]
                    queries_global = np.concatenate(q_blocks, axis=0)
                    Q = jax.device_put(queries_global)
                else:
                    if k > item_ex.n_rows:
                        raise ValueError(
                            f"k={k} exceeds the number of item rows {item_ex.n_rows}"
                        )
                    n_dev = min(self.num_workers, len(default_devices()))
                    mesh = get_mesh(n_dev)
                    X, w, _ = make_global_rows(mesh, items.astype(np_dtype))
                    global_item_ids = item_ids
                    Q = jax.device_put(queries)
                    q_offset, nq_local = 0, queries.shape[0]

                d_dev, gidx_dev = exact_knn(
                    X, w > 0, Q, mesh=mesh, k=k,
                    # 0 -> None: resolves config["distance_tile_rows"]
                    batch_queries=int(self._solver_params["batch_queries"]) or None,
                )
                dist = np.asarray(d_dev, dtype=np.float64)[q_offset : q_offset + nq_local]
                gidx = np.asarray(gidx_dev)[q_offset : q_offset + nq_local]
                indices = global_item_ids[gidx]  # global row position -> user item id

        knn_df = pd.DataFrame(
            {
                "query_id": query_ids,
                "indices": list(indices),
                "distances": list(dist),
            }
        )
        item_out = self._item_pdf.copy(deep=False)
        id_col = self.getOrDefault("idCol") if self.isDefined("idCol") else alias.row_number
        if id_col not in item_out.columns:
            item_out[id_col] = item_ids
        query_out = query_pdf.copy(deep=False)
        if id_col not in query_out.columns:
            query_out[id_col] = query_ids
        return item_out, query_out, knn_df

    def exactNearestNeighborsJoin(self, query_df: Any, distCol: str = "distCol") -> Any:
        """Exploded (item, query, distance) join (reference knn.py:421-468).

        Single-controller only: under multi-process SPMD the neighbor ids
        returned by ``kneighbors`` routinely live on OTHER ranks, and the item
        attribute join is a data-plane operation (the reference performs it as
        a Spark dataframe join over the distributed item set, knn.py:421-468) —
        join the per-rank ``knn_df`` outputs against the full item table in the
        caller's data layer instead."""
        import pandas as pd

        from ..parallel import TpuContext

        active = TpuContext.current()
        if active is not None and active.is_spmd:
            raise NotImplementedError(
                "exactNearestNeighborsJoin/approxSimilarityJoin need the full item "
                "table on one node; under multi-process SPMD use kneighbors() and "
                "join the returned ids against your distributed item dataframe"
            )
        item_out, query_out, knn_df = self.kneighbors(query_df)
        id_col = self.getOrDefault("idCol") if self.isDefined("idCol") else alias.row_number
        item_by_id = item_out.set_index(id_col)
        query_by_id = query_out.set_index(id_col)
        # vectorized explode of the [nq, k] neighbor lists; ANN search pads
        # under-filled probe results with +inf distance — those aren't real
        # neighbors, drop them (a real hit always has finite distance)
        if len(knn_df):
            indices = np.stack(knn_df["indices"].to_numpy())
            dists = np.stack(knn_df["distances"].to_numpy())
        else:  # 0-row query frame: np.stack rejects an empty list
            indices = np.zeros((0, 1), dtype=np.int64)
            dists = np.zeros((0, 1), dtype=np.float64)
        k = indices.shape[1]
        flat_q = np.repeat(knn_df["query_id"].to_numpy(), k)
        flat_i = indices.ravel()
        flat_d = dists.ravel()
        finite = np.isfinite(flat_d)
        pairs = pd.DataFrame(
            {"_query_id": flat_q[finite], "_item_id": flat_i[finite], distCol: flat_d[finite]}
        )
        item_side = item_by_id.loc[pairs["_item_id"]].reset_index()
        item_side.columns = [f"item_{c}" if c != id_col else f"item_{id_col}" for c in item_side.columns]
        query_side = query_by_id.loc[pairs["_query_id"]].reset_index()
        query_side.columns = [f"query_{c}" if c != id_col else f"query_{id_col}" for c in query_side.columns]
        out = pd.concat(
            [item_side.reset_index(drop=True), query_side.reset_index(drop=True), pairs[[distCol]]],
            axis=1,
        )
        return out

    def transform(self, dataset: Any):
        raise NotImplementedError("use kneighbors()/exactNearestNeighborsJoin() (reference parity)")

    # serving hooks (docs/serving.md) -------------------------------------

    _serve_dtypes = (None, "float32", "float64", "bf16")

    def _serve_n_cols(self) -> int:
        if self._item_extracted is None:
            raise ValueError(
                "NearestNeighborsModel is not bound to an item dataframe; "
                "fit it before loading into the serving plane"
            )
        return int(self._item_extracted.n_cols)

    def _serve_placement_terms(self) -> Dict[str, int]:
        # the resident state is the ITEM BLOCK (plus its row norms and the
        # int64 id map), not the tiny param surface
        itemsize = 4 if self._float32_inputs else 8
        n = int(self._item_extracted.n_rows) if self._item_extracted is not None else 0
        d = self._serve_n_cols()
        return {
            "items": n * d * itemsize,
            "item_sq": n * itemsize,
            "item_ids": n * 8,
        }

    def _serve_workspace_terms(self, bucket_rows_count, itemsize) -> Dict[str, int]:
        # the tiled top-k merge's live blocks per dispatched bucket: the
        # [bucket, k_tile] distance block (VMEM-sized item tiles on the
        # kernel path; the one-matmul [bucket, n] fallback on CPU/older
        # jaxlibs) plus the [bucket, k] best-list carry x2 (d2 + index) —
        # the distance core is exactly why no [bucket, n_items] block lands
        # in HBM on the kernel path
        from ..ops import distance as dist

        n_items = int(self._item_extracted.n_rows) if self._item_extracted is not None else 0
        k = int(self._solver_params["n_neighbors"])
        b = max(1, int(bucket_rows_count))
        if dist.kernel_mode() == "jnp":
            k_tile = max(1, n_items)
        else:
            plan = dist.plan_blocks(b, max(1, n_items), self._serve_n_cols(), itemsize)
            k_tile = max(plan[1], 128) if plan is not None else max(1, n_items)
        return {
            "topk_block": b * min(k_tile, max(1, n_items)) * itemsize,
            "topk_carry": 2 * b * min(k, max(1, n_items)) * itemsize,
        }

    def _serve_flop_estimate(self, n_rows, n_cols):
        # roofline numerator: the full [queries, items] squared-distance
        # sweep (~3*n*m*d); top-k selection epilogue omitted (lower bound)
        n_items = int(self._item_extracted.n_rows) if self._item_extracted is not None else 0
        return 3.0 * n_rows * max(1, n_items) * n_cols

    def _serve_program(self, serve_dtype=None, *, cap=None):
        """kNN serving hook: queries route through the PR-10 tiled distance
        core (`ops/distance.topk_tile`) so no `[batch, n_items]` distance
        block lands in HBM on the kernel path. Returns per query row
        (euclidean distances [B, k], USER item ids [B, k]) — the same values
        `kneighbors`' knn_df carries. `serve_dtype="bf16"` scores through the
        core's parity-tested fast-bf16 mode (docs/serving.md "bf16 serving")."""
        import jax
        import jax.numpy as jnp

        from ..core import PredictProgram
        from ..ops import distance as dist
        from ..parallel.mesh import default_local_device

        self._serve_check(serve_dtype)  # dtype surface + bound item set
        fast = serve_dtype == "bf16"
        dtype = np.float32 if self._float32_inputs else np.float64
        items = self._item_extracted.features
        if hasattr(items, "todense"):
            items = np.asarray(items.todense())
        items_np = np.ascontiguousarray(np.asarray(items, dtype=dtype))
        ids_np = np.asarray(
            self._ensure_id(self._item_pdf, self._item_extracted), dtype=np.int64
        )
        k = min(int(self._solver_params["n_neighbors"]), items_np.shape[0])

        def construct():
            dev = default_local_device()
            it = jax.device_put(items_np, dev)
            return (it, dist.row_sq(it), jax.device_put(ids_np, dev))

        @jax.jit
        def predict(state, qb):
            it, it_sq, ids = state
            q = qb.astype(dtype)
            d2, idx = dist.topk_tile(q, it, None, k, item_sq=it_sq, fast=fast)
            d = jnp.sqrt(jnp.maximum(d2 + dist.row_sq(q)[:, None], 0.0))
            return d, ids[idx]

        return PredictProgram(self, construct=construct, predict=predict, cap=cap)

    def write(self):
        raise NotImplementedError("NearestNeighborsModel does not support saving (reference parity)")


class _ANNParams(_KNNParams):
    algorithm = Param("algorithm", "ANN algorithm: 'ivfflat', 'ivfpq' or 'cagra'", TypeConverters.toString)
    algoParams = Param("algoParams", "algorithm-specific parameters dict", TypeConverters.identity)
    metric = Param("metric", "distance metric: euclidean | sqeuclidean | cosine", TypeConverters.toString)

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors", "metric": "metric"}

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {
            "metric": "euclidean",
            "n_neighbors": 5,
            "batch_queries": 1024,
            "n_lists": 64,
            "n_probes": 8,
            "pq_m": 8,       # cuML algoParams key "M": subquantizer count
            "pq_n_bits": 8,  # cuML algoParams key "n_bits": bits per PQ code
            # ivfpq retrieves k*refine_ratio ADC candidates, then re-ranks them
            # with exact distances (the cuVS refine step) — raw ADC ordering
            # alone caps recall well below the probe ceiling
            "refine_ratio": 4,
            # cagra index params (reference knn.py:927-931 IndexParams)
            "build_algo": "ivf_pq",
            "graph_degree": 64,
            "intermediate_graph_degree": 128,
            # cagra build knobs beyond the reference surface (ops/cagra.py):
            # seeding reps / max descent rounds / cuVS-style update-rate
            # termination / bf16 candidate scoring
            "cluster_reps": 8,
            "nn_descent_niter": 0,
            "termination_threshold": 0.003,
            "fast_score": True,
            # cagra search params (reference knn.py:933-938 SearchParams)
            "itopk_size": 64,
            "search_width": 1,
            "max_iterations": 0,
            "min_iterations": 0,
            "num_random_samplings": 1,
            "verbose": False,
        }


class ApproximateNearestNeighbors(_ANNParams, _TpuEstimator):
    """Approximate kNN via IVFFlat, IVFPQ or CAGRA (reference
    knn.py:787-1544; algorithm set knn.py:1089-1094).

    Local-index strategy like the reference: a coarse KMeans quantizer with
    padded inverted lists; queries probe `n_probes` lists. IVFPQ additionally
    product-quantizes the residuals and searches via ADC lookup tables.
    CAGRA builds a fixed-degree kNN graph by tiled NN-descent and answers
    queries with a batched greedy graph search (ops/cagra.py).
    `algoParams` accepts the cuML/cuVS-style keys {"nlist", "nprobe", "M",
    "n_bits"} and the cagra keys {"build_algo", "graph_degree",
    "intermediate_graph_degree", "itopk_size", "search_width",
    "max_iterations", "min_iterations", "num_random_samplings"} plus the
    TPU-build knobs {"cluster_reps", "nn_descent_niter",
    "termination_threshold", "fast_score"} (ops/cagra.py build_cagra).
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=5, algorithm="ivfflat")
        self._set_params(**kwargs)

    def _set_params(self, **kwargs):
        if "algorithm" in kwargs and kwargs["algorithm"] not in (
            "ivfflat", "ivfpq", "cagra",
        ):
            raise ValueError(
                f"algorithm {kwargs['algorithm']!r} not supported"
                " (ivfflat | ivfpq | cagra)"
            )
        if "metric" in kwargs and kwargs["metric"] not in (
            "euclidean", "sqeuclidean", "cosine",
        ):
            raise ValueError(
                f"metric {kwargs['metric']!r} not supported"
                " (euclidean | sqeuclidean | cosine)"
            )
        if "algoParams" in kwargs:
            ap = kwargs.pop("algoParams") or {}
            if "compression" in ap:
                raise ValueError(
                    "cagra 'compression' is not supported by the TPU backend"
                )
            mapped = {
                "nlist": "n_lists", "nprobe": "n_probes", "M": "pq_m",
                "n_bits": "pq_n_bits", "refine_ratio": "refine_ratio",
            }
            # REPLACE semantics (reference setAlgoParams resets the whole
            # Param dict): keys a previous algoParams set revert to their
            # defaults first, so config sweeps don't inherit stale knobs
            defaults = self._get_solver_params_default()
            for prev in getattr(self, "_algo_params_keys", ()):  # type: ignore[attr-defined]
                if prev in defaults:
                    self._solver_params[prev] = defaults[prev]
                else:
                    self._solver_params.pop(prev, None)
            applied = set()
            for key, v in ap.items():
                solver_key = mapped.get(key, key)
                self._solver_params[solver_key] = v
                applied.add(solver_key)
            self._algo_params_keys = applied
        return super()._set_params(**kwargs)

    def setK(self, value: int) -> "ApproximateNearestNeighbors":
        return self._set_params(k=value)

    def setInputCol(self, value) -> "ApproximateNearestNeighbors":
        return self._set_params(inputCol=value) if isinstance(value, str) else self._set_params(inputCols=value)

    def setIdCol(self, value: str) -> "ApproximateNearestNeighbors":
        return self._set_params(idCol=value)

    # reference accessor surface (knn.py:850-888)
    def setAlgorithm(self, value: str) -> "ApproximateNearestNeighbors":
        return self._set_params(algorithm=value)

    def getAlgorithm(self) -> str:
        return self.getOrDefault("algorithm")

    def setAlgoParams(self, value: Dict[str, Any]) -> "ApproximateNearestNeighbors":
        return self._set_params(algoParams=value)

    def setMetric(self, value: str) -> "ApproximateNearestNeighbors":
        return self._set_params(metric=value)

    def getMetric(self) -> str:
        return str(self._solver_params["metric"])

    def _get_tpu_fit_func(self, extracted):  # pragma: no cover - _fit_internal overridden
        raise NotImplementedError

    def _fit_internal(self, dataset: Any, paramMaps):
        from ..ops.knn import build_ivfflat, build_ivfpq
        from ..parallel.mesh import dtype_scope

        pdf = as_pandas(dataset)
        extracted = self._pre_process_data(dataset, for_fit=True)
        feats = extracted.features
        if hasattr(feats, "todense"):
            feats = np.asarray(feats.todense())
        if str(self._solver_params["metric"]) == "cosine":
            # cosine rides the euclidean kernels on unit vectors (identical
            # ranking); stored index vectors are normalized, searches
            # normalize queries and convert distances (kneighbors)
            from ..utils import unit_rows

            feats = unit_rows(feats)
        algo = self.getOrDefault("algorithm")
        # index BUILD must not run at raw TPU bf16 (1-pass, ~3 digits — wrecks
        # quantizer training and recall), but the 3-pass mode's ~1e-6 relative
        # error is far below quantization error, at ~2x the f32 throughput
        with dtype_scope(np.float32, "BF16_BF16_F32_X3"):
            if algo == "ivfpq":
                index = build_ivfpq(
                    feats, int(self._solver_params["n_lists"]),
                    M=int(self._solver_params["pq_m"]),
                    n_bits=int(self._solver_params["pq_n_bits"]),
                    seed=0,
                )
            elif algo == "cagra":
                from ..ops.cagra import build_cagra

                # cuVS validates itopk_size >= k up front (knn.py:1286-1297);
                # fail at fit like the reference does at first use
                itopk = int(self._solver_params.get("itopk_size", 64))
                internal = -(-itopk // 32) * 32
                if internal < int(self._solver_params["n_neighbors"]):
                    raise ValueError(
                        f"cagra rounds itopk_size up to a multiple of 32"
                        f" ({internal}) and requires it >= k"
                        f" ({int(self._solver_params['n_neighbors'])})"
                    )
                index = build_cagra(
                    feats,
                    graph_degree=int(self._solver_params["graph_degree"]),
                    intermediate_graph_degree=int(
                        self._solver_params["intermediate_graph_degree"]
                    ),
                    build_algo=str(self._solver_params["build_algo"]),
                    nn_descent_niter=int(self._solver_params["nn_descent_niter"]),
                    cluster_reps=int(self._solver_params["cluster_reps"]),
                    termination_threshold=float(
                        self._solver_params["termination_threshold"]
                    ),
                    fast_score=bool(self._solver_params["fast_score"]),
                    seed=0,
                )
            else:
                index = build_ivfflat(feats, int(self._solver_params["n_lists"]), seed=0)
        model = ApproximateNearestNeighborsModel(
            n_cols=extracted.n_cols, dtype="float32" if self._float32_inputs else "float64"
        )
        self._copyValues(model)
        self._copy_solver_params(model)
        model._item_pdf = pdf
        model._item_extracted = extracted
        model._index = index
        model._algorithm = algo
        return [model]

    def _create_model(self, attrs):  # pragma: no cover
        return ApproximateNearestNeighborsModel(**attrs)

    def write(self):
        raise NotImplementedError("ApproximateNearestNeighbors does not support saving")


class ApproximateNearestNeighborsModel(NearestNeighborsModel):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._index = None
        self._algorithm = "ivfflat"

    def _refine_exact(self, queries: np.ndarray, cand_idx: np.ndarray, k: int):
        """Exact re-rank of ADC candidates (cuVS refine): gather the candidate
        item vectors and score true euclidean distances; −1 pads stay last.
        Under metric='cosine' both sides are unit-normalized (queries arrive
        normalized from kneighbors; the stored item vectors are raw)."""
        items = self._item_extracted.features
        if hasattr(items, "todense"):
            items = np.asarray(items.todense())
        items = np.asarray(items, dtype=np.float64)
        if str(self._solver_params["metric"]) == "cosine":
            from ..utils import unit_rows

            items = np.asarray(unit_rows(items), dtype=np.float64)
        q = np.asarray(queries, dtype=np.float64)
        safe = np.maximum(cand_idx, 0)
        cand = items[safe]  # [nq, k_adc, d]
        d2 = ((cand - q[:, None, :]) ** 2).sum(axis=2)
        d2 = np.where(cand_idx >= 0, d2, np.inf)
        order = np.argsort(d2, axis=1)[:, :k]
        dist = np.sqrt(np.take_along_axis(d2, order, axis=1))
        idx = np.take_along_axis(cand_idx, order, axis=1)
        return dist, idx

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return _ANNParams._get_solver_params_default(self)

    # the reference mixes the accessor surface into the model too (knn.py
    # params class shared by estimator and model)
    def getAlgorithm(self) -> str:
        return self._algorithm

    def getMetric(self) -> str:
        return str(self._solver_params["metric"])

    def kneighbors(self, query_df: Any) -> Tuple[Any, Any, Any]:
        """Under multi-process SPMD this is the reference's local-index +
        broadcast-query + global top-k merge (knn.py:1189-1261): each rank
        built an index over ITS item partition at fit time; query blocks are
        rendezvous-replicated, every rank searches its local index for ALL
        queries, the per-rank top-k candidate sets are allgathered and merged
        by distance, and each rank keeps its own queries' rows."""
        import jax
        import pandas as pd

        from ..parallel import TpuContext
        from ..parallel.context import allgather_concat, allgather_ndarray
        from ..ops.knn import ivfflat_search, ivfpq_search
        from ..parallel.mesh import dtype_scope

        assert self._index is not None and self._item_pdf is not None
        k = int(self._solver_params["n_neighbors"])
        item_ex = self._item_extracted
        query_pdf = as_pandas(query_df)
        query_ex = self._pre_process_data(query_df, for_fit=False)
        item_ids = self._ensure_id(self._item_pdf, item_ex)
        query_ids = self._ensure_id(query_pdf, query_ex)

        active = TpuContext.current()
        spmd = active is not None and active.is_spmd
        q_offset, nq_local = 0, len(query_pdf)
        if spmd:
            rdv = active.rendezvous
            # default row-number ids must be GLOBAL: offset by the rows held
            # on lower ranks (an explicit idCol is used as-is) — item AND
            # query ids, so per-rank result frames concatenate unambiguously
            if item_ex.row_id is None:
                counts = [int(c) for c in rdv.allgather(str(len(item_ids)))]
                item_ids = item_ids + sum(counts[: active.rank])
            if query_ex.row_id is None:
                qcounts = [int(c) for c in rdv.allgather(str(len(query_ids)))]
                query_ids = query_ids + sum(qcounts[: active.rank])

        metric = str(self._solver_params["metric"])
        with dtype_scope(np.float32):
            queries = query_ex.features
            if hasattr(queries, "todense"):
                queries = np.asarray(queries.todense())
            if metric == "cosine":
                from ..utils import unit_rows

                queries = unit_rows(queries)
            if spmd:
                queries, q_offset = allgather_concat(
                    active.rendezvous, np.asarray(queries, dtype=np.float32)
                )
            if self._algorithm == "ivfpq":
                refine = max(1, int(self._solver_params.get("refine_ratio", 4)))
                k_adc = min(k * refine, item_ex.n_rows)
                dist, idx = ivfpq_search(
                    jax.device_put(queries.astype(np.float32)),
                    self._index,
                    k=k_adc,
                    n_probes=int(self._solver_params["n_probes"]),
                    batch_queries=int(self._solver_params["batch_queries"]),
                )
                if k_adc > k:
                    dist, idx = self._refine_exact(np.asarray(queries), np.asarray(idx), k)
            elif self._algorithm == "cagra":
                from ..ops.cagra import cagra_search

                sp = self._solver_params
                idx, d2 = cagra_search(
                    np.asarray(queries, dtype=np.float32),
                    self._index,
                    k=min(k, item_ex.n_rows),
                    itopk_size=int(sp["itopk_size"]),
                    search_width=int(sp["search_width"]),
                    max_iterations=int(sp["max_iterations"]),
                    min_iterations=int(sp["min_iterations"]),
                    num_random_samplings=int(sp["num_random_samplings"]),
                    batch_queries=int(sp["batch_queries"]),
                )
                # framework-wide convention: euclidean distances (the
                # reference returns squared L2 for its ANN algorithms —
                # documented deviation, docs/compatibility.md)
                dist = np.sqrt(np.maximum(d2, 0.0))
                if k > item_ex.n_rows:  # pad like the ivf paths
                    padw = k - item_ex.n_rows
                    idx = np.concatenate(
                        [idx, np.full((len(idx), padw), -1, idx.dtype)], axis=1
                    )
                    dist = np.concatenate(
                        [dist, np.full((len(dist), padw), np.inf, dist.dtype)], axis=1
                    )
            else:
                dist, idx = ivfflat_search(
                    jax.device_put(queries.astype(np.float32)),
                    jax.device_put(self._index["centroids"].astype(np.float32)),
                    jax.device_put(self._index["buckets"]),
                    jax.device_put(self._index["bucket_ids"]),
                    k=k,
                    n_probes=int(self._solver_params["n_probes"]),
                    batch_queries=int(self._solver_params["batch_queries"]),
                )
        dist = np.asarray(dist, dtype=np.float64)
        # metric output conversion (monotone — safe before the SPMD merge):
        # the kernels produce euclidean distances (on unit vectors for cosine)
        if metric == "sqeuclidean":
            dist = dist * dist
        elif metric == "cosine":
            dist = (dist * dist) / 2.0  # unit vectors: 1 - cosθ; inf pads stay inf
        idx = np.asarray(idx)
        indices = np.where(idx >= 0, item_ids[np.maximum(idx, 0)], -1)
        if spmd:
            # global top-k merge of the per-rank candidate sets (the
            # reference's _agg_topk groupBy, knn.py:1221-1261), then keep this
            # rank's own queries
            d_all = np.concatenate(
                allgather_ndarray(active.rendezvous, dist), axis=1
            )  # [nq_global, R*k]
            i_all = np.concatenate(
                allgather_ndarray(active.rendezvous, indices.astype(np.int64)), axis=1
            )
            order = np.argsort(d_all, axis=1, kind="stable")[:, :k]
            dist = np.take_along_axis(d_all, order, axis=1)
            indices = np.take_along_axis(i_all, order, axis=1)
            dist = dist[q_offset : q_offset + nq_local]
            indices = indices[q_offset : q_offset + nq_local]
        knn_df = pd.DataFrame(
            {"query_id": query_ids, "indices": list(indices), "distances": list(dist)}
        )
        id_col = self.getOrDefault("idCol") if self.isDefined("idCol") else alias.row_number
        item_out = self._item_pdf.copy(deep=False)
        if id_col not in item_out.columns:
            item_out[id_col] = item_ids
        query_out = query_pdf.copy(deep=False)
        if id_col not in query_out.columns:
            query_out[id_col] = query_ids
        return item_out, query_out, knn_df

    def approxSimilarityJoin(self, query_df: Any, distCol: str = "distCol") -> Any:
        return self.exactNearestNeighborsJoin(query_df, distCol)
