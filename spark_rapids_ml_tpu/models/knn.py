#
# Exact + approximate nearest-neighbor estimators.
#
# API-parity target: reference knn.py (`NearestNeighbors` :74-785,
# `ApproximateNearestNeighbors` :787-1544): fit() registers the item set,
# `kneighbors(query_df)` returns (item_df, query_df, knn_df) with knn_df =
# (query_id, indices, distances); `exactNearestNeighborsJoin` /
# `approxSimilarityJoin` explode the pairs. Neither supports persistence
# (reference knn.py:370-394 raises the same way).
#
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core import FitInputs, _TpuEstimator, _TpuModel, alias
from ..data import ExtractedData, as_pandas
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasIDCol,
    HasInputCol,
    HasInputCols,
    HasLabelCol,
    Param,
    TypeConverters,
)


class _KNNParams(HasInputCol, HasInputCols, HasFeaturesCol, HasFeaturesCols, HasIDCol, HasLabelCol):
    k = Param("k", "the number of nearest neighbors to retrieve", TypeConverters.toInt)

    def getK(self) -> int:
        return self.getOrDefault("k")

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors"}

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {"n_neighbors": 5, "batch_queries": 4096, "verbose": False}


class NearestNeighbors(_KNNParams, _TpuEstimator):
    """Exact kNN estimator (reference knn.py:74-447).

    >>> gnn = NearestNeighbors(k=2).setInputCol("features").setIdCol("id")
    >>> model = gnn.fit(item_df)
    >>> item_out, query_out, knn_df = model.kneighbors(query_df)

    Distributed strategy: items row-sharded on the mesh, queries replicated;
    per-shard MXU distance tiles + top-k, then an all-gather of the [k·nq]
    candidates and one final top-k — replacing the reference's UCX all-to-all
    item/query shuffle (knn.py:712-723) with one small ICI collective.
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=5)
        self._set_params(**kwargs)

    def setK(self, value: int) -> "NearestNeighbors":
        return self._set_params(k=value)

    def setInputCol(self, value) -> "NearestNeighbors":
        return self._set_params(inputCol=value) if isinstance(value, str) else self._set_params(inputCols=value)

    def setIdCol(self, value: str) -> "NearestNeighbors":
        return self._set_params(idCol=value)

    def _get_tpu_fit_func(self, extracted: ExtractedData):
        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            return {"n_cols": inputs.n_cols, "dtype": np.dtype(inputs.dtype).name}

        return _fit

    def _fit_internal(self, dataset: Any, paramMaps):
        # fit just registers the (host) item set; the heavy work happens in
        # kneighbors — mirroring the reference where fit returns a model bound
        # to the item dataframe (knn.py:333-368)
        pdf = as_pandas(dataset)
        extracted = self._pre_process_data(dataset, for_fit=True)
        model = NearestNeighborsModel(
            n_cols=extracted.n_cols, dtype="float32" if self._float32_inputs else "float64"
        )
        self._copyValues(model)
        self._copy_solver_params(model)
        model._item_pdf = pdf
        model._item_extracted = extracted
        return [model]

    def _create_model(self, attrs):  # pragma: no cover - _fit_internal overridden
        return NearestNeighborsModel(**attrs)

    def write(self):
        raise NotImplementedError("NearestNeighbors does not support saving (reference parity)")


class NearestNeighborsModel(_KNNParams, _TpuModel):
    def __init__(self, n_cols: int = 0, dtype: str = "float32", **kwargs: Any) -> None:
        super().__init__(n_cols=n_cols, dtype=dtype)
        self.n_cols = int(n_cols)
        self.dtype = dtype
        self._item_pdf = None
        self._item_extracted: Optional[ExtractedData] = None

    def _ensure_id(self, pdf, extracted) -> np.ndarray:
        if extracted.row_id is not None:
            return extracted.row_id
        return np.arange(len(pdf), dtype=np.int64)

    def kneighbors(self, query_df: Any) -> Tuple[Any, Any, Any]:
        """Returns (item_df, query_df, knn_df) — knn_df has columns
        (query_id, indices, distances), indices being item id values."""
        import pandas as pd

        from ..ops.knn import exact_knn
        from ..parallel import get_mesh, make_global_rows
        from ..parallel.mesh import default_devices, dtype_scope

        assert self._item_pdf is not None, "model is not bound to an item dataframe"
        k = int(self._solver_params["n_neighbors"])
        item_ex = self._item_extracted
        query_pdf = as_pandas(query_df)
        query_ex = self._pre_process_data(query_df, for_fit=False)
        item_ids = self._ensure_id(self._item_pdf, item_ex)
        query_ids = self._ensure_id(query_pdf, query_ex)
        if k > item_ex.n_rows:
            raise ValueError(f"k={k} exceeds the number of item rows {item_ex.n_rows}")

        np_dtype = np.float32 if self._float32_inputs else np.float64
        with dtype_scope(np_dtype):
            import jax

            n_dev = min(self.num_workers, len(default_devices()))
            mesh = get_mesh(n_dev)
            items = item_ex.features
            if hasattr(items, "todense"):
                items = np.asarray(items.todense())
            queries = query_ex.features
            if hasattr(queries, "todense"):
                queries = np.asarray(queries.todense())
            X, w, _ = make_global_rows(mesh, items.astype(np_dtype))
            Q = jax.device_put(queries.astype(np_dtype))
            dist, gidx = exact_knn(
                X, w > 0, Q, mesh=mesh, k=k,
                batch_queries=int(self._solver_params["batch_queries"]),
            )
        dist = np.asarray(dist, dtype=np.float64)
        gidx = np.asarray(gidx)
        indices = item_ids[gidx]  # map global row position -> user item id

        knn_df = pd.DataFrame(
            {
                "query_id": query_ids,
                "indices": list(indices),
                "distances": list(dist),
            }
        )
        item_out = self._item_pdf.copy(deep=False)
        id_col = self.getOrDefault("idCol") if self.isDefined("idCol") else alias.row_number
        if id_col not in item_out.columns:
            item_out[id_col] = item_ids
        query_out = query_pdf.copy(deep=False)
        if id_col not in query_out.columns:
            query_out[id_col] = query_ids
        return item_out, query_out, knn_df

    def exactNearestNeighborsJoin(self, query_df: Any, distCol: str = "distCol") -> Any:
        """Exploded (item, query, distance) join (reference knn.py:421-468)."""
        import pandas as pd

        item_out, query_out, knn_df = self.kneighbors(query_df)
        id_col = self.getOrDefault("idCol") if self.isDefined("idCol") else alias.row_number
        rows = []
        item_by_id = item_out.set_index(id_col)
        query_by_id = query_out.set_index(id_col)
        for _, r in knn_df.iterrows():
            for item_id, d in zip(r["indices"], r["distances"]):
                # ANN search pads under-filled probe results with +inf
                # distance — those aren't real neighbors, skip them (a real
                # hit always has finite distance, whatever its user id)
                if not np.isfinite(d):
                    continue
                rows.append((r["query_id"], item_id, d))
        pairs = pd.DataFrame(rows, columns=["_query_id", "_item_id", distCol])
        item_side = item_by_id.loc[pairs["_item_id"]].reset_index()
        item_side.columns = [f"item_{c}" if c != id_col else f"item_{id_col}" for c in item_side.columns]
        query_side = query_by_id.loc[pairs["_query_id"]].reset_index()
        query_side.columns = [f"query_{c}" if c != id_col else f"query_{id_col}" for c in query_side.columns]
        out = pd.concat(
            [item_side.reset_index(drop=True), query_side.reset_index(drop=True), pairs[[distCol]]],
            axis=1,
        )
        return out

    def transform(self, dataset: Any):
        raise NotImplementedError("use kneighbors()/exactNearestNeighborsJoin() (reference parity)")

    def write(self):
        raise NotImplementedError("NearestNeighborsModel does not support saving (reference parity)")


class _ANNParams(_KNNParams):
    algorithm = Param("algorithm", "ANN algorithm: 'ivfflat'", TypeConverters.toString)
    algoParams = Param("algoParams", "algorithm-specific parameters dict", TypeConverters.identity)

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {
            "n_neighbors": 5,
            "batch_queries": 1024,
            "n_lists": 64,
            "n_probes": 8,
            "verbose": False,
        }


class ApproximateNearestNeighbors(_ANNParams, _TpuEstimator):
    """Approximate kNN via IVFFlat (reference knn.py:787-1544).

    Local-index strategy like the reference: a coarse KMeans quantizer with
    padded inverted lists; queries probe `n_probes` lists. `algoParams` accepts
    the cuML-style keys {"nlist", "nprobe"}.
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=5, algorithm="ivfflat")
        self._set_params(**kwargs)

    def _set_params(self, **kwargs):
        if "algorithm" in kwargs and kwargs["algorithm"] not in ("ivfflat",):
            raise ValueError(
                f"algorithm {kwargs['algorithm']!r} not supported (ivfflat only in this build)"
            )
        if "algoParams" in kwargs:
            ap = kwargs.pop("algoParams") or {}
            mapped = {"nlist": "n_lists", "nprobe": "n_probes"}
            for key, v in ap.items():
                self._solver_params[mapped.get(key, key)] = v
        return super()._set_params(**kwargs)

    def setK(self, value: int) -> "ApproximateNearestNeighbors":
        return self._set_params(k=value)

    def setInputCol(self, value) -> "ApproximateNearestNeighbors":
        return self._set_params(inputCol=value) if isinstance(value, str) else self._set_params(inputCols=value)

    def setIdCol(self, value: str) -> "ApproximateNearestNeighbors":
        return self._set_params(idCol=value)

    def _get_tpu_fit_func(self, extracted):  # pragma: no cover - _fit_internal overridden
        raise NotImplementedError

    def _fit_internal(self, dataset: Any, paramMaps):
        from ..ops.knn import build_ivfflat

        pdf = as_pandas(dataset)
        extracted = self._pre_process_data(dataset, for_fit=True)
        feats = extracted.features
        if hasattr(feats, "todense"):
            feats = np.asarray(feats.todense())
        index = build_ivfflat(
            feats, int(self._solver_params["n_lists"]),
            seed=0,
        )
        model = ApproximateNearestNeighborsModel(
            n_cols=extracted.n_cols, dtype="float32" if self._float32_inputs else "float64"
        )
        self._copyValues(model)
        self._copy_solver_params(model)
        model._item_pdf = pdf
        model._item_extracted = extracted
        model._index = index
        return [model]

    def _create_model(self, attrs):  # pragma: no cover
        return ApproximateNearestNeighborsModel(**attrs)

    def write(self):
        raise NotImplementedError("ApproximateNearestNeighbors does not support saving")


class ApproximateNearestNeighborsModel(NearestNeighborsModel):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._index = None

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return _ANNParams._get_solver_params_default(self)

    def kneighbors(self, query_df: Any) -> Tuple[Any, Any, Any]:
        import jax
        import pandas as pd

        from ..ops.knn import ivfflat_search
        from ..parallel.mesh import dtype_scope

        assert self._index is not None and self._item_pdf is not None
        k = int(self._solver_params["n_neighbors"])
        item_ex = self._item_extracted
        query_pdf = as_pandas(query_df)
        query_ex = self._pre_process_data(query_df, for_fit=False)
        item_ids = self._ensure_id(self._item_pdf, item_ex)
        query_ids = self._ensure_id(query_pdf, query_ex)

        with dtype_scope(np.float32):
            queries = query_ex.features
            if hasattr(queries, "todense"):
                queries = np.asarray(queries.todense())
            dist, idx = ivfflat_search(
                jax.device_put(queries.astype(np.float32)),
                jax.device_put(self._index["centroids"].astype(np.float32)),
                jax.device_put(self._index["buckets"]),
                jax.device_put(self._index["bucket_ids"]),
                k=k,
                n_probes=int(self._solver_params["n_probes"]),
                batch_queries=int(self._solver_params["batch_queries"]),
            )
        dist = np.asarray(dist, dtype=np.float64)
        idx = np.asarray(idx)
        indices = np.where(idx >= 0, item_ids[np.maximum(idx, 0)], -1)
        knn_df = pd.DataFrame(
            {"query_id": query_ids, "indices": list(indices), "distances": list(dist)}
        )
        id_col = self.getOrDefault("idCol") if self.isDefined("idCol") else alias.row_number
        item_out = self._item_pdf.copy(deep=False)
        if id_col not in item_out.columns:
            item_out[id_col] = item_ids
        query_out = query_pdf.copy(deep=False)
        if id_col not in query_out.columns:
            query_out[id_col] = query_ids
        return item_out, query_out, knn_df

    def approxSimilarityJoin(self, query_df: Any, distCol: str = "distCol") -> Any:
        return self.exactNearestNeighborsJoin(query_df, distCol)
