#
# Out-of-core solver drivers: fits for datasets whose resident placement does
# not fit HBM (docs/robustness.md "Memory safety", ROADMAP item 2).
#
# Every driver here consumes a `FitInputs` whose `stream` field carries a
# `core.StreamPlan` (host-resident extracted blocks + admitted chunk size) and
# feeds row chunks through the double-buffered host->HBM pipeline
# (`parallel.mesh.stream_place_blocks`: chunk N+1's `device_put` in flight
# while chunk N computes). The solvers are restructured around ACCUMULABLE
# state, so only two chunks are ever device-resident:
#
#   linear / PCA   sufficient statistics (X'WX, X'Wy / mean+covariance)
#                  summed over chunks, then the SAME replicated (d, d) solve
#                  as the resident path (ops/linear._solve_from_stats /
#                  ops/pca._pca_finish) — identical finish kernels, so
#                  streaming matches resident to summation rounding;
#   logistic       the GLM quasi-Newton loop of ops/logistic._glm_qn_setup
#                  re-expressed with streamed reductions: per iteration, ONE
#                  chunked pass evaluates the line-search logits z_d and the
#                  batched-Armijo candidate losses, and ONE chunked pass
#                  accumulates the analytic gradient — the same two
#                  data-reads-per-iteration the resident program performs.
#                  Logits (n x k_out, tiny next to X) stay on host between
#                  passes;
#   k-means        per-chunk assignment + center accumulation
#                  (ops/kmeans.block_assign_accumulate) inside the SAME
#                  deferred-convergence host loop as the resident fit, with
#                  the SAME checkpoint key (ops/kmeans.kmeans_ckpt_key) — a
#                  resident fit's checkpoint resumes a streaming retry.
#
# Math parity: every formula mirrors its resident counterpart term by term;
# only the summation ORDER differs (per-chunk partials instead of one fused
# reduction), so streaming results match resident fits to accumulation
# rounding — pinned at rtol 1e-9 in float64 by tests/test_oocore.py.
#
from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import telemetry
from ..parallel.mesh import stream_place_blocks
from ..utils import numcheck


def _ranges(n: int, chunk_rows: int) -> List[Tuple[int, int]]:
    step = max(1, int(chunk_rows))
    return [(lo, min(lo + step, n)) for lo in range(0, max(0, int(n)), step)]


def _maybe_validate(plan: Any, lo: int, hi: int) -> None:
    """Per-row-block NaN/Inf scan (``config["validate_ingest"]``): validation
    rides the stream — the dataset is never host-materialized a second time
    just to validate it, and later passes over already-scanned rows are
    free."""
    if not getattr(plan, "validate", False) or lo < plan.validated_rows:
        return
    from ..data import run_deferred_validation

    run_deferred_validation(plan.extracted, lo=lo, hi=hi)
    plan.validated_rows = hi


def _ell_host_blocks(inputs: Any) -> List[Tuple[int, int, np.ndarray, np.ndarray]]:
    """CSR row slices converted ONCE per fit to padded-ELL host blocks at the
    GLOBAL k_max (every pass then re-places the same host arrays). Cached on
    the plan; per-block validation happens at conversion.

    The cache trades host memory (a full padded-ELL copy of the dataset
    alongside the CSR — up to `k_max / mean_nnz` times its size on skewed
    data) for conversion work, which the streamed GLM loop would otherwise
    redo three passes per iteration. Single-pass consumers must NOT build
    it — they go through `_ell_block_iter(cache=False)`, which converts one
    chunk at a time and retains nothing."""
    plan = inputs.stream
    if plan.ell_blocks is None:
        from .sparse import csr_to_ell

        csr = inputs.X_sparse
        k_max = (
            max(1, int(np.diff(csr.indptr).max())) if csr.shape[0] else 1
        )
        blocks = []
        for lo, hi in _ranges(inputs.n_valid, plan.chunk_rows):
            _maybe_validate(plan, lo, hi)
            idx, val, _ = csr_to_ell(csr[lo:hi], k_max=k_max, dtype=inputs.dtype)
            blocks.append((lo, hi, val, idx))
        plan.ell_blocks = blocks
        plan.ell_k_max = k_max
    return plan.ell_blocks


def _dense_block_iter(inputs: Any, extras: Dict[str, np.ndarray], per_block=None):
    """Host dicts for one dense pass: the features slice + aligned slices of
    `extras` (+ optional per-block arrays, e.g. the host-retained logits)."""
    plan = inputs.stream
    feats = plan.extracted.features
    dtype = inputs.dtype
    for bi, (lo, hi) in enumerate(_ranges(inputs.n_valid, plan.chunk_rows)):
        _maybe_validate(plan, lo, hi)
        blk = {"X": np.asarray(feats[lo:hi], dtype=dtype)}
        for name, arr in extras.items():
            blk[name] = arr[lo:hi]
        if per_block is not None:
            for name, arrs in per_block.items():
                blk[name] = arrs[bi]
        yield blk


def _ell_block_iter(
    inputs: Any, extras: Dict[str, np.ndarray], per_block=None, cache: bool = True
):
    plan = inputs.stream
    if not cache and plan.ell_blocks is None:
        # single-pass consumer: convert chunk by chunk, retain nothing — a
        # dataset streamed for device-memory pressure must not grow a second
        # full host copy just to be read once
        from .sparse import csr_to_ell

        csr = inputs.X_sparse
        if not plan.ell_k_max:
            plan.ell_k_max = (
                max(1, int(np.diff(csr.indptr).max())) if csr.shape[0] else 1
            )
        for lo, hi in _ranges(inputs.n_valid, plan.chunk_rows):
            _maybe_validate(plan, lo, hi)
            idx, val, _ = csr_to_ell(csr[lo:hi], k_max=plan.ell_k_max, dtype=inputs.dtype)
            blk = {"val": val, "idx": idx}
            for name, arr in extras.items():
                blk[name] = arr[lo:hi]
            yield blk
        return
    for bi, (lo, hi, val, idx) in enumerate(_ell_host_blocks(inputs)):
        blk = {"val": val, "idx": idx}
        for name, arr in extras.items():
            blk[name] = arr[lo:hi]
        if per_block is not None:
            for name, arrs in per_block.items():
                blk[name] = arrs[bi]
        yield blk


# ------------------------------------------------------- linear / PCA -------


def linear_streaming_stats(inputs: Any, fast: bool = False) -> Dict[str, np.ndarray]:
    """One streamed pass accumulating the normal-equation sufficient
    statistics (ops/linear._sufficient_stats tuple) — dense or padded-ELL.
    Padding rows carry zero weight and zero features, so per-chunk partials
    sum to exactly the resident statistics (up to summation rounding).
    ``fast`` runs each chunk's stat contractions bf16-in / f32-accumulate;
    the cross-chunk host accumulation stays at full precision."""
    from .linear import _STATS_NAMES, _ell_stats_jit, _stats_jit

    dtype = inputs.dtype
    y = np.asarray(inputs.y, dtype=dtype)
    w = np.asarray(inputs.w, dtype=dtype)
    extras = {"y": y, "w": w}
    acc: Optional[List[np.ndarray]] = None
    _nc = numcheck.hook()  # SRML_NUMCHECK=1: sweep per-chunk host partials
    if inputs.X_sparse is not None:
        d = inputs.n_cols
        for blk in stream_place_blocks(
            inputs.mesh, _ell_block_iter(inputs, extras, cache=False)
        ):
            part = _ell_stats_jit(
                blk["val"], blk["idx"], blk["y"], blk["w"], d=d, tile=8192,
                fast=fast,
            )
            # per-chunk partial fetch = the streaming pipeline's existing
            # sync; the efficiency attributor times the wait as `execute`
            with telemetry.device_wait("stream_chunk"):
                part = [np.asarray(p) for p in part]
            if _nc is not None:
                _nc("linear_stream.chunk", solver="linear_stream",
                    **{n: p for n, p in zip(_STATS_NAMES, part)})
            acc = part if acc is None else [a + b for a, b in zip(acc, part)]
    else:
        for blk in stream_place_blocks(inputs.mesh, _dense_block_iter(inputs, extras)):
            part = _stats_jit(blk["X"], blk["y"], blk["w"], fast=fast)
            with telemetry.device_wait("stream_chunk"):
                part = [np.asarray(p) for p in part]
            if _nc is not None:
                _nc("linear_stream.chunk", solver="linear_stream",
                    **{n: p for n, p in zip(_STATS_NAMES, part)})
            acc = part if acc is None else [a + b for a, b in zip(acc, part)]
    assert acc is not None, "streaming stats over an empty dataset"
    return {name: np.asarray(v) for name, v in zip(_STATS_NAMES, acc)}


def linear_fit_streaming(
    inputs: Any,
    *,
    alpha: float,
    l1_ratio: float,
    fit_intercept: bool = True,
    standardize: bool = True,
    use_cd: bool = False,
    max_iter: int = 1000,
    tol: float = 1e-6,
    fast: bool = False,
) -> Dict[str, jax.Array]:
    """Out-of-core linear regression: the one streamed statistics pass feeds
    the SAME replicated (d, d) solve as the resident path. The statistics are
    retained in the active `CheckpointStore` (when one is installed), so a
    transient retry — or every further param set of a sequential sweep —
    skips the data pass, exactly like the resident checkpointed fit. `fast`
    statistics are keyed apart from full-precision ones."""
    from .. import checkpoint as _ckpt
    from ..parallel import chaos
    from .linear import _STATS_NAMES, _solve_stats_jit

    dtype = inputs.dtype
    store = _ckpt.active_store()
    key = "linear_stats_stream" + ("_ell" if inputs.X_sparse is not None else "")
    if fast:
        key = key + ":bf16"
    pkey = ("stream", int(inputs.n_valid), int(inputs.n_cols), np.dtype(dtype).name)
    if store is not None:
        state = store.get_or_compute(
            key, lambda: linear_streaming_stats(inputs, fast=fast), solver="linear",
            placement_key=pkey,
        )
    else:
        state = linear_streaming_stats(inputs, fast=fast)
    chaos.maybe_fail_stage("solve", 0)
    stats = tuple(jnp.asarray(state[n], dtype) for n in _STATS_NAMES)
    return _solve_stats_jit(
        stats, jnp.zeros((), dtype),
        alpha=alpha, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, use_cd=use_cd, max_iter=int(max_iter), tol=tol,
    )


@jax.jit
def _moments_block(xb, wb):
    """Per-chunk weighted raw moments: (Σw, Σw·x [d], Σw·x² [d])."""
    return (
        jnp.sum(wb),
        jnp.einsum("n,nd->d", wb, xb),
        jnp.einsum("n,nd->d", wb, xb * xb),
    )


@partial(jax.jit, static_argnames=("fast",))
def _cov_block(xb, wb, mean, fast: bool = False):
    """Per-chunk CENTERED outer-product sum: Σ w (x-μ)(x-μ)ᵀ. Padding rows
    contribute (0-μ) terms scaled by w=0 — nothing. ``fast`` runs the outer
    product bf16-in / f32-accumulate (weights applied at full precision
    first — linalg.weighted_cov's contract)."""
    xc = xb - mean
    if fast:
        xcw = xc * wb[:, None]
        return jnp.einsum(
            "nd,ne->de",
            xcw.astype(jnp.bfloat16),
            xc.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ).astype(xb.dtype)
    return jnp.einsum("nd,n,ne->de", xc, wb, xc)


def pca_fit_streaming(inputs: Any, *, k: int, fast: bool = False) -> Dict[str, jax.Array]:
    """Out-of-core PCA: two streamed passes — weighted mean, then the
    CENTERED covariance (the same ``Σw(x-μ)(x-μ)ᵀ/(Σw-1)`` formula as
    linalg.weighted_cov, never the cancellation-prone uncentered form) — and
    the SAME finish kernel as the resident fit. Statistics retained through
    the checkpoint store like the resident checkpointed path. ``fast``
    applies to each chunk's covariance contraction only; the mean pass and
    the eigendecomposition stay full precision."""
    from .. import checkpoint as _ckpt
    from ..parallel import chaos
    from .pca import _pca_finish

    dtype = inputs.dtype
    w = np.asarray(inputs.w, dtype=dtype)

    def compute() -> Dict[str, np.ndarray]:
        sw = None
        sx = None
        _nc = numcheck.hook()  # SRML_NUMCHECK=1: sweep per-chunk host partials
        for blk in stream_place_blocks(inputs.mesh, _dense_block_iter(inputs, {"w": w})):
            b_sw, b_sx, _ = _moments_block(blk["X"], blk["w"])
            with telemetry.device_wait("stream_chunk"):
                b_sw, b_sx = np.asarray(b_sw), np.asarray(b_sx)  # host-fetch-ok: out-of-core by design — per-CHUNK moment partials accumulate on host (tiny [d]-sized payloads)
            if _nc is not None:
                _nc("pca_stream.chunk", solver="pca_stream", sum_w=b_sw, sum_x=b_sx)
            sw = b_sw if sw is None else sw + b_sw
            sx = b_sx if sx is None else sx + b_sx
        assert sw is not None
        mean = sx / sw
        mean_dev = jnp.asarray(mean, dtype)
        cov_sum = None
        for blk in stream_place_blocks(inputs.mesh, _dense_block_iter(inputs, {"w": w})):
            with telemetry.device_wait("stream_chunk"):
                part = np.asarray(_cov_block(blk["X"], blk["w"], mean_dev, fast=fast))  # host-fetch-ok: out-of-core by design — per-CHUNK [d,d] covariance partial accumulates on host
            if _nc is not None:
                _nc("pca_stream.chunk", solver="pca_stream", cov_partial=part)
            cov_sum = part if cov_sum is None else cov_sum + part
        cov = cov_sum / (sw - 1.0)
        if _nc is not None:
            _nc("pca_stream.stats", solver="pca_stream", mean=mean, cov=cov)
        return {"total_w": np.asarray(sw), "mean": np.asarray(mean), "cov": cov}

    store = _ckpt.active_store()
    # bf16 statistics are keyed apart from full-precision ones
    stats_key = "pca_stats_stream" + (":bf16" if fast else "")
    pkey = ("stream", int(inputs.n_valid), int(inputs.n_cols), np.dtype(dtype).name)
    if store is not None:
        state = store.get_or_compute(
            stats_key, compute, solver="pca", placement_key=pkey
        )
    else:
        state = compute()
    chaos.maybe_fail_stage("solve", 0)
    return _pca_finish(
        jnp.asarray(state["total_w"], dtype),
        jnp.asarray(state["mean"], dtype),
        jnp.asarray(state["cov"], dtype),
        k=k,
    )


# ------------------------------------------------------------- k-means ------


def kmeans_fit_streaming(
    inputs: Any,
    init_centers: np.ndarray,
    *,
    max_iter: int = 20,
    tol: float = 1e-4,
    final_inertia: bool = True,
    precision_mode: str = "high",
) -> Dict[str, jax.Array]:
    """Out-of-core Lloyd: each iteration streams the row chunks through the
    double-buffered pipeline, accumulating (sums, counts, inertia) per chunk.
    The host loop — deferred convergence check, last-good tracking,
    divergence guard, final high-precision inertia, checkpoint cadence — is
    the resident `kmeans_fit` loop verbatim, and the checkpoint key is
    SHARED with it (`kmeans_ckpt_key`), so a resident fit interrupted by an
    OOM resumes on this path from its own checkpoint (centers are replicated
    state: fully portable).

    precision_mode: "high" (default) keeps every chunk at the ambient
    precision; "fast" (solver_precision="bf16", f32 inputs only) runs the
    IN-LOOP chunk assignment matmuls in one-pass bf16 — the final inertia
    pass always reruns at full precision, resident-contract parity."""
    from .. import checkpoint as _ckpt
    from ..parallel import chaos
    from .kmeans import (
        _finish_centers_jit,
        _raise_diverged,
        block_assign_accumulate,
        kmeans_ckpt_key,
    )

    dtype = inputs.dtype
    fast = precision_mode == "fast" and dtype == jnp.float32
    w = np.asarray(inputs.w, dtype=dtype)
    centers = jnp.asarray(np.asarray(init_centers), dtype=dtype)
    _nc = numcheck.hook()  # SRML_NUMCHECK=1: chunk partials + iterate boundary

    def step(c, f=False):
        sums = counts = inertia = None
        for blk in stream_place_blocks(inputs.mesh, _dense_block_iter(inputs, {"w": w})):
            s, n_, i_ = block_assign_accumulate(blk["X"], blk["w"], c, fast=f)
            with telemetry.device_wait("stream_chunk"):
                s, n_, i_ = np.asarray(s), np.asarray(n_), np.asarray(i_)  # host-fetch-ok: out-of-core by design — per-CHUNK [k,d] assignment partials accumulate on host
            if _nc is not None:
                _nc("kmeans_stream.chunk", solver="kmeans_stream",
                    sums=s, inertia=i_)
            if sums is None:
                sums, counts, inertia = s, n_, i_
            else:
                sums, counts, inertia = sums + s, counts + n_, inertia + i_
        return _finish_centers_jit(
            jnp.asarray(sums, dtype), jnp.asarray(counts, dtype),
            jnp.asarray(inertia, dtype), c,
        )

    inertia = jnp.zeros((), dtype)
    n_iter = 0
    prev_shift = None
    last_good = centers
    ckpt_store = _ckpt.active_store()
    ckpt_every = _ckpt.every_iters()
    ckpt_key = None
    if ckpt_store is not None and ckpt_every > 0:
        ckpt_key = kmeans_ckpt_key(init_centers, max_iter, tol)
        if fast:  # bf16 trajectories key apart (same suffix as the resident loop)
            ckpt_key = ckpt_key + ":bf16"
        saved = ckpt_store.load(ckpt_key)
        if saved is not None and tuple(saved.state["centers"].shape) == tuple(
            jnp.shape(centers)
        ):
            centers = jnp.asarray(saved.state["centers"], dtype=dtype)
            lg = saved.state.get("last_good")
            last_good = centers if lg is None else jnp.asarray(lg, dtype=dtype)
            n_iter = int(saved.iteration)
            ps = saved.state.get("prev_shift")
            prev_shift = None if ps is None else float(ps)
    while n_iter < max_iter:
        step_in = centers
        centers, inertia, shift = step(centers, fast)
        n_iter += 1
        if prev_shift is not None:
            with telemetry.device_wait("kmeans_shift"):
                shift_host = float(prev_shift)  # host-fetch-ok: the DEFERRED convergence fetch (resident-loop parity) — overlapped with the current step's compute
            if not math.isfinite(shift_host):
                _raise_diverged(n_iter - 1, last_good, f"center shift = {shift_host}")
            if _nc is not None:
                # after the divergence guard (resident-loop parity)
                _nc("kmeans_stream.iterate", solver="kmeans_stream",
                    iteration=n_iter - 1, watermark=centers.dtype,
                    shift=shift_host)
            if telemetry.enabled():
                telemetry.record_convergence_point("kmeans.shift", n_iter - 1, shift_host)
            if shift_host <= tol:
                break
        prev_shift = shift
        last_good = step_in
        if ckpt_store is not None and ckpt_every > 0 and n_iter % ckpt_every == 0:
            prev_shift = float(prev_shift)  # host-fetch-ok: checkpoint-cadence boundary (config["checkpoint_every_iters"])
            ckpt_store.save(ckpt_key, _ckpt.SolverCheckpoint(
                solver="kmeans", iteration=n_iter,
                state={
                    "centers": np.asarray(centers),  # host-fetch-ok: the checkpoint itself — centers must land on host to survive
                    "prev_shift": prev_shift,
                    "last_good": np.asarray(last_good),  # host-fetch-ok: checkpoint payload (divergence-fallback iterate)
                },
            ))
            chaos.maybe_fail_oom("solve", n_iter)
            chaos.maybe_fail_stage("solve", n_iter)
            # cooperative scheduler preemption — post-checkpoint, like the
            # resident loop (a demoted job can still yield to higher priority)
            from ..scheduler.context import preemption_point

            preemption_point("kmeans_stream", n_iter)
    if telemetry.enabled():
        telemetry.record_solver_result("kmeans", n_iter=n_iter)
    if final_inertia:
        # always at full precision: the REPORTED inertia (and the divergence
        # guard on it) must never see bf16 rounding, resident-loop parity
        _, inertia, _ = step(centers, False)
        inertia_host = float(inertia)
        if not math.isfinite(inertia_host):
            _raise_diverged(n_iter, last_good, f"final inertia = {inertia_host}")
    else:
        inertia = jnp.full((), jnp.nan, dtype)
    return {
        "cluster_centers_": centers,
        "inertia_": inertia,
        "n_iter_": jnp.asarray(n_iter, jnp.int32),
    }


# ------------------------------------------------------------ logistic ------
#
# Streamed GLM quasi-Newton (the ops/logistic._glm_qn_setup algorithm with
# chunked reductions). Per-chunk kernels below are the per-row math of the
# resident objective closures, returning UNNORMALIZED partial sums the driver
# divides by total_w once — same per-row formulas, chunked summation order.


@partial(jax.jit, static_argnames=("multinomial",))
def _glm_loss_block(zb, yb, wb, *, multinomial):
    if multinomial:
        z_true = jnp.take_along_axis(zb, yb[:, None], axis=1)[:, 0]
        return jnp.sum(wb * (jax.nn.logsumexp(zb, axis=1) - z_true))
    y = yb.astype(zb.dtype)
    z0 = zb[:, 0]
    return jnp.sum(wb * (jax.nn.softplus(z0) - y * z0))


def _glm_residual(zb, yb, wb, total_w, k: int, multinomial: bool):
    if multinomial:
        p = jax.nn.softmax(zb, axis=1)
        return wb[:, None] * (p - jax.nn.one_hot(yb, k, dtype=zb.dtype)) / total_w
    p = jax.nn.sigmoid(zb[:, 0])
    return ((wb * (p - yb.astype(zb.dtype))) / total_w)[:, None]


def _search_losses(zb, z_d, yb, wb, alphas, multinomial: bool):
    if multinomial:
        z = zb[:, None, :] + alphas[None, :, None] * z_d[:, None, :]
        idx = jnp.broadcast_to(yb[:, None, None], (z.shape[0], alphas.shape[0], 1))
        z_true = jnp.take_along_axis(z, idx, axis=2)[..., 0]
        return jnp.einsum("n,ns->s", wb, jax.nn.logsumexp(z, axis=2) - z_true)
    yf = yb.astype(zb.dtype)
    z = zb[:, :1] + alphas[None, :] * z_d[:, :1]
    return jnp.einsum("n,ns->s", wb, jax.nn.softplus(z) - yf[:, None] * z)


def _fdot(a, b, fast: bool):
    """a @ b, optionally on the bf16-compute / f32-accumulate contract
    (``solver_precision="bf16"``): both operands rounded to bf16 so the MXU
    runs its native-width pass, `preferred_element_type` pins the f32
    accumulator, result cast back to the working dtype. Mirrors
    ops/logistic._dense_ops for the resident solver."""
    if not fast:
        return a @ b
    return jax.lax.dot(
        a.astype(jnp.bfloat16),
        b.astype(jnp.bfloat16),
        precision=jax.lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32,
    ).astype(a.dtype)


@partial(jax.jit, static_argnames=("k", "multinomial", "fast"))
def _glm_eval_block_dense(xb, yb, wb, Beff, offset, total_w, *, k, multinomial, fast=False):
    """z + loss + gradient partials for one dense chunk (the init/warm pass)."""
    z = _fdot(xb, Beff, fast) + offset[None, :]
    loss = _glm_loss_block(z, yb, wb, multinomial=multinomial)
    r = _glm_residual(z, yb, wb, total_w, k, multinomial)
    return z, loss, _fdot(xb.T, r, fast), jnp.sum(r, axis=0)


@partial(jax.jit, static_argnames=("multinomial", "fast"))
def _glm_search_block_dense(xb, zb, yb, wb, Beff_d, offset_d, alphas, *, multinomial, fast=False):
    """Line-search pass: the direction's logits z_d (ONE data read) and the
    batched-Armijo candidate losses for all step sizes from it."""
    z_d = _fdot(xb, Beff_d, fast) + offset_d[None, :]
    return z_d, _search_losses(zb, z_d, yb, wb, alphas, multinomial)


@partial(jax.jit, static_argnames=("k", "multinomial", "fast"))
def _glm_grad_block_dense(xb, zb, yb, wb, total_w, *, k, multinomial, fast=False):
    """Gradient pass: analytic Xᵀ·residual from the accepted logits."""
    r = _glm_residual(zb, yb, wb, total_w, k, multinomial)
    return _fdot(xb.T, r, fast), jnp.sum(r, axis=0)


def _ell_fast_values(val, fast: bool):
    """ELL gather/scatter has no MXU contraction to cast — the honest bf16
    analog (resident ops/logistic._ell_ops parity) rounds the stored values
    once; index arithmetic and accumulation stay full precision."""
    return val.astype(jnp.bfloat16).astype(val.dtype) if fast else val


@partial(jax.jit, static_argnames=("d", "k", "multinomial", "fast"))
def _glm_eval_block_ell(val, idx, yb, wb, Beff, offset, total_w, *, d, k, multinomial, fast=False):
    from .sparse import ell_matmul, ell_rmatvec

    gv = _ell_fast_values(val, fast)
    z = ell_matmul(gv, idx, Beff) + offset[None, :]
    loss = _glm_loss_block(z, yb, wb, multinomial=multinomial)
    r = _glm_residual(z, yb, wb, total_w, k, multinomial)
    g = jnp.stack(
        [ell_rmatvec(gv, idx, r[:, j], d) for j in range(r.shape[1])], axis=1
    )
    return z, loss, g, jnp.sum(r, axis=0)


@partial(jax.jit, static_argnames=("multinomial", "fast"))
def _glm_search_block_ell(val, idx, zb, yb, wb, Beff_d, offset_d, alphas, *, multinomial, fast=False):
    from .sparse import ell_matmul

    z_d = ell_matmul(_ell_fast_values(val, fast), idx, Beff_d) + offset_d[None, :]
    return z_d, _search_losses(zb, z_d, yb, wb, alphas, multinomial)


@partial(jax.jit, static_argnames=("d", "k", "multinomial", "fast"))
def _glm_grad_block_ell(val, idx, zb, yb, wb, total_w, *, d, k, multinomial, fast=False):
    from .sparse import ell_rmatvec

    gv = _ell_fast_values(val, fast)
    r = _glm_residual(zb, yb, wb, total_w, k, multinomial)
    g = jnp.stack(
        [ell_rmatvec(gv, idx, r[:, j], d) for j in range(r.shape[1])], axis=1
    )
    return g, jnp.sum(r, axis=0)


@partial(jax.jit, static_argnames=("d",))
def _ell_moments_block(val, idx, wb, *, d):
    """Per-chunk scale-only standardization partials (ops/sparse.
    ell_col_moments accumulables): (Σw, Σw·x [d] scatter, Σw·x² [d] scatter)."""
    sw = jnp.sum(wb)
    wv = val * wb[:, None]
    s1 = jnp.zeros((d,), val.dtype).at[idx.ravel()].add(wv.ravel())
    s2 = jnp.zeros((d,), val.dtype).at[idx.ravel()].add((wv * val).ravel())
    return sw, s1, s2


def _streaming_scaling(inputs, w_host, standardize: bool, fit_intercept: bool):
    """(mu, d_scale, total_w) matching ops/logistic._make_scaling (dense) /
    _ell_scaling (sparse, scale-only), accumulated over streamed chunks."""
    dtype = inputs.dtype
    d = inputs.n_cols
    sparse = inputs.X_sparse is not None
    if not standardize:
        total_w = np.asarray(np.sum(w_host, dtype=dtype))
        return (
            np.zeros((d,), dtype),
            np.ones((d,), dtype),
            total_w,
        )
    sw = s1 = s2 = None
    if sparse:
        for blk in stream_place_blocks(inputs.mesh, _ell_block_iter(inputs, {"w": w_host})):
            p = _ell_moments_block(blk["val"], blk["idx"], blk["w"], d=d)
            p = [np.asarray(x) for x in p]
            sw, s1, s2 = (
                (p[0], p[1], p[2]) if sw is None else (sw + p[0], s1 + p[1], s2 + p[2])
            )
        mean = s1 / sw
        var = s2 / sw - mean * mean  # ell_col_moments: population, no clamp
    else:
        for blk in stream_place_blocks(inputs.mesh, _dense_block_iter(inputs, {"w": w_host})):
            p = _moments_block(blk["X"], blk["w"])
            p = [np.asarray(x) for x in p]
            sw, s1, s2 = (
                (p[0], p[1], p[2]) if sw is None else (sw + p[0], s1 + p[1], s2 + p[2])
            )
        mean = s1 / sw
        var = np.maximum(s2 / sw - mean * mean, 0.0)  # weighted_moments clamp
    sigma = np.sqrt(var * (sw / np.maximum(sw - 1.0, 1.0)))
    with np.errstate(invalid="ignore", divide="ignore"):
        d_scale = np.where(sigma > 0, 1.0 / np.maximum(sigma, 1e-30), 0.0)
    if sparse:
        mu = np.zeros((d,), dtype)  # scale-only: sparse data is never centered
    else:
        mu = mean if fit_intercept else np.zeros((d,), dtype)
    return (
        np.asarray(mu, dtype),
        np.asarray(d_scale, dtype),
        np.asarray(sw, dtype),
    )


def logistic_fit_streaming(
    inputs: Any,
    y_idx_host: np.ndarray,
    *,
    k: int,
    multinomial: bool,
    lam_l2: float,
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
    n_alphas: int = 12,
    c1: float = 1e-4,
    fast: bool = False,
    ckpt_key: str = "logistic_stream",
) -> Dict[str, jax.Array]:
    """Out-of-core logistic regression (smooth L2 path; the L1/elastic-net
    OWL-QN solver has no streaming form — callers gate on it).

    The ops/logistic._glm_qn_setup loop with streamed reductions: per
    iteration, one chunked pass computes the direction's logits + batched
    Armijo candidates and one chunked pass the analytic gradient — the same
    two data reads per iteration as the resident program. The per-row logits
    (n x k_out) are retained on HOST between passes; the accepted point's
    logits are the free linear update z_p + a·z_d, never a third data read.
    Checkpoints (``config["checkpoint_every_iters"]``) save the iterate +
    L-BFGS memory — placement-independent state, so a resume re-derives the
    logits from the iterate with one pass and continues exactly."""
    from .. import checkpoint as _ckpt
    from ..parallel import chaos
    from .logistic import _finish_glm
    from .owlqn import lbfgs_two_loop

    if fast:
        # bf16 iterates/logits are keyed apart: a bf16 run must never resume
        # from (or serve) a full-precision checkpoint
        ckpt_key = ckpt_key + ":bf16"
    dtype = np.dtype(inputs.dtype)
    d = int(inputs.n_cols)
    k_out = k if multinomial else 1
    n_flat = d * k_out + k_out
    m = int(lbfgs_memory)
    sparse = inputs.X_sparse is not None
    mesh = inputs.mesh

    w_host = np.asarray(inputs.w, dtype=dtype)
    y_host = np.asarray(y_idx_host, dtype=np.int32)
    extras = {"y": y_host, "w": w_host}

    mu, d_scale, total_w = _streaming_scaling(
        inputs, w_host, standardize, fit_intercept
    )
    total_w_f = dtype.type(total_w)

    def unflatten(xf: np.ndarray):
        return xf[: d * k_out].reshape(d, k_out), xf[d * k_out :]

    def beff_offset(xf: np.ndarray):
        B, b0 = unflatten(xf)
        Beff = B * d_scale[:, None]
        off = (b0 - mu @ Beff) if fit_intercept else -(mu @ Beff)
        return jnp.asarray(Beff), jnp.asarray(np.asarray(off, dtype))

    def penalty_terms(xf: np.ndarray, dv: np.ndarray):
        Bx, Bd = xf[: d * k_out], dv[: d * k_out]
        return (
            0.5 * lam_l2 * float(np.sum(Bx * Bx)),
            lam_l2 * float(np.dot(Bx, Bd)),
            0.5 * lam_l2 * float(np.sum(Bd * Bd)),
        )

    def assemble_grad(xf: np.ndarray, g_beff: np.ndarray, sum_r: np.ndarray):
        B, _ = unflatten(xf)
        g_b = g_beff - mu[:, None] * sum_r[None, :]
        dB = g_b * d_scale[:, None] + lam_l2 * B
        db0 = sum_r if fit_intercept else np.zeros((k_out,), dtype)
        return np.concatenate([dB.ravel(), db0]).astype(dtype)

    def blocks(per_block=None):
        return (
            _ell_block_iter(inputs, extras, per_block)
            if sparse
            else _dense_block_iter(inputs, extras, per_block)
        )

    # placed blocks are row-padded to the mesh multiple: fetched logits must
    # be TRIMMED back to each chunk's valid rows before they re-enter a later
    # pass as host arrays (the placer re-pads them consistently)
    row_counts = [hi - lo for lo, hi in _ranges(inputs.n_valid, inputs.stream.chunk_rows)]

    def eval_pass(xf: np.ndarray):
        """z blocks + loss + gradient at `xf` (init / resume re-derivation)."""
        Beff, off = beff_offset(xf)
        z_blocks: List[np.ndarray] = []
        loss = 0.0
        g_beff = np.zeros((d, k_out), dtype)
        sum_r = np.zeros((k_out,), dtype)
        for bi, blk in enumerate(stream_place_blocks(mesh, blocks())):
            if sparse:
                z, l_, g, sr = _glm_eval_block_ell(
                    blk["val"], blk["idx"], blk["y"], blk["w"], Beff, off,
                    total_w_f, d=d, k=k, multinomial=multinomial, fast=fast,
                )
            else:
                z, l_, g, sr = _glm_eval_block_dense(
                    blk["X"], blk["y"], blk["w"], Beff, off, total_w_f,
                    k=k, multinomial=multinomial, fast=fast,
                )
            z_blocks.append(np.asarray(z)[: row_counts[bi]])  # host-fetch-ok: out-of-core by design — per-CHUNK logits retained on host (z-block reuse saves an X pass per line search)
            loss += float(l_)  # host-fetch-ok: per-CHUNK scalar loss partial, accumulated on host
            g_beff = g_beff + np.asarray(g)  # host-fetch-ok: per-CHUNK [d,k] gradient partial, accumulated on host
            sum_r = sum_r + np.asarray(sr)  # host-fetch-ok: per-CHUNK residual-sum partial, accumulated on host
        return z_blocks, loss / float(total_w), g_beff, sum_r

    # --- state (host numpy, the working dtype throughout) -----------------
    x = np.zeros((n_flat,), dtype)
    S = np.zeros((m, n_flat), dtype)
    Y = np.zeros((m, n_flat), dtype)
    rho = np.zeros((m,), dtype)
    count = pos = 0
    it = 0
    stalled = False
    f_prev = np.inf

    store = _ckpt.active_store()
    every = _ckpt.every_iters()
    use_ckpt = store is not None and every > 0
    restored = False
    if use_ckpt:
        saved = store.peek(ckpt_key)
        if saved is not None and np.shape(saved.state.get("x")) == (n_flat,):
            st = saved.state
            x = np.asarray(st["x"], dtype)
            S = np.asarray(st["S"], dtype)
            Y = np.asarray(st["Y"], dtype)
            rho = np.asarray(st["rho"], dtype)
            count, pos = int(st["count"]), int(st["pos"])
            f_prev = float(st["f_prev"])
            it = int(saved.iteration)
            store.load(ckpt_key)  # count the restore + flight-recorder event
            restored = True

    z_blocks, loss, g_beff, sum_r = eval_pass(x)
    p0_x, _, _ = penalty_terms(x, np.zeros_like(x))
    f_cur = loss + p0_x
    if restored:
        # the saved f_cur is the exact continuation value (the re-derived one
        # equals it up to rounding; prefer the saved scalar so the resumed
        # convergence test sees precisely what the uninterrupted run would)
        f_cur = float(saved.state["f_cur"])
    g = assemble_grad(x, g_beff, sum_r)

    alphas_np = np.asarray(
        [2.0] + [0.5 ** i for i in range(n_alphas - 1)], np.float32
    ).astype(dtype)
    alphas_dev = jnp.asarray(alphas_np)
    _two_loop = jax.jit(lbfgs_two_loop, static_argnums=(6,))

    trace_convergence = telemetry.convergence_trace_enabled()
    _nc = numcheck.hook()  # SRML_NUMCHECK=1: outer-iteration boundary sweep
    while it < max_iter and not stalled:
        rel = abs(f_prev - f_cur) / max(abs(f_cur), 1.0)
        if not rel > tol:
            break
        d_dir = np.asarray(  # host-fetch-ok: ONE direction fetch per outer L-BFGS iteration — the host-stepped streaming solver's step size, not an inner-loop sync
            _two_loop(
                jnp.asarray(g), jnp.asarray(S), jnp.asarray(Y), jnp.asarray(rho),
                jnp.asarray(count, jnp.int32), jnp.asarray(pos, jnp.int32), m,
            ),
            dtype,
        )
        gd = float(np.dot(g, d_dir))
        if not gd < 0:  # steepest-descent fallback (resident parity)
            d_dir = -g
            gd = -float(np.dot(g, g))
        Beff_d, off_d = beff_offset(d_dir)
        loss_cand = np.zeros((len(alphas_np),), dtype)
        z_d_blocks: List[np.ndarray] = []
        for bi, blk in enumerate(
            stream_place_blocks(mesh, blocks(per_block={"z": z_blocks}))
        ):
            if sparse:
                z_d, part = _glm_search_block_ell(
                    blk["val"], blk["idx"], blk["z"], blk["y"], blk["w"],
                    Beff_d, off_d, alphas_dev, multinomial=multinomial, fast=fast,
                )
            else:
                z_d, part = _glm_search_block_dense(
                    blk["X"], blk["z"], blk["y"], blk["w"], Beff_d, off_d,
                    alphas_dev, multinomial=multinomial, fast=fast,
                )
            z_d_blocks.append(np.asarray(z_d)[: row_counts[bi]])  # host-fetch-ok: out-of-core by design — per-CHUNK direction logits retained on host
            loss_cand = loss_cand + np.asarray(part)  # host-fetch-ok: per-CHUNK batched-Armijo loss partials, accumulated on host
        p0, p1, p2 = penalty_terms(x, d_dir)
        a = alphas_np
        f_cand = loss_cand / float(total_w) + p0 + a * p1 + a * a * p2
        ok_mask = f_cand <= f_cur + c1 * a * gd
        ok = bool(ok_mask.any())
        if not ok:
            # no acceptable step: the batched-Armijo stall (resident parity —
            # the loop ends with `stalled` set, iterate unchanged)
            stalled = True
            f_prev = f_cur
            it += 1
            if trace_convergence:
                telemetry.record_convergence_point("glm_qn", it - 1, f_cur)
            break
        first_ok = int(np.argmax(ok_mask))
        a_sel = dtype.type(a[first_ok])
        f_new = float(f_cand[first_ok])
        xn = (x + a_sel * d_dir).astype(dtype)
        z_n_blocks = [zp + a_sel * zd for zp, zd in zip(z_blocks, z_d_blocks)]
        g_beff = np.zeros((d, k_out), dtype)
        sum_r = np.zeros((k_out,), dtype)
        for blk in stream_place_blocks(mesh, blocks(per_block={"z": z_n_blocks})):
            if sparse:
                gb, sr = _glm_grad_block_ell(
                    blk["val"], blk["idx"], blk["z"], blk["y"], blk["w"],
                    total_w_f, d=d, k=k, multinomial=multinomial, fast=fast,
                )
            else:
                gb, sr = _glm_grad_block_dense(
                    blk["X"], blk["z"], blk["y"], blk["w"], total_w_f,
                    k=k, multinomial=multinomial, fast=fast,
                )
            g_beff = g_beff + np.asarray(gb)  # host-fetch-ok: per-CHUNK gradient partial at the accepted point, accumulated on host
            sum_r = sum_r + np.asarray(sr)  # host-fetch-ok: per-CHUNK residual-sum partial, accumulated on host
        gn = assemble_grad(xn, g_beff, sum_r)
        s = xn - x
        yv = gn - g
        sy = float(np.dot(s, yv))
        if sy > 1e-10:
            S[pos] = s
            Y[pos] = yv
            rho[pos] = 1.0 / max(sy, 1e-30)
            count = min(count + 1, m)
            pos = (pos + 1) % m
        x, z_blocks, g = xn, z_n_blocks, gn
        f_prev, f_cur = f_cur, f_new
        it += 1
        if _nc is not None:
            # objective, iterate, and gradient are host state already —
            # the outer L-BFGS iteration IS the host boundary here
            _nc("glm_stream.iterate", solver="glm_qn_stream", iteration=it - 1,
                objective=f_cur, iterate=x, gradient=g)
        if trace_convergence:
            telemetry.record_convergence_point("glm_qn", it - 1, f_cur)
        if use_ckpt and it % every == 0:
            store.save(ckpt_key, _ckpt.SolverCheckpoint(
                solver="glm_qn_stream", iteration=it,
                state={
                    "x": x.copy(), "S": S.copy(), "Y": Y.copy(),
                    "rho": rho.copy(), "count": count, "pos": pos,
                    "f_prev": f_prev, "f_cur": f_cur,
                },
                portable={"x": x.copy()},
            ))
            chaos.maybe_fail_oom("solve", it)
            chaos.maybe_fail_stage("solve", it)
            # cooperative scheduler preemption — post-checkpoint boundary
            from ..scheduler.context import preemption_point

            preemption_point("glm_qn_stream", it)

    def unflat_jnp(xf):
        return xf[: d * k_out].reshape(d, k_out), xf[d * k_out :]

    return _finish_glm(
        jnp.asarray(x), jnp.asarray(f_cur, dtype), jnp.asarray(it, jnp.int32),
        jnp.asarray(stalled), unflat_jnp, jnp.asarray(d_scale), jnp.asarray(mu),
        fit_intercept=fit_intercept, multinomial=multinomial,
    )
