#
# Distributed linear regression solvers — in-tree replacements for
# `cuml.linear_model.{linear_regression_mg.LinearRegressionMG, ridge_mg.RidgeMG,
# cd_mg.CDMG}` (selected by reg params in reference regression.py:510-548).
#
# Design: ALL paths run ONE distributed pass computing the normal-equation
# sufficient statistics (XᵀWX gram, XᵀWy, weighted means — MXU contractions per
# row shard + GSPMD psum, the NCCL allreduce equivalent), then solve locally on
# replicated (d,d) data:
#   * reg=0            → weighted OLS solve               (OLS-eig analog)
#   * l1=0, reg>0      → ridge with alpha scaled by Σw    (reference parity
#                        trick, regression.py:536-542: Spark's 1/(2n)·RSS+λ/2‖b‖²
#                        ⇔ RSS+nλ‖b‖²)
#   * l1>0             → coordinate descent ON THE GRAM with incremental
#                        q=A·b updates — O(d²) per sweep, no further passes
#                        over the data (CDMG analog; sklearn/Spark objective
#                        1/(2n)·RSS + λα‖b‖₁ + λ(1-α)/2‖b‖²)
#
# `standardization=True` (Spark default) scales the penalty space by feature
# std and unscales afterward, penalizing the intercept never.
#
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp


def _sufficient_stats(X, y, w, fast: bool = False):
    """One distributed pass: (Σw, Σwx [d], Σwy, XᵀWX [d,d], XᵀWy [d], Σwy²).

    ``fast`` (solver_precision="bf16") runs the O(n·d²) gram and the O(n·d)
    correlation bf16-in / f32-accumulate; the weighting and every scalar
    moment stay full precision (docs/performance.md "Mixed-precision
    solvers"; parity pinned by tests/test_precision.py)."""
    sw = jnp.sum(w)
    sx = jnp.einsum("n,nd->d", w, X)
    sy = jnp.sum(w * y)
    Xw = X * w[:, None]
    if fast:
        bXw = Xw.astype(jnp.bfloat16)
        G = jnp.einsum(
            "nd,ne->de", bXw, X.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ).astype(X.dtype)
        c = jnp.einsum(
            "nd,n->d", bXw, y.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ).astype(X.dtype)
    else:
        G = jnp.einsum("nd,ne->de", Xw, X)
        c = jnp.einsum("nd,n->d", Xw, y)
    syy = jnp.sum(w * y * y)
    return sw, sx, sy, G, c, syy


def _cd_elastic_net(A, r, lam, l1_ratio, max_iter, tol):
    """Coordinate descent on normalized gram A=G/n, r=c/n.

    Soft-threshold updates with incremental q = A·b maintenance; converges when
    the max coefficient change in a sweep is <= tol."""
    d = A.shape[0]
    l1 = lam * l1_ratio
    l2 = lam * (1.0 - l1_ratio)
    denom = jnp.diag(A) + l2

    def sweep(b_q):
        b, q = b_q

        def coord(j, state):
            b, q, max_delta = state
            rho = r[j] - q[j] + A[j, j] * b[j]
            bj = jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - l1, 0.0) / jnp.maximum(denom[j], 1e-30)
            delta = bj - b[j]
            q = q + A[:, j] * delta
            b = b.at[j].set(bj)
            return b, q, jnp.maximum(max_delta, jnp.abs(delta))

        b, q, max_delta = jax.lax.fori_loop(0, d, coord, (b, q, jnp.zeros((), A.dtype)))
        return (b, q), max_delta

    def cond(state):
        (_, _), it, max_delta = state
        return jnp.logical_and(it < max_iter, max_delta > tol)

    def body(state):
        b_q, it, _ = state
        b_q, max_delta = sweep(b_q)
        return b_q, it + 1, max_delta

    from .owlqn import freeze_when_done

    b0 = jnp.zeros((d,), A.dtype)
    q0 = jnp.zeros((d,), A.dtype)
    # freeze_when_done: vmap-safe for batched (alpha, l1_ratio) grids — a
    # converged grid element must stop sweeping while slower ones finish
    (b, _), n_iter, _ = jax.lax.while_loop(
        cond, freeze_when_done(cond, body), ((b0, q0), 0, jnp.array(jnp.inf, A.dtype))
    )
    return b, n_iter


@partial(jax.jit, static_argnames=("fit_intercept", "standardize", "max_iter", "use_cd", "fast"))
def linear_fit(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    *,
    alpha: float,
    l1_ratio: float,
    fit_intercept: bool = True,
    standardize: bool = True,
    use_cd: bool = False,
    max_iter: int = 1000,
    tol: float = 1e-6,
    fast: bool = False,
) -> Dict[str, jax.Array]:
    """Weighted linear regression on row-sharded global (X, y).

    `alpha` is Spark's regParam (per-sample-normalized objective); the Σw
    scaling for the ridge path happens inside. `fast` runs the sufficient-
    stat contractions bf16-in / f32-accumulate (`_sufficient_stats`)."""
    stats = _sufficient_stats(X, y, w, fast)
    return _solve_from_stats(
        stats, X.dtype,
        alpha=alpha, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, use_cd=use_cd, max_iter=max_iter, tol=tol,
    )


@partial(
    jax.jit,
    static_argnames=("d", "tile", "fit_intercept", "standardize", "max_iter", "use_cd", "fast"),
)
def linear_fit_ell(
    values: jax.Array,  # [n, k_max] padded-ELL (ops/sparse.py)
    indices: jax.Array,  # [n, k_max] int32
    y: jax.Array,
    w: jax.Array,
    *,
    d: int,
    alpha: float,
    l1_ratio: float,
    fit_intercept: bool = True,
    standardize: bool = True,
    use_cd: bool = False,
    max_iter: int = 1000,
    tol: float = 1e-6,
    tile: int = 8192,
    fast: bool = False,
) -> Dict[str, jax.Array]:
    """Sparse linear regression: identical math to `linear_fit` — the gram and
    moment sufficient statistics are accumulated from the ELL layout by
    scatter-adding per-row outer products (tiled over `tile`-row blocks to
    bound the [tile, k_max, k_max] intermediate), then the SAME replicated
    (d, d) solve runs. Centering/standardization operate on the statistics,
    never the data, so sparsity is preserved AND full dense-parity holds
    (unlike the logistic path, no scale-only compromise is needed)."""
    return _solve_from_stats(
        _ell_sufficient_stats(values, indices, y, w, d, tile, fast), values.dtype,
        alpha=alpha, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, use_cd=use_cd, max_iter=max_iter, tol=tol,
    )


def _ell_sufficient_stats(values, indices, y, w, d: int, tile: int, fast: bool = False):
    """ELL-layout sufficient statistics (same tuple as `_sufficient_stats`).

    ``fast`` is the scatter-add analog of the dense bf16 contract: there is
    no MXU dot to cast here, so the stored values feeding the gram and the
    XᵀWy correlation are ROUNDED through bf16 once (bf16 inputs) while all
    accumulation stays at full precision — same contract shape, parity
    pinned by tests/test_precision.py."""
    from .sparse import ell_rmatvec

    dtype = values.dtype
    gv = values.astype(jnp.bfloat16).astype(dtype) if fast else values
    sw = jnp.sum(w)
    sy = jnp.sum(w * y)
    syy = jnp.sum(w * y * y)
    sx = ell_rmatvec(values, indices, w, d)
    c = ell_rmatvec(gv, indices, w * y, d)

    # tiled gram accumulation: scan a reshape of the full-tile prefix (free,
    # contiguous view) + one direct tail step — never jnp.pad the whole block
    # (that would materialize a second ELL-sized buffer)
    n = values.shape[0]
    k_max = values.shape[1]
    tile = min(tile, n)
    n_full = (n // tile) * tile

    def add_tile(G, args):
        v, i, wt = args  # [b, k_max] ...
        contrib = jnp.einsum("nk,n,nl->nkl", v, wt, v)
        ii = jnp.broadcast_to(i[:, :, None], contrib.shape)
        jj = jnp.broadcast_to(i[:, None, :], contrib.shape)
        G = G.at[ii.ravel(), jj.ravel()].add(contrib.ravel())
        return G, None

    G = jnp.zeros((d, d), dtype)
    if n_full:
        G, _ = jax.lax.scan(
            add_tile,
            G,
            (
                gv[:n_full].reshape(-1, tile, k_max),
                indices[:n_full].reshape(-1, tile, k_max),
                w[:n_full].reshape(-1, tile),
            ),
        )
    if n - n_full:
        G, _ = add_tile(G, (gv[n_full:], indices[n_full:], w[n_full:]))
    return sw, sx, sy, G, c, syy


def _solve_grid_from_stats(
    stats, dtype, alphas, l1_ratios, *, fit_intercept, standardize, use_cd, max_iter, tol
) -> Dict[str, jax.Array]:
    """vmap the replicated (d, d) solve over an (alpha, l1_ratio) grid. The
    data-dependent sufficient statistics are shared — the WHOLE grid costs
    one pass over X regardless of grid size. Converged CD elements freeze
    exactly (`_cd_elastic_net`), so every grid point matches its sequential
    counterpart."""

    def solve(a, l1):
        return _solve_from_stats(
            stats, dtype,
            alpha=a, l1_ratio=l1, fit_intercept=fit_intercept,
            standardize=standardize, use_cd=use_cd, max_iter=max_iter, tol=tol,
        )

    return jax.vmap(solve)(alphas, l1_ratios)


@partial(jax.jit, static_argnames=("fit_intercept", "standardize", "max_iter", "use_cd", "fast"))
def linear_fit_batched(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    alphas: jax.Array,  # [S] Spark regParam grid
    l1_ratios: jax.Array,  # [S] elasticNetParam grid
    *,
    fit_intercept: bool = True,
    standardize: bool = True,
    use_cd: bool = False,
    max_iter: int = 1000,
    tol: float = 1e-6,
    fast: bool = False,
) -> Dict[str, jax.Array]:
    """ONE compiled program solving a whole (alpha, l1_ratio) grid: the
    normal-equation sufficient statistics are computed in ONE distributed
    pass and every grid point solves on the replicated (d, d) gram — grid
    size adds zero passes over the data. `use_cd` is a static of the traced
    program (it selects the solver), so the model layer groups grids by it.

    Returns the `linear_fit` dict with a leading [S] axis on every entry."""
    stats = _sufficient_stats(X, y, w, fast)
    return _solve_grid_from_stats(
        stats, X.dtype, alphas, l1_ratios,
        fit_intercept=fit_intercept, standardize=standardize, use_cd=use_cd,
        max_iter=max_iter, tol=tol,
    )


@partial(
    jax.jit,
    static_argnames=("d", "tile", "fit_intercept", "standardize", "max_iter", "use_cd", "fast"),
)
def linear_fit_ell_batched(
    values: jax.Array,
    indices: jax.Array,
    y: jax.Array,
    w: jax.Array,
    alphas: jax.Array,
    l1_ratios: jax.Array,
    *,
    d: int,
    fit_intercept: bool = True,
    standardize: bool = True,
    use_cd: bool = False,
    max_iter: int = 1000,
    tol: float = 1e-6,
    tile: int = 8192,
    fast: bool = False,
) -> Dict[str, jax.Array]:
    """Sparse (padded-ELL) analog of `linear_fit_batched`: one tiled gram
    accumulation feeds the whole grid's solves."""
    stats = _ell_sufficient_stats(values, indices, y, w, d, tile, fast)
    return _solve_grid_from_stats(
        stats, values.dtype, alphas, l1_ratios,
        fit_intercept=fit_intercept, standardize=standardize, use_cd=use_cd,
        max_iter=max_iter, tol=tol,
    )


def _solve_from_stats(
    stats, dtype, *, alpha, l1_ratio, fit_intercept, standardize, use_cd, max_iter, tol
) -> Dict[str, jax.Array]:
    sw, sx, sy, G, c, syy = stats

    if fit_intercept:
        xm = sx / sw
        ym = sy / sw
        Gc = G - sw * jnp.outer(xm, xm)
        cc = c - sx * ym
    else:
        xm = jnp.zeros_like(sx)
        ym = jnp.zeros((), dtype)
        Gc, cc = G, c

    var = jnp.maximum(jnp.diag(Gc) / sw, 0.0)
    if standardize:
        sigma = jnp.sqrt(var)
        d_scale = jnp.where(sigma > 0, 1.0 / jnp.maximum(sigma, 1e-30), 0.0)
    else:
        d_scale = jnp.ones_like(var)

    Gs = Gc * d_scale[:, None] * d_scale[None, :]
    cs = cc * d_scale

    alpha = jnp.asarray(alpha, dtype)
    if use_cd:
        A = Gs / sw
        r = cs / sw
        b_s, n_iter = _cd_elastic_net(A, r, alpha, jnp.asarray(l1_ratio, dtype), max_iter, tol)
    else:
        # ridge normal equations; alpha==0 degenerates to OLS (+ tiny jitter for
        # numerical safety on singular grams)
        eye = jnp.eye(Gs.shape[0], dtype=dtype)
        ridge_term = alpha * sw + jnp.asarray(1e-10, dtype) * jnp.trace(Gs) / Gs.shape[0]
        b_s = jnp.linalg.solve(Gs + ridge_term * eye, cs)
        n_iter = jnp.array(1, jnp.int32)

    coef = b_s * d_scale
    intercept = jnp.where(fit_intercept, ym - jnp.dot(xm, coef), jnp.zeros((), dtype))

    # training summary stats (RegressionMetrics inputs)
    rss = syy - 2.0 * jnp.dot(coef, c) - 2.0 * intercept * sy + jnp.dot(coef, G @ coef) \
        + 2.0 * intercept * jnp.dot(sx, coef) + intercept * intercept * sw
    return {"coef_": coef, "intercept_": intercept, "n_iter_": n_iter, "rss_": jnp.maximum(rss, 0.0), "sw_": sw}


# names for the host-retained sufficient-statistics checkpoint payload, in
# `_sufficient_stats` tuple order
_STATS_NAMES = ("sw", "sx", "sy", "G", "c", "syy")

_stats_jit = jax.jit(_sufficient_stats, static_argnames=("fast",))
_ell_stats_jit = jax.jit(_ell_sufficient_stats, static_argnames=("d", "tile", "fast"))


@partial(jax.jit, static_argnames=("fit_intercept", "standardize", "max_iter", "use_cd"))
def _solve_stats_jit(
    stats, dtype_probe, *, alpha, l1_ratio, fit_intercept, standardize, use_cd,
    max_iter, tol,
):
    return _solve_from_stats(
        stats, dtype_probe.dtype,
        alpha=alpha, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, use_cd=use_cd, max_iter=max_iter, tol=tol,
    )


def _fit_from_retained_stats(
    compute_stats, dtype, *, alpha, l1_ratio, fit_intercept, standardize,
    use_cd, max_iter, tol, ckpt_key, placement_key,
) -> Dict[str, jax.Array]:
    """Linear-family fit through host-RETAINED sufficient statistics
    (docs/robustness.md "Elastic recovery"): the one distributed data pass
    lands its (d,d)-sized outputs in the active `CheckpointStore`, so a
    transient retry — and every further param set of a sequential sweep in
    the same fit stage — solves from the retained statistics WITHOUT another
    pass over the data (``checkpoint.stats_reuses``). The replicated solve
    is deterministic given the statistics, so a resumed fit is bit-identical
    to an uninterrupted one."""
    import numpy as np

    from .. import checkpoint as _ckpt
    from ..parallel import chaos

    store = _ckpt.active_store()

    def compute() -> Dict:
        stats = compute_stats()
        return {n: np.asarray(v) for n, v in zip(_STATS_NAMES, stats)}

    if store is not None:
        state = store.get_or_compute(
            ckpt_key, compute, solver="linear", placement_key=placement_key
        )
    else:
        state = compute()
    # mid-solve fault injection point: `fail:stage=solve` fires after the
    # stats were retained, so the retried attempt provably reuses them
    chaos.maybe_fail_stage("solve", 0)
    stats = tuple(jnp.asarray(state[n], dtype) for n in _STATS_NAMES)
    return _solve_stats_jit(
        stats, jnp.zeros((), dtype),
        alpha=alpha, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, use_cd=use_cd, max_iter=int(max_iter), tol=tol,
    )


def linear_fit_checkpointed(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    *,
    alpha: float,
    l1_ratio: float,
    fit_intercept: bool = True,
    standardize: bool = True,
    use_cd: bool = False,
    max_iter: int = 1000,
    tol: float = 1e-6,
    fast: bool = False,
    ckpt_key: str = "linear_stats",
    placement_key=None,
) -> Dict[str, jax.Array]:
    """`linear_fit` with the sufficient statistics retained on host (see
    `_fit_from_retained_stats`). The statistics depend only on (X, y, w) —
    never on alpha/l1_ratio — so one retained pass serves a whole sequential
    hyperparameter sweep AND any bounded-retry resume. `fast` statistics are
    keyed separately: a bf16 pass must never be resumed from (or serve) a
    full-precision one."""
    if fast:
        ckpt_key = ckpt_key + ":bf16"
    return _fit_from_retained_stats(
        lambda: _stats_jit(X, y, w, fast=fast), X.dtype,
        alpha=alpha, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, use_cd=use_cd, max_iter=max_iter, tol=tol,
        ckpt_key=ckpt_key, placement_key=placement_key,
    )


def linear_fit_ell_checkpointed(
    values: jax.Array,
    indices: jax.Array,
    y: jax.Array,
    w: jax.Array,
    *,
    d: int,
    alpha: float,
    l1_ratio: float,
    fit_intercept: bool = True,
    standardize: bool = True,
    use_cd: bool = False,
    max_iter: int = 1000,
    tol: float = 1e-6,
    tile: int = 8192,
    fast: bool = False,
    ckpt_key: str = "linear_stats_ell",
    placement_key=None,
) -> Dict[str, jax.Array]:
    """Sparse (padded-ELL) analog of `linear_fit_checkpointed`: the tiled
    gram accumulation is the retained pass."""
    if fast:
        ckpt_key = ckpt_key + ":bf16"
    return _fit_from_retained_stats(
        lambda: _ell_stats_jit(values, indices, y, w, d=d, tile=min(tile, values.shape[0]), fast=fast),
        values.dtype,
        alpha=alpha, l1_ratio=l1_ratio, fit_intercept=fit_intercept,
        standardize=standardize, use_cd=use_cd, max_iter=max_iter, tol=tol,
        ckpt_key=ckpt_key, placement_key=placement_key,
    )


@jax.jit
def linear_predict(X: jax.Array, coef: jax.Array, intercept: jax.Array) -> jax.Array:
    return X @ coef + intercept
