#
# Sparse-matrix support for the solvers: CSR -> padded ELL, ELL matvec, and
# sparse column moments.
#
# The reference's sparse path hands scipy/cupyx CSR straight to cuML's qn
# solver (reference classification.py:975-1098, incl. the int64-index
# fallback). CSR is a poor fit for XLA: ragged rows mean dynamic shapes. The
# TPU-native layout is padded ELL — every row stores exactly `k_max`
# (column-index, value) pairs, short rows padded with (0, 0.0) — which makes
# every sparse op a static-shape gather/scatter the compiler can tile:
#
#   * X @ B       -> gather B rows by index, einsum-reduce over the k axis
#   * column sums -> scatter-add of values into a [d] accumulator
#
# Zero-padding is self-neutralizing in both (value 0 contributes nothing), so
# no masks are needed. Under the row-sharded mesh the same code is SPMD: the
# gather is local (B is replicated), the scatter-add and loss reductions are
# partial sums XLA completes with psum — the NCCL allreduce of the reference.
#
# Density guidance: ELL costs n*k_max*(4+itemsize) bytes. For the reference's
# headline sparse shape (1e7 x 2200 at ~0.1% density, tests_large) k_max is a
# few dozen — orders of magnitude below dense. Pathologically skewed rows
# (k_max ~ d) would be better densified; `csr_to_ell` reports k_max so callers
# can decide.
#
from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import telemetry


def csr_to_ell(
    csr, k_max: int | None = None, dtype=None
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Convert a scipy CSR matrix to padded ELL host arrays.

    Returns ``(indices [n, k_max] int32, values [n, k_max], k_max)``; rows with
    fewer than `k_max` nonzeros are padded with index 0 / value 0. When `k_max`
    is given (e.g. the rendezvous-agreed global max under SPMD) rows are padded
    to it; it must cover the widest local row.
    """
    csr = csr.tocsr()
    n, _ = csr.shape
    row_nnz = np.diff(csr.indptr)
    local_max = int(row_nnz.max()) if n else 0
    if k_max is None:
        k_max = local_max
    elif local_max > k_max:
        raise ValueError(f"k_max={k_max} < widest row nnz {local_max}")
    dtype = dtype or csr.dtype
    indices = np.zeros((n, max(k_max, 1)), dtype=np.int32)
    values = np.zeros((n, max(k_max, 1)), dtype=dtype)
    # vectorized fill, one row-chunk at a time: the whole-matrix scatter needs
    # (rows, offsets) index temporaries of 16 bytes/nnz — at the 1e7 x 2200
    # scale shape that is more memory than the data itself. Chunking bounds
    # the temporaries by core.config["ingest_chunk_bytes"].
    if csr.nnz:
        from ..data import ingest_chunk_rows

        step = ingest_chunk_rows(max(k_max, 1) * (4 + np.dtype(dtype).itemsize))
        indptr = csr.indptr
        for lo in range(0, n, step):
            hi = min(lo + step, n)
            nnz_lo, nnz_hi = int(indptr[lo]), int(indptr[hi])
            if nnz_hi == nnz_lo:
                continue
            cnt = row_nnz[lo:hi]
            rows = np.repeat(np.arange(hi - lo), cnt)
            offsets = np.arange(nnz_hi - nnz_lo) - np.repeat(indptr[lo:hi] - nnz_lo, cnt)
            indices[lo:hi][rows, offsets] = csr.indices[nnz_lo:nnz_hi].astype(np.int32)
            values[lo:hi][rows, offsets] = csr.data[nnz_lo:nnz_hi].astype(dtype, copy=False)
    if telemetry.enabled():
        reg = telemetry.registry()
        reg.inc("sparse.csr_to_ell_calls")
        reg.inc("sparse.ell_rows", n)
        reg.inc("sparse.ell_bytes", values.nbytes + indices.nbytes)
        # density bookkeeping: how many ELL cells are padding (value 0)
        reg.inc("sparse.ell_pad_cells", n * max(k_max, 1) - int(csr.nnz))
        reg.gauge("sparse.k_max", max(k_max, 1))
    return indices, values, max(k_max, 1)


def ell_matmul(values: jax.Array, indices: jax.Array, B: jax.Array) -> jax.Array:
    """X @ B for ELL X: gather the needed B rows, reduce over the nnz axis.

    values/indices [n, k_max], B [d, k_out] -> [n, k_out]. Padding entries
    gather B[0] but multiply by 0.
    """
    return jnp.einsum("nk,nko->no", values, B[indices])


def ell_matvec(values: jax.Array, indices: jax.Array, b: jax.Array) -> jax.Array:
    """X @ b for ELL X: [n, k_max] x [d] -> [n]."""
    return jnp.sum(values * b[indices], axis=1)


def ell_rmatvec(values: jax.Array, indices: jax.Array, r: jax.Array, d: int) -> jax.Array:
    """Xᵀ @ r for ELL X: scatter-add of r-scaled values into a [d] vector."""
    return jnp.zeros((d,), values.dtype).at[indices.ravel()].add(
        (values * r[:, None]).ravel()
    )


def ell_col_moments(
    values: jax.Array, indices: jax.Array, w: jax.Array, d: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Weighted per-column moments of ELL X without densifying.

    Returns (total_w, mean [d], var [d]) with var = E[x²] − mean² (population).
    Padding (value 0) never contributes; implicit zeros DO contribute to the
    moments exactly as in the dense computation because sums over missing
    entries are 0 and the divisor is the full Σw.
    """
    total_w = jnp.sum(w)
    wv = values * w[:, None]
    s1 = jnp.zeros((d,), values.dtype).at[indices.ravel()].add(wv.ravel())
    s2 = jnp.zeros((d,), values.dtype).at[indices.ravel()].add((wv * values).ravel())
    mean = s1 / total_w
    var = s2 / total_w - mean * mean
    return total_w, mean, var
