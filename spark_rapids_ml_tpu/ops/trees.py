#
# Distributed random-forest solver — the in-tree replacement for
# `cuml.RandomForestClassifier/Regressor` + Treelite concat (consumed by
# reference tree.py:324-378).
#
# TPU-native design (no CUDA-style per-node kernels):
#  * features are QUANTILE-BINNED once (maxBins edges from a host sample — the
#    same sketch-then-bin scheme Spark ML uses), so tree growth only touches
#    compact bin ids (uint8 at <=256 bins);
#  * trees grow LEVEL-WISE in a full binary-array layout: one
#    `jax.ops.segment_sum` scatter per level builds the (node, feature, bin,
#    stat) histogram for every active row at once, prefix sums over bins give
#    every candidate split's left/right stats, and the best (feature, bin) per
#    node is an argmax — all static shapes, fully jittable;
#  * deep levels are processed in node CHUNKS to bound the histogram tensor
#    (the `max_batch_size` idea of cuML's RF builder);
#  * the ensemble is split across the mesh exactly like the reference
#    (_estimators_per_worker, tree.py:270-281): each device grows its share of
#    trees on ITS row shard via shard_map (no collectives during growth), and
#    the stacked tree arrays are gathered at the end — the Treelite-concat
#    analog with arrays instead of serialized C++ objects.
#
# A forest is a dict of arrays (n_trees leading axis):
#   feature   [T, M] int32   (-1 = leaf)           M = 2^(max_depth+1) - 1
#   threshold [T, M] f32     (split: x <= thr -> left child 2i+1)
#   leaf      [T, M, S] f32  (class counts / (w, wy) stats per node)
#
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------


def quantile_bins(x_host: np.ndarray, max_bins: int, sample_cap: int = 100_000, seed: int = 0) -> np.ndarray:
    """Per-feature quantile bin edges from a host sample: [d, max_bins-1].

    Mirrors Spark ML's approxQuantile-based continuous-feature binning."""
    n = x_host.shape[0]
    if n > sample_cap:
        idx = np.random.default_rng(seed).choice(n, sample_cap, replace=False)
        sample = np.asarray(x_host[idx], dtype=np.float64)
    else:
        sample = np.asarray(x_host, dtype=np.float64)
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    edges = np.quantile(sample, qs, axis=0).T  # [d, max_bins-1]
    return np.ascontiguousarray(edges)


@jax.jit
def bin_features(X: jax.Array, edges: jax.Array) -> jax.Array:
    """X [n, d] -> bin ids [n, d] via per-feature searchsorted.

    Stored uint8 when max_bins <= 256 (the protocol's 128-bin config halves the
    persistent binned-matrix footprint vs int32 — 3 GiB instead of 12 GiB at
    1M x 3k); consumers upcast at the arithmetic sites."""
    out_dtype = jnp.uint8 if edges.shape[1] + 1 <= 256 else jnp.int32

    def one_feature(col, e):
        return jnp.searchsorted(e, col, side="left").astype(out_dtype)

    return jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(X, edges)


# ---------------------------------------------------------------------------
# Impurity / split evaluation
# ---------------------------------------------------------------------------


def _split_gains(hist: jax.Array, impurity: str, min_instances: float):
    """hist: [S, C, d, B] per-node histograms (STAT-MAJOR layout: the bin axis
    B sits in the 128-lane tile dimension — a stat-minor [C, d, B, S] layout
    pads S=2 up to 128 lanes, a 64x memory blowup that crashes the TPU worker
    at benchmark scale). Returns (gain [C, d, B], total [C, S]) where
    gain[c, f, b] is the impurity decrease of splitting node c on feature f at
    bin <= b."""
    left = jnp.cumsum(hist, axis=3)  # [S, C, d, B]
    total_s = left[:, :, 0, -1]  # [S, C] (any feature's full sum)
    right = total_s[:, :, None, None] - left

    if impurity in ("gini", "entropy"):
        def node_impurity(stats):  # stats [S, ...] class counts
            cnt = jnp.sum(stats, axis=0)
            p = stats / jnp.maximum(cnt, 1e-30)[None]
            if impurity == "gini":
                return 1.0 - jnp.sum(p * p, axis=0), cnt
            return -jnp.sum(jnp.where(p > 0, p * jnp.log2(p), 0.0), axis=0), cnt

        imp_l, cnt_l = node_impurity(left)
        imp_r, cnt_r = node_impurity(right)
        imp_p, cnt_p = node_impurity(total_s)  # [C], [C]
        cnt_p_b = cnt_p[:, None, None]
        weighted_child = (cnt_l * imp_l + cnt_r * imp_r) / jnp.maximum(cnt_p_b, 1e-30)
        gain = imp_p[:, None, None] - weighted_child
    else:  # variance (regression): S = (w, wy, wyy)
        w_l, wy_l, wyy_l = left[0], left[1], left[2]
        w_r, wy_r, wyy_r = right[0], right[1], right[2]
        w_p = total_s[0][:, None, None]

        def var_sum(w_, wy_, wyy_):  # Σw·(y-μ)² = Σwy² − (Σwy)²/Σw
            return wyy_ - wy_ * wy_ / jnp.maximum(w_, 1e-30)

        ss_p = var_sum(total_s[0], total_s[1], total_s[2])[:, None, None]
        ss_child = var_sum(w_l, wy_l, wyy_l) + var_sum(w_r, wy_r, wyy_r)
        gain = (ss_p - ss_child) / jnp.maximum(w_p, 1e-30)
        cnt_l, cnt_r = w_l, w_r
        cnt_p_b = w_p

    valid = (cnt_l >= min_instances) & (cnt_r >= min_instances)
    # the last bin means "everything left" — never a real split
    valid = valid & (jnp.arange(hist.shape[3])[None, None, :] < hist.shape[3] - 1)
    return jnp.where(valid, gain, -jnp.inf), total_s.T


def _feature_subset_ids(key, n_nodes: int, d: int, m: int):
    """Exact-m random feature subset per node: int32 ids [n_nodes, m].

    The subset is applied WHERE THE WORK IS: histogram accumulation only
    touches the m chosen features per node (seg space chunk·m·B), so
    featureSubsetStrategy="auto" (√d for classification, d/3 for regression —
    Spark semantics) cuts the dominant scatter work by d/m (~54× at the
    protocol's 3000-feature classification config), instead of masking gains
    after a full-d histogram pass."""
    if m >= d:
        return jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32), (n_nodes, d))
    u = jax.random.uniform(key, (n_nodes, d))
    return jnp.argsort(u, axis=1)[:, :m].astype(jnp.int32)


# ---------------------------------------------------------------------------
# Single-tree growth (level-wise, full binary layout)
# ---------------------------------------------------------------------------


def _grow_tree(
    key,
    Xb: jax.Array,  # [n, d] bin ids (uint8 at <=256 bins; upcast at arithmetic sites)
    stats_row: jax.Array,  # [n, S] per-row stat contributions (already w-weighted)
    params: Dict,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Grow one tree; returns (feature [M], split_bin [M], node_stats [M, S])."""
    n, d = Xb.shape
    S = stats_row.shape[1]
    B = params["max_bins"]
    max_depth = params["max_depth"]
    node_cap = params["node_chunk"]
    M = 2 ** (max_depth + 1) - 1

    feature = jnp.full((M,), -1, jnp.int32)
    split_bin = jnp.zeros((M,), jnp.int32)
    node_stats = jnp.zeros((M, S), stats_row.dtype)
    node_id = jnp.zeros((n,), jnp.int32)  # current node per row (level-order id)
    active = jnp.ones((n,), bool)  # row not yet in a leaf

    m = min(params["max_features"], d)
    for depth in range(max_depth):
        level_size = 2**depth
        offset = level_size - 1
        n_chunks = max(1, -(-level_size // node_cap))
        chunk = min(level_size, node_cap)
        key, kf = jax.random.split(key)
        fids_level = _feature_subset_ids(kf, level_size, d, m)  # [level, m]

        # histogram accumulation is tiled over ROWS: the scatter operand is
        # bounded to ~4M elements per pass. One [n*m]-sized scatter both
        # crashes the TPU worker at moderate scale (observed: kernel fault at
        # 50k x 500) and would materialize a huge seg intermediate at the
        # 1M x 3k protocol shape.
        tile_rows = min(n, max(256, 4_000_000 // max(m, 1)))
        n_row_tiles = -(-n // tile_rows)
        n_seg = chunk * m * B

        def chunk_body(ci, carry):
            feature, split_bin, node_stats = carry
            c0 = offset + ci * chunk
            fids = jax.lax.dynamic_slice_in_dim(fids_level, ci * chunk, chunk, 0)  # [chunk, m]

            def row_tile_body(ti, hist_cols):
                # clamp the last tile back and mask rows already covered
                r0 = jnp.minimum(ti * tile_rows, n - tile_rows)
                fresh = (r0 + jnp.arange(tile_rows)) >= ti * tile_rows
                xb_t = jax.lax.dynamic_slice(Xb, (r0, 0), (tile_rows, d))
                nid_t = jax.lax.dynamic_slice(node_id, (r0,), (tile_rows,))
                act_t = jax.lax.dynamic_slice(active, (r0,), (tile_rows,))
                st_t = jax.lax.dynamic_slice(stats_row, (r0, 0), (tile_rows, S))
                local = nid_t - c0
                ok = act_t & (local >= 0) & (local < chunk) & fresh
                # each row's bins at ITS node's feature subset: [rows, m]
                ids_r = fids[jnp.clip(local, 0, chunk - 1)]  # [rows, m]
                xb_sub = jnp.take_along_axis(xb_t, ids_r.astype(jnp.int32), axis=1)
                # flat segment id: (node_local * m + j) * B + bin
                seg = (local[:, None] * m + jnp.arange(m)[None, :]) * B + xb_sub.astype(jnp.int32)
                seg = jnp.where(ok[:, None], seg, n_seg)  # dump masked rows
                seg_flat = seg.reshape(-1)
                # one 1-D scatter PER STAT column: a [rows, S] scatter operand
                # gets its minor dim padded to the 128-lane tile on TPU (64x
                # memory blowup at S=2); 1-D operands tile without padding
                return tuple(
                    hist_cols[s_i]
                    + jax.ops.segment_sum(
                        jnp.broadcast_to(st_t[:, s_i : s_i + 1], (tile_rows, m)).reshape(-1),
                        seg_flat,
                        num_segments=n_seg + 1,
                    )[:-1]
                    for s_i in range(S)
                )

            from ..parallel.mesh import ROWS_AXIS

            # the carry accumulates per-shard values: type it as varying over
            # the mesh axis (shard_map vma typing, like the KMeans carry)
            hist_cols0 = tuple(
                jax.lax.pcast(jnp.zeros((n_seg,), stats_row.dtype), ROWS_AXIS, to="varying")
                for _ in range(S)
            )
            if n_row_tiles == 1:
                hist_cols = row_tile_body(0, hist_cols0)
            else:
                hist_cols = jax.lax.fori_loop(0, n_row_tiles, row_tile_body, hist_cols0)
            hist = jnp.stack(hist_cols, axis=0).reshape(S, chunk, m, B)

            gain, total = _split_gains(hist, params["impurity"], params["min_instances"])
            flat_best = jnp.argmax(gain.reshape(chunk, -1), axis=1)
            best_gain = jnp.take_along_axis(gain.reshape(chunk, -1), flat_best[:, None], 1)[:, 0]
            best_j = (flat_best // B).astype(jnp.int32)
            best_f = jnp.take_along_axis(fids, best_j[:, None], axis=1)[:, 0].astype(jnp.int32)
            best_b = (flat_best % B).astype(jnp.int32)

            is_split = best_gain > params["min_info_gain"]
            feature = jax.lax.dynamic_update_slice_in_dim(
                feature, jnp.where(is_split, best_f, -1), c0, 0
            )
            split_bin = jax.lax.dynamic_update_slice_in_dim(
                split_bin, jnp.where(is_split, best_b, 0), c0, 0
            )
            node_stats = jax.lax.dynamic_update_slice(node_stats, total, (c0, 0))
            return feature, split_bin, node_stats

        # deep levels iterate chunks in a fori_loop: unrolling them in Python
        # (63 chunk bodies at depth 13) produced an HLO big enough to break the
        # remote TPU compiler; one rolled body per level keeps it linear in
        # depth
        if n_chunks == 1:
            feature, split_bin, node_stats = chunk_body(0, (feature, split_bin, node_stats))
        else:
            feature, split_bin, node_stats = jax.lax.fori_loop(
                0, n_chunks, chunk_body, (feature, split_bin, node_stats)
            )

        # advance rows: split nodes send rows to children; leaf rows deactivate
        node_f = feature[node_id]
        went_split = active & (node_f >= 0)
        row_bin = jnp.take_along_axis(Xb, jnp.maximum(node_f, 0)[:, None], axis=1)[:, 0]
        go_left = row_bin.astype(jnp.int32) <= split_bin[node_id]
        child = 2 * node_id + jnp.where(go_left, 1, 2)
        node_id = jnp.where(went_split, child, node_id)
        active = went_split

    # last level: record stats for rows that reached it (all remaining leaves)
    level_size = 2**max_depth
    offset = level_size - 1
    local = node_id - offset
    in_level = active & (local >= 0)
    seg = jnp.where(in_level, local, level_size)
    last_stats = jnp.stack(
        [
            jax.ops.segment_sum(stats_row[:, s_i], seg, num_segments=level_size + 1)[:-1]
            for s_i in range(S)
        ],
        axis=1,
    )
    node_stats = jax.lax.dynamic_update_slice(node_stats, last_stats, (offset, 0))
    return feature, split_bin, node_stats


# ---------------------------------------------------------------------------
# Forest over the mesh
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=(
        "mesh", "seed", "n_trees", "max_depth", "max_bins", "max_features", "impurity",
        "node_chunk", "bootstrap", "subsample_rate", "min_instances", "min_info_gain", "n_stats",
    ),
)
def forest_fit(
    Xb: jax.Array,  # [n_pad, d] bin ids (row-sharded; uint8 at <=256 bins)
    stats_row: jax.Array,  # [n_pad, S] per-row stats, zero on padding
    w: jax.Array,  # [n_pad] weights (bootstrap sampling distribution)
    seed: int,
    *,
    mesh,
    n_trees: int,
    max_depth: int,
    max_bins: int,
    max_features: int,
    impurity: str,
    node_chunk: int = 256,
    bootstrap: bool = True,
    subsample_rate: float = 1.0,
    min_instances: float = 1.0,
    min_info_gain: float = 0.0,
    n_stats: int = 2,
) -> Dict[str, jax.Array]:
    """Ensemble-split forest fit: device i grows trees [i*t0, (i+1)*t0) on its
    row shard. Returns stacked (feature [T, M], split_bin [T, M],
    node_stats [T, M, S])."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROWS_AXIS

    n_dev = mesh.devices.size
    trees_per_dev = -(-n_trees // n_dev)  # reference _estimators_per_worker
    params = {
        "max_depth": max_depth, "max_bins": max_bins, "max_features": max_features,
        "impurity": impurity, "node_chunk": node_chunk,
        "min_instances": min_instances, "min_info_gain": min_info_gain,
    }

    def local(Xb_l, stats_l, w_l):
        rank = jax.lax.axis_index(ROWS_AXIS)
        n_l = Xb_l.shape[0]

        def one_tree(tree_i):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), rank * trees_per_dev + tree_i)
            n_draws = int(max(1, round(subsample_rate * n_l)))
            k1, key = jax.random.split(key)
            if bootstrap:
                # draw UNIFORMLY over valid (non-padding) rows; the user weights
                # already scale stats_l, so weighting the draw too would apply
                # them twice (w² effective weighting)
                valid = (w_l > 0).astype(stats_l.dtype)
                p = valid / jnp.maximum(jnp.sum(valid), 1e-30)
                idx = jax.random.choice(k1, n_l, (n_draws,), replace=True, p=p)
                wb = jnp.zeros((n_l,), stats_l.dtype).at[idx].add(1.0)
            elif subsample_rate < 1.0:
                # subsample without replacement (Spark bootstrap=False semantics);
                # padding rows drawn here contribute nothing (stats are w-scaled)
                idx = jax.random.choice(k1, n_l, (n_draws,), replace=False)
                wb = jnp.zeros((n_l,), stats_l.dtype).at[idx].set(1.0)
            else:
                wb = jnp.ones((n_l,), stats_l.dtype)
            return _grow_tree(key, Xb_l, stats_l * wb[:, None], params)

        feats, bins_, nstats = jax.lax.map(one_tree, jnp.arange(trees_per_dev))
        return feats, bins_, nstats

    feats, bins_, nstats = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS, None), P(ROWS_AXIS)),
        out_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS, None), P(ROWS_AXIS, None, None)),
    )(Xb, stats_row, w)
    # out axis 0 is [n_dev * trees_per_dev] (device-major) — the tree concat.
    # Replicate the (small) tree arrays so every process can fetch the full
    # forest under multi-process SPMD — the in-graph form of the reference's
    # serialized-tree allGather + concat (tree.py:333-378).
    from jax.sharding import NamedSharding

    rep = NamedSharding(mesh, P())
    feats, bins_, nstats = (
        jax.lax.with_sharding_constraint(a, rep) for a in (feats, bins_, nstats)
    )
    return {"feature": feats, "split_bin": bins_, "node_stats": nstats}


# ---------------------------------------------------------------------------
# Prediction
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("max_depth",))
def forest_raw_predict(
    X: jax.Array,  # [n, d] float
    feature: jax.Array,  # [T, M]
    threshold: jax.Array,  # [T, M] real-valued thresholds
    leaf_value: jax.Array,  # [T, M, S]
    *,
    max_depth: int,
) -> jax.Array:
    """Average of per-tree leaf values: [n, S]. Traversal is a fixed-depth
    gather loop (vectorized oblivious descent, SURVEY.md §7 architecture map)."""

    def one_tree(feat, thr, leaves):
        def step(_, node):
            f = feat[node]
            is_split = f >= 0
            xv = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
            child = 2 * node + jnp.where(xv <= thr[node], 1, 2)
            return jnp.where(is_split, child, node)

        node = jax.lax.fori_loop(0, max_depth, step, jnp.zeros(X.shape[0], jnp.int32))
        return leaves[node]  # [n, S]

    per_tree = jax.vmap(one_tree)(feature, threshold, leaf_value)  # [T, n, S]
    return jnp.mean(per_tree, axis=0)


def split_bins_to_thresholds(
    feature: np.ndarray, split_bin: np.ndarray, edges: np.ndarray
) -> np.ndarray:
    """Convert bin-id splits to real thresholds using the bin edges.

    Split 'bin <= b' corresponds to 'x <= edges[f, b]' (searchsorted-left)."""
    f = np.maximum(feature, 0)
    b = np.minimum(split_bin, edges.shape[1] - 1)
    thr = edges[f, b]
    return np.where(feature >= 0, thr, np.inf).astype(np.float64)
