#
# Distributed random-forest solver — the in-tree replacement for
# `cuml.RandomForestClassifier/Regressor` + Treelite concat (consumed by
# reference tree.py:324-378).
#
# TPU-native design (no CUDA-style per-node kernels):
#  * features are QUANTILE-BINNED once (maxBins edges from a host sample — the
#    same sketch-then-bin scheme Spark ML uses), so tree growth only touches
#    compact bin ids (uint8 at <=256 bins);
#  * trees grow LEVEL-WISE in a full binary-array layout: one
#    `jax.ops.segment_sum` scatter per level builds the (node, feature, bin,
#    stat) histogram for every active row at once, prefix sums over bins give
#    every candidate split's left/right stats, and the best (feature, bin) per
#    node is an argmax — all static shapes, fully jittable;
#  * deep levels are processed in node CHUNKS to bound the histogram tensor
#    (the `max_batch_size` idea of cuML's RF builder);
#  * the ensemble is split across the mesh exactly like the reference
#    (_estimators_per_worker, tree.py:270-281): each device grows its share of
#    trees on ITS row shard via shard_map (no collectives during growth), and
#    the stacked tree arrays are gathered at the end — the Treelite-concat
#    analog with arrays instead of serialized C++ objects.
#
# A forest is a dict of arrays (n_trees leading axis):
#   feature   [T, M] int32   (-1 = leaf)           M = 2^(max_depth+1) - 1
#   threshold [T, M] f32     (split: x <= thr -> left child 2i+1)
#   leaf      [T, M, S] f32  (class counts / (w, wy) stats per node)
#
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------


def quantile_bins(x_host: np.ndarray, max_bins: int, sample_cap: int = 100_000, seed: int = 0) -> np.ndarray:
    """Per-feature quantile bin edges from a host sample: [d, max_bins-1].

    Mirrors Spark ML's approxQuantile-based continuous-feature binning."""
    n = x_host.shape[0]
    if n > sample_cap:
        idx = np.random.default_rng(seed).choice(n, sample_cap, replace=False)
        sample = np.asarray(x_host[idx], dtype=np.float64)
    else:
        sample = np.asarray(x_host, dtype=np.float64)
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    edges = np.quantile(sample, qs, axis=0).T  # [d, max_bins-1]
    return np.ascontiguousarray(edges)


def _bin_dtype(edges):
    return jnp.uint8 if edges.shape[1] + 1 <= 256 else jnp.int32


def _bin_impl(X: jax.Array, edges: jax.Array) -> jax.Array:
    out_dtype = _bin_dtype(edges)

    def one_feature(col, e):
        return jnp.searchsorted(e, col, side="left").astype(out_dtype)

    return jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(X, edges)


_bin_all = jax.jit(_bin_impl)


@partial(jax.jit, static_argnames=("size",), donate_argnums=(2,))
def _bin_tile(X, edges, out, start, *, size):
    xb = jax.lax.dynamic_slice(X, (start, 0), (size, X.shape[1]))
    return jax.lax.dynamic_update_slice(out, _bin_impl(xb, edges), (start, 0))


def bin_features(X: jax.Array, edges: jax.Array, batch_rows: int = 0) -> jax.Array:
    """X [n, d] -> bin ids [n, d] via per-feature searchsorted.

    Stored uint8 when max_bins <= 256 (the protocol's 128-bin config halves the
    persistent binned-matrix footprint vs int32 — 3 GiB instead of 12 GiB at
    1M x 3k); consumers upcast at the arithmetic sites.

    Large single-device inputs are binned in row tiles (host loop of
    dynamic_slice programs into one donated output buffer): XLA's
    searchsorted lowering keeps ~5 s32/f32 temporaries at the FULL operand
    shape through its while loop, so a monolithic [1M, 3k] program wants
    >50 GB of temp HBM next to the 11 GB X (compile-time OOM on one chip).
    The default tile bounds the temps to ~1 GB. Sharded inputs keep the
    one-program path (per-shard size is what matters there)."""
    n, d = X.shape
    if not batch_rows:
        # ~5 full-shape temps in the searchsorted while loop, target <=1 GB
        batch_rows = max(1024, int(50_000_000 // max(d, 1)))
    one_dev = not hasattr(X, "devices") or len(X.devices()) == 1
    if not one_dev or n <= 2 * batch_rows:
        return _bin_all(X, edges)
    import numpy as np

    out = jnp.zeros((n, d), _bin_dtype(edges))
    n_full = (n // batch_rows) * batch_rows
    for start in range(0, n_full, batch_rows):
        out = _bin_tile(X, edges, out, np.int32(start), size=batch_rows)
    if n - n_full:
        out = _bin_tile(X, edges, out, np.int32(n_full), size=n - n_full)
    return out


# ---------------------------------------------------------------------------
# Impurity / split evaluation
# ---------------------------------------------------------------------------


def _split_gains(hist: jax.Array, impurity: str, min_instances: float):
    """hist: [S, C, d, B] per-node histograms (STAT-MAJOR layout: the bin axis
    B sits in the 128-lane tile dimension — a stat-minor [C, d, B, S] layout
    pads S=2 up to 128 lanes, a 64x memory blowup that crashes the TPU worker
    at benchmark scale). Returns (gain [C, d, B], total [C, S]) where
    gain[c, f, b] is the impurity decrease of splitting node c on feature f at
    bin <= b."""
    left = jnp.cumsum(hist, axis=3)  # [S, C, d, B]
    total_s = left[:, :, 0, -1]  # [S, C] (any feature's full sum)
    right = total_s[:, :, None, None] - left

    if impurity in ("gini", "entropy"):
        def node_impurity(stats):  # stats [S, ...] class counts
            cnt = jnp.sum(stats, axis=0)
            p = stats / jnp.maximum(cnt, 1e-30)[None]
            if impurity == "gini":
                return 1.0 - jnp.sum(p * p, axis=0), cnt
            return -jnp.sum(jnp.where(p > 0, p * jnp.log2(p), 0.0), axis=0), cnt

        imp_l, cnt_l = node_impurity(left)
        imp_r, cnt_r = node_impurity(right)
        imp_p, cnt_p = node_impurity(total_s)  # [C], [C]
        cnt_p_b = cnt_p[:, None, None]
        weighted_child = (cnt_l * imp_l + cnt_r * imp_r) / jnp.maximum(cnt_p_b, 1e-30)
        gain = imp_p[:, None, None] - weighted_child
    else:  # variance (regression): S = (w, wy, wyy)
        w_l, wy_l, wyy_l = left[0], left[1], left[2]
        w_r, wy_r, wyy_r = right[0], right[1], right[2]
        w_p = total_s[0][:, None, None]

        def var_sum(w_, wy_, wyy_):  # Σw·(y-μ)² = Σwy² − (Σwy)²/Σw
            return wyy_ - wy_ * wy_ / jnp.maximum(w_, 1e-30)

        ss_p = var_sum(total_s[0], total_s[1], total_s[2])[:, None, None]
        ss_child = var_sum(w_l, wy_l, wyy_l) + var_sum(w_r, wy_r, wyy_r)
        gain = (ss_p - ss_child) / jnp.maximum(w_p, 1e-30)
        cnt_l, cnt_r = w_l, w_r
        cnt_p_b = w_p

    valid = (cnt_l >= min_instances) & (cnt_r >= min_instances)
    # the last bin means "everything left" — never a real split
    valid = valid & (jnp.arange(hist.shape[3])[None, None, :] < hist.shape[3] - 1)
    return jnp.where(valid, gain, -jnp.inf), total_s.T


def _feature_subset_ids(key, n_nodes: int, d: int, m: int):
    """Exact-m random feature subset per node: int32 ids [n_nodes, m].

    The subset is applied WHERE THE WORK IS: histogram accumulation only
    touches the m chosen features per node (seg space chunk·m·B), so
    featureSubsetStrategy="auto" (√d for classification, d/3 for regression —
    Spark semantics) cuts the dominant scatter work by d/m (~54× at the
    protocol's 3000-feature classification config), instead of masking gains
    after a full-d histogram pass."""
    if m >= d:
        return jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32), (n_nodes, d))
    u = jax.random.uniform(key, (n_nodes, d))
    return jnp.argsort(u, axis=1)[:, :m].astype(jnp.int32)


# ---------------------------------------------------------------------------
# Single-tree growth (level-wise, full binary layout)
# ---------------------------------------------------------------------------


def _tree_level(
    key,
    Xb: jax.Array,  # [n, d] bin ids (uint8 at <=256 bins; upcast at arithmetic sites)
    stats_row: jax.Array,  # [n, S] per-row stat contributions (already w-weighted)
    node_id: jax.Array,  # [n] current node per row (level-order id)
    active: jax.Array,  # [n] row not yet in a leaf
    feature: jax.Array,  # [M] chosen feature per node (−1 = leaf)
    split_bin: jax.Array,  # [M]
    node_stats: jax.Array,  # [M, S]
    params: Dict,
    depth: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Grow ONE level of one tree: chunked histograms + split selection +
    row advance. Returns (node_id, active, feature, split_bin, node_stats)."""
    n, d = Xb.shape
    S = stats_row.shape[1]
    B = params["max_bins"]
    node_cap = params["node_chunk"]
    m = min(params["max_features"], d)

    if True:  # keep the body's original indentation (one level of the old loop)
        level_size = 2**depth
        offset = level_size - 1
        n_chunks = max(1, -(-level_size // node_cap))
        chunk = min(level_size, node_cap)
        fids_level = _feature_subset_ids(key, level_size, d, m)  # [level, m]

        # histogram accumulation is tiled over ROWS: the scatter operand is
        # bounded to ~4M elements per pass. One [n*m]-sized scatter both
        # crashes the TPU worker at moderate scale (observed: kernel fault at
        # 50k x 500) and would materialize a huge seg intermediate at the
        # 1M x 3k protocol shape.
        tile_rows = min(n, max(256, 4_000_000 // max(m, 1)))
        n_row_tiles = -(-n // tile_rows)
        n_seg = chunk * m * B

        def chunk_body(ci, carry):
            feature, split_bin, node_stats = carry
            c0 = offset + ci * chunk
            fids = jax.lax.dynamic_slice_in_dim(fids_level, ci * chunk, chunk, 0)  # [chunk, m]

            def row_tile_body(ti, hist_cols):
                # clamp the last tile back and mask rows already covered
                r0 = jnp.minimum(ti * tile_rows, n - tile_rows)
                fresh = (r0 + jnp.arange(tile_rows)) >= ti * tile_rows
                xb_t = jax.lax.dynamic_slice(Xb, (r0, 0), (tile_rows, d))
                nid_t = jax.lax.dynamic_slice(node_id, (r0,), (tile_rows,))
                act_t = jax.lax.dynamic_slice(active, (r0,), (tile_rows,))
                st_t = jax.lax.dynamic_slice(stats_row, (r0, 0), (tile_rows, S))
                local = nid_t - c0
                ok = act_t & (local >= 0) & (local < chunk) & fresh
                # each row's bins at ITS node's feature subset: [rows, m]
                ids_r = fids[jnp.clip(local, 0, chunk - 1)]  # [rows, m]
                xb_sub = jnp.take_along_axis(xb_t, ids_r.astype(jnp.int32), axis=1)
                # flat segment id: (node_local * m + j) * B + bin
                seg = (local[:, None] * m + jnp.arange(m)[None, :]) * B + xb_sub.astype(jnp.int32)
                seg = jnp.where(ok[:, None], seg, n_seg)  # dump masked rows
                seg_flat = seg.reshape(-1)
                # one 1-D scatter PER STAT column: a [rows, S] scatter operand
                # gets its minor dim padded to the 128-lane tile on TPU (64x
                # memory blowup at S=2); 1-D operands tile without padding
                return tuple(
                    hist_cols[s_i]
                    + jax.ops.segment_sum(
                        jnp.broadcast_to(st_t[:, s_i : s_i + 1], (tile_rows, m)).reshape(-1),
                        seg_flat,
                        num_segments=n_seg + 1,
                    )[:-1]
                    for s_i in range(S)
                )

            from ..parallel.mesh import ROWS_AXIS, pcast_varying

            # the carry accumulates per-shard values: type it as varying over
            # the mesh axis (shard_map vma typing, like the KMeans carry)
            hist_cols0 = tuple(
                pcast_varying(jnp.zeros((n_seg,), stats_row.dtype), ROWS_AXIS)
                for _ in range(S)
            )
            if n_row_tiles == 1:
                hist_cols = row_tile_body(0, hist_cols0)
            else:
                hist_cols = jax.lax.fori_loop(0, n_row_tiles, row_tile_body, hist_cols0)
            hist = jnp.stack(hist_cols, axis=0).reshape(S, chunk, m, B)

            gain, total = _split_gains(hist, params["impurity"], params["min_instances"])
            flat_best = jnp.argmax(gain.reshape(chunk, -1), axis=1)
            best_gain = jnp.take_along_axis(gain.reshape(chunk, -1), flat_best[:, None], 1)[:, 0]
            best_j = (flat_best // B).astype(jnp.int32)
            best_f = jnp.take_along_axis(fids, best_j[:, None], axis=1)[:, 0].astype(jnp.int32)
            best_b = (flat_best % B).astype(jnp.int32)

            is_split = best_gain > params["min_info_gain"]
            feature = jax.lax.dynamic_update_slice_in_dim(
                feature, jnp.where(is_split, best_f, -1), c0, 0
            )
            split_bin = jax.lax.dynamic_update_slice_in_dim(
                split_bin, jnp.where(is_split, best_b, 0), c0, 0
            )
            node_stats = jax.lax.dynamic_update_slice(node_stats, total, (c0, 0))
            return feature, split_bin, node_stats

        # deep levels iterate chunks in a fori_loop: unrolling them in Python
        # (63 chunk bodies at depth 13) produced an HLO big enough to break the
        # remote TPU compiler; one rolled body per level keeps it linear in
        # depth
        if n_chunks == 1:
            feature, split_bin, node_stats = chunk_body(0, (feature, split_bin, node_stats))
        else:
            feature, split_bin, node_stats = jax.lax.fori_loop(
                0, n_chunks, chunk_body, (feature, split_bin, node_stats)
            )

        # advance rows: split nodes send rows to children; leaf rows deactivate
        node_f = feature[node_id]
        went_split = active & (node_f >= 0)
        row_bin = jnp.take_along_axis(Xb, jnp.maximum(node_f, 0)[:, None], axis=1)[:, 0]
        go_left = row_bin.astype(jnp.int32) <= split_bin[node_id]
        child = 2 * node_id + jnp.where(go_left, 1, 2)
        node_id = jnp.where(went_split, child, node_id)
        active = went_split
    return node_id, active, feature, split_bin, node_stats


def _tree_final_level(stats_row, node_id, active, node_stats, max_depth: int):
    """Record stats for rows that reached the last level (remaining leaves)."""
    S = stats_row.shape[1]
    level_size = 2**max_depth
    offset = level_size - 1
    local = node_id - offset
    in_level = active & (local >= 0)
    seg = jnp.where(in_level, local, level_size)
    last_stats = jnp.stack(
        [
            jax.ops.segment_sum(stats_row[:, s_i], seg, num_segments=level_size + 1)[:-1]
            for s_i in range(S)
        ],
        axis=1,
    )
    return jax.lax.dynamic_update_slice(node_stats, last_stats, (offset, 0))


def _grow_tree(
    key,
    Xb: jax.Array,
    stats_row: jax.Array,  # [n, S] per-row stat contributions (already w-weighted)
    params: Dict,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Grow one tree IN-GRAPH (all levels in the caller's trace); returns
    (feature [M], split_bin [M], node_stats [M, S]). The forest path instead
    dispatches `_tree_level` per level from the host (see forest_fit)."""
    n, d = Xb.shape
    S = stats_row.shape[1]
    max_depth = params["max_depth"]
    M = 2 ** (max_depth + 1) - 1

    feature = jnp.full((M,), -1, jnp.int32)
    split_bin = jnp.zeros((M,), jnp.int32)
    node_stats = jnp.zeros((M, S), stats_row.dtype)
    node_id = jnp.zeros((n,), jnp.int32)
    active = jnp.ones((n,), bool)
    for depth in range(max_depth):
        key, kf = jax.random.split(key)
        node_id, active, feature, split_bin, node_stats = _tree_level(
            kf, Xb, stats_row, node_id, active, feature, split_bin, node_stats,
            params, depth,
        )
    node_stats = _tree_final_level(stats_row, node_id, active, node_stats, max_depth)
    return feature, split_bin, node_stats


# ---------------------------------------------------------------------------
# Forest over the mesh
# ---------------------------------------------------------------------------


# NOT jitted: forest_fit is a HOST orchestrator — it dispatches one compact
# jitted program per (tree round, level). Wrapping it in jit would trace the
# whole ensemble into a single giant program (compile-helper OOM and
# multi-minute single dispatches that kill the TPU worker at 1M x 3k).
def forest_fit(
    Xb: jax.Array,  # [n_pad, d] bin ids (row-sharded; uint8 at <=256 bins)
    stats_row: jax.Array,  # [n_pad, S] per-row stats, zero on padding
    w: jax.Array,  # [n_pad] weights (bootstrap sampling distribution)
    seed: int,
    *,
    mesh,
    n_trees: int,
    max_depth: int,
    max_bins: int,
    max_features: int,
    impurity: str,
    node_chunk: int = 256,
    bootstrap: bool = True,
    subsample_rate: float = 1.0,
    min_instances: float = 1.0,
    min_info_gain: float = 0.0,
    n_stats: int = 2,
) -> Dict[str, jax.Array]:
    """Ensemble-split forest fit: device i grows trees [i*t0, (i+1)*t0) on its
    row shard. Returns stacked (feature [T, M], split_bin [T, M],
    node_stats [T, M, S])."""
    from ..parallel.mesh import shard_map
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROWS_AXIS

    n_dev = mesh.devices.size
    trees_per_dev = -(-n_trees // n_dev)  # reference _estimators_per_worker
    # the axon TPU runtime kernel-faults when a level's chunk fori_loop runs
    # more than ~16 iterations at benchmark scale (bisected at 1M x 3k,
    # depth 13: 32 chunks of 256 nodes crashes the worker, 16 chunks of 512
    # passes) — scale the chunk so the DEEPEST level stays within 16 chunks,
    # while keeping the per-chunk segment space (chunk*m*bins) bounded
    deepest = 1 << max(max_depth - 1, 0)
    min_chunk = -(-deepest // 16)
    seg_budget = 16_000_000
    mem_chunk = max(64, seg_budget // max(max_features * max_bins, 1))
    node_chunk = int(max(min(max(node_chunk, min_chunk), mem_chunk), min_chunk))
    params = {
        "max_depth": max_depth, "max_bins": max_bins, "max_features": max_features,
        "impurity": impurity, "node_chunk": node_chunk,
        "min_instances": min_instances, "min_info_gain": min_info_gain,
    }

    S = stats_row.shape[1]
    M = 2 ** (max_depth + 1) - 1
    n_dev_axis = P(ROWS_AXIS)

    def boot_fn(stats_l, w_l, tree_i):
        # per-device bootstrap weighting for THIS round's tree
        rank = jax.lax.axis_index(ROWS_AXIS)
        n_l = stats_l.shape[0]
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed), rank * trees_per_dev + tree_i
        )
        k1, _ = jax.random.split(key)
        n_draws = int(max(1, round(subsample_rate * n_l)))
        if bootstrap:
            # draw UNIFORMLY over valid (non-padding) rows; the user weights
            # already scale stats_l, so weighting the draw too would apply
            # them twice (w² effective weighting)
            valid = (w_l > 0).astype(stats_l.dtype)
            p = valid / jnp.maximum(jnp.sum(valid), 1e-30)
            idx = jax.random.choice(k1, n_l, (n_draws,), replace=True, p=p)
            wb = jnp.zeros((n_l,), stats_l.dtype).at[idx].add(1.0)
        elif subsample_rate < 1.0:
            # subsample without replacement (Spark bootstrap=False semantics);
            # padding rows drawn here contribute nothing (stats are w-scaled)
            idx = jax.random.choice(k1, n_l, (n_draws,), replace=False)
            wb = jnp.zeros((n_l,), stats_l.dtype).at[idx].set(1.0)
        else:
            wb = jnp.ones((n_l,), stats_l.dtype)
        return stats_l * wb[:, None]

    boot_step = jax.jit(shard_map(
        boot_fn, mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS), P()),
        out_specs=P(ROWS_AXIS, None),
    ))

    def make_level_step(depth):
        def fn(Xb_l, stw_l, nid_l, act_l, feat_b, bin_b, nst_b, tree_i):
            rank = jax.lax.axis_index(ROWS_AXIS)
            tkey = jax.random.fold_in(
                jax.random.PRNGKey(seed), rank * trees_per_dev + tree_i
            )
            kf = jax.random.fold_in(tkey, 7919 + depth)  # per-level stream
            nid, act, f, b, s = _tree_level(
                kf, Xb_l, stw_l, nid_l, act_l,
                feat_b[0], bin_b[0], nst_b[0], params, depth,
            )
            return nid, act, f[None], b[None], s[None]

        return jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(
                P(ROWS_AXIS, None), P(ROWS_AXIS, None), n_dev_axis, n_dev_axis,
                P(ROWS_AXIS, None), P(ROWS_AXIS, None), P(ROWS_AXIS, None, None),
                P(),
            ),
            out_specs=(
                n_dev_axis, n_dev_axis,
                P(ROWS_AXIS, None), P(ROWS_AXIS, None), P(ROWS_AXIS, None, None),
            ),
        ))

    level_steps = [make_level_step(depth) for depth in range(max_depth)]

    def final_fn(stw_l, nid_l, act_l, nst_b):
        return _tree_final_level(stw_l, nid_l, act_l, nst_b[0], max_depth)[None]

    final_step = jax.jit(shard_map(
        final_fn, mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), n_dev_axis, n_dev_axis, P(ROWS_AXIS, None, None)),
        out_specs=P(ROWS_AXIS, None, None),
    ))

    n_rows = Xb.shape[0]
    tree_init = jax.jit(
        lambda: (
            jnp.zeros((n_rows,), jnp.int32),
            jnp.ones((n_rows,), bool),
            jnp.full((n_dev, M), -1, jnp.int32),
            jnp.zeros((n_dev, M), jnp.int32),
            jnp.zeros((n_dev, M, S), stats_row.dtype),
        ),
        out_shardings=(
            NamedSharding(mesh, P(ROWS_AXIS)),
            NamedSharding(mesh, P(ROWS_AXIS)),
            NamedSharding(mesh, P(ROWS_AXIS, None)),
            NamedSharding(mesh, P(ROWS_AXIS, None)),
            NamedSharding(mesh, P(ROWS_AXIS, None, None)),
        ),
    )

    # HOST loops over tree rounds AND levels — one dispatch per (round,
    # level), each a compact program reused across rounds. One program
    # growing the whole ensemble (or even one whole deep tree at protocol
    # scale) is both a compile-memory hazard (the remote compile helper gets
    # OOM-killed unrolling 13 levels at 1M x 3k) and a runtime hazard (the
    # multi-minute single dispatch reproducibly kills the axon TPU worker).
    # Tree order is ROUND-major ([round0: dev0..devN, round1: ...]) — forest
    # aggregation is order-invariant.
    # Per-round replication of the (small) tree arrays so every process can
    # fetch the full forest under multi-process SPMD — the in-graph form of
    # the reference's serialized-tree allGather + concat (tree.py:333-378).
    # Rounds are fetched to host as they finish and concatenated in numpy:
    # one tiny replication program compiled after round 0 (an end-of-run
    # concat over 3x50 device arrays was a fresh multi-minute-later compile,
    # one more exposure to remote-compile-service flakiness for no benefit).
    import numpy as np

    rep = NamedSharding(mesh, P())
    replicate = jax.jit(lambda f, b, s: (f, b, s), out_shardings=(rep, rep, rep))

    def dispatch(fn, *args, _retries=2):
        # the remote TPU compile service drops requests transiently (HTTP
        # 500s, closed response bodies); every step here is a pure program
        # over live inputs, so a bounded retry is safe and turns a dead
        # 20-minute protocol run into a logged hiccup
        import time as _time

        for attempt in range(_retries + 1):
            try:
                return fn(*args)
            except jax.errors.JaxRuntimeError as e:  # pragma: no cover - env
                msg = str(e)
                transient = "remote_compile" in msg or "INTERNAL" in msg
                if not transient or attempt == _retries:
                    raise
                from ..utils import get_logger

                get_logger("RandomForest").warning(
                    "transient TPU compile failure (attempt %d): %s",
                    attempt + 1, msg.splitlines()[0],
                )
                _time.sleep(15.0 * (attempt + 1))  # sleep-ok: capped transient-compile retry backoff (≤45s over at most _retries attempts); the regex-era gate missed this aliased call

    rounds = []
    for t_i in range(trees_per_dev):
        ti = jnp.int32(t_i)
        stw = dispatch(boot_step, stats_row, w, ti)
        nid, act, feat_b, bin_b, nst_b = dispatch(tree_init)
        for depth in range(max_depth):
            nid, act, feat_b, bin_b, nst_b = dispatch(
                level_steps[depth], Xb, stw, nid, act, feat_b, bin_b, nst_b, ti
            )
        nst_b = dispatch(final_step, stw, nid, act, nst_b)
        f, b, s = dispatch(replicate, feat_b, bin_b, nst_b)
        rounds.append((np.asarray(f), np.asarray(b), np.asarray(s)))  # host-fetch-ok: per-TREE round results land on host (trees are independent; the forest assembles in numpy)
    feats = np.concatenate([r[0] for r in rounds], axis=0)
    bins_ = np.concatenate([r[1] for r in rounds], axis=0)
    nstats = np.concatenate([r[2] for r in rounds], axis=0)
    return {"feature": feats, "split_bin": bins_, "node_stats": nstats}


# ---------------------------------------------------------------------------
# Prediction
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("max_depth",))
def forest_raw_predict(
    X: jax.Array,  # [n, d] float
    feature: jax.Array,  # [T, M]
    threshold: jax.Array,  # [T, M] real-valued thresholds
    leaf_value: jax.Array,  # [T, M, S]
    *,
    max_depth: int,
) -> jax.Array:
    """Average of per-tree leaf values: [n, S]. Traversal is a fixed-depth
    gather loop (vectorized oblivious descent, SURVEY.md §7 architecture map)."""

    def one_tree(feat, thr, leaves):
        def step(_, node):
            f = feat[node]
            is_split = f >= 0
            xv = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
            child = 2 * node + jnp.where(xv <= thr[node], 1, 2)
            return jnp.where(is_split, child, node)

        node = jax.lax.fori_loop(0, max_depth, step, jnp.zeros(X.shape[0], jnp.int32))
        return leaves[node]  # [n, S]

    per_tree = jax.vmap(one_tree)(feature, threshold, leaf_value)  # [T, n, S]
    return jnp.mean(per_tree, axis=0)


def split_bins_to_thresholds(
    feature: np.ndarray, split_bin: np.ndarray, edges: np.ndarray
) -> np.ndarray:
    """Convert bin-id splits to real thresholds using the bin edges.

    Split 'bin <= b' corresponds to 'x <= edges[f, b]' (searchsorted-left)."""
    f = np.maximum(feature, 0)
    b = np.minimum(split_bin, edges.shape[1] - 1)
    thr = edges[f, b]
    return np.where(feature >= 0, thr, np.inf).astype(np.float64)
