#
# Distributed KMeans solver — the in-tree replacement for
# `cuml.cluster.kmeans_mg.KMeansMG` (consumed by reference clustering.py:353).
#
# Lloyd iterations as an explicit SPMD program (`shard_map` over the rows axis):
# each device scans its row block in fixed-size tiles (the reference's
# `max_samples_per_batch` memory knob, clustering.py:110-121) through the
# SHARED tiled distance core (ops/distance.py — fused assignment + one-hot
# accumulation, Pallas-k-tiled on TPU); partial (k,d) sums/counts/inertia are
# `psum`'d across devices — the NCCL allreduce the cuML MG solver does
# internally. The outer loop is a `lax.while_loop` on center movement +
# max_iter, so the whole fit is ONE XLA program: no per-iteration host
# round-trips.
#
from __future__ import annotations

import math
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from ..parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from .. import telemetry
from ..parallel.mesh import ROWS_AXIS
from .distance import (
    argmin_assign,
    assign_accumulate,
    min_d2_update,
    tile_assign_accumulate as _tile_assign_accumulate,
)

# jitted once per shape: the seeding paths dispatch these eagerly per round
_min_d2_update = jax.jit(min_d2_update)


def _finish_centers(sums, counts, inertia, centers):
    # empty clusters keep their previous center (cuML behavior)
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1e-30)[:, None], centers
    )
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, inertia, shift


_finish_centers_jit = jax.jit(_finish_centers)


@partial(jax.jit, static_argnames=("mesh", "batch_rows", "fast"))
def _lloyd_step(X, w, centers, *, mesh, batch_rows, fast=False):
    """One Lloyd iteration as a TOP-LEVEL XLA program: per-shard tiled
    assignment + accumulation, psum'd (k,d) sums/counts/inertia, center update.

    Kept out of a `lax.while_loop` deliberately: XLA duplicates any array whose
    consumer sits inside nested loops (the tile scan inside a while body costs
    +1 full copy of X — 11 GiB at the 1M x 3k benchmark shape, an OOM on one
    chip). The iteration loop lives on the host instead; each step is one
    dispatch (~ms) against seconds of compute, and the convergence scalar is a
    replicated global value so every SPMD rank steps identically."""

    def local(Xl, wl):
        sums, counts, inertia = _tile_assign_accumulate(Xl, wl, centers, batch_rows, fast)
        sums = jax.lax.psum(sums, ROWS_AXIS)
        counts = jax.lax.psum(counts, ROWS_AXIS)
        inertia = jax.lax.psum(inertia, ROWS_AXIS)
        return sums, counts, inertia

    sums, counts, inertia = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS)),
        out_specs=(P(), P(), P()),
    )(X, w)
    return _finish_centers(sums, counts, inertia, centers)


@partial(jax.jit, static_argnames=("batch_rows", "fast"))
def _lloyd_step_fused_1dev(X, w, centers, *, batch_rows, fast=False):
    """One Lloyd iteration as ONE local program (no mesh, no collectives):
    the in-program tile scan of `_tile_assign_accumulate` plus the center
    update. This is the small-dataset single-device path — it must NOT touch
    a Mesh: under multi-process SPMD a 1-device `get_mesh(1)` holds GLOBAL
    device 0, which other ranks cannot address, while per-rank local fits
    (e.g. each rank's ANN coarse quantizer) run on the rank's own default
    device. The in-program scan may double-buffer X (see _tile_accum_1dev) —
    affordable below _ONE_DISPATCH_MAX_BYTES, where this path is used."""
    sums, counts, inertia = _tile_assign_accumulate(
        X, w, centers, batch_rows, fast, spmd=False
    )
    return _finish_centers(sums, counts, inertia, centers)


@partial(jax.jit, static_argnames=("size", "fast"), donate_argnums=(3, 4, 5))
def _tile_accum_1dev(X, w, centers, sums, counts, inertia, start, *, size, fast=False):
    """Single-device tile accumulation: dynamic_slice at the PROGRAM TOP LEVEL
    (no in-program loop over X at all). XLA's choice to duplicate a loop-
    consumed operand is size-dependent — at the 1M x 3k benchmark shape even
    the fori_loop-of-dynamic_slice form gets a full X copy — so on one device
    the tile loop lives on the host and the (k,d) accumulators are DONATED
    device buffers updated in place. The per-tile math is the shared core's
    fused assign+accumulate (ops/distance.py)."""
    xb = jax.lax.dynamic_slice_in_dim(X, start, size, 0)
    wb = jax.lax.dynamic_slice_in_dim(w, start, size, 0)
    s, c, i = assign_accumulate(xb, wb, centers, fast=fast)
    return sums + s, counts + c, inertia + i


def _lloyd_step_1dev(X, w, centers, batch_rows, fast=False):
    """Host-tiled Lloyd iteration for a 1-device mesh (see _tile_accum_1dev)."""
    import numpy as np

    n, d = X.shape
    k = centers.shape[0]
    dtype = X.dtype
    batch_rows = min(batch_rows, n)
    sums = jnp.zeros((k, d), dtype)
    counts = jnp.zeros((k,), dtype)
    inertia = jnp.zeros((), dtype)
    n_full = (n // batch_rows) * batch_rows
    for start in range(0, n_full, batch_rows):
        sums, counts, inertia = _tile_accum_1dev(
            X, w, centers, sums, counts, inertia, np.int32(start),
            size=batch_rows, fast=fast,
        )
    if n - n_full:
        sums, counts, inertia = _tile_accum_1dev(
            X, w, centers, sums, counts, inertia, np.int32(n_full),
            size=n - n_full, fast=fast,
        )
    return _finish_centers_jit(sums, counts, inertia, centers)


# Below this size a 1-device fit takes the SAME one-dispatch-per-iteration
# program as the mesh path (fori_loop of tiles inside `_lloyd_step` over a
# 1-device mesh). The host-tiled `_lloyd_step_1dev` exists to keep the big-X
# regime single-buffered (XLA copies a loop-consumed X at the 1M×3k protocol
# shape), but it costs one dispatch PER TILE — through a remote PJRT tunnel
# (~140ms/dispatch) that dominated medium datasets (measured: 45s for the ANN
# coarse quantizer's 500k×512 k=1024 training vs ~3s with per-iteration
# dispatch; the in-program X copy is affordable below this cap). A fully
# fused while_loop-of-iterations variant was tried and is PATHOLOGICAL on
# the axon backend (~80s at the same shape) — keep the iteration loop on the
# host.
_ONE_DISPATCH_MAX_BYTES = 2 << 30


@partial(jax.jit, static_argnames=("fast",))
def block_assign_accumulate(
    xb: jax.Array, wb: jax.Array, centers: jax.Array, fast: bool = False
):
    """One streaming chunk's Lloyd contribution: (sums [k,d], counts [k],
    inertia) — the shared core's fused assign+accumulate
    (ops/distance.py), over ONE placed row block. The out-of-core driver
    (ops/streaming.py) sums these per-chunk partials across the
    double-buffered pipeline; padding rows carry zero weight, so they
    contribute nothing — exactly the resident pad contract. `fast` runs the
    chunk's distance matmuls in the parity-tested fast-bf16 mode; the
    streaming driver keeps its final inertia pass at full precision."""
    return assign_accumulate(xb, wb, centers, fast=fast)


def kmeans_ckpt_key(init_centers, max_iter: int, tol: float) -> str:
    """Trajectory-identifying checkpoint key shared by the resident and
    streaming Lloyd loops: init-centers digest + shape + loop statics. ONE
    format for both, so a resident fit's checkpoint resumes a streaming
    retry (the OOM-demotion ladder) and vice versa — centers are replicated,
    fully portable state."""
    import hashlib

    import numpy as np

    init_digest = hashlib.sha1(
        np.ascontiguousarray(np.asarray(init_centers)).tobytes()
    ).hexdigest()[:12]
    shape = tuple(np.shape(init_centers))
    return f"kmeans:{shape}:{init_digest}:{max_iter}:{tol}"


def _raise_diverged(iteration: int, last_good_centers, detail: str) -> None:
    """Typed divergence error off the already-fetched per-iteration shift:
    carries the iterate that ENTERED the diverging update (still finite)."""
    import numpy as np

    from ..errors import SolverDivergedError

    telemetry.registry().inc("solver.divergence")
    telemetry.registry().inc("kmeans.divergence")
    raise SolverDivergedError(
        "kmeans",
        iteration,
        last_good={"cluster_centers_": np.asarray(last_good_centers)},
        detail=detail,
    )


def kmeans_fit(
    X: jax.Array,
    w: jax.Array,
    init_centers: jax.Array,
    *,
    mesh,
    max_iter: int = 20,
    tol: float = 1e-4,
    batch_rows: int = 32768,
    precision_mode: str = "fast",
    final_inertia: bool = True,
) -> Dict[str, jax.Array]:
    """Lloyd's algorithm on a row-sharded global X. Returns
    cluster_centers_ [k,d], inertia_, n_iter_.

    Convergence: squared center movement <= tol (sklearn/cuML semantics; the
    reference maps Spark's `tol` straight through, clustering.py:96-108).
    Host-stepped loop of jitted `_lloyd_step` programs — see the step's
    docstring for why the loop is not a `lax.while_loop`. Small single-device
    datasets take the fused one-program path instead (_lloyd_fit_fused).

    The deferred (pipelined) convergence check means `n_iter_` can be ONE
    HIGHER than sklearn/cuML would report for the same tol crossing — the
    extra iteration runs at the converged fixpoint, so centers match. With
    ``final_inertia=False`` no trustworthy inertia exists (the in-loop value
    is a stale, possibly-bf16 partial) and `inertia_` is returned as NaN.

    precision_mode: "fast" (default for f32) runs the IN-LOOP distance and
    center-update matmuls in one-pass bf16 (see distance._mm — 1.6× per iteration at
    the protocol shape, true inertia agrees to ~1e-5); "high" keeps the
    ambient (3-pass-bf16 "f32") precision everywhere. f64 inputs always run
    "high". The final reported inertia is high-precision in both modes."""
    import numpy as np

    from .. import checkpoint as _ckpt

    centers = jnp.asarray(init_centers)
    fast = precision_mode == "fast" and X.dtype == jnp.float32
    # measured autotuner (ops/autotune.py): make sure a tiling winner exists
    # for this fit's tile shape BEFORE the jitted loop traces — the traced
    # block planner then hits the persisted table; off-TPU (and with
    # SRML_AUTOTUNE=0) this is a no-op and the static heuristic plans.
    from . import autotune

    autotune.ensure(
        min(batch_rows, X.shape[0]), centers.shape[0], X.shape[1], X.dtype, fast
    )
    inertia = jnp.zeros((), X.dtype)
    n_iter = 0
    one_dev = mesh.devices.size == 1
    host_tiled = one_dev and X.size * X.dtype.itemsize > _ONE_DISPATCH_MAX_BYTES

    def step(c, f):
        if host_tiled:
            return _lloyd_step_1dev(X, w, c, batch_rows, fast=f)
        if one_dev:  # meshless local program (see _lloyd_step_fused_1dev)
            return _lloyd_step_fused_1dev(X, w, c, batch_rows=batch_rows, fast=f)
        return _lloyd_step(X, w, c, mesh=mesh, batch_rows=batch_rows, fast=f)

    # convergence is tested one iteration LATE: fetching the shift scalar
    # synchronizes with the device (~50ms each through a remote tunnel —
    # 1.5s of the protocol fit); checking the PREVIOUS iteration's shift
    # overlaps the fetch with the current step's compute. At most one extra
    # Lloyd iteration runs after the tol crossing (same fixpoint).
    # Convergence trace + divergence guard: the shift scalar for iteration
    # i-1 is fetched here ANYWAY (the deferred check), so both the telemetry
    # point and the NaN/Inf check cost no extra device synchronization.
    prev_shift = None
    last_good = centers  # iterate entering the step that produced prev_shift
    # runtime numerics sanitizer (SRML_NUMCHECK=1): resolved ONCE per solve;
    # disabled = a None local, one `is not None` test per boundary
    from ..utils import numcheck

    _nc = numcheck.hook()
    # Solver checkpoints (docs/robustness.md "Elastic recovery"): the host
    # loop already fetches the shift scalar every iteration, so host-fetching
    # the centers at the configured cadence is near-free. Centers are
    # REPLICATED state — fully portable across meshes — so a resume after a
    # transient retry or a survivor re-mesh restarts Lloyd from the
    # checkpointed iterate: bit-identical on the same mesh (the host
    # round-trip is lossless and each step depends only on (X, w, centers)),
    # deterministic given the survivor set on a degraded one.
    ckpt_store = _ckpt.active_store()
    ckpt_every = _ckpt.every_iters()
    ckpt_key = None
    if ckpt_store is not None and ckpt_every > 0:
        # the key must identify THIS solve's trajectory, not just its shape:
        # sequential param sets in one fit stage (a maxIter/tol sweep, or a
        # different init seed) share the store, and a shape-only key would
        # resume solve N from solve N-1's converged state. The init-centers
        # fingerprint (one tiny host fetch, once per fit) plus the loop
        # statics pin the trajectory; tol/maxIter only move the STOP point
        # on it, but keying them too keeps the entries disjoint and cheap.
        # The fast flag is part of the trajectory too (bf16 assignments walk
        # a different path), so bf16 keys apart — same suffix on the
        # streaming driver, preserving the resident<->streaming sharing.
        ckpt_key = kmeans_ckpt_key(init_centers, max_iter, tol)
        if fast:
            ckpt_key = ckpt_key + ":bf16"
        saved = ckpt_store.load(ckpt_key)
        if saved is not None and tuple(saved.state["centers"].shape) == tuple(
            jnp.shape(centers)
        ):
            centers = jnp.asarray(saved.state["centers"], dtype=X.dtype)
            # last_good is the iterate ENTERING the step that produced
            # prev_shift — one step BEHIND the checkpointed centers. Restore
            # it too, so a divergence detected right after resume reports the
            # same last-good iterate an uninterrupted run would.
            lg = saved.state.get("last_good")
            last_good = centers if lg is None else jnp.asarray(lg, dtype=X.dtype)
            n_iter = int(saved.iteration)
            ps = saved.state.get("prev_shift")
            prev_shift = None if ps is None else float(ps)
    while n_iter < max_iter:
        step_in = centers
        centers, inertia, shift = step(centers, fast)
        n_iter += 1
        if prev_shift is not None:
            # the deferred shift fetch is Lloyd's per-iteration sync — the
            # efficiency attributor times the wait as `execute` (this IS the
            # solver cadence point; no sync added)
            with telemetry.device_wait("kmeans_shift"):
                shift_host = float(prev_shift)  # host-fetch-ok: the DEFERRED convergence fetch (documented above) — overlapped with the current step's compute
            if not math.isfinite(shift_host):
                _raise_diverged(n_iter - 1, last_good, f"center shift = {shift_host}")
            if _nc is not None:
                # AFTER the divergence guard (typed SolverDivergedError owns
                # non-finite shifts); sweeps the already-fetched scalar and
                # records the iterate's dtype watermark without a new fetch
                _nc("kmeans.iterate", solver="kmeans", iteration=n_iter - 1,
                    watermark=centers.dtype, shift=shift_host)
            if telemetry.enabled():
                telemetry.record_convergence_point("kmeans.shift", n_iter - 1, shift_host)
            if shift_host <= tol:
                break
        prev_shift = shift
        last_good = step_in
        if ckpt_store is not None and ckpt_every > 0 and n_iter % ckpt_every == 0:
            # the cadence fetch of prev_shift syncs with the device — the
            # documented checkpoint overhead; the float survives the
            # round-trip exactly, so the resumed convergence pipeline sees
            # the same value the uninterrupted run would
            with telemetry.device_wait("kmeans_checkpoint"):
                prev_shift = float(prev_shift)  # host-fetch-ok: checkpoint-cadence boundary (config["checkpoint_every_iters"])
                centers_host = np.asarray(centers)  # host-fetch-ok: the checkpoint itself — replicated centers must land on host to survive
            if _nc is not None:
                # the checkpoint already fetched the full iterate: sweep it
                # (a non-finite checkpoint would poison every later resume)
                _nc("kmeans.checkpoint", solver="kmeans", iteration=n_iter,
                    centers=centers_host)
            with telemetry.host_section("kmeans_checkpoint"):
                ckpt_store.save(ckpt_key, _ckpt.SolverCheckpoint(
                    solver="kmeans", iteration=n_iter,
                    state={
                        "centers": centers_host,
                        "prev_shift": prev_shift,
                        # the divergence-fallback iterate (one step behind)
                        "last_good": np.asarray(last_good),  # host-fetch-ok: checkpoint payload (one step behind, for divergence fallback)
                    },
                ))
            # mid-solve fault injection points (`fail:stage=solve` and
            # `oom:stage=solve` plans): both fire AFTER the boundary
            # checkpoint landed, so a retried fit — bounded transient retry
            # or the OOM demotion to the streaming path — provably resumes
            # instead of restarting Lloyd from scratch
            from ..parallel import chaos

            chaos.maybe_fail_oom("solve", n_iter)
            chaos.maybe_fail_stage("solve", n_iter)
            # cooperative scheduler preemption (docs/scheduling.md): checked
            # where the loop already host-fetched (the cadence shift fetch
            # above), AFTER the boundary checkpoint landed — a preempted
            # fit resumes from exactly this iterate
            from ..scheduler.context import preemption_point

            preemption_point("kmeans", n_iter)
    if telemetry.enabled():
        telemetry.record_solver_result("kmeans", n_iter=n_iter)
    # inertia reported is one iteration stale; recompute once with final
    # centers — always at high precision. Callers that don't consume inertia
    # (e.g. the IVF coarse quantizer) skip the pass: the high-precision
    # program is a separate ~79s compile in a fresh process. The stale value
    # must not leak to them either — return NaN so accidental consumption is
    # loud instead of subtly wrong.
    if final_inertia:
        _, inertia, _ = step(centers, False)
        inertia_host = float(inertia)
        if not math.isfinite(inertia_host):
            # the loop's deferred check trails by one fetch: a divergence on
            # the FINAL step (or a 1-iteration fit) is caught here, on the
            # inertia scalar the caller fetches anyway
            _raise_diverged(n_iter, last_good, f"final inertia = {inertia_host}")
    else:
        inertia = jnp.full((), jnp.nan, X.dtype)
    return {
        "cluster_centers_": centers,
        "inertia_": inertia,
        "n_iter_": jnp.asarray(n_iter, jnp.int32),
    }


@jax.jit
def kmeans_predict(X: jax.Array, centers: jax.Array) -> jax.Array:
    """Nearest-center assignment for a batch of rows (transform path).

    Row-tiled through the shared core (`distance.argmin_assign`,
    `config["distance_tile_rows"]` rows per tile): the full [n, k] distance
    matrix never materializes, so a fit the HBM admission controller
    approved cannot OOM at PREDICT — the predict-side tile is a budgeted
    workspace term (memory.py / KMeans._solver_workspace_terms)."""
    return argmin_assign(X, centers)


_INIT_SAMPLE_CAP = 262_144  # rows used for seeding (both init paths)


def _init_subsample(x_host, sample_weight, rng):
    """Bounded (row, weight) subsample shared by both seeding paths."""
    import numpy as np

    n = x_host.shape[0]
    if n > _INIT_SAMPLE_CAP:
        idx = np.sort(rng.choice(n, _INIT_SAMPLE_CAP, replace=False))
        x = np.ascontiguousarray(np.asarray(x_host[idx], dtype=np.float64))
        sw = None if sample_weight is None else np.asarray(sample_weight[idx], dtype=np.float64)
    else:
        x = np.ascontiguousarray(np.asarray(x_host, dtype=np.float64))
        sw = None if sample_weight is None else np.asarray(sample_weight, dtype=np.float64)
    if sw is None:
        sw = np.ones(x.shape[0])
    return x, sw


# nearest-candidate assignment for the seeding paths: the shared row-tiled
# core (never a full [n, k] distance matrix), jitted once per shape
_assign_nearest = jax.jit(argmin_assign)


@partial(jax.jit, static_argnames=("k",))
def _kmeanspp_device(x, sw, seed, *, k: int):
    """Classic k-means++ as ONE device program (fori_loop over the k sequential
    draws; categorical sampling by inverse-CDF). The host numpy loop this
    replaces costs ~50 ms per draw at 10k×512 — 51 s for the ANN coarse
    quantizer's k=1024 reduce; here the whole reduce is a single dispatch."""
    n, d = x.shape
    x_sq = jnp.sum(x * x, axis=1)

    def sample(key, probs):
        c = jnp.cumsum(probs)
        u = jax.random.uniform(key, dtype=c.dtype) * c[-1]
        return jnp.clip(jnp.searchsorted(c, u), 0, n - 1)

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    i0 = sample(k0, sw)
    centers0 = jnp.zeros((k, d), x.dtype).at[0].set(x[i0])

    def body(i, carry):
        centers, closest, key = carry
        prev = jax.lax.dynamic_slice_in_dim(centers, i - 1, 1, 0)[0]
        d2 = x_sq - 2.0 * (x @ prev) + jnp.sum(prev * prev)
        closest = jnp.minimum(closest, jnp.maximum(d2, 0.0))
        probs = closest * sw
        s = jnp.sum(probs)
        probs = jnp.where(s > 0, probs, sw)  # degenerate: all points covered
        key, kk = jax.random.split(key)
        idx = sample(kk, probs)
        return centers.at[i].set(x[idx]), closest, key

    centers, _, _ = jax.lax.fori_loop(
        1, k, body, (centers0, jnp.full((n,), jnp.inf, x.dtype), key)
    )
    return centers


def scalable_kmeans_init(x_host, k: int, seed: int, sample_weight=None, rounds: int = 5):
    """k-means|| (Bahmani et al.) seeding — the reference's
    'scalable-k-means++' (cuML KMeansMG init). Device-assisted: each round
    computes distances to ONLY the new candidates (one incremental matmul
    program), samples ~2k further candidates with probability ∝ d², then the
    ~2k·rounds candidate set is weighted by assignment counts and reduced to k
    with classic k-means++ on the host — O(rounds) device passes instead of
    the O(k) sequential host passes of plain k-means++ (minutes at the
    protocol's k=1000)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    x, sw = _init_subsample(x_host, sample_weight, rng)
    x = x.astype(np.float32)
    n_sub = x.shape[0]
    l = max(1, 2 * k)  # oversampling factor per round

    xd = jax.device_put(x)
    # every candidate block is PADDED to exactly l rows (repeating one row —
    # duplicates never change a running min-distance): all `_min_d2_update`
    # calls then share ONE compiled shape instead of one compile per block
    # size (a fresh compile through a remote PJRT tunnel costs ~20-40s).
    first = np.broadcast_to(x[rng.choice(n_sub, p=sw / sw.sum())], (l, x.shape[1]))
    cand_list = [np.ascontiguousarray(first)]
    min_d2 = _min_d2_update(xd, jax.device_put(cand_list[0]), jnp.full((n_sub,), np.inf, jnp.float32))
    for _ in range(rounds):
        probs = np.maximum(np.asarray(min_d2), 0.0) * sw  # host-fetch-ok: one fetch per k-means|| seeding ROUND (host does the ∝d² sampling); rounds is small and fixed
        s = probs.sum()
        # without-replacement sampling needs enough nonzero-probability rows
        n_new = min(l, n_sub, int(np.count_nonzero(probs)))
        if s <= 0 or n_new == 0:
            break
        new_idx = rng.choice(n_sub, size=n_new, replace=False, p=probs / s)
        new = x[np.sort(new_idx)]
        if n_new < l:  # pad to the fixed block shape
            new = np.concatenate([new, np.broadcast_to(new[0], (l - n_new, new.shape[1]))])
        cand_list.append(new)
        min_d2 = _min_d2_update(xd, jax.device_put(new), min_d2)
    cand = np.concatenate(cand_list, axis=0)
    # weight candidates by how many points they own (one assignment pass);
    # duplicate (padding) rows lose every argmin tie, so they get weight 0
    assign = np.asarray(_assign_nearest(xd, jax.device_put(cand)))
    weights = np.bincount(assign, weights=sw, minlength=len(cand)).astype(np.float32)
    # reduce the small weighted candidate set to k with k-means++ ON DEVICE
    # (one dispatch; the host loop costs ~50s at the ANN build's k=1024)
    centers = _kmeanspp_device(
        jax.device_put(cand.astype(np.float32)),
        jax.device_put(np.maximum(weights, 1e-12)),
        seed + 1, k=k,
    )
    return np.asarray(centers, dtype=np.float64)


def kmeans_plus_plus_init(x_host, k: int, seed: int, sample_weight=None):
    """k-means++ seeding on the host (numpy), optionally on a subsample.

    Used for Spark's default ``k-means||`` init mode: the reference delegates to
    cuML's scalable-k-means++; here we seed with classic k-means++ over a
    bounded subsample (equivalent quality for the benchmark regime), then let
    the distributed Lloyd loop refine.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    x, sw = _init_subsample(x_host, sample_weight, rng)
    centers = np.empty((k, x.shape[1]), dtype=np.float64)
    p = sw / sw.sum()
    centers[0] = x[rng.choice(x.shape[0], p=p)]
    closest = np.full(x.shape[0], np.inf)
    for i in range(1, k):
        d2 = np.sum((x - centers[i - 1]) ** 2, axis=1)
        closest = np.minimum(closest, d2)
        probs = closest * sw
        s = probs.sum()
        if s <= 0:
            centers[i] = x[rng.choice(x.shape[0], p=p)]
        else:
            centers[i] = x[rng.choice(x.shape[0], p=probs / s)]
    return centers


def random_init(x_host, k: int, seed: int):
    """Sample k distinct rows as initial centers (initMode='random')."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n = x_host.shape[0]
    if k > n:
        raise ValueError(f"k={k} exceeds number of rows {n}")
    idx = rng.choice(n, k, replace=False)
    return np.asarray(x_host[idx], dtype=np.float64)


@partial(jax.jit, static_argnames=("l",), donate_argnums=(2,))
def _kmeanspar_round(xd, cand_prev, min_d2, sw, key, *, l: int):
    """One k-means|| round fully on device: update min-d² against the
    previous candidate block, then draw the next `l` candidates WITHOUT
    replacement with probability ∝ d²·w via Gumbel-top-k (keys
    log p + Gumbel(0,1); the top-l keys are exactly a weighted
    without-replacement sample). Returns (new candidate block [l, d],
    updated min_d2)."""
    min_d2 = min_d2_update(xd, cand_prev, min_d2)
    probs = min_d2 * sw
    total = jnp.sum(probs)
    # degenerate (all points covered): fall back to uniform-by-weight
    probs = jnp.where(total > 0, probs, sw)
    gumbel = -jnp.log(-jnp.log(
        jax.random.uniform(key, (xd.shape[0],), minval=1e-20, maxval=1.0)
    ))
    keys = jnp.where(probs > 0, jnp.log(probs) + gumbel, -jnp.inf)
    _, idx = jax.lax.top_k(keys, l)
    return xd[idx], min_d2


def scalable_kmeans_init_device(
    xd: jax.Array, k: int, seed: int, sample_weight=None, rounds: int = 5
) -> jax.Array:
    """k-means|| seeding with every step device-resident — for data that
    already lives in HBM (the ANN index builds). No candidate rows, distance
    vectors or weights ever cross the host boundary: each round is one
    fused program (_kmeanspar_round), the candidate weighting is a device
    scatter-add, and the final reduce-to-k is `_kmeanspp_device`. Returns
    [k, d] f32 centers ON DEVICE.

    Equivalent in distribution to `scalable_kmeans_init` (Bahmani et al.
    k-means||); the without-replacement sampling uses Gumbel-top-k instead
    of host `rng.choice`.

    Size bound: the per-round `xd[idx]` candidate gather is the fancy-index
    pattern XLA may answer with a full temporary copy of xd at very large
    shapes (see the 1-device KMeans notes) — callers keep xd below a few GB
    (the ANN index builds, whose per-partition data is well under that)."""
    n, d = xd.shape
    l = max(1, min(2 * k, n))  # top_k sample size cannot exceed n
    sw = (
        jnp.ones((n,), jnp.float32)
        if sample_weight is None
        else jnp.asarray(sample_weight, jnp.float32)
    )
    key = jax.random.PRNGKey(seed)
    k0, key = jax.random.split(key)
    i0 = jax.random.categorical(k0, jnp.log(jnp.maximum(sw, 1e-30)))
    cand = jnp.broadcast_to(xd[i0], (l, d))
    min_d2 = jnp.full((n,), jnp.inf, jnp.float32)
    blocks = [cand]
    for r in range(rounds):
        key, kr = jax.random.split(key)
        cand, min_d2 = _kmeanspar_round(xd, blocks[-1], min_d2, sw, kr, l=l)
        blocks.append(cand)
    cand_all = jnp.concatenate(blocks, axis=0)
    assign = _assign_nearest(xd, cand_all)
    weights = jnp.zeros((cand_all.shape[0],), jnp.float32).at[assign].add(sw)
    return _kmeanspp_device(
        cand_all, jnp.maximum(weights, 1e-12), seed + 1, k=min(k, cand_all.shape[0])
    )
