#
# Distributed KMeans solver — the in-tree replacement for
# `cuml.cluster.kmeans_mg.KMeansMG` (consumed by reference clustering.py:353).
#
# Lloyd iterations as an explicit SPMD program (`shard_map` over the rows axis):
# each device scans its row block in fixed-size tiles (the reference's
# `max_samples_per_batch` memory knob, clustering.py:110-121), computing
# argmin distances on the MXU (x·cᵀ matmul) and accumulating one-hot weighted
# center sums; partial (k,d) sums/counts/inertia are `psum`'d across devices —
# the NCCL allreduce the cuML MG solver does internally. The outer loop is a
# `lax.while_loop` on center movement + max_iter, so the whole fit is ONE XLA
# program: no per-iteration host round-trips.
#
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ROWS_AXIS


def _tile_assign_accumulate(
    Xl: jax.Array, wl: jax.Array, centers: jax.Array, batch_rows: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scan one device's rows in tiles; returns (sums [k,d], counts [k], inertia)."""
    nl, d = Xl.shape
    k = centers.shape[0]
    n_tiles = max(1, -(-nl // batch_rows))
    pad = n_tiles * batch_rows - nl
    Xp = jnp.pad(Xl, ((0, pad), (0, 0)))
    wp = jnp.pad(wl, (0, pad))
    Xt = Xp.reshape(n_tiles, batch_rows, d)
    wt = wp.reshape(n_tiles, batch_rows)
    c_sq = jnp.sum(centers * centers, axis=1)  # [k]

    def step(carry, xw):
        sums, counts, inertia = carry
        xb, wb = xw
        # ||x-c||² = ||x||² - 2 x·c + ||c||²; the x·cᵀ term is the MXU matmul
        xc = xb @ centers.T  # [b, k]
        d2 = c_sq[None, :] - 2.0 * xc
        assign = jnp.argmin(d2, axis=1)  # [b]
        min_d2 = jnp.min(d2, axis=1) + jnp.sum(xb * xb, axis=1)
        oh = jax.nn.one_hot(assign, k, dtype=xb.dtype) * wb[:, None]  # [b, k]
        sums = sums + oh.T @ xb  # [k, d] — MXU again
        counts = counts + jnp.sum(oh, axis=0)
        inertia = inertia + jnp.sum(jnp.maximum(min_d2, 0.0) * wb)
        return (sums, counts, inertia), None

    init = (
        jnp.zeros((k, d), Xl.dtype),
        jnp.zeros((k,), Xl.dtype),
        jnp.zeros((), Xl.dtype),
    )
    # carry must be typed as varying over the mesh axis to match the per-shard
    # accumulators (JAX shard_map vma typing)
    init = jax.tree.map(lambda t: jax.lax.pcast(t, ROWS_AXIS, to="varying"), init)
    (sums, counts, inertia), _ = jax.lax.scan(step, init, (Xt, wt))
    return sums, counts, inertia


@partial(jax.jit, static_argnames=("mesh", "max_iter", "batch_rows"))
def kmeans_fit(
    X: jax.Array,
    w: jax.Array,
    init_centers: jax.Array,
    *,
    mesh,
    max_iter: int = 20,
    tol: float = 1e-4,
    batch_rows: int = 32768,
) -> Dict[str, jax.Array]:
    """Lloyd's algorithm on a row-sharded global X. Returns
    cluster_centers_ [k,d], inertia_, n_iter_.

    Convergence: squared center movement <= tol (sklearn/cuML semantics; the
    reference maps Spark's `tol` straight through, clustering.py:96-108)."""

    def one_iteration(centers):
        def local(Xl, wl):
            sums, counts, inertia = _tile_assign_accumulate(Xl, wl, centers, batch_rows)
            sums = jax.lax.psum(sums, ROWS_AXIS)
            counts = jax.lax.psum(counts, ROWS_AXIS)
            inertia = jax.lax.psum(inertia, ROWS_AXIS)
            return sums, counts, inertia

        sums, counts, inertia = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS)),
            out_specs=(P(), P(), P()),
        )(X, w)
        # empty clusters keep their previous center (cuML behavior)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1e-30)[:, None], centers
        )
        return new_centers, inertia

    def cond(state):
        centers, prev_shift, inertia, it = state
        return jnp.logical_and(it < max_iter, prev_shift > tol)

    def body(state):
        centers, _, _, it = state
        new_centers, inertia = one_iteration(centers)
        shift = jnp.sum((new_centers - centers) ** 2)
        return (new_centers, shift, inertia, it + 1)

    init_state = (init_centers, jnp.array(jnp.inf, X.dtype), jnp.zeros((), X.dtype), 0)
    centers, _, inertia, n_iter = jax.lax.while_loop(cond, body, init_state)
    # final inertia is one iteration stale; recompute once with final centers
    _, final_inertia = one_iteration(centers)
    return {"cluster_centers_": centers, "inertia_": final_inertia, "n_iter_": n_iter}


@jax.jit
def kmeans_predict(X: jax.Array, centers: jax.Array) -> jax.Array:
    """Nearest-center assignment for a batch of rows (transform path)."""
    c_sq = jnp.sum(centers * centers, axis=1)
    d2 = c_sq[None, :] - 2.0 * (X @ centers.T)
    return jnp.argmin(d2, axis=1).astype(jnp.int32)


def kmeans_plus_plus_init(x_host, k: int, seed: int, sample_weight=None):
    """k-means++ seeding on the host (numpy), optionally on a subsample.

    Used for Spark's default ``k-means||`` init mode: the reference delegates to
    cuML's scalable-k-means++; here we seed with classic k-means++ over a
    bounded subsample (equivalent quality for the benchmark regime), then let
    the distributed Lloyd loop refine.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    n = x_host.shape[0]
    cap = 262_144
    if n > cap:
        idx = rng.choice(n, cap, replace=False)
        x = np.asarray(x_host[idx], dtype=np.float64)
        sw = None if sample_weight is None else np.asarray(sample_weight[idx], dtype=np.float64)
    else:
        x = np.asarray(x_host, dtype=np.float64)
        sw = None if sample_weight is None else np.asarray(sample_weight, dtype=np.float64)
    if sw is None:
        sw = np.ones(x.shape[0])
    centers = np.empty((k, x.shape[1]), dtype=np.float64)
    p = sw / sw.sum()
    centers[0] = x[rng.choice(x.shape[0], p=p)]
    closest = np.full(x.shape[0], np.inf)
    for i in range(1, k):
        d2 = np.sum((x - centers[i - 1]) ** 2, axis=1)
        closest = np.minimum(closest, d2)
        probs = closest * sw
        s = probs.sum()
        if s <= 0:
            centers[i] = x[rng.choice(x.shape[0], p=p)]
        else:
            centers[i] = x[rng.choice(x.shape[0], p=probs / s)]
    return centers


def random_init(x_host, k: int, seed: int):
    """Sample k distinct rows as initial centers (initMode='random')."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n = x_host.shape[0]
    if k > n:
        raise ValueError(f"k={k} exceeds number of rows {n}")
    idx = rng.choice(n, k, replace=False)
    return np.asarray(x_host[idx], dtype=np.float64)
