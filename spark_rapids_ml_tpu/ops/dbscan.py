#
# Distributed DBSCAN solver — the in-tree replacement for
# `cuml.cluster.dbscan_mg.DBSCANMG` (consumed by reference
# clustering.py:944-1006).
#
# TPU-native design. The reference replicates the dataset to every rank and
# rank-slices the N² pairwise-distance problem (reference
# clustering.py:1013-1091); here the same shape becomes three tiled SPMD
# passes over a `shard_map` row-sliced mesh, each an MXU distance contraction:
#
#   1. CORE pass: per-point eps-neighbor counts -> core mask
#      (one tiled N x N pass, rows sliced across devices).
#   2. EXPANSION: connected components of the core-core eps-graph by
#      min-label propagation with pointer jumping (host-compacted core
#      subset, so each round is nc x nc, not N x N; rounds ~ O(log n)).
#   3. BORDER pass: non-core points adopt the min-labeled core neighbor;
#      no core neighbor -> noise (-1).
#
# Labels match sklearn/cuML: clusters numbered by ascending first-core-point
# index (min-label propagation's fixpoint root IS the cluster's minimum core
# index), noise = -1. Border points attach to their minimum-labeled core
# neighbor — deterministic where sklearn's is scan-order dependent.
#
# The `max_mbytes_per_batch` knob bounds each device's distance-tile footprint
# exactly like the reference's DBSCANMG batching (clustering.py:570-579).
#
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ROWS_AXIS
from .distance import pairwise_d2 as _pairwise_d2


def _tile_rows_for_budget(n: int, max_mbytes: Optional[int], default: int = 8192) -> int:
    """Rows per distance tile so one [tile, n] f32 tile fits the budget."""
    if not max_mbytes:
        return default
    rows = int(max_mbytes * 1e6 / (4 * max(n, 1)))
    return max(64, min(rows, max(n, 64)))


def _replicate_out(mesh, x):
    """Outputs of the rank-sliced passes come back rows-sharded; replicate the
    (small, [n]-sized) result so every SPMD process can fetch it whole."""
    from jax.sharding import NamedSharding

    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


# the distance tile is the SHARED core's (distance.pairwise_d2, imported
# above): squared euclidean / cosine / precomputed pass-through — dbscan_fit
# pre-normalizes cosine rows and hands "precomputed" passes the matching
# column slice of the user's distance matrix, so the tile is just `q` there


def _map_row_tiles(fn, rows, tile_rows: int, extra=None):
    """Scan `fn` over row tiles of the per-device slice: pad the [n_loc, ...]
    leading axis to a tile multiple, `lax.map` over [tiles, tile_rows, ...]
    (bounding the live distance-tile footprint), and slice the padding back
    off. `extra` is a second per-row array carried alongside the rows."""
    n_loc = rows.shape[0]
    tiles = max(1, -(-n_loc // tile_rows))
    pad = tiles * tile_rows - n_loc
    qp = jnp.pad(rows, [(0, pad)] + [(0, 0)] * (rows.ndim - 1))
    qt = qp.reshape((tiles, tile_rows) + rows.shape[1:])
    if extra is not None:
        ep = jnp.pad(extra, (0, pad)).reshape(tiles, tile_rows)
        out = jax.lax.map(fn, (qt, ep))
    else:
        out = jax.lax.map(fn, qt)
    return out.reshape(-1)[: n_loc]


@partial(jax.jit, static_argnames=("mesh", "metric", "tile_rows"))
def core_mask(
    X: jax.Array,  # [n, d] REPLICATED
    valid: jax.Array,  # [n] bool
    eps2: float,
    min_samples: int,
    *,
    mesh,
    metric: str = "euclidean",
    tile_rows: int = 8192,
) -> jax.Array:
    """Per-point eps-neighborhood size (incl. self) >= min_samples: bool [n].

    Each device counts neighbors for ITS row slice (replicated data,
    rank-sliced N² — SURVEY.md §2.4 'replicated-data parallelism')."""
    n, d = X.shape
    n_dev = mesh.devices.size
    n_loc = n // n_dev

    def local(Xl, X_all, valid_all):  # Xl: [n_loc, d] this device's row slice
        def one_tile(q):
            d2 = _pairwise_d2(q, X_all, metric)
            neigh = (d2 <= eps2) & valid_all[None, :]
            return jnp.sum(neigh, axis=1)

        return _map_row_tiles(one_tile, Xl, tile_rows)

    counts = shard_map(
        local, mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(None, None), P(None)),
        out_specs=P(ROWS_AXIS),
    )(X, X, valid)
    return _replicate_out(mesh, (counts >= min_samples) & valid)


@partial(jax.jit, static_argnames=("mesh", "metric", "tile_rows"))
def core_components(
    Xc: jax.Array,  # [nc_pad, d] core points, REPLICATED
    valid: jax.Array,  # [nc_pad] bool
    eps2: float,
    *,
    mesh,
    metric: str = "euclidean",
    tile_rows: int = 8192,
) -> jax.Array:
    """Connected components of the core-core eps-graph.

    Returns per-core root index [nc_pad]: the minimum core index of its
    component. Min-label propagation (one tiled nc x nc pass per round) plus
    two pointer-jumping hops per round -> rounds grow with log(component
    diameter), not diameter."""
    nc, d = Xc.shape
    n_dev = mesh.devices.size
    n_loc = nc // n_dev
    idx = jnp.arange(nc, dtype=jnp.int32)

    def propagate(labels):
        def local(Xl, idx_l, X_all, valid_all, labels_all):
            def one_tile(args):
                q, qi = args
                d2 = _pairwise_d2(q, X_all, metric)
                neigh = (d2 <= eps2) & valid_all[None, :]
                m = jnp.min(jnp.where(neigh, labels_all[None, :], nc), axis=1)
                return jnp.minimum(m.astype(jnp.int32), labels_all[qi])

            return _map_row_tiles(one_tile, Xl, tile_rows, extra=idx_l)

        return _replicate_out(mesh, shard_map(
            local, mesh=mesh,
            in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS), P(None, None), P(None), P(None)),
            out_specs=P(ROWS_AXIS),
        )(Xc, idx, Xc, valid, labels))

    labels0 = jnp.where(valid, idx, jnp.int32(nc))

    def cond(state):
        labels, prev, it = state
        return jnp.logical_and(jnp.any(labels != prev), it < nc)

    def body(state):
        labels, _, it = state
        new = propagate(labels)
        # pointer jumping: hop each label to its label's label (path halving)
        safe = jnp.minimum(new, nc - 1)
        new = jnp.where(valid, jnp.minimum(new, new[safe]), nc)
        safe = jnp.minimum(new, nc - 1)
        new = jnp.where(valid, jnp.minimum(new, new[safe]), nc)
        return new, labels, it + 1

    labels, _, _ = jax.lax.while_loop(
        cond, body, (labels0, jnp.full((nc,), -1, jnp.int32), jnp.int32(0))
    )
    return labels


@partial(jax.jit, static_argnames=("mesh", "metric", "tile_rows"))
def border_assign(
    X: jax.Array,  # [n, d] all points, REPLICATED
    valid: jax.Array,  # [n] bool
    Xc: jax.Array,  # [nc_pad, d] core points
    core_valid: jax.Array,  # [nc_pad] bool
    core_labels: jax.Array,  # [nc_pad] int32 cluster ids of core points
    eps2: float,
    *,
    mesh,
    metric: str = "euclidean",
    tile_rows: int = 8192,
) -> jax.Array:
    """For every point: the minimum cluster id among eps-neighboring core
    points, or -1 (noise) if none. Core points are their own neighbors."""
    n, d = X.shape
    n_dev = mesh.devices.size
    n_loc = n // n_dev
    big = jnp.int32(2**30)

    def local(Xl, Xc_all, cvalid_all, clabels_all):
        def one_tile(q):
            d2 = _pairwise_d2(q, Xc_all, metric)
            neigh = (d2 <= eps2) & cvalid_all[None, :]
            return jnp.min(jnp.where(neigh, clabels_all[None, :], big), axis=1)

        return _map_row_tiles(one_tile, Xl, tile_rows)

    m = shard_map(
        local, mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(None, None), P(None), P(None)),
        out_specs=P(ROWS_AXIS),
    )(X, Xc, core_valid, core_labels)
    return _replicate_out(mesh, jnp.where((m < big) & valid, m, -1))


def dbscan_fit(
    x_host: np.ndarray,
    *,
    mesh,
    eps: float,
    min_samples: int,
    metric: str = "euclidean",
    max_mbytes_per_batch: Optional[int] = None,
    calc_core_sample_indices: bool = False,
) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Full DBSCAN: returns (labels [n] int32 with -1 noise, optional core
    sample indices). Orchestrates the three jitted passes; the host round-trip
    between passes compacts the core subset so expansion is nc², not N².

    metric="precomputed": `x_host` is the [n, n] distance matrix (sklearn/cuML
    convention, raw distances vs `eps`). Each pass receives the matching
    column slice of the matrix — the N² "distance" tiles become free reads
    (see _pairwise_d2) and everything else is unchanged.
    """
    n, d = x_host.shape
    n_dev = mesh.devices.size
    x = np.ascontiguousarray(x_host, dtype=np.float32)
    precomputed = metric == "precomputed"
    if precomputed:
        if n != d:
            raise ValueError(f"precomputed metric needs a square distance matrix, got {n}x{d}")
        eps2 = float(eps)
    elif metric == "cosine":
        norms = np.linalg.norm(x, axis=1, keepdims=True)
        x = x / np.maximum(norms, 1e-12)
        eps2 = float(eps)
    elif metric == "euclidean":
        eps2 = float(eps) ** 2
    else:
        raise ValueError(
            f"metric must be 'euclidean', 'cosine' or 'precomputed', got {metric!r}"
        )

    def pad_repl(a, multiple, fill=0.0):
        rem = (-a.shape[0]) % multiple
        if rem:
            a = np.pad(a, [(0, rem)] + [(0, 0)] * (a.ndim - 1), constant_values=fill)
        return a

    def pad_cols(a, width, fill=np.float32(1e30)):
        # precomputed slices must stay column-aligned with the passes' valid
        # masks; padded columns are masked, the fill is belt-and-braces
        if a.shape[1] < width:
            a = np.pad(a, [(0, 0), (0, width - a.shape[1])], constant_values=fill)
        return a

    tile = _tile_rows_for_budget(n, max_mbytes_per_batch)
    # replicated placement: under multi-process SPMD every rank passes the SAME
    # host array and the explicit replicated NamedSharding makes it one global
    # array over the full mesh (single-process device_put suffices otherwise)
    if jax.process_count() > 1:
        from ..parallel.mesh import replicated

        rep = replicated(mesh)
        put = lambda a: jax.device_put(a, rep)  # noqa: E731
    else:
        put = jax.device_put
    xp = pad_repl(x, n_dev)
    if precomputed:
        xp = pad_cols(xp, xp.shape[0])  # square: columns align with `valid`
    validp = np.arange(xp.shape[0]) < n
    X = put(xp)  # replicated
    valid = put(validp)

    core = np.asarray(core_mask(X, valid, eps2, min_samples, mesh=mesh, metric=metric, tile_rows=tile))
    core = core[:n]
    core_idx = np.flatnonzero(core)
    nc = len(core_idx)
    if nc == 0:
        labels = np.full(n, -1, np.int32)
        return labels, (core_idx if calc_core_sample_indices else None)

    xc = pad_repl(x[np.ix_(core_idx, core_idx)] if precomputed else x[core_idx], n_dev)
    if precomputed:
        xc = pad_cols(xc, xc.shape[0])
    cvalidp = np.arange(xc.shape[0]) < nc
    Xc = put(xc)
    cvalid = put(cvalidp)
    tile_c = _tile_rows_for_budget(xc.shape[0], max_mbytes_per_batch)

    roots = np.asarray(
        core_components(Xc, cvalid, eps2, mesh=mesh, metric=metric, tile_rows=tile_c)
    )[:nc]
    # sklearn/cuML numbering: clusters ordered by ascending first (minimum)
    # core index — exactly the propagation roots, ranked
    uniq_roots = np.unique(roots)
    core_cluster = np.searchsorted(uniq_roots, roots).astype(np.int32)

    core_labels_p = np.full(xc.shape[0], -1, np.int32)
    core_labels_p[:nc] = core_cluster
    if precomputed:
        # border pass rows must carry point-to-CORE distances, column-aligned
        # with the (padded) core axis
        xb = pad_cols(pad_repl(x[:, core_idx], n_dev), xc.shape[0])
        X_border = put(xb)
    else:
        X_border = X
    labels = np.asarray(
        border_assign(
            X_border, valid, Xc, cvalid, put(core_labels_p), eps2,
            mesh=mesh, metric=metric, tile_rows=tile,
        )
    )[:n].astype(np.int32)
    return labels, (core_idx if calc_core_sample_indices else None)
