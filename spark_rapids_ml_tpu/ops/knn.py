#
# Distributed k-nearest-neighbors solvers — in-tree replacements for
# `cuml.neighbors.nearest_neighbors_mg.NearestNeighborsMG` (exact, reference
# knn.py:649) and the local-index ANN path (`cuml.neighbors.NearestNeighbors`
# IVFFlat, reference knn.py:1393-1404).
#
# Exact kNN, TPU-native shape: instead of the reference's UCX all-to-all
# (query blocks shuffled between ranks), ITEMS stay row-sharded and QUERIES are
# replicated: every device computes a [q_tile, n_local] distance tile on the
# MXU, takes a per-shard top-k, and the [n_dev, nq, k] candidates are gathered
# and merged with one final top-k — an all-gather of k·nq scalars instead of an
# item shuffle, which is the right trade on ICI (SURVEY.md §2.4 all-to-all row).
#
# ANN IVFFlat: per-shard KMeans coarse quantizer + PADDED cluster buckets
# (fixed list length -> static shapes); queries probe the nprobe closest
# centroids and search only those buckets via gather — the TPU analog of the
# IVF list scan.
#
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ROWS_AXIS


def _tile_topk(items, queries, valid, k, batch_queries=4096):
    """Per-device exact top-k: items [n_loc, d], queries [nq, d] ->
    (dist [nq, k], idx [nq, k] local). Scans query tiles; padding items get
    +inf distance."""
    n_loc, d = items.shape
    nq = queries.shape[0]
    n_tiles = max(1, -(-nq // batch_queries))
    pad = n_tiles * batch_queries - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    item_sq = jnp.sum(items * items, axis=1)  # [n_loc]
    big = jnp.asarray(jnp.inf, items.dtype)
    # k may exceed the per-shard row count (only the GLOBAL row count bounds
    # it); take what the shard has and pad candidates with +inf distance so the
    # global merge never selects them
    kk = min(k, n_loc)

    def one_tile(q):
        # ||q - x||² = ||q||² - 2 q·x + ||x||²; q·xᵀ rides the MXU
        d2 = item_sq[None, :] - 2.0 * (q @ items.T)
        d2 = jnp.where(valid[None, :], d2, big)
        neg_d, idx = jax.lax.top_k(-d2, kk)
        d_out = -neg_d + jnp.sum(q * q, axis=1)[:, None]
        if kk < k:
            d_out = jnp.pad(d_out, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
            idx = jnp.pad(idx, ((0, 0), (0, k - kk)))
        return d_out, idx

    qt = qp.reshape(n_tiles, batch_queries, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]


@jax.jit
def _row_sq(x):
    return jnp.sum(x * x, axis=1)


@partial(jax.jit, static_argnames=("kk",))
def _topk_tile_1dev(items, valid, item_sq, q, *, kk):
    d2 = item_sq[None, :] - 2.0 * (q @ items.T)
    d2 = jnp.where(valid[None, :], d2, jnp.inf)
    neg_d, idx = jax.lax.top_k(-d2, kk)
    return -neg_d + jnp.sum(q * q, axis=1)[:, None], idx


def _exact_knn_1dev(items, valid, queries, k, batch_queries):
    """Single-device exact kNN with a HOST loop over query tiles: each tile is
    one top-level program (matmul + top_k). The shard_map/in-program tiling
    form costs a full copy of the item matrix at benchmark scale (measured
    +11 GiB at 1M x 3k -> OOM), same XLA behavior as the KMeans tile loop."""
    import numpy as np

    nq = queries.shape[0]
    if nq == 0:
        return (
            np.zeros((0, k), dtype=np.asarray(queries).dtype),
            np.zeros((0, k), dtype=np.int32),
        )
    kk = min(k, items.shape[0])
    batch_queries = min(batch_queries, nq)
    item_sq = _row_sq(items)
    d_parts, i_parts = [], []
    for start in range(0, nq, batch_queries):
        # keep every tile the SAME shape (clamp back + drop the overlap) so the
        # tile program compiles exactly once
        s0 = min(start, nq - batch_queries)
        q = queries[s0 : s0 + batch_queries]
        d2, idx = _topk_tile_1dev(items, valid, item_sq, q, kk=kk)
        fresh = start - s0
        d_parts.append(np.asarray(d2)[fresh:])
        i_parts.append(np.asarray(idx)[fresh:])
    # results stay HOST numpy: every caller fetches to numpy immediately, so a
    # device round-trip here would be pure waste
    d2 = np.concatenate(d_parts, axis=0)
    idx = np.concatenate(i_parts, axis=0)
    if kk < k:
        d2 = np.pad(d2, ((0, 0), (0, k - kk)), constant_values=np.inf)
        idx = np.pad(idx, ((0, 0), (0, k - kk)))
    return np.sqrt(np.maximum(d2, 0.0)), idx


@partial(jax.jit, static_argnames=())
def _sparse_tile_merge(xt, q, q_sq, best_d2, best_i, tile_ids, fresh):
    """Merge one densified item tile into the running top-k: d² tile vs all
    queries (one MXU matmul), concat with the carried best, re-top-k.
    `fresh` masks rows already merged by a previous tile (the clamped last
    tile overlaps — a duplicate candidate would otherwise occupy two slots)."""
    d2 = (
        q_sq[:, None]
        - 2.0 * q @ xt.T
        + jnp.sum(xt * xt, axis=1)[None, :]
    )  # [nq, bt]
    d2 = jnp.where(fresh[None, :], d2, jnp.inf)
    cat_d = jnp.concatenate([best_d2, d2], axis=1)
    cat_i = jnp.concatenate([best_i, jnp.broadcast_to(tile_ids[None, :], d2.shape)], axis=1)
    neg_d, pos = jax.lax.top_k(-cat_d, best_d2.shape[1])
    return -neg_d, jnp.take_along_axis(cat_i, pos, axis=1)


def exact_knn_sparse(items_csr, queries, k: int, batch_items: int = 65536):
    """Exact kNN with SPARSE (scipy CSR) items: item tiles are densified one at
    a time on device and merged into a running top-k — CSR never fully
    densifies in memory (the reference's sparse kNN capability,
    cuML NearestNeighborsMG on cupyx CSR). Queries are dense [nq, d].

    Returns host (distances [nq, k] euclidean, item row indices [nq, k])."""
    import numpy as np

    n, d = items_csr.shape
    nq = queries.shape[0]
    kk = min(k, n)
    batch_items = min(batch_items, n)
    dtype = queries.dtype if queries.dtype in (np.float32, np.float64) else np.float32
    if nq == 0:
        return np.zeros((0, k), dtype=dtype), np.zeros((0, k), dtype=np.int32)
    q_dev = jax.device_put(np.ascontiguousarray(queries, dtype=dtype))
    q_sq = _row_sq(q_dev)
    best_d2 = jnp.full((nq, kk), jnp.inf, dtype)
    best_i = jnp.full((nq, kk), -1, jnp.int32)
    for start in range(0, n, batch_items):
        # clamp the last tile back so every tile has the same shape (single
        # compile); `fresh` masks the re-visited overlap rows
        s0 = min(start, max(0, n - batch_items))
        stop = s0 + batch_items
        xt = np.asarray(items_csr[s0:stop].todense(), dtype=dtype)
        tile_ids = jnp.arange(s0, stop, dtype=jnp.int32)
        fresh = tile_ids >= start
        best_d2, best_i = _sparse_tile_merge(
            xt, q_dev, q_sq, best_d2, best_i, tile_ids, fresh
        )
    dist = np.sqrt(np.maximum(np.asarray(best_d2), 0.0))
    idx = np.asarray(best_i)
    if kk < k:
        dist = np.pad(dist, ((0, 0), (0, k - kk)), constant_values=np.inf)
        idx = np.pad(idx, ((0, 0), (0, k - kk)), constant_values=-1)
    return dist, idx


def exact_knn(
    items: jax.Array,  # [n_pad, d] row-sharded
    valid: jax.Array,  # [n_pad] bool (False on padding)
    queries: jax.Array,  # [nq, d] replicated
    *,
    mesh,
    k: int,
    batch_queries: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Global exact kNN: returns (distances [nq, k], GLOBAL item indices [nq, k])
    sorted ascending by distance. Distances are euclidean (not squared), Spark/
    cuML convention."""
    if mesh.devices.size == 1:
        return _exact_knn_1dev(items, valid, queries, k, batch_queries)
    return _exact_knn_sharded(
        items, valid, queries, mesh=mesh, k=k, batch_queries=batch_queries
    )


@partial(jax.jit, static_argnames=("mesh", "k", "batch_queries"))
def _exact_knn_sharded(
    items: jax.Array,
    valid: jax.Array,
    queries: jax.Array,
    *,
    mesh,
    k: int,
    batch_queries: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    n_dev = mesh.devices.size
    n_loc = items.shape[0] // n_dev

    def local(items_l, valid_l):
        rank = jax.lax.axis_index(ROWS_AXIS)
        d2, idx = _tile_topk(items_l, queries, valid_l, k, batch_queries)
        gidx = idx + rank * n_loc
        return d2, gidx

    # per-shard candidates come back stacked over the mesh axis ([n_dev*nq, k]);
    # the merge below is a tiny [nq, n_dev*k] top-k that XLA gathers itself —
    # an all-gather of k·nq scalars, not an item shuffle
    d2_all, gidx_all = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS)),
        out_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS, None)),
    )(items, valid)
    nq = queries.shape[0]
    d2_cat = jnp.moveaxis(d2_all.reshape(n_dev, nq, k), 0, 1).reshape(nq, -1)
    gidx_cat = jnp.moveaxis(gidx_all.reshape(n_dev, nq, k), 0, 1).reshape(nq, -1)
    neg_d, pos = jax.lax.top_k(-d2_cat, k)
    final_idx = jnp.take_along_axis(gidx_cat, pos, axis=1)
    d2_final = jnp.maximum(-neg_d, 0.0)
    # replicate the [nq, k] result so every process can fetch it whole under
    # multi-process SPMD (each rank then slices its own queries' rows)
    from jax.sharding import NamedSharding

    rep = NamedSharding(mesh, P())
    return (
        jax.lax.with_sharding_constraint(jnp.sqrt(d2_final), rep),
        jax.lax.with_sharding_constraint(final_idx, rep),
    )


# ---------------------------------------------------------------------------
# IVFFlat approximate kNN (single-shard index; the estimator runs one per
# partition like the reference's local-index design)
# ---------------------------------------------------------------------------


def build_ivfflat(x, n_lists: int, seed: int = 0, kmeans_iters: int = 10):
    """Build an IVFFlat index on host+device: returns dict with centroids
    [n_lists, d], buckets [n_lists, L, d], bucket_ids [n_lists, L] (−1 pad).

    Bucket fill is vectorized: stable-sort rows by list, compute each row's
    offset within its list, one fancy-index scatter (no Python loop)."""
    import numpy as np

    x, centroids, assign, sorted_assign, order, offsets, n_lists, L = _coarse_quantizer(
        x, n_lists, seed, kmeans_iters
    )
    n, d = x.shape
    buckets = np.zeros((n_lists, L, d), np.float32)
    bucket_ids = np.full((n_lists, L), -1, np.int64)
    buckets[sorted_assign, offsets] = x[order]
    bucket_ids[sorted_assign, offsets] = order
    return {"centroids": centroids, "buckets": buckets, "bucket_ids": bucket_ids}


def _coarse_quantizer(x, n_lists: int, seed: int, kmeans_iters: int = 10):
    """Shared IVF coarse step: KMeans centroids + per-row assignment + the
    sorted-fill layout (order, offsets, counts, L)."""
    import numpy as np

    from .kmeans import kmeans_fit, kmeans_plus_plus_init, scalable_kmeans_init
    from ..parallel.mesh import get_mesh

    x = np.asarray(x, dtype=np.float32)
    n, d = x.shape
    n_lists = min(n_lists, n)
    init = scalable_kmeans_init if n_lists >= 64 else kmeans_plus_plus_init
    centers0 = init(x, n_lists, seed).astype(np.float32)
    # ONE h2d transfer of x, reused for training and assignment; no final
    # high-precision inertia pass (nothing consumes it, and its program is a
    # separate ~79s compile in a fresh process)
    xd = jax.device_put(x)
    state = kmeans_fit(
        xd, jnp.ones((n,), jnp.float32), jax.device_put(centers0),
        mesh=get_mesh(1), max_iter=kmeans_iters, tol=1e-6, final_inertia=False,
    )
    centroids_dev = state["cluster_centers_"]
    centroids = np.asarray(centroids_dev)
    assign = np.asarray(
        jax.jit(lambda X, C: jnp.argmin(
            jnp.sum(C * C, 1)[None, :] - 2.0 * X @ C.T, axis=1
        ))(xd, centroids_dev)
    )
    counts = np.bincount(assign, minlength=n_lists)
    L = max(1, int(counts.max()))
    order = np.argsort(assign, kind="stable")
    sorted_assign = assign[order]
    offsets = np.arange(n) - (np.cumsum(counts) - counts)[sorted_assign]
    return x, centroids, assign, sorted_assign, order, offsets, n_lists, L


def build_ivfpq(
    x, n_lists: int, *, M: int = 8, n_bits: int = 8, seed: int = 0,
    kmeans_iters: int = 10, pq_iters: int = 10, train_cap: int = 65536,
):
    """Build an IVFPQ index: coarse quantizer + per-subspace product
    quantization of the RESIDUALS (x − centroid), ADC-searchable.

    `algoParams` naming follows cuML ({"M": subquantizers, "n_bits": bits per
    code}, reference knn.py:1393-1404). Returns dict with centroids
    [C, d], codebooks [M, K, dsub] (K = 2^n_bits), code_buckets [C, L, M] uint8,
    bucket_ids [C, L] (−1 pad).
    """
    import numpy as np

    from .kmeans import _kmeanspp_device, kmeans_fit
    from ..parallel.mesh import get_mesh

    x, centroids, assign, sorted_assign, order, offsets, n_lists, L = _coarse_quantizer(
        x, n_lists, seed, kmeans_iters
    )
    n, d = x.shape
    if d % M:
        raise ValueError(f"M={M} must divide the feature dimension d={d}")
    dsub = d // M
    K = 1 << n_bits
    resid = (x - centroids[assign]).astype(np.float32)  # [n, d]

    # train per-subspace codebooks on a residual subsample
    rs = np.random.default_rng(seed)
    train = resid[rs.choice(n, min(n, train_cap), replace=False)]
    codebooks = np.zeros((M, K, dsub), np.float32)
    mesh1 = get_mesh(1)
    for m in range(M):
        # ONE h2d transfer of the sub-block, shared by seeding and training
        sub = jax.device_put(np.ascontiguousarray(train[:, m * dsub : (m + 1) * dsub]))
        sub_w = jnp.ones((sub.shape[0],), jnp.float32)
        k_eff = min(K, sub.shape[0])
        c0 = _kmeanspp_device(  # one dispatch; shared shape across all M
            sub, sub_w, seed + m, k=k_eff,
        )
        st = kmeans_fit(
            sub, sub_w, c0,
            mesh=mesh1, max_iter=pq_iters, tol=1e-6, final_inertia=False,
        )
        codebooks[m, :k_eff] = np.asarray(st["cluster_centers_"])
        if k_eff < K:  # degenerate tiny datasets: repeat the first centroid
            codebooks[m, k_eff:] = codebooks[m, 0]

    # encode all residuals: nearest codeword per subspace (device matmul)
    @jax.jit
    def encode(R, CB):  # R [n, M, dsub], CB [M, K, dsub]
        d2 = (
            jnp.sum(CB * CB, axis=2)[None, :, :]           # [1, M, K]
            - 2.0 * jnp.einsum("nmd,mkd->nmk", R, CB)      # [n, M, K]
        )
        return jnp.argmin(d2, axis=2).astype(jnp.int32)    # [n, M]

    codes = np.asarray(encode(
        jax.device_put(resid.reshape(n, M, dsub)), jax.device_put(codebooks)
    )).astype(np.uint8 if n_bits <= 8 else np.int32)

    code_buckets = np.zeros((n_lists, L, M), codes.dtype)
    bucket_ids = np.full((n_lists, L), -1, np.int64)
    code_buckets[sorted_assign, offsets] = codes[order]
    bucket_ids[sorted_assign, offsets] = order
    return {
        "centroids": centroids,
        "codebooks": codebooks,
        "code_buckets": code_buckets,
        "bucket_ids": bucket_ids,
    }


@partial(jax.jit, static_argnames=("k", "n_probes", "batch_queries"))
def _ivfpq_search_impl(
    queries, centroids, codebooks, code_buckets, bucket_ids,
    *, k: int, n_probes: int, batch_queries: int,
):
    nq, d = queries.shape
    C, L, M = code_buckets.shape
    K = codebooks.shape[1]
    dsub = d // M
    n_probes = min(n_probes, C)
    n_tiles = max(1, -(-nq // batch_queries))
    pad = n_tiles * batch_queries - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    cb_sq = jnp.sum(codebooks * codebooks, axis=2)  # [M, K]

    def one_tile(q):  # [B, d]
        B = q.shape[0]
        cd = jnp.sum(centroids * centroids, 1)[None, :] - 2.0 * q @ centroids.T
        probe_d, probe = jax.lax.top_k(-cd, n_probes)  # [B, P]
        # residual per probed list, split into subspaces
        q_res = q[:, None, :] - centroids[probe]  # [B, P, d]
        q_res = q_res.reshape(B, n_probes, M, dsub)
        # ADC lookup table: ||q_res_m − cb_mk||² (the einsum rides the MXU)
        lut = (
            jnp.sum(q_res * q_res, axis=3)[..., None]      # [B, P, M, 1]
            - 2.0 * jnp.einsum("bpmd,mkd->bpmk", q_res, codebooks)
            + cb_sq[None, None, :, :]
        )  # [B, P, M, K]
        cand_codes = code_buckets[probe].astype(jnp.int32)  # [B, P, L, M]
        cand_ids = bucket_ids[probe]  # [B, P, L]
        # dist[b,p,l] = Σ_m lut[b,p,m,codes[b,p,l,m]] — index the K axis
        # directly with codes transposed to [B, P, M, L]; broadcasting lut to
        # a [B,P,L,M,K] intermediate would materialize tens of GB
        codes_t = jnp.swapaxes(cand_codes, 2, 3)  # [B, P, M, L]
        picked = jnp.take_along_axis(lut, codes_t, axis=3)  # [B, P, M, L]
        dist = jnp.sum(picked, axis=2)  # [B, P, L]
        dist = jnp.where(cand_ids >= 0, dist, jnp.inf)
        dist = dist.reshape(B, n_probes * L)
        ids = cand_ids.reshape(B, n_probes * L)
        kk = min(k, n_probes * L)
        neg_d, pos = jax.lax.top_k(-dist, kk)
        out_ids = jnp.take_along_axis(ids, pos, axis=1)
        out_d = jnp.maximum(-neg_d, 0.0)
        if kk < k:
            out_d = jnp.pad(out_d, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
            out_ids = jnp.pad(out_ids, ((0, 0), (0, k - kk)), constant_values=-1)
        return jnp.sqrt(out_d), out_ids

    qt = qp.reshape(n_tiles, batch_queries, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]


def ivfpq_search(queries, index, *, k: int, n_probes: int, batch_queries: int = 256):
    """ADC search over an IVFPQ index (see build_ivfpq). Returns (approximate
    euclidean distances [nq, k], item ids [nq, k], −1 where short)."""
    return _ivfpq_search_impl(
        queries,
        jax.device_put(jnp.asarray(index["centroids"], jnp.float32)),
        jax.device_put(jnp.asarray(index["codebooks"], jnp.float32)),
        jax.device_put(jnp.asarray(index["code_buckets"])),
        jax.device_put(jnp.asarray(index["bucket_ids"])),
        k=k, n_probes=n_probes, batch_queries=batch_queries,
    )


@partial(jax.jit, static_argnames=("k", "n_probes", "batch_queries"))
def ivfflat_search(
    queries: jax.Array,  # [nq, d]
    centroids: jax.Array,  # [C, d]
    buckets: jax.Array,  # [C, L, d]
    bucket_ids: jax.Array,  # [C, L]
    *,
    k: int,
    n_probes: int,
    batch_queries: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """Probe the n_probes nearest lists per query; returns (sqrt distances,
    item ids) [nq, k] (id −1 where fewer than k candidates).

    Lists are scanned ONE PROBE AT A TIME with a running top-k: gathering all
    probed buckets at once is [B, P, L, d] — hundreds of GB at benchmark
    scale. The query-tile width additionally adapts so the per-probe gather
    [B, L, d] stays under ~1 GB."""
    nq, d = queries.shape
    C, L, _ = buckets.shape
    n_probes = min(n_probes, C)
    # bound the per-probe gather to ~1 GB of f32
    b_mem = max(16, int(1e9 / max(1, 4 * L * d)))
    batch_queries = max(16, min(batch_queries, b_mem))
    n_tiles = max(1, -(-nq // batch_queries))
    pad = n_tiles * batch_queries - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    kk = min(k, n_probes * L)

    def one_tile(q):  # [B, d]
        B = q.shape[0]
        cd = jnp.sum(centroids * centroids, 1)[None, :] - 2.0 * q @ centroids.T
        _, probe = jax.lax.top_k(-cd, n_probes)  # [B, n_probes]
        q_sq = jnp.sum(q * q, axis=1)  # [B]

        def probe_body(p_i, carry):
            best_d, best_i = carry  # [B, kk]
            pb = probe[:, p_i]  # [B]
            bucket = buckets[pb]  # [B, L, d] — the bounded gather
            ids = bucket_ids[pb]  # [B, L]
            # ||q − x||² = ||q||² − 2 q·x + ||x||²; q·x via batched matmul
            d2 = (
                q_sq[:, None]
                - 2.0 * jnp.einsum("bld,bd->bl", bucket, q)
                + jnp.sum(bucket * bucket, axis=2)
            )
            d2 = jnp.where(ids >= 0, d2, jnp.inf)
            cat_d = jnp.concatenate([best_d, d2], axis=1)
            cat_i = jnp.concatenate([best_i, ids], axis=1)
            neg_d, pos = jax.lax.top_k(-cat_d, kk)
            return -neg_d, jnp.take_along_axis(cat_i, pos, axis=1)

        init = (
            jnp.full((B, kk), jnp.inf, queries.dtype),
            jnp.full((B, kk), -1, bucket_ids.dtype),
        )
        best_d, best_i = jax.lax.fori_loop(0, n_probes, probe_body, init)
        dist = jnp.maximum(best_d, 0.0)
        if kk < k:  # fewer candidates than k: pad
            dist = jnp.pad(dist, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
            best_i = jnp.pad(best_i, ((0, 0), (0, k - kk)), constant_values=-1)
        return jnp.sqrt(dist), best_i

    qt = qp.reshape(n_tiles, batch_queries, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]
