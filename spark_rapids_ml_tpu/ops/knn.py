#
# Distributed k-nearest-neighbors solvers — in-tree replacements for
# `cuml.neighbors.nearest_neighbors_mg.NearestNeighborsMG` (exact, reference
# knn.py:649) and the local-index ANN path (`cuml.neighbors.NearestNeighbors`
# IVFFlat, reference knn.py:1393-1404).
#
# Exact kNN, TPU-native shape: instead of the reference's UCX all-to-all
# (query blocks shuffled between ranks), ITEMS stay row-sharded and QUERIES are
# replicated: every device computes a [q_tile, n_local] distance tile on the
# MXU, takes a per-shard top-k, and the [n_dev, nq, k] candidates are gathered
# and merged with one final top-k — an all-gather of k·nq scalars instead of an
# item shuffle, which is the right trade on ICI (SURVEY.md §2.4 all-to-all row).
#
# ANN IVFFlat: per-shard KMeans coarse quantizer + PADDED cluster buckets
# (fixed list length -> static shapes); queries probe the nprobe closest
# centroids and search only those buckets via gather — the TPU analog of the
# IVF list scan.
#
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ROWS_AXIS


def _tile_topk(items, queries, valid, k, batch_queries=4096):
    """Per-device exact top-k: items [n_loc, d], queries [nq, d] ->
    (dist [nq, k], idx [nq, k] local). Scans query tiles; padding items get
    +inf distance."""
    n_loc, d = items.shape
    nq = queries.shape[0]
    n_tiles = max(1, -(-nq // batch_queries))
    pad = n_tiles * batch_queries - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    item_sq = jnp.sum(items * items, axis=1)  # [n_loc]
    big = jnp.asarray(jnp.inf, items.dtype)
    # k may exceed the per-shard row count (only the GLOBAL row count bounds
    # it); take what the shard has and pad candidates with +inf distance so the
    # global merge never selects them
    kk = min(k, n_loc)

    def one_tile(q):
        # ||q - x||² = ||q||² - 2 q·x + ||x||²; q·xᵀ rides the MXU
        d2 = item_sq[None, :] - 2.0 * (q @ items.T)
        d2 = jnp.where(valid[None, :], d2, big)
        neg_d, idx = jax.lax.top_k(-d2, kk)
        d_out = -neg_d + jnp.sum(q * q, axis=1)[:, None]
        if kk < k:
            d_out = jnp.pad(d_out, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
            idx = jnp.pad(idx, ((0, 0), (0, k - kk)))
        return d_out, idx

    qt = qp.reshape(n_tiles, batch_queries, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]


@partial(jax.jit, static_argnames=("mesh", "k", "batch_queries"))
def exact_knn(
    items: jax.Array,  # [n_pad, d] row-sharded
    valid: jax.Array,  # [n_pad] bool (False on padding)
    queries: jax.Array,  # [nq, d] replicated
    *,
    mesh,
    k: int,
    batch_queries: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Global exact kNN: returns (distances [nq, k], GLOBAL item indices [nq, k])
    sorted ascending by distance. Distances are euclidean (not squared), Spark/
    cuML convention."""
    n_dev = mesh.devices.size
    n_loc = items.shape[0] // n_dev

    def local(items_l, valid_l):
        rank = jax.lax.axis_index(ROWS_AXIS)
        d2, idx = _tile_topk(items_l, queries, valid_l, k, batch_queries)
        gidx = idx + rank * n_loc
        return d2, gidx

    # per-shard candidates come back stacked over the mesh axis ([n_dev*nq, k]);
    # the merge below is a tiny [nq, n_dev*k] top-k that XLA gathers itself —
    # an all-gather of k·nq scalars, not an item shuffle
    d2_all, gidx_all = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS)),
        out_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS, None)),
    )(items, valid)
    nq = queries.shape[0]
    d2_cat = jnp.moveaxis(d2_all.reshape(n_dev, nq, k), 0, 1).reshape(nq, -1)
    gidx_cat = jnp.moveaxis(gidx_all.reshape(n_dev, nq, k), 0, 1).reshape(nq, -1)
    neg_d, pos = jax.lax.top_k(-d2_cat, k)
    final_idx = jnp.take_along_axis(gidx_cat, pos, axis=1)
    d2_final = jnp.maximum(-neg_d, 0.0)
    return jnp.sqrt(d2_final), final_idx


# ---------------------------------------------------------------------------
# IVFFlat approximate kNN (single-shard index; the estimator runs one per
# partition like the reference's local-index design)
# ---------------------------------------------------------------------------


def build_ivfflat(x, n_lists: int, seed: int = 0, kmeans_iters: int = 10):
    """Build an IVFFlat index on host+device: returns dict with centroids
    [n_lists, d], buckets [n_lists, L, d], bucket_ids [n_lists, L] (−1 pad)."""
    import numpy as np

    from .kmeans import kmeans_fit, kmeans_plus_plus_init
    from ..parallel.mesh import get_mesh

    x = np.asarray(x, dtype=np.float32)
    n, d = x.shape
    n_lists = min(n_lists, n)
    centers0 = kmeans_plus_plus_init(x, n_lists, seed).astype(np.float32)
    mesh1 = get_mesh(1)
    state = kmeans_fit(
        jax.device_put(x), jnp.ones((n,), jnp.float32), jax.device_put(centers0),
        mesh=mesh1, max_iter=kmeans_iters, tol=1e-6,
    )
    centroids = np.asarray(state["cluster_centers_"])
    d2 = ((x[:, None, :] - centroids[None, :, :]) ** 2).sum(-1) if n * n_lists * d < 5e7 else None
    if d2 is None:
        assign = np.asarray(
            jax.jit(lambda X, C: jnp.argmin(
                jnp.sum(C * C, 1)[None, :] - 2.0 * X @ C.T, axis=1
            ))(jax.device_put(x), jax.device_put(centroids))
        )
    else:
        assign = d2.argmin(1)
    L = max(1, int(np.bincount(assign, minlength=n_lists).max()))
    buckets = np.zeros((n_lists, L, d), np.float32)
    bucket_ids = np.full((n_lists, L), -1, np.int64)
    fill = np.zeros(n_lists, np.int64)
    for i, c in enumerate(assign):
        buckets[c, fill[c]] = x[i]
        bucket_ids[c, fill[c]] = i
        fill[c] += 1
    return {"centroids": centroids, "buckets": buckets, "bucket_ids": bucket_ids}


@partial(jax.jit, static_argnames=("k", "n_probes", "batch_queries"))
def ivfflat_search(
    queries: jax.Array,  # [nq, d]
    centroids: jax.Array,  # [C, d]
    buckets: jax.Array,  # [C, L, d]
    bucket_ids: jax.Array,  # [C, L]
    *,
    k: int,
    n_probes: int,
    batch_queries: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """Probe the n_probes nearest lists per query; returns (sqrt distances,
    item ids) [nq, k] (id −1 where fewer than k candidates)."""
    nq, d = queries.shape
    C, L, _ = buckets.shape
    n_probes = min(n_probes, C)
    n_tiles = max(1, -(-nq // batch_queries))
    pad = n_tiles * batch_queries - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))

    def one_tile(q):  # [B, d]
        B = q.shape[0]
        cd = jnp.sum(centroids * centroids, 1)[None, :] - 2.0 * q @ centroids.T
        _, probe = jax.lax.top_k(-cd, n_probes)  # [B, n_probes]
        cand = buckets[probe]  # [B, n_probes, L, d]
        cand_ids = bucket_ids[probe]  # [B, n_probes, L]
        cand = cand.reshape(B, n_probes * L, d)
        cand_ids = cand_ids.reshape(B, n_probes * L)
        d2 = jnp.sum((cand - q[:, None, :]) ** 2, axis=2)
        d2 = jnp.where(cand_ids >= 0, d2, jnp.inf)
        neg_d, pos = jax.lax.top_k(-d2, min(k, n_probes * L))
        ids = jnp.take_along_axis(cand_ids, pos, axis=1)
        dist = jnp.maximum(-neg_d, 0.0)
        if dist.shape[1] < k:  # fewer candidates than k: pad
            padk = k - dist.shape[1]
            dist = jnp.pad(dist, ((0, 0), (0, padk)), constant_values=jnp.inf)
            ids = jnp.pad(ids, ((0, 0), (0, padk)), constant_values=-1)
        return jnp.sqrt(dist), ids

    qt = qp.reshape(n_tiles, batch_queries, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]
