#
# Distributed k-nearest-neighbors solvers — in-tree replacements for
# `cuml.neighbors.nearest_neighbors_mg.NearestNeighborsMG` (exact, reference
# knn.py:649) and the local-index ANN path (`cuml.neighbors.NearestNeighbors`
# IVFFlat, reference knn.py:1393-1404).
#
# Exact kNN, TPU-native shape: instead of the reference's UCX all-to-all
# (query blocks shuffled between ranks), ITEMS stay row-sharded and QUERIES are
# replicated: every device computes a [q_tile, n_local] distance tile on the
# MXU, takes a per-shard top-k, and the [n_dev, nq, k] candidates are gathered
# and merged with one final top-k — an all-gather of k·nq scalars instead of an
# item shuffle, which is the right trade on ICI (SURVEY.md §2.4 all-to-all row).
#
# ANN IVFFlat: per-shard KMeans coarse quantizer + PADDED cluster buckets
# (fixed list length -> static shapes); queries probe the nprobe closest
# centroids and search only those buckets via gather — the TPU analog of the
# IVF list scan.
#
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from ..parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import ROWS_AXIS
from .distance import argmin_assign, pairwise_d2, row_sq, tile_topk, topk_tile

# row-tiled nearest-centroid assignment (shared core), compiled once per shape
_assign_rows = jax.jit(argmin_assign)

# the per-device query-tile scan is the SHARED core's (ops/distance.py):
# query tiles of config["distance_tile_rows"] rows, item axis k-tiled so the
# [tile, n_loc] distance block never materializes on the kernel path
_tile_topk = tile_topk


@jax.jit
def _row_sq(x):
    return row_sq(x)


@partial(jax.jit, static_argnames=("kk",))
def _topk_tile_1dev(items, valid, item_sq, q, *, kk):
    """One compiled query-tile program over the shared core (the host-looped
    single-device path below)."""
    d2, idx = topk_tile(q, items, valid, kk, item_sq=item_sq)
    return d2 + row_sq(q)[:, None], idx


def _exact_knn_1dev(items, valid, queries, k, batch_queries):
    """Single-device exact kNN with a HOST loop over query tiles: each tile is
    one top-level program over the shared core (distance.topk_tile). The
    shard_map/in-program tiling form costs a full copy of the item matrix at
    benchmark scale (measured +11 GiB at 1M x 3k -> OOM), same XLA behavior
    as the KMeans tile loop."""
    import numpy as np

    from .distance import tile_rows

    batch_queries = batch_queries or tile_rows()
    nq = queries.shape[0]
    if nq == 0:
        return (
            np.zeros((0, k), dtype=np.asarray(queries).dtype),
            np.zeros((0, k), dtype=np.int32),
        )
    kk = min(k, items.shape[0])
    batch_queries = min(batch_queries, nq)
    item_sq = _row_sq(items)
    d_parts, i_parts = [], []
    for start in range(0, nq, batch_queries):
        # keep every tile the SAME shape (clamp back + drop the overlap) so the
        # tile program compiles exactly once
        s0 = min(start, nq - batch_queries)
        q = queries[s0 : s0 + batch_queries]
        d2, idx = _topk_tile_1dev(items, valid, item_sq, q, kk=kk)
        fresh = start - s0
        d_parts.append(np.asarray(d2)[fresh:])  # host-fetch-ok: per-TILE result fetch — every caller consumes numpy (comment below), a device round-trip here is pure waste
        i_parts.append(np.asarray(idx)[fresh:])  # host-fetch-ok: per-TILE result fetch — see above
    # results stay HOST numpy: every caller fetches to numpy immediately, so a
    # device round-trip here would be pure waste
    d2 = np.concatenate(d_parts, axis=0)
    idx = np.concatenate(i_parts, axis=0)
    if kk < k:
        d2 = np.pad(d2, ((0, 0), (0, k - kk)), constant_values=np.inf)
        idx = np.pad(idx, ((0, 0), (0, k - kk)))
    return np.sqrt(np.maximum(d2, 0.0)), idx


@partial(jax.jit, static_argnames=())
def _sparse_tile_merge(xt, q, best_d2, best_i, tile_ids, fresh):
    """Merge one densified item tile into the running top-k: d² tile vs all
    queries (one shared-core distance tile, ops/distance.py), concat with
    the carried best, re-top-k. `fresh` masks rows already merged by a
    previous tile (the clamped last tile overlaps — a duplicate candidate
    would otherwise occupy two slots)."""
    d2 = pairwise_d2(q, xt)  # [nq, bt]
    d2 = jnp.where(fresh[None, :], d2, jnp.inf)
    cat_d = jnp.concatenate([best_d2, d2], axis=1)
    cat_i = jnp.concatenate([best_i, jnp.broadcast_to(tile_ids[None, :], d2.shape)], axis=1)
    neg_d, pos = jax.lax.top_k(-cat_d, best_d2.shape[1])
    return -neg_d, jnp.take_along_axis(cat_i, pos, axis=1)


def exact_knn_sparse(items_csr, queries, k: int, batch_items: int = 65536):
    """Exact kNN with SPARSE (scipy CSR) items: item tiles are densified one at
    a time on device and merged into a running top-k — CSR never fully
    densifies in memory (the reference's sparse kNN capability,
    cuML NearestNeighborsMG on cupyx CSR). Queries are dense [nq, d].

    Returns host (distances [nq, k] euclidean, item row indices [nq, k])."""
    import numpy as np

    n, d = items_csr.shape
    nq = queries.shape[0]
    kk = min(k, n)
    batch_items = min(batch_items, n)
    dtype = queries.dtype if queries.dtype in (np.float32, np.float64) else np.float32
    if nq == 0:
        return np.zeros((0, k), dtype=dtype), np.zeros((0, k), dtype=np.int32)
    q_dev = jax.device_put(np.ascontiguousarray(queries, dtype=dtype))
    best_d2 = jnp.full((nq, kk), jnp.inf, dtype)
    best_i = jnp.full((nq, kk), -1, jnp.int32)
    for start in range(0, n, batch_items):
        # clamp the last tile back so every tile has the same shape (single
        # compile); `fresh` masks the re-visited overlap rows
        s0 = min(start, max(0, n - batch_items))
        stop = s0 + batch_items
        xt = np.asarray(items_csr[s0:stop].todense(), dtype=dtype)
        tile_ids = jnp.arange(s0, stop, dtype=jnp.int32)
        fresh = tile_ids >= start
        best_d2, best_i = _sparse_tile_merge(
            xt, q_dev, best_d2, best_i, tile_ids, fresh
        )
    dist = np.sqrt(np.maximum(np.asarray(best_d2), 0.0))
    idx = np.asarray(best_i)
    if kk < k:
        dist = np.pad(dist, ((0, 0), (0, k - kk)), constant_values=np.inf)
        idx = np.pad(idx, ((0, 0), (0, k - kk)), constant_values=-1)
    return dist, idx


def exact_knn(
    items: jax.Array,  # [n_pad, d] row-sharded
    valid: jax.Array,  # [n_pad] bool (False on padding)
    queries: jax.Array,  # [nq, d] replicated
    *,
    mesh,
    k: int,
    batch_queries: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Global exact kNN: returns (distances [nq, k], GLOBAL item indices [nq, k])
    sorted ascending by distance. Distances are euclidean (not squared), Spark/
    cuML convention. `batch_queries` defaults to
    ``config["distance_tile_rows"]`` (the shared core's row-tile knob)."""
    if mesh.devices.size == 1:
        return _exact_knn_1dev(items, valid, queries, k, batch_queries)
    return _exact_knn_sharded(
        items, valid, queries, mesh=mesh, k=k, batch_queries=batch_queries
    )


@partial(jax.jit, static_argnames=("mesh", "k", "batch_queries"))
def _exact_knn_sharded(
    items: jax.Array,
    valid: jax.Array,
    queries: jax.Array,
    *,
    mesh,
    k: int,
    batch_queries: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    n_dev = mesh.devices.size
    n_loc = items.shape[0] // n_dev

    def local(items_l, valid_l):
        rank = jax.lax.axis_index(ROWS_AXIS)
        d2, idx = _tile_topk(items_l, queries, valid_l, k, batch_queries)
        gidx = idx + rank * n_loc
        return d2, gidx

    # per-shard candidates come back stacked over the mesh axis ([n_dev*nq, k]);
    # the merge below is a tiny [nq, n_dev*k] top-k that XLA gathers itself —
    # an all-gather of k·nq scalars, not an item shuffle
    d2_all, gidx_all = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS)),
        out_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS, None)),
    )(items, valid)
    nq = queries.shape[0]
    d2_cat = jnp.moveaxis(d2_all.reshape(n_dev, nq, k), 0, 1).reshape(nq, -1)
    gidx_cat = jnp.moveaxis(gidx_all.reshape(n_dev, nq, k), 0, 1).reshape(nq, -1)
    neg_d, pos = jax.lax.top_k(-d2_cat, k)
    final_idx = jnp.take_along_axis(gidx_cat, pos, axis=1)
    d2_final = jnp.maximum(-neg_d, 0.0)
    # replicate the [nq, k] result so every process can fetch it whole under
    # multi-process SPMD (each rank then slices its own queries' rows)
    from jax.sharding import NamedSharding

    rep = NamedSharding(mesh, P())
    return (
        jax.lax.with_sharding_constraint(jnp.sqrt(d2_final), rep),
        jax.lax.with_sharding_constraint(final_idx, rep),
    )


# ---------------------------------------------------------------------------
# IVFFlat approximate kNN (single-shard index; the estimator runs one per
# partition like the reference's local-index design)
# ---------------------------------------------------------------------------


def build_ivfflat(x, n_lists: int, seed: int = 0, kmeans_iters: int = 10):
    """Build an IVFFlat index: returns dict with centroids [n_lists, d],
    buckets [n_lists, L, d], bucket_ids [n_lists, L] (−1 pad) — centroids and
    buckets are DEVICE arrays (the search consumes them in HBM; only the tiny
    id layout is host-built).

    Bucket fill is one device gather through the host-computed padded id
    layout — the item matrix itself never crosses back to the host (a 1 GB
    device→host→device round trip costs minutes through a remote PJRT
    tunnel)."""
    import numpy as np

    xd, centroids, assign, sorted_assign, order, offsets, n_lists, L = _coarse_quantizer(
        x, n_lists, seed, kmeans_iters
    )
    n, d = xd.shape
    bucket_ids = np.full((n_lists, L), -1, np.int64)
    bucket_ids[sorted_assign, offsets] = order
    idsj = jax.device_put(bucket_ids)
    buckets = _gather_buckets(xd, idsj)
    return {"centroids": centroids, "buckets": buckets, "bucket_ids": idsj}


@jax.jit
def _gather_buckets(X, I):
    """Padded bucket layout via one device gather (pad ids −1 -> zero row)."""
    n = X.shape[0]
    return jnp.where((I >= 0)[:, :, None], X[jnp.clip(I, 0, n - 1)], 0.0)


def _coarse_quantizer(x, n_lists: int, seed: int, kmeans_iters: int = 10):
    """Shared IVF coarse step: KMeans centroids + per-row assignment + the
    sorted-fill layout (order, offsets, counts, L).

    Accepts a host array OR a device-resident jax.Array (benchmark datagen
    produces the latter). Every heavy step — k-means|| seeding, Lloyd
    iterations, assignment — is device-resident; only the [n] int32
    assignment vector is fetched for the host-side bucket layout."""
    import numpy as np

    from .kmeans import _kmeanspp_device, kmeans_fit, scalable_kmeans_init_device
    from ..parallel.mesh import get_mesh

    if isinstance(x, jax.Array):
        xd = x if x.dtype == jnp.float32 else x.astype(jnp.float32)
    else:
        xd = jax.device_put(np.ascontiguousarray(np.asarray(x, dtype=np.float32)))
    n, d = xd.shape
    n_lists = min(n_lists, n)
    ones = jnp.ones((n,), jnp.float32)
    if n_lists >= 64:
        centers0 = scalable_kmeans_init_device(xd, n_lists, seed)
    else:
        # bound the k-means++ scan: one contiguous slice (ordering bias is
        # washed out by the full-data Lloyd refinement below)
        n_pp = min(n, 262_144)
        xs = jax.lax.dynamic_slice_in_dim(xd, 0, n_pp, 0) if n_pp < n else xd
        centers0 = _kmeanspp_device(
            xs, jnp.ones((n_pp,), jnp.float32), seed, k=n_lists
        )
    # no final high-precision inertia pass: nothing consumes it, and its
    # program is a separate ~79s compile in a fresh process
    state = kmeans_fit(
        xd, ones, centers0,
        mesh=get_mesh(1), max_iter=kmeans_iters, tol=1e-6, final_inertia=False,
    )
    centroids_dev = state["cluster_centers_"].astype(jnp.float32)
    assign = np.asarray(_assign_rows(xd, centroids_dev))
    counts = np.bincount(assign, minlength=n_lists)
    L = max(1, int(counts.max()))
    order = np.argsort(assign, kind="stable")
    sorted_assign = assign[order]
    offsets = np.arange(n) - (np.cumsum(counts) - counts)[sorted_assign]
    return xd, centroids_dev, assign, sorted_assign, order, offsets, n_lists, L


def build_ivfpq(
    x, n_lists: int, *, M: int = 8, n_bits: int = 8, seed: int = 0,
    kmeans_iters: int = 10, pq_iters: int = 10, train_cap: int = 65536,
):
    """Build an IVFPQ index: coarse quantizer + per-subspace product
    quantization of the RESIDUALS (x − centroid), ADC-searchable.

    `algoParams` naming follows cuML ({"M": subquantizers, "n_bits": bits per
    code}, reference knn.py:1393-1404). Returns dict with centroids
    [C, d], codebooks [M, K, dsub] (K = 2^n_bits), code_buckets [C, L, M] uint8,
    bucket_ids [C, L] (−1 pad).
    """
    import numpy as np

    from .kmeans import _kmeanspp_device, kmeans_fit
    from ..parallel.mesh import get_mesh

    xd, centroids, assign, sorted_assign, order, offsets, n_lists, L = _coarse_quantizer(
        x, n_lists, seed, kmeans_iters
    )
    n, d = xd.shape
    if d % M:
        raise ValueError(f"M={M} must divide the feature dimension d={d}")
    dsub = d // M
    K = 1 << n_bits

    # train per-subspace codebooks on a RESIDUAL subsample built from a few
    # contiguous row blocks at random offsets: no full [n, d] residual matrix
    # is ever materialized (that doubles HBM at large shapes), and no
    # fancy-index gather touches the big x (the pattern XLA answers with a
    # full device copy)
    rs = np.random.default_rng(seed)
    cap = min(n, train_cap)
    n_blocks = min(16, max(1, cap // 1024)) if cap < n else 1
    bs = cap // n_blocks
    assign_dev = jax.device_put(assign)
    blocks = []
    for b in range(n_blocks):
        off = int(rs.integers(0, max(1, n - bs + 1))) if cap < n else b * bs
        blocks.append(_residual_block(xd, centroids, assign_dev, off, size=bs))
    train = jnp.concatenate(blocks, axis=0) if len(blocks) > 1 else blocks[0]
    codebooks = np.zeros((M, K, dsub), np.float32)
    mesh1 = get_mesh(1)
    for m in range(M):
        sub = train[:, m * dsub : (m + 1) * dsub]
        sub_w = jnp.ones((sub.shape[0],), jnp.float32)
        k_eff = min(K, sub.shape[0])
        c0 = _kmeanspp_device(  # one dispatch; shared shape across all M
            sub, sub_w, seed + m, k=k_eff,
        )
        st = kmeans_fit(
            sub, sub_w, c0,
            mesh=mesh1, max_iter=pq_iters, tol=1e-6, final_inertia=False,
        )
        codebooks[m, :k_eff] = np.asarray(st["cluster_centers_"])  # host-fetch-ok: one codebook fetch per PQ subspace (M is small and fixed), landing in the host codebook table
        if k_eff < K:  # degenerate tiny datasets: repeat the first centroid
            codebooks[m, k_eff:] = codebooks[m, 0]

    # encode all points: residual + nearest codeword per subspace, TILED over
    # rows inside one program — the per-tile residual is transient, so peak
    # HBM stays x + one tile; only the [n, M] code matrix crosses to host
    codes = np.asarray(
        _encode_residuals(xd, centroids, assign_dev, jax.device_put(codebooks))
    ).astype(np.uint8 if n_bits <= 8 else np.int32)

    code_buckets = np.zeros((n_lists, L, M), codes.dtype)
    bucket_ids = np.full((n_lists, L), -1, np.int64)
    code_buckets[sorted_assign, offsets] = codes[order]
    bucket_ids[sorted_assign, offsets] = order
    return {
        "centroids": centroids,
        "codebooks": codebooks,
        "code_buckets": code_buckets,
        "bucket_ids": bucket_ids,
    }


@partial(jax.jit, static_argnames=("size",))
def _residual_block(X, C, A, off, *, size):
    """Residuals of one contiguous row block: X[off:off+size] − C[A[...]]."""
    xb = jax.lax.dynamic_slice_in_dim(X, off, size, 0)
    ab = jax.lax.dynamic_slice_in_dim(A, off, size, 0)
    return (xb - C[ab]).astype(jnp.float32)


@jax.jit
def _encode_residuals(X, C, A, CB):
    """PQ-encode every row: nearest codeword per subspace of (x − centroid),
    tiled over rows so the residual never exists in full. CB [M, K, dsub]."""
    n, d = X.shape
    M, K, dsub = CB.shape
    tile = max(256, min(n, 4_000_000 // max(d, 1)))
    n_tiles = -(-n // tile)
    cb_sq = jnp.sum(CB * CB, axis=2)  # [M, K]

    def body(ti, out):
        r0 = jnp.minimum(ti * tile, n - tile)
        xb = jax.lax.dynamic_slice(X, (r0, 0), (tile, d))
        ab = jax.lax.dynamic_slice(A, (r0,), (tile,))
        R = (xb - C[ab]).reshape(tile, M, dsub)
        d2 = cb_sq[None] - 2.0 * jnp.einsum("nmd,mkd->nmk", R, CB)
        codes_t = jnp.argmin(d2, axis=2).astype(jnp.int32)  # distance-ok: PQ nearest-codeword argmin over [tile, M, K] per-SUBSPACE residual distances — M parallel tiny codebooks, not the row-tile x·cᵀ shape the core owns
        return jax.lax.dynamic_update_slice(out, codes_t, (r0, 0))

    if n <= tile:
        R = (X - C[A]).reshape(n, M, dsub)
        d2 = cb_sq[None] - 2.0 * jnp.einsum("nmd,mkd->nmk", R, CB)
        return jnp.argmin(d2, axis=2).astype(jnp.int32)  # distance-ok: same per-subspace PQ codeword argmin as the tiled branch above
    return jax.lax.fori_loop(
        0, n_tiles, body, jnp.zeros((n, M), jnp.int32)
    )


@partial(jax.jit, static_argnames=("k", "n_probes", "batch_queries"))
def _ivfpq_search_impl(
    queries, centroids, codebooks, code_buckets, bucket_ids,
    *, k: int, n_probes: int, batch_queries: int,
):
    nq, d = queries.shape
    C, L, M = code_buckets.shape
    K = codebooks.shape[1]
    dsub = d // M
    n_probes = min(n_probes, C)
    n_tiles = max(1, -(-nq // batch_queries))
    pad = n_tiles * batch_queries - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    cb_sq = jnp.sum(codebooks * codebooks, axis=2)  # [M, K]

    def one_tile(q):  # [B, d]
        B = q.shape[0]
        # coarse probe through the shared core (identical ranking: the
        # ||q||^2 term is constant per row)
        _, probe = topk_tile(q, centroids, None, n_probes)  # [B, P]
        # residual per probed list, split into subspaces
        q_res = q[:, None, :] - centroids[probe]  # [B, P, d]
        q_res = q_res.reshape(B, n_probes, M, dsub)
        # ADC lookup table: ||q_res_m − cb_mk||² (the einsum rides the MXU)
        lut = (
            jnp.sum(q_res * q_res, axis=3)[..., None]      # [B, P, M, 1]
            - 2.0 * jnp.einsum("bpmd,mkd->bpmk", q_res, codebooks)
            + cb_sq[None, None, :, :]
        )  # [B, P, M, K]
        cand_codes = code_buckets[probe].astype(jnp.int32)  # [B, P, L, M]
        cand_ids = bucket_ids[probe]  # [B, P, L]
        # dist[b,p,l] = Σ_m lut[b,p,m,codes[b,p,l,m]] — index the K axis
        # directly with codes transposed to [B, P, M, L]; broadcasting lut to
        # a [B,P,L,M,K] intermediate would materialize tens of GB
        codes_t = jnp.swapaxes(cand_codes, 2, 3)  # [B, P, M, L]
        picked = jnp.take_along_axis(lut, codes_t, axis=3)  # [B, P, M, L]
        dist = jnp.sum(picked, axis=2)  # [B, P, L]
        dist = jnp.where(cand_ids >= 0, dist, jnp.inf)
        dist = dist.reshape(B, n_probes * L)
        ids = cand_ids.reshape(B, n_probes * L)
        kk = min(k, n_probes * L)
        neg_d, pos = jax.lax.top_k(-dist, kk)
        out_ids = jnp.take_along_axis(ids, pos, axis=1)
        out_d = jnp.maximum(-neg_d, 0.0)
        if kk < k:
            out_d = jnp.pad(out_d, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
            out_ids = jnp.pad(out_ids, ((0, 0), (0, k - kk)), constant_values=-1)
        return jnp.sqrt(out_d), out_ids

    qt = qp.reshape(n_tiles, batch_queries, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]


def ivfpq_search(queries, index, *, k: int, n_probes: int, batch_queries: int = 256):
    """ADC search over an IVFPQ index (see build_ivfpq). Returns (approximate
    euclidean distances [nq, k], item ids [nq, k], −1 where short)."""
    return _ivfpq_search_impl(
        queries,
        jax.device_put(jnp.asarray(index["centroids"], jnp.float32)),
        jax.device_put(jnp.asarray(index["codebooks"], jnp.float32)),
        jax.device_put(jnp.asarray(index["code_buckets"])),
        jax.device_put(jnp.asarray(index["bucket_ids"])),
        k=k, n_probes=n_probes, batch_queries=batch_queries,
    )


@partial(jax.jit, static_argnames=("k", "n_probes", "batch_queries"))
def ivfflat_search(
    queries: jax.Array,  # [nq, d]
    centroids: jax.Array,  # [C, d]
    buckets: jax.Array,  # [C, L, d]
    bucket_ids: jax.Array,  # [C, L]
    *,
    k: int,
    n_probes: int,
    batch_queries: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """Probe the n_probes nearest lists per query; returns (sqrt distances,
    item ids) [nq, k] (id −1 where fewer than k candidates).

    Lists are scanned ONE PROBE AT A TIME with a running top-k: gathering all
    probed buckets at once is [B, P, L, d] — hundreds of GB at benchmark
    scale. The query-tile width additionally adapts so the per-probe gather
    [B, L, d] stays under ~1 GB."""
    nq, d = queries.shape
    C, L, _ = buckets.shape
    n_probes = min(n_probes, C)
    # bound the per-probe gather to ~1 GB of f32
    b_mem = max(16, int(1e9 / max(1, 4 * L * d)))
    batch_queries = max(16, min(batch_queries, b_mem))
    n_tiles = max(1, -(-nq // batch_queries))
    pad = n_tiles * batch_queries - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    kk = min(k, n_probes * L)

    def one_tile(q):  # [B, d]
        B = q.shape[0]
        # coarse probe through the shared core (ranking-identical, see above)
        _, probe = topk_tile(q, centroids, None, n_probes)  # [B, n_probes]
        q_sq = jnp.sum(q * q, axis=1)  # [B]

        def probe_body(p_i, carry):
            best_d, best_i = carry  # [B, kk]
            pb = probe[:, p_i]  # [B]
            bucket = buckets[pb]  # [B, L, d] — the bounded gather
            ids = bucket_ids[pb]  # [B, L]
            # ||q − x||² = ||q||² − 2 q·x + ||x||²; q·x via batched matmul
            d2 = (
                q_sq[:, None]
                - 2.0 * jnp.einsum("bld,bd->bl", bucket, q)
                + jnp.sum(bucket * bucket, axis=2)
            )
            d2 = jnp.where(ids >= 0, d2, jnp.inf)
            cat_d = jnp.concatenate([best_d, d2], axis=1)
            cat_i = jnp.concatenate([best_i, ids], axis=1)
            neg_d, pos = jax.lax.top_k(-cat_d, kk)  # distance-ok: IVF bucket scan — per-query GATHERED buckets ([B, L, d] batched einsum), not the shared row-tile x·cᵀ shape; the running kk-merge is the memory bound here
            return -neg_d, jnp.take_along_axis(cat_i, pos, axis=1)

        init = (
            jnp.full((B, kk), jnp.inf, queries.dtype),
            jnp.full((B, kk), -1, bucket_ids.dtype),
        )
        best_d, best_i = jax.lax.fori_loop(0, n_probes, probe_body, init)
        dist = jnp.maximum(best_d, 0.0)
        if kk < k:  # fewer candidates than k: pad
            dist = jnp.pad(dist, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
            best_i = jnp.pad(best_i, ((0, 0), (0, k - kk)), constant_values=-1)
        return jnp.sqrt(dist), best_i

    qt = qp.reshape(n_tiles, batch_queries, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]
