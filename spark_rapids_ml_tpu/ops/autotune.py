#
# Measured kernel autotuner for the tiled distance core's block planner
# (docs/performance.md "Kernel autotuner").
#
# The static `plan_blocks` heuristic (ops/distance.py) fits half a v5e
# core's VMEM and is a fine cold-start default, but the best (block_rows,
# block_k) tiling is a property of the part and the shape, not of a fixed
# budget. This module measures it: on first TPU contact per (shape-class,
# dtype, fast-flag) it times a small candidate grid of tilings ON DEVICE,
# picks the winner, and persists the table as JSON beside the XLA compile
# cache (`config["compilation_cache_dir"]`) so later PROCESSES reuse the
# measurement instead of redoing it — the same amortization contract as the
# compile cache itself.
#
# Degradation contract (pinned by tests/test_autotune.py and the
# ci/analysis fixture pair): a missing, malformed, stale-version, or
# unwritable table NEVER fails a fit — every failure path returns "no
# entry" and the caller falls back to the heuristic. `SRML_AUTOTUNE=0`
# (config["autotune_enabled"]) disables lookup and measurement entirely;
# off-TPU (kernel_mode() != "pallas") nothing is ever measured, so CPU/CI
# behavior is byte-identical to the heuristic-only planner.
#
# `lookup` runs at TRACE time (the block planner is called while tracing
# the jitted assignment programs); `ensure` — the actual measurement — is
# HOST-side only, called eagerly by solver drivers before their loop with
# host-known shapes. Counters follow the distance.* trace-time idiom.
#
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import telemetry

# Persisted-table schema version: a table written by an incompatible older
# build is STALE — discarded wholesale (degrade to heuristic), not patched.
_TABLE_VERSION = 1

_TABLE_BASENAME = "srml_autotune.json"

# candidate (block_rows, block_k) grid; filtered per shape by the VMEM-fit
# predicate before timing, and the heuristic's own pick is always included
_CANDIDATE_BR = (128, 256, 512)
_CANDIDATE_BK = (128, 256, 512)

_LOCK = threading.Lock()
_TABLE: Optional[Dict[str, Any]] = None  # guarded-by: _LOCK (lazy-loaded)
_STATS = {"hits": 0, "misses": 0, "measurements": 0, "table_errors": 0}  # guarded-by: _LOCK


def enabled() -> bool:
    """Autotuner opt-out: `config["autotune_enabled"]`, seeded from
    SRML_AUTOTUNE (docs/configuration.md)."""
    from ..core import config

    return bool(config.get("autotune_enabled", True))


def shape_class(n_rows: int, k_side: int, d: int, dtype: Any, fast: bool) -> str:
    """Bucketed table key: rows/k-side round UP to the next power of two
    (one measurement covers the whole bucket — tile shapes inside a bucket
    share a winner), the feature depth stays exact (d decides how many
    full-depth blocks fit VMEM, the quantity being tuned)."""
    import numpy as np

    def _bucket(v: int) -> int:
        v = max(1, int(v))
        return 1 << (v - 1).bit_length()

    mode = "fast" if fast else "full"
    return f"r{_bucket(n_rows)}:k{_bucket(k_side)}:d{int(d)}:{np.dtype(dtype).name}:{mode}"


def table_path() -> Optional[str]:
    """Where the measured table persists: beside the XLA compile cache.
    None (cache dir unset) = in-memory only for this process."""
    from ..core import config

    cache_dir = config.get("compilation_cache_dir")
    if not cache_dir:
        return None
    return os.path.join(str(cache_dir), _TABLE_BASENAME)


def _count(name: str, key: str) -> None:
    # guarded-by: _LOCK (callers hold it)
    _STATS[key] += 1
    if telemetry.enabled():  # traced-ok: autotune.* counters tick at trace time by design — lookup runs while tracing the assignment programs, one tick per planned program (docs/observability.md)
        telemetry.registry().inc(name)  # traced-ok: see line above (deliberate trace-time tick)


def _load_table_locked() -> Dict[str, Any]:
    """Lazy-load the persisted table ONCE per process; every failure mode
    (unreadable, malformed JSON, wrong shape, stale version) degrades to an
    empty table — the heuristic keeps planning, a fit never fails here."""
    global _TABLE
    if _TABLE is not None:
        return _TABLE
    entries: Dict[str, Any] = {}
    path = table_path()
    if path is not None and os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                raw = json.load(f)
            if (
                isinstance(raw, dict)
                and raw.get("version") == _TABLE_VERSION
                and isinstance(raw.get("entries"), dict)
            ):
                for key, val in raw["entries"].items():
                    if (
                        isinstance(val, (list, tuple))
                        and len(val) == 2
                        and all(isinstance(v, int) and v > 0 for v in val)
                    ):
                        entries[str(key)] = [int(val[0]), int(val[1])]
                    else:
                        _count("autotune.table_errors", "table_errors")
            else:
                _count("autotune.table_errors", "table_errors")
        except (OSError, ValueError):
            _count("autotune.table_errors", "table_errors")
    _TABLE = entries
    return _TABLE


def _persist_locked() -> None:
    """Atomic write-through (tmp + os.replace — the numcheck.write_report
    discipline); persistence failure is silent: the in-memory table still
    serves this process."""
    path = table_path()
    if path is None or _TABLE is None:
        return
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": _TABLE_VERSION, "entries": _TABLE}, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:  # pragma: no cover - persistence is best-effort
        try:
            os.unlink(tmp)
        except OSError:
            pass


def lookup(
    n_rows: int, k_side: int, d: int, dtype: Any, fast: bool
) -> Optional[Tuple[int, int]]:
    """Persisted winner for this shape class, or None (caller falls back to
    the heuristic). Trace-time safe: pure host dict read + counter tick."""
    if not enabled():
        return None
    key = shape_class(n_rows, k_side, d, dtype, fast)
    with _LOCK:  # held-ok: the table lock exists to serialize exactly this one-shot lazy load of a tiny JSON (+ dict read); no other lock is ever taken under it
        entry = _load_table_locked().get(key)
        if entry is None:
            _count("autotune.misses", "misses")
            return None
        _count("autotune.hits", "hits")
        return int(entry[0]), int(entry[1])


def record(
    n_rows: int, k_side: int, d: int, dtype: Any, fast: bool, plan: Tuple[int, int]
) -> None:
    """Store one measured winner and write the table through to disk."""
    key = shape_class(n_rows, k_side, d, dtype, fast)
    with _LOCK:  # held-ok: the table lock exists to serialize exactly this load+mutate+atomic-rewrite of a tiny JSON; no other lock is ever taken under it
        table = _load_table_locked()
        table[key] = [int(plan[0]), int(plan[1])]
        _persist_locked()


def _candidates(n_rows: int, k_side: int, d: int, dtype: Any, fast: bool) -> List[Tuple[int, int]]:
    """VMEM-feasible candidate tilings for this shape, heuristic pick
    included (the tuner can only match or beat the static planner)."""
    from .distance import _VMEM_BUDGET_BYTES, effective_itemsize, plan_blocks

    itemsize = effective_itemsize(dtype, fast)
    budget = _VMEM_BUDGET_BYTES // max(1, itemsize)
    out: List[Tuple[int, int]] = []
    heuristic = plan_blocks(n_rows, k_side, d, itemsize)
    if heuristic is not None:
        out.append(heuristic)
    for br in _CANDIDATE_BR:
        for bk in _CANDIDATE_BK:
            # same VMEM-fit predicate the static planner budgets against
            if br * d + bk * d + br * bk > budget:
                continue
            cand = (min(br, max(1, n_rows)), min(bk, max(1, k_side)))
            if cand not in out:
                out.append(cand)
    return out


def _default_timer(n_rows: int, k_side: int, d: int, dtype: Any, fast: bool) -> Callable[[int, int], float]:
    """On-device timing closure over the REAL argmin kernel at (a capped
    version of) the call shape: best-of-`config["autotune_repeats"]` wall
    time per candidate, first call per candidate excluded (compile)."""
    import numpy as np

    import jax.numpy as jnp

    from ..core import config
    from .distance import _c_sq, _pl_argmin

    rows = int(min(max(1, n_rows), 4096))
    k = int(min(max(1, k_side), 2048))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((rows, d)), dtype=dtype)
    c = jnp.asarray(rng.standard_normal((k, d)), dtype=dtype)
    c_sq = _c_sq(c)
    try:
        repeats = max(1, int(config.get("autotune_repeats", 3)))
    except (TypeError, ValueError):
        repeats = 3

    def timer(br: int, bk: int) -> float:
        def run() -> None:
            mind, best = _pl_argmin(
                x, c, c_sq, block_rows=min(br, rows), block_k=min(bk, k),
                fast=fast, interpret=False,
            )
            mind.block_until_ready()
            best.block_until_ready()

        run()  # compile + warm
        best_t = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()  # telemetry-ok: the measurement ITSELF — the tuner compares raw candidate wall times; a span here would recursively meter the meter
            run()
            best_t = min(best_t, time.perf_counter() - t0)  # telemetry-ok: see line above
        return best_t

    return timer


def ensure(
    n_rows: int,
    k_side: int,
    d: int,
    dtype: Any,
    fast: bool,
    timer: Optional[Callable[[int, int], float]] = None,
) -> Optional[Tuple[int, int]]:
    """HOST-side measurement entry: make sure a winner exists for this shape
    class, measuring the candidate grid on first contact. Returns the table
    entry (existing or just measured) or None when nothing can be tuned —
    disabled, off-TPU without an injected timer, or no feasible candidates.
    Solver drivers call this eagerly BEFORE their jitted loop, where shapes
    are host-known; the traced planner then hits the table via `lookup`.
    A timer that raises degrades to the heuristic — measurement must never
    fail a fit."""
    if not enabled():
        return None
    key = shape_class(n_rows, k_side, d, dtype, fast)
    with _LOCK:  # held-ok: the table lock exists to serialize exactly this one-shot lazy load of a tiny JSON (+ dict read); no other lock is ever taken under it
        existing = _load_table_locked().get(key)
    if existing is not None:
        return int(existing[0]), int(existing[1])
    if timer is None:
        from .distance import kernel_mode

        if kernel_mode() != "pallas":
            return None  # nothing to measure off-TPU: heuristic is the contract
        timer = _default_timer(n_rows, k_side, d, dtype, fast)
    candidates = _candidates(n_rows, k_side, d, dtype, fast)
    if not candidates:
        return None
    best: Optional[Tuple[int, int]] = None
    best_t = float("inf")
    try:
        # the whole measurement session is one compile-ledger entry: every
        # candidate run compiles its own kernel variant, and the efficiency
        # plane should see the session's wall as compile time, not idle
        with telemetry.compile_event("autotune.measure", key):
            for br, bk in candidates:
                t = float(timer(br, bk))
                if t < best_t:
                    best_t, best = t, (br, bk)
    except Exception:
        # a failed measurement (kernel error on an exotic part, OOM on a
        # candidate) must not fail the fit — the heuristic keeps planning
        with _LOCK:
            _count("autotune.table_errors", "table_errors")
        return None
    if best is None:
        return None
    with _LOCK:  # held-ok: the table lock exists to serialize exactly this load+mutate+atomic-rewrite of a tiny JSON; no other lock is ever taken under it
        _count("autotune.measurements", "measurements")
        table = _load_table_locked()
        table[key] = [int(best[0]), int(best[1])]
        _persist_locked()
    return best


def stats() -> Dict[str, int]:
    """Counter snapshot for the BENCH artifact embed (bench.py)."""
    with _LOCK:
        out = dict(_STATS)
        out["entries"] = len(_TABLE) if _TABLE is not None else 0
        return out


def reset() -> None:
    """Forget the in-memory table cache and counters (test isolation); the
    persisted file is untouched — the next lookup lazily reloads it."""
    global _TABLE
    with _LOCK:
        _TABLE = None
        for k in _STATS:
            _STATS[k] = 0
