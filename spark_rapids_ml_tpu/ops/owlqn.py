#
# OWL-QN (Orthant-Wise Limited-memory Quasi-Newton) — the L1-capable solver
# behind the reference's full logistic penalty surface
# (`LogisticRegressionMG(penalty='l1'/'elasticnet')`, reference
# classification.py:1051-1057; cuML's qn solver implements the same
# Andrew & Gao 2007 algorithm).
#
# TPU-native form: the entire minimization is ONE jitted `lax.while_loop` —
# fixed-size circular (s, y) history buffers, a two-loop recursion unrolled
# with `lax.fori_loop`, orthant projection as masked `where`s, and a
# backtracking line search as an inner while_loop. Every objective/gradient
# evaluation inside is whatever SPMD program the caller's `smooth_f` closes
# over (matmul+psum over the mesh), so the solver itself adds no collectives.
#
from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from .. import telemetry


def check_solver_state(
    solver: str,
    state: dict,
    *,
    scalars: Tuple[str, ...] = ("objective_",),
    arrays: Tuple[str, ...] = ("coef_", "intercept_"),
) -> dict:
    """Host-side divergence guard shared by the quasi-Newton family (OWL-QN,
    the GLM L-BFGS in ops/logistic.py, and the linear solvers that reuse this
    module's recursion).

    These solvers run as ONE jitted while_loop — there is no per-iteration
    host scalar to watch, so the guard piggybacks on the values the model
    layer fetches ANYWAY (final objective + coefficients; zero extra device
    sync). Non-finite state raises `SolverDivergedError` carrying the
    iteration count and whatever parts of the state are still finite as the
    last-good iterate. Returns `state` unchanged so call sites can wrap."""
    import numpy as np

    from ..errors import SolverDivergedError

    bad = []
    for key in scalars:
        if key in state and not np.isfinite(np.asarray(state[key])).all():
            bad.append(key)
    for key in arrays:
        if key in state and not np.isfinite(np.asarray(state[key])).all():
            bad.append(key)
    if not bad:
        return state
    n_iter = int(np.asarray(state.get("n_iter_", 0)))
    last_good = {
        k: np.asarray(v)
        for k, v in state.items()
        if k not in bad and isinstance(v, (np.ndarray, jax.Array))
        and np.isfinite(np.asarray(v)).all()
    }
    telemetry.registry().inc("solver.divergence")
    telemetry.registry().inc(f"{solver}.divergence")
    raise SolverDivergedError(
        solver, n_iter, last_good=last_good, detail=f"non-finite {', '.join(bad)}"
    )


def freeze_when_done(cond_fn: Callable, body_fn: Callable) -> Callable:
    """Make a `lax.while_loop` body vmap-safe for batched hyperparameter
    sweeps: under `vmap` the loop steps until EVERY batch element's cond is
    false, and already-converged elements keep executing the body — their
    iterates would drift past the stopping point and a batched grid solve
    would no longer match N sequential solves. The wrapped body re-evaluates
    this element's own cond and, when it is already false, returns the state
    UNCHANGED (frozen), so extra steps are exact no-ops.

    Unbatched, `body` only ever runs while cond holds, so the guard selects
    the new state every time — results are bit-identical to the bare body."""

    def body(state):
        new = body_fn(state)
        done = ~cond_fn(state)
        return jax.tree.map(lambda old, upd: jnp.where(done, old, upd), state, new)

    return body


def lbfgs_two_loop(pg, S, Y, rho, count, pos, m):
    """Shared L-BFGS two-loop recursion over circular (s, y) history buffers:
    returns the descent direction −H·pg. Used by OWL-QN below and by the
    GLM quasi-Newton solver (ops/logistic.py)."""

    def bwd(i, carry):
        q, alphas = carry
        j = (pos - 1 - i) % m
        valid = i < count
        a = jnp.where(valid, rho[j] * jnp.dot(S[j], q), 0.0)
        q = q - jnp.where(valid, a, 0.0) * Y[j]
        return q, alphas.at[j].set(a)

    q, alphas = jax.lax.fori_loop(0, m, bwd, (pg, jnp.zeros((m,), pg.dtype)))
    newest = (pos - 1) % m
    sy = jnp.dot(S[newest], Y[newest])
    yy = jnp.dot(Y[newest], Y[newest])
    gamma = jnp.where((count > 0) & (yy > 0), sy / jnp.maximum(yy, 1e-30), 1.0)
    r = gamma * q

    def fwd(i, r):
        j = (pos - count + i) % m
        valid = i < count
        beta = jnp.where(valid, rho[j] * jnp.dot(Y[j], r), 0.0)
        return r + jnp.where(valid, alphas[j] - beta, 0.0) * S[j]

    r = jax.lax.fori_loop(0, m, fwd, r)
    return -r


def _owlqn_setup(
    smooth_f: Callable[[jax.Array], jax.Array],
    x0: jax.Array,  # flat [n]
    l1_mask: jax.Array,  # [n]: per-coordinate L1 weight multiplier (0 = unpenalized)
    lam1: float,
    *,
    max_iter: int,
    tol: float,
    memory: int = 10,
    ls_max: int = 25,
    c1: float = 1e-4,
):
    """Build the OWL-QN loop triple ``(cond, body, state0)`` — shared by the
    one-program `owlqn_minimize` path and the host-segmented checkpointing
    driver (`owlqn_minimize_segmented`), so both run the IDENTICAL body."""
    n = x0.shape[0]
    m = memory
    lam = lam1 * l1_mask
    grad_f = jax.grad(smooth_f)
    # per-iteration convergence trace — gated at TRACE time (see ops/logistic.py)
    trace_convergence = telemetry.convergence_trace_enabled()

    def f_total(x):
        return smooth_f(x) + jnp.sum(lam * jnp.abs(x))

    def pseudo_grad(x, g):
        at0 = jnp.where(g + lam < 0, g + lam, jnp.where(g - lam > 0, g - lam, 0.0))
        return jnp.where(x > 0, g + lam, jnp.where(x < 0, g - lam, at0))

    def two_loop(pg, S, Y, rho, count, pos):
        # descent direction for the PSEUDO gradient (shared recursion above)
        return lbfgs_two_loop(pg, S, Y, rho, count, pos, m)

    def line_search(x, d, f0, pg, xi):
        # backtracking with orthant projection: candidate = pi(x + a*d; xi).
        # vmap-safe as written: once `ok` holds, the body recomputes the SAME
        # accepted candidate (a is no longer halved), so batched extra steps
        # are exact no-ops without a freeze_when_done wrapper
        def proj(z):
            return jnp.where(z * xi < 0, 0.0, z)

        def cond(carry):
            a, ok, it = carry[0], carry[3], carry[4]
            return jnp.logical_and(~ok, it < ls_max)

        def body(carry):
            a, _, _, _, it = carry
            xn = proj(x + a * d)
            fn = f_total(xn)
            # Armijo on the projected step against the pseudo-gradient
            ok = fn <= f0 + c1 * jnp.dot(pg, xn - x)
            return jnp.where(ok, a, a * 0.5), xn, fn, ok, it + 1

        a0 = jnp.asarray(1.0, x.dtype)
        _, xn, fn, ok, _ = jax.lax.while_loop(
            cond, body, (a0, x, f0, jnp.asarray(False), jnp.asarray(0, jnp.int32))
        )
        return xn, fn, ok

    def cond(state):
        _, _, _, _, _, _, f_prev, f_cur, it, stalled = state
        rel = jnp.abs(f_prev - f_cur) / jnp.maximum(jnp.abs(f_cur), 1.0)
        return jnp.logical_and(jnp.logical_and(it < max_iter, rel > tol), ~stalled)

    def body(state):
        x, g, S, Y, rho, meta, f_prev, f_cur, it, _ = state
        count, pos = meta
        pg = pseudo_grad(x, g)
        d = two_loop(pg, S, Y, rho, count, pos)
        # orthant-wise sign alignment: drop components fighting the pseudo-grad
        d = jnp.where(d * (-pg) > 0, d, 0.0)
        xi = jnp.where(x != 0, jnp.sign(x), jnp.sign(-pg))
        xn, fn, ok = line_search(x, d, f_cur, pg, xi)
        # pin the gradient to the iterate's dtype: under the bf16 solver
        # contract the loss closes over bf16-input matvecs, and autodiff of
        # a mixed-precision loss must not leak a narrowed dtype into the
        # L-BFGS S/Y memory (docs/performance.md "Mixed-precision solvers")
        gn = grad_f(xn).astype(x0.dtype)
        s = xn - x
        y = gn - g
        sy = jnp.dot(s, y)
        do_update = ok & (sy > 1e-10)
        S = jnp.where(do_update, S.at[pos].set(s), S)
        Y = jnp.where(do_update, Y.at[pos].set(y), Y)
        rho = jnp.where(do_update, rho.at[pos].set(1.0 / jnp.maximum(sy, 1e-30)), rho)
        count = jnp.where(do_update, jnp.minimum(count + 1, m), count)
        pos = jnp.where(do_update, (pos + 1) % m, pos)
        x = jnp.where(ok, xn, x)
        g = jnp.where(ok, gn, g)
        f_new = jnp.where(ok, fn, f_cur)
        if trace_convergence:
            jax.debug.callback(
                partial(telemetry.record_convergence_point, "owlqn"), it, f_new
            )
        return x, g, S, Y, rho, (count, pos), f_cur, f_new, it + 1, ~ok

    g0 = grad_f(x0).astype(x0.dtype)  # same dtype pin as the in-loop gradient
    f0 = f_total(x0)
    state0 = (
        x0, g0,
        jnp.zeros((m, n), x0.dtype), jnp.zeros((m, n), x0.dtype), jnp.zeros((m,), x0.dtype),
        (jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32)),
        jnp.asarray(jnp.inf, x0.dtype), f0, jnp.asarray(0, jnp.int32), jnp.asarray(False),
    )
    return cond, body, state0


def owlqn_minimize(
    smooth_f: Callable[[jax.Array], jax.Array],
    x0: jax.Array,  # flat [n]
    l1_mask: jax.Array,  # [n]: per-coordinate L1 weight multiplier (0 = unpenalized)
    lam1: float,
    *,
    max_iter: int,
    tol: float,
    memory: int = 10,
    ls_max: int = 25,
    c1: float = 1e-4,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Minimize smooth_f(x) + lam1 * sum(l1_mask * |x|).

    Returns (x, objective, n_iter). With lam1=0 this degrades to plain
    two-loop L-BFGS (used as the common path for testing)."""
    cond, body, state0 = _owlqn_setup(
        smooth_f, x0, l1_mask, lam1,
        max_iter=max_iter, tol=tol, memory=memory, ls_max=ls_max, c1=c1,
    )
    x, _, _, _, _, _, _, obj, n_iter, _ = jax.lax.while_loop(
        cond, freeze_when_done(cond, body), state0
    )
    return x, obj, n_iter


def owlqn_minimize_segmented(
    smooth_f: Callable[[jax.Array], jax.Array],
    x0: jax.Array,
    l1_mask: jax.Array,
    lam1: float,
    *,
    max_iter: int,
    tol: float,
    memory: int = 10,
    ls_max: int = 25,
    c1: float = 1e-4,
    ckpt_key: str = "owlqn",
    placement_key=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """`owlqn_minimize` with the one big ``lax.while_loop`` segmented into
    outer HOST segments of ``config["checkpoint_every_iters"]`` inner
    iterations (docs/robustness.md "Elastic recovery"): each segment
    boundary host-fetches the full iterate — (x, L-BFGS (s, y, rho) memory,
    n_iter, line-search state) — into the active `CheckpointStore`, and a
    resumed fit re-enters from the last boundary. The segment body is the
    SAME traced body as the monolithic loop and the boundary round-trip is
    lossless, so a same-mesh resume is bit-identical to an uninterrupted
    segmented run."""
    import numpy as np

    from .. import checkpoint as _ckpt

    cond, body, state0 = _owlqn_setup(
        smooth_f, x0, l1_mask, lam1,
        max_iter=max_iter, tol=tol, memory=memory, ls_max=ls_max, c1=c1,
    )
    state = _ckpt.run_segmented_while(
        cond, body, state0,
        it_of=lambda s: s[8],  # (x, g, S, Y, rho, meta, f_prev, f_cur, IT, stalled)
        every=_ckpt.every_iters() or max_iter,
        store=_ckpt.active_store(),
        key=ckpt_key,
        solver="owlqn",
        placement_key=placement_key,
        max_iter=max_iter,
        portable_of=lambda s: {"x": np.asarray(s[0])},
    )
    x, _, _, _, _, _, _, obj, n_iter, _ = state
    return x, obj, n_iter
