#
# The ONE tiled distance / argmin / top-k core shared by the whole neighbor
# family (docs/performance.md "Tiled distance core").
#
# Every neighbor-shaped estimator reduces to the same inner loop: a
# `[rows_tile, d] x [k_side, d]` distance contraction followed by a running
# reduction (argmin for KMeans assignment, top-k for kNN/UMAP/CAGRA, an
# eps-threshold count for DBSCAN). Before this module each of
# kmeans/knn/dbscan/umap/cagra hand-rolled that loop — and the hand-rolled
# KMeans form fell ~2.2x going from 400k to 1M rows (BENCH_r01 ~226k -> r03
# ~100k rows/sec/chip at k=1000): at k=1000 the un-k-tiled `[batch, k]`
# distance block plus its one-hot twin stop fitting close to the compute and
# the MXU starves. This module is the single owner of that loop:
#
#   * a Pallas-TPU kernel path: the distance block is computed in
#     `[block_rows, d] x [block_k, d]` VMEM tiles (the grid pipeline
#     double-buffers the HBM->VMEM tile fetches), with the argmin merged
#     IN-KERNEL across k tiles — a `[rows_tile, k]` matrix never exists in
#     HBM, which is exactly the r01->r03 cliff;
#   * a bit-compatible pure-jnp fallback: the same formulas as one XLA
#     program (what CPU CI and older jaxlibs run); parity between the two is
#     pinned by tests/test_distance.py (rtol 1e-9 f64, exact assignments f32)
#     across tile boundaries, ragged tails, weights, and the `fast`
#     precision mode;
#   * the backend probe (`kernel_mode`) that picks between them once per
#     process: Pallas only on a TPU backend whose jaxlib passes a tiny
#     end-to-end kernel self-test; `SRML_DISTANCE_KERNEL` overrides
#     (`pallas` | `jnp` | `interpret` — the interpret form runs the REAL
#     kernels through the Pallas interpreter, which is how CPU CI exercises
#     kernel code paths at all).
#
# The ci/analysis `raw-distance` rule forbids re-growing private copies:
# `jnp.argmin` / `lax.top_k` over a locally-built `x @ c.T`-shaped operand
# anywhere in the framework outside this file is a finding
# (`# distance-ok: <reason>` waives a deliberate exception).
#
# `distance.*` counters (docs/observability.md) count PROGRAM TRACES, not
# executions — they increment at trace time by design, so "a KMeans fit
# compiles ONE distance program across its iterations" is a testable
# invariant instead of folklore.
#
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import telemetry

# Outer row-tile default when config["distance_tile_rows"] is missing or
# invalid (the config default matches this).
_DEFAULT_TILE_ROWS = 4096

# VMEM budget the kernel block planner fits (x block + k-side block + the
# [block_rows, block_k] distance block, each double-buffered by the grid
# pipeline). Half of a v5e core's ~16 MB, leaving the other half for the
# pipeline's second buffers and compiler scratch.
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024

_MODE: Optional[str] = None  # kernel_mode() cache: "pallas" | "interpret" | "jnp"


# ----------------------------------------------------------- tile planning --


def tile_rows() -> int:
    """Outer row-tile size shared by every consumer's query/row scan —
    `config["distance_tile_rows"]` (docs/configuration.md)."""
    from ..core import config

    try:
        v = int(config.get("distance_tile_rows", _DEFAULT_TILE_ROWS))
    except (TypeError, ValueError):
        return _DEFAULT_TILE_ROWS
    return v if v > 0 else _DEFAULT_TILE_ROWS


def plan_blocks(
    n_rows: int, k_side: int, d: int, itemsize: int = 4
) -> Optional[Tuple[int, int]]:
    """Kernel-internal (block_rows, block_k) so one x block [br, d], one
    k-side block [bk, d] and the [br, bk] distance block fit the VMEM
    budget. Returns None when even the floor blocks don't fit (enormous d)
    — callers fall back to the jnp path then."""
    budget = _VMEM_BUDGET_BYTES // max(1, itemsize)
    br, bk = 512, 512
    while br * d + bk * d + br * bk > budget and (br > 8 or bk > 128):
        if bk > 128:
            bk //= 2
        elif br > 8:
            br //= 2
    if br * d + bk * d + br * bk > budget:
        return None
    return min(br, max(1, n_rows)), min(bk, max(1, k_side))


def effective_itemsize(dtype, fast: bool) -> int:
    """Bytes per element the kernel blocks ACTUALLY hold on-chip: the fast
    path casts its VMEM tiles to bf16 (2 bytes), so planning with the input
    dtype's itemsize (4 for f32) would budget half the tile the core can
    hold. Pinned by tests/test_autotune.py."""
    size = jnp.dtype(dtype).itemsize
    return min(size, 2) if fast else size


def _plan(
    n_rows: int, k_side: int, d: int, dtype, fast: bool
) -> Optional[Tuple[int, int]]:
    """Block plan for one kernel dispatch: the measured autotuner's
    persisted winner when one exists for this (shape-class, dtype, fast)
    — else the static half-VMEM heuristic over the EFFECTIVE on-chip
    itemsize. None still means "fall back to the jnp path" (enormous d)."""
    heuristic = plan_blocks(n_rows, k_side, d, effective_itemsize(dtype, fast))
    if heuristic is None:
        return None
    from . import autotune

    tuned = autotune.lookup(n_rows, k_side, d, dtype, fast)
    return tuned if tuned is not None else heuristic


# ---------------------------------------------------------- backend probe ---


def kernel_mode() -> str:
    """Which inner-loop implementation this process runs: "pallas" (TPU
    backend, kernels verified by a tiny self-test), "interpret" (the real
    kernels through the Pallas interpreter — CI parity testing), or "jnp"
    (the bit-compatible fallback; CPU and older jaxlibs). Resolved once;
    `SRML_DISTANCE_KERNEL` overrides."""
    global _MODE
    if _MODE is None:
        _MODE = _probe()
        if telemetry.enabled():  # traced-ok: one-shot probe-result gauge — resolves once per process, trace-time reads return the cached string
            telemetry.registry().gauge(  # traced-ok: same one-shot probe gauge (see line above)
                "distance.kernel_pallas", 1.0 if _MODE != "jnp" else 0.0
            )
    return _MODE


def _probe() -> str:
    env = os.environ.get("SRML_DISTANCE_KERNEL", "").strip().lower()
    if env in ("jnp", "fallback", "off"):
        return "jnp"
    if env == "interpret":
        return "interpret"
    if env == "pallas":
        # explicit override really FORCES the kernel path: no self-test
        # fallback — an operator debugging a kernel failure needs it to
        # surface at the kernel call, not be silently probed away
        return "pallas"
    if jax.default_backend() != "tpu":
        return "jnp"
    try:
        import numpy as np

        x = jnp.asarray(np.arange(64, dtype=np.float32).reshape(8, 8) / 64.0)
        c = jnp.asarray(np.arange(32, dtype=np.float32).reshape(4, 8) / 32.0)
        mind, best = _pl_argmin(x, c, _c_sq(c), block_rows=8, block_k=4,
                                fast=False, interpret=False)
        ref_d2 = _c_sq(c)[None, :] - 2.0 * (x @ c.T)
        ok = np.allclose(np.asarray(mind), np.asarray(jnp.min(ref_d2, 1)), rtol=1e-5)
        ok &= bool(np.all(np.asarray(best) == np.asarray(jnp.argmin(ref_d2, 1))))
        return "pallas" if ok else "jnp"
    except Exception:
        # older jaxlib / no Mosaic lowering: the fallback is the contract
        return "jnp"


def _use_kernel() -> bool:
    return kernel_mode() != "jnp"


def _interpret() -> bool:
    return kernel_mode() == "interpret"


# --------------------------------------------------------------- helpers ----


def row_sq(x: jax.Array) -> jax.Array:
    return jnp.sum(x * x, axis=1)


def _c_sq(c: jax.Array) -> jax.Array:
    return jnp.sum(c * c, axis=1)


def _mm(a: jax.Array, b: jax.Array, fast: bool) -> jax.Array:
    """Matmul at the neighbor-family loop precision. `fast` = one-pass bf16
    on the MXU with f32 accumulation (explicit casts, so CPU tests see the
    same rounding). Measured at the protocol shape (1M x 3k, k=1000, v5e):
    in-loop bf16 drops 331 -> 208 ms/iter while the TRUE inertia (recomputed
    at 3-pass-bf16 "f32" precision with the final centers) agrees to 7e-6
    relative — assignment flips only for near-tied rows, which contribute
    equally either way."""
    if fast:
        return jax.lax.dot(
            a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32,
        ).astype(a.dtype)
    return a @ b


def _note(name: str) -> None:
    """Trace-time program counter (see module docstring): one tick per
    compiled distance program, NOT per execution."""
    if telemetry.enabled():  # traced-ok: distance.* counters count program TRACES by design — one tick per compile is the invariant tests/test_distance.py pins
        telemetry.registry().inc(name)  # traced-ok: see line above (deliberate trace-time tick, docs/observability.md "Tiled distance core")


# ---------------------------------------------------------- Pallas kernels --
#
# Kernels never tile the feature axis: blocks are [block_rows, d] and
# [block_k, d] with full-depth dots, so each distance entry is ONE dot
# reduction — bitwise identical to the fallback's single big matmul slice-
# for-slice (the parity suite leans on this). The block planner refuses
# (-> jnp fallback) when full-depth blocks cannot fit VMEM.


def _pl_argmin(
    x: jax.Array,  # [B, d] row tile
    c_pad: jax.Array,  # [kp, d] centers, padded to a block_k multiple
    c_sq_pad: jax.Array,  # [kp] (+inf on padding rows)
    *,
    block_rows: int,
    block_k: int,
    fast: bool,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Fused distance + running argmin: returns (min d2 [B] WITHOUT the
    ||x||^2 term, argmin index [B] int32). Grid = (row blocks, k blocks)
    with the k axis innermost: each step computes one [br, bk] distance
    block in VMEM and merges it into the carried per-row minimum — the full
    [B, k] matrix never exists."""
    from jax.experimental import pallas as pl

    B, d = x.shape
    kp = c_pad.shape[0]
    n_rb = B // block_rows
    n_kb = kp // block_k
    dtype = x.dtype

    def kernel(x_ref, c_ref, csq_ref, mind_ref, best_ref):
        kb = pl.program_id(1)
        xb = x_ref[...]
        cb = c_ref[...]
        if fast:
            xc = jnp.dot(
                xb.astype(jnp.bfloat16), cb.astype(jnp.bfloat16).T,
                preferred_element_type=jnp.float32,
            ).astype(dtype)
        else:
            xc = jnp.dot(xb, cb.T)
        d2 = csq_ref[...] - 2.0 * xc  # [br, bk]
        blk_min = jnp.min(d2, axis=1, keepdims=True)
        blk_arg = (
            jnp.argmin(d2, axis=1).astype(jnp.int32)[:, None] + kb * block_k
        )

        @pl.when(kb == 0)
        def _init():
            mind_ref[...] = blk_min
            best_ref[...] = blk_arg

        @pl.when(kb > 0)
        def _merge():
            cur = mind_ref[...]
            take = blk_min < cur  # strict: first-k-block wins ties, like argmin
            mind_ref[...] = jnp.where(take, blk_min, cur)
            best_ref[...] = jnp.where(take, blk_arg, best_ref[...])

    mind, best = pl.pallas_call(
        kernel,
        grid=(n_rb, n_kb),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda r, k: (r, 0)),
            pl.BlockSpec((block_k, d), lambda r, k: (k, 0)),
            pl.BlockSpec((1, block_k), lambda r, k: (0, k)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda r, k: (r, 0)),
            pl.BlockSpec((block_rows, 1), lambda r, k: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, 1), dtype),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
        ],
        interpret=interpret,
    )(x, c_pad, c_sq_pad[None, :])
    return mind[:, 0], best[:, 0]


def _pl_accumulate(
    x: jax.Array,  # [B, d]
    w: jax.Array,  # [B]
    assign: jax.Array,  # [B] int32
    kp: int,  # padded center count (block_k multiple)
    *,
    block_rows: int,
    block_k: int,
    fast: bool,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Weighted one-hot accumulation: (sums [kp, d], counts [kp]). Grid =
    (k blocks, row blocks) with rows innermost: each step builds one
    [br, bk] one-hot block and accumulates its [bk, d] contribution — the
    full [B, k] one-hot matrix never exists."""
    from jax.experimental import pallas as pl

    B, d = x.shape
    n_rb = B // block_rows
    n_kb = kp // block_k
    dtype = x.dtype

    def kernel(x_ref, w_ref, a_ref, sums_ref, counts_ref):
        kb = pl.program_id(0)
        rb = pl.program_id(1)
        xb = x_ref[...]
        wb = w_ref[...]  # [br, 1]
        ab = a_ref[...]  # [br, 1]
        ids = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        oh = jnp.where(ab == ids, wb, jnp.zeros((), dtype))  # [br, bk]
        if fast:
            contrib = jnp.dot(
                oh.astype(jnp.bfloat16).T, xb.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            ).astype(dtype)
        else:
            contrib = jnp.dot(oh.T, xb)

        @pl.when(rb == 0)
        def _init():
            sums_ref[...] = contrib
            counts_ref[...] = jnp.sum(oh, axis=0)[:, None]

        @pl.when(rb > 0)
        def _acc():
            sums_ref[...] += contrib
            counts_ref[...] += jnp.sum(oh, axis=0)[:, None]

    sums, counts = pl.pallas_call(
        kernel,
        grid=(n_kb, n_rb),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda k, r: (r, 0)),
            pl.BlockSpec((block_rows, 1), lambda k, r: (r, 0)),
            pl.BlockSpec((block_rows, 1), lambda k, r: (r, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_k, d), lambda k, r: (k, 0)),
            pl.BlockSpec((block_k, 1), lambda k, r: (k, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, d), dtype),
            jax.ShapeDtypeStruct((kp, 1), dtype),
        ],
        interpret=interpret,
    )(x, w[:, None], assign[:, None].astype(jnp.int32))
    return sums, counts[:, 0]


def _pl_d2_block(
    q: jax.Array,  # [B, d] query/row tile
    xt: jax.Array,  # [bk_total, d] item tile (fully VMEM-resident per block)
    xt_sq: jax.Array,  # [bk_total]
    *,
    block_rows: int,
    fast: bool,
    interpret: bool,
) -> jax.Array:
    """One [B, k_tile] distance block (WITHOUT the ||q||^2 term): the inner
    matmul of the top-k merge loop. Grid over row blocks only — the item
    tile is sized by the caller to fit VMEM whole."""
    from jax.experimental import pallas as pl

    B, d = q.shape
    kt = xt.shape[0]
    n_rb = B // block_rows
    dtype = q.dtype

    def kernel(q_ref, x_ref, xsq_ref, out_ref):
        qb = q_ref[...]
        xb = x_ref[...]
        if fast:
            dots = jnp.dot(
                qb.astype(jnp.bfloat16), xb.astype(jnp.bfloat16).T,
                preferred_element_type=jnp.float32,
            ).astype(dtype)
        else:
            dots = jnp.dot(qb, xb.T)
        out_ref[...] = xsq_ref[...] - 2.0 * dots

    return pl.pallas_call(
        kernel,
        grid=(n_rb,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda r: (r, 0)),
            pl.BlockSpec((kt, d), lambda r: (0, 0)),
            pl.BlockSpec((1, kt), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, kt), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((B, kt), dtype),
        interpret=interpret,
    )(q, xt, xt_sq[None, :])


def _pad_rows_multiple(a: jax.Array, mult: int) -> Tuple[jax.Array, int]:
    n = a.shape[0]
    pad = (-n) % mult
    if pad:
        a = jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
    return a, n


# ----------------------------------------------------- fused assign (KMeans) --


def assign_argmin(
    xb: jax.Array,  # [B, d] one row tile
    centers: jax.Array,  # [k, d]
    *,
    fast: bool = False,
    block_rows: Optional[int] = None,
    block_k: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Nearest-center reduction for one row tile: (min d2 [B] WITHOUT the
    ||x||^2 term, assignment [B] int32). The k-tiled kernel and the one-shot
    fallback share the exact `c_sq - 2 x.c^T` formula; first-index argmin
    ties are preserved across k blocks by the kernel's strict-< merge."""
    k, d = centers.shape
    c_sq = _c_sq(centers)
    plan = (
        _plan(xb.shape[0], k, d, xb.dtype, fast)
        if _use_kernel()
        else None
    )
    if plan is None:
        d2 = c_sq[None, :] - 2.0 * _mm(xb, centers.T, fast)
        return jnp.min(d2, axis=1), jnp.argmin(d2, axis=1).astype(jnp.int32)
    br, bk = block_rows or plan[0], block_k or plan[1]
    xp, n = _pad_rows_multiple(xb, br)
    cp, _ = _pad_rows_multiple(centers, bk)
    csq_p = jnp.pad(c_sq, (0, cp.shape[0] - k), constant_values=jnp.inf)
    mind, best = _pl_argmin(
        xp, cp, csq_p, block_rows=br, block_k=min(bk, cp.shape[0]),
        fast=fast, interpret=_interpret(),
    )
    return mind[:n], best[:n]


def assign_accumulate(
    xb: jax.Array,  # [B, d] one row tile
    wb: jax.Array,  # [B] weights (0 on padding rows — they contribute nothing)
    centers: jax.Array,  # [k, d]
    *,
    fast: bool = False,
    block_rows: Optional[int] = None,
    block_k: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One row tile's fused Lloyd contribution: (sums [k, d], counts [k],
    inertia scalar). THE kmeans inner loop: assignment (k-tiled argmin) plus
    the weighted one-hot accumulation, never materializing [B, k] on the
    kernel path."""
    k, d = centers.shape
    plan = (
        _plan(xb.shape[0], k, d, xb.dtype, fast)
        if _use_kernel()
        else None
    )
    if plan is None:
        c_sq = _c_sq(centers)
        d2 = c_sq[None, :] - 2.0 * _mm(xb, centers.T, fast)
        assign = jnp.argmin(d2, axis=1)
        min_d2 = jnp.min(d2, axis=1) + row_sq(xb)
        oh = jax.nn.one_hot(assign, k, dtype=xb.dtype) * wb[:, None]
        return (
            _mm(oh.T, xb, fast),
            jnp.sum(oh, axis=0),
            jnp.sum(jnp.maximum(min_d2, 0.0) * wb),
        )
    br, bk = block_rows or plan[0], block_k or plan[1]
    xp, n = _pad_rows_multiple(xb, br)
    wp, _ = _pad_rows_multiple(wb, br)
    cp, _ = _pad_rows_multiple(centers, bk)
    bk = min(bk, cp.shape[0])
    csq_p = jnp.pad(_c_sq(centers), (0, cp.shape[0] - k), constant_values=jnp.inf)
    mind, best = _pl_argmin(
        xp, cp, csq_p, block_rows=br, block_k=bk, fast=fast,
        interpret=_interpret(),
    )
    sums_p, counts_p = _pl_accumulate(
        xp, wp, best, cp.shape[0], block_rows=br, block_k=bk, fast=fast,
        interpret=_interpret(),
    )
    min_d2 = mind[:n] + row_sq(xb)
    inertia = jnp.sum(jnp.maximum(min_d2, 0.0) * wb)
    return sums_p[:k], counts_p[:k], inertia


def tile_assign_accumulate(
    Xl: jax.Array, wl: jax.Array, centers: jax.Array, batch_rows: int,
    fast: bool = False, spmd: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scan one device's rows in tiles; returns (sums [k,d], counts [k],
    inertia) — the whole-shard Lloyd accumulation every KMeans path shares.

    Tiles are cut with `dynamic_slice` DIRECTLY out of Xl inside a fori_loop,
    and the ragged tail is one extra direct step. Neither `jnp.pad` of the
    shard nor a `lax.scan` over a reshaped view is safe here: both make XLA
    materialize a second X-sized buffer (11 GiB at the 1M x 3k benchmark
    shape, measured) — the slice-in-loop form keeps X single-buffered."""
    _note("distance.assign_programs")
    nl, d = Xl.shape
    k = centers.shape[0]

    def step(carry, xw):
        sums, counts, inertia = carry
        xb, wb = xw
        s, c, i = assign_accumulate(xb, wb, centers, fast=fast)
        return (sums + s, counts + c, inertia + i), None

    init = (
        jnp.zeros((k, d), Xl.dtype),
        jnp.zeros((k,), Xl.dtype),
        jnp.zeros((), Xl.dtype),
    )
    if spmd:
        # carry must be typed as varying over the mesh axis to match the
        # per-shard accumulators (JAX shard_map vma typing); the meshless
        # 1-device program has no axis to cast over
        from ..parallel.mesh import ROWS_AXIS, pcast_varying

        init = jax.tree.map(lambda t: pcast_varying(t, ROWS_AXIS), init)
    batch_rows = min(batch_rows, nl)
    n_full = (nl // batch_rows) * batch_rows

    def tile_body(i, carry):
        xb = jax.lax.dynamic_slice_in_dim(Xl, i * batch_rows, batch_rows, 0)
        wb = jax.lax.dynamic_slice_in_dim(wl, i * batch_rows, batch_rows, 0)
        return step(carry, (xb, wb))[0]

    carry = jax.lax.fori_loop(0, n_full // batch_rows, tile_body, init)
    if nl - n_full:
        carry, _ = step(carry, (Xl[n_full:], wl[n_full:]))
    return carry


# ---------------------------------------------------- row-tiled assignment --


def argmin_assign(
    X: jax.Array, centers: jax.Array, *, batch_rows: Optional[int] = None,
    fast: bool = False,
) -> jax.Array:
    """Nearest-center assignment over ALL rows, row-tiled through the core:
    int32 [n]. The predict-side entry (kmeans transform, k-means|| candidate
    weighting, IVF/CAGRA anchor assignment, the serving plane's bf16 query
    path) — an admission-approved fit must not OOM at predict because the
    full [n, k] distance matrix materialized (docs/performance.md "Tiled
    distance core"). `fast` runs the distance matmuls in the parity-tested
    fast-bf16 mode (docs/serving.md "bf16 serving"). Tiles are clamped back
    at the ragged tail (overlap rows recompute the same assignment — writes
    are idempotent), so no padded copy of X is ever made."""
    _note("distance.argmin_programs")
    n = X.shape[0]
    tr = min(batch_rows or tile_rows(), max(n, 1))
    if n <= tr:
        return assign_argmin(X, centers, fast=fast)[1]
    n_tiles = -(-n // tr)

    def body(i, out):
        s0 = jnp.minimum(i * tr, n - tr)
        xb = jax.lax.dynamic_slice_in_dim(X, s0, tr, 0)
        a = assign_argmin(xb, centers, fast=fast)[1]
        return jax.lax.dynamic_update_slice(out, a, (s0,))

    return jax.lax.fori_loop(0, n_tiles, body, jnp.zeros((n,), jnp.int32))


# ----------------------------------------------------------- top-k (kNN) ----


def topk_tile(
    q: jax.Array,  # [B, d] one query tile
    items: jax.Array,  # [n, d]
    valid: Optional[jax.Array],  # [n] bool, or None for all-valid
    kk: int,
    *,
    item_sq: Optional[jax.Array] = None,
    fast: bool = False,
    k_tile: Optional[int] = None,
    block_rows: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Running top-kk of one query tile against ALL items: (d2 [B, kk]
    WITHOUT the ||q||^2 term, item index [B, kk] int32), ascending by
    distance with `jax.lax.top_k` tie semantics (lower index first — pinned
    vs a full-matrix top_k by tests/test_distance.py).

    The item axis is scanned in `k_tile` blocks with the [B, kk] best list
    as the loop carry, so the [B, n] distance matrix never materializes; the
    last block is clamped back and its overlap columns masked +inf (already
    merged). On the kernel path each block's distances come from the Pallas
    d2-block kernel; the fallback runs the same merge with a plain matmul —
    identical selection logic, bit-compatible results."""
    _note("distance.topk_programs")
    n, d = items.shape
    kk = min(kk, n)
    if item_sq is None:
        item_sq = row_sq(items)
    plan = _plan(q.shape[0], n, d, q.dtype, fast) if _use_kernel() else None
    use_kernel = plan is not None
    if k_tile is None:
        # fallback: one block (today's one-matmul shape, right for CPU);
        # kernel: VMEM-sized item blocks
        k_tile = max(plan[1], 128) if use_kernel else n
    kt = min(k_tile, n)
    big = jnp.asarray(jnp.inf, items.dtype)

    def block_d2(xt, xt_sq):
        if use_kernel:
            br = block_rows or plan[0]
            qp, nq = _pad_rows_multiple(q, br)
            out = _pl_d2_block(
                qp, xt, xt_sq, block_rows=br, fast=fast, interpret=_interpret()
            )
            return out[:nq]
        return xt_sq[None, :] - 2.0 * _mm(q, xt.T, fast)

    def masked_block(start):
        s0 = jnp.minimum(start, n - kt)
        xt = jax.lax.dynamic_slice_in_dim(items, s0, kt, 0)
        sq = jax.lax.dynamic_slice_in_dim(item_sq, s0, kt, 0)
        ids = s0 + jnp.arange(kt, dtype=jnp.int32)
        d2 = block_d2(xt, sq)
        keep = ids >= start  # clamp-back overlap: already merged columns
        if valid is not None:
            keep = keep & jax.lax.dynamic_slice_in_dim(valid, s0, kt, 0)
        return jnp.where(keep[None, :], d2, big), ids

    if kt >= n:  # single block: exactly the one-shot top_k
        d2, ids = masked_block(jnp.int32(0))
        neg_d, pos = jax.lax.top_k(-d2, kk)
        return -neg_d, jnp.take_along_axis(
            jnp.broadcast_to(ids[None, :], d2.shape), pos, axis=1
        )

    n_tiles = -(-n // kt)

    def body(i, carry):
        best_d2, best_i = carry
        d2, ids = masked_block(i * kt)
        cat_d = jnp.concatenate([best_d2, d2], axis=1)
        cat_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(ids[None, :], d2.shape)], axis=1
        )
        neg_d, pos = jax.lax.top_k(-cat_d, kk)
        return -neg_d, jnp.take_along_axis(cat_i, pos, axis=1)

    init = (
        jnp.full((q.shape[0], kk), jnp.inf, items.dtype),
        jnp.zeros((q.shape[0], kk), jnp.int32),
    )
    return jax.lax.fori_loop(0, n_tiles, body, init)


def tile_topk(
    items: jax.Array,  # [n_loc, d]
    queries: jax.Array,  # [nq, d]
    valid: jax.Array,  # [n_loc] bool (False on padding)
    k: int,
    batch_queries: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact top-k of every query against one device's items: (dist [nq, k]
    SQUARED incl. the ||q||^2 term, idx [nq, k] local), scanning query tiles
    of `batch_queries` rows (default `config["distance_tile_rows"]`).
    Padding items get +inf distance; k past the shard's row count is padded
    with +inf so a global merge never selects it."""
    n_loc, d = items.shape
    nq = queries.shape[0]
    bq = batch_queries or tile_rows()
    n_tiles = max(1, -(-nq // bq))
    pad = n_tiles * bq - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    item_sq = row_sq(items)
    kk = min(k, n_loc)

    def one_tile(q):
        d2, idx = topk_tile(q, items, valid, kk, item_sq=item_sq)
        d_out = d2 + row_sq(q)[:, None]
        if kk < k:
            d_out = jnp.pad(d_out, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
            idx = jnp.pad(idx, ((0, 0), (0, k - kk)))
        return d_out, idx

    qt = qp.reshape(n_tiles, bq, d)
    dists, idxs = jax.lax.map(one_tile, qt)
    return dists.reshape(-1, k)[:nq], idxs.reshape(-1, k)[:nq]


# ------------------------------------------------------ distance tiles ------


def pairwise_d2(q: jax.Array, x: jax.Array, metric: str = "euclidean") -> jax.Array:
    """One dense distance tile [tq, n]: squared euclidean, or cosine
    distance. The tile IS the intended output here (DBSCAN's threshold
    passes, running-min merges), so it stays a single MXU contraction — the
    Pallas path exists for the fused argmin/top-k reductions above, where
    NOT materializing the tile is the win.

    Inputs are pre-normalized for cosine by the caller, so cosine distance
    is 1 - q.x^T — both metrics ride the MXU. For "precomputed" the rows ARE
    distances already (DBSCAN hands each pass the matching column slice of
    the user's distance matrix), so the tile is just `q` — no compute."""
    _note("distance.pairwise_programs")
    if metric == "precomputed":
        return q
    if metric == "cosine":
        return 1.0 - q @ x.T
    return row_sq(q)[:, None] - 2.0 * (q @ x.T) + row_sq(x)[None, :]


def min_d2_update(x: jax.Array, cand: jax.Array, min_d2: jax.Array) -> jax.Array:
    """min(min_d2, min distance^2 to the NEW candidate block) — the k-means||
    seeding round's incremental matmul (one tile, running min)."""
    d2 = pairwise_d2(x, cand)
    return jnp.minimum(min_d2, jnp.maximum(jnp.min(d2, axis=1), 0.0))


def score_candidates(
    q_rows: jax.Array, cand: jax.Array, x: jax.Array, x_sq: jax.Array,
    fast: bool = False,
) -> jax.Array:
    """d2[t, c] = ||q_rows[t] - x[cand[t, c]]||^2 (squared L2, >= 0); the
    [T, C, d] gather feeds one batched einsum (the MXU side of a graph-ANN
    round). fast=True runs the einsum with bf16 inputs and f32 accumulation
    (the KMeans fast-path policy): CAGRA's BUILD only uses these distances
    to RANK candidate edges, so the ~1e-3 relative rounding is absorbed by
    the descent's redundancy, while the one-pass MXU einsum runs ~2.6x the
    f32-highest rate on a v5e. Searches keep exact f32 scoring (their
    distances are returned to the user)."""
    xc = x[cand]  # [T, C, d]
    if fast:
        dots = jnp.einsum(
            "td,tcd->tc",
            q_rows.astype(jnp.bfloat16),
            xc.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    else:
        dots = jnp.einsum("td,tcd->tc", q_rows, xc)
    d2 = row_sq(q_rows)[:, None] + x_sq[cand] - 2.0 * dots
    return jnp.maximum(d2, 0.0)


def batched_self_topk(
    xb: jax.Array, ids_b: jax.Array, *, kk: int
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN inside padded buckets: xb [Cb, L, d], ids_b [Cb, L] global
    ids (-1 pad). One batched [Cb, L, L] distance matmul on the MXU + top-k
    — CAGRA's clustered brute-force seeding unit. Returns (d2 [Cb, L, kk],
    neighbor ids [Cb, L, kk])."""
    big = jnp.asarray(jnp.inf, jnp.float32)
    sq = jnp.sum(xb * xb, axis=2)  # [Cb, L]
    G = jnp.einsum("cld,cmd->clm", xb, xb)
    d2 = sq[:, :, None] + sq[:, None, :] - 2.0 * G
    valid = ids_b >= 0
    mask = valid[:, None, :] & valid[:, :, None]
    eye = jnp.eye(xb.shape[1], dtype=bool)[None]
    d2 = jnp.where(mask & ~eye, jnp.maximum(d2, 0.0), big)
    nd2, pos = jax.lax.top_k(-d2, kk)
    nid = jnp.take_along_axis(
        jnp.broadcast_to(ids_b[:, None, :], d2.shape), pos, axis=2
    )
    return -nd2, nid
