"""CAGRA-class graph ANN, TPU-native.

The reference exposes cuVS CAGRA through ApproximateNearestNeighbors
(algorithm="cagra", reference knn.py:902-935, 1264-1298, 1452-1481): a
fixed-degree kNN graph is built over the item vectors (build_algo
"ivf_pq" | "nn_descent") and queried with a greedy best-first search
(itopk_size, search_width, max_iterations, num_random_samplings).

This module re-designs both phases for the TPU instead of wrapping a CUDA
graph library:

* **Build = clustered brute-force seeding + NN-descent refinement, all as a
  handful of big device programs.** Seeding (`build_algo="ivf_pq"`, the TPU
  analog of cuVS's IVF-based seeding): several repetitions partition the rows
  by nearest random anchor (one assignment matmul), lay every partition out
  as a padded bucket, and run EXACT kNN inside each bucket — a [C, L, L]
  batched distance matmul that lands squarely on the MXU; each row appears in
  exactly one bucket per repetition, so the per-rep results merge into the
  [n, K_int] graph with one conflict-free scatter. Refinement (both
  build_algos) is NN-descent: each round is ONE jitted program that
  fori-loops over row tiles; a tile expands the FULL adjacency lists of its
  closest / random / reverse neighbors, scores the candidates with an einsum
  over the gathered vectors, and merges sort-dedup'd. Reverse edges are
  rebuilt between rounds by one device-wide sort — no host round trips and no
  dynamic shapes anywhere. `build_algo="nn_descent"` skips the cluster
  seeding (random init, more descent rounds).
* **Search = batched greedy expansion, one program per query tile.** Each
  query keeps an itopk-wide candidate list; every iteration expands the best
  `search_width` unexpanded nodes, gathers their adjacency rows, scores the
  new frontier (einsum over gathered vectors), and merges sort-dedup'd — the
  whole search for a 4096-query tile is a single fori_loop'd XLA program.

Distances are squared L2 ("sqeuclidean" — the only metric the reference
accepts for cagra, knn.py:1267).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["build_cagra", "cagra_search"]

from .distance import argmin_assign, batched_self_topk, row_sq as _row_sq
from .distance import score_candidates as _score_candidates

_SENTINEL_F = jnp.float32(jnp.inf)

# row-tiled nearest-anchor assignment (shared core), compiled once per shape
_assign_rows = jax.jit(argmin_assign)


def _merge_dedup_topk(all_ids, all_d2, keep: int, extra=None):
    """Per-row merge of candidate lists: drop duplicate ids (keeping the
    smallest-d2 copy), then keep the `keep` smallest distances.

    Sort twice — by d2, then STABLY by id — so the first entry of every
    equal-id run is its best copy; later copies get +inf and fall out of the
    final top-k. `extra` (e.g. the search's expanded flags) rides along."""
    ord1 = jnp.argsort(all_d2, axis=1)
    ids1 = jnp.take_along_axis(all_ids, ord1, axis=1)
    d21 = jnp.take_along_axis(all_d2, ord1, axis=1)
    ord2 = jnp.argsort(ids1, axis=1, stable=True)
    ids2 = jnp.take_along_axis(ids1, ord2, axis=1)
    d22 = jnp.take_along_axis(d21, ord2, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros_like(ids2[:, :1], bool), ids2[:, 1:] == ids2[:, :-1]], axis=1
    )
    d22 = jnp.where(dup, _SENTINEL_F, d22)
    _, pos = jax.lax.top_k(-d22, keep)
    out_ids = jnp.take_along_axis(ids2, pos, axis=1)
    out_d2 = jnp.take_along_axis(d22, pos, axis=1)
    if extra is None:
        return out_ids, out_d2
    ex = jnp.take_along_axis(
        jnp.take_along_axis(jnp.take_along_axis(extra, ord1, axis=1), ord2, axis=1),
        pos,
        axis=1,
    )
    return out_ids, out_d2, ex


# candidate scoring is the shared core's gather-scoring primitive
# (distance.score_candidates — imported above): d2[t, c] =
# ||q_rows[t] - x[cand[t, c]]||², fast=True runs the einsum one-pass bf16
# (ranking-only distances; recall asserted in tests/test_knn.py)


@partial(jax.jit, static_argnames=("r_max",), donate_argnums=())
def _reverse_edges(ids: jax.Array, *, r_max: int) -> jax.Array:
    """[n, r_max] reverse adjacency (pad −1) built fully on device: sort the
    flat edge list by tail, position-within-run via searchsorted, one scatter
    (mode='drop' discards overflow past r_max — hubs keep an arbitrary
    subset, which is exactly the sampling NN-descent wants)."""
    n, k = ids.shape
    flat = ids.reshape(-1)
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, k)).reshape(-1)
    order = jnp.argsort(flat)
    st = flat[order]
    ss = src[order]
    seg_start = jnp.searchsorted(st, jnp.arange(n, dtype=ids.dtype))
    offs = jnp.arange(st.shape[0]) - seg_start[st]
    rev = jnp.full((n, r_max), -1, jnp.int32)
    return rev.at[st, offs].set(ss, mode="drop")


@partial(
    jax.jit,
    static_argnames=("tile", "s_top", "s_rnd", "s_rev", "c_rnd", "fast"),
    donate_argnums=(2, 3),
)
def _descent_round(
    x, x_sq, ids, d2, rev, key, *, tile: int, s_top: int, s_rnd: int,
    s_rev: int, c_rnd: int, fast: bool = False
):
    """One NN-descent round over every row, a single XLA program.

    Per tile of `tile` rows: expand the FULL adjacency lists of `s_top`
    closest + `s_rnd` random + `s_rev` reverse neighbors (full-list expansion
    converges far better than subsampling the 2-hop set — measured 0.81 vs
    0.59 node-level graph recall at 20k x 64), plus the reverse edges
    themselves and `c_rnd` fresh random ids; score; merge-dedup-topk back
    into the [n, K_int] graph. The per-row lists are distance-sorted (top_k
    output), so `ids_t[:, :s_top]` IS the closest-neighbor set.

    Returns (ids, d2, n_new) where n_new counts candidate slots accepted into
    the lists this round — the convergence signal for the caller's
    early-exit (cuVS NN-descent terminates on update rate the same way)."""
    n, d = x.shape
    k_int = ids.shape[1]
    n_tiles = -(-n // tile)

    half = min(64, k_int)  # expand each source's TOP-half list only

    def body(ti, carry):
        ids_c, d2_c, n_new = carry
        r0 = jnp.minimum(ti * tile, n - tile)
        rows = (r0 + jnp.arange(tile)).astype(jnp.int32)
        tkey = jax.random.fold_in(key, ti)
        ids_t = jax.lax.dynamic_slice(ids_c, (r0, 0), (tile, k_int))
        d2_t = jax.lax.dynamic_slice(d2_c, (r0, 0), (tile, k_int))
        q_rows = jax.lax.dynamic_slice(x, (r0, 0), (tile, d))

        k1, k2, k3 = jax.random.split(tkey, 3)
        top_src = ids_t[:, :s_top]
        # clamp the random-slot range: when k_int <= s_top (tiny n or tiny
        # intermediate degree) [s_top, k_int) is empty — sample the whole list
        rnd_lo = s_top if k_int > s_top else 0
        rnd_slots = jax.random.randint(k1, (tile, s_rnd), rnd_lo, k_int, jnp.int32)
        rnd_src = jnp.take_along_axis(ids_t, rnd_slots, axis=1)
        rev_t = jax.lax.dynamic_slice(rev, (r0, 0), (tile, rev.shape[1]))
        rev_slots = jax.random.randint(k2, (tile, s_rev), 0, rev.shape[1], jnp.int32)
        rev_src = jnp.clip(jnp.take_along_axis(rev_t, rev_slots, axis=1), 0, n - 1)
        src = jnp.concatenate([top_src, rnd_src, rev_src], axis=1)
        cand_fwd = ids_c[src][:, :, :half].reshape(tile, -1)
        cand_rnd = jax.random.randint(k3, (tile, c_rnd), 0, n, jnp.int32)

        cand = jnp.concatenate([cand_fwd, rev_t, cand_rnd], axis=1)
        # drop pads/self, anything already in the row's list, and repeat
        # proposals within the candidate block (keep the first occurrence) —
        # all elementwise compare masks; NO sort-based dedup in the hot loop
        # (XLA row sorts dominated the round: 26-33s/round of 500k x 736-wide
        # sorts, vs <1s for the masks + approx top-k)
        invalid = (cand < 0) | (cand == rows[:, None])
        invalid |= jnp.any(cand[:, :, None] == ids_t[:, None, :], axis=2)
        c_w = cand.shape[1]
        earlier = jnp.arange(c_w)[None, :] < jnp.arange(c_w)[:, None]  # [C, C]
        invalid |= jnp.any(
            (cand[:, :, None] == cand[:, None, :]) & earlier[None], axis=2
        )
        cand = jnp.clip(cand, 0, n - 1)
        d2_cand = _score_candidates(q_rows, cand, x, x_sq, fast=fast)
        d2_cand = jnp.where(invalid, _SENTINEL_F, d2_cand)

        # merge with approx_min_k (the TPU-native top-k path). In-round
        # duplicate proposals (same NEW id from two sources) may transiently
        # occupy two slots; the next round's compare mask stops them from
        # multiplying, and the final prune keeps k_out << k_int slack.
        all_ids = jnp.concatenate([ids_t, cand], axis=1)
        all_d2 = jnp.concatenate([d2_t, d2_cand], axis=1)
        new_d2, pos = jax.lax.approx_min_k(all_d2, k_int)
        new_ids = jnp.take_along_axis(all_ids, pos, axis=1)
        # accepted-candidate count (pos past the old list = a fresh edge);
        # only count rows this tile owns (the last tile is clamped back)
        fresh_rows = (r0 + jnp.arange(tile)) >= ti * tile
        n_new = n_new + jnp.sum(
            jnp.where(fresh_rows[:, None], pos >= k_int, False)
        ).astype(jnp.int32)
        ids_c = jax.lax.dynamic_update_slice(ids_c, new_ids, (r0, 0))
        d2_c = jax.lax.dynamic_update_slice(d2_c, new_d2, (r0, 0))
        return ids_c, d2_c, n_new

    return jax.lax.fori_loop(0, n_tiles, body, (ids, d2, jnp.zeros((), jnp.int32)))


@partial(jax.jit, static_argnames=("kk",))
def _bucket_knn(xb, ids_b, *, kk: int):
    """Exact kNN inside padded buckets — the shared core's batched
    self-top-k (distance.batched_self_topk): one [Cb, L, L] distance matmul
    on the MXU + top-k. Returns (d2 [Cb, L, kk], neighbor ids [Cb, L, kk])."""
    return batched_self_topk(xb, ids_b, kk=kk)


def _cluster_seed_rep(xd, x_sq, n: int, anchors_c: int, kk: int, seed: int):
    """One clustered brute-force seeding repetition: partition rows by
    nearest random anchor, exact kNN within each padded bucket, scatter the
    per-row results into [n, kk] (each row lives in exactly ONE bucket, so
    the scatter is conflict-free). Different seeds give different Voronoi
    partitions; merged across reps they seed the graph with near-exact local
    edges (the IVF analog of cuVS's ivf_pq build seeding)."""
    d = xd.shape[1]
    rng = np.random.default_rng(seed)
    anchors = xd[jnp.asarray(rng.choice(n, min(anchors_c, n), replace=False))]
    assign = np.asarray(_assign_rows(xd, anchors))
    C = anchors.shape[0]
    counts = np.bincount(assign, minlength=C)
    # cap pathological buckets: overflow rows just miss THIS rep's edges
    l_cap = max(kk + 1, int(4 * max(1, n // max(C, 1))))
    L = int(min(counts.max(), l_cap))
    order = np.argsort(assign, kind="stable")
    offs = np.arange(n) - (np.cumsum(counts) - counts)[assign[order]]
    keep = offs < L
    ids_b = np.full((C, L), -1, np.int64)
    ids_b[assign[order][keep], offs[keep]] = order[keep]
    idsj = jnp.asarray(ids_b)

    rep_d2 = jnp.full((n, kk), _SENTINEL_F)
    rep_id = jnp.zeros((n, kk), jnp.int32)
    # batch buckets so the [Cb, L, L] + [Cb, L, d] tensors stay bounded
    cb = max(1, int(500_000_000 // max(L * L * 4 + L * d * 4, 1)))
    for c0 in range(0, C, cb):
        idc = idsj[c0 : c0 + cb]
        xb = xd[jnp.clip(idc, 0, n - 1)]
        nd2, nid = _bucket_knn(xb, idc, kk=kk)
        # pad slots (-1) are routed OUT OF BOUNDS so mode='drop' discards them
        flat_rows = jnp.where(idc >= 0, idc, n).reshape(-1)
        rep_d2 = rep_d2.at[flat_rows].set(nd2.reshape(-1, kk), mode="drop")
        # under-filled buckets yield -1 neighbor ids at +inf d2: clamp to 0
        # (a harmless inf-distance duplicate that top-k drops)
        rep_id = rep_id.at[flat_rows].set(
            jnp.maximum(nid.reshape(-1, kk), 0).astype(jnp.int32), mode="drop"
        )
    return rep_id, rep_d2


def build_cagra(
    x,
    *,
    graph_degree: int = 64,
    intermediate_graph_degree: int = 128,
    build_algo: str = "ivf_pq",
    nn_descent_niter: int = 0,
    cluster_reps: int = 8,
    seed: int = 0,
    termination_threshold: float = 0.003,
    fast_score: bool = True,
) -> Dict[str, Any]:
    """Build the CAGRA graph index. Returns {"x": [n,d] f32,
    "graph": [n, graph_degree] int32} — both DEVICE-resident jax.Arrays
    (the search consumes them in HBM; fetch with np.asarray if a host copy
    is needed).

    Parameter names/defaults mirror the reference's cagra IndexParams
    (knn.py:927-931): graph_degree 64, intermediate_graph_degree 128,
    build_algo "ivf_pq" | "nn_descent". "ivf_pq" (default) runs
    `cluster_reps` clustered brute-force seeding repetitions
    (_cluster_seed_rep — exact kNN inside Voronoi buckets, pure MXU batched
    matmuls) and then NN-descent refinement rounds; "nn_descent" is pure
    NN-descent from a random graph. nn_descent_niter=0 auto-selects the
    MAX round count per build_algo (3 after cluster seeding, 14 from random).

    The seeding/descent budget split is tuned for the TPU cost model:
    seeding reps are batched MXU matmuls (cheap on chip) while descent
    rounds are gather+sort bound (expensive), and reps buy MORE node recall
    per unit work — measured at 20k x 64: reps=3+8 rounds 0.733 recall,
    reps=8+3 rounds ~0.80, reps=20+1 0.942. Hence the defaults
    cluster_reps=8, 3 seeded rounds (was 3 reps + 8 rounds — strictly worse
    on both axes).

    Descent terminates EARLY when a round accepts fewer than
    `termination_threshold * n * k_int` new edges (cuVS NN-descent's
    update-rate termination, termination_threshold there too): well-seeded
    builds typically stop several rounds short of the max. `fast_score=True`
    runs the candidate-scoring einsum with bf16 inputs / f32 accumulation —
    ranking-only distances, ~2.6x the MXU rate (see _score_candidates).
    """
    if isinstance(x, jax.Array):
        # device-resident input (benchmark datagen): no host round trip
        xd = x if x.dtype == jnp.float32 else x.astype(jnp.float32)
    else:
        xd = jax.device_put(
            np.ascontiguousarray(np.asarray(x, dtype=np.float32))
        )
    n, d = xd.shape
    if build_algo not in ("ivf_pq", "nn_descent"):
        raise ValueError(
            f"build_algo {build_algo!r} not supported (ivf_pq | nn_descent)"
        )
    k_int = int(min(intermediate_graph_degree, max(n - 1, 1)))
    k_out = int(min(graph_degree, k_int))
    # pick the round count from whether cluster seeding ACTUALLY runs (small n
    # falls back to random init, which needs the longer random-init schedule)
    use_seeding = build_algo == "ivf_pq" and n > 4 * k_int
    n_rounds = int(nn_descent_niter) or (3 if use_seeding else 14)

    rng = np.random.default_rng(seed)
    x_sq = _row_sq(xd)

    if use_seeding:
        # clustered brute-force seeding: target bucket size ~512 rows.
        # All reps are merged in ONE sort-dedup pass (each 500k-row sort
        # merge costs ~8s on a v5e; one wide merge beats three narrow ones)
        anchors_c = max(2, n // 512)
        kk = min(64, k_int, n - 1)
        reps = [
            _cluster_seed_rep(xd, x_sq, n, anchors_c, kk, seed * 1000 + rep)
            for rep in range(max(1, cluster_reps))
        ]
        rep_ids = jnp.concatenate([r[0] for r in reps], axis=1)
        rep_d2 = jnp.concatenate([r[1] for r in reps], axis=1)
        if rep_ids.shape[1] < k_int:
            # top-k needs width >= k_int (e.g. large intermediate_graph_degree
            # with few reps): pad with inf-distance slots
            pad = k_int - rep_ids.shape[1]
            rep_ids = jnp.concatenate(
                [rep_ids, jnp.zeros((n, pad), jnp.int32)], axis=1
            )
            rep_d2 = jnp.concatenate(
                [rep_d2, jnp.full((n, pad), _SENTINEL_F)], axis=1
            )
        ids, d2 = _merge_dedup_topk(rep_ids, rep_d2, k_int)
    else:
        # random init; descent round 0 scores these ids through the
        # candidate channels, so +inf stored distances are correct
        ids = jax.device_put(rng.integers(0, n, size=(n, k_int)).astype(np.int32))
        d2 = jnp.full((n, k_int), _SENTINEL_F)

    # expansion budget: (s_top+s_rnd+s_rev) * top-64-of-list + r_max + c_rnd
    s_top, s_rnd, s_rev, c_rnd, r_max = 2, 1, 1, 32, 64
    c_total = (s_top + s_rnd + s_rev) * min(64, k_int) + r_max + c_rnd
    # tile sized so the [tile, c_total, d] candidate gather stays ~1.5 GB
    tile = int(min(n, max(64, (1_500_000_000 // (c_total * d * 4)) & ~63)))
    tile = max(1, min(tile, n))
    key = jax.random.PRNGKey(seed)
    rev = None
    # early-exit bar: new-edge count below this fraction of the n*k_int slots
    # ends the descent (the scalar fetch per round is ~50ms of sync through a
    # remote tunnel vs ~seconds per skipped round at 500k x 512)
    min_new = max(1, int(termination_threshold * n * k_int))
    for rnd in range(n_rounds):
        if rnd % 2 == 0 or rev is None:
            # refresh reverse edges every OTHER round: the device-wide sort
            # costs ~3s at 500k x 128 and one-round staleness is harmless
            rev = _reverse_edges(ids, r_max=r_max)
        ids, d2, n_new = _descent_round(
            xd, x_sq, ids, d2, rev, jax.random.fold_in(key, rnd),
            tile=tile, s_top=s_top, s_rnd=s_rnd, s_rev=s_rev, c_rnd=c_rnd,
            fast=bool(fast_score),
        )
        if int(n_new) < min_new:  # host-fetch-ok: per-ROUND termination probe (documented above: ~50ms fetch vs ~seconds per skipped descent round)
            break
    # prune to the final degree: the K_int list is distance-sorted by top_k;
    # both index halves stay ON DEVICE (the search consumes them there)
    return {"x": xd, "graph": ids[:, :k_out]}


@partial(
    jax.jit,
    static_argnames=("itopk", "k", "search_width", "iters"),
)
def _search_tile(
    xq, x, x_sq, graph, key, *, itopk: int, k: int, search_width: int, iters: int
):
    """Greedy graph search for one query tile — a single XLA program.

    State per query: `itopk` best ids/d2 plus an expanded flag. Each
    iteration expands the best `search_width` unexpanded candidates, scores
    their adjacency rows, and merges (sort-dedup + top-k, flags ride along)."""
    qn, d = xq.shape
    n = x.shape[0]
    deg = graph.shape[1]
    q_sq = _row_sq(xq)

    init_ids = jax.random.randint(key, (qn, itopk), 0, n, jnp.int32)
    d2 = _score_candidates(xq, init_ids, x, x_sq)
    ids, d2 = _merge_dedup_topk(init_ids, d2, itopk)
    expanded = jnp.zeros((qn, itopk), bool)

    def body(_, state):
        ids, d2, expanded = state
        sel_score = jnp.where(expanded, _SENTINEL_F, d2)
        _, sel = jax.lax.top_k(-sel_score, search_width)  # positions [Q, W]
        sel_ids = jnp.take_along_axis(ids, sel, axis=1)
        hit = jnp.any(
            jnp.arange(itopk)[None, :, None] == sel[:, None, :], axis=2
        )
        expanded = expanded | hit
        cand = graph[sel_ids].reshape(qn, search_width * deg)
        dup = jnp.any(cand[:, :, None] == ids[:, None, :], axis=2)
        c_w = cand.shape[1]
        earlier = jnp.arange(c_w)[None, :] < jnp.arange(c_w)[:, None]
        dup |= jnp.any(
            (cand[:, :, None] == cand[:, None, :]) & earlier[None], axis=2
        )
        d2c = _score_candidates(xq, cand, x, x_sq)
        d2c = jnp.where(dup | (cand < 0), _SENTINEL_F, d2c)
        all_ids = jnp.concatenate([ids, cand], axis=1)
        all_d2 = jnp.concatenate([d2, d2c], axis=1)
        all_exp = jnp.concatenate(
            [expanded, jnp.zeros_like(dup)], axis=1
        )
        # approx_min_k: the TPU-native top-k (row sorts here dominate the
        # whole search otherwise); cand-vs-list dups are masked above, and
        # rare cand-vs-cand dups cost one wasted expansion at most
        d2, pos = jax.lax.approx_min_k(all_d2, itopk)
        ids = jnp.take_along_axis(all_ids, pos, axis=1)
        expanded = jnp.take_along_axis(all_exp, pos, axis=1)
        return ids, d2, expanded

    ids, d2, _ = jax.lax.fori_loop(0, iters, body, (ids, d2, expanded))
    _, pos = jax.lax.top_k(-d2, k)
    out_ids = jnp.take_along_axis(ids, pos, axis=1)
    out_d2 = jnp.take_along_axis(d2, pos, axis=1)
    return out_ids, out_d2


def cagra_search(
    queries,
    index: Dict[str, Any],
    *,
    k: int,
    itopk_size: int = 64,
    search_width: int = 1,
    max_iterations: int = 0,
    min_iterations: int = 0,
    num_random_samplings: int = 1,
    seed: int = 0,
    batch_queries: int = 4096,
):
    """Batched greedy search over the CAGRA graph. Returns (indices [q, k]
    int64, d2 [q, k] f32 squared-L2), both host arrays.

    Search params mirror the reference's cagra SearchParams
    (knn.py:933-938). itopk_size is rounded up to a multiple of 32 (cuVS
    semantics, knn.py:1286-1297); max_iterations=0 auto-selects enough
    iterations to expand the whole itopk list at the given search_width."""
    itopk = max(32, int(math.ceil(itopk_size / 32) * 32))
    if itopk < k:
        raise ValueError(f"itopk_size ({itopk}) must be >= k ({k})")
    width = max(1, int(search_width))
    iters = int(max_iterations) if max_iterations else -(-itopk // width)
    iters = max(iters, int(min_iterations), 1)

    q = np.ascontiguousarray(np.asarray(queries, dtype=np.float32))
    nq, d = q.shape
    # accept pre-device-put index arrays (device_put of a jax.Array is a
    # no-op; converting one through numpy would round-trip it to host)
    x = index["x"]
    graph = index["graph"]
    if not isinstance(graph, jax.Array):
        graph = np.asarray(graph, dtype=np.int32)
    x = jax.device_put(x)
    graph = jax.device_put(graph)
    x_sq = _row_sq(x)

    out_i = np.empty((nq, k), np.int64)
    out_d = np.empty((nq, k), np.float32)
    # tile sized so the per-iteration [bq, W*deg, d] frontier gather stays
    # ~1.5 GB regardless of dimensionality
    deg = index["graph"].shape[1]
    cap = int(max(256, (1_500_000_000 // (width * deg * d * 4)) & ~63))
    bq = max(1, min(batch_queries, cap, max(nq, 1)))
    key = jax.random.PRNGKey(seed)
    qd = None
    for s in range(0, nq, bq):
        qt = q[s : s + bq]
        valid = len(qt)
        if valid < bq:
            qt = np.concatenate([qt, np.zeros((bq - valid, d), np.float32)])
        qd = jax.device_put(qt)
        # num_random_samplings re-runs the random seeding; keep the best run
        best_i, best_d = None, None
        for r in range(max(1, int(num_random_samplings))):
            ti, td = _search_tile(
                qd, x, x_sq, graph, jax.random.fold_in(key, s * 131 + r),
                itopk=itopk, k=k, search_width=width, iters=iters,
            )
            if best_i is None:
                best_i, best_d = ti, td
            else:
                best_i, best_d = _merge_dedup_topk(
                    jnp.concatenate([best_i, ti], axis=1),
                    jnp.concatenate([best_d, td], axis=1),
                    k,
                )
        out_i[s : s + valid] = np.asarray(best_i)[:valid]  # host-fetch-ok: per-query-TILE result landing in the preallocated host output
        out_d[s : s + valid] = np.asarray(best_d)[:valid]  # host-fetch-ok: per-query-TILE result landing in the preallocated host output
    return out_i, out_d
