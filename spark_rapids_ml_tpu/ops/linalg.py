#
# Distributed dense linear-algebra primitives shared by PCA / linear models:
# weighted mean/covariance/gram with cross-chip reduction, symmetric eigensolve,
# and eigenvector sign canonicalization.
#
# Replaces the cuML/RAFT pieces the reference calls through `PCAMG` /
# `LinearRegressionMG` (local cov gemm + NCCL allreduce + eig; see reference
# feature.py:220-241 and the JNI path rapidsml_jni.cu:109-127 `dgemmCov`,
# :215-269 `calSVD`). Design: inputs are row-sharded global arrays; the
# `einsum` contractions below hit the MXU per shard and GSPMD inserts the
# `psum` for the row (sharded) dimension — the NCCL allreduce equivalent.
#
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def weighted_moments(X: jax.Array, w: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (total_weight, mean [d], var [d]) with padding rows zero-weighted."""
    total_w = jnp.sum(w)
    mean = jnp.einsum("n,nd->d", w, X) / total_w
    sq = jnp.einsum("n,nd->d", w, X * X) / total_w
    var = jnp.maximum(sq - mean * mean, 0.0)
    return total_w, mean, var


def weighted_cov(
    X: jax.Array, w: jax.Array, ddof: int = 1, fast: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Weighted covariance: returns (total_weight, mean [d], cov [d, d]).

    ``cov = Σ w_i (x_i-μ)(x_i-μ)ᵀ / (Σw - ddof)`` — matches the reference's
    sample covariance (cuML PCA divides by n-1). The centered outer-product
    contraction is one large MXU matmul per shard + one psum.

    ``fast`` runs the big contraction bf16-in / f32-accumulate (the
    solver_precision="bf16" contract, docs/performance.md "Mixed-precision
    solvers"): weighting and centering stay at full precision, only the
    [n,d]x[n,d] outer product is cast. Parity vs the full-precision cov is
    pinned by tests/test_precision.py.
    """
    total_w = jnp.sum(w)
    mean = jnp.einsum("n,nd->d", w, X) / total_w
    Xc = X - mean
    if fast:
        # weights applied at FULL precision first — a mixed-dtype einsum
        # would promote the bf16 operand straight back to f32 and defeat
        # the cast; the bf16 dot accumulates in f32 on the MXU
        Xcw = Xc * w[:, None]
        cov = jnp.einsum(
            "nd,ne->de",
            Xcw.astype(jnp.bfloat16),
            Xc.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ).astype(X.dtype) / (total_w - ddof)
    else:
        cov = jnp.einsum("nd,n,ne->de", Xc, w, Xc) / (total_w - ddof)
    return total_w, mean, cov


def sign_flip(components: jax.Array) -> jax.Array:
    """Canonicalize eigenvector signs: the max-|value| element of each component
    row is made positive — the exact semantics of the reference's thrust
    `signFlip` kernel (reference jvm/native/src/rapidsml_jni.cu:35-61) and of
    cuML MG PCA, so component outputs are comparable bit-for-sign."""
    idx = jnp.argmax(jnp.abs(components), axis=1)
    signs = jnp.sign(components[jnp.arange(components.shape[0]), idx])
    signs = jnp.where(signs == 0, 1.0, signs)
    return components * signs[:, None]


def topk_eigh_desc(sym: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Full symmetric eigendecomposition, top-k in descending eigenvalue order.

    Mirrors the reference JNI `calSVD` post-processing (eigDC + column/row
    reverse, rapidsml_jni.cu:215-269): LAPACK/XLA return ascending order, the
    framework contract is descending. Returns (eigvals [k], eigvecs [k, d]).
    """
    evals, evecs = jnp.linalg.eigh(sym)  # ascending
    evals = evals[::-1][:k]
    comps = evecs.T[::-1][:k]
    return evals, comps
