#
# UMAP solver — the in-tree replacement for `cuml.manifold.UMAP` (consumed by
# reference umap.py:928-950; the reference only orchestrates, cuML owns the
# math, so this file implements the algorithm itself, matching umap-learn /
# cuML semantics).
#
# TPU-native design:
#  * the kNN graph comes from the exact sharded kNN solver (ops/knn.py) — the
#    only O(n²) stage, tiled on the MXU across the mesh;
#  * smooth-kNN calibration (per-point rho/sigma via bisection to hit
#    log2(k) effective neighbors) is one vectorized jitted program — no
#    per-point Python;
#  * the fuzzy simplicial set stays in fixed [n, k] edge layout (static
#    shapes); the transpose weights needed for symmetrization are looked up
#    with a vectorized membership test instead of sparse-matrix ops;
#  * the SGD layout optimization runs as a `lax.fori_loop` over epochs; each
#    epoch applies ALL due edges at once (umap-learn's epochs_per_sample
#    schedule), attraction via scatter-add on both endpoints, repulsion via
#    per-edge negative samples — a parallel variant of umap-learn's
#    sequential SGD with the same schedule and force model.
#
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

SMOOTH_K_TOLERANCE = 1e-5
MIN_K_DIST_SCALE = 1e-3


def find_ab_params(spread: float, min_dist: float) -> Tuple[float, float]:
    """Fit the differentiable curve 1/(1+a*x^(2b)) to the desired fuzzy-member
    curve (umap-learn's find_ab_params)."""
    from scipy.optimize import curve_fit

    def curve(x, a, b):
        return 1.0 / (1.0 + a * x ** (2 * b))

    xv = np.linspace(0, spread * 3, 300)
    yv = np.ones_like(xv)
    mask = xv >= min_dist
    yv[mask] = np.exp(-(xv[mask] - min_dist) / spread)
    params, _ = curve_fit(curve, xv, yv)
    return float(params[0]), float(params[1])


@partial(jax.jit, static_argnames=("n_iter",))
def smooth_knn(
    knn_dist: jax.Array,  # [n, k] ascending distances, col 0 = self (0.0)
    local_connectivity: float = 1.0,
    bandwidth: float = 1.0,
    n_iter: int = 64,
) -> Tuple[jax.Array, jax.Array]:
    """Per-point (rho, sigma): rho = distance to the local_connectivity-th
    nearest neighbor (interpolated); sigma solves
    sum_j exp(-max(0, d_ij - rho)/sigma) = log2(k) by bisection."""
    n, k = knn_dist.shape
    target = jnp.log2(k) * bandwidth

    # rho: interpolated local_connectivity-th smallest NONZERO distance
    nonzero = knn_dist > 0.0
    num_nonzero = jnp.sum(nonzero, axis=1)
    big = jnp.max(knn_dist) + 1.0
    nz_sorted = jnp.sort(jnp.where(nonzero, knn_dist, big), axis=1)  # [n, k]
    lc = jnp.asarray(local_connectivity, knn_dist.dtype)
    idx = jnp.floor(lc).astype(jnp.int32) - 1
    frac = lc - jnp.floor(lc)

    def rho_of(row, nnz):
        lo = jnp.where(idx >= 0, row[jnp.maximum(idx, 0)], 0.0)
        hi = row[jnp.minimum(idx + 1, k - 1)]
        interp = jnp.where(idx >= 0, lo + frac * (hi - lo), frac * row[0])
        # umap-learn: if fewer nonzero distances than local_connectivity, rho
        # is the max distance
        return jnp.where(nnz >= lc, interp, jnp.where(nnz > 0, row[jnp.maximum(nnz - 1, 0)], 0.0))

    rho = jax.vmap(rho_of)(nz_sorted, num_nonzero)

    def psum_of(sigma):
        d = jnp.maximum(knn_dist - rho[:, None], 0.0)
        # col 0 is the self-distance: umap-learn sums over the k-1 others + 1
        return jnp.sum(jnp.exp(-d / sigma[:, None]), axis=1)

    lo = jnp.zeros(n, knn_dist.dtype)
    hi = jnp.full(n, jnp.inf, knn_dist.dtype)
    mid = jnp.ones(n, knn_dist.dtype)

    def body(_, state):
        lo, hi, mid = state
        val = psum_of(mid)
        too_big = val > target
        hi = jnp.where(too_big, mid, hi)
        lo = jnp.where(too_big, lo, mid)
        mid = jnp.where(
            too_big, (lo + hi) / 2.0, jnp.where(jnp.isinf(hi), mid * 2.0, (lo + hi) / 2.0)
        )
        return lo, hi, mid

    _, _, sigma = jax.lax.fori_loop(0, n_iter, body, (lo, hi, mid))
    # umap-learn floor: sigma >= MIN_K_DIST_SCALE * mean distance
    mean_d = jnp.mean(knn_dist)
    mean_row = jnp.mean(knn_dist, axis=1)
    floor = jnp.where(rho > 0.0, MIN_K_DIST_SCALE * mean_row, MIN_K_DIST_SCALE * mean_d)
    return rho, jnp.maximum(sigma, floor)


@jax.jit
def fuzzy_simplicial_set(
    knn_idx: jax.Array,  # [n, k] neighbor indices (col 0 = self)
    knn_dist: jax.Array,  # [n, k]
    rho: jax.Array,
    sigma: jax.Array,
    set_op_mix_ratio: float = 1.0,
) -> jax.Array:
    """Symmetrized membership strengths in the fixed [n, k] edge layout.

    w_ij = exp(-max(0, d_ij - rho_i)/sigma_i); the transpose entry w_ji is
    found with a vectorized membership probe of i in knn[j], then
    sym = mix*(w + wT - w*wT) + (1-mix)*(w*wT)."""
    n, k = knn_idx.shape
    w = jnp.exp(-jnp.maximum(knn_dist - rho[:, None], 0.0) / sigma[:, None])
    w = jnp.where(knn_idx == jnp.arange(n)[:, None], 0.0, w)  # no self-edges

    # wT[i, j_slot] = weight of edge (knn_idx[i, j_slot] -> i), 0 if absent:
    # one [n, k, k] gather + a vectorized membership probe of i in knn[j]
    cand_idx = knn_idx[knn_idx]  # [n, k, k]
    cand_w = w[knn_idx]  # [n, k, k]
    match = cand_idx == jnp.arange(n)[:, None, None]
    wT = jnp.sum(jnp.where(match, cand_w, 0.0), axis=2)
    prod = w * wT
    return set_op_mix_ratio * (w + wT - prod) + (1.0 - set_op_mix_ratio) * prod


@partial(jax.jit, static_argnames=("n", "iters"))
def _spectral_subspace(rows_s, cols_s, vals_s, u0, *, n: int, iters: int):
    """Deflated orthogonal iteration for the top `c` non-trivial eigenvectors
    of the normalized adjacency P = D^-1/2 A D^-1/2 (equivalently the
    SMALLEST non-trivial of the normalized Laplacian). Edge arrays are the
    row-sorted symmetric COO; each matvec is one gather + one sorted
    segment-sum — everything stays on device, and ~`iters` rounds of a
    [n, c] QR are microscopic next to scipy's shift-invert LU (measured
    17 min at 20k nodes for eigsh(sigma=0))."""
    deg = jax.ops.segment_sum(vals_s, rows_s, num_segments=n, indices_are_sorted=True)
    dis = 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-12))
    v0 = jnp.sqrt(jnp.maximum(deg, 0.0))
    v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-12)

    def pmat(U):  # (I + P)/2 @ U for U [n, c] — shifted so the spectrum is
        # [0, 1]: plain power iteration on P converges to largest-MAGNITUDE
        # eigenvalues, and near-bipartite graphs have lambda ~ -1 modes that
        # would displace the smooth modes we want
        su = dis[:, None] * U
        e = vals_s[:, None] * su[cols_s]
        pu = dis[:, None] * jax.ops.segment_sum(
            e, rows_s, num_segments=n, indices_are_sorted=True
        )
        return 0.5 * (U + pu)

    def body(_, U):
        U = pmat(U)
        U = U - v0[:, None] * (v0 @ U)[None, :]  # deflate the trivial mode
        Q, _ = jnp.linalg.qr(U)
        return Q

    U = jax.lax.fori_loop(0, iters, body, u0)
    # Rayleigh-Ritz rotation orders the subspace by eigenvalue (descending
    # eigenvalue of P = ascending Laplacian eigenvalue)
    B = U.T @ pmat(U)
    evals, R = jnp.linalg.eigh((B + B.T) / 2.0)
    return U @ R[:, ::-1]


def spectral_init(
    knn_idx: np.ndarray, weights: np.ndarray, n_components: int, seed: int
) -> np.ndarray:
    """Normalized-Laplacian spectral layout of the fuzzy graph (umap-learn's
    spectral_layout semantics), computed ON DEVICE by deflated orthogonal
    iteration over the symmetrized edge list — this is an embedding INIT, so
    a subspace accurate to a few digits is ample."""
    n, k = knn_idx.shape
    if n <= n_components + 1:
        rng = np.random.default_rng(seed)
        return rng.uniform(-10, 10, (n, n_components)).astype(np.float32)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = knn_idx.reshape(-1).astype(np.int64)
    vals = weights.reshape(-1).astype(np.float32) / 2.0
    # symmetrize: (A + Aᵀ)/2 as a doubled edge list; sort by row once
    r2 = np.concatenate([rows, cols])
    c2 = np.concatenate([cols, rows])
    v2 = np.concatenate([vals, vals])
    order = np.argsort(r2, kind="stable")
    rng = np.random.default_rng(seed)
    u0 = rng.normal(size=(n, n_components)).astype(np.float32)
    emb = np.asarray(
        _spectral_subspace(
            jnp.asarray(r2[order], dtype=jnp.int32),
            jnp.asarray(c2[order], dtype=jnp.int32),
            jnp.asarray(v2[order]),
            jnp.asarray(u0),
            n=n,
            iters=120,
        )
    )
    if not np.all(np.isfinite(emb)):
        from ..utils import get_logger

        get_logger("UMAP").warning(
            "spectral initialization diverged; falling back to random init"
        )
        return rng.uniform(-10, 10, (n, n_components)).astype(np.float32)
    expansion = 10.0 / max(np.abs(emb).max(), 1e-12)
    return (emb * expansion + rng.normal(0, 1e-4, emb.shape)).astype(np.float32)


def _inverse_adjacency(
    tail_idx: np.ndarray, n: int, cap: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side inverse adjacency of the [n, k] edge layout: inv[t, s] = flat
    edge id e (= i*k + j) whose tail is node t, padded with E. Lets the
    tail-side SGD update be a dense GATHER instead of a scatter-add — TPU
    scatters with duplicate indices are both slow to run (~36 ms/epoch for
    300k edges, measured) and very slow to compile.

    In-degree is capped at `cap` (default 8·k): real kNN graphs have hub
    nodes whose in-degree is tens of times the mean (measured 841 vs mean 15
    at 20k iid Gaussian rows), and padding every row to the hub's width
    bloats the per-epoch gather ~56×. Edges past the cap are returned as a
    flat-id overflow list the optimizer applies with one SMALL scatter-add.
    Returns (inv [n, k_in<=cap], overflow edge ids [E_ov])."""
    flat = tail_idx.reshape(-1).astype(np.int64)
    E = flat.shape[0]
    if cap is None:
        # skew bound (8x the out-degree) AND an absolute memory bound (~512MB
        # of int64 inv at huge n); past the cap the overflow scatter degrades
        # gracefully toward the full-edge-set scatter
        cap = max(64, min(8 * tail_idx.shape[1], int(5e8 // max(n * 8, 1))))
    counts = np.bincount(flat, minlength=n)
    k_in = int(min(counts.max(), cap)) if E else 0
    order = np.argsort(flat, kind="stable")
    sorted_t = flat[order]
    offs = np.arange(E) - (np.cumsum(counts) - counts)[sorted_t]
    keep = offs < k_in
    inv = np.full((n, max(k_in, 1)), E, dtype=np.int64)
    inv[sorted_t[keep], offs[keep]] = order[keep]
    return inv, order[~keep]


@partial(
    jax.jit,
    static_argnames=("n_epochs", "negative_sample_rate", "fit_mode"),
)
def optimize_embedding(
    Y0: jax.Array,  # [n, c] initial embedding (optimized rows)
    ref: jax.Array,  # [m, c] frozen reference embedding (transform mode)
    tail_idx: jax.Array,  # [n, k] tail node per edge (head = row index)
    weights: jax.Array,  # [n, k] membership strengths
    inv_idx: Optional[jax.Array],  # [n, k_in] capped inverse adjacency (fit mode)
    ov_idx: Optional[jax.Array] = None,  # [E_ov] overflow flat edge ids (hubs)
    *,
    n_epochs: int,
    a: float,
    b: float,
    gamma: float = 1.0,
    initial_alpha: float = 1.0,
    negative_sample_rate: int = 5,
    fit_mode: bool = True,
    seed: int = 0,
) -> jax.Array:
    """Parallel epoch-scheduled SGD over the fuzzy graph (umap-learn's
    optimize_layout_euclidean force model and epochs_per_sample schedule,
    applied to all due edges at once).

    Edges live in the dense [n, k] kNN layout, so the head-side update is a
    plain per-row reduction and the tail-side update is a gather through the
    capped inverse adjacency, plus one small scatter-add for the few
    hub-overflow edges (see _inverse_adjacency) — the full-edge-set scatter
    never touches the TPU.

    `fit_mode=True`: tails index the OPTIMIZED embedding and both edge ends
    move. `fit_mode=False` (transform): tails index the frozen `ref`."""
    n, k = tail_idx.shape
    c = Y0.shape[1]
    E = n * k
    w_max = jnp.max(weights)
    eps_per_sample = jnp.where(weights > 0, w_max / jnp.maximum(weights, 1e-12), jnp.inf)

    def clip(g):
        return jnp.clip(g, -4.0, 4.0)

    def epoch(e, state):
        Y, next_due = state
        ef = e.astype(Y.dtype)
        alpha = initial_alpha * (1.0 - ef / n_epochs)
        due = next_due <= ef  # [n, k]
        key = jax.random.fold_in(jax.random.PRNGKey(seed), e)

        tails = Y if fit_mode else ref
        yh = Y[:, None, :]  # [n, 1, c]
        yt = tails[tail_idx]  # [n, k, c]
        diff = yh - yt
        d2 = jnp.sum(diff * diff, axis=2)  # [n, k]
        # attraction: d/dy of the a,b membership curve — the d2^(b-1) factor
        # (negative exponent for the default b≈0.9) needs a zero guard, not an
        # exponent clamp, to keep the true force model
        d2_safe = jnp.where(d2 > 0, d2, 1.0)
        att = (-2.0 * a * b * d2_safe ** (b - 1.0)) / (1.0 + a * d2**b)
        att = jnp.where(d2 > 0, att, 0.0)
        g_att = clip(att[..., None] * diff) * jnp.where(due, 1.0, 0.0)[..., None]  # [n, k, c]
        delta = alpha * jnp.sum(g_att, axis=1)  # head side: per-row reduction
        if fit_mode:
            # tail side: gather the per-edge grads through the capped
            # inverse adjacency (out-of-range pad ids → zero row), plus one
            # small scatter-add for hub-overflow edges past the cap
            g_flat = jnp.concatenate(
                [g_att.reshape(E, c), jnp.zeros((1, c), Y.dtype)], axis=0
            )
            delta = delta - alpha * jnp.sum(g_flat[inv_idx], axis=1)
            if ov_idx is not None and ov_idx.shape[0]:
                t_ov = tail_idx.reshape(-1)[ov_idx]
                delta = delta.at[t_ov].add(-alpha * g_flat[ov_idx])

        # repulsion: negative samples drawn from the tail set
        m = tails.shape[0]
        neg = jax.random.randint(key, (n, k, negative_sample_rate), 0, m)
        yn = tails[neg]  # [n, k, S, c]
        diff_n = yh[:, None, :, :] - yn
        d2n = jnp.sum(diff_n * diff_n, axis=3)  # [n, k, S]
        rep = (2.0 * gamma * b) / ((0.001 + d2n) * (1.0 + a * d2n**b))
        g_rep = clip(rep[..., None] * diff_n)
        # coincident-but-distinct points repel with the clip bound; a point
        # drawn as its own negative contributes nothing (umap-learn skips it)
        g_rep = jnp.where(d2n[..., None] > 0, g_rep, 4.0)
        if fit_mode:
            self_hit = neg == jnp.arange(n)[:, None, None]
            g_rep = jnp.where(self_hit[..., None], 0.0, g_rep)
        g_rep = g_rep * jnp.where(due, 1.0, 0.0)[..., None, None]
        delta = delta + alpha * jnp.sum(g_rep, axis=(1, 2))

        next_due = jnp.where(due, next_due + eps_per_sample, next_due)
        return Y + delta, next_due

    Y, _ = jax.lax.fori_loop(0, n_epochs, epoch, (Y0, eps_per_sample - 1.0))
    return Y


def default_n_epochs(n: int) -> int:
    return 500 if n <= 10000 else 200


def _self_first(idx: np.ndarray, dist: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Normalize a kNN graph so column 0 is the point itself at distance 0
    (ties can reorder equal-distance neighbors; precomputed graphs may omit
    self entirely — then the farthest slot is sacrificed)."""
    n, k = idx.shape
    row = np.arange(n)
    self_pos = np.argmax(idx == row[:, None], axis=1)
    has_self = (idx == row[:, None]).any(axis=1)
    for i in np.flatnonzero(~has_self):  # degenerate duplicates / no self
        idx[i, -1] = i
        dist[i, -1] = 0.0
        self_pos[i] = k - 1
    idx[row, self_pos], idx[:, 0] = idx[:, 0].copy(), row
    dist[row, self_pos], dist[:, 0] = dist[:, 0].copy(), 0.0
    return idx, dist


def build_knn_graph(
    x: np.ndarray, n_neighbors: int, mesh, batch_queries: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact kNN graph incl. self in column 0: ([n, k] idx, [n, k] dist).

    The graph build runs on the shared tiled distance core (ops/distance.py
    via exact_knn); `batch_queries` defaults to
    ``config["distance_tile_rows"]``."""
    from ..parallel.mesh import make_global_rows
    from .knn import exact_knn

    from jax import device_put

    xf = np.ascontiguousarray(x, dtype=np.float32)
    X, w, _ = make_global_rows(mesh, xf)
    Q = device_put(xf)
    dist, idx = exact_knn(X, w > 0, Q, mesh=mesh, k=n_neighbors, batch_queries=batch_queries)
    # writable copies: self-normalized below
    return _self_first(np.array(idx), np.array(dist, dtype=np.float32))


def categorical_intersection(
    weights: np.ndarray, knn_idx: np.ndarray, labels: np.ndarray, far_dist: float = 5.0
) -> np.ndarray:
    """Supervised fit: intersect the fuzzy set with the label metric —
    different-label edges are downweighted by exp(-far_dist) (umap-learn's
    categorical_simplicial_set_intersection with unknown labels untouched)."""
    lab_i = labels[:, None]
    lab_j = labels[knn_idx]
    known = ~(np.isnan(lab_i) | np.isnan(lab_j))
    differ = known & (lab_i != lab_j)
    return np.where(differ, weights * np.exp(-far_dist), weights).astype(weights.dtype)


def umap_fit(
    x: np.ndarray,
    y: Optional[np.ndarray],
    *,
    mesh,
    n_neighbors: int = 15,
    n_components: int = 2,
    n_epochs: Optional[int] = None,
    learning_rate: float = 1.0,
    init: str = "spectral",
    min_dist: float = 0.1,
    spread: float = 1.0,
    set_op_mix_ratio: float = 1.0,
    local_connectivity: float = 1.0,
    repulsion_strength: float = 1.0,
    negative_sample_rate: int = 5,
    a: Optional[float] = None,
    b: Optional[float] = None,
    random_state: Optional[int] = None,
    precomputed_knn: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    metric: str = "euclidean",
) -> Dict[str, np.ndarray]:
    """Full UMAP fit; returns {'embedding_': [n, c]} plus graph internals.

    `precomputed_knn` is the reference's (knn_indices, knn_dists) pair
    (umap.py `precomputed_knn` param → cuML): [n, >=k] arrays over THESE
    rows; the graph build is skipped and the arrays are self-normalized and
    truncated to k columns.

    metric="cosine": rows are unit-normalized, the graph is built with the
    euclidean kernel (identical neighbor RANKING on unit vectors) and the
    stored distances become cosine distances via d_cos = d²/2 — so
    smooth-kNN bandwidths live in the metric's own scale, umap-learn
    semantics. Only the graph stage sees the metric; the layout SGD is
    metric-free."""
    if metric not in ("euclidean", "cosine"):
        raise ValueError(f"metric must be 'euclidean' or 'cosine', got {metric!r}")
    if metric == "cosine":
        from ..utils import unit_rows

        x = unit_rows(x)
    n = x.shape[0]
    k = min(n_neighbors, n)
    seed = int(random_state if random_state is not None else 0)
    if a is None or b is None:
        a, b = find_ab_params(spread, min_dist)
    n_epochs = int(n_epochs) if n_epochs else default_n_epochs(n)

    if precomputed_knn is not None:
        pre_idx, pre_dist = precomputed_knn
        pre_idx = np.asarray(pre_idx)
        pre_dist = np.asarray(pre_dist, dtype=np.float32)
        if pre_idx.shape != pre_dist.shape or pre_idx.shape[0] != n or pre_idx.shape[1] < k:
            raise ValueError(
                f"precomputed_knn must be ([n, >=k], [n, >=k]) over the fit rows; "
                f"got {pre_idx.shape}/{pre_dist.shape} for n={n}, k={k}"
            )
        # self in column 0 plus the k-1 NEAREST non-self entries — a plain
        # swap-then-truncate would teleport the displaced column past k and
        # silently drop each row's nearest neighbor whenever self was missing
        # or sat at a column >= k. Augment with a -1-distance self column
        # (beats every real distance), neutralize any user-supplied self
        # duplicates at +inf, and keep the k best by a stable row sort.
        rows = np.arange(n)
        dist_m = np.where(pre_idx == rows[:, None], np.inf, pre_dist)
        idx_aug = np.concatenate([rows[:, None].astype(pre_idx.dtype), pre_idx], axis=1)
        dist_aug = np.concatenate(
            [np.full((n, 1), -1.0, np.float32), dist_m.astype(np.float32)], axis=1
        )
        order = np.argsort(dist_aug, axis=1, kind="stable")[:, :k]
        knn_idx = np.take_along_axis(idx_aug, order, axis=1)
        knn_dist = np.take_along_axis(dist_aug, order, axis=1)
        knn_dist[:, 0] = 0.0  # the augmented self column
    else:
        knn_idx, knn_dist = build_knn_graph(x, k, mesh)
        if metric == "cosine":
            knn_dist = (knn_dist * knn_dist) / 2.0  # unit rows: 1 - cosθ
    rho, sigma = smooth_knn(jnp.asarray(knn_dist), local_connectivity)
    w = np.asarray(fuzzy_simplicial_set(
        jnp.asarray(knn_idx), jnp.asarray(knn_dist), rho, sigma, set_op_mix_ratio
    ))
    if y is not None:
        w = categorical_intersection(w, knn_idx, np.asarray(y, dtype=np.float64))

    if init == "spectral":
        Y0 = spectral_init(knn_idx, w, n_components, seed)
    else:
        Y0 = np.random.default_rng(seed).uniform(-10, 10, (n, n_components)).astype(np.float32)

    # umap-learn drops edges below max_w/n_epochs before optimization
    w_opt = np.where(w >= w.max() / float(n_epochs), w, 0.0)
    tail = knn_idx.astype(np.int32)
    inv, ov = _inverse_adjacency(tail, n)
    Y0j = jnp.asarray(Y0)
    Y = optimize_embedding(
        Y0j, Y0j, jnp.asarray(tail), jnp.asarray(w_opt),
        jnp.asarray(inv), jnp.asarray(ov),
        n_epochs=n_epochs, a=float(a), b=float(b), gamma=float(repulsion_strength),
        initial_alpha=float(learning_rate), negative_sample_rate=int(negative_sample_rate),
        fit_mode=True, seed=seed,
    )
    return {
        "embedding_": np.asarray(Y, dtype=np.float32),
        "a_": np.float64(a),
        "b_": np.float64(b),
    }


def umap_transform(
    x_new: np.ndarray,
    raw_data: np.ndarray,
    embedding: np.ndarray,
    *,
    mesh,
    n_neighbors: int = 15,
    n_epochs: Optional[int] = None,
    learning_rate: float = 1.0,
    local_connectivity: float = 1.0,
    repulsion_strength: float = 1.0,
    negative_sample_rate: int = 5,
    a: float = 1.577,
    b: float = 0.895,
    random_state: Optional[int] = None,
    metric: str = "euclidean",
) -> np.ndarray:
    """Embed NEW points against a fitted model: kNN into the training set,
    smooth-kNN weights, init at the weighted mean of neighbor embeddings, then
    a short optimization against the FROZEN training embedding (umap-learn
    transform semantics). metric="cosine" matches the fit-side convention
    (unit-normalize both sides, d_cos = d²/2)."""
    from ..parallel.mesh import make_global_rows
    from .knn import exact_knn

    x_new = np.ascontiguousarray(x_new, dtype=np.float32)
    raw_data = np.ascontiguousarray(raw_data, dtype=np.float32)
    if metric == "cosine":
        from ..utils import unit_rows

        x_new = np.ascontiguousarray(unit_rows(x_new))
        raw_data = np.ascontiguousarray(unit_rows(raw_data))
    n_new = x_new.shape[0]
    k = min(n_neighbors, raw_data.shape[0])
    seed = int(random_state if random_state is not None else 0)

    X, w_mask, _ = make_global_rows(mesh, raw_data)
    dist, idx = exact_knn(X, w_mask > 0, jax.device_put(x_new), mesh=mesh, k=k)
    dist = np.asarray(dist, np.float32)
    if metric == "cosine":
        dist = (dist * dist) / 2.0
    idx = np.asarray(idx)

    rho, sigma = smooth_knn(jnp.asarray(dist), local_connectivity)
    wgt = np.asarray(jnp.exp(-jnp.maximum(jnp.asarray(dist) - np.asarray(rho)[:, None], 0.0)
                             / np.asarray(sigma)[:, None]))
    wsum = np.maximum(wgt.sum(axis=1, keepdims=True), 1e-12)
    Y0 = (wgt[:, :, None] * embedding[idx]).sum(axis=1) / wsum

    # umap-learn's transform schedule: explicit n_epochs runs a third of it
    # (int(n_epochs // 3.0), no floor — 0 epochs returns the weighted-mean
    # init); defaulted n_epochs runs a fixed 100 epochs when the TRANSFORMED
    # set has <= 10000 rows, 30 otherwise
    if n_epochs is not None:
        total_epochs = int(n_epochs) // 3
    else:
        total_epochs = 100 if n_new <= 10000 else 30
    Y = optimize_embedding(
        jnp.asarray(Y0.astype(np.float32)), jnp.asarray(embedding.astype(np.float32)),
        jnp.asarray(idx.astype(np.int32)), jnp.asarray(wgt.astype(np.float32)), None,
        n_epochs=total_epochs, a=float(a), b=float(b), gamma=float(repulsion_strength),
        initial_alpha=float(learning_rate), negative_sample_rate=int(negative_sample_rate),
        fit_mode=False, seed=seed,
    )
    return np.asarray(Y, dtype=np.float32)
