#
# Solver library: pure-JAX SPMD programs over the `rows` mesh axis.
#
# This package is the in-tree replacement for the external cuML MG C++/CUDA
# solvers the reference imports (SURVEY.md L3): every solver consumes
# row-sharded global `jax.Array`s plus a zero-on-padding weight vector, and its
# cross-chip reductions are XLA collectives inserted by GSPMD (with `shard_map`
# where the collective pattern must be explicit). Everything is jit-compiled:
# static shapes, `lax` control flow, bf16/f32 matmuls on the MXU.
#
