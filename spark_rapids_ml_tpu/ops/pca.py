#
# Distributed PCA solver — the in-tree replacement for `cuml.decomposition.
# pca_mg.PCAMG` (consumed by reference feature.py:220-241).
#
# Algorithm (single pass + local eig, the same math cuML MG runs):
#   1. weighted mean + covariance of the row-sharded X — one fused MXU
#      contraction per shard, GSPMD psum across the `rows` mesh axis
#      (the NCCL-allreduce-of-covariance equivalent);
#   2. replicated d×d symmetric eigendecomposition, top-k descending;
#   3. sign canonicalization (reference signFlip kernel parity,
#      rapidsml_jni.cu:35-61).
#
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from .. import telemetry
from .linalg import sign_flip, topk_eigh_desc, weighted_cov


def check_pca_state(state: Dict, *, k: int) -> Dict:
    """Divergence guard on a HOST-fetched PCA state (callers pass the state
    after model-attribute conversion, so no extra device sync): the one-shot
    eigendecomposition has no iterations, but non-finite input rows surface
    as NaN covariance -> NaN components/variances. Raises SolverDivergedError
    (iteration 0 — `n_iter_` is absent from a single-shot solver's state)
    keeping the finite attributes as the last-good payload; returns `state`
    untouched otherwise. One shared guard implementation for every solver
    family (ops/owlqn.check_solver_state)."""
    from .owlqn import check_solver_state

    return check_solver_state(
        "pca", state,
        scalars=(),
        arrays=("components_", "explained_variance_", "mean_"),
    )


def record_pca_fit(state: Dict[str, jax.Array], *, k: int) -> None:
    """Host-side telemetry for a completed `pca_fit` (the solver itself is one
    jitted program — no iterations to trace): fit counter plus the captured
    variance ratio, the solver's single convergence-quality scalar. Callers
    pass the state AFTER fetching it to host (model-attribute conversion), so
    this forces no extra device sync."""
    if not telemetry.enabled():
        return
    import numpy as np

    reg = telemetry.registry()
    reg.inc("pca.fits")
    reg.gauge("pca.n_components", k)
    reg.gauge(
        "pca.explained_variance_ratio_sum",
        float(np.sum(np.asarray(state["explained_variance_ratio_"]))),
    )


@partial(jax.jit, static_argnames=("k", "fast"))
def pca_fit(X: jax.Array, w: jax.Array, *, k: int, fast: bool = False) -> Dict[str, jax.Array]:
    """Fit PCA on a row-sharded global X with padding/sample weights w.

    Returns the model-state dict matching the reference's model attributes
    (reference feature.py:250-257): mean_, components_, explained_variance_,
    explained_variance_ratio_, singular_values_. `components_` rows are always
    unit-norm (cuML/sklearn store unwhitened components; whitening is applied
    at transform time). `fast` runs the covariance contraction bf16-in /
    f32-accumulate (linalg.weighted_cov); the eigendecomposition and every
    reported variance stay full precision.
    """
    total_w, mean, cov = weighted_cov(X, w, ddof=1, fast=fast)
    # one shared finish kernel with the checkpointed path (stats -> model),
    # so the two entry points cannot drift
    return _pca_finish(total_w, mean, cov, k=k)


@partial(jax.jit, static_argnames=("fast",))
def _pca_stats(X: jax.Array, w: jax.Array, fast: bool = False):
    return weighted_cov(X, w, ddof=1, fast=fast)


@partial(jax.jit, static_argnames=("k",))
def _pca_finish(total_w, mean, cov, *, k: int) -> Dict[str, jax.Array]:
    evals, comps = topk_eigh_desc(cov, k)
    evals = jnp.maximum(evals, 0.0)
    comps = sign_flip(comps)
    total_var = jnp.trace(cov)
    ratio = evals / total_var
    singular_values = jnp.sqrt(evals * (total_w - 1.0))
    return {
        "mean_": mean,
        "components_": comps,
        "explained_variance_": evals,
        "explained_variance_ratio_": ratio,
        "singular_values_": singular_values,
    }


def pca_fit_checkpointed(
    X: jax.Array, w: jax.Array, *, k: int, fast: bool = False,
    ckpt_key: str = "pca_stats", placement_key=None,
) -> Dict[str, jax.Array]:
    """`pca_fit` with the sufficient statistics — weighted (total_w, mean,
    covariance), the output of the ONE distributed data pass — retained on
    host in the active `CheckpointStore` (docs/robustness.md "Elastic
    recovery"). A transient retry (or a k sweep in the same fit stage)
    re-runs only the replicated d×d eigendecomposition from the retained
    statistics; the data pass is never repeated (``checkpoint.stats_reuses``).
    Identical math to `pca_fit`: same stats kernel, same finish kernel."""
    import numpy as np

    from .. import checkpoint as _ckpt
    from ..parallel import chaos

    store = _ckpt.active_store()
    if fast:
        # bf16 statistics are keyed apart: a bf16 pass must never be
        # resumed from (or serve) a full-precision one
        ckpt_key = ckpt_key + ":bf16"

    def compute() -> Dict:
        total_w, mean, cov = _pca_stats(X, w, fast=fast)
        return {
            "total_w": np.asarray(total_w),
            "mean": np.asarray(mean),
            "cov": np.asarray(cov),
        }

    if store is not None:
        state = store.get_or_compute(
            ckpt_key, compute, solver="pca", placement_key=placement_key
        )
    else:
        state = compute()
    chaos.maybe_fail_stage("solve", 0)  # after retention: retries reuse stats
    dtype = X.dtype
    return _pca_finish(
        jnp.asarray(state["total_w"], dtype),
        jnp.asarray(state["mean"], dtype),
        jnp.asarray(state["cov"], dtype),
        k=k,
    )


@partial(jax.jit, static_argnames=("whiten",))
def pca_transform(
    X: jax.Array, components: jax.Array, explained_variance: jax.Array, *, whiten: bool = False
) -> jax.Array:
    """Project rows onto the principal axes WITHOUT mean-centering.

    Spark ML's PCA.transform does not center; cuML's does, and the reference
    undoes cuML's centering by adding the mean back (reference
    feature.py:426-438). Net effect there — and the contract here — is
    ``X @ componentsᵀ`` (scaled by 1/√eigenvalue when whitening).
    """
    T = X @ components.T
    if whiten:
        T = T * jax.lax.rsqrt(jnp.maximum(explained_variance, 1e-30))
    return T
