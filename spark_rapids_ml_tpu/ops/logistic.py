#
# Distributed logistic regression solver — the in-tree replacement for
# `cuml.linear_model.logistic_regression_mg.LogisticRegressionMG` (the L-BFGS
# "qn" solver consumed by reference classification.py:1051-1057).
#
# Design: the whole fit is ONE jitted program over the row-sharded X:
#  * standardization stats (weighted mean/var) are psum'd in-graph — the
#    reference's hand-rolled CuPy allgather pre-standardization
#    (classification.py:984-1089) collapses into two einsum+psum lines, and the
#    scaling is folded INTO the coefficients (logits = X @ (D·B) + (b0 − μᵀD·B))
#    so no standardized copy of X is ever materialized in HBM;
#  * L-BFGS (memory=10, zoom linesearch — optax) runs inside a lax.while_loop;
#    each objective/gradient evaluation is a fused MXU matmul + psum over the
#    mesh, the NCCL-allreduce-per-iteration of the reference;
#  * binomial (sigmoid, coef [1,d]) and multinomial (softmax, coef [k,d]) with
#    Spark's multinomial intercept centering (classification.py:1077-1089).
#
# Objective (Spark semantics): (Σ wᵢ·logloss_i)/Σw + λ·[(1−α)/2·‖B_std‖² +
# α·‖B_std‖₁] with the penalty applied in standardized space when
# standardization=True and never to intercepts. The smooth part (logloss + L2)
# goes through optax L-BFGS when α·λ=0 and through the in-tree OWL-QN solver
# (ops/owlqn.py — the same Andrew & Gao 2007 algorithm behind cuML's qn
# `penalty='l1'/'elasticnet'`, reference classification.py:1051-1057) when the
# L1 term is active.
#
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from .. import telemetry
from .linalg import weighted_moments


def _make_scaling(X, w, standardize: bool, fit_intercept: bool):
    """Returns (mu [d], d_scale [d]): logits use Beff = d_scale·B, offset −μ·Beff."""
    total_w, mean, var = weighted_moments(X, w)
    if not standardize:
        return jnp.zeros_like(mean), jnp.ones_like(mean), total_w
    sigma = jnp.sqrt(var * (total_w / jnp.maximum(total_w - 1.0, 1.0)))  # unbiased, Spark summarizer
    d_scale = jnp.where(sigma > 0, 1.0 / jnp.maximum(sigma, 1e-30), 0.0)
    mu = mean if fit_intercept else jnp.zeros_like(mean)
    return mu, d_scale, total_w


def _glm_qn_setup(
    z_of, rowloss, rowloss_alphas, grad_from_z, z_shape, n_flat: int, dtype,
    penalty_terms, max_iter: int, tol: float, memory: int = 10,
    n_alphas: int = 12, c1: float = 1e-4, x0=None,
):
    """L-BFGS specialized to GLM objectives: loss(p) = rowloss(z_of(p)) +
    penalty(p) with z LINEAR in p. Builds and returns the loop triple
    ``(cond, body, state0)`` — shared verbatim by the one-program
    `_glm_qn_minimize` path and the host-segmented checkpointing driver
    (`glm_qn_minimize_segmented`). `x0` warm-starts the iterate (the
    degraded-mesh portable resume; z0/g0/f0 are re-derived from it).

    Two structural exploits of linearity keep every iteration at TWO passes
    over the data matrix (the HBM-bandwidth floor for a logit model):
      1. Line search: along direction D the logits are z(p + a·D) = z_p + a·z_D,
         so ALL candidate step sizes are scored elementwise from one new matmul
         result (z_D) — no inner while_loop touches X. cuML's qn does the same;
         it also avoids the XLA pattern where a loss evaluated inside a NESTED
         while loop costs a full copy of X (11 GiB at 1M x 3k, measured).
      2. Gradients: z at the accepted point is z_p + a·z_D (free), and the
         gradient is computed ANALYTICALLY from it as Xᵀ·(∂loss/∂z) via the
         caller's `grad_from_z` — autodiff re-evaluating the forward would
         re-read X twice more per iteration.

    Interfaces (all jax-traceable):
      z_of(flat_params [F]) -> z [n, k_out]             (linear)
      rowloss(z) -> scalar                               (data term)
      rowloss_alphas(z_p, z_d, alphas [S]) -> [S]        (data term at p + a·d)
      grad_from_z(flat_p, z) -> flat grad [F]            (incl. penalty grad)
      penalty_terms(flat_p, flat_d) -> (p0, p1, p2)      (penalty(p + a·d) =
                                                          p0 + a·p1 + a²·p2)
    Returns (flat_params, objective, n_iter, stalled) — `stalled` is True when
    the run ended because the batched Armijo check found NO acceptable step
    (see the KNOWN LIMIT note below), not because tol/maxIter was reached.
    """
    m = memory
    # step candidates: one growth step, unit step, then geometric backtracking.
    # KNOWN LIMIT (documented, matches the reference's practical envelope): on
    # badly-scaled UNSTANDARDIZED problems whose minimizer sits at |coef|>>1
    # (e.g. raw 0.1%-density features), per-step objective improvements fall
    # below the f32 mean-loss reduction noise at ~1e6+ rows and the Armijo
    # stall check fires early. Spark/cuML standardize by default, and the
    # sparse path's scale-only standardization restores conditioning without
    # densifying — certified by tests/test_large_sparse.py at 1e7 x 2200.
    alphas = jnp.asarray([2.0] + [0.5 ** i for i in range(n_alphas - 1)], jnp.float32)

    from .owlqn import freeze_when_done, lbfgs_two_loop

    # Per-iteration convergence trace (telemetry): gated at TRACE time — the
    # host callback is free on CPU but a dispatch round-trip through a remote
    # TPU tunnel per L-BFGS iteration, so it only exists in programs traced
    # while SRML_TRACE_CONVERGENCE / enable(convergence=True) was active.
    trace_convergence = telemetry.convergence_trace_enabled()  # traced-ok: the TRACE-TIME gate by design — callbacks exist only in programs traced while convergence tracing was on (docs/observability.md)

    def cond(state):
        _, _, _, _, _, _, _, f_prev, f_cur, it, stalled = state
        rel = jnp.abs(f_prev - f_cur) / jnp.maximum(jnp.abs(f_cur), 1.0)
        return jnp.logical_and(jnp.logical_and(it < max_iter, rel > tol), ~stalled)

    def body(state):
        x, z_p, g, S, Y, rho, meta, f_prev, f_cur, it, _ = state
        count, pos = meta
        d = lbfgs_two_loop(g, S, Y, rho, count, pos, m)
        # fall back to steepest descent if the direction isn't a descent one
        gd = jnp.dot(g, d)
        d = jnp.where(gd < 0, d, -g)
        # true directional derivative: g·d when the L-BFGS direction is kept,
        # -g·g only in the steepest-descent fallback branch
        gd = jnp.where(gd < 0, gd, -jnp.dot(g, g))
        # batched Armijo over all candidates from ONE new logit evaluation
        z_d = z_of(d)  # linear => z(x + a d) = z_p + a z_d     [X read 1]
        p0, p1, p2 = penalty_terms(x, d)
        a = alphas.astype(x.dtype)
        f_cand = rowloss_alphas(z_p, z_d, a) + p0 + a * p1 + a * a * p2
        ok_mask = f_cand <= f_cur + c1 * a * gd
        # LARGEST passing step (alphas sorted descending)
        first_ok = jnp.argmax(ok_mask)
        ok = jnp.any(ok_mask)
        a_sel = a[first_ok]
        f_new = f_cand[first_ok]
        xn = x + a_sel * d
        z_n = z_p + a_sel * z_d  # logits at the accepted point, no X pass
        gn = grad_from_z(xn, z_n)  # analytic Xᵀ·residual          [X read 2]
        s = xn - x
        yv = gn - g
        sy = jnp.dot(s, yv)
        do_update = ok & (sy > 1e-10)
        S = jnp.where(do_update, S.at[pos].set(s), S)
        Y = jnp.where(do_update, Y.at[pos].set(yv), Y)
        rho = jnp.where(do_update, rho.at[pos].set(1.0 / jnp.maximum(sy, 1e-30)), rho)
        count = jnp.where(do_update, jnp.minimum(count + 1, m), count)
        pos = jnp.where(do_update, (pos + 1) % m, pos)
        x = jnp.where(ok, xn, x)
        z_p = jnp.where(ok, z_n, z_p)
        g = jnp.where(ok, gn, g)
        f_out = jnp.where(ok, f_new, f_cur)
        if trace_convergence:
            jax.debug.callback(
                partial(telemetry.record_convergence_point, "glm_qn"), it, f_out
            )
        return x, z_p, g, S, Y, rho, (count, pos), f_cur, f_out, it + 1, ~ok

    if x0 is None:
        x0 = jnp.zeros((n_flat,), dtype)
        z0 = jnp.zeros(z_shape, dtype)  # z_of(0) == 0: z is linear with no constant
    else:
        x0 = jnp.asarray(x0, dtype)
        z0 = z_of(x0)
    g0 = grad_from_z(x0, z0)
    p00, _, _ = penalty_terms(x0, jnp.zeros_like(x0))
    f0 = rowloss(z0) + p00
    state0 = (
        x0, z0, g0,
        jnp.zeros((m, n_flat), x0.dtype), jnp.zeros((m, n_flat), x0.dtype),
        jnp.zeros((m,), x0.dtype),
        (jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32)),
        jnp.asarray(jnp.inf, x0.dtype), f0, jnp.asarray(0, jnp.int32), jnp.asarray(False),
    )
    return cond, body, state0


def _glm_qn_minimize(
    z_of, rowloss, rowloss_alphas, grad_from_z, z_shape, n_flat: int, dtype,
    penalty_terms, max_iter: int, tol: float, memory: int = 10,
    n_alphas: int = 12, c1: float = 1e-4, x0=None,
):
    """One-program GLM quasi-Newton minimization (see `_glm_qn_setup` for
    the algorithm and its two structural exploits of linearity). `x0`
    warm-starts the iterate (the public warm_start_from API). Returns
    (flat_params, objective, n_iter, stalled)."""
    from .owlqn import freeze_when_done

    cond, body, state0 = _glm_qn_setup(
        z_of, rowloss, rowloss_alphas, grad_from_z, z_shape, n_flat, dtype,
        penalty_terms, max_iter, tol, memory, n_alphas, c1, x0=x0,
    )
    # freeze_when_done makes the loop vmap-safe: batched hyperparameter
    # sweeps (vmap over lam_l2/lam_l1) step until the SLOWEST grid element
    # converges, and converged elements must hold their iterate exactly
    x, _, _, _, _, _, _, _, obj, n_iter, stalled = jax.lax.while_loop(
        cond, freeze_when_done(cond, body), state0
    )
    return x, obj, n_iter, stalled


def glm_qn_minimize_segmented(
    z_of, rowloss, rowloss_alphas, grad_from_z, z_shape, n_flat: int, dtype,
    penalty_terms, max_iter: int, tol: float, memory: int = 10,
    n_alphas: int = 12, c1: float = 1e-4, *,
    ckpt_key: str = "glm_qn", placement_key=None, x0=None,
):
    """`_glm_qn_minimize` with the one big ``lax.while_loop`` segmented into
    outer HOST segments of ``config["checkpoint_every_iters"]`` inner
    iterations: each boundary host-fetches the full solver state — the
    iterate x, its logits z_p, the gradient, the circular L-BFGS (S, Y, rho)
    memory, and n_iter — into the active `CheckpointStore` so an interrupted
    fit resumes there instead of from scratch. The segment body is the SAME
    traced body and the boundary round-trip is lossless, so a same-mesh
    resume is bit-identical to an uninterrupted segmented run (pinned by
    tests/test_recovery.py). When a checkpoint's shapes no longer match (a
    survivor re-mesh changed n), the PORTABLE subset — the iterate x — warm-
    starts a fresh loop with re-derived logits/gradient: deterministic given
    the survivor set."""
    import numpy as np

    from .. import checkpoint as _ckpt

    store = _ckpt.active_store()
    x_warm = x0  # user warm start (warm_start_from); checkpoints override
    if store is not None:
        saved = store.peek(ckpt_key)
        if saved is not None and saved.placement_key != placement_key:
            # degraded-mesh resume: leaf shapes changed with the data, but
            # the iterate is mesh-independent — warm-start from it
            x_saved = saved.portable.get("x")
            if x_saved is not None and np.shape(x_saved) == (n_flat,):
                x_warm = x_saved
                store.load(ckpt_key)  # count the (portable) restore
    cond, body, state0 = _glm_qn_setup(
        z_of, rowloss, rowloss_alphas, grad_from_z, z_shape, n_flat, dtype,
        penalty_terms, max_iter, tol, memory, n_alphas, c1, x0=x_warm,
    )
    every = _ckpt.every_iters() or max_iter

    def _save_portable(state):  # ride the generic driver's save with x
        return {"x": np.asarray(state[0])}

    state = _ckpt.run_segmented_while(
        cond, body, state0,
        it_of=lambda s: s[9],  # (x, z_p, g, S, Y, rho, meta, f_prev, f_cur, IT, stalled)
        every=every,
        store=store,
        key=ckpt_key,
        solver="glm_qn",
        placement_key=placement_key,
        max_iter=max_iter,
        portable_of=_save_portable,
    )
    x, _, _, _, _, _, _, _, obj, n_iter, stalled = state
    return x, obj, n_iter, stalled


def check_glm_result(state: Dict, *, solver: str = "logistic") -> Dict:
    """Divergence guard for a fetched GLM fit state: piggybacks on the final
    objective/coef scalars the model layer converts to host anyway (the
    jitted while_loop exposes no per-iteration scalar to watch). Raises
    `SolverDivergedError` (with iteration count and the finite remainder of
    the state as last-good) on NaN/Inf; returns `state` otherwise. Shared by
    the dense and ELL fit call sites (models/classification.py)."""
    from .owlqn import check_solver_state

    return check_solver_state(solver, state)


def warn_if_early_stall(state: Dict, *, standardize: bool, max_iter: int, logger=None) -> bool:
    """Host-side signal for the KNOWN LIMIT above: when the Armijo stall check
    ended an UNSTANDARDIZED fit well before maxIter/tol, the returned model is
    silently under-converged — warn and point at standardization=True (the
    sparse path's scale-only standardization restores conditioning without
    densifying). Returns whether the warning fired; shared by the dense and
    ELL fit wrappers' callers (models/classification.py)."""
    stalled = bool(np.asarray(state.get("stalled_", False)))
    n_iter = int(np.asarray(state.get("n_iter_", 0)))
    if not stalled or standardize or n_iter >= max_iter:
        return False
    if logger is None:
        from ..utils import get_logger

        logger = get_logger("LogisticRegression")
    logger.warning(
        "L-BFGS line search stalled after %d/%d iterations on an "
        "unstandardized fit — the model may be under-converged. Badly scaled "
        "features shrink per-step objective improvements below f32 noise; "
        "set standardization=True (sparse fits standardize scale-only, "
        "preserving sparsity).",
        n_iter, max_iter,
    )
    return True


def _lbfgs_minimize(loss, params0, max_iter: int, tol: float, memory: int = 10):
    """L-BFGS in a lax.while_loop; converges on relative objective decrease
    (the qn-solver criterion the reference relies on)."""
    import optax.tree_utils as otu

    opt = optax.lbfgs(memory_size=memory)
    value_and_grad = optax.value_and_grad_from_state(loss)

    def cond(carry):
        _, _, prev, cur, it = carry
        rel = jnp.abs(prev - cur) / jnp.maximum(jnp.abs(cur), 1.0)
        return jnp.logical_and(it < max_iter, rel > tol)

    def body(carry):
        params, state, _, cur, it = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=loss
        )
        params = optax.apply_updates(params, updates)
        # the zoom linesearch evaluated the loss at the NEW params; read it from
        # the optimizer state so the convergence check compares new vs old
        new_value = otu.tree_get(state, "value")
        return params, state, cur, new_value, it + 1

    state0 = opt.init(params0)
    v0 = loss(params0)
    params, state, _, obj, n_iter = jax.lax.while_loop(
        cond, body, (params0, state0, jnp.inf, v0, jnp.array(0, jnp.int32))
    )
    return params, obj, n_iter


@partial(
    jax.jit,
    static_argnames=(
        "k", "fit_intercept", "standardize", "max_iter", "lbfgs_memory", "multinomial", "use_l1",
        "fast",
    ),
)
def logistic_fit(
    X: jax.Array,
    y_idx: jax.Array,  # int32 class indices in [0, k)
    w: jax.Array,
    *,
    k: int,
    multinomial: bool,
    lam_l2: float,
    lam_l1: float = 0.0,
    use_l1: bool = False,  # static solver choice; lam_l1/lam_l2 stay traced so
    # hyperparameter sweeps (fitMultiple/CV) never recompile
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
    fast: bool = False,
    warm_start=None,  # (coef [k_out, d], intercept [k_out]) original-space seed
) -> Dict[str, jax.Array]:
    """Returns coef_ [k_out, d] and intercept_ [k_out] in ORIGINAL feature space
    (standardization folded out), plus objective_ and n_iter_. `warm_start`
    seeds the iterate from a previous model's coefficients (the public
    warm_start_from API, docs/scheduling.md "Warm starts"). `fast` runs the
    per-iteration matvecs bf16-in / f32-accumulate (`_dense_ops`)."""
    d = X.shape[1]
    mu, d_scale, total_w = _make_scaling(X, w, standardize, fit_intercept)
    matvec, rmat = _dense_ops(X, fast)
    return _fit_common(
        matvec, rmat, X.shape[0],
        X.dtype, d, y_idx, w, mu, d_scale, total_w,
        k=k, multinomial=multinomial, lam_l2=lam_l2, lam_l1=lam_l1, use_l1=use_l1,
        fit_intercept=fit_intercept, max_iter=max_iter, tol=tol, lbfgs_memory=lbfgs_memory,
        warm_start=warm_start,
    )


@partial(
    jax.jit,
    static_argnames=(
        "d", "k", "fit_intercept", "standardize", "max_iter", "lbfgs_memory", "multinomial",
        "use_l1", "fast",
    ),
)
def logistic_fit_ell(
    values: jax.Array,  # [n, k_max] ELL values (ops/sparse.py)
    indices: jax.Array,  # [n, k_max] int32 column indices
    y_idx: jax.Array,
    w: jax.Array,
    *,
    d: int,
    k: int,
    multinomial: bool,
    lam_l2: float,
    lam_l1: float = 0.0,
    use_l1: bool = False,
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
    fast: bool = False,
    warm_start=None,
) -> Dict[str, jax.Array]:
    """Sparse (padded-ELL) logistic fit. Standardization is SCALE-ONLY — the
    data is divided by the per-column std but never centered, preserving
    sparsity (the reference's sparse trick, classification.py:975-1098: cuML qn
    standardizes sparse input without mean subtraction). Coefficients return in
    original space; no mu offset is folded into the intercept."""
    mu, d_scale, total_w = _ell_scaling(values, indices, w, d, standardize)
    matvec, rmat = _ell_ops(values, indices, d, fast)
    return _fit_common(
        matvec, rmat, values.shape[0],
        values.dtype, d, y_idx, w, mu, d_scale, total_w,
        k=k, multinomial=multinomial, lam_l2=lam_l2, lam_l1=lam_l1, use_l1=use_l1,
        fit_intercept=fit_intercept, max_iter=max_iter, tol=tol, lbfgs_memory=lbfgs_memory,
        warm_start=warm_start,
    )


def _ell_scaling(values, indices, w, d: int, standardize: bool):
    """Scale-only standardization statistics for the padded-ELL layout:
    returns (mu=0, d_scale [d], total_w) — sparse data is never centered."""
    from .sparse import ell_col_moments

    if standardize:
        total_w, _, var = ell_col_moments(values, indices, w, d)
        sigma = jnp.sqrt(var * (total_w / jnp.maximum(total_w - 1.0, 1.0)))
        d_scale = jnp.where(sigma > 0, 1.0 / jnp.maximum(sigma, 1e-30), 0.0)
    else:
        total_w = jnp.sum(w)
        d_scale = jnp.ones((d,), values.dtype)
    mu = jnp.zeros((d,), values.dtype)  # scale-only: never centered
    return mu, d_scale, total_w


def _dense_ops(X, fast: bool = False):
    """(matvec, rmat) closures over dense X for `_fit_common`. ``fast``
    (solver_precision="bf16") runs the X·β forward and Xᵀr gradient matvecs
    — the two O(n·d) contractions every L-BFGS iteration pays twice — with
    bf16 inputs and f32 accumulation on the MXU; the L-BFGS state, line
    search, and convergence scalars downstream stay at the ambient
    precision (docs/performance.md "Mixed-precision solvers"; parity pinned
    by tests/test_precision.py)."""
    if not fast:
        return (lambda Beff: X @ Beff), (lambda r: X.T @ r)
    bX = X.astype(jnp.bfloat16)

    def matvec(Beff):
        return jax.lax.dot(
            bX, Beff.astype(jnp.bfloat16),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32,
        ).astype(X.dtype)

    def rmat(r):
        return jax.lax.dot(
            bX.T, r.astype(jnp.bfloat16),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32,
        ).astype(X.dtype)

    return matvec, rmat


def _ell_ops(values, indices, d: int, fast: bool = False):
    """(matvec, rmat) closures over the ELL layout for `_fit_common`.
    ``fast`` is the scatter-path analog of `_dense_ops`' bf16 contract:
    no MXU dot to cast, so the stored values are ROUNDED through bf16 once
    (bf16 inputs) while all accumulation stays at the ambient precision."""
    from .sparse import ell_matmul, ell_rmatvec

    gv = values.astype(jnp.bfloat16).astype(values.dtype) if fast else values

    def rmat(r):  # Xᵀ r via per-column ELL scatter
        return jnp.stack(
            [ell_rmatvec(gv, indices, r[:, j], d) for j in range(r.shape[1])],
            axis=1,
        )

    return (lambda Beff: ell_matmul(gv, indices, Beff)), rmat


@partial(
    jax.jit,
    static_argnames=(
        "k", "fit_intercept", "standardize", "max_iter", "lbfgs_memory", "multinomial", "use_l1",
        "fast",
    ),
)
def logistic_fit_batched(
    X: jax.Array,
    y_idx: jax.Array,
    w: jax.Array,
    lam_l2s: jax.Array,  # [S] per-grid-point L2 strengths
    lam_l1s: jax.Array,  # [S] per-grid-point L1 strengths
    *,
    k: int,
    multinomial: bool,
    use_l1: bool = False,
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
    fast: bool = False,
) -> Dict[str, jax.Array]:
    """ONE compiled program that solves a whole (lam_l2, lam_l1) grid.

    The regularization strengths are traced scalars of the objective, so the
    grid vmaps over them: XLA fuses the S per-model logit matmuls into one
    wider matmul per L-BFGS iteration — X is read TWICE PER ITERATION FOR THE
    WHOLE GRID instead of twice per iteration per model, and the grid pays
    max(iters) loop steps instead of sum(iters). Converged grid elements
    freeze exactly (`freeze_when_done`), so each returned model matches its
    sequential `logistic_fit` counterpart. Statics (use_l1, max_iter, ...)
    must be uniform across the grid — the model layer groups param sets by
    that signature and falls back to sequential solves otherwise.

    Returns the `logistic_fit` dict with a leading [S] axis on every entry."""
    d = X.shape[1]
    mu, d_scale, total_w = _make_scaling(X, w, standardize, fit_intercept)
    matvec, rmat = _dense_ops(X, fast)

    def fit_one(lam_l2, lam_l1):
        return _fit_common(
            matvec, rmat, X.shape[0],
            X.dtype, d, y_idx, w, mu, d_scale, total_w,
            k=k, multinomial=multinomial, lam_l2=lam_l2, lam_l1=lam_l1, use_l1=use_l1,
            fit_intercept=fit_intercept, max_iter=max_iter, tol=tol,
            lbfgs_memory=lbfgs_memory,
        )

    return jax.vmap(fit_one)(lam_l2s, lam_l1s)


@partial(
    jax.jit,
    static_argnames=(
        "d", "k", "fit_intercept", "standardize", "max_iter", "lbfgs_memory", "multinomial",
        "use_l1", "fast",
    ),
)
def logistic_fit_ell_batched(
    values: jax.Array,
    indices: jax.Array,
    y_idx: jax.Array,
    w: jax.Array,
    lam_l2s: jax.Array,
    lam_l1s: jax.Array,
    *,
    d: int,
    k: int,
    multinomial: bool,
    use_l1: bool = False,
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
    fast: bool = False,
) -> Dict[str, jax.Array]:
    """Sparse (padded-ELL) analog of `logistic_fit_batched`: one program for
    the whole grid, scale-only standardization computed once and shared."""
    mu, d_scale, total_w = _ell_scaling(values, indices, w, d, standardize)
    matvec, rmat = _ell_ops(values, indices, d, fast)

    def fit_one(lam_l2, lam_l1):
        return _fit_common(
            matvec, rmat, values.shape[0],
            values.dtype, d, y_idx, w, mu, d_scale, total_w,
            k=k, multinomial=multinomial, lam_l2=lam_l2, lam_l1=lam_l1, use_l1=use_l1,
            fit_intercept=fit_intercept, max_iter=max_iter, tol=tol,
            lbfgs_memory=lbfgs_memory,
        )

    return jax.vmap(fit_one)(lam_l2s, lam_l1s)


def _build_glm_problem(
    matvec, rmat, dtype, d, y_idx, w, mu, d_scale, total_w,
    *, k, multinomial, lam_l2, fit_intercept,
) -> Dict[str, Any]:
    """The GLM objective closures — z_of / rowloss / rowloss_alphas /
    penalty_terms / grad_from_z plus the flat-parameter geometry — shared by
    the one-program `_fit_common` path and the host-segmented checkpointing
    driver (`logistic_fit_checkpointed`), so both trace the identical math."""
    k_out = k if multinomial else 1
    n_flat = d * k_out + k_out

    def unflatten(xf):
        return xf[: d * k_out].reshape(d, k_out), xf[d * k_out :]

    def z_of(xf):
        B, b0 = unflatten(xf)
        Beff = B * d_scale[:, None]
        offset = (b0 - mu @ Beff) if fit_intercept else -(mu @ Beff)
        return matvec(Beff) + offset[None, :]  # LINEAR in (B, b0)

    if multinomial:
        def rowloss(z):
            z_true = jnp.take_along_axis(z, y_idx[:, None], axis=1)[:, 0]
            return jnp.sum(w * (jax.nn.logsumexp(z, axis=1) - z_true)) / total_w

        def rowloss_alphas(z_p, z_d, a):
            z = z_p[:, None, :] + a[None, :, None] * z_d[:, None, :]  # [n, S, k]
            idx = jnp.broadcast_to(y_idx[:, None, None], (z.shape[0], a.shape[0], 1))
            z_true = jnp.take_along_axis(z, idx, axis=2)[..., 0]  # [n, S]
            return jnp.einsum("n,ns->s", w, jax.nn.logsumexp(z, axis=2) - z_true) / total_w
    else:
        y = y_idx.astype(dtype)

        def rowloss(z):
            z0 = z[:, 0]
            return jnp.sum(w * (jax.nn.softplus(z0) - y * z0)) / total_w

        def rowloss_alphas(z_p, z_d, a):
            z = z_p[:, :1] + a[None, :] * z_d[:, :1]  # [n, S]
            return jnp.einsum(
                "n,ns->s", w, jax.nn.softplus(z) - y[:, None] * z
            ) / total_w

    def penalty_terms(xf, df_):
        Bx, Bd = xf[: d * k_out], df_[: d * k_out]
        return (
            0.5 * lam_l2 * jnp.sum(Bx * Bx),
            lam_l2 * jnp.dot(Bx, Bd),
            0.5 * lam_l2 * jnp.sum(Bd * Bd),
        )

    def grad_from_z(xf, z):
        """Analytic gradient from the logits: ∂loss/∂z is the GLM residual,
        the chain through z = matvec(B·d_scale) + (b0 − mu·Beff) is one
        transposed data pass (rmat) plus tiny vector algebra."""
        B, _ = unflatten(xf)
        if multinomial:
            p = jax.nn.softmax(z, axis=1)
            r = w[:, None] * (p - jax.nn.one_hot(y_idx, k, dtype=dtype)) / total_w
        else:
            p = jax.nn.sigmoid(z[:, 0])
            r = ((w * (p - y)) / total_w)[:, None]  # [n, 1]
        g_beff = rmat(r) - mu[:, None] * jnp.sum(r, axis=0)[None, :]  # [d, k_out]
        dB = g_beff * d_scale[:, None] + lam_l2 * B
        db0 = jnp.sum(r, axis=0) if fit_intercept else jnp.zeros((k_out,), dtype)
        return jnp.concatenate([dB.ravel(), db0])

    return dict(
        k_out=k_out, n_flat=n_flat, unflatten=unflatten, z_of=z_of,
        rowloss=rowloss, rowloss_alphas=rowloss_alphas,
        penalty_terms=penalty_terms, grad_from_z=grad_from_z,
    )


def _finish_glm(
    xf, obj, n_iter, stalled, unflatten, d_scale, mu, *, fit_intercept, multinomial,
) -> Dict[str, jax.Array]:
    """Flat iterate -> model-attribute dict in ORIGINAL feature space
    (standardization folded out, Spark multinomial intercept centering)."""
    B, b0 = unflatten(xf)
    coef = (B * d_scale[:, None]).T  # [k_out, d] original space
    intercept = b0 - coef @ mu if fit_intercept else jnp.zeros_like(b0)
    if multinomial:
        # softmax shift invariance: center intercepts (Spark parity,
        # reference classification.py:1077-1089)
        intercept = intercept - jnp.mean(intercept)
    return {
        "coef_": coef, "intercept_": intercept, "objective_": obj,
        "n_iter_": n_iter, "stalled_": stalled,
    }


def _warm_x0(warm_start, d, k_out, mu, d_scale, fit_intercept, dtype):
    """ORIGINAL-space (coef [k_out, d], intercept [k_out]) -> the flat
    STANDARDIZED iterate the solvers walk — the exact inverse of
    `_finish_glm`'s fold-out, so seeding from a converged model restarts the
    solver AT that model (docs/scheduling.md "Warm starts"). Columns whose
    d_scale is 0 (constant features) carry zero coefficient either way."""
    coef, intercept = warm_start
    coef = jnp.asarray(coef, dtype).reshape(k_out, d)
    intercept = jnp.asarray(intercept, dtype).reshape(k_out)
    scale = d_scale[:, None]
    B = jnp.where(scale != 0, coef.T / jnp.where(scale == 0, 1.0, scale), 0.0)
    b0 = (intercept + coef @ mu) if fit_intercept else jnp.zeros((k_out,), dtype)
    return jnp.concatenate([B.ravel(), b0])


def _fit_common(
    matvec, rmat, n_rows, dtype, d, y_idx, w, mu, d_scale, total_w,
    *, k, multinomial, lam_l2, lam_l1, use_l1, fit_intercept, max_iter, tol, lbfgs_memory,
    warm_start=None,
) -> Dict[str, jax.Array]:
    prob = _build_glm_problem(
        matvec, rmat, dtype, d, y_idx, w, mu, d_scale, total_w,
        k=k, multinomial=multinomial, lam_l2=lam_l2, fit_intercept=fit_intercept,
    )
    k_out, n_flat, unflatten = prob["k_out"], prob["n_flat"], prob["unflatten"]
    z_of, rowloss, rowloss_alphas = prob["z_of"], prob["rowloss"], prob["rowloss_alphas"]
    penalty_terms, grad_from_z = prob["penalty_terms"], prob["grad_from_z"]
    x_warm = (
        _warm_x0(warm_start, d, k_out, mu, d_scale, fit_intercept, dtype)
        if warm_start is not None
        else None
    )

    if use_l1:
        # L1/ElasticNet: OWL-QN over the flattened (B, b0) with the L1 mask
        # covering coefficients only (intercepts are never penalized — Spark
        # semantics; reference classification.py:1051-1057 `penalty='elasticnet'`)
        from .owlqn import owlqn_minimize

        def flat_loss(xf):
            p0, _, _ = penalty_terms(xf, jnp.zeros_like(xf))
            return rowloss(z_of(xf)) + p0

        l1_mask = jnp.concatenate(
            [jnp.ones((d * k_out,), dtype), jnp.zeros((k_out,), dtype)]
        )
        x0 = x_warm if x_warm is not None else jnp.zeros((n_flat,), dtype)
        xf, obj, n_iter = owlqn_minimize(
            flat_loss, x0, l1_mask, lam_l1,
            max_iter=max_iter, tol=tol, memory=lbfgs_memory,
        )
        stalled = jnp.asarray(False)
    else:
        xf, obj, n_iter, stalled = _glm_qn_minimize(
            z_of, rowloss, rowloss_alphas, grad_from_z, (n_rows, k_out), n_flat,
            dtype, penalty_terms, max_iter=max_iter, tol=tol, memory=lbfgs_memory,
            x0=x_warm,
        )
    return _finish_glm(
        xf, obj, n_iter, stalled, unflatten, d_scale, mu,
        fit_intercept=fit_intercept, multinomial=multinomial,
    )


def _fit_common_checkpointed(
    matvec, rmat, n_rows, dtype, d, y_idx, w, mu, d_scale, total_w,
    *, k, multinomial, lam_l2, lam_l1, use_l1, fit_intercept, max_iter, tol,
    lbfgs_memory, ckpt_key, placement_key, warm_start=None,
) -> Dict[str, jax.Array]:
    """`_fit_common` with the solver loop segmented for checkpointing
    (docs/robustness.md "Elastic recovery"): the IDENTICAL objective closures
    (`_build_glm_problem`) drive the host-segmented OWL-QN / GLM-QN loops
    instead of the one-program `lax.while_loop`, so an interrupted fit
    resumes from the last segment boundary. Runs eagerly (the segments are
    jitted; the glue is host code) — callers gate on
    `checkpoint.solver_checkpoints_active()`."""
    prob = _build_glm_problem(
        matvec, rmat, dtype, d, y_idx, w, mu, d_scale, total_w,
        k=k, multinomial=multinomial, lam_l2=lam_l2, fit_intercept=fit_intercept,
    )
    k_out, n_flat, unflatten = prob["k_out"], prob["n_flat"], prob["unflatten"]
    z_of, rowloss, rowloss_alphas = prob["z_of"], prob["rowloss"], prob["rowloss_alphas"]
    penalty_terms, grad_from_z = prob["penalty_terms"], prob["grad_from_z"]
    x_warm = (
        _warm_x0(warm_start, d, k_out, mu, d_scale, fit_intercept, dtype)
        if warm_start is not None
        else None
    )

    if use_l1:
        from .owlqn import owlqn_minimize_segmented

        def flat_loss(xf):
            p0, _, _ = penalty_terms(xf, jnp.zeros_like(xf))
            return rowloss(z_of(xf)) + p0

        l1_mask = jnp.concatenate(
            [jnp.ones((d * k_out,), dtype), jnp.zeros((k_out,), dtype)]
        )
        x0 = x_warm if x_warm is not None else jnp.zeros((n_flat,), dtype)
        xf, obj, n_iter = owlqn_minimize_segmented(
            flat_loss, x0, l1_mask, lam_l1,
            max_iter=max_iter, tol=tol, memory=lbfgs_memory,
            ckpt_key=ckpt_key + ":owlqn", placement_key=placement_key,
        )
        stalled = jnp.asarray(False)
    else:
        xf, obj, n_iter, stalled = glm_qn_minimize_segmented(
            z_of, rowloss, rowloss_alphas, grad_from_z, (n_rows, k_out), n_flat,
            dtype, penalty_terms, max_iter=max_iter, tol=tol, memory=lbfgs_memory,
            ckpt_key=ckpt_key, placement_key=placement_key, x0=x_warm,
        )
    return _finish_glm(
        xf, obj, n_iter, stalled, unflatten, d_scale, mu,
        fit_intercept=fit_intercept, multinomial=multinomial,
    )


def logistic_fit_checkpointed(
    X: jax.Array,
    y_idx: jax.Array,
    w: jax.Array,
    *,
    k: int,
    multinomial: bool,
    lam_l2: float,
    lam_l1: float = 0.0,
    use_l1: bool = False,
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
    fast: bool = False,
    ckpt_key: str = "logistic",
    placement_key=None,
    warm_start=None,
) -> Dict[str, jax.Array]:
    """`logistic_fit` with solver checkpoints: same returns, same math
    (shared closures), segmented loop. The model layer routes here when
    ``config["checkpoint_every_iters"]`` > 0 and a `CheckpointStore` is
    active; a same-placement resume is bit-identical to an uninterrupted
    checkpointed fit (pinned by tests/test_recovery.py). `fast` trajectories
    are keyed apart — a bf16 solve must never resume a full-precision one."""
    d = X.shape[1]
    mu, d_scale, total_w = _make_scaling(X, w, standardize, fit_intercept)
    if fast:
        ckpt_key = ckpt_key + ":bf16"
    matvec, rmat = _dense_ops(X, fast)
    return _fit_common_checkpointed(
        matvec, rmat, X.shape[0],
        X.dtype, d, y_idx, w, mu, d_scale, total_w,
        k=k, multinomial=multinomial, lam_l2=lam_l2, lam_l1=lam_l1, use_l1=use_l1,
        fit_intercept=fit_intercept, max_iter=max_iter, tol=tol,
        lbfgs_memory=lbfgs_memory, ckpt_key=ckpt_key, placement_key=placement_key,
        warm_start=warm_start,
    )


def logistic_fit_ell_checkpointed(
    values: jax.Array,
    indices: jax.Array,
    y_idx: jax.Array,
    w: jax.Array,
    *,
    d: int,
    k: int,
    multinomial: bool,
    lam_l2: float,
    lam_l1: float = 0.0,
    use_l1: bool = False,
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
    fast: bool = False,
    ckpt_key: str = "logistic_ell",
    placement_key=None,
    warm_start=None,
) -> Dict[str, jax.Array]:
    """Sparse (padded-ELL) analog of `logistic_fit_checkpointed` — scale-only
    standardization, same closures as `logistic_fit_ell`, segmented loop."""
    mu, d_scale, total_w = _ell_scaling(values, indices, w, d, standardize)
    if fast:
        ckpt_key = ckpt_key + ":bf16"
    matvec, rmat = _ell_ops(values, indices, d, fast)
    return _fit_common_checkpointed(
        matvec, rmat, values.shape[0],
        values.dtype, d, y_idx, w, mu, d_scale, total_w,
        k=k, multinomial=multinomial, lam_l2=lam_l2, lam_l1=lam_l1, use_l1=use_l1,
        fit_intercept=fit_intercept, max_iter=max_iter, tol=tol,
        lbfgs_memory=lbfgs_memory, ckpt_key=ckpt_key, placement_key=placement_key,
        warm_start=warm_start,
    )


@partial(jax.jit, static_argnames=("multinomial",))
def logistic_predict(
    X: jax.Array, coef: jax.Array, intercept: jax.Array, *, multinomial: bool
) -> Tuple[jax.Array, jax.Array]:
    """Returns (raw [n, k], prob [n, k]) — Spark's rawPrediction/probability.

    Binary: raw = [-m, m] with m the margin (Spark convention)."""
    if multinomial:
        raw = X @ coef.T + intercept[None, :]
        prob = jax.nn.softmax(raw, axis=1)
    else:
        m = X @ coef[0] + intercept[0]
        raw = jnp.stack([-m, m], axis=1)
        p1 = jax.nn.sigmoid(m)
        prob = jnp.stack([1.0 - p1, p1], axis=1)
    return raw, prob
