#
# Distributed logistic regression solver — the in-tree replacement for
# `cuml.linear_model.logistic_regression_mg.LogisticRegressionMG` (the L-BFGS
# "qn" solver consumed by reference classification.py:1051-1057).
#
# Design: the whole fit is ONE jitted program over the row-sharded X:
#  * standardization stats (weighted mean/var) are psum'd in-graph — the
#    reference's hand-rolled CuPy allgather pre-standardization
#    (classification.py:984-1089) collapses into two einsum+psum lines, and the
#    scaling is folded INTO the coefficients (logits = X @ (D·B) + (b0 − μᵀD·B))
#    so no standardized copy of X is ever materialized in HBM;
#  * L-BFGS (memory=10, zoom linesearch — optax) runs inside a lax.while_loop;
#    each objective/gradient evaluation is a fused MXU matmul + psum over the
#    mesh, the NCCL-allreduce-per-iteration of the reference;
#  * binomial (sigmoid, coef [1,d]) and multinomial (softmax, coef [k,d]) with
#    Spark's multinomial intercept centering (classification.py:1077-1089).
#
# Objective (Spark semantics): (Σ wᵢ·logloss_i)/Σw + λ·[(1−α)/2·‖B_std‖² +
# α·‖B_std‖₁] with the penalty applied in standardized space when
# standardization=True and never to intercepts. The smooth part (logloss + L2)
# goes through optax L-BFGS when α·λ=0 and through the in-tree OWL-QN solver
# (ops/owlqn.py — the same Andrew & Gao 2007 algorithm behind cuML's qn
# `penalty='l1'/'elasticnet'`, reference classification.py:1051-1057) when the
# L1 term is active.
#
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from .linalg import weighted_moments


def _make_scaling(X, w, standardize: bool, fit_intercept: bool):
    """Returns (mu [d], d_scale [d]): logits use Beff = d_scale·B, offset −μ·Beff."""
    total_w, mean, var = weighted_moments(X, w)
    if not standardize:
        return jnp.zeros_like(mean), jnp.ones_like(mean), total_w
    sigma = jnp.sqrt(var * (total_w / jnp.maximum(total_w - 1.0, 1.0)))  # unbiased, Spark summarizer
    d_scale = jnp.where(sigma > 0, 1.0 / jnp.maximum(sigma, 1e-30), 0.0)
    mu = mean if fit_intercept else jnp.zeros_like(mean)
    return mu, d_scale, total_w


def _binomial_loss(X, y, w, total_w, mu, d_scale, lam_l2, fit_intercept):
    def loss(params):
        B, b0 = params  # [d, 1], [1]
        Beff = B * d_scale[:, None]
        z = (X @ Beff)[:, 0] + (b0[0] - mu @ Beff[:, 0] if fit_intercept else -mu @ Beff[:, 0])
        # logloss = softplus(z) - y*z  (y in {0,1})
        ll = jnp.sum(w * (jax.nn.softplus(z) - y * z)) / total_w
        return ll + 0.5 * lam_l2 * jnp.sum(B * B)

    return loss


def _multinomial_loss(X, y_idx, w, total_w, mu, d_scale, lam_l2, fit_intercept, k):
    def loss(params):
        B, b0 = params  # [d, k], [k]
        Beff = B * d_scale[:, None]
        offset = b0 - mu @ Beff if fit_intercept else -(mu @ Beff)
        z = X @ Beff + offset[None, :]  # [n, k]
        z_true = jnp.take_along_axis(z, y_idx[:, None], axis=1)[:, 0]
        ll = jnp.sum(w * (jax.nn.logsumexp(z, axis=1) - z_true)) / total_w
        return ll + 0.5 * lam_l2 * jnp.sum(B * B)

    return loss


def _lbfgs_minimize(loss, params0, max_iter: int, tol: float, memory: int = 10):
    """L-BFGS in a lax.while_loop; converges on relative objective decrease
    (the qn-solver criterion the reference relies on)."""
    import optax.tree_utils as otu

    opt = optax.lbfgs(memory_size=memory)
    value_and_grad = optax.value_and_grad_from_state(loss)

    def cond(carry):
        _, _, prev, cur, it = carry
        rel = jnp.abs(prev - cur) / jnp.maximum(jnp.abs(cur), 1.0)
        return jnp.logical_and(it < max_iter, rel > tol)

    def body(carry):
        params, state, _, cur, it = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=loss
        )
        params = optax.apply_updates(params, updates)
        # the zoom linesearch evaluated the loss at the NEW params; read it from
        # the optimizer state so the convergence check compares new vs old
        new_value = otu.tree_get(state, "value")
        return params, state, cur, new_value, it + 1

    state0 = opt.init(params0)
    v0 = loss(params0)
    params, state, _, obj, n_iter = jax.lax.while_loop(
        cond, body, (params0, state0, jnp.inf, v0, jnp.array(0, jnp.int32))
    )
    return params, obj, n_iter


@partial(
    jax.jit,
    static_argnames=(
        "k", "fit_intercept", "standardize", "max_iter", "lbfgs_memory", "multinomial", "use_l1",
    ),
)
def logistic_fit(
    X: jax.Array,
    y_idx: jax.Array,  # int32 class indices in [0, k)
    w: jax.Array,
    *,
    k: int,
    multinomial: bool,
    lam_l2: float,
    lam_l1: float = 0.0,
    use_l1: bool = False,  # static solver choice; lam_l1/lam_l2 stay traced so
    # hyperparameter sweeps (fitMultiple/CV) never recompile
    fit_intercept: bool = True,
    standardize: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    lbfgs_memory: int = 10,
) -> Dict[str, jax.Array]:
    """Returns coef_ [k_out, d] and intercept_ [k_out] in ORIGINAL feature space
    (standardization folded out), plus objective_ and n_iter_."""
    d = X.shape[1]
    mu, d_scale, total_w = _make_scaling(X, w, standardize, fit_intercept)
    k_out = k if multinomial else 1
    if multinomial:
        loss = _multinomial_loss(X, y_idx, w, total_w, mu, d_scale, lam_l2, fit_intercept, k)
    else:
        y = y_idx.astype(X.dtype)
        loss = _binomial_loss(X, y, w, total_w, mu, d_scale, lam_l2, fit_intercept)

    if use_l1:
        # L1/ElasticNet: OWL-QN over the flattened (B, b0) with the L1 mask
        # covering coefficients only (intercepts are never penalized — Spark
        # semantics; reference classification.py:1051-1057 `penalty='elasticnet'`)
        from .owlqn import owlqn_minimize

        def flat_loss(xf):
            return loss((xf[: d * k_out].reshape(d, k_out), xf[d * k_out :]))

        l1_mask = jnp.concatenate(
            [jnp.ones((d * k_out,), X.dtype), jnp.zeros((k_out,), X.dtype)]
        )
        x0 = jnp.zeros((d * k_out + k_out,), X.dtype)
        xf, obj, n_iter = owlqn_minimize(
            flat_loss, x0, l1_mask, lam_l1,
            max_iter=max_iter, tol=tol, memory=lbfgs_memory,
        )
        B, b0 = xf[: d * k_out].reshape(d, k_out), xf[d * k_out :]
    else:
        params0 = (jnp.zeros((d, k_out), X.dtype), jnp.zeros((k_out,), X.dtype))
        (B, b0), obj, n_iter = _lbfgs_minimize(loss, params0, max_iter, tol, lbfgs_memory)

    coef = (B * d_scale[:, None]).T  # [k_out, d] original space
    intercept = b0 - coef @ mu if fit_intercept else jnp.zeros_like(b0)
    if multinomial:
        # softmax shift invariance: center intercepts (Spark parity,
        # reference classification.py:1077-1089)
        intercept = intercept - jnp.mean(intercept)
    return {"coef_": coef, "intercept_": intercept, "objective_": obj, "n_iter_": n_iter}


@partial(jax.jit, static_argnames=("multinomial",))
def logistic_predict(
    X: jax.Array, coef: jax.Array, intercept: jax.Array, *, multinomial: bool
) -> Tuple[jax.Array, jax.Array]:
    """Returns (raw [n, k], prob [n, k]) — Spark's rawPrediction/probability.

    Binary: raw = [-m, m] with m the margin (Spark convention)."""
    if multinomial:
        raw = X @ coef.T + intercept[None, :]
        prob = jax.nn.softmax(raw, axis=1)
    else:
        m = X @ coef[0] + intercept[0]
        raw = jnp.stack([-m, m], axis=1)
        p1 = jax.nn.sigmoid(m)
        prob = jnp.stack([1.0 - p1, p1], axis=1)
    return raw, prob
