#
# TPU-native reimplementation of the minimal `pyspark.ml.linalg` vector surface the
# reference framework consumes (VectorUDT columns, Vectors.dense/sparse factories).
# The reference relies on pyspark for these (e.g. /root/reference/python/src/
# spark_rapids_ml/core.py:205-250 decodes unwrapped Spark vectors); since this
# framework is Spark-optional, the vector types live in-tree and are recognised by
# the data-ingest layer (data.py) inside object columns of any DataFrame-like input.
#
from __future__ import annotations

from typing import Iterable, Sequence, Union

import numpy as np

__all__ = ["DenseVector", "SparseVector", "Vector", "Vectors"]


class Vector:
    """Abstract vector: a 1-D float64 feature container."""

    def toArray(self) -> np.ndarray:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    def __len__(self) -> int:
        return self.size


class DenseVector(Vector):
    """Dense column vector backed by a float64 numpy array."""

    __slots__ = ("values",)

    def __init__(self, values: Union[Sequence[float], np.ndarray]):
        self.values = np.asarray(values, dtype=np.float64).reshape(-1)

    def toArray(self) -> np.ndarray:
        return self.values

    @property
    def size(self) -> int:
        return int(self.values.shape[0])

    def dot(self, other) -> float:
        if isinstance(other, SparseVector):
            return other.dot(self)
        other = other.toArray() if isinstance(other, Vector) else np.asarray(other)
        return float(np.dot(self.values, other))

    def squared_distance(self, other) -> float:
        other = other.toArray() if isinstance(other, Vector) else np.asarray(other)
        diff = self.values - other
        return float(np.dot(diff, diff))

    def __getitem__(self, item):
        return self.values[item]

    def __eq__(self, other):
        if isinstance(other, DenseVector):
            return np.array_equal(self.values, other.values)
        if isinstance(other, SparseVector):
            return self.size == other.size and np.array_equal(self.values, other.toArray())
        return NotImplemented

    def __repr__(self) -> str:
        return f"DenseVector({self.values.tolist()!r})"


class SparseVector(Vector):
    """Sparse vector in (size, indices, values) COO-for-one-row form.

    Accepts the same construction styles as ``pyspark.ml.linalg.SparseVector``:
    ``SparseVector(4, [1, 3], [2.0, 3.0])``, ``SparseVector(4, {1: 2.0, 3: 3.0})``,
    or ``SparseVector(4, [(1, 2.0), (3, 3.0)])``.
    """

    __slots__ = ("_size", "indices", "values")

    def __init__(self, size: int, *args):
        self._size = int(size)
        if len(args) == 1:
            pairs = args[0]
            if isinstance(pairs, dict):
                pairs = sorted(pairs.items())
            pairs = list(pairs)
            self.indices = np.array([p[0] for p in pairs], dtype=np.int32)
            self.values = np.array([p[1] for p in pairs], dtype=np.float64)
        elif len(args) == 2:
            self.indices = np.asarray(args[0], dtype=np.int32).reshape(-1)
            self.values = np.asarray(args[1], dtype=np.float64).reshape(-1)
        else:
            raise TypeError("SparseVector expects (size, pairs) or (size, indices, values)")
        if self.indices.shape != self.values.shape:
            raise ValueError("indices and values must have the same length")
        if np.any(np.diff(self.indices) < 0):
            order = np.argsort(self.indices, kind="stable")
            self.indices = self.indices[order]
            self.values = self.values[order]
        if self.indices.size and int(self.indices[-1]) >= self._size:
            raise ValueError("index out of bounds")

    @property
    def size(self) -> int:
        return self._size

    def toArray(self) -> np.ndarray:
        arr = np.zeros(self._size, dtype=np.float64)
        arr[self.indices] = self.values
        return arr

    def dot(self, other) -> float:
        other_arr = other.toArray() if isinstance(other, Vector) else np.asarray(other)
        return float(np.dot(self.values, other_arr[self.indices]))

    def __eq__(self, other):
        if isinstance(other, (DenseVector, SparseVector)):
            return self.size == other.size and np.array_equal(self.toArray(), other.toArray())
        return NotImplemented

    def __repr__(self) -> str:
        return f"SparseVector({self._size}, {self.indices.tolist()!r}, {self.values.tolist()!r})"


class Vectors:
    """Factory namespace matching ``pyspark.ml.linalg.Vectors``."""

    @staticmethod
    def dense(*elements) -> DenseVector:
        if len(elements) == 1 and isinstance(elements[0], (Iterable, np.ndarray)):
            return DenseVector(elements[0])
        return DenseVector(elements)

    @staticmethod
    def sparse(size: int, *args) -> SparseVector:
        return SparseVector(size, *args)
