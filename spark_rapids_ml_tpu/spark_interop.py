#
# Spark JVM model interop: convert fitted TPU models into GENUINE pyspark.ml
# JVM models (`model.cpu()`), so a fitted model can be handed to existing
# Spark-ML pipelines, persisted with Spark writers, or served by JVM-only
# infrastructure — the reference's `.cpu()` capability (reference
# utils.py:311-481 translate_trees, tree.py:524-569 _convert_to_java_trees,
# feature.py:365-379 PCAModel.cpu, regression.py:658-672, and
# classification.py:1301-1323).
#
# Split into two layers so the logic is testable without a JVM:
#   * `tree_spec(model, t)` — pure numpy: walks the array forest and emits a
#     nested node spec carrying everything Spark's tree nodes need (split,
#     REAL impurity stats, gain, prediction). Unlike the reference (which
#     fakes internal-node impurity stats with zeros, utils.py:312-325), the
#     array forest retains per-node sufficient statistics, so the converted
#     Spark model gets real impurities/gains everywhere.
#   * `*_to_spark(model)` — thin py4j constructions over the specs, gated on
#     an active SparkSession.
#
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


# ------------------------------------------------------------- pure layer ---


def tree_spec(model, t: int) -> Dict[str, Any]:
    """Nested Spark-node spec of tree `t` of an array-forest model.

    Keys: every node has `impurity`, `stats` (ImpurityCalculator layout:
    per-class weighted counts for gini/entropy, [count, sum, sumSq] for
    variance — exactly Spark's internal stats vectors), `instance_count` and
    `prediction` (label INDEX for classification, node mean for regression —
    Spark's label-space contract); internal nodes add `split_feature`,
    `threshold`, `gain` (fractional-weight Spark semantics) and
    `left`/`right` children.
    """
    stats = np.asarray(model.node_stats, dtype=np.float64)
    imp, w = model._node_impurity_weight(stats)
    feature, threshold = model.feature, model.threshold
    M = feature.shape[1]
    is_clf = model._is_classification

    def build(i: int) -> Dict[str, Any]:
        node_stats = stats[t, i]
        node: Dict[str, Any] = {
            "impurity": float(imp[t, i]),
            "instance_count": int(round(float(w[t, i]))),
        }
        if is_clf:
            node["stats"] = [float(v) for v in node_stats]
            node["prediction"] = float(np.argmax(node_stats))
        else:
            n, sy, syy = (float(v) for v in node_stats)
            node["stats"] = [n, sy, syy]
            node["prediction"] = sy / max(n, 1e-30)
        f = int(feature[t, i])
        if f >= 0 and 2 * i + 2 < M:
            l, r = 2 * i + 1, 2 * i + 2
            wl, wr = float(w[t, l]), float(w[t, r])
            tot = max(wl + wr, 1e-30)
            node.update(
                split_feature=f,
                threshold=float(threshold[t, i]),
                gain=float(
                    node["impurity"]
                    - (wl / tot) * float(imp[t, l])
                    - (wr / tot) * float(imp[t, r])
                ),
                left=build(l),
                right=build(r),
            )
        return node

    return build(0)


def forest_specs(model) -> List[Dict[str, Any]]:
    return [tree_spec(model, t) for t in range(model.num_trees)]


# ------------------------------------------------------------- py4j layer ---


def _require_spark() -> Tuple[Any, Any]:
    """(SparkSession, SparkContext) of the ACTIVE session, or a clear error.

    `.cpu()` builds JVM objects, so it only works where the JVM runs — inside
    an application that already holds a SparkSession (reference
    _get_spark_session contract, utils.py core)."""
    try:
        from pyspark.sql import SparkSession
    except ImportError as e:
        raise ImportError(
            "model.cpu() requires pyspark (JVM model conversion); "
            "pip install pyspark or run inside a Spark application"
        ) from e
    spark = SparkSession.getActiveSession()
    if spark is None:
        raise RuntimeError(
            "model.cpu() needs an active SparkSession to reach the JVM; "
            "create one first (SparkSession.builder.getOrCreate())"
        )
    return spark, spark.sparkContext


def java_uid(sc, prefix: str) -> str:
    return sc._jvm.org.apache.spark.ml.util.Identifiable.randomUID(prefix)


def to_spark_vector(value):
    """Any row representation (framework Vector, ndarray, list, pyspark
    Vector) -> pyspark.ml.linalg Vector. py4j cannot marshal numpy arrays
    (Pyrolite ClassDict pickling error), so every JVM-bound single-vector
    call must cross through this."""
    from pyspark.ml.linalg import Vector as SparkVector, Vectors as SparkVectors

    if isinstance(value, SparkVector):
        return value
    if hasattr(value, "toArray"):
        value = value.toArray()
    return SparkVectors.dense([float(v) for v in np.asarray(value).ravel()])


def _first_non_null(col):
    """First non-null cell of a pandas Series (None when all-null/empty).
    Column-kind probing must skip leading None/NaN rows: deciding off row 0
    alone leaves a vector column unconverted whenever its first cell is null,
    and `spark.createDataFrame` then dies in the MLSerDe pickle branch."""
    non_null = col.dropna()
    return non_null.iloc[0] if len(non_null) else None


def _vector_cell_or_none(v):
    """Cell converter for a vector-typed column: null cells (None, float NaN,
    pd.NA/NaT — everything `Series.dropna` skips) become None — a bare null
    scalar in a VectorUDT column breaks Spark's serializer — everything else
    goes through `to_spark_vector`."""
    if v is None:
        return None
    if not isinstance(v, (list, tuple, np.ndarray)) and not hasattr(v, "toArray"):
        import pandas as pd

        if pd.isna(v):  # scalar here, so isna returns a scalar bool
            return None
    return to_spark_vector(v)


def as_spark_df(dataset):
    """Any framework dataset (pandas DataFrame, pyarrow Table, dict, or an
    actual Spark DataFrame) -> Spark DataFrame, with array/Vector cells
    converted to pyspark Vectors. The JVM-summary paths (`model.evaluate`)
    need a genuine Spark DataFrame; handing py4j a pandas frame dies in the
    MLSerDe pickle branch."""
    if hasattr(dataset, "sparkSession") and hasattr(dataset, "rdd"):
        return dataset  # already a Spark DataFrame
    from .data import as_pandas

    spark, _ = _require_spark()
    pdf = as_pandas(dataset).copy(deep=False)
    for col in pdf.columns:
        first = _first_non_null(pdf[col])
        if isinstance(first, (list, tuple, np.ndarray)) or hasattr(first, "toArray"):
            pdf[col] = pdf[col].map(_vector_cell_or_none)
    return spark.createDataFrame(pdf)


def _java_double_array(sc, values) -> Any:
    arr = sc._gateway.new_array(sc._jvm.double, len(values))
    for i, v in enumerate(values):
        arr[i] = float(v)
    return arr


def _impurity_calculator(sc, impurity: str, stats, raw_count: int):
    jvm_imp = sc._jvm.org.apache.spark.mllib.tree.impurity
    cls = {
        "gini": jvm_imp.GiniCalculator,
        "entropy": jvm_imp.EntropyCalculator,
        "variance": jvm_imp.VarianceCalculator,
    }[impurity]
    return cls(_java_double_array(sc, stats), int(raw_count))


def _build_java_node(sc, spec: Dict[str, Any], impurity: str):
    tree_pkg = sc._jvm.org.apache.spark.ml.tree
    calc = _impurity_calculator(sc, impurity, spec["stats"], spec["instance_count"])
    if "split_feature" not in spec:
        return tree_pkg.LeafNode(float(spec["prediction"]), float(spec["impurity"]), calc)
    split = tree_pkg.ContinuousSplit(int(spec["split_feature"]), float(spec["threshold"]))
    return tree_pkg.InternalNode(
        float(spec["prediction"]),
        float(spec["impurity"]),
        float(spec["gain"]),
        _build_java_node(sc, spec["left"], impurity),
        _build_java_node(sc, spec["right"], impurity),
        split,
        calc,
    )


def rf_to_spark(model):
    """Array forest -> pyspark.ml RandomForest{Classification,Regression}Model.

    Classification note: Spark tree models predict label INDICES 0..k-1 (its
    fit contract requires such labels), so exact prediction parity holds when
    the TPU model was trained on 0..k-1 labels — the same contract the
    reference's cuML-JSON conversion has."""
    spark, sc = _require_spark()
    is_clf = model._is_classification
    impurity = str(
        model._solver_params.get("split_criterion") or ("gini" if is_clf else "variance")
    )
    roots = [_build_java_node(sc, spec, impurity) for spec in forest_specs(model)]

    if is_clf:
        from pyspark.ml.classification import (
            RandomForestClassificationModel as SparkRFClassificationModel,
        )

        uid = java_uid(sc, "rfc")
        dt_cls = sc._jvm.org.apache.spark.ml.classification.DecisionTreeClassificationModel
        dtrees = [dt_cls(uid, root, model.n_cols, model.numClasses) for root in roots]
        java_trees = sc._gateway.new_array(dt_cls, len(dtrees))
        for i, dt in enumerate(dtrees):
            java_trees[i] = dt
        java_model = sc._jvm.org.apache.spark.ml.classification.RandomForestClassificationModel(
            uid, java_trees, model.n_cols, model.numClasses
        )
        py_model = SparkRFClassificationModel(java_model)
        py_model.setProbabilityCol(model.getOrDefault("probabilityCol"))
        py_model.setRawPredictionCol(model.getOrDefault("rawPredictionCol"))
    else:
        from pyspark.ml.regression import (
            RandomForestRegressionModel as SparkRFRegressionModel,
        )

        uid = java_uid(sc, "rfr")
        dt_cls = sc._jvm.org.apache.spark.ml.regression.DecisionTreeRegressionModel
        dtrees = [dt_cls(uid, root, model.n_cols) for root in roots]
        java_trees = sc._gateway.new_array(dt_cls, len(dtrees))
        for i, dt in enumerate(dtrees):
            java_trees[i] = dt
        java_model = sc._jvm.org.apache.spark.ml.regression.RandomForestRegressionModel(
            uid, java_trees, model.n_cols
        )
        py_model = SparkRFRegressionModel(java_model)
    py_model.setFeaturesCol(model.getOrDefault("featuresCol"))
    py_model.setPredictionCol(model.getOrDefault("predictionCol"))
    return py_model


def pca_to_spark(model):
    """PCAModel -> pyspark.ml.feature.PCAModel (reference feature.py:365-379).

    Spark's PCAModel.transform does NOT mean-center its input (pyspark.ml
    semantics); the TPU model's transform does (solver semantics). The
    converted model carries the same `pc`/`explainedVariance`, so projections
    agree on centered data — identical to the reference's `.cpu()` behavior."""
    from pyspark.ml.common import _py2java
    from pyspark.ml.feature import PCAModel as SparkPCAModel
    from pyspark.ml.linalg import DenseMatrix, Vectors

    spark, sc = _require_spark()
    pc = np.asarray(model.pc, dtype=np.float64)  # [d, k] columns = components
    d, k = pc.shape
    java_pc = _py2java(sc, DenseMatrix(d, k, pc.ravel(order="F").tolist(), False))
    java_ev = _py2java(sc, Vectors.dense(np.asarray(model.explainedVariance, dtype=np.float64)))
    java_model = sc._jvm.org.apache.spark.ml.feature.PCAModel(
        java_uid(sc, "pca"), java_pc, java_ev
    )
    py_model = SparkPCAModel(java_model)
    in_col = model.getOrDefault("inputCol") if model.isDefined("inputCol") else None
    if in_col:
        py_model.setInputCol(in_col)
    py_model.setOutputCol(model._out_column_names()[0])
    return py_model


def kmeans_to_spark(model):
    """KMeansModel -> pyspark.ml.clustering.KMeansModel via the mllib model
    (reference clustering.py:422-443 — the JVM ml.KMeansModel has no public
    centers constructor, so it wraps an mllib KMeansModel)."""
    from pyspark.mllib.common import _py2java
    from pyspark.mllib.linalg import _convert_to_vector
    from pyspark.ml.clustering import KMeansModel as SparkKMeansModel

    spark, sc = _require_spark()
    centers = np.asarray(model.cluster_centers_, dtype=np.float64)
    java_centers = _py2java(sc, [_convert_to_vector(c) for c in centers])
    java_mllib_model = sc._jvm.org.apache.spark.mllib.clustering.KMeansModel(java_centers)
    java_model = sc._jvm.org.apache.spark.ml.clustering.KMeansModel(
        java_uid(sc, "kmeans"), java_mllib_model
    )
    py_model = SparkKMeansModel(java_model)
    py_model.setFeaturesCol(model.getOrDefault("featuresCol"))
    py_model.setPredictionCol(model.getOrDefault("predictionCol"))
    return py_model


def linreg_to_spark(model):
    """LinearRegressionModel -> pyspark.ml.regression.LinearRegressionModel
    (reference regression.py:658-672)."""
    from pyspark.ml.common import _py2java
    from pyspark.ml.linalg import Vectors

    spark, sc = _require_spark()
    coef = _py2java(sc, Vectors.dense(np.asarray(model.coef_, dtype=np.float64).ravel()))
    java_model = sc._jvm.org.apache.spark.ml.regression.LinearRegressionModel(
        java_uid(sc, "linReg"), coef, float(model.intercept), 1.0
    )
    from pyspark.ml.regression import LinearRegressionModel as SparkLinearRegressionModel

    py_model = SparkLinearRegressionModel(java_model)
    py_model.setFeaturesCol(model.getOrDefault("featuresCol"))
    py_model.setPredictionCol(model.getOrDefault("predictionCol"))
    return py_model


def logreg_to_spark(model):
    """LogisticRegressionModel -> pyspark.ml.classification counterpart
    (reference classification.py:1301-1323)."""
    from pyspark.ml.common import _py2java
    from pyspark.ml.classification import (
        LogisticRegressionModel as SparkLogisticRegressionModel,
    )
    from pyspark.ml.linalg import DenseMatrix, Vectors

    spark, sc = _require_spark()
    coef = np.atleast_2d(np.asarray(model.coef_, dtype=np.float64))
    k_rows, d = coef.shape
    is_multinomial = len(model.classes_) > 2 or k_rows > 1
    java_coef = _py2java(sc, DenseMatrix(k_rows, d, coef.ravel(order="F").tolist(), False))
    java_intercept = _py2java(
        sc, Vectors.dense(np.atleast_1d(np.asarray(model.intercept_, dtype=np.float64)))
    )
    java_model = sc._jvm.org.apache.spark.ml.classification.LogisticRegressionModel(
        java_uid(sc, "logreg"), java_coef, java_intercept, len(model.classes_), is_multinomial
    )
    py_model = SparkLogisticRegressionModel(java_model)
    py_model.setFeaturesCol(model.getOrDefault("featuresCol"))
    py_model.setPredictionCol(model.getOrDefault("predictionCol"))
    py_model.setProbabilityCol(model.getOrDefault("probabilityCol"))
    py_model.setRawPredictionCol(model.getOrDefault("rawPredictionCol"))
    return py_model
