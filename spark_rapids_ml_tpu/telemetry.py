#
# Structured telemetry: spans, counters/gauges/histograms, and sinks.
#
# The observability substrate for the whole hot path (ingest -> layout ->
# solve -> transform). The reference's story here is NVTX ranges in the Scala
# plugin plus ad-hoc wall-clock logs in the Python tier (SURVEY.md §5); the
# TPU-native answer is:
#
#   * `span("stage", **attrs)` — a nestable context manager that records wall
#     time into the registry, emits a `jax.profiler.TraceAnnotation` so the
#     stage lines up inside xprof traces (the NVTX-range analog), and logs the
#     stage timing at a caller-provided logger (the old `verbose` prints).
#   * `MetricsRegistry` — a process-global store of counters (bytes ingested,
#     device_put calls, rendezvous rounds), gauges (HBM watermark, solver
#     objective), histograms (rendezvous latency), span aggregates, and
#     per-iteration solver convergence traces.
#   * sinks — a JSONL file (`SRML_METRICS_PATH`) receiving one record per
#     span plus one snapshot record per fit, and an in-process `snapshot()`
#     dict that bench.py embeds into BENCH_* emission and `fit` attaches to
#     models as `model._fit_metrics`.
#
# Contracts:
#   * ZERO-COST WHEN DISABLED: `span()` returns a shared no-op object and
#     every record method is behind one flag check — a disabled fit does no
#     timing, no allocation, no I/O.
#   * SPMD-SAFE: records are rank-tagged, the JSONL sink writes to a per-rank
#     file (rank 0 owns the bare path), and nothing here performs a
#     collective of its own.
#   * Per-iteration convergence traces from jitted solvers use
#     `jax.debug.callback` and are gated SEPARATELY (`SRML_TRACE_CONVERGENCE`
#     / `enable(convergence=True)`): a host callback per L-BFGS iteration is
#     free on CPU but a dispatch round-trip through a remote TPU tunnel, so
#     it never rides along with plain counter telemetry. The gate is read at
#     TRACE time — toggling it after a solver shape has compiled does not
#     retrace that shape.
#
from __future__ import annotations

import atexit
import contextlib
import json
import os
import re
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .utils import lockcheck

__all__ = [
    "enabled",
    "enable",
    "disable",
    "convergence_trace_enabled",
    "span",
    "registry",
    "MetricsRegistry",
    "snapshot",
    "summary",
    "fit_scope",
    "record_device_memory",
    "record_solver_result",
    "record_convergence_point",
    "quantile_of",
    "summarize_histogram",
    "tenant_metric",
    "merge_counters",
    "merge_gauges",
    "merge_histograms",
    "merge_windows",
    "MergedWindows",
]

# Span records kept in-process (the JSONL sink receives every record; the
# in-memory list is for snapshot()/summary() and stays bounded).
_MAX_SPAN_RECORDS = 4096
_MAX_CONVERGENCE_POINTS = 10_000
# Most-recent observations retained per histogram for quantile() estimation
# (serving latency p50/p99); the count/sum/min/max summary sees EVERY
# observation — only the quantile view is windowed.
_MAX_HIST_SAMPLES = 1024
# Per-bucket sample retention for the TIME-windowed quantile view (the ops
# plane's rolling windows): bounded so a traffic burst cannot grow the ring —
# a bucket past the cap keeps its count/sum exact and its quantiles
# approximate (computed over the retained samples).
_MAX_BUCKET_SAMPLES = 256


class _State:
    __slots__ = ("on", "sink_path", "convergence")

    def __init__(self) -> None:
        self.sink_path: Optional[str] = os.environ.get("SRML_METRICS_PATH") or None
        self.on: bool = bool(self.sink_path) or bool(os.environ.get("SRML_TELEMETRY"))
        self.convergence: bool = bool(os.environ.get("SRML_TRACE_CONVERGENCE"))


_STATE = _State()
_LOCAL = threading.local()  # per-thread span stack (nesting -> paths)

# Cached handle to the diagnostics module (trace tags + flight recorder).
# Lazy: diagnostics never imports telemetry at module level and vice versa,
# so whichever loads first wins without a cycle.
_DIAG: Any = None


def _diag():
    global _DIAG
    if _DIAG is None:
        from . import diagnostics

        _DIAG = diagnostics
    return _DIAG


def enabled() -> bool:
    """Whether telemetry recording is on (one branch — THE hot-path check)."""
    return _STATE.on


def convergence_trace_enabled() -> bool:
    """Whether jitted solvers should bake per-iteration host callbacks in.
    Read at trace time; see the module header for the compile-cache caveat."""
    return _STATE.on and _STATE.convergence


def enable(sink_path: Optional[str] = None, *, convergence: Optional[bool] = None) -> None:
    """Turn telemetry on, optionally pointing the JSONL sink at `sink_path`
    and/or toggling per-iteration convergence tracing. Re-pointing the sink
    closes the previous file handles (no fd accumulation across jobs)."""
    _STATE.on = True
    if sink_path is not None:
        if sink_path != _STATE.sink_path:
            _close_sinks()
        _STATE.sink_path = sink_path
    if convergence is not None:
        _STATE.convergence = bool(convergence)
    # opt-in live scrape surface (docs/observability.md "Ops plane"): when
    # SRML_METRICS_PORT names a port, enabling telemetry also stands up the
    # exporter thread. Best-effort — a busy port degrades to no server, never
    # to a failed fit.
    if os.environ.get("SRML_METRICS_PORT"):
        try:
            from . import ops_plane

            ops_plane.ensure_server()
        except Exception:  # pragma: no cover - exporter must never break enable
            pass


def disable() -> None:
    """Turn telemetry off (records already taken stay in the registry) and
    close any open sink files."""
    _STATE.on = False
    _close_sinks()


def _rank() -> int:
    """This process's rank for record tagging and per-rank sink naming.
    Delegates to diagnostics (active TpuContext > set_process_rank >
    SRML_RANK env > 0) so telemetry records and flight-recorder dumps agree
    on rank identity. Control-plane only — never touches the XLA backend
    (jax.process_index() would initialize it)."""
    return _diag()._rank()


# --------------------------------------------------------- rolling windows --
#
# Time-bucketed ring aggregation (docs/observability.md "Ops plane"): every
# counter gets `rate()` and every histogram gets `window_quantile()` over a
# configurable recent horizon (bucket width x bucket count,
# `config["metrics_bucket_seconds"]` x `config["metrics_bucket_count"]`,
# default 10s x 18 = 3 minutes) ALONGSIDE the cumulative views — a long-lived
# serving process answers "what is the error rate NOW", not since boot.
# Window updates ride the same single `_STATE.on` check as every recorder
# (zero-cost when telemetry is disabled, the PR-2 contract); window params are
# resolved lazily at first record after construction/reset, so tests that
# shrink the bucket width set config and call `registry().reset()`.


def _window_params() -> Tuple[float, int]:
    """(bucket_seconds, bucket_count) from core.config, via sys.modules like
    diagnostics.flightrec_dir — telemetry must never pay core's import chain
    (and an uncustomized process cannot have customized the knobs)."""
    bucket_s, n = 10.0, 18
    core = sys.modules.get(__package__ + ".core")
    if core is not None:
        try:
            bucket_s = float(core.config.get("metrics_bucket_seconds") or 10.0)
            n = int(core.config.get("metrics_bucket_count") or 18)
        except Exception:  # pragma: no cover - malformed knob keeps defaults
            pass
    return max(0.001, bucket_s), max(2, n)


class _CounterRing:
    """Per-counter ring of per-bucket increment sums."""

    __slots__ = ("bucket_s", "n", "vals", "head")

    def __init__(self, bucket_s: float, n: int) -> None:
        self.bucket_s = bucket_s
        self.n = n
        self.vals = [0.0] * n
        self.head: Optional[int] = None  # absolute index of the newest bucket

    def _advance(self, b: int) -> None:
        if self.head is None or b - self.head >= self.n:
            self.vals = [0.0] * self.n
            self.head = b
            return
        while self.head < b:
            self.head += 1
            self.vals[self.head % self.n] = 0.0

    def add(self, now: float, v: float) -> None:
        b = int(now // self.bucket_s)
        if self.head is None or b > self.head:
            self._advance(b)
        # a clock reading from just before the head bucket opened lands in
        # the head bucket rather than rewriting history
        self.vals[(self.head if b < (self.head or 0) else b) % self.n] += v

    def window_sum(self, now: float, window_s: Optional[float]) -> Tuple[float, float]:
        """(sum over the window, window span seconds). The span is clamped to
        the ring horizon — asking for 1h over a 3min ring reads 3min."""
        b = int(now // self.bucket_s)
        if self.head is None or b > self.head:
            self._advance(b)
        horizon = self.n * self.bucket_s
        span = horizon if window_s is None else min(max(float(window_s), self.bucket_s), horizon)
        k = max(1, min(self.n, int(round(span / self.bucket_s))))
        assert self.head is not None
        return sum(self.vals[(self.head - i) % self.n] for i in range(k)), k * self.bucket_s


class _HistRing:
    """Per-histogram ring of per-bucket (count, sum, bounded samples)."""

    __slots__ = ("bucket_s", "n", "counts", "sums", "samples", "head")

    def __init__(self, bucket_s: float, n: int) -> None:
        self.bucket_s = bucket_s
        self.n = n
        self.counts = [0.0] * n
        self.sums = [0.0] * n
        self.samples: List[List[float]] = [[] for _ in range(n)]
        self.head: Optional[int] = None

    def _advance(self, b: int) -> None:
        if self.head is None or b - self.head >= self.n:
            self.counts = [0.0] * self.n
            self.sums = [0.0] * self.n
            self.samples = [[] for _ in range(self.n)]
            self.head = b
            return
        while self.head < b:
            self.head += 1
            i = self.head % self.n
            self.counts[i] = 0.0
            self.sums[i] = 0.0
            self.samples[i] = []

    def add(self, now: float, v: float) -> None:
        b = int(now // self.bucket_s)
        if self.head is None or b > self.head:
            self._advance(b)
        i = (self.head if b < (self.head or 0) else b) % self.n
        self.counts[i] += 1.0
        self.sums[i] += v
        if len(self.samples[i]) < _MAX_BUCKET_SAMPLES:
            self.samples[i].append(v)

    def _slots(self, now: float, window_s: Optional[float]) -> List[int]:
        b = int(now // self.bucket_s)
        if self.head is None or b > self.head:
            self._advance(b)
        horizon = self.n * self.bucket_s
        span = horizon if window_s is None else min(max(float(window_s), self.bucket_s), horizon)
        k = max(1, min(self.n, int(round(span / self.bucket_s))))
        assert self.head is not None
        return [(self.head - i) % self.n for i in range(k)]

    def window_samples(self, now: float, window_s: Optional[float]) -> List[float]:
        out: List[float] = []
        for i in self._slots(now, window_s):
            out.extend(self.samples[i])
        return out

    def window_count(self, now: float, window_s: Optional[float]) -> float:
        return sum(self.counts[i] for i in self._slots(now, window_s))


def quantile_of(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank quantile over a (possibly unsorted) sample list — THE one
    quantile-extraction implementation (ScoringEngine.stats,
    FitScheduler.stats, the registry's quantile views, and the bench lanes
    all delegate here, so they cannot drift). None on an empty list."""
    if not values:
        return None
    ordered = sorted(values)
    q = min(max(float(q), 0.0), 1.0)
    idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return float(ordered[idx])


# --------------------------------------------------------- merge semantics --
#
# THE cross-rank merge definitions (docs/observability.md "Fleet plane") —
# both fleet transports (the live ops round and the offline snapshot merge)
# delegate here so they cannot drift: counters SUM; gauges keep every
# per-rank value plus min/max/sum (averaging a watermark would lie); window
# histograms merge per-bucket with exact counts/sums preserved and sample
# multisets concatenated (bounded at `_MAX_BUCKET_SAMPLES` per bucket PER
# RANK — quantiles over the merged window are approximate past the cap,
# exactly as approximate as each rank's own view). Merging is associative
# and rank-order-independent, and merging a single rank is the identity
# (pinned in tests/test_fleet.py).


def merge_counters(per_rank: List[Dict[str, float]]) -> Dict[str, float]:
    """Sum counter dicts across ranks (missing names = 0 contribution)."""
    out: Dict[str, float] = {}
    for counters in per_rank:
        for name, v in (counters or {}).items():
            out[name] = out.get(name, 0.0) + float(v)
    return out


def merge_gauges(per_rank: Dict[Any, Dict[str, float]]) -> Dict[str, Dict[str, Any]]:
    """Merge gauge dicts keyed by rank: each name keeps the full per-rank
    map plus min/max/sum rollups. Rank keys may be ints or their JSON string
    round-trips; the merged `by_rank` map is keyed by int rank."""
    out: Dict[str, Dict[str, Any]] = {}
    for rank in sorted(per_rank, key=lambda r: int(r)):
        for name, v in (per_rank[rank] or {}).items():
            e = out.setdefault(
                name,
                {"by_rank": {}, "min": float("inf"), "max": float("-inf"), "sum": 0.0},
            )
            v = float(v)
            e["by_rank"][int(rank)] = v
            e["min"] = min(e["min"], v)
            e["max"] = max(e["max"], v)
            e["sum"] += v
    return out


def merge_histograms(
    per_rank: List[Dict[str, Dict[str, float]]]
) -> Dict[str, Dict[str, float]]:
    """Merge cumulative histogram summaries: counts/sums add, min/max fold."""
    out: Dict[str, Dict[str, float]] = {}
    for hists in per_rank:
        for name, h in (hists or {}).items():
            e = out.setdefault(
                name,
                {"count": 0.0, "sum": 0.0, "min": float("inf"), "max": float("-inf")},
            )
            e["count"] += float(h.get("count", 0.0))
            e["sum"] += float(h.get("sum", 0.0))
            e["min"] = min(e["min"], float(h.get("min", float("inf"))))
            e["max"] = max(e["max"], float(h.get("max", float("-inf"))))
    return out


def merge_windows(exports: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge `windows_export()` payloads from several ranks, aligned by
    bucket AGE (newest first). Exports must share one bucket width — a
    heterogeneous fleet has no meaningful common window and raises
    ValueError (the fleet plane treats that rank's payload as unusable, it
    never averages misaligned buckets). Per-bucket counts and sums stay
    exact; merged sample lists are the sorted concatenation."""
    exports = [e for e in exports if e]
    if not exports:
        return {"bucket_seconds": None, "bucket_count": 0, "counters": {}, "hists": {}, "ranks": 0}
    bucket_s = float(exports[0]["bucket_seconds"])
    for e in exports[1:]:
        if abs(float(e["bucket_seconds"]) - bucket_s) > 1e-9:
            raise ValueError(
                "merge_windows: mismatched bucket_seconds "
                f"({e['bucket_seconds']} vs {bucket_s}) — ranks must share "
                "metrics_bucket_seconds for their windows to align"
            )
    n = max(int(e["bucket_count"]) for e in exports)
    counters: Dict[str, List[float]] = {}
    hists: Dict[str, Dict[str, List[Any]]] = {}
    for e in exports:
        for name, vals in (e.get("counters") or {}).items():
            acc = counters.setdefault(name, [0.0] * n)
            for i, v in enumerate(vals[:n]):
                acc[i] += float(v)
        for name, h in (e.get("hists") or {}).items():
            hacc = hists.setdefault(
                name,
                {
                    "counts": [0.0] * n,
                    "sums": [0.0] * n,
                    "samples": [[] for _ in range(n)],
                },
            )
            m = min(n, len(h["counts"]))
            for i in range(m):
                hacc["counts"][i] += float(h["counts"][i])
                hacc["sums"][i] += float(h["sums"][i])
                hacc["samples"][i].extend(h["samples"][i])
    for h in hists.values():
        h["samples"] = [sorted(s) for s in h["samples"]]
    return {
        "bucket_seconds": bucket_s,
        "bucket_count": n,
        "counters": counters,
        "hists": hists,
        "ranks": len(exports),
    }


class MergedWindows:
    """Read-side view over a `merge_windows()` result that duck-types the
    registry's windowed readers (`rate` / `window_count` / `window_quantile`
    / `window_fraction_over` / `snapshot()["gauges"]`) so the SLO evaluator
    runs unchanged over a CLUSTER window (ops_plane.slo.evaluate_reader).
    The merged export is a static snapshot: "now" is the newest bucket, and
    a `window_s` selects the newest ``round(window_s / bucket)`` buckets."""

    def __init__(
        self,
        merged: Optional[Dict[str, Any]],
        gauges: Optional[Dict[str, float]] = None,
    ) -> None:
        self._m = merged or {
            "bucket_seconds": None,
            "bucket_count": 0,
            "counters": {},
            "hists": {},
        }
        # cluster gauge view for gauge_ceiling specs: name -> the value the
        # ceiling should judge (the fleet plane passes per-rank MAX — a
        # ceiling breached anywhere is breached)
        self._gauges = dict(gauges or {})

    def _k(self, window_s: Optional[float]) -> int:
        bucket_s = self._m.get("bucket_seconds") or 0.0
        n = int(self._m.get("bucket_count") or 0)
        if not bucket_s or not n:
            return 0
        horizon = bucket_s * n
        span = horizon if window_s is None else min(max(float(window_s), bucket_s), horizon)
        return max(1, min(n, int(round(span / bucket_s))))

    def bucket_seconds(self) -> float:
        return float(self._m.get("bucket_seconds") or 0.0)

    def window_horizon_s(self) -> float:
        return self.bucket_seconds() * int(self._m.get("bucket_count") or 0)

    def rate(self, name: str, window_s: Optional[float] = None) -> Optional[float]:
        vals = (self._m.get("counters") or {}).get(name)
        k = self._k(window_s)
        if vals is None or not k:
            return None
        span = k * float(self._m["bucket_seconds"])
        return sum(vals[:k]) / span if span > 0 else None

    def window_samples(self, name: str, window_s: Optional[float] = None) -> List[float]:
        h = (self._m.get("hists") or {}).get(name)
        if h is None:
            return []
        out: List[float] = []
        for i in range(min(self._k(window_s), len(h["samples"]))):
            out.extend(h["samples"][i])
        return out

    def window_count(self, name: str, window_s: Optional[float] = None) -> float:
        h = (self._m.get("hists") or {}).get(name)
        if h is None:
            return 0.0
        return float(sum(h["counts"][: self._k(window_s)]))

    def window_quantile(
        self, name: str, q: float, window_s: Optional[float] = None
    ) -> Optional[float]:
        return quantile_of(self.window_samples(name, window_s), q)

    def window_fraction_over(
        self, name: str, threshold: float, window_s: Optional[float] = None
    ) -> Optional[Tuple[float, int]]:
        samples = self.window_samples(name, window_s)
        if not samples:
            return None
        bad = sum(1 for s in samples if s > threshold)
        return bad / len(samples), len(samples)

    def snapshot(self) -> Dict[str, Any]:
        return {"gauges": dict(self._gauges)}


# ---------------------------------------------------------------- registry --


class MetricsRegistry:
    """Process-global metrics store. All methods are thread-safe; all record
    methods are no-ops while telemetry is disabled (callers may skip the call
    entirely with `enabled()` — both layers check)."""

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("telemetry.MetricsRegistry._lock")
        self._counters: Dict[str, float] = {}  # guarded-by: _lock
        self._gauges: Dict[str, float] = {}  # guarded-by: _lock
        self._hists: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock
        # per-histogram ring of the most recent observations (quantile())
        self._hist_samples: Dict[str, List[float]] = {}  # guarded-by: _lock
        self._spans: List[Dict[str, Any]] = []  # guarded-by: _lock
        # monotone count of ALL spans ever recorded — `_spans` is trimmed to a
        # bound, so marks must not be absolute list indices
        self._spans_total: int = 0  # guarded-by: _lock
        self._convergence: Dict[str, List[List[float]]] = {}  # guarded-by: _lock
        # rolling windows (ops plane): params resolved at first record after
        # construction/reset, one ring per counter/histogram
        self._win_cfg: Optional[Tuple[float, int]] = None  # guarded-by: _lock
        self._win_counters: Dict[str, _CounterRing] = {}  # guarded-by: _lock
        self._win_hists: Dict[str, _HistRing] = {}  # guarded-by: _lock

    def _win(self) -> Tuple[float, int]:
        """Window params, resolved once per construction/reset (caller holds
        the lock)."""
        if self._win_cfg is None:
            self._win_cfg = _window_params()
        return self._win_cfg

    # -- record ------------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        if not _STATE.on:
            return
        now = time.monotonic()
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value
            ring = self._win_counters.get(name)
            if ring is None:
                bucket_s, n = self._win()
                ring = self._win_counters[name] = _CounterRing(bucket_s, n)
            ring.add(now, value)

    def gauge(self, name: str, value: float) -> None:
        if not _STATE.on:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def gauge_max(self, name: str, value: float) -> None:
        """Watermark gauge: keep the maximum ever seen (HBM peaks)."""
        if not _STATE.on:
            return
        with self._lock:
            self._gauges[name] = max(self._gauges.get(name, float("-inf")), float(value))

    def observe(self, name: str, value: float) -> None:
        """Histogram observation (count/sum/min/max summary, not buckets)."""
        if not _STATE.on:
            return
        now = time.monotonic()
        with self._lock:
            h = self._hists.setdefault(
                name, {"count": 0.0, "sum": 0.0, "min": float("inf"), "max": float("-inf")}
            )
            h["count"] += 1.0
            h["sum"] += value
            h["min"] = min(h["min"], value)
            h["max"] = max(h["max"], value)
            samples = self._hist_samples.setdefault(name, [])
            samples.append(float(value))
            if len(samples) > _MAX_HIST_SAMPLES:
                del samples[: -_MAX_HIST_SAMPLES // 2]
            ring = self._win_hists.get(name)
            if ring is None:
                bucket_s, n = self._win()
                ring = self._win_hists[name] = _HistRing(bucket_s, n)
            ring.add(now, float(value))

    def record_span(
        self,
        name: str,
        path: str,
        wall_s: float,
        attrs: Dict[str, Any],
        t0: Optional[float] = None,
    ) -> None:
        if not _STATE.on:
            return
        rec = {"kind": "span", "name": name, "path": path, "wall_s": wall_s,
               "rank": _rank(), **_diag().trace_tags(), **attrs}
        if t0 is not None:
            # wall-clock start: what lets trace_merge place this span on a
            # cross-rank timeline (perf_counter has no cross-process meaning)
            rec["t0"] = t0
        with self._lock:
            self._spans.append(rec)
            self._spans_total += 1
            if len(self._spans) > _MAX_SPAN_RECORDS:
                del self._spans[: -_MAX_SPAN_RECORDS // 2]
        self.observe(f"span.{path}", wall_s)
        _sink_write(rec)

    def record_convergence(self, solver: str, iteration: int, value: float) -> None:
        if not _STATE.on:
            return
        with self._lock:
            pts = self._convergence.setdefault(solver, [])
            if len(pts) >= _MAX_CONVERGENCE_POINTS:
                # ring-buffer semantics: drop the OLDEST point so `last` (and
                # the tail a long-lived process cares about) stays current;
                # surface the truncation instead of silently losing data
                pts.pop(0)
                self._counters[f"{solver}.convergence_points_dropped"] = (
                    self._counters.get(f"{solver}.convergence_points_dropped", 0.0) + 1.0
                )
            pts.append([int(iteration), float(value)])

    # -- read --------------------------------------------------------------
    def quantile(self, name: str, q: float) -> Optional[float]:
        """Quantile estimate over histogram `name`'s retained sample window
        (the most recent ``_MAX_HIST_SAMPLES`` observations — a long-lived
        serving process reads CURRENT latency, not all-time). None when no
        observations exist. Nearest-rank on the sorted window."""
        with self._lock:
            samples = list(self._hist_samples.get(name) or ())
        return quantile_of(samples, q)

    # -- windowed reads (ops plane) ----------------------------------------
    def window_horizon_s(self) -> float:
        """The rolling-window horizon (bucket width x bucket count)."""
        with self._lock:
            bucket_s, n = self._win()
        return bucket_s * n

    def bucket_seconds(self) -> float:
        with self._lock:
            return self._win()[0]

    def rate(self, name: str, window_s: Optional[float] = None) -> Optional[float]:
        """Counter increments per second over the most recent `window_s`
        (None = the whole ring horizon; any window clamps to it). None for a
        counter never incremented since the last reset — a never-seen metric
        has no rate, which is different from a zero one."""
        with self._lock:
            ring = self._win_counters.get(name)
            if ring is None:
                return None
            total, span = ring.window_sum(time.monotonic(), window_s)
        return total / span if span > 0 else None

    def window_count(self, name: str, window_s: Optional[float] = None) -> float:
        """Observations recorded into histogram `name` within the window."""
        with self._lock:
            ring = self._win_hists.get(name)
            if ring is None:
                return 0.0
            return float(ring.window_count(time.monotonic(), window_s))

    def window_quantile(
        self, name: str, q: float, window_s: Optional[float] = None
    ) -> Optional[float]:
        """Quantile over histogram `name`'s observations within the most
        recent `window_s` (clamped to the ring horizon). Approximate past
        ``_MAX_BUCKET_SAMPLES`` observations per bucket; None when the window
        holds no samples."""
        with self._lock:
            ring = self._win_hists.get(name)
            if ring is None:
                return None
            samples = ring.window_samples(time.monotonic(), window_s)
        return quantile_of(samples, q)

    def window_fraction_over(
        self, name: str, threshold: float, window_s: Optional[float] = None
    ) -> Optional[Tuple[float, int]]:
        """(fraction of windowed observations strictly above `threshold`,
        sample count) — the SLO burn-rate numerator. None when the window is
        empty (no traffic is not a violation)."""
        with self._lock:
            ring = self._win_hists.get(name)
            if ring is None:
                return None
            samples = ring.window_samples(time.monotonic(), window_s)
        if not samples:
            return None
        bad = sum(1 for s in samples if s > threshold)
        return bad / len(samples), len(samples)

    def windows_snapshot(self) -> Dict[str, Any]:
        """Machine-readable rolling-window view — what the exporters and
        `ops_plane.report()` serve: per-counter rates over the fast window
        (60s, clamped to the horizon) AND the full horizon, and per-histogram
        p50/p99/count over the full horizon. Taken under ONE lock hold at ONE
        clock instant, so every metric in the snapshot describes the same
        window — and a scrape costs one lock round-trip, not O(metrics)."""
        now = time.monotonic()
        with self._lock:
            bucket_s, n = self._win()
            horizon = bucket_s * n
            fast = min(60.0, horizon)
            rates: Dict[str, Any] = {}
            for name, ring in self._win_counters.items():
                fsum, fspan = ring.window_sum(now, fast)
                hsum, hspan = ring.window_sum(now, None)
                rates[name] = {
                    "fast_per_s": fsum / fspan if fspan > 0 else None,
                    "horizon_per_s": hsum / hspan if hspan > 0 else None,
                }
            quantiles: Dict[str, Any] = {}
            for name, ring in self._win_hists.items():
                samples = ring.window_samples(now, None)
                quantiles[name] = {
                    "p50": quantile_of(samples, 0.5),
                    "p99": quantile_of(samples, 0.99),
                    "count": float(ring.window_count(now, None)),
                }
        return {
            "bucket_seconds": bucket_s,
            "bucket_count": n,
            "horizon_s": horizon,
            "rates": rates,
            "quantiles": quantiles,
        }

    def windows_export(self) -> Dict[str, Any]:
        """Merge-form export of the rolling windows (docs/observability.md
        "Fleet plane"): per-counter per-bucket increment sums and
        per-histogram per-bucket (count, sum, sorted samples), all indexed by
        bucket AGE (newest first). Ring heads are per-process
        ``time.monotonic()`` bucket indices with no cross-process meaning, so
        age is the only alignment the fleet merger can use — cross-rank skew
        is bounded by one bucket width. Samples are sorted here so the merge
        is canonical: merging one export is the identity, and merge order
        cannot change the result. Taken under one lock hold at one clock
        instant, like `windows_snapshot`."""
        now = time.monotonic()
        with self._lock:
            bucket_s, n = self._win()
            counters: Dict[str, List[float]] = {}
            for name, ring in self._win_counters.items():
                b = int(now // ring.bucket_s)
                if ring.head is None or b > ring.head:
                    ring._advance(b)
                assert ring.head is not None
                counters[name] = [
                    float(ring.vals[(ring.head - i) % ring.n]) for i in range(ring.n)
                ]
            hists: Dict[str, Dict[str, List[Any]]] = {}
            for name, hring in self._win_hists.items():
                b = int(now // hring.bucket_s)
                if hring.head is None or b > hring.head:
                    hring._advance(b)
                assert hring.head is not None
                idx = [(hring.head - i) % hring.n for i in range(hring.n)]
                hists[name] = {
                    "counts": [float(hring.counts[i]) for i in idx],
                    "sums": [float(hring.sums[i]) for i in idx],
                    "samples": [sorted(hring.samples[i]) for i in idx],
                }
        return {
            "bucket_seconds": bucket_s,
            "bucket_count": n,
            "counters": counters,
            "hists": hists,
        }

    def convergence_trace(self, solver: str) -> List[List[float]]:
        """[(iteration, value), ...] points recorded for `solver`."""
        with self._lock:
            return [list(p) for p in self._convergence.get(solver, [])]

    def snapshot(self) -> Dict[str, Any]:
        """Machine-readable state: counters, gauges, histogram summaries, and
        per-path span aggregates. Safe to json.dumps. Span aggregates come
        from the `span.<path>` histograms, which see EVERY span — the raw
        record list is trimmed to a bound and would under-count."""
        with self._lock:
            spans: Dict[str, Dict[str, float]] = {}
            for hname, h in self._hists.items():
                if hname.startswith("span."):
                    spans[hname[len("span."):]] = {
                        "count": h["count"],
                        "total_s": h["sum"],
                        "min_s": h["min"],
                        "max_s": h["max"],
                    }
            snap = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: dict(v) for k, v in self._hists.items()},
                "spans": spans,
                "convergence": {
                    k: {"points": len(v), "last": v[-1] if v else None}
                    for k, v in self._convergence.items()
                },
            }
        # flight-recorder health rides the snapshot (and therefore the bench
        # JSON "telemetry" embedding) — outside the lock: the recorder has its
        # own and never calls back into the registry while holding it
        snap["flightrec"] = _diag().flight_recorder().stats()
        return snap

    class _Mark:
        __slots__ = ("counters", "hists", "spans_total")

    def mark(self) -> "MetricsRegistry._Mark":
        """Cheap position marker for `delta()` (fit-scoped metrics)."""
        m = MetricsRegistry._Mark()
        with self._lock:
            m.counters = dict(self._counters)
            m.hists = {k: dict(v) for k, v in self._hists.items()}
            m.spans_total = self._spans_total
        return m

    def delta(self, m: "MetricsRegistry._Mark") -> Dict[str, Any]:
        """Counters/histograms accumulated SINCE `m`, spans recorded since
        `m`, and current gauges — the per-fit view attached to models."""
        with self._lock:
            counters = {
                k: v - m.counters.get(k, 0.0)
                for k, v in self._counters.items()
                if v != m.counters.get(k, 0.0)
            }
            hists = {}
            for k, v in self._hists.items():
                prev = m.hists.get(k)
                count = v["count"] - (prev["count"] if prev else 0.0)
                if count:
                    hists[k] = {
                        "count": count,
                        "sum": v["sum"] - (prev["sum"] if prev else 0.0),
                    }
            # spans recorded since the mark, bounded by what the trim kept:
            # the count since the mark is exact (monotone counter); if more
            # than the retained window were recorded, only the tail survives
            since = max(0, self._spans_total - m.spans_total)
            spans = [dict(r) for r in self._spans[len(self._spans) - min(since, len(self._spans)):]] if since else []
            # copy gauges UNDER the lock: the copy used to happen in the
            # return expression after releasing it, so a concurrent gauge()
            # could resize the dict mid-iteration (found by the
            # guard-discipline rule)
            gauges = dict(self._gauges)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "spans": spans,
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._hist_samples.clear()
            self._spans.clear()
            self._convergence.clear()
            # window rings rebuild against the CURRENT config on next record —
            # this is how tests (and reconfiguring operators) apply new
            # bucket params
            self._win_cfg = None
            self._win_counters.clear()
            self._win_hists.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def summary() -> str:
    """One-line-per-stage human summary of the current registry state:
    ``print(telemetry.summary())`` after any fit. Ends with a flight-recorder
    health line (events recorded/dropped for this rank) — ring truncation is
    never silent (docs/observability.md "no silent caps")."""
    snap = _REGISTRY.snapshot()
    lines = []
    for path, agg in sorted(snap["spans"].items()):
        lines.append(
            f"{path}: {agg['total_s']:.3f}s total / {int(agg['count'])} call(s)"
        )
    for name, v in sorted(snap["counters"].items()):
        lines.append(f"{name}: {v:,.0f}")
    for name, v in sorted(snap["gauges"].items()):
        lines.append(f"{name}: {v:,.6g}")
    fr = snap["flightrec"]  # snapshot() already embeds the recorder stats
    if fr["enabled"]:
        lines.append(
            f"flightrec rank{_rank()}: {fr['recorded']} events recorded / "
            f"{fr['dropped']} dropped (capacity {fr['capacity']})"
        )
    else:
        lines.append("flightrec: disabled (SRML_FLIGHTREC=0)")
    return "\n".join(lines)


def summarize_histogram(name: str, *, window_s: Optional[float] = None) -> Dict[str, Optional[float]]:
    """One histogram's summary view: cumulative count/sum/mean/min/max plus
    p50/p99 — over the retained cumulative sample window by default, over the
    most recent `window_s` of the rolling ring when given. THE shared p50/p99
    extraction (`ScoringEngine.stats`, `FitScheduler.stats`, and the ops
    plane all delegate here — hand-rolled copies would silently diverge now
    that windowed quantiles exist). All values None when nothing was
    observed."""
    reg = _REGISTRY
    with reg._lock:
        h = reg._hists.get(name)
        cum = dict(h) if h else None
    out: Dict[str, Optional[float]] = {
        "count": cum["count"] if cum else None,
        "sum": cum["sum"] if cum else None,
        "mean": (cum["sum"] / cum["count"]) if cum and cum["count"] else None,
        "min": cum["min"] if cum else None,
        "max": cum["max"] if cum else None,
    }
    if window_s is None:
        out["p50"] = reg.quantile(name, 0.5)
        out["p99"] = reg.quantile(name, 0.99)
    else:
        out["p50"] = reg.window_quantile(name, 0.5, window_s)
        out["p99"] = reg.window_quantile(name, 0.99, window_s)
        out["window_count"] = reg.window_count(name, window_s)
    return out


def tenant_metric(base: str, tenant: str) -> str:
    """THE per-tenant metric naming contract: ``<base>.<tenant>`` with the
    tenant sanitized to the metric-name alphabet (every run of characters
    outside ``[A-Za-z0-9_.:-]`` collapses to one ``_``). The serving plane
    records per-tenant siblings of its global surfaces
    (``serve.queue_wait_s.<tenant>``, ``serve.e2e_s.<tenant>``,
    ``serve.rows.<tenant>``) through this one helper — the overload
    controller and the ops report read the SAME names back, so the contract
    lives here, not duplicated at each call site
    (docs/observability.md "Serving plane")."""
    safe = re.sub(r"[^A-Za-z0-9_.:\-]+", "_", tenant) or "_"
    return f"{base}.{safe}"


# ------------------------------------------------------------------- sinks --

_SINK_LOCK = lockcheck.make_lock("telemetry._SINK_LOCK")
_SINK_FILES: Dict[str, Any] = {}


def _close_sinks() -> None:
    """Close every cached sink handle (disable() and interpreter exit) so
    re-pointing the sink per job never accumulates open fds."""
    with _SINK_LOCK:
        for f in _SINK_FILES.values():
            try:
                f.close()
            except OSError:  # pragma: no cover
                pass
        _SINK_FILES.clear()


atexit.register(_close_sinks)


def _sink_path() -> Optional[str]:
    """Per-rank JSONL path: rank 0 owns the configured path, other ranks get
    `<path>.rank<r>` so SPMD processes on a shared filesystem never interleave
    writes in one file."""
    path = _STATE.sink_path
    if not path:
        return None
    r = _rank()
    return path if r == 0 else f"{path}.rank{r}"


def _sink_write(rec: Dict[str, Any]) -> None:
    path = _sink_path()
    if path is None:
        return
    line = json.dumps(rec, default=_json_default) + "\n"
    with _SINK_LOCK:  # held-ok: the sink lock exists to serialize exactly this local append (open-once + write + flush); no other lock is ever taken under it
        f = _SINK_FILES.get(path)
        if f is None or f.closed:
            try:
                f = open(path, "a")
            except OSError:
                return
            _SINK_FILES[path] = f
        f.write(line)
        f.flush()


def _json_default(o: Any):
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:  # pragma: no cover
        pass
    return str(o)


# ------------------------------------------------------------------- spans --


class _NoopSpan:
    """Shared do-nothing span — what `span()` returns while disabled."""

    __slots__ = ()
    wall_s: Optional[float] = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "logger", "path", "wall_s", "_t0", "_w0", "_ta")

    def __init__(self, name: str, logger: Any, attrs: Dict[str, Any]) -> None:
        self.name = name
        self.logger = logger
        self.attrs = attrs
        self.wall_s: Optional[float] = None

    def __enter__(self) -> "_Span":
        stack = getattr(_LOCAL, "stack", None)
        if stack is None:
            stack = _LOCAL.stack = []
        stack.append(self.name)
        self.path = "/".join(stack)
        # xprof alignment: TraceAnnotation is the NVTX-range analog — it tags
        # this wall-clock interval in any ACTIVE jax.profiler trace and is
        # near-free when no trace is running. Spans must never break when the
        # profiler is inactive, so failures here are swallowed.
        self._ta = None
        try:
            import jax

            self._ta = jax.profiler.TraceAnnotation(self.path)
            self._ta.__enter__()
        except Exception:
            self._ta = None
        self._w0 = time.time()  # wall clock, for cross-rank trace merging
        _diag().record_event("span_begin", name=self.name, path=self.path)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> bool:
        self.wall_s = time.perf_counter() - self._t0
        if self._ta is not None:
            try:
                self._ta.__exit__(exc_type, exc_val, exc_tb)
            except Exception:
                pass
        stack = _LOCAL.stack
        if stack and stack[-1] == self.name:
            stack.pop()
        if exc_type is None:
            _diag().record_event("span_end", name=self.name, path=self.path,
                                 wall_s=self.wall_s)
            _REGISTRY.record_span(self.name, self.path, self.wall_s, self.attrs,
                                  t0=self._w0)
            if self.logger is not None:
                self.logger.info("stage %s: %.3fs", self.path, self.wall_s)
        else:
            _diag().record_event("span_fail", name=self.name, path=self.path,
                                 error=exc_type.__name__)
        return False


def span(name: str, *, logger: Any = None, **attrs: Any):
    """Nestable timing span.

    ``with telemetry.span("solve", index=0): ...`` records wall time (and the
    nesting path, e.g. ``fit/solve``) into the registry + JSONL sink, tags the
    interval in any active `jax.profiler` trace, and — when `logger` is passed
    (the estimator `verbose` path) — logs ``stage <path>: <t>s``. Returns a
    shared no-op object when telemetry is disabled and no logger wants the
    timing, so the disabled cost is one branch."""
    if not _STATE.on and logger is None:
        return _NOOP_SPAN
    return _Span(name, logger, attrs)


# ------------------------------------------------------ efficiency hooks ----
#
# The profiling hook layer for the efficiency attribution plane
# (ops_plane/efficiency.py, docs/observability.md "Efficiency plane").
# Instrumented call sites stay one cheap call away from telemetry — they
# never import the ops_plane package themselves — and the disabled path is
# one `_STATE.on` branch returning a shared no-op (the same identity
# contract `span()` pins). Timers only ever wrap a host fetch the caller
# already performs; they add no syncs of their own.


class _NoopCompileEvent:
    """Shared do-nothing compile event — what `compile_event()` returns
    while disabled (`cache_hit` stays False)."""

    __slots__ = ()
    cache_hit = False

    def __enter__(self) -> "_NoopCompileEvent":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NOOP_COMPILE_EVENT = _NoopCompileEvent()


def _efficiency():
    """sys.modules probe for the efficiency plane (the `_window_params`
    idiom): attribution scopes are only ever opened through code that
    imported the module, so an absent module means no scope can be active
    and the hook can bail without importing anything."""
    return sys.modules.get(
        (__package__ or "spark_rapids_ml_tpu") + ".ops_plane.efficiency"
    )


def device_wait(stage: str):
    """Time a `block_until_ready`/`np.asarray` wait at a boundary that
    ALREADY host-fetches, attributing the wall to the active attribution
    scope's `execute` kind under `stage`. Shared no-op when telemetry is
    disabled or no scope is open on this thread."""
    if not _STATE.on:
        return _NOOP_SPAN
    eff = _efficiency()
    if eff is None or not eff.active():
        return _NOOP_SPAN
    return eff.device_wait_timer(stage)


def host_section(stage: str):
    """Time host-side boundary work (checkpoint serialization, response
    slicing) into the active scope's `host` kind. Same no-op contract as
    `device_wait`."""
    if not _STATE.on:
        return _NOOP_SPAN
    eff = _efficiency()
    if eff is None or not eff.active():
        return _NOOP_SPAN
    return eff.host_section_timer(stage)


def compile_event(program: str, shape_key: Any):
    """Ledger one jit entry-point execution keyed (program, shape-class) —
    first sighting records the body's wall as compile time, later sightings
    count as cache hits (`cm.cache_hit`). Process-wide: records with or
    without an attribution scope. Shared no-op when disabled."""
    if not _STATE.on:
        return _NOOP_COMPILE_EVENT
    from .ops_plane import efficiency

    return efficiency.compile_event(program, str(shape_key))


def note_flops(flops: float, *, chips: int = 1) -> None:
    """Record the active attribution scope's analytic FLOP estimate (the
    `_solver_flop_estimate` hooks) — the roofline/MFU numerator. No-op when
    disabled or outside a scope."""
    if not _STATE.on:
        return
    eff = _efficiency()
    if eff is not None and eff.active():
        eff.note_flops(flops, chips=chips)


def attribution(label: str, *, tenant: Any = None):
    """Open an efficiency attribution window outside the fit path (the
    serving engine opens one per dispatch group). Shared no-op span when
    telemetry is disabled; fits get theirs through `fit_scope`."""
    if not _STATE.on:
        return _NOOP_SPAN
    from .ops_plane import efficiency

    return efficiency.attribution_scope(label, tenant=tenant)


# ------------------------------------------------------- derived recorders --


def record_device_memory() -> None:
    """Sample per-device memory stats into HBM watermark gauges, where the
    backend exposes them (`Device.memory_stats()` — TPU/GPU yes, CPU None).
    Callers invoke this only where the backend is already live (inside fit);
    it never initializes a backend on its own."""
    if not _STATE.on:
        return
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return
    peak = in_use = 0
    seen = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        seen = True
        peak = max(peak, int(stats.get("peak_bytes_in_use", 0)))
        in_use = max(in_use, int(stats.get("bytes_in_use", 0)))
    if seen:
        _REGISTRY.gauge_max("device.peak_bytes_in_use", peak)
        _REGISTRY.gauge("device.bytes_in_use", in_use)


def record_solver_result(
    solver: str,
    *,
    n_iter: int,
    objective: Optional[float] = None,
    stalled: bool = False,
) -> None:
    """Host-side record of a completed iterative solve: iteration counter,
    final objective gauge, and a final convergence point."""
    if not _STATE.on:
        return
    _REGISTRY.inc(f"{solver}.fits")
    _REGISTRY.inc(f"{solver}.iterations", float(n_iter))
    if stalled:
        _REGISTRY.inc(f"{solver}.line_search_stalls")
    if objective is not None:
        _REGISTRY.gauge(f"{solver}.objective", float(objective))
        _REGISTRY.record_convergence(solver, int(n_iter), float(objective))
    _diag().record_event(
        "solver_result", solver=solver, n_iter=int(n_iter),
        objective=float(objective) if objective is not None else None,
    )


def record_convergence_point(solver: str, iteration: Any, value: Any) -> None:
    """Per-iteration convergence sample. Shaped for `jax.debug.callback`
    (iteration/value arrive as device scalars); also callable from host loops
    (KMeans passes plain floats)."""
    if not _STATE.on:
        return
    import numpy as np

    it, val = int(np.asarray(iteration)), float(np.asarray(value))
    _REGISTRY.record_convergence(solver, it, val)
    _diag().record_event("solver_tick", solver=solver, iteration=it, value=val)


# --------------------------------------------------------------- fit scope --


@contextlib.contextmanager
def fit_scope(label: str):
    """Fit-scoped metrics view. Yields a dict whose ``metrics`` key is filled
    at exit with the registry DELTA accumulated during the fit (counters,
    per-fit spans, histogram deltas, current gauges) — what `core` attaches
    to models as ``_fit_metrics`` — and writes one ``{"kind": "fit"}``
    snapshot record to the JSONL sink."""
    scope: Dict[str, Any] = {"metrics": {}}
    if not _STATE.on:
        yield scope
        return
    m = _REGISTRY.mark()
    # the efficiency attribution scope rides the fit scope: one window per
    # top-level fit (nested fits attribute into the outer window — the
    # scope itself refuses to nest)
    from .ops_plane import efficiency

    eff_cm = efficiency.attribution_scope(label)
    try:
        with eff_cm:
            yield scope
    finally:
        delta = _REGISTRY.delta(m)
        scope["metrics"] = delta
        eff_summary = getattr(eff_cm, "summary", None)
        if eff_summary:
            scope["efficiency"] = eff_summary
        _sink_write(
            {
                "kind": "fit",
                "estimator": label,
                "rank": _rank(),
                **_diag().trace_tags(),
                "counters": delta["counters"],
                "gauges": delta["gauges"],
                "histograms": delta["histograms"],
            }
        )
