#
# Structured telemetry: spans, counters/gauges/histograms, and sinks.
#
# The observability substrate for the whole hot path (ingest -> layout ->
# solve -> transform). The reference's story here is NVTX ranges in the Scala
# plugin plus ad-hoc wall-clock logs in the Python tier (SURVEY.md §5); the
# TPU-native answer is:
#
#   * `span("stage", **attrs)` — a nestable context manager that records wall
#     time into the registry, emits a `jax.profiler.TraceAnnotation` so the
#     stage lines up inside xprof traces (the NVTX-range analog), and logs the
#     stage timing at a caller-provided logger (the old `verbose` prints).
#   * `MetricsRegistry` — a process-global store of counters (bytes ingested,
#     device_put calls, rendezvous rounds), gauges (HBM watermark, solver
#     objective), histograms (rendezvous latency), span aggregates, and
#     per-iteration solver convergence traces.
#   * sinks — a JSONL file (`SRML_METRICS_PATH`) receiving one record per
#     span plus one snapshot record per fit, and an in-process `snapshot()`
#     dict that bench.py embeds into BENCH_* emission and `fit` attaches to
#     models as `model._fit_metrics`.
#
# Contracts:
#   * ZERO-COST WHEN DISABLED: `span()` returns a shared no-op object and
#     every record method is behind one flag check — a disabled fit does no
#     timing, no allocation, no I/O.
#   * SPMD-SAFE: records are rank-tagged, the JSONL sink writes to a per-rank
#     file (rank 0 owns the bare path), and nothing here performs a
#     collective of its own.
#   * Per-iteration convergence traces from jitted solvers use
#     `jax.debug.callback` and are gated SEPARATELY (`SRML_TRACE_CONVERGENCE`
#     / `enable(convergence=True)`): a host callback per L-BFGS iteration is
#     free on CPU but a dispatch round-trip through a remote TPU tunnel, so
#     it never rides along with plain counter telemetry. The gate is read at
#     TRACE time — toggling it after a solver shape has compiled does not
#     retrace that shape.
#
from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "enabled",
    "enable",
    "disable",
    "convergence_trace_enabled",
    "span",
    "registry",
    "MetricsRegistry",
    "snapshot",
    "summary",
    "fit_scope",
    "record_device_memory",
    "record_solver_result",
    "record_convergence_point",
]

# Span records kept in-process (the JSONL sink receives every record; the
# in-memory list is for snapshot()/summary() and stays bounded).
_MAX_SPAN_RECORDS = 4096
_MAX_CONVERGENCE_POINTS = 10_000
# Most-recent observations retained per histogram for quantile() estimation
# (serving latency p50/p99); the count/sum/min/max summary sees EVERY
# observation — only the quantile view is windowed.
_MAX_HIST_SAMPLES = 1024


class _State:
    __slots__ = ("on", "sink_path", "convergence")

    def __init__(self) -> None:
        self.sink_path: Optional[str] = os.environ.get("SRML_METRICS_PATH") or None
        self.on: bool = bool(self.sink_path) or bool(os.environ.get("SRML_TELEMETRY"))
        self.convergence: bool = bool(os.environ.get("SRML_TRACE_CONVERGENCE"))


_STATE = _State()
_LOCAL = threading.local()  # per-thread span stack (nesting -> paths)

# Cached handle to the diagnostics module (trace tags + flight recorder).
# Lazy: diagnostics never imports telemetry at module level and vice versa,
# so whichever loads first wins without a cycle.
_DIAG: Any = None


def _diag():
    global _DIAG
    if _DIAG is None:
        from . import diagnostics

        _DIAG = diagnostics
    return _DIAG


def enabled() -> bool:
    """Whether telemetry recording is on (one branch — THE hot-path check)."""
    return _STATE.on


def convergence_trace_enabled() -> bool:
    """Whether jitted solvers should bake per-iteration host callbacks in.
    Read at trace time; see the module header for the compile-cache caveat."""
    return _STATE.on and _STATE.convergence


def enable(sink_path: Optional[str] = None, *, convergence: Optional[bool] = None) -> None:
    """Turn telemetry on, optionally pointing the JSONL sink at `sink_path`
    and/or toggling per-iteration convergence tracing. Re-pointing the sink
    closes the previous file handles (no fd accumulation across jobs)."""
    _STATE.on = True
    if sink_path is not None:
        if sink_path != _STATE.sink_path:
            _close_sinks()
        _STATE.sink_path = sink_path
    if convergence is not None:
        _STATE.convergence = bool(convergence)


def disable() -> None:
    """Turn telemetry off (records already taken stay in the registry) and
    close any open sink files."""
    _STATE.on = False
    _close_sinks()


def _rank() -> int:
    """This process's rank for record tagging and per-rank sink naming.
    Delegates to diagnostics (active TpuContext > set_process_rank >
    SRML_RANK env > 0) so telemetry records and flight-recorder dumps agree
    on rank identity. Control-plane only — never touches the XLA backend
    (jax.process_index() would initialize it)."""
    return _diag()._rank()


# ---------------------------------------------------------------- registry --


class MetricsRegistry:
    """Process-global metrics store. All methods are thread-safe; all record
    methods are no-ops while telemetry is disabled (callers may skip the call
    entirely with `enabled()` — both layers check)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Dict[str, float]] = {}
        # per-histogram ring of the most recent observations (quantile())
        self._hist_samples: Dict[str, List[float]] = {}
        self._spans: List[Dict[str, Any]] = []
        # monotone count of ALL spans ever recorded — `_spans` is trimmed to a
        # bound, so marks must not be absolute list indices
        self._spans_total: int = 0
        self._convergence: Dict[str, List[List[float]]] = {}

    # -- record ------------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        if not _STATE.on:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        if not _STATE.on:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def gauge_max(self, name: str, value: float) -> None:
        """Watermark gauge: keep the maximum ever seen (HBM peaks)."""
        if not _STATE.on:
            return
        with self._lock:
            self._gauges[name] = max(self._gauges.get(name, float("-inf")), float(value))

    def observe(self, name: str, value: float) -> None:
        """Histogram observation (count/sum/min/max summary, not buckets)."""
        if not _STATE.on:
            return
        with self._lock:
            h = self._hists.setdefault(
                name, {"count": 0.0, "sum": 0.0, "min": float("inf"), "max": float("-inf")}
            )
            h["count"] += 1.0
            h["sum"] += value
            h["min"] = min(h["min"], value)
            h["max"] = max(h["max"], value)
            samples = self._hist_samples.setdefault(name, [])
            samples.append(float(value))
            if len(samples) > _MAX_HIST_SAMPLES:
                del samples[: -_MAX_HIST_SAMPLES // 2]

    def record_span(
        self,
        name: str,
        path: str,
        wall_s: float,
        attrs: Dict[str, Any],
        t0: Optional[float] = None,
    ) -> None:
        if not _STATE.on:
            return
        rec = {"kind": "span", "name": name, "path": path, "wall_s": wall_s,
               "rank": _rank(), **_diag().trace_tags(), **attrs}
        if t0 is not None:
            # wall-clock start: what lets trace_merge place this span on a
            # cross-rank timeline (perf_counter has no cross-process meaning)
            rec["t0"] = t0
        with self._lock:
            self._spans.append(rec)
            self._spans_total += 1
            if len(self._spans) > _MAX_SPAN_RECORDS:
                del self._spans[: -_MAX_SPAN_RECORDS // 2]
        self.observe(f"span.{path}", wall_s)
        _sink_write(rec)

    def record_convergence(self, solver: str, iteration: int, value: float) -> None:
        if not _STATE.on:
            return
        with self._lock:
            pts = self._convergence.setdefault(solver, [])
            if len(pts) >= _MAX_CONVERGENCE_POINTS:
                # ring-buffer semantics: drop the OLDEST point so `last` (and
                # the tail a long-lived process cares about) stays current;
                # surface the truncation instead of silently losing data
                pts.pop(0)
                self._counters[f"{solver}.convergence_points_dropped"] = (
                    self._counters.get(f"{solver}.convergence_points_dropped", 0.0) + 1.0
                )
            pts.append([int(iteration), float(value)])

    # -- read --------------------------------------------------------------
    def quantile(self, name: str, q: float) -> Optional[float]:
        """Quantile estimate over histogram `name`'s retained sample window
        (the most recent ``_MAX_HIST_SAMPLES`` observations — a long-lived
        serving process reads CURRENT latency, not all-time). None when no
        observations exist. Nearest-rank on the sorted window."""
        with self._lock:
            samples = self._hist_samples.get(name)
            if not samples:
                return None
            ordered = sorted(samples)
        q = min(max(float(q), 0.0), 1.0)
        idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
        return ordered[idx]

    def convergence_trace(self, solver: str) -> List[List[float]]:
        """[(iteration, value), ...] points recorded for `solver`."""
        with self._lock:
            return [list(p) for p in self._convergence.get(solver, [])]

    def snapshot(self) -> Dict[str, Any]:
        """Machine-readable state: counters, gauges, histogram summaries, and
        per-path span aggregates. Safe to json.dumps. Span aggregates come
        from the `span.<path>` histograms, which see EVERY span — the raw
        record list is trimmed to a bound and would under-count."""
        with self._lock:
            spans: Dict[str, Dict[str, float]] = {}
            for hname, h in self._hists.items():
                if hname.startswith("span."):
                    spans[hname[len("span."):]] = {
                        "count": h["count"],
                        "total_s": h["sum"],
                        "min_s": h["min"],
                        "max_s": h["max"],
                    }
            snap = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: dict(v) for k, v in self._hists.items()},
                "spans": spans,
                "convergence": {
                    k: {"points": len(v), "last": v[-1] if v else None}
                    for k, v in self._convergence.items()
                },
            }
        # flight-recorder health rides the snapshot (and therefore the bench
        # JSON "telemetry" embedding) — outside the lock: the recorder has its
        # own and never calls back into the registry while holding it
        snap["flightrec"] = _diag().flight_recorder().stats()
        return snap

    class _Mark:
        __slots__ = ("counters", "hists", "spans_total")

    def mark(self) -> "MetricsRegistry._Mark":
        """Cheap position marker for `delta()` (fit-scoped metrics)."""
        m = MetricsRegistry._Mark()
        with self._lock:
            m.counters = dict(self._counters)
            m.hists = {k: dict(v) for k, v in self._hists.items()}
            m.spans_total = self._spans_total
        return m

    def delta(self, m: "MetricsRegistry._Mark") -> Dict[str, Any]:
        """Counters/histograms accumulated SINCE `m`, spans recorded since
        `m`, and current gauges — the per-fit view attached to models."""
        with self._lock:
            counters = {
                k: v - m.counters.get(k, 0.0)
                for k, v in self._counters.items()
                if v != m.counters.get(k, 0.0)
            }
            hists = {}
            for k, v in self._hists.items():
                prev = m.hists.get(k)
                count = v["count"] - (prev["count"] if prev else 0.0)
                if count:
                    hists[k] = {
                        "count": count,
                        "sum": v["sum"] - (prev["sum"] if prev else 0.0),
                    }
            # spans recorded since the mark, bounded by what the trim kept:
            # the count since the mark is exact (monotone counter); if more
            # than the retained window were recorded, only the tail survives
            since = max(0, self._spans_total - m.spans_total)
            spans = [dict(r) for r in self._spans[len(self._spans) - min(since, len(self._spans)):]] if since else []
        return {
            "counters": counters,
            "gauges": dict(self._gauges),
            "histograms": hists,
            "spans": spans,
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._hist_samples.clear()
            self._spans.clear()
            self._convergence.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def summary() -> str:
    """One-line-per-stage human summary of the current registry state:
    ``print(telemetry.summary())`` after any fit. Ends with a flight-recorder
    health line (events recorded/dropped for this rank) — ring truncation is
    never silent (docs/observability.md "no silent caps")."""
    snap = _REGISTRY.snapshot()
    lines = []
    for path, agg in sorted(snap["spans"].items()):
        lines.append(
            f"{path}: {agg['total_s']:.3f}s total / {int(agg['count'])} call(s)"
        )
    for name, v in sorted(snap["counters"].items()):
        lines.append(f"{name}: {v:,.0f}")
    for name, v in sorted(snap["gauges"].items()):
        lines.append(f"{name}: {v:,.6g}")
    fr = snap["flightrec"]  # snapshot() already embeds the recorder stats
    if fr["enabled"]:
        lines.append(
            f"flightrec rank{_rank()}: {fr['recorded']} events recorded / "
            f"{fr['dropped']} dropped (capacity {fr['capacity']})"
        )
    else:
        lines.append("flightrec: disabled (SRML_FLIGHTREC=0)")
    return "\n".join(lines)


# ------------------------------------------------------------------- sinks --

_SINK_LOCK = threading.Lock()
_SINK_FILES: Dict[str, Any] = {}


def _close_sinks() -> None:
    """Close every cached sink handle (disable() and interpreter exit) so
    re-pointing the sink per job never accumulates open fds."""
    with _SINK_LOCK:
        for f in _SINK_FILES.values():
            try:
                f.close()
            except OSError:  # pragma: no cover
                pass
        _SINK_FILES.clear()


atexit.register(_close_sinks)


def _sink_path() -> Optional[str]:
    """Per-rank JSONL path: rank 0 owns the configured path, other ranks get
    `<path>.rank<r>` so SPMD processes on a shared filesystem never interleave
    writes in one file."""
    path = _STATE.sink_path
    if not path:
        return None
    r = _rank()
    return path if r == 0 else f"{path}.rank{r}"


def _sink_write(rec: Dict[str, Any]) -> None:
    path = _sink_path()
    if path is None:
        return
    line = json.dumps(rec, default=_json_default) + "\n"
    with _SINK_LOCK:
        f = _SINK_FILES.get(path)
        if f is None or f.closed:
            try:
                f = open(path, "a")
            except OSError:
                return
            _SINK_FILES[path] = f
        f.write(line)
        f.flush()


def _json_default(o: Any):
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:  # pragma: no cover
        pass
    return str(o)


# ------------------------------------------------------------------- spans --


class _NoopSpan:
    """Shared do-nothing span — what `span()` returns while disabled."""

    __slots__ = ()
    wall_s: Optional[float] = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "logger", "path", "wall_s", "_t0", "_w0", "_ta")

    def __init__(self, name: str, logger: Any, attrs: Dict[str, Any]) -> None:
        self.name = name
        self.logger = logger
        self.attrs = attrs
        self.wall_s: Optional[float] = None

    def __enter__(self) -> "_Span":
        stack = getattr(_LOCAL, "stack", None)
        if stack is None:
            stack = _LOCAL.stack = []
        stack.append(self.name)
        self.path = "/".join(stack)
        # xprof alignment: TraceAnnotation is the NVTX-range analog — it tags
        # this wall-clock interval in any ACTIVE jax.profiler trace and is
        # near-free when no trace is running. Spans must never break when the
        # profiler is inactive, so failures here are swallowed.
        self._ta = None
        try:
            import jax

            self._ta = jax.profiler.TraceAnnotation(self.path)
            self._ta.__enter__()
        except Exception:
            self._ta = None
        self._w0 = time.time()  # wall clock, for cross-rank trace merging
        _diag().record_event("span_begin", name=self.name, path=self.path)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> bool:
        self.wall_s = time.perf_counter() - self._t0
        if self._ta is not None:
            try:
                self._ta.__exit__(exc_type, exc_val, exc_tb)
            except Exception:
                pass
        stack = _LOCAL.stack
        if stack and stack[-1] == self.name:
            stack.pop()
        if exc_type is None:
            _diag().record_event("span_end", name=self.name, path=self.path,
                                 wall_s=self.wall_s)
            _REGISTRY.record_span(self.name, self.path, self.wall_s, self.attrs,
                                  t0=self._w0)
            if self.logger is not None:
                self.logger.info("stage %s: %.3fs", self.path, self.wall_s)
        else:
            _diag().record_event("span_fail", name=self.name, path=self.path,
                                 error=exc_type.__name__)
        return False


def span(name: str, *, logger: Any = None, **attrs: Any):
    """Nestable timing span.

    ``with telemetry.span("solve", index=0): ...`` records wall time (and the
    nesting path, e.g. ``fit/solve``) into the registry + JSONL sink, tags the
    interval in any active `jax.profiler` trace, and — when `logger` is passed
    (the estimator `verbose` path) — logs ``stage <path>: <t>s``. Returns a
    shared no-op object when telemetry is disabled and no logger wants the
    timing, so the disabled cost is one branch."""
    if not _STATE.on and logger is None:
        return _NOOP_SPAN
    return _Span(name, logger, attrs)


# ------------------------------------------------------- derived recorders --


def record_device_memory() -> None:
    """Sample per-device memory stats into HBM watermark gauges, where the
    backend exposes them (`Device.memory_stats()` — TPU/GPU yes, CPU None).
    Callers invoke this only where the backend is already live (inside fit);
    it never initializes a backend on its own."""
    if not _STATE.on:
        return
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return
    peak = in_use = 0
    seen = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        seen = True
        peak = max(peak, int(stats.get("peak_bytes_in_use", 0)))
        in_use = max(in_use, int(stats.get("bytes_in_use", 0)))
    if seen:
        _REGISTRY.gauge_max("device.peak_bytes_in_use", peak)
        _REGISTRY.gauge("device.bytes_in_use", in_use)


def record_solver_result(
    solver: str,
    *,
    n_iter: int,
    objective: Optional[float] = None,
    stalled: bool = False,
) -> None:
    """Host-side record of a completed iterative solve: iteration counter,
    final objective gauge, and a final convergence point."""
    if not _STATE.on:
        return
    _REGISTRY.inc(f"{solver}.fits")
    _REGISTRY.inc(f"{solver}.iterations", float(n_iter))
    if stalled:
        _REGISTRY.inc(f"{solver}.line_search_stalls")
    if objective is not None:
        _REGISTRY.gauge(f"{solver}.objective", float(objective))
        _REGISTRY.record_convergence(solver, int(n_iter), float(objective))
    _diag().record_event(
        "solver_result", solver=solver, n_iter=int(n_iter),
        objective=float(objective) if objective is not None else None,
    )


def record_convergence_point(solver: str, iteration: Any, value: Any) -> None:
    """Per-iteration convergence sample. Shaped for `jax.debug.callback`
    (iteration/value arrive as device scalars); also callable from host loops
    (KMeans passes plain floats)."""
    if not _STATE.on:
        return
    import numpy as np

    it, val = int(np.asarray(iteration)), float(np.asarray(value))
    _REGISTRY.record_convergence(solver, it, val)
    _diag().record_event("solver_tick", solver=solver, iteration=it, value=val)


# --------------------------------------------------------------- fit scope --


@contextlib.contextmanager
def fit_scope(label: str):
    """Fit-scoped metrics view. Yields a dict whose ``metrics`` key is filled
    at exit with the registry DELTA accumulated during the fit (counters,
    per-fit spans, histogram deltas, current gauges) — what `core` attaches
    to models as ``_fit_metrics`` — and writes one ``{"kind": "fit"}``
    snapshot record to the JSONL sink."""
    scope: Dict[str, Any] = {"metrics": {}}
    if not _STATE.on:
        yield scope
        return
    m = _REGISTRY.mark()
    try:
        yield scope
    finally:
        delta = _REGISTRY.delta(m)
        scope["metrics"] = delta
        _sink_write(
            {
                "kind": "fit",
                "estimator": label,
                "rank": _rank(),
                **_diag().trace_tags(),
                "counters": delta["counters"],
                "gauges": delta["gauges"],
                "histograms": delta["histograms"],
            }
        )
