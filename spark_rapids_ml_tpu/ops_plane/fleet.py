#
# Fleet plane: cluster-level aggregation over the per-rank ops planes
# (docs/observability.md "Fleet plane").
#
# Everything PRs 13/17 built is per-process: each rank evaluates SLOs over
# its own windows, serves its own /metrics, writes its own snapshot. This
# module answers the CLUSTER questions — "is the fleet healthy", "which rank
# is the straggler", "what is fleet chip utilization per tenant" — through
# two transports that share ONE set of merge definitions (telemetry.py's
# merge_counters / merge_gauges / merge_histograms / merge_windows):
#
#   * LIVE ops round — a compact window-snapshot exchange piggybacked on the
#     rendezvous control plane (the reference's BarrierTaskContext.allGather
#     analog). Rank 0 alone decides WHEN a round is due (`ops_due`, at most
#     one per `config["fleet_ops_round_seconds"]`, default one metrics
#     bucket width) and broadcasts the decision as a `|ops` suffix on the
#     trace-exchange payload it already sends (diagnostics.trace_scope) —
#     a local time throttle on every rank would desync the lockstep round
#     counters, a single decider cannot. The round itself is NON-FATAL at
#     two layers (the PR-5 trace-exchange contract): a rank that cannot
#     build its payload sends the bare marker so the round still completes
#     lockstep, and a failed allgather (dead peer, timeout) records
#     `ops_round_failed`, ticks `fleet.ops_rounds_failed`, and returns the
#     survivors to local-only views — the fit's own next round surfaces the
#     real failure WITH retry protection. Disabled telemetry short-circuits
#     before any rendezvous use: zero extra rounds, zero records.
#
#   * OFFLINE merge — `read_rank_snapshots()` over the per-rank rotating
#     `ops_snapshot*.json` files (export.write_snapshot). Works post-hoc and
#     with dead ranks: each snapshot's `meta` header (rank/host/pid/t) lets
#     the merger DROP stale dead-rank data (`config["fleet_stale_snapshot_s"]`)
#     and name missing ranks instead of silently averaging them in.
#
# Layered on the merged view: cluster SLO verdicts over the merged windows
# (slo.evaluate_reader x telemetry.MergedWindows — a `min_count` spec floor
# is what lets a fleet-wide burn trip while every thin per-rank slice stays
# vacuously healthy), straggler attribution from per-rank rendezvous
# round-entry/exit stamps (a rank slowest by `fleet_straggler_min_lag_s`
# for `fleet_straggler_windows` consecutive ops rounds fires a
# flight-recorder event + an audit entry naming it), and the fleet rollup
# of the 2-D ledger's chip occupancy (`fleet.chips_busy`/`fleet.chips_idle`
# gauges, per-tenant device-time sums via ledger.merge_tenant_usage).
#
from __future__ import annotations

import json
import os
import socket
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..utils import lockcheck

__all__ = [
    "ops_due",
    "ops_round",
    "note_round_exit",
    "cluster_view",
    "cluster_report",
    "local_payload",
    "merge_payloads",
    "merge_reports",
    "read_rank_snapshots",
    "reset",
    "OPS_ROUND_PREFIX",
    "OPS_ROUND_FLAG",
]

# Versioned payload prefix (the trace-round convention): a format change is
# detectable instead of silently misparsed. The bare prefix with no body is
# the degraded "I could not build a payload" marker — it keeps the round
# lockstep and is skipped at merge.
OPS_ROUND_PREFIX = "OPS1:"
# The flag rank 0 appends to its trace-exchange payload to schedule a round.
OPS_ROUND_FLAG = "ops"

# Rounds of (epoch, round, t_enter, t_exit) stamps retained per rank for the
# straggler attributor — enough to cover the rounds between two ops rounds
# without growing with fit length.
_MAX_ROUND_EXITS = 64

_LOCK = lockcheck.make_lock("ops_plane.fleet._LOCK")
_LAST_ROUND_T: Optional[float] = None  # rank-0 throttle clock  # guarded-by: _LOCK
_LAST_INGEST_KEY: Optional[Tuple[Any, Any]] = None  # guarded-by: _LOCK
_CLUSTER: Optional[Dict[str, Any]] = None  # last merged live view  # guarded-by: _LOCK
# rank -> deque of [epoch, round, t_enter, t_exit]; in a real deployment
# each process only ever holds its own rank's stamps, in the threaded
# LocalRendezvous harness all ranks share this dict (keyed apart by rank)
_ROUND_EXITS: Dict[int, "deque[List[Any]]"] = {}  # guarded-by: _LOCK
_STRAGGLER_STREAKS: Dict[int, int] = {}  # guarded-by: _LOCK


def _cfg(key: str, default: Any) -> Any:
    """Config knob via lazy core import (the slo._specs pattern)."""
    try:
        from ..core import config

        v = config.get(key)
        return default if v is None else v
    except Exception:  # pragma: no cover - config must never fail the plane
        return default


def _interval_s() -> float:
    from .. import telemetry

    v = _cfg("fleet_ops_round_seconds", None)
    if v is None:
        return float(telemetry.registry().bucket_seconds())
    return max(0.0, float(v))


# ------------------------------------------------------------- live round --


def ops_due(now: Optional[float] = None) -> bool:
    """Rank 0's throttle decision for the piggybacked ops round: True at
    most once per `fleet_ops_round_seconds` (default: one metrics bucket
    width), and never while telemetry is disabled. ONLY rank 0 calls this —
    every other rank follows the `|ops` flag rank 0 broadcasts, so the
    fleet agrees on whether a round happens without a clock agreement."""
    from .. import telemetry

    if not telemetry.enabled():
        return False
    global _LAST_ROUND_T
    t = time.monotonic() if now is None else float(now)
    interval = _interval_s()
    with _LOCK:
        if _LAST_ROUND_T is None or t - _LAST_ROUND_T >= interval:
            _LAST_ROUND_T = t
            return True
    return False


def note_round_exit(
    rank: int, round_index: Any, epoch: Any, t_enter: float, t_exit: float
) -> None:
    """Stamp one rendezvous round's entry/exit wall-clock for `rank`
    (called from the base allgather's telemetry branch via sys.modules
    probe). The stamps ride the next ops-round payload; the merger turns
    cross-rank deltas into straggler lags. Bounded per rank."""
    with _LOCK:
        dq = _ROUND_EXITS.get(int(rank))
        if dq is None:
            dq = _ROUND_EXITS[int(rank)] = deque(maxlen=_MAX_ROUND_EXITS)
        dq.append([epoch, round_index, float(t_enter), float(t_exit)])


def local_payload(rank: Optional[int] = None) -> Dict[str, Any]:
    """This rank's compact ops-round payload: identity meta, cumulative
    counters/gauges/histograms, the age-indexed window export, per-tenant
    ledger usage, and the recent rendezvous round stamps."""
    from .. import diagnostics, telemetry

    reg = telemetry.registry()
    snap = reg.snapshot()
    r = diagnostics._rank() if rank is None else int(rank)
    with _LOCK:
        exits = [list(e) for e in _ROUND_EXITS.get(r, ())]
    tenants: Dict[str, Any] = {}
    try:
        from ..scheduler.ledger import global_ledger

        tenants = global_ledger().tenant_usage()
    except Exception:  # pragma: no cover - the ledger is optional here
        tenants = {}
    return {
        "v": 1,
        "rank": r,
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "t": time.time(),
        "trace_id": diagnostics.trace_tags().get("trace_id"),
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "hists": snap["histograms"],
        "windows": reg.windows_export(),
        "tenants": tenants,
        "round_exits": exits,
    }


def ops_round(
    rendezvous: Any,
    *,
    force: bool = False,
    payload: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """Run ONE ops round over `rendezvous` — every rank must call in
    lockstep (the trace-scope piggyback guarantees that for the implicit
    path; harnesses call with `force=True`). Returns the merged cluster
    view, or None when degraded to local-only (failed round, local
    telemetry off). NON-FATAL by contract: no exception escapes.

    `payload` is a TEST HOOK: a crafted per-rank payload exchanged instead
    of `local_payload()` (the threaded LocalRendezvous harness shares one
    registry across "ranks", so distinct-rank assertions need it)."""
    from .. import diagnostics, telemetry

    body = ""
    try:
        if payload is not None:
            body = json.dumps(payload)
        elif telemetry.enabled():
            body = json.dumps(local_payload(getattr(rendezvous, "rank", None)))
    except Exception:
        # degraded: the bare marker keeps the round lockstep — peers merge
        # without this rank and name it missing
        body = ""
    try:
        gathered = rendezvous.allgather(OPS_ROUND_PREFIX + body)
    except Exception as e:
        # a dead peer / timeout degrades THIS rank to local-only views; the
        # fit's own next round surfaces the real failure with retry
        # protection (the trace-exchange contract, diagnostics.trace_scope)
        diagnostics.record_event("ops_round_failed", error=type(e).__name__)
        if telemetry.enabled():
            telemetry.registry().inc("fleet.ops_rounds_failed")
        return None
    if not telemetry.enabled() and payload is None:
        return None  # participated for lockstep only; nothing recorded
    try:
        return _ingest_round(
            gathered,
            epoch=getattr(rendezvous, "_epoch", None),
            round_index=getattr(rendezvous, "_round", None),
            nranks=int(getattr(rendezvous, "nranks", len(gathered))),
        )
    except Exception as e:  # pragma: no cover - merge must never fail a fit
        diagnostics.record_event("ops_round_failed", error=type(e).__name__)
        if telemetry.enabled():
            telemetry.registry().inc("fleet.ops_rounds_failed")
        return None


def _parse_gathered(gathered: List[str]) -> List[Dict[str, Any]]:
    payloads: List[Dict[str, Any]] = []
    for item in gathered:
        if not isinstance(item, str) or not item.startswith(OPS_ROUND_PREFIX):
            continue
        raw = item[len(OPS_ROUND_PREFIX):]
        if not raw:
            continue  # degraded bare marker
        try:
            p = json.loads(raw)
        except (ValueError, TypeError):
            continue  # unparseable peers are merged around, never fatal
        if isinstance(p, dict):
            payloads.append(p)
    return payloads


def _ingest_round(
    gathered: List[str], *, epoch: Any, round_index: Any, nranks: int
) -> Optional[Dict[str, Any]]:
    """Merge one gathered round into the cluster view. Idempotent per
    (epoch, round): in the threaded LocalRendezvous harness every "rank"
    thread lands here with the SAME gathered list — the first merges and
    fires events, the rest get the cached view (a real multi-process fleet
    never dedups: each process is its own fleet-plane instance)."""
    from .. import telemetry

    global _LAST_INGEST_KEY, _CLUSTER
    key = (epoch, round_index) if round_index is not None else None
    with _LOCK:
        if key is not None and _LAST_INGEST_KEY == key:
            # another "rank" thread of this process already claimed this
            # round's merge — return its view (or None if it is still
            # merging; the next round refreshes). Re-merging here would
            # double-advance the straggler streaks.
            return dict(_CLUSTER) if _CLUSTER is not None else None
        _LAST_INGEST_KEY = key
    payloads = _parse_gathered(gathered)
    view = merge_payloads(payloads, expected=nranks)
    events = _update_stragglers(view)
    with _LOCK:
        _CLUSTER = view
    reg = telemetry.registry()
    if telemetry.enabled():
        reg.inc("fleet.ops_rounds")
        reg.gauge("fleet.ranks_reporting", float(len(payloads)))
        lags = (view.get("straggler") or {}).get("lags_s") or {}
        if lags:
            reg.gauge("rendezvous.straggler_lag_s", max(lags.values()))
        pool = (view.get("tenants") or {}).get("_pool") or {}
        if "chips_busy" in pool:
            reg.gauge("fleet.chips_busy", float(pool["chips_busy"]))
        if "chips_idle" in pool:
            reg.gauge("fleet.chips_idle", float(pool["chips_idle"]))
    _fire_straggler_events(events)
    return view


# ------------------------------------------------------------------ merge --


def merge_payloads(
    payloads: List[Dict[str, Any]], *, expected: Optional[int] = None
) -> Dict[str, Any]:
    """Merge per-rank payloads (live round or snapshot-derived) into the
    cluster view, delegating every metric-surface merge to telemetry.py's
    one set of definitions. Ranks that sent nothing usable are NAMED in
    `missing`, never silently averaged in."""
    from .. import telemetry
    from ..scheduler import ledger as _ledger
    from . import slo as _slo

    by_rank: Dict[int, Dict[str, Any]] = {}
    for p in payloads:
        try:
            by_rank[int(p.get("rank", 0))] = p
        except (TypeError, ValueError):
            continue
    ranks_meta = {
        r: {
            "host": p.get("host"),
            "pid": p.get("pid"),
            "t": p.get("t"),
            "trace_id": p.get("trace_id"),
        }
        for r, p in by_rank.items()
    }
    n = int(expected) if expected else (max(by_rank) + 1 if by_rank else 0)
    missing = sorted(set(range(n)) - set(by_rank))
    ordered = [by_rank[r] for r in sorted(by_rank)]
    counters = telemetry.merge_counters([p.get("counters") or {} for p in ordered])
    gauges = telemetry.merge_gauges(
        {r: (by_rank[r].get("gauges") or {}) for r in by_rank}
    )
    hists = telemetry.merge_histograms([p.get("hists") or {} for p in ordered])
    windows: Optional[Dict[str, Any]] = None
    windows_error: Optional[str] = None
    try:
        windows = telemetry.merge_windows(
            [p["windows"] for p in ordered if p.get("windows")]
        )
    except ValueError as e:
        windows_error = str(e)
    tenants = _ledger.merge_tenant_usage([p.get("tenants") or {} for p in ordered])
    view: Dict[str, Any] = {
        "t": time.time(),
        "nranks": n,
        "ranks_reporting": len(by_rank),
        "ranks": ranks_meta,
        "missing": missing,
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "windows": windows,
        "tenants": tenants,
        "straggler": {"lags_s": _round_lags(ordered)},
    }
    if windows_error:
        view["windows_error"] = windows_error
    # cluster SLO verdict over the MERGED window: gauge ceilings judge the
    # per-rank max (breached anywhere = breached)
    reader = telemetry.MergedWindows(
        windows, {name: e["max"] for name, e in gauges.items()}
    )
    try:
        view["health"] = _slo.cluster_health(reader)
    except Exception:  # pragma: no cover - a bad spec never fails the merge
        view["health"] = {"healthy": True, "failing": [], "specs": 0, "verdicts": []}
    return view


def _round_lags(payloads: List[Dict[str, Any]]) -> Dict[int, float]:
    """Per-rank straggler lag from the exchanged round stamps: for every
    (epoch, round) at least two ranks stamped, a rank's lag is how long
    AFTER the first arrival it entered the round (exit deltas are
    barrier-flattened — everyone leaves when the last rank arrives, so
    arrival skew measured on the same exit-correlated round is the
    attributable delta). A rank's reported lag is its worst over the
    stamped rounds."""
    by_round: Dict[Tuple[Any, Any], Dict[int, float]] = {}
    for p in payloads:
        try:
            r = int(p.get("rank", 0))
        except (TypeError, ValueError):
            continue
        for stamp in p.get("round_exits") or []:
            try:
                e, rnd, t_enter = stamp[0], stamp[1], float(stamp[2])
            except (TypeError, ValueError, IndexError):
                continue
            by_round.setdefault((e, rnd), {})[r] = t_enter
    lags: Dict[int, float] = {}
    for times in by_round.values():
        if len(times) < 2:
            continue
        t0 = min(times.values())
        for r, t in times.items():
            lags[r] = max(lags.get(r, 0.0), t - t0)
    return lags


def _update_stragglers(view: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Advance the consecutive-slowest streaks from one merged view; return
    the flag events to fire (outside the lock). A rank must be the slowest
    by at least `fleet_straggler_min_lag_s` for `fleet_straggler_windows`
    consecutive ops rounds; firing resets its streak so one sustained
    straggle names it once per K rounds, not every round."""
    lags: Dict[int, float] = (view.get("straggler") or {}).get("lags_s") or {}
    min_lag = float(_cfg("fleet_straggler_min_lag_s", 0.05))
    k = max(1, int(_cfg("fleet_straggler_windows", 3)))
    slowest: Optional[int] = None
    if lags:
        slowest = max(lags, key=lambda r: lags[r])
        if lags[slowest] < min_lag:
            slowest = None
    events: List[Dict[str, Any]] = []
    with _LOCK:
        for r in list(_STRAGGLER_STREAKS):
            if r != slowest:
                _STRAGGLER_STREAKS.pop(r)
        if slowest is not None:
            streak = _STRAGGLER_STREAKS.get(slowest, 0) + 1
            if streak >= k:
                events.append(
                    {"rank": slowest, "lag_s": lags[slowest], "rounds": streak}
                )
                streak = 0
            _STRAGGLER_STREAKS[slowest] = streak
        streaks = dict(_STRAGGLER_STREAKS)
    view["straggler"]["slowest"] = slowest
    view["straggler"]["streaks"] = streaks
    return events


def _fire_straggler_events(events: List[Dict[str, Any]]) -> None:
    from .. import diagnostics, telemetry
    from . import audit

    for ev in events:
        diagnostics.record_event(
            "straggler_detected",
            rank=ev["rank"], lag_s=ev["lag_s"], rounds=ev["rounds"],
        )
        audit.record_decision(
            "straggler",
            "fleet",
            "flagged",
            subject=f"rank:{ev['rank']}",
            reason=(
                f"slowest rank for {ev['rounds']} consecutive ops rounds "
                f"(lag {ev['lag_s']:.3f}s)"
            ),
            lag_s=ev["lag_s"],
        )
        if telemetry.enabled():
            telemetry.registry().inc("fleet.stragglers_flagged")


# ---------------------------------------------------------------- offline --


def _report_to_payload(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Shape one per-rank `ops_plane.report()` snapshot like a live
    payload so both transports share merge_payloads."""
    meta = rep.get("meta") or {}
    tel = rep.get("telemetry") or {}
    return {
        "rank": meta.get("rank", 0),
        "host": meta.get("hostname"),
        "pid": meta.get("pid"),
        "t": meta.get("t"),
        "trace_id": meta.get("trace_id"),
        "counters": tel.get("counters") or {},
        "gauges": tel.get("gauges") or {},
        "hists": tel.get("histograms") or {},
        "windows": rep.get("windows_detail"),
        "tenants": rep.get("tenants") or {},
        "round_exits": meta.get("round_exits") or [],
    }


def merge_reports(
    reports: List[Dict[str, Any]], *, expected: Optional[int] = None
) -> Dict[str, Any]:
    """Offline transport: merge per-rank `ops_plane.report()` snapshot dicts
    into the cluster view. No events fire (post-hoc analysis must not
    rewrite the audit trail of the run it examines)."""
    return merge_payloads(
        [_report_to_payload(r) for r in reports], expected=expected
    )


_SNAPSHOT_NAME_RE = None


def read_rank_snapshots(
    directory: str,
    *,
    nranks: Optional[int] = None,
    stale_s: Optional[float] = None,
    now: Optional[float] = None,
) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Scan `directory` for current-generation per-rank snapshots
    (`ops_snapshot.json` = rank 0, `ops_snapshot_rank_<r>.json` for r>0 —
    rotated `.1`.. generations are skipped) and return `(reports, issues)`.
    `issues` names every rank that is `missing` (expected but no file),
    `stale` (meta.t older than `stale_s`, default
    `config["fleet_stale_snapshot_s"]` — dropped from `reports`), or
    `unreadable` — the `opsreport --cluster` partial-fleet verdict."""
    import re as _re

    global _SNAPSHOT_NAME_RE
    if _SNAPSHOT_NAME_RE is None:
        _SNAPSHOT_NAME_RE = _re.compile(r"^ops_snapshot(?:_rank_(\d+))?\.json$")
    if stale_s is None:
        stale_s = float(_cfg("fleet_stale_snapshot_s", 600.0))
    t_now = time.time() if now is None else float(now)
    reports: List[Dict[str, Any]] = []
    seen: Dict[int, str] = {}
    issues: Dict[str, Any] = {"missing": [], "stale": [], "unreadable": []}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return [], {"missing": [], "stale": [], "unreadable": [str(directory)]}
    for name in names:
        m = _SNAPSHOT_NAME_RE.match(name)
        if not m:
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                rep = json.load(f)
        except (OSError, ValueError):
            issues["unreadable"].append(name)
            continue
        meta = rep.get("meta") or {}
        rank = int(meta.get("rank", int(m.group(1) or 0)))
        t = meta.get("t") or rep.get("t")
        if stale_s and t is not None and t_now - float(t) > stale_s:  # wallclock-ok: staleness compares the snapshot's own wall-clock meta.t stamp (written by another process — monotonic clocks don't cross processes)
            issues["stale"].append(rank)
            seen.setdefault(rank, name)
            continue
        if rank in seen and rank not in issues["stale"]:
            continue  # first (canonical) file for a rank wins
        seen[rank] = name
        reports.append(rep)
    have = {int((r.get("meta") or {}).get("rank", 0)) for r in reports}
    n = int(nranks) if nranks else (max(seen) + 1 if seen else 0)
    issues["missing"] = sorted(set(range(n)) - have - set(issues["stale"]))
    issues["nranks"] = n
    return reports, issues


# ------------------------------------------------------------------- views --


def cluster_view() -> Optional[Dict[str, Any]]:
    """The last merged LIVE cluster view (None before any ops round)."""
    with _LOCK:
        return dict(_CLUSTER) if _CLUSTER is not None else None


def cluster_report() -> Dict[str, Any]:
    """The `report(cluster=True)` section: the last live view plus how old
    it is, or `available: False` before any round completed."""
    view = cluster_view()
    if view is None:
        return {"available": False}
    return {"available": True, "age_s": max(0.0, time.time() - view["t"]), **view}


def reset() -> None:
    """Forget throttle/streak/view state (test isolation)."""
    global _LAST_ROUND_T, _LAST_INGEST_KEY, _CLUSTER
    with _LOCK:
        _LAST_ROUND_T = None
        _LAST_INGEST_KEY = None
        _CLUSTER = None
        _ROUND_EXITS.clear()
        _STRAGGLER_STREAKS.clear()
