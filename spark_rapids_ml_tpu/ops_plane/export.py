#
# Exporters: the live scrape surface and the headless snapshot
# (docs/observability.md "Ops plane").
#
# Three ways out of the process:
#
#   * Prometheus text exposition (`render_prometheus()`, served at
#     `/metrics`): every cumulative counter/gauge plus a summary per
#     histogram (count/sum and windowed p50/p99 as `quantile` labels).
#     Names are sanitized `srml_<subsystem>_<name>` and every sample carries
#     a `rank` label — the per-rank attribution mirroring the JSONL sink
#     family's `<path>.rank<r>` naming, so a multi-process SPMD job scrapes
#     into distinct series instead of colliding.
#   * JSON snapshot (`/snapshot`): the full `ops_plane.report()` dict —
#     registry snapshot + rolling windows + SLO verdicts + decision log +
#     per-tenant accounting.
#   * `/healthz`: the SLO health verdict, HTTP 200 while healthy and 503
#     while any configured SLO is failing — evaluated fresh per scrape, so
#     a probe sees the fast burn-rate window's state, not a stale cache.
#
# The HTTP thread is OPT-IN (`SRML_METRICS_PORT`, or an explicit
# `start_server(port)`): a stdlib `http.server.ThreadingHTTPServer` daemon
# thread, default-bound to 127.0.0.1 (`SRML_METRICS_HOST` to widen). This
# module is the ONE sanctioned owner of raw http.server/socket surface and
# Prometheus string assembly in the framework — the ci/analysis
# `exporter-scope` rule keeps it that way (`# exporter-ok` waiver elsewhere).
#
# Headless runs (bench children, CI) skip the port and write ROTATING
# on-disk snapshots instead: `write_snapshot()` renames the previous
# `ops_snapshot.json` down a bounded `.1`/`.2`/... chain under
# `config["ops_snapshot_dir"]`, so a wedged process's last report survives
# for `benchmark/opsreport.py` without unbounded disk growth.
#
from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils import lockcheck

__all__ = [
    "render_prometheus",
    "start_server",
    "stop_server",
    "ensure_server",
    "server_address",
    "write_snapshot",
    "SNAPSHOT_KEEP",
]

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")
SNAPSHOT_KEEP = 5  # rotated generations kept on disk


def _prom_name(name: str) -> str:
    return "srml_" + _NAME_SANITIZE.sub("_", name)


def render_prometheus() -> str:
    """The registry's cumulative + windowed state in Prometheus text format
    (exposition format 0.0.4)."""
    from .. import diagnostics, telemetry

    reg = telemetry.registry()
    snap = reg.snapshot()
    rank = diagnostics._rank()
    lines: List[str] = []

    def sample(name: str, value: Any, extra_labels: str = "") -> None:
        if value is None:
            return
        lines.append(f'{name}{{rank="{rank}"{extra_labels}}} {float(value):g}')

    for name, v in sorted(snap["counters"].items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} counter")
        sample(pname, v)
    for name, v in sorted(snap["gauges"].items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} gauge")
        sample(pname, v)
    for name, h in sorted(snap["histograms"].items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} summary")
        sample(pname, reg.window_quantile(name, 0.5), ',quantile="0.5"')
        sample(pname, reg.window_quantile(name, 0.99), ',quantile="0.99"')
        sample(f"{pname}_count", h.get("count"))
        sample(f"{pname}_sum", h.get("sum"))
    lines.extend(_cluster_lines())
    return "\n".join(lines) + "\n"


def _cluster_lines() -> List[str]:
    """`rank="cluster"` samples from the last merged fleet view (empty
    before any ops round — docs/observability.md "Fleet plane"). Merged
    counters are cluster sums; gauges expose the per-rank min/max/sum
    rollups as `agg`-labelled samples (a scraper must not mistake a
    watermark's sum for a value one rank reported)."""
    from . import fleet as _fleet

    view = _fleet.cluster_view()
    if not view:
        return []
    lines: List[str] = []

    def sample(name: str, value: Any, extra_labels: str = "") -> None:
        if value is None:
            return
        lines.append(f'{name}{{rank="cluster"{extra_labels}}} {float(value):g}')

    for name, v in sorted((view.get("counters") or {}).items()):
        sample(_prom_name(name), v)
    for name, g in sorted((view.get("gauges") or {}).items()):
        pname = _prom_name(name)
        for agg in ("min", "max", "sum"):
            sample(pname, g.get(agg), f',agg="{agg}"')
    for name, h in sorted((view.get("histograms") or {}).items()):
        pname = _prom_name(name)
        sample(f"{pname}_count", h.get("count"))
        sample(f"{pname}_sum", h.get("sum"))
    health = view.get("health") or {}
    if "healthy" in health:
        sample("srml_cluster_healthy", 1.0 if health["healthy"] else 0.0)
    sample("srml_cluster_ranks_reporting", view.get("ranks_reporting"))
    return lines


# ------------------------------------------------------------- HTTP server --

_SERVER_LOCK = lockcheck.make_lock("ops_plane.export._SERVER_LOCK")
_SERVER: Any = None  # guarded-by: _SERVER_LOCK
_SERVER_THREAD: Optional[threading.Thread] = None  # guarded-by: _SERVER_LOCK


def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class _Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, body: bytes, content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 - http.server API
            from .. import ops_plane as _ops  # the package is fully built by serve time
            from . import slo as _slo

            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    self._send(200, render_prometheus().encode(), "text/plain; version=0.0.4")
                elif path == "/healthz":
                    verdict = _slo.health(fresh=True)
                    # the rank-0 exporter also answers for the CLUSTER: the
                    # last merged fleet view's verdict rides the body, and a
                    # failing cluster flips 503 even while this rank's own
                    # windows look healthy (docs/observability.md "Fleet
                    # plane"). No view merged yet -> local-only, unchanged.
                    from . import fleet as _fleet

                    cview = _fleet.cluster_view()
                    healthy = bool(verdict["healthy"])
                    if cview is not None:
                        chealth = cview.get("health") or {}
                        verdict["cluster"] = {
                            "healthy": chealth.get("healthy", True),
                            "failing": chealth.get("failing", []),
                            "ranks_reporting": cview.get("ranks_reporting"),
                            "missing": cview.get("missing", []),
                        }
                        healthy = healthy and bool(chealth.get("healthy", True))
                    body = json.dumps(verdict, default=str).encode()
                    self._send(200 if healthy else 503, body, "application/json")
                elif path in ("/snapshot", "/snapshot.json"):
                    body = json.dumps(_ops.report(), default=str).encode()
                    self._send(200, body, "application/json")
                else:
                    self._send(404, b"not found\n", "text/plain")
            except Exception as e:  # pragma: no cover - a scrape must never kill the thread
                self._send(500, f"{type(e).__name__}: {e}\n".encode(), "text/plain")

        def log_message(self, *args: Any) -> None:  # silence per-request stderr
            pass

    return _Handler


def start_server(port: Optional[int] = None, host: Optional[str] = None) -> Tuple[str, int]:
    """Start (or return) the exporter thread; returns the bound (host, port)
    — port 0 binds an ephemeral port (tests read the returned one)."""
    global _SERVER, _SERVER_THREAD
    from http.server import ThreadingHTTPServer

    if port is None:
        port = int(os.environ.get("SRML_METRICS_PORT", "0") or 0)
    if host is None:
        host = os.environ.get("SRML_METRICS_HOST") or "127.0.0.1"
    with _SERVER_LOCK:
        if _SERVER is not None:
            return _SERVER.server_address[0], int(_SERVER.server_address[1])
        server = ThreadingHTTPServer((host, int(port)), _make_handler())
        server.daemon_threads = True
        thread = threading.Thread(
            target=server.serve_forever, name="srml-ops-exporter", daemon=True
        )
        thread.start()
        _SERVER, _SERVER_THREAD = server, thread
        return server.server_address[0], int(server.server_address[1])


def stop_server() -> None:
    global _SERVER, _SERVER_THREAD
    with _SERVER_LOCK:
        server, thread = _SERVER, _SERVER_THREAD
        _SERVER = _SERVER_THREAD = None
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(5.0)


def server_address() -> Optional[Tuple[str, int]]:
    with _SERVER_LOCK:
        if _SERVER is None:
            return None
        return _SERVER.server_address[0], int(_SERVER.server_address[1])


def ensure_server() -> Optional[Tuple[str, int]]:
    """Start the exporter iff `SRML_METRICS_PORT` is set and no server runs
    yet — the opt-in entry the serving engine, the scheduler, and
    `telemetry.enable()` all call. Best-effort: a busy port logs nothing and
    returns None (the exporter must never fail the plane it observes).

    Multi-rank hosts (docs/observability.md "Fleet plane"): by default only
    RANK 0 binds — co-located ranks racing for one port meant every rank
    but the winner silently lost its scrape surface. `SRML_METRICS_ALL_RANKS=1`
    opts every rank in at `port + rank`, so each rank's surface is
    addressable instead of colliding."""
    from .. import diagnostics

    port = os.environ.get("SRML_METRICS_PORT")
    if not port:
        return server_address()
    try:
        rank = diagnostics._rank()
        if rank:
            if os.environ.get("SRML_METRICS_ALL_RANKS", "") not in ("1", "true", "on"):
                return server_address()
            return start_server(int(port) + rank)
        return start_server(int(port))
    except (OSError, ValueError):
        return None


# ----------------------------------------------------------- disk snapshot --


def _rotate(path: str, keep: int) -> None:
    base, ext = os.path.splitext(path)
    oldest = f"{base}.{keep}{ext}"
    if os.path.exists(oldest):
        os.unlink(oldest)
    for i in range(keep - 1, 0, -1):
        src = f"{base}.{i}{ext}"
        if os.path.exists(src):
            os.replace(src, f"{base}.{i + 1}{ext}")
    if os.path.exists(path):
        os.replace(path, f"{base}.1{ext}")


def write_snapshot(
    path: Optional[str] = None, *, keep: int = SNAPSHOT_KEEP
) -> Optional[str]:
    """Write one `ops_plane.report()` JSON snapshot, rotating previous
    generations down a bounded `.1`..`.keep` chain. `path` defaults to
    ``ops_snapshot.json`` under ``config["ops_snapshot_dir"]`` (seeded from
    `SRML_OPS_SNAPSHOT_DIR`); no directory configured -> no file, returns
    None. Write-then-rename, so a concurrent reader never sees a torn
    file."""
    from .. import ops_plane as _ops

    if path is None:
        d = _snapshot_dir()
        if not d:
            return None
        # per-rank default naming (docs/observability.md "Fleet plane"):
        # rank 0 keeps the canonical name, co-located ranks write
        # `ops_snapshot_rank_<r>.json` — the fleet merger
        # (fleet.read_rank_snapshots / `opsreport --cluster`) scans both,
        # and multi-rank hosts stop overwriting one file
        from .. import diagnostics

        rank = diagnostics._rank()
        name = "ops_snapshot.json" if not rank else f"ops_snapshot_rank_{rank}.json"
        path = os.path.join(d, name)
    rep = _ops.report()
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # write FIRST, rotate only once the new snapshot exists: a failed
        # write (ENOSPC, permissions) must leave the previous generation at
        # the canonical path — "the last report survives" is the contract
        with open(tmp, "w") as f:
            json.dump(rep, f, default=str)
        _rotate(path, max(0, int(keep)))
        os.replace(tmp, path)
    except OSError:  # pragma: no cover - snapshots are best-effort by design
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def _snapshot_dir() -> Optional[str]:
    import sys

    d = os.environ.get("SRML_OPS_SNAPSHOT_DIR")
    if d:
        return d
    # sys.modules probe, not an import: this may run from error paths where
    # paying core's import chain is wrong (same argument as
    # diagnostics.flightrec_dir)
    core = sys.modules.get("spark_rapids_ml_tpu.core")
    if core is not None:
        try:
            return core.config.get("ops_snapshot_dir") or None
        except Exception:  # pragma: no cover
            return None
    return None
