#
# Decision audit trail: one bounded per-process log of every control-plane
# VERDICT, keyed by tenant + trace id (docs/observability.md "Ops plane").
#
# Before this log, "why was tenant X's job demoted at 14:02" meant replaying
# a flight-recorder dump: the verdicts existed, but scattered — fit admission
# on `model._fit_metrics["admission"]`, serving loads/evictions on
# `_serve_metrics`, scheduler preemptions on `_fit_metrics["scheduler"]` —
# each reachable only through the model object that happened to carry it.
# Every admission / demotion / preemption / eviction now ALSO appends one
# structured record here, so the question is one indexed query
# (`decisions(tenant=..., trace_id=...)`, `ops_plane.report()`, or the
# `benchmark/opsreport.py` CLI) against a live process or its snapshot.
#
# Contracts (mirroring the flight recorder, diagnostics.py):
#   * ALWAYS-ON and bounded: recording is one dict + one lock'd deque append;
#     capacity is `SRML_AUDIT_EVENTS` (default 4096) and overwrites are
#     counted (`ops.decisions_dropped`), never silent. Decisions are
#     robustness state, not metrics — they record regardless of the
#     telemetry flag, exactly like the admission stamps they mirror.
#   * every record carries tenant (explicit > enclosing scheduler job >
#     "default"), the active trace tags, and the rank — so the per-tenant
#     query works across fits, serving loads, and scheduler jobs alike.
#   * each decision is mirrored into the flight recorder (`decision` events)
#     so post-mortem timelines interleave verdicts with the failure record.
#
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils import lockcheck

__all__ = ["record_decision", "decisions", "stats", "clear"]

_DEFAULT_CAPACITY = 4096


def _capacity() -> int:
    try:
        return max(1, int(os.environ.get("SRML_AUDIT_EVENTS", _DEFAULT_CAPACITY)))
    except ValueError:  # a typo'd knob must not crash module import
        return _DEFAULT_CAPACITY


_LOCK = lockcheck.make_lock("ops_plane.audit._LOCK")
_LOG: "deque[Dict[str, Any]]" = deque(maxlen=_capacity())  # guarded-by: _LOCK
_TOTAL = 0  # decisions ever recorded (dropped = total - retained)  # guarded-by: _LOCK


def record_decision(
    kind: str,
    subsystem: str,
    verdict: str,
    *,
    subject: str = "",
    tenant: Optional[str] = None,
    reason: str = "",
    **detail: Any,
) -> Dict[str, Any]:
    """Append one decision record.

    `kind` is the verdict family (``admission`` | ``demotion`` |
    ``preemption`` | ``eviction``), `subsystem` the plane that decided
    (``fit`` | ``serving`` | ``scheduler``), `subject` what was decided about
    (estimator/model/job name), and `detail` any JSON-able specifics (byte
    terms, priorities, the violated knob). Returns the record."""
    global _TOTAL
    from .. import diagnostics, telemetry

    if tenant is None:
        try:
            from ..scheduler import context as _sched_ctx

            job = _sched_ctx.current_job()
            tenant = str(job.tenant) if job is not None else "default"
        except Exception:  # pragma: no cover - teardown ordering
            tenant = "default"
    rec: Dict[str, Any] = {
        "t": time.time(),
        "kind": str(kind),
        "subsystem": str(subsystem),
        "subject": str(subject),
        "tenant": tenant,
        "verdict": str(verdict),
        "reason": str(reason),
        "rank": diagnostics._rank(),
        **diagnostics.trace_tags(),
    }
    if detail:
        rec["detail"] = detail
    with _LOCK:
        dropped = len(_LOG) == _LOG.maxlen
        _LOG.append(rec)
        _TOTAL += 1
    if telemetry.enabled():
        reg = telemetry.registry()
        reg.inc("ops.decisions_recorded")
        if dropped:
            reg.inc("ops.decisions_dropped")
    # the flight recorder interleaves verdicts with failures in post-mortems
    diagnostics.record_event(
        "decision", decision_kind=rec["kind"], subsystem=rec["subsystem"],
        subject=rec["subject"], tenant=tenant, verdict=rec["verdict"],
    )
    return rec


def decisions(
    *,
    tenant: Optional[str] = None,
    trace_id: Optional[str] = None,
    kind: Optional[str] = None,
    subsystem: Optional[str] = None,
    limit: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Retained decisions, oldest first, filtered by any combination of
    tenant / trace id / kind / subsystem; `limit` keeps the newest N."""
    with _LOCK:
        out = [dict(r) for r in _LOG]
    if tenant is not None:
        out = [r for r in out if r.get("tenant") == tenant]
    if trace_id is not None:
        out = [r for r in out if r.get("trace_id") == trace_id]
    if kind is not None:
        out = [r for r in out if r.get("kind") == kind]
    if subsystem is not None:
        out = [r for r in out if r.get("subsystem") == subsystem]
    if limit is not None and limit >= 0:
        out = out[-limit:] if limit else []
    return out


def stats() -> Dict[str, Any]:
    with _LOCK:
        return {
            "capacity": _LOG.maxlen,
            "recorded": _TOTAL,
            "retained": len(_LOG),
            "dropped": _TOTAL - len(_LOG),
        }


def clear() -> None:
    """Drop every retained decision (test isolation)."""
    global _TOTAL
    with _LOCK:
        _LOG.clear()
        _TOTAL = 0
