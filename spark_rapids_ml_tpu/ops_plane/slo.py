#
# SLO monitors: declarative objectives over the rolling-window metrics,
# evaluated by multi-window burn rate (docs/observability.md "Ops plane").
#
# `config["slo"]` is a list of specs; each names a metric surface and an
# objective, and the monitor turns the telemetry registry's windowed views
# into a live health verdict:
#
#   {"name": "serve_p99", "kind": "latency",
#    "histogram": "serve.e2e_s", "threshold_s": 0.25, "objective": 0.99}
#   {"name": "queue_wait", "kind": "latency",
#    "histogram": "scheduler.queue_wait_s", "threshold_s": 5.0,
#    "objective": 0.95}
#   {"name": "serve_errors", "kind": "error_rate",
#    "errors": "serve.errors", "total": "serve.requests", "threshold": 0.01}
#   {"name": "ledger_util", "kind": "gauge_ceiling",
#    "gauge": "scheduler.ledger_utilization", "ceiling": 0.95}
#
# BURN RATE (the SRE multiwindow pattern): the error budget of a latency SLO
# with objective 0.99 is 1% of requests over threshold; burn = observed bad
# fraction / budget, so burn 1.0 spends the budget exactly and burn 14.4 on
# the FAST window is a page-now spike. Each spec is evaluated over two
# windows — fast (default 60s) and slow (default 1h, clamped to the ring
# horizon) — and fails when EITHER window's burn crosses its factor
# (`fast_burn` default 14.4, `slow_burn` default 1.0): the fast window
# catches spikes within one bucket width, the slow window catches quiet
# sustained burns the fast one averages away. An EMPTY window is healthy —
# no traffic is not a violation.
#
# Transitions (healthy -> failing and back) fire structured `slo.trip` /
# `slo.clear` events into the flight recorder and tick `slo.trips` /
# `slo.clears`; the current failing-spec count rides the `slo.failing`
# gauge. `maybe_evaluate()` is the inline hook the serving engine and the
# scheduler call where they already record histograms — throttled to one
# evaluation per bucket width, and a no-op without configured specs; the
# /healthz endpoint and `report()` call `evaluate(force=True)` so a scrape
# is always fresh.
#
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import lockcheck

__all__ = [
    "evaluate",
    "evaluate_reader",
    "cluster_health",
    "maybe_evaluate",
    "health",
    "last_verdicts",
    "reset",
    "burn_rate",
    "serving_latency_spec",
]

DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 3600.0
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 1.0

_LOCK = lockcheck.make_lock("ops_plane.slo._LOCK")
_LAST: Dict[str, Dict[str, Any]] = {}  # spec name -> newest verdict  # guarded-by: _LOCK
_TRIPPED: Dict[str, bool] = {}  # guarded-by: _LOCK
_LAST_EVAL: float = 0.0  # guarded-by: _LOCK


def _specs() -> List[Dict[str, Any]]:
    from ..core import config

    raw = config.get("slo") or []
    return [s for s in raw if isinstance(s, dict)]


def _burn_windows(spec: Dict[str, Any], horizon: float):
    fast = min(float(spec.get("fast_window_s", DEFAULT_FAST_WINDOW_S)), horizon)
    slow = min(float(spec.get("slow_window_s", DEFAULT_SLOW_WINDOW_S)), horizon)
    return fast, slow


def _eval_one(spec: Dict[str, Any], reg: Any, horizon: float) -> Dict[str, Any]:
    name = str(spec.get("name") or spec.get("kind") or "slo")
    kind = str(spec.get("kind", ""))
    fast_w, slow_w = _burn_windows(spec, horizon)
    fast_factor = float(spec.get("fast_burn", DEFAULT_FAST_BURN))
    slow_factor = float(spec.get("slow_burn", DEFAULT_SLOW_BURN))
    v: Dict[str, Any] = {
        "name": name,
        "kind": kind,
        "failing": False,
        "fast_window_s": fast_w,
        "slow_window_s": slow_w,
        "fast_burn_threshold": fast_factor,
        "slow_burn_threshold": slow_factor,
        "fast_burn": None,
        "slow_burn": None,
    }

    def burn_from_fraction(window_s: float, budget: float) -> Optional[float]:
        hist = str(spec.get("histogram", ""))
        thr = float(spec.get("threshold_s", 0.0))
        got = reg.window_fraction_over(hist, thr, window_s)
        if got is None:
            return None
        frac, count = got
        v.setdefault("samples", {})[f"{window_s:g}s"] = count
        # statistical floor (docs/observability.md "SLO specs"): a window
        # holding fewer than `min_count` samples is treated as no-traffic —
        # healthy — so a 3-request blip cannot page. This is also what makes
        # CLUSTER evaluation meaningful: each rank's thin slice can sit
        # under the floor while the merged fleet window clears it and burns.
        if count < int(spec.get("min_count", 0)):
            return None
        return frac / budget if budget > 0 else (float("inf") if frac else 0.0)

    try:
        if kind == "latency":
            budget = 1.0 - float(spec.get("objective", 0.99))
            v["threshold_s"] = float(spec.get("threshold_s", 0.0))
            v["objective"] = float(spec.get("objective", 0.99))
            v["fast_burn"] = burn_from_fraction(fast_w, budget)
            v["slow_burn"] = burn_from_fraction(slow_w, budget)
            v["p99"] = reg.window_quantile(str(spec.get("histogram", "")), 0.99, fast_w)
        elif kind == "error_rate":
            thr = float(spec.get("threshold", 0.01))
            v["threshold"] = thr
            for key, window_s in (("fast_burn", fast_w), ("slow_burn", slow_w)):
                total = reg.rate(str(spec.get("total", "")), window_s)
                errors = reg.rate(str(spec.get("errors", "")), window_s) or 0.0
                if not total:
                    continue  # no traffic in the window: healthy
                ratio = errors / total
                v.setdefault("ratio", {})[key] = ratio
                v[key] = ratio / thr if thr > 0 else (float("inf") if ratio else 0.0)
        elif kind == "gauge_ceiling":
            ceiling = float(spec.get("ceiling", 1.0))
            v["ceiling"] = ceiling
            value = reg.snapshot()["gauges"].get(str(spec.get("gauge", "")))
            v["value"] = value
            if value is not None:
                burn = value / ceiling if ceiling > 0 else float("inf")
                v["fast_burn"] = v["slow_burn"] = burn
        else:
            v["error"] = f"unknown slo kind {kind!r}"
    except (TypeError, ValueError) as e:
        # a malformed spec must degrade to a visible error verdict, never
        # take down the serving/scheduling path evaluating it
        v["error"] = f"{type(e).__name__}: {e}"
    v["failing"] = bool(
        (v["fast_burn"] is not None and v["fast_burn"] >= fast_factor)
        or (v["slow_burn"] is not None and v["slow_burn"] >= slow_factor)
    )
    return v


def evaluate(force: bool = True) -> List[Dict[str, Any]]:
    """Evaluate every configured SLO spec against the rolling windows; record
    transitions; return the verdict list (empty without specs)."""
    global _LAST_EVAL
    from .. import diagnostics, telemetry

    specs = _specs()
    reg = telemetry.registry()
    now = time.monotonic()
    with _LOCK:
        if not force and specs and now - _LAST_EVAL < reg.bucket_seconds():
            return [dict(v) for v in _LAST.values()]
        _LAST_EVAL = now
    if not specs:
        with _LOCK:
            _LAST.clear()
        return []
    horizon = reg.window_horizon_s()
    verdicts = [_eval_one(s, reg, horizon) for s in specs]
    if telemetry.enabled():
        reg.inc("slo.evaluations")
        reg.gauge("slo.failing", float(sum(v["failing"] for v in verdicts)))
    trips: List[Dict[str, Any]] = []
    clears: List[Dict[str, Any]] = []
    with _LOCK:
        # check-and-set under the lock so a concurrent engine-thread + scrape
        # evaluation cannot both observe the same transition (double trip)
        for v in verdicts:
            was = _TRIPPED.get(v["name"], False)
            if v["failing"] and not was:
                trips.append(v)
            elif was and not v["failing"]:
                clears.append(v)
            _TRIPPED[v["name"]] = v["failing"]
        _LAST.clear()
        for v in verdicts:
            _LAST[v["name"]] = v
    for v in trips:
        diagnostics.record_event(
            "slo.trip", slo=v["name"], slo_kind=v["kind"],
            fast_burn=v["fast_burn"], slow_burn=v["slow_burn"],
        )
        if telemetry.enabled():
            reg.inc("slo.trips")
    for v in clears:
        diagnostics.record_event("slo.clear", slo=v["name"], slo_kind=v["kind"])
        if telemetry.enabled():
            reg.inc("slo.clears")
    return verdicts


def evaluate_reader(
    reader: Any,
    specs: Optional[List[Dict[str, Any]]] = None,
    horizon: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Stateless evaluation of SLO specs against ANY windowed reader —
    `_eval_one` only needs `window_fraction_over` / `rate` /
    `window_quantile` / `snapshot()["gauges"]`, which both the live registry
    and `telemetry.MergedWindows` (the fleet plane's merged CLUSTER window)
    provide. No trip/clear state is touched: cluster verdicts are a view,
    the per-process monitors stay the event source."""
    specs = _specs() if specs is None else [s for s in specs if isinstance(s, dict)]
    if not specs:
        return []
    if horizon is None:
        try:
            horizon = float(reader.window_horizon_s())
        except Exception:
            horizon = DEFAULT_SLOW_WINDOW_S
    return [_eval_one(s, reader, horizon) for s in specs]


def cluster_health(
    reader: Any, specs: Optional[List[Dict[str, Any]]] = None
) -> Dict[str, Any]:
    """Cluster-wide health verdict over a merged fleet window — same shape
    as `health()`, evaluated via `evaluate_reader` (docs/observability.md
    "Fleet plane")."""
    verdicts = evaluate_reader(reader, specs)
    failing = [v["name"] for v in verdicts if v["failing"]]
    return {
        "healthy": not failing,
        "failing": failing,
        "specs": len(verdicts),
        "verdicts": verdicts,
        "t": time.time(),
    }


def maybe_evaluate() -> None:
    """The inline hook at histogram record points (serving dispatch,
    scheduler admission): near-free without configured specs, throttled to
    one evaluation per bucket width with them."""
    try:
        if not _specs():
            return
        evaluate(force=False)
    except Exception:  # pragma: no cover - monitors never fail the hot path
        pass


def burn_rate(
    histogram: str,
    *,
    threshold_s: float,
    objective: float,
    window_s: Optional[float] = None,
) -> Optional[float]:
    """Point burn rate of ONE latency surface over ONE window: the observed
    fraction of samples over `threshold_s` divided by the error budget
    (1 - objective). None when the window holds no samples (no traffic is
    not a burn — the same vacuous-health rule `evaluate` applies).

    The public seam the serving backpressure ladder uses to compute
    PER-TENANT burn from the per-tenant histogram siblings
    (``telemetry.tenant_metric("serve.e2e_s", tenant)``) of a configured
    spec's surface — same arithmetic as `_eval_one`'s fast/slow burns, one
    window at a time."""
    from .. import telemetry

    reg = telemetry.registry()
    w = min(
        float(window_s) if window_s is not None else DEFAULT_FAST_WINDOW_S,
        reg.window_horizon_s(),
    )
    got = reg.window_fraction_over(histogram, float(threshold_s), w)
    if got is None:
        return None
    frac, _count = got
    budget = 1.0 - float(objective)
    return frac / budget if budget > 0 else (float("inf") if frac else 0.0)


def serving_latency_spec() -> Optional[Dict[str, Any]]:
    """The first configured latency SLO spec over a serving histogram
    (``serve.*``) — the objective the backpressure ladder closes its loop
    on. None when no such spec is configured (the ladder stays inert;
    deadlines and the queue bound do not need a spec)."""
    for spec in _specs():
        if (
            str(spec.get("kind", "")) == "latency"
            and str(spec.get("histogram", "")).startswith("serve.")
        ):
            return dict(spec)
    return None


def last_verdicts() -> List[Dict[str, Any]]:
    with _LOCK:
        return [dict(v) for v in _LAST.values()]


def health(*, fresh: bool = True) -> Dict[str, Any]:
    """The health verdict /healthz serves: healthy iff no configured SLO is
    failing (a process with no specs is vacuously healthy)."""
    verdicts = evaluate(force=True) if fresh else last_verdicts()
    failing = [v["name"] for v in verdicts if v["failing"]]
    return {
        "healthy": not failing,
        "failing": failing,
        "specs": len(verdicts),
        "verdicts": verdicts,
        "t": time.time(),
    }


def reset() -> None:
    """Forget verdict/trip state (test isolation)."""
    global _LAST_EVAL
    with _LOCK:
        _LAST.clear()
        _TRIPPED.clear()
        _LAST_EVAL = 0.0
